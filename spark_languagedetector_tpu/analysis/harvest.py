"""AST/text harvesters for the contract checker (pure stdlib, no imports
of the audited modules — a module with an import-time side effect or a
jax dependency must still be checkable from a cold CI host).

One :class:`PyFile` per scanned source file carries everything the rule
families in :mod:`.rules` need: resolved ``LANGDETECT_*`` env reads, knob
literals, telemetry emit sites (counter/histogram/gauge/span names,
f-string heads kept as prefixes), ``faults.inject`` call sites,
host-impure calls inside traced functions, and suppression pragmas.

The harvesters are deliberately *syntactic*: a name is an env read when
it is a ``.get``/``getenv``/subscript whose key resolves to a
``LANGDETECT_*`` string (literal or module-level constant), an emit site
when the receiver's terminal name is ``REGISTRY``/``reg``/``registry``.
Reads threaded through helper parameters (``_env_int(env, name, ...)``)
are out of reach by design — but their *name constants* still hit the
knob-literal rule, so a knob can't exist outside the audited table
either way. docs/ANALYSIS.md §2 spells out the boundary.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path

# One token per knob mention; a trailing ``*`` (docs) or ``_`` marks a
# wildcard family reference (``LANGDETECT_RETRY_*``) rather than a row.
KNOB_TOKEN_RE = re.compile(r"LANGDETECT_[A-Z0-9_]*\*?")

# Inline suppression (hash sign, then): ``contract: ignore[R1] -- reason``
# — comma list of rule ids; the reason is mandatory, an unexplained
# suppression is noise for the next reader. Honored on the violating line
# or alone on the line directly above it.
PRAGMA_RE = re.compile(
    r"#\s*contract:\s*ignore\[([A-Za-z0-9_,\s]+)\]\s*--\s*(\S.*?)\s*$"
)

_EMIT_RECEIVERS = ("REGISTRY", "reg", "registry")
_EMIT_METHODS = ("incr", "observe", "set_gauge", "record_span")
_JIT_NAMES = ("jit", "pjit")
_WRAP_NAMES = ("pallas_call", "shard_map", "shard_map_compat")


@dataclass
class EmitSites:
    """Telemetry names one file emits; values are first-seen lines."""

    counters: dict[str, int] = field(default_factory=dict)
    counter_prefixes: dict[str, int] = field(default_factory=dict)
    hists: dict[str, int] = field(default_factory=dict)
    hist_prefixes: dict[str, int] = field(default_factory=dict)
    gauges: dict[str, int] = field(default_factory=dict)
    gauge_prefixes: dict[str, int] = field(default_factory=dict)
    spans: dict[str, int] = field(default_factory=dict)
    span_prefixes: dict[str, int] = field(default_factory=dict)


@dataclass
class PyFile:
    """One parsed source file's harvest."""

    rel: str
    text: str
    tree: ast.Module | None
    parse_error: str | None = None
    consts: dict[str, str] = field(default_factory=dict)
    env_reads: list[tuple[int, str]] = field(default_factory=list)
    knob_tokens: list[tuple[int, str, bool]] = field(default_factory=list)
    emits: EmitSites = field(default_factory=EmitSites)
    injects: list[tuple[int, str]] = field(default_factory=list)
    impure: list[tuple[int, str, str]] = field(default_factory=list)
    pragmas: dict[int, tuple[frozenset[str], str]] = field(
        default_factory=dict
    )


# ------------------------------------------------------------ helpers -------
def _terminal_name(node: ast.expr) -> str | None:
    """The last dotted component of a receiver expression."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _str_of(node: ast.expr, consts: dict[str, str]) -> str | None:
    """A string literal, or a module-level string constant by name."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


def _name_args(node: ast.expr) -> tuple[set[str], list[ast.Lambda]]:
    """All Name ids + Lambda nodes anywhere under an argument expression."""
    names: set[str] = set()
    lambdas: list[ast.Lambda] = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            names.add(sub.id)
        elif isinstance(sub, ast.Lambda):
            lambdas.append(sub)
    return names, lambdas


def _is_jitish(node: ast.expr) -> bool:
    return _terminal_name(node) in _JIT_NAMES


def _is_trace_wrap(node: ast.expr) -> bool:
    """jit/pjit/shard_map/pallas_call — or partial(jax.jit, ...)."""
    if _is_jitish(node) or _terminal_name(node) in _WRAP_NAMES:
        return True
    if (
        isinstance(node, ast.Call)
        and _terminal_name(node.func) == "partial"
        and node.args
        and _is_jitish(node.args[0])
    ):
        return True
    return False


def _emit_names(node: ast.expr) -> tuple[list[str], list[str]]:
    """(full literal names, prefix heads) a name argument can produce."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value], []
    if isinstance(node, ast.JoinedStr) and node.values:
        head = node.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return [], [head.value]
    if isinstance(node, ast.IfExp):
        full: list[str] = []
        pref: list[str] = []
        for branch in (node.body, node.orelse):
            f, p = _emit_names(branch)
            full += f
            pref += p
        return full, pref
    return [], []


# --------------------------------------------------------- file harvest -----
def harvest_file(path: Path, rel: str) -> PyFile:
    text = path.read_text(encoding="utf-8")
    try:
        tree = ast.parse(text)
    except SyntaxError as e:
        return PyFile(rel=rel, text=text, tree=None, parse_error=str(e))
    pf = PyFile(rel=rel, text=text, tree=tree)
    _harvest_consts(pf)
    _harvest_knob_tokens(pf)
    _harvest_pragmas(pf)
    _harvest_calls(pf)
    _harvest_trace_purity(pf)
    return pf


def _harvest_consts(pf: PyFile) -> None:
    for node in pf.tree.body:
        # Both spellings of a module-level string constant — a missed
        # form here is an R1 bypass (env reads resolve keys through
        # these), so keep this in sync with what _str_of can be handed.
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            target, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            target, value = node.target.id, node.value
        else:
            continue
        if isinstance(value, ast.Constant) and isinstance(value.value, str):
            pf.consts[target] = value.value


def _harvest_knob_tokens(pf: PyFile) -> None:
    for lineno, line in enumerate(pf.text.splitlines(), start=1):
        for m in KNOB_TOKEN_RE.finditer(line):
            token = m.group(0)
            wildcard = token.endswith(("*", "_"))
            token = token.rstrip("*")
            if token == "LANGDETECT_":
                continue  # generic family mention ("every LANGDETECT_* knob")
            pf.knob_tokens.append((lineno, token, wildcard))


def _harvest_pragmas(pf: PyFile) -> None:
    for lineno, line in enumerate(pf.text.splitlines(), start=1):
        m = PRAGMA_RE.search(line)
        if m is None:
            continue
        rules = frozenset(
            r.strip() for r in m.group(1).split(",") if r.strip()
        )
        pf.pragmas[lineno] = (rules, m.group(2))


def _record(table: dict[str, int], name: str, line: int) -> None:
    table.setdefault(name, line)


def _harvest_calls(pf: PyFile) -> None:
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.Subscript) and isinstance(
            node.ctx, ast.Load
        ):
            key = _str_of(node.slice, pf.consts)
            if (
                key
                and key.startswith("LANGDETECT_")
                and _terminal_name(node.value) in ("environ",)
            ):
                pf.env_reads.append((node.lineno, key))
            continue
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        # --- env reads: <x>.get("LANGDETECT_…") / os.getenv(…) ----------
        if node.args:
            key = _str_of(node.args[0], pf.consts)
            if key and key.startswith("LANGDETECT_"):
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr in ("get", "getenv")
                ) or (isinstance(func, ast.Name) and func.id == "getenv"):
                    pf.env_reads.append((node.lineno, key))
        # --- telemetry emits --------------------------------------------
        if (
            isinstance(func, ast.Attribute)
            and func.attr in _EMIT_METHODS
            and _terminal_name(func.value) in _EMIT_RECEIVERS
            and node.args
        ):
            full, prefixes = _emit_names(node.args[0])
            emits = pf.emits
            kind = {
                "incr": (emits.counters, emits.counter_prefixes),
                "observe": (emits.hists, emits.hist_prefixes),
                "set_gauge": (emits.gauges, emits.gauge_prefixes),
                "record_span": (emits.spans, emits.span_prefixes),
            }[func.attr]
            for name in full:
                _record(kind[0], name, node.lineno)
            for prefix in prefixes:
                _record(kind[1], prefix, node.lineno)
        # --- span("name") ------------------------------------------------
        if isinstance(func, ast.Name) and func.id == "span" and node.args:
            full, prefixes = _emit_names(node.args[0])
            for name in full:
                _record(pf.emits.spans, name, node.lineno)
            for prefix in prefixes:
                _record(pf.emits.span_prefixes, prefix, node.lineno)
        # --- fault injection sites --------------------------------------
        if (
            isinstance(func, ast.Attribute)
            and func.attr == "inject"
            and _terminal_name(func.value) == "faults"
        ) or (isinstance(func, ast.Name) and func.id == "inject"):
            if node.args:
                site = _str_of(node.args[0], pf.consts)
                if site:
                    pf.injects.append((node.lineno, site))
        if (
            isinstance(func, ast.Attribute)
            and func.attr == "corrupt_batch"
        ):
            site = "stream/batch"  # the signature's default site
            for kw in node.keywords:
                if kw.arg == "site":
                    site = _str_of(kw.value, pf.consts) or site
            if len(node.args) >= 3:
                site = _str_of(node.args[2], pf.consts) or site
            pf.injects.append((node.lineno, site))


# ------------------------------------------------------- trace purity -------
def _impure_calls(body: ast.AST):
    """(line, description) for host-impure calls under a traced node."""
    for node in ast.walk(body):
        if isinstance(node, ast.Subscript) and isinstance(
            node.ctx, ast.Load
        ):
            if _terminal_name(node.value) == "environ":
                yield node.lineno, "os.environ[...] read"
            continue
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Name):
            if func.id == "print":
                yield node.lineno, "print() (per-trace no-op on device)"
            elif func.id == "span":
                yield node.lineno, "telemetry span() emission"
            elif func.id == "getenv":
                yield node.lineno, "os.getenv() read"
            continue
        if not isinstance(func, ast.Attribute):
            continue
        recv = func.value
        recv_name = _terminal_name(recv)
        if recv_name == "time":
            yield node.lineno, f"time.{func.attr}() (baked at trace time)"
        elif recv_name == "random" and isinstance(recv, ast.Name):
            yield node.lineno, f"random.{func.attr}() (host RNG)"
        elif (
            isinstance(recv, ast.Attribute)
            and recv.attr == "random"
            and _terminal_name(recv.value) in ("np", "numpy")
        ):
            yield node.lineno, f"np.random.{func.attr}() (host RNG)"
        elif recv_name == "environ" and func.attr == "get":
            yield node.lineno, "os.environ.get() read"
        elif recv_name == "os" and func.attr == "getenv":
            yield node.lineno, "os.getenv() read"
        elif recv_name == "REGISTRY":
            yield node.lineno, f"REGISTRY.{func.attr}() emission"


def _harvest_trace_purity(pf: PyFile) -> None:
    """Flag host-impure calls inside jit/pjit/shard_map/pallas_call bodies.

    Traced functions are found two ways: decorator forms (``@jax.jit``,
    ``@partial(jax.jit, …)``) and wrap forms (``jit(f)``,
    ``pl.pallas_call(kernel, …)``, ``shard_map_compat(f, …)`` — any Name
    in the wrap call's positional args that resolves to a function
    defined in this module, plus inline lambdas).
    """
    defs: dict[str, list[ast.AST]] = {}
    for node in ast.walk(pf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)

    traced: dict[int, tuple[str, ast.AST]] = {}

    def mark(node: ast.AST, context: str) -> None:
        traced.setdefault(id(node), (context, node))

    for node in ast.walk(pf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if _is_trace_wrap(target) or (
                    isinstance(dec, ast.Call) and _is_trace_wrap(dec)
                ):
                    mark(node, node.name)
        elif isinstance(node, ast.Call) and _is_trace_wrap(node.func):
            for arg in node.args:
                names, lambdas = _name_args(arg)
                for name in names:
                    for fn in defs.get(name, ()):
                        mark(fn, name)
                for lam in lambdas:
                    mark(lam, "<lambda>")

    seen: set[int] = set()
    for context, node in traced.values():
        for line, desc in _impure_calls(node):
            if (line, desc) in seen:
                continue
            seen.add((line, desc))
            pf.impure.append((line, context, desc))


# ------------------------------------------- contract-module extraction -----
def knob_table(config: PyFile | None) -> dict[str, tuple[str | None, int]]:
    """``{knob name: (env spelling, line)}`` from ``Knob(...)`` rows."""
    out: dict[str, tuple[str | None, int]] = {}
    if config is None or config.tree is None:
        return out
    for node in ast.walk(config.tree):
        if (
            isinstance(node, ast.Call)
            and _terminal_name(node.func) == "Knob"
            and node.args
        ):
            name = _str_of(node.args[0], config.consts)
            env = None
            if len(node.args) > 1:
                env = _str_of(node.args[1], config.consts)
            if name:
                out[name] = (env, node.lineno)
    return out


def _module_assign(pf: PyFile, name: str) -> ast.expr | None:
    for node in pf.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == name
        ):
            return node.value
        if (
            isinstance(node, ast.AnnAssign)
            and isinstance(node.target, ast.Name)
            and node.target.id == name
            and node.value is not None
        ):
            return node.value
    return None


def _str_elements(node: ast.expr | None, consts: dict[str, str]) -> list[str]:
    if node is None or not isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return []
    out = []
    for el in node.elts:
        s = _str_of(el, consts)
        if s:
            out.append(s)
    return out


def fault_sites(faults: PyFile | None) -> dict[str, int]:
    """``SITES`` rows (name -> declaration line)."""
    if faults is None or faults.tree is None:
        return {}
    node = _module_assign(faults, "SITES")
    if node is None:
        return {}
    return {s: node.lineno for s in _str_elements(node, faults.consts)}


@dataclass
class CompareContracts:
    """Names ``telemetry/compare`` consumes from a capture."""

    tracked_gauges: dict[str, int] = field(default_factory=dict)
    tracked_ratio_counters: dict[str, int] = field(default_factory=dict)
    tracked_ratio_names: dict[str, int] = field(default_factory=dict)
    reliability_counters: dict[str, int] = field(default_factory=dict)
    reliability_prefixes: dict[str, int] = field(default_factory=dict)
    informational_counters: dict[str, int] = field(default_factory=dict)
    cold_start_histograms: dict[str, int] = field(default_factory=dict)


def compare_contracts(compare: PyFile | None) -> CompareContracts:
    out = CompareContracts()
    if compare is None or compare.tree is None:
        return out
    node = _module_assign(compare, "_TRACKED_GAUGES")
    if isinstance(node, ast.Dict):
        for key in node.keys:
            s = _str_of(key, compare.consts)
            if s:
                out.tracked_gauges[s] = node.lineno
    node = _module_assign(compare, "_TRACKED_RATIOS")
    if isinstance(node, ast.Dict):
        for key, value in zip(node.keys, node.values):
            name = _str_of(key, compare.consts)
            if name:
                out.tracked_ratio_names[name] = node.lineno
            for counter in _str_elements(value, compare.consts):
                out.tracked_ratio_counters[counter] = node.lineno
    for const, table in (
        ("_RELIABILITY_COUNTERS", out.reliability_counters),
        ("_RELIABILITY_COUNTER_PREFIXES", out.reliability_prefixes),
        ("_INFORMATIONAL_COUNTERS", out.informational_counters),
        ("_COLD_START_HISTOGRAMS", out.cold_start_histograms),
    ):
        node = _module_assign(compare, const)
        for s in _str_elements(node, compare.consts):
            table[s] = node.lineno
    return out


@dataclass
class FleetContracts:
    """Names the fleet observability plane consumes (docs/OBSERVABILITY.md
    §14): counters the aggregate's pressure readers sum, the collector's
    own guard counters (which must stay pinned in ``telemetry/compare``'s
    tables), and the SLO layer's burn-rate inputs."""

    consumed_counters: dict[str, int] = field(default_factory=dict)
    guard_counters: dict[str, int] = field(default_factory=dict)
    slo_counters: dict[str, int] = field(default_factory=dict)
    slo_histograms: dict[str, int] = field(default_factory=dict)
    slo_gauges: dict[str, int] = field(default_factory=dict)


def fleet_contracts(
    aggregate: PyFile | None, slo: PyFile | None
) -> FleetContracts:
    """Contract tables from ``telemetry/aggregate`` + ``telemetry/slo``."""
    out = FleetContracts()

    def pull(pf: PyFile | None, const: str, table: dict[str, int]) -> None:
        if pf is None or pf.tree is None:
            return
        node = _module_assign(pf, const)
        if node is None:
            return
        for s in _str_elements(node, pf.consts):
            table[s] = node.lineno

    pull(aggregate, "CONSUMED_COUNTERS", out.consumed_counters)
    pull(aggregate, "GUARD_COUNTERS", out.guard_counters)
    pull(slo, "SLO_INPUT_COUNTERS", out.slo_counters)
    pull(slo, "SLO_INPUT_HISTOGRAMS", out.slo_histograms)
    pull(slo, "SLO_INPUT_GAUGES", out.slo_gauges)
    return out


def tune_consumed(tune: PyFile | None) -> dict[str, tuple[int, str, bool]]:
    """Capture names ``exec/tune`` replays: ``{name: (line, kind, prefix)}``.

    Everything read off the last snapshot via ``counters.get("…")`` /
    ``hists.get("…")`` (kind follows the receiver), plus the
    ``LEN_BIN_PREFIX`` counter family.
    """
    out: dict[str, tuple[int, str, bool]] = {}
    if tune is None or tune.tree is None:
        return out
    for node in ast.walk(tune.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in ("counters", "hists")
            and node.args
        ):
            name = _str_of(node.args[0], tune.consts)
            if name:
                kind = (
                    "counter"
                    if node.func.value.id == "counters"
                    else "histogram"
                )
                out.setdefault(name, (node.lineno, kind, False))
    prefix = tune.consts.get("LEN_BIN_PREFIX")
    if prefix:
        out.setdefault(prefix, (1, "counter", True))
    return out
