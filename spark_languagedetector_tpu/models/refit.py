"""Incremental refit: the count table as a persisted sufficient statistic.

The device fit is map(count) → reduce(top-k) (DrJAX, arXiv:2403.07128), and
the count table is a *sufficient statistic* for the whole model: weighting,
top-k, and the final profile depend on nothing else. Because the dense
int32 scatter-add is order- and batching-independent, counts accumulated
over any sequence of document batches equal counts from one pass over the
concatenated corpus — so a fit can be *grown*: new streaming batches update
the accumulator through the same pipelined count path the from-scratch fit
uses (``ops.fit_tpu.accumulate_counts``), and a refit re-runs only the
on-device finalize (``ops.fit_tpu.finalize_counts``), bit-identical to
fitting from scratch on everything seen so far (pinned by
``tests/test_refit.py``; gated by ``bench.py --smoke-refit``).

:class:`FitAccumulator` owns that state: the device count table (mesh-
sharded exactly like the from-scratch fit's — ``device_fit_context``
decides once for both paths), per-language doc coverage for the
estimator's validation, and the resume token ``committed`` (how many
source batches the table already contains). ``save``/``load`` persist it
through the crash-atomic ``persist.io`` codec; the token rides inside the
state, so counts and token can never commit separately.

The streaming driver that feeds this from a source and pushes refits
through the serving registry's hot-swap lives in :mod:`..stream.refit`.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.encoding import UTF8, texts_to_bytes
from ..ops.fit_tpu import (
    accumulate_counts,
    device_fit_context,
    finalize_counts,
)
from ..ops.vocab import EXACT, MAX_DEVICE_ID_GRAM_LEN, VocabSpec
from ..telemetry import span, trace_request
from ..utils.logging import get_logger, log_event

_log = get_logger("models.refit")


class FitAccumulator:
    """Checkpointable incremental device fit: update counts, finalize later.

    Built from an estimator (``LanguageDetector.accumulator()``) or
    restored from disk (:meth:`load`). Not thread-safe — one updater at a
    time (the streaming refit driver owns it from a single thread).

    Supported specs are the ones a single dense device table can hold:
    hashed vocabs (any gram lengths) and exact vocabs with gram lengths ≤
    ``MAX_DEVICE_ID_GRAM_LEN``. The exact long-gram split fit is a
    two-substrate corpus pass, not one table — incremental refit refuses it
    loudly instead of silently keeping half a statistic.
    """

    def __init__(
        self,
        spec: VocabSpec,
        languages: Sequence[str],
        *,
        profile_size: int,
        weight_mode: str = "parity",
        train_encoding: str = UTF8,
        label_col: str = "lang",
        input_col: str = "fulltext",
        batch_rows: int | None = None,
        mesh=None,
    ):
        if spec.mode == EXACT and max(spec.gram_lengths) > MAX_DEVICE_ID_GRAM_LEN:
            raise ValueError(
                "incremental refit needs a single dense count table; exact "
                f"gram lengths > {MAX_DEVICE_ID_GRAM_LEN} take the split "
                "host/device fit, which has no one-table sufficient "
                "statistic — use hashed vocab or fit from scratch"
            )
        self.spec = spec
        self.languages = tuple(languages)
        self.profile_size = int(profile_size)
        self.weight_mode = weight_mode
        self.train_encoding = train_encoding
        self.label_col = label_col
        self.input_col = input_col
        self.batch_rows = batch_rows
        self._lang_to_idx = {l: i for i, l in enumerate(self.languages)}
        self._ctx = device_fit_context(spec, len(self.languages), mesh)
        self.counts = self._ctx.counts
        self.committed = 0  # resume token: source batches in the table
        self.docs_seen = 0
        self.lang_docs = np.zeros(len(self.languages), dtype=np.int64)
        # A raising update may have donated/partially-updated the device
        # table (the count steps donate the accumulator on accelerators);
        # the in-memory state is then unusable and must be reloaded from
        # the last checkpoint.
        self._poisoned = False

    # ------------------------------------------------------------ builders --
    @classmethod
    def for_estimator(cls, estimator, mesh=None) -> "FitAccumulator":
        """Accumulator configured exactly like ``estimator.fit`` would fit
        (spec, languages, weight mode, profile size, encoding, batch rows);
        ``mesh`` None resolves the same fit mesh the device fit uses."""
        from ..api.runner import resolve_fit_mesh

        return cls(
            estimator._vocab_spec(),
            list(estimator.get("supportedLanguages")),
            profile_size=estimator.get("languageProfileSize"),
            weight_mode=estimator.get("weightMode"),
            train_encoding=estimator.get("trainEncoding"),
            label_col=estimator.get_label_col(),
            input_col=estimator.get_input_col(),
            batch_rows=estimator.get("fitBatchRows"),
            mesh=mesh if mesh is not None else resolve_fit_mesh(),
        )

    @classmethod
    def load(cls, path, *, mesh=None) -> "FitAccumulator":
        """Restore a persisted accumulator: sparse rows scatter back into a
        fresh (mesh-placed) dense table; the resume token comes along."""
        from ..api.runner import resolve_fit_mesh
        from ..persist.io import load_fit_state

        state = load_fit_state(path)
        acc = cls(
            state["spec"],
            state["languages"],
            profile_size=state["profile_size"],
            weight_mode=state["weight_mode"],
            train_encoding=state["train_encoding"],
            label_col=state["label_col"],
            input_col=state["input_col"],
            batch_rows=state["batch_rows"],
            mesh=mesh if mesh is not None else resolve_fit_mesh(),
        )
        if len(state["ids"]):
            if int(state["rows"].max(initial=0)) > np.iinfo(np.int32).max:
                raise ValueError(
                    "persisted counts exceed int32 — this accumulator "
                    "outgrew the device fit's precision contract"
                )
            acc.counts = acc.counts.at[jnp.asarray(state["ids"])].set(
                jnp.asarray(state["rows"].astype(np.int32))
            )
        acc.committed = state["committed"]
        acc.docs_seen = state["docs_seen"]
        acc.lang_docs = np.asarray(state["lang_docs"], dtype=np.int64)
        log_event(
            _log, "refit.state_loaded", path=str(path),
            committed=acc.committed, docs=acc.docs_seen,
        )
        return acc

    # ------------------------------------------------------------- updates --
    def _check_usable(self) -> None:
        if self._poisoned:
            raise RuntimeError(
                "accumulator state was invalidated by a failed update "
                "(count steps donate the device table); reload it from the "
                "last checkpoint"
            )

    def update(self, dataset) -> int:
        """Accumulate one Table of (label, text) rows; returns rows added.

        The same validation as ``LanguageDetector.fit``'s Validation A
        (unknown labels raise, message preserved verbatim); coverage
        (Validation B) is checked cumulatively at :meth:`finalize`.
        """
        labels = dataset.column(self.label_col).tolist()
        texts = dataset.column(self.input_col).tolist()
        for lang in dict.fromkeys(labels):
            if lang not in self._lang_to_idx:
                raise ValueError(
                    f"Input data contians {lang}, but it is not "
                    f"in the list of supported languages"
                )
        docs = texts_to_bytes(texts, self.train_encoding)
        lang_idx = np.asarray(
            [self._lang_to_idx[l] for l in labels], dtype=np.int32
        )
        return self.update_raw(docs, lang_idx)

    def update_raw(self, byte_docs, lang_indices) -> int:
        """Accumulate pre-encoded docs through the pipelined count path."""
        self._check_usable()
        lang_arr = np.asarray(lang_indices, dtype=np.int32)
        if len(byte_docs) != len(lang_arr):
            raise ValueError(
                f"{len(byte_docs)} docs vs {len(lang_arr)} labels"
            )
        if len(byte_docs) == 0:
            self.committed += 1
            return 0
        self._poisoned = True  # cleared on success; see _check_usable
        with trace_request(), span(
            "fit", rows=len(byte_docs), backend="device", incremental=True,
            languages=len(self.languages),
        ):
            self.counts = accumulate_counts(
                self._ctx, self.counts, byte_docs, lang_arr,
                spec=self.spec, num_langs=len(self.languages),
                batch_rows=self.batch_rows,
            )
        self._poisoned = False
        self.committed += 1
        self.docs_seen += len(byte_docs)
        np.add.at(self.lang_docs, lang_arr, 1)
        return len(byte_docs)

    # ------------------------------------------------------------ finalize --
    def coverage_gaps(self) -> list[str]:
        """Supported languages with zero training docs so far (finalize
        refuses while non-empty — the estimator's Validation B)."""
        return [
            lang for lang, n in zip(self.languages, self.lang_docs) if n == 0
        ]

    def finalize(self):
        """(ids, weights) — the reduce half only: on-device weighting +
        top-k + winner-rows collect over the accumulated table. Bit-
        identical to a from-scratch fit over everything updated so far."""
        self._check_usable()
        missing = self.coverage_gaps()
        if missing:
            raise ValueError(
                f"No training examples found for language {missing[0]}. "
                f"Provide examples for each language"
            )
        return finalize_counts(
            self.counts,
            num_langs=len(self.languages),
            profile_size=self.profile_size,
            weight_mode=self.weight_mode,
            mesh=self._ctx.mesh,
            table_sharded=self._ctx.table_sharded,
        )

    # ----------------------------------------------------------- persistence --
    def save(self, path) -> None:
        """Checkpoint the accumulator (sparse nonzero rows + resume token)
        through the crash-atomic ``persist.io`` codec. Only the occurring
        rows cross the wire: occurrence is decided on device and the
        gather fetches just those rows."""
        self._check_usable()
        from ..persist.io import save_fit_state

        occurred = np.asarray(self.counts.sum(axis=1) > 0)
        ids = np.nonzero(occurred)[0].astype(np.int64)
        rows = (
            np.asarray(self.counts[jnp.asarray(ids)], dtype=np.int64)
            if len(ids)
            else np.zeros((0, len(self.languages)), dtype=np.int64)
        )
        save_fit_state(
            path,
            spec=self.spec,
            languages=self.languages,
            weight_mode=self.weight_mode,
            profile_size=self.profile_size,
            train_encoding=self.train_encoding,
            label_col=self.label_col,
            input_col=self.input_col,
            batch_rows=self.batch_rows,
            committed=self.committed,
            docs_seen=self.docs_seen,
            lang_docs=self.lang_docs,
            ids=ids,
            rows=rows,
        )

    def matches_estimator(self, estimator) -> bool:
        """Whether this state was produced under the estimator's exact fit
        configuration (spec, languages, weight mode, profile size, train
        encoding) — the precondition for ``fit_from_accumulator`` and for
        resuming a persisted state under a driver built from that
        estimator."""
        return (
            self.spec == estimator._vocab_spec()
            and self.languages == tuple(estimator.get("supportedLanguages"))
            and self.weight_mode == estimator.get("weightMode")
            and self.profile_size == estimator.get("languageProfileSize")
            and self.train_encoding == estimator.get("trainEncoding")
        )
