"""LanguageDetector (Estimator) and LanguageDetectorModel (Model/Transformer).

The public fit/transform API, mirroring the reference's Spark ML pair
(``/root/reference/src/main/.../LanguageDetector.scala:176-265``,
``LanguageDetectorModel.scala:178-245``) with the same defaults
(``inputCol="fulltext"``, ``labelCol``/``outputCol="lang"``), the same
validation errors, the same decision semantics — re-architected for TPU:
fit builds a columnar :class:`GramProfile` in one corpus pass; transform ships
micro-batches through :class:`~..api.runner.BatchRunner` where scoring is a
jit-compiled gather/accumulate on device.

Unlike the reference, *every* hyper-parameter is a Param (SURVEY.md §5.6):
``supportedLanguages``/``gramLengths``/``languageProfileSize`` are constructor
conveniences that land in the params system, covered by ``copy`` and
persistence — plus the BASELINE north star's ``backend`` switch.
"""

from __future__ import annotations

import threading
from typing import Sequence

import numpy as np

from ..api.params import HasInputCol, HasLabelCol, HasOutputCol, Param, Params
from ..api.runner import BatchRunner, resolve_device, resolve_mesh
from ..api.table import STRING, Schema, Table, require_string_column
from ..ops import fit as fit_ops
from ..ops.encoding import LOW_BYTE, UTF8, text_to_bytes, texts_to_bytes
from ..ops.vocab import (
    EXACT,
    HASHED,
    MAX_DEVICE_ID_GRAM_LEN,
    MAX_EXACT_GRAM_LEN,
    VocabSpec,
)
from ..telemetry import flightrec, span, trace_request
from ..utils.logging import get_logger, log_event
from .profile import GramProfile

_log = get_logger("models.estimator")

BACKEND_AUTO = "auto"
BACKEND_TPU = "tpu"
BACKEND_CPU = "cpu"
BACKEND_MESH = "mesh"
BACKEND_MESH_VOCAB = "mesh:vocab"
BACKENDS = (
    BACKEND_AUTO, BACKEND_TPU, BACKEND_CPU, BACKEND_MESH, BACKEND_MESH_VOCAB
)


def _positive_int(v) -> bool:
    return isinstance(v, int) and v > 0


RESULT_MODES = ("label", "segment")


def _valid_reject_threshold(v) -> bool:
    return isinstance(v, (int, float)) and 0.0 <= float(v) < 1.0


class _ResultModeParams:
    """Segmentation result-type params, shared by estimator and model
    (docs/SEGMENTATION.md): the estimator stamps them onto the fitted
    model like ``backend``/``quantization``, Spark-style."""

    result_mode = Param(
        "resultMode",
        "transform output type: 'label' (the reference's one-language "
        "argmax column) or 'segment' (span-level code-switch decode — the "
        "output column carries one JSON object per document with byte-"
        "offset spans, calibrated top-k languages, and the unknown "
        "reject; docs/SEGMENTATION.md)",
        lambda v: v in RESULT_MODES,
    )
    top_k = Param(
        "topK",
        "segment mode: candidate languages returned per document with "
        "calibrated probabilities",
        _positive_int,
    )
    reject_threshold = Param(
        "rejectThreshold",
        "segment mode: calibrated-probability floor in [0, 1) below which "
        "a document (or span) answers 'unknown' instead of a low-"
        "confidence language; 0 disables the reject",
        _valid_reject_threshold,
    )


class _DetectorParams(HasInputCol, HasLabelCol, _ResultModeParams):
    """Params shared by the estimator (model adds output col instead)."""

    supported_languages = Param(
        "supportedLanguages", "languages the detector can emit, in vector order"
    )
    gram_lengths = Param("gramLengths", "byte n-gram window sizes")
    language_profile_size = Param(
        "languageProfileSize", "top-k grams kept per language", _positive_int
    )
    save_grams_to = Param(
        "saveGrams",
        "optional path: persist the fitted gram-probability dataset (the "
        "reference's saveGramsToHDFS, LanguageDetector.scala:203-205)",
    )
    vocab_mode = Param(
        "vocabMode",
        f"'exact' (bijective ids, gram lengths <= {MAX_EXACT_GRAM_LEN}), "
        "'hashed' (2^hashBits buckets, any length), or 'auto'",
        lambda v: v in ("auto", EXACT, HASHED),
    )
    hash_bits = Param("hashBits", "log2 bucket count for hashed vocab", _positive_int)
    hash_scheme = Param(
        "hashScheme",
        "hashed bucket scheme: 'auto' (exact12 when hashBits >= 17), "
        "'exact12' (grams <= 2 bytes keep collision-free polynomial ids; "
        "longer grams FNV-fold into the remaining buckets — enables the "
        "pallas histogram fast path), or 'fnv1a' (all lengths FNV-folded)",
        lambda v: v in ("auto", "fnv1a", "exact12"),
    )
    weight_mode = Param(
        "weightMode",
        "'parity': reference formula log(1+presence/#langs) (SURVEY.md Q1); "
        "'counts': corrected log(1+count/total)",
        lambda v: v in fit_ops.WEIGHT_MODES,
    )
    train_encoding = Param(
        "trainEncoding",
        "text→bytes for fit: 'utf8' (reference fit behavior)",
        lambda v: v in (UTF8, LOW_BYTE),
    )
    fit_backend = Param(
        "fitBackend",
        "'cpu' (host fit — the reference keeps fit on CPU) or 'device': "
        "streaming dense-count fit on the jax default device "
        "(micro-batched scatter-add + device weighting/top-k)",
        lambda v: v in ("cpu", "device"),
    )
    fit_batch_rows = Param(
        "fitBatchRows",
        "device-fit micro-batch rows per count dispatch; None ⇒ rows adapt "
        "per length bucket under a byte budget (LANGDETECT_FIT_BATCH_BYTES, "
        "default 8MB per padded transfer; LANGDETECT_FIT_BATCH_ROWS forces "
        "a fixed row count). Ignored by fitBackend='cpu'",
        lambda v: v is None or _positive_int(v),
    )
    backend = Param(
        "backend",
        "scoring backend stamped onto the fitted model "
        "(LanguageDetectorModel.backend — 'tpu' | 'cpu' | 'auto' | 'mesh' "
        "| 'mesh:vocab'); set here so the Spark-style "
        "estimator-configures-model flow works in one place",
        lambda v: v in BACKENDS,
    )
    quantization = Param(
        "quantization",
        "weight-table quantization stamped onto the fitted model "
        "(LanguageDetectorModel.quantization): 'int8' | 'int16' ship the "
        "fused detect kernel int8/int16 table tiles with per-language f32 "
        "scales (f32 accumulation; docs/PERFORMANCE.md §7); None keeps "
        "f32 tables",
        lambda v: v in (None, "int8", "int16"),
    )


class LanguageDetector(_DetectorParams):
    """Estimator: ``fit(table) -> LanguageDetectorModel``.

    Reference: ``class LanguageDetector`` (LanguageDetector.scala:176-265).
    """

    def __init__(
        self,
        supported_languages: Sequence[str],
        gram_lengths: Sequence[int],
        language_profile_size: int,
        uid: str | None = None,
    ):
        super().__init__(uid, uid_prefix="LanguageDetector")
        self.set_default(
            inputCol="fulltext",
            labelCol="lang",
            saveGrams=None,
            vocabMode="auto",
            hashBits=20,
            hashScheme="auto",
            weightMode=fit_ops.PARITY,
            trainEncoding=UTF8,
            fitBackend="cpu",
            fitBatchRows=None,
        )
        self.set("supportedLanguages", list(supported_languages))
        self.set("gramLengths", [int(n) for n in gram_lengths])
        self.set("languageProfileSize", int(language_profile_size))

    @classmethod
    def _from_param_metadata(cls, uid: str, metadata: dict) -> "LanguageDetector":
        """Rebuild an estimator from persisted params (pipeline persistence:
        every hyper-parameter is a Param here — SURVEY.md §5.6 — so the
        constructor arguments come back out of the metadata)."""
        flat = {
            **metadata.get("defaultParams", {}),
            **metadata.get("params", {}),
        }
        det = cls(
            flat["supportedLanguages"],
            flat["gramLengths"],
            flat["languageProfileSize"],
            uid=uid,
        )
        det._set_params_from_metadata(metadata)
        return det

    # -- convenience setters (Spark ML style) ---------------------------------
    def set_save_grams_to(self, path: str | None):
        return self.set("saveGrams", path)

    def set_fit_backend(self, value: str):
        return self.set("fitBackend", value)

    def set_fit_batch_rows(self, value: int | None):
        return self.set("fitBatchRows", value)

    def set_backend(self, value: str):
        return self.set("backend", value)

    def set_quantization(self, value: str | None):
        return self.set("quantization", value)

    def set_vocab_mode(self, mode: str):
        return self.set("vocabMode", mode)

    def set_hash_bits(self, bits: int):
        return self.set("hashBits", bits)

    def set_hash_scheme(self, scheme: str):
        return self.set("hashScheme", scheme)

    def set_weight_mode(self, mode: str):
        return self.set("weightMode", mode)

    def set_result_mode(self, mode: str):
        return self.set("resultMode", mode)

    def set_top_k(self, k: int):
        return self.set("topK", k)

    def set_reject_threshold(self, value: float):
        return self.set("rejectThreshold", value)

    # -- contract --------------------------------------------------------------
    def transform_schema(self, schema: Schema) -> Schema:
        """Estimator schema pass-through (LanguageDetector.scala:207)."""
        return schema

    def _vocab_spec(self) -> VocabSpec:
        gram_lengths = tuple(self.get("gramLengths"))
        mode = self.get("vocabMode")
        if mode == "auto":
            # Auto prefers the dense/LUT id forms: exact through n = 3 (int32
            # device ids), hashed beyond. Exact n = 4..5 (cuckoo membership)
            # is available by explicit vocabMode="exact".
            mode = EXACT if max(gram_lengths) <= MAX_DEVICE_ID_GRAM_LEN else HASHED
        return VocabSpec(
            mode,
            gram_lengths,
            hash_bits=self.get("hashBits"),
            hash_scheme=self.get("hashScheme"),
        )

    def fit(self, dataset: Table) -> "LanguageDetectorModel":
        label_col, input_col = self.get_label_col(), self.get_input_col()
        supported = list(self.get("supportedLanguages"))

        # select(labelCol, inputCol) — raises KeyError on missing columns
        # (the reference's Spark analysis error).
        labels = dataset.column(label_col)
        texts = dataset.column(input_col)

        lang_to_idx = {lang: i for i, lang in enumerate(supported)}

        # Validation A (LanguageDetector.scala:221-228): all training labels
        # must be supported. Message preserved verbatim, typo included — it is
        # part of the reference's observable behavior.
        label_list = labels.tolist()
        for lang in dict.fromkeys(label_list):
            if lang not in lang_to_idx:
                raise ValueError(
                    f"Input data contians {lang}, but it is not "
                    f"in the list of supported languages"
                )

        # Validation B (LanguageDetector.scala:232-238): every supported
        # language needs at least one training row.
        label_set = set(label_list)
        for lang in supported:
            if lang not in label_set:
                raise ValueError(
                    f"No training examples found for language {lang}. "
                    f"Provide examples for each language"
                )

        spec = self._vocab_spec()
        docs = texts_to_bytes(texts.tolist(), self.get("trainEncoding"))
        lang_idx = np.asarray([lang_to_idx[l] for l in label_list])
        # Root telemetry span: the count/weights/topk stage spans recorded
        # by ops.fit / ops.fit_tpu nest under "fit" (docs/OBSERVABILITY.md).
        # One request trace per fit; a raising fit dumps the flight
        # recorder's ring (when armed) before propagating. Transient
        # device/runtime failures replay the whole fit under the env-tuned
        # retry policy — the fit builds its accumulator from scratch each
        # attempt, so replay is exact; on a multi-process mesh the policy
        # and any armed fault plan are deterministic, so every process
        # replays together and collectives stay aligned
        # (docs/RESILIENCE.md §5).
        from ..resilience.policy import RetryPolicy

        policy = RetryPolicy.from_env()
        try:
            with trace_request(), span(
                "fit",
                rows=dataset.num_rows,
                backend=self.get("fitBackend"),
                languages=len(supported),
            ):
                ids, weights = policy.run(
                    lambda: self._fit_profile(spec, docs, lang_idx, supported),
                    site="fit/count",
                    log_fields={"rows": dataset.num_rows},
                )
        except Exception as e:
            flightrec.record_crash("fit", e)
            raise
        # Both modes store the compact columnar form (sorted unique ids +
        # weight rows); the device view picks dense-table vs LUT strategy.
        profile = GramProfile(
            spec=spec, languages=tuple(supported), ids=ids, weights=weights
        )
        log_event(
            _log, "fit.done", rows=dataset.num_rows, grams=profile.num_grams,
            languages=len(supported),
        )

        save_path = self.get("saveGrams")
        if save_path is not None:
            from ..persist.io import save_gram_dump

            save_gram_dump(save_path, profile)

        return self._build_model(profile)

    def _build_model(self, profile: GramProfile) -> "LanguageDetectorModel":
        """Profile → configured model — the estimator-configures-model tail
        shared by ``fit`` and ``fit_from_accumulator``."""
        model = LanguageDetectorModel(profile)
        model.set_default(inputCol=self.get_or_default("inputCol"))
        if self.is_set("backend"):
            model.set("backend", self.get("backend"))
        if self.is_set("quantization"):
            model.set("quantization", self.get("quantization"))
        for p in ("resultMode", "topK", "rejectThreshold"):
            if self.is_set(p):
                model.set(p, self.get(p))
        return model

    # -- incremental refit -----------------------------------------------------
    def accumulator(self) -> "FitAccumulator":
        """An empty incremental-fit accumulator configured exactly like this
        estimator's device fit (spec, languages, weight mode, profile size,
        encoding, batch rows, fit mesh). Feed it batches with
        ``acc.update(table)`` — the same pipelined count path ``fit`` uses —
        then :meth:`fit_from_accumulator`. See ``models.refit``."""
        from .refit import FitAccumulator

        return FitAccumulator.for_estimator(self)

    def fit_from_accumulator(self, acc: "FitAccumulator") -> "LanguageDetectorModel":
        """Model from an accumulated count table: re-runs only the on-device
        finalize (weighting + collective top-k + winner-rows collect) — bit-
        identical to ``fit`` on the concatenation of every batch the
        accumulator has seen. The accumulator must have been built under
        this estimator's exact fit configuration, and every supported
        language must have coverage (the same validation ``fit`` applies)."""
        if not acc.matches_estimator(self):
            raise ValueError(
                "accumulator state does not match this estimator's fit "
                "configuration (vocab spec / languages / weightMode / "
                "languageProfileSize); refit needs the exact fit setup "
                "its counts were accumulated under"
            )
        # Same transient-failure story as fit: finalize reads the count
        # table without donating it, so it is idempotent and replays
        # exactly under the env-tuned policy (the auto-refit daemon must
        # not die on a retryable device hiccup mid-refit).
        from ..resilience.policy import RetryPolicy

        policy = RetryPolicy.from_env()
        try:
            with trace_request(), span(
                "fit",
                rows=acc.docs_seen,
                backend="device",
                incremental=True,
                languages=len(acc.languages),
            ):
                ids, weights = policy.run(
                    acc.finalize,
                    site="fit/finalize",
                    log_fields={"rows": acc.docs_seen},
                )
        except Exception as e:
            flightrec.record_crash("fit", e)
            raise
        profile = GramProfile(
            spec=acc.spec, languages=acc.languages, ids=ids, weights=weights
        )
        log_event(
            _log, "refit.done", rows=acc.docs_seen, grams=profile.num_grams,
            languages=len(acc.languages), committed=acc.committed,
        )
        return self._build_model(profile)

    def _fit_profile(self, spec, docs, lang_idx, supported):
        """(ids, weights) via the configured fit backend — the body of the
        ``fit`` span (factored out so the crash hook wraps one site)."""
        if self.get("fitBackend") == "device":
            from ..api.runner import resolve_fit_mesh
            from ..ops.fit_tpu import (
                fit_profile_device,
                fit_profile_device_split,
            )

            # More than one visible device ⇒ run the distributed
            # training step on a data-parallel mesh (the reference's fit
            # is cluster-parallel via Spark shuffles; VERDICT r1 #3).
            mesh = resolve_fit_mesh()
            if (
                spec.mode == EXACT
                and max(spec.gram_lengths) > MAX_DEVICE_ID_GRAM_LEN
            ):
                # Exact n=4..5: no dense device table can hold the
                # long-gram id space — the split fit counts gram lengths
                # <= 3 on device and the long lengths through the exact
                # host path, merged with exact joint top-k (fit_tpu
                # docstring).
                return fit_profile_device_split(
                    docs,
                    lang_idx,
                    len(supported),
                    spec,
                    self.get("languageProfileSize"),
                    self.get("weightMode"),
                    batch_rows=self.get("fitBatchRows"),
                    mesh=mesh,
                )
            return fit_profile_device(
                docs,
                lang_idx,
                len(supported),
                spec,
                self.get("languageProfileSize"),
                self.get("weightMode"),
                batch_rows=self.get("fitBatchRows"),
                mesh=mesh,
            )
        return fit_ops.fit_profile_numpy(
            docs,
            lang_idx,
            len(supported),
            spec,
            self.get("languageProfileSize"),
            self.get("weightMode"),
        )


class LanguageDetectorModel(HasInputCol, HasOutputCol, _ResultModeParams):
    """Model/Transformer: appends the detected-language column.

    Reference: ``class LanguageDetectorModel`` (LanguageDetectorModel.scala:178-245).

    ``resultMode="segment"`` switches ``transform``/``detect`` to the
    span-level code-switch result type (docs/SEGMENTATION.md): the output
    column carries one JSON object per document — byte-offset spans,
    calibrated top-k languages, and the unknown reject — decoded by
    :func:`..segment.segment_documents` over the runner's per-cell device
    output. ``calibrate(heldout)`` fits the per-language temperatures the
    calibrated probabilities use; an uncalibrated model segments with
    T = 1.0 and stamps ``calibrated: false`` on every result.
    """

    predict_encoding = Param(
        "predictEncoding",
        "text→bytes for transform: 'utf8' (default) or 'low_byte' — the "
        "reference's predict path truncates UTF-16 units to their low byte "
        "(SURVEY.md Q2); 'low_byte' reproduces that for parity runs",
        lambda v: v in (UTF8, LOW_BYTE),
    )
    backend = Param(
        "backend",
        "'tpu' | 'cpu' | 'auto' | 'mesh' | 'mesh:vocab': where transform's "
        "scoring runs (the BASELINE north star's .setBackend switch). "
        "'mesh' shards micro-batches over every visible device (the "
        "reference's transform is cluster-parallel by default, "
        "LanguageDetectorModel.scala:219-240); 'mesh:vocab' additionally "
        "shards the dense weight table across a vocab mesh axis when it "
        "would be too large to replicate; 'auto' builds a mesh "
        "automatically when several accelerators are visible",
        lambda v: v in BACKENDS,
    )
    batch_size = Param(
        "batchSize",
        "micro-batch rows per device dispatch; None ⇒ auto per strategy",
        lambda v: v is None or _positive_int(v),
    )
    quantization = Param(
        "quantization",
        "'int8' | 'int16': score through the fused detect kernel with a "
        "quantized weight table (per-language f32 scales, f32 "
        "accumulation) — ~4x/2x fewer table bytes streamed per dispatch "
        "at a bounded argmax-agreement cost (docs/ARCHITECTURE.md "
        "quantized tolerance class; bench gates int16 at exact argmax "
        "parity, int8 at >= 0.999 agreement). None (default) keeps f32 "
        "tables and the strategy auto-select",
        lambda v: v in (None, "int8", "int16"),
    )
    max_score_bytes = Param(
        "maxScoreBytes",
        "score only the first N bytes of each document (UTF-8-boundary-"
        "safe truncation; fastText-style cap). Language identity saturates "
        "within a few hundred bytes, so N≈256 preserves accuracy while "
        "shipping ~len/N× fewer bytes over the host→device wire — the "
        "binding bottleneck for short-gram configs. None ⇒ score "
        "everything (reference behavior: the reference always scores the "
        "full document, LanguageDetectorModel.scala:139-152)",
        lambda v: v is None or _positive_int(v),
    )

    def __init__(self, profile: GramProfile, uid: str | None = None):
        super().__init__(uid, uid_prefix="LanguageDetectorModel")
        self.profile = profile
        self.set_default(
            inputCol="fulltext",
            outputCol="lang",
            predictEncoding=UTF8,
            backend=BACKEND_AUTO,
            batchSize=None,
            maxScoreBytes=None,
            quantization=None,
            resultMode="label",
            topK=3,
            rejectThreshold=0.0,
        )
        # Per-language temperature calibration (segment.calibrate) — not a
        # Param: it is fitted state like the profile, persisted alongside
        # it, never copied through paramMap metadata.
        self.calibration = None
        self._runner: BatchRunner | None = None
        # Concurrent transforms (the streaming engine runs >1 transform
        # worker) must not each build a runner: construction uploads device
        # arrays and triggers jit compiles, and last-writer-wins would leak
        # the loser's buffers.
        self._runner_lock = threading.Lock()

    # -- constructors mirroring reference conveniences ------------------------
    @staticmethod
    def from_gram_map(
        gram_probabilities: dict[bytes, "Sequence[float]"],
        gram_lengths: Sequence[int],
        languages: Sequence[str],
        uid: str | None = None,
    ) -> "LanguageDetectorModel":
        """Hand-built model from a gram→weights map — the reference's primary
        constructor shape (LanguageDetectorModel.scala:189-198)."""
        profile = GramProfile.from_gram_map(
            gram_probabilities, tuple(languages), tuple(gram_lengths)
        )
        return LanguageDetectorModel(profile, uid)

    def set_backend(self, value: str):
        return self.set("backend", value)

    def set_predict_encoding(self, value: str):
        return self.set("predictEncoding", value)

    def set_batch_size(self, value: int):
        return self.set("batchSize", value)

    def set_max_score_bytes(self, value: int | None):
        return self.set("maxScoreBytes", value)

    def set_quantization(self, value: str | None):
        return self.set("quantization", value)

    def set_result_mode(self, mode: str):
        return self.set("resultMode", mode)

    def set_top_k(self, k: int):
        return self.set("topK", k)

    def set_reject_threshold(self, value: float):
        return self.set("rejectThreshold", value)

    # -- reference accessors ---------------------------------------------------
    @property
    def supported_languages(self) -> tuple[str, ...]:
        return self.profile.languages

    @property
    def gram_lengths(self) -> tuple[int, ...]:
        return self.profile.spec.gram_lengths

    # The reference misspells this public accessor (gramLenghts,
    # LanguageDetectorModel.scala:180 — SURVEY.md Q10); keep the alias so
    # ported user code works.
    @property
    def gram_lenghts(self) -> tuple[int, ...]:
        return self.gram_lengths

    @property
    def gram_probabilities(self) -> dict[bytes, np.ndarray]:
        return self.profile.gram_probabilities

    # -- transform -------------------------------------------------------------
    def transform_schema(self, schema: Schema) -> Schema:
        """StringType check + append nullable string output column
        (LanguageDetectorModel.scala:206-210)."""
        require_string_column(schema, self.get_input_col())
        return schema.append(self.get_output_col(), STRING, nullable=True)

    def set(self, param, value):
        # Any param change invalidates the cached runner (batchSize, backend,
        # predictEncoding all affect dispatch).
        self._runner = None
        return super().set(param, value)

    def copy(self, extra=None):
        new = super().copy(extra)
        new._runner = None  # never share a runner (device arrays) via deepcopy
        return new

    # Locks can't be deepcopied/pickled (Params.copy deepcopies the model);
    # drop the runner with the lock — copies rebuild both lazily.
    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop("_runner_lock", None)
        state["_runner"] = None
        # Baked-artifact membership tables are mmap views of a local file —
        # meaningless (and unpicklable as views) in another process; copies
        # rebuild membership from the profile like any other model.
        state.pop("_prebuilt_membership", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._runner_lock = threading.Lock()

    def _get_runner(self) -> BatchRunner:
        with self._runner_lock:
            if self._runner is None:
                import numpy as _np

                from .profile import DENSE_TABLE_BUDGET_BYTES

                backend = self.get("backend")
                # Start from the plain data-parallel mesh; 'mesh:vocab' only
                # carves a vocab axis when the dense table is actually the
                # chosen device form — a cuckoo/LUT profile can't shard over
                # vocab, and shrinking the data axis for it would just
                # duplicate compute.
                mesh = resolve_mesh(
                    "mesh" if backend == BACKEND_MESH_VOCAB else backend
                )
                budget = DENSE_TABLE_BUDGET_BYTES
                if backend == BACKEND_MESH_VOCAB and mesh is not None:
                    # Sharding across devices makes the dense form
                    # affordable at device-count x the replication budget.
                    budget *= int(_np.prod(list(mesh.shape.values())))
                # The baked-artifact loader (artifacts.bake) attaches the
                # device membership tables it mmapped — built under the
                # default budget with no mesh. When this construction asks
                # for exactly that shape, skip the LUT/cuckoo rebuild and
                # hand the mapped views straight to the runner; any other
                # geometry (vocab mesh, widened budget) rebuilds from the
                # profile as before.
                prebuilt = getattr(self, "_prebuilt_membership", None)
                if (
                    prebuilt is not None
                    and mesh is None
                    and budget == prebuilt["dense_budget_bytes"]
                ):
                    weights, lut, cuckoo = (
                        prebuilt["weights"], prebuilt["lut"],
                        prebuilt["cuckoo"],
                    )
                else:
                    weights, lut, cuckoo = self.profile.device_membership(
                        dense_budget_bytes=budget
                    )
                if backend == BACKEND_MESH_VOCAB and mesh is not None:
                    dense = (
                        lut is None
                        and cuckoo is None
                        and weights.shape[0]
                        == self.profile.spec.id_space_size
                    )
                    if dense:
                        mesh = resolve_mesh(
                            "mesh:vocab", table_bytes=int(weights.nbytes)
                        )
                    else:
                        log_event(
                            _log,
                            "mesh_vocab.fallback_data_parallel",
                            reason="device form is compact (cuckoo/LUT); "
                            "vocab axis would not shard anything",
                        )
                self._runner = BatchRunner(
                    weights=weights,
                    lut=lut,
                    cuckoo=cuckoo,
                    spec=self.profile.spec,
                    batch_size=self.get("batchSize"),
                    quantization=self.get("quantization"),
                    device=(
                        None if mesh is not None else resolve_device(backend)
                    ),
                    mesh=mesh,
                    max_score_bytes=self.get("maxScoreBytes"),
                    # maxScoreBytes truncation must know how the docs were
                    # encoded: low_byte docs take a hard slice (bytes in
                    # 0x80-0xBF are characters there, not UTF-8
                    # continuations the cap should back off).
                    score_encoding=self.get("predictEncoding"),
                )
            return self._runner

    def transform(self, dataset: Table) -> Table:
        out_schema = self.transform_schema(dataset.schema)
        texts = dataset.column(self.get_input_col()).tolist()
        docs = texts_to_bytes(texts, self.get("predictEncoding"))
        if self.get("resultMode") == "segment":
            import json

            # Segment mode: the output column carries one canonical JSON
            # object per document (sort_keys ⇒ byte-stable for identical
            # results — stream/batch/serve parity is string equality).
            # Same STRING schema as label mode, so every Table/stream
            # consumer composes unchanged.
            detected = [
                json.dumps(r, sort_keys=True)
                for r in self.segment_bytes(docs)
            ]
        else:
            runner = self._get_runner()
            detected = runner.predict(docs, self.profile.languages)
        result = dataset.with_column(self.get_output_col(), detected, STRING)
        if result.schema != out_schema:
            raise RuntimeError(
                "transform produced a schema that disagrees with "
                f"transform_schema: {result.schema} != {out_schema}"
            )
        return result

    def detect(self, text: str):
        """Single-document convenience — the reference's static ``detect``
        (LanguageDetectorModel.scala:131-165) as a method. In
        ``resultMode="segment"`` this returns the decoded result dict
        (spans, top-k, reject — docs/SEGMENTATION.md) instead of one
        label string."""
        if self.get("resultMode") == "segment":
            return self.segment([text])[0]
        return self.transform(Table({self.get_input_col(): [text]})).column(
            self.get_output_col()
        )[0]

    # -- segmentation ----------------------------------------------------------
    def _segment_options(self):
        from ..segment import SegmentOptions

        return SegmentOptions(
            top_k=int(self.get("topK")),
            reject_threshold=float(self.get("rejectThreshold")),
        )

    def segment(self, texts: Sequence[str]) -> list[dict]:
        """Span-level code-switch decode for ``texts``
        (docs/SEGMENTATION.md): one dict per document with byte-offset
        ``spans``, calibrated ``topk`` candidates, and the ``unknown``
        reject — regardless of the ``resultMode`` param (``transform``
        consults the param; this method IS segment mode)."""
        return self.segment_bytes(
            texts_to_bytes(list(texts), self.get("predictEncoding"))
        )

    def segment_bytes(self, byte_docs: Sequence[bytes]) -> list[dict]:
        from ..segment import segment_documents

        return segment_documents(
            self._get_runner(),
            byte_docs,
            self.profile.languages,
            options=self._segment_options(),
            calibration=self.calibration,
        )

    def calibrate(
        self, heldout: Table, *, label_col: str = "lang"
    ) -> "LanguageDetectorModel":
        """Fit the per-language temperature calibration on a held-out
        labeled table (``inputCol`` text + ``label_col`` true language) —
        docs/SEGMENTATION.md §calibration. Deterministic (fixed grids, no
        RNG); the fitted state lives on ``self.calibration``, persists
        with the model (``write().save``), and stamps every segment
        result ``calibrated: true``. Returns ``self``.
        """
        from ..segment.calibrate import fit_calibration, normalize_scores

        texts = heldout.column(self.get_input_col()).tolist()
        labels = heldout.column(label_col).tolist()
        langs = list(self.profile.languages)
        lang_idx = {l: i for i, l in enumerate(langs)}
        unknown = sorted({l for l in labels if l not in lang_idx})
        if unknown:
            raise ValueError(
                f"held-out labels {unknown} not in supportedLanguages"
            )
        docs = texts_to_bytes(texts, self.get("predictEncoding"))
        runner = self._get_runner()
        scores = runner.score(docs)
        # Length-normalize by the byte count the runner actually scored
        # (maxScoreBytes truncation included) — the same transform the
        # segment decode applies at serve time, or the temperatures would
        # be fit on a different logit scale than they are used on.
        cap = runner.max_score_bytes
        if cap:
            if runner.score_encoding == UTF8:
                from ..ops.encoding import truncate_utf8

                lens = [len(truncate_utf8(d, cap)) for d in docs]
            else:
                lens = [min(len(d), cap) for d in docs]
        else:
            lens = [len(d) for d in docs]
        self.calibration = fit_calibration(
            normalize_scores(np.asarray(scores, dtype=np.float64), lens),
            np.asarray([lang_idx[l] for l in labels], dtype=np.int64),
            len(langs),
        )
        log_event(
            _log, "model.calibrated", uid=self.uid,
            heldout_docs=len(docs), **{
                k: v for k, v in self.calibration.meta.items()
                if k.startswith(("nll_", "ece_"))
            },
        )
        return self

    # -- persistence -----------------------------------------------------------
    def write(self) -> "_ModelWriter":
        return _ModelWriter(self)

    def save(self, path: str) -> None:
        """Overwrite semantics, like the reference's writer
        (SaveMode.Overwrite, LanguageDetectorModel.scala:43). Use
        ``write().save(path)`` for the fail-if-exists contract."""
        self.write().overwrite().save(path)

    @staticmethod
    def load(path: str) -> "LanguageDetectorModel":
        from ..persist.io import load_model

        profile, uid, params, calibration = load_model(path)
        model = LanguageDetectorModel(profile, uid=uid)
        model._set_params_from_metadata(params)
        if calibration is not None:
            from ..segment.calibrate import Calibration

            model.calibration = Calibration.from_dict(calibration)
        return model


class _ModelWriter:
    """``model.write().save(path)`` — MLWritable shape
    (LanguageDetectorModel.scala:242)."""

    def __init__(self, model: LanguageDetectorModel):
        self._model = model
        self._overwrite = False  # MLWriter contract: destructive only after .overwrite()
        self._layout = "native"
        self._quantize: str | None = None

    def overwrite(self) -> "_ModelWriter":
        self._overwrite = True
        return self

    def quantized(self, dtype: str = "int8") -> "_ModelWriter":
        """Store the weight table quantized ('int8' | 'int16'): integer
        parquet rows + per-language f32 scales in the metadata — 4x/2x
        less disk, save/load-stable fused quantized scores (native layout
        only; see persist.io.save_model)."""
        self._quantize = dtype
        return self

    def reference_layout(self) -> "_ModelWriter":
        """Write the Scala implementation's on-disk shape (tuple-column
        probabilities parquet, JVM class name) so Spark's reader
        (LanguageDetectorModel.scala:60-105) can load the model. Exact
        vocabs only."""
        self._layout = "reference"
        return self

    def save(self, path: str) -> None:
        from ..persist.io import save_model

        calibration = self._model.calibration
        save_model(
            path,
            self._model.profile,
            self._model.uid,
            self._model.param_metadata(),
            overwrite=self._overwrite,
            layout=self._layout,
            quantize=self._quantize,
            calibration=(
                None if calibration is None else calibration.to_dict()
            ),
        )
