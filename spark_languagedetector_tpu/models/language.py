"""Language enumeration: ISO 639-1 codes in canonical vector order.

Parity with the reference's ``Language`` enumeration
(``/root/reference/src/main/.../language/Language.scala:11-201``): the same 182
ISO 639-1 codes in the same order, where the index of a code is its intended
position in a full-coverage probability vector. As in the reference (SURVEY.md
§2.9 Q10) the estimator/model accept arbitrary language-string sequences; this
enum is the documented canonical ordering plus a validation vocabulary.
"""

from __future__ import annotations

# Same codes, same order as the reference enum (Language.scala:13-196).
ISO_LANGUAGE_CODES: tuple[str, ...] = (
    "ab", "aa", "af", "ak", "sq", "am", "ar", "an", "hy", "as",
    "av", "ae", "ay", "az", "bm", "ba", "eu", "be", "bn", "bh",
    "bi", "bs", "br", "bg", "my", "ca", "km", "ch", "ce", "ny",
    "zh", "cu", "cv", "kw", "co", "cr", "hr", "cs", "da", "dv",
    "nl", "dz", "en", "eo", "et", "ee", "fj", "fi", "fr", "ff",
    "gd", "gl", "lg", "ka", "de", "ki", "el", "kl", "gn", "gu",
    "ht", "ha", "he", "hz", "hi", "ho", "hu", "is", "io", "ig",
    "id", "ia", "ie", "iu", "ik", "ga", "it", "ja", "jv", "kn",
    "kr", "ks", "kk", "rw", "kv", "kg", "ko", "kj", "ku", "ky",
    "lo", "la", "lv", "lb", "li", "ln", "lt", "lu", "mk", "mg",
    "ms", "ml", "mt", "gv", "mi", "mr", "mh", "ro", "mn", "na",
    "nv", "nd", "ng", "ne", "se", "no", "nb", "nn", "ii", "oc",
    "oj", "or", "om", "os", "pi", "pa", "ps", "fa", "pl", "pt",
    "qu", "rm", "rn", "ru", "sm", "sg", "sa", "sc", "sr", "sn",
    "sd", "si", "sk", "sl", "so", "st", "nr", "es", "su", "sw",
    "ss", "sv", "tl", "ty", "tg", "ta", "tt", "te", "th", "bo",
    "ti", "to", "ts", "tn", "tr", "tk", "tw", "uk", "ur", "uz",
    "ve", "vi", "vo", "wa", "cy", "fy", "wo", "xh", "yi", "yo",
    "za", "zu",
)

_INDEX: dict[str, int] = {code: i for i, code in enumerate(ISO_LANGUAGE_CODES)}


class Language:
    """Enumeration value: a code plus its canonical vector position."""

    __slots__ = ("code", "id")

    def __init__(self, code: str):
        if code not in _INDEX:
            raise KeyError(f"No language with name {code!r}")
        self.code = code
        self.id = _INDEX[code]

    # Reference API: ``Language.withName("de")`` (LanguageSpecs.scala:10-14).
    @staticmethod
    def with_name(code: str) -> "Language":
        return Language(code)

    @staticmethod
    def is_supported(code: str) -> bool:
        return code in _INDEX

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Language) and other.code == self.code

    def __hash__(self) -> int:
        return hash(self.code)

    def __repr__(self) -> str:
        return f"Language({self.code!r}, id={self.id})"

    def __str__(self) -> str:
        return self.code
