"""models subpackage."""
