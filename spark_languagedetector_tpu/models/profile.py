"""GramProfile: the trained model state, host + device views.

The reference's model state is ``Map[Seq[Byte], Array[Double]]`` — a JVM map
from gram bytes to per-language log-weights
(``/root/reference/src/main/.../LanguageDetectorModel.scala:179``). The
TPU-native state is columnar: a sorted id vector plus a compact weight matrix
(both vocab modes), with hashed profiles also accepted in dense ``[V, L]``
bucket-table form. The map view is still offered for API/test parity
(``gram_probabilities``).

Device view strategy (``device_membership``): there is no TPU analog of the
reference's pointer-chasing hash lookup, and binary search (``searchsorted``)
lowers to a serial scan — so membership is resolved by *tables*:

* when the dense ``[id_space, L]`` weight table fits a budget, window ids
  index it directly (one gather, and the one-hot/pallas MXU strategies apply
  for gram lengths ≤ 2);
* otherwise a dense int32 ``[id_space]`` lookup table maps ids to rows of a
  compact ``[G+1, L]`` table (row G zeros for misses) — two small gathers —
  for id spaces that fit int32 (exact n ≤ 3, hashed 2^bits);
* exact gram lengths 4..5 exceed any int32 id space, so membership ships as
  a cuckoo hash table over packed byte keys (``ops.cuckoo``).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import cached_property

import jax.numpy as jnp
import numpy as np

from ..ops.vocab import EXACT, HASHED, VocabSpec

# Dense [id_space, L] tables at or under this size are shipped whole; larger
# ones go through the compact LUT path. 256MB ≈ the exact-trigram table at
# L=3 (202MB) passing, the hashed 2^20 table at L=176 (738MB f32) compacting.
DENSE_TABLE_BUDGET_BYTES = 256 * 1024 * 1024

# Quantized weight-table dtypes (the fused detect kernel's storage option):
# name -> (numpy dtype, symmetric integer range). Scales are per-language
# f32: w[r, l] ≈ q[r, l] * scale[l], so the dequantize multiply factors out
# of the window sum and is applied once per (doc, language) — accumulation
# stays f32 over exact integer products (docs/ARCHITECTURE.md tolerance
# classes).
QUANT_DTYPES: dict[str, tuple[str, int]] = {
    "int8": ("int8", 127),
    "int16": ("int16", 32767),
}


def quantize_weights(
    weights: np.ndarray, dtype: str
) -> tuple[np.ndarray, np.ndarray]:
    """Symmetric per-language absmax quantization: (q [R, L], scales [L]).

    ``q = rint(w / scale)`` with ``scale[l] = absmax(w[:, l]) / qmax``
    (all-zero columns get scale 1.0 so dequantize is total). Deterministic
    (``np.rint`` half-to-even), and a fixed point of
    quantize∘dequantize: requantizing ``q * scale`` returns ``q`` exactly,
    which is what makes the persisted int8/int16 form round-trip to
    bit-identical quantized scores (pinned by tests/test_score_fused.py).
    """
    if dtype not in QUANT_DTYPES:
        raise ValueError(
            f"unknown quantization dtype {dtype!r}; expected one of "
            f"{tuple(QUANT_DTYPES)}"
        )
    np_dtype, qmax = QUANT_DTYPES[dtype]
    w = np.asarray(weights, dtype=np.float32)
    absmax = np.abs(w).max(axis=0) if w.size else np.zeros(w.shape[1])
    scales = np.where(absmax > 0, absmax / qmax, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scales), -qmax, qmax).astype(np_dtype)
    return q, scales


def dequantize_weights(q: np.ndarray, scales: np.ndarray) -> np.ndarray:
    """float32 [R, L] reconstruction ``q * scale`` (exact in f32 — the
    products are small integers times one float)."""
    return q.astype(np.float32) * np.asarray(scales, dtype=np.float32)


@dataclass(frozen=True)
class GramProfile:
    """Immutable trained profile.

    ``ids``: int64 [G] ascending gram ids (compact form). A hashed profile
    may instead be *dense*: ``ids`` empty and ``weights`` covering all
    ``2^hash_bits`` buckets.
    ``weights``: float [G, L] (compact) or [V, L] (dense hashed).
    ``languages``: decision order — index i ⇒ ``languages[i]`` (the
    reference's ``supportedLanguages(argmax)``).
    """

    spec: VocabSpec
    languages: tuple[str, ...]
    ids: np.ndarray
    weights: np.ndarray

    def __post_init__(self):
        if self.is_dense:
            if self.spec.mode == EXACT:
                raise ValueError("exact profiles must be compact (ids + weights)")
        else:
            if self.ids.shape[0] != self.weights.shape[0]:
                raise ValueError(
                    f"ids/weights mismatch: {self.ids.shape} vs {self.weights.shape}"
                )
            if len(self.ids) > 1 and not bool(np.all(np.diff(self.ids) > 0)):
                raise ValueError("profile ids must be strictly ascending")
            if len(self.ids) and (
                int(self.ids[0]) < 0
                or int(self.ids[-1]) >= self.spec.id_space_size
            ):
                # A negative id would wrap through numpy indexing into the
                # wrong table row — the same silent-corruption class as a
                # NaN weight; reject at the boundary instead.
                raise ValueError(
                    f"profile ids must lie in [0, {self.spec.id_space_size}); "
                    f"got range [{int(self.ids[0])}, {int(self.ids[-1])}]"
                )
        if self.weights.shape[1] != len(self.languages):
            raise ValueError(
                f"weights have {self.weights.shape[1]} columns for "
                f"{len(self.languages)} languages"
            )
        # Trust boundary: profiles are built from fit output or persisted
        # artifacts; a NaN/Inf weight would silently corrupt every argmax.
        from ..utils.debug import assert_finite

        assert_finite(self.weights, "profile weights")

    @property
    def is_dense(self) -> bool:
        """True for the dense hashed bucket-table form."""
        return (
            self.spec.mode == HASHED
            and self.ids.shape[0] == 0
            and self.weights.shape[0] == self.spec.id_space_size
        )

    @property
    def num_languages(self) -> int:
        return len(self.languages)

    @property
    def num_grams(self) -> int:
        return int(self.weights.shape[0]) if self.is_dense else int(self.ids.shape[0])

    # -- form conversion -------------------------------------------------------
    def compacted(self) -> "GramProfile":
        """Compact form: nonzero rows only (no-op if already compact)."""
        if not self.is_dense:
            return self
        nonzero = np.flatnonzero(np.abs(self.weights).sum(axis=1))
        return GramProfile(
            spec=self.spec,
            languages=self.languages,
            ids=nonzero.astype(np.int64),
            weights=np.ascontiguousarray(self.weights[nonzero]),
        )

    def _dense_table(self, dtype) -> np.ndarray:
        if self.is_dense:
            return np.asarray(self.weights, dtype=dtype)
        table = np.zeros((self.spec.id_space_size, self.num_languages), dtype=dtype)
        if len(self.ids):
            table[self.ids] = self.weights
        return table

    # -- device view -----------------------------------------------------------
    def device_arrays(
        self,
        dtype=jnp.float32,
        dense_budget_bytes: int = DENSE_TABLE_BUDGET_BYTES,
    ) -> tuple[jnp.ndarray, jnp.ndarray | None]:
        """(weights_dev, lut_dev) ready for ``ops.score.score_batch``.

        ``lut_dev`` is None when the dense table fits ``dense_budget_bytes``
        (direct indexing — and the one-hot MXU strategy becomes eligible);
        otherwise an int32 [id_space] id→row table plus compact weights with
        the zeros miss-row appended at row G.
        """
        from ..ops.vocab import MAX_DEVICE_ID_GRAM_LEN

        if (
            self.spec.mode == EXACT
            and max(self.spec.gram_lengths) > MAX_DEVICE_ID_GRAM_LEN
        ):
            raise ValueError(
                "exact gram lengths > 3 have no dense/LUT device form "
                "(id space exceeds int32); use device_membership(), whose "
                "cuckoo table handles them"
            )
        itemsize = jnp.dtype(dtype).itemsize
        L = self.num_languages
        V = self.spec.id_space_size
        dense_bytes = V * L * itemsize
        # LUT (int32 [V]) + compact weights — what the alternative costs.
        compact_bytes = V * 4 + (self.num_grams + 1) * L * itemsize
        use_dense = dense_bytes <= dense_budget_bytes and (
            # Short exact grams: the table is small and enables the
            # gather-free one-hot MXU strategy — always worth shipping dense.
            (self.spec.mode == EXACT and max(self.spec.gram_lengths) <= 2)
            # Otherwise only when dense isn't grossly larger than compact
            # (a tiny profile over a 2^24 exact-trigram id space would
            # otherwise ship hundreds of MB of zeros).
            or dense_bytes <= 4 * compact_bytes
        )
        if use_dense:
            np_dtype = np.float64 if itemsize > 4 else np.float32
            return jnp.asarray(self._dense_table(np_dtype), dtype=dtype), None
        compact = self.compacted()
        G = compact.num_grams
        w = np.concatenate(
            [compact.weights, np.zeros((1, L), compact.weights.dtype)]
        )
        lut = np.full(self.spec.id_space_size, G, dtype=np.int32)
        lut[compact.ids] = np.arange(G, dtype=np.int32)
        return jnp.asarray(w, dtype=dtype), jnp.asarray(lut)

    def device_membership(
        self,
        dtype=jnp.float32,
        dense_budget_bytes: int = DENSE_TABLE_BUDGET_BYTES,
    ):
        """(weights_dev, lut_dev, cuckoo) — the general device view.

        Exact vocabs with gram lengths > 3 overflow int32 device ids and the
        LUT over their id space is impossible, so membership ships as a
        cuckoo table over packed keys (``ops.cuckoo``); everything else
        returns the :meth:`device_arrays` forms with ``cuckoo=None``.
        """
        from ..ops.cuckoo import build_cuckoo
        from ..ops.vocab import MAX_DEVICE_ID_GRAM_LEN, gram_key

        if (
            self.spec.mode == EXACT
            and max(self.spec.gram_lengths) > MAX_DEVICE_ID_GRAM_LEN
        ):
            L = self.num_languages
            keys = [gram_key(self.spec.id_to_gram(int(i))) for i in self.ids]
            keys_lo = np.asarray([k[0] for k in keys], dtype=np.int32)
            keys_hi = np.asarray([k[1] for k in keys], dtype=np.int32)
            table = build_cuckoo(keys_lo, keys_hi)
            w = np.concatenate(
                [self.weights, np.zeros((1, L), self.weights.dtype)]
            )
            return jnp.asarray(w, dtype=dtype), None, table
        w, lut = self.device_arrays(dtype, dense_budget_bytes)
        return w, lut, None

    def host_arrays(self) -> tuple[np.ndarray, np.ndarray | None]:
        """(weights, sorted_ids) for ``ops.score.score_batch_numpy``: compact
        weights + miss row + ascending ids (searchsorted membership — fast on
        CPU), or the dense table + None for dense hashed profiles."""
        if self.is_dense:
            return self.weights, None
        w = np.concatenate(
            [self.weights, np.zeros((1, self.num_languages), self.weights.dtype)]
        )
        return w, self.ids

    # -- map view (reference API parity) --------------------------------------
    @cached_property
    def gram_probabilities(self) -> dict[bytes, np.ndarray]:
        """``Map[gram bytes → weight vector]`` — exact mode only."""
        if self.spec.mode != EXACT:
            raise ValueError(
                "hashed profiles store bucket weights, not gram byte maps"
            )
        return {
            self.spec.id_to_gram(int(i)): self.weights[r]
            for r, i in enumerate(self.ids)
        }

    @staticmethod
    def from_gram_map(
        gram_map: dict[bytes, "np.ndarray | list[float]"],
        languages: tuple[str, ...] | list[str],
        gram_lengths: tuple[int, ...] | list[int],
    ) -> "GramProfile":
        """Build an exact profile from a hand-written gram→weights map — the
        reference tests' oracle pattern (LanguageDetectorModelSpecs.scala:26-35).
        """
        spec = VocabSpec(EXACT, tuple(gram_lengths))
        items = sorted(
            ((spec.gram_to_id(g), np.asarray(w, dtype=np.float64)) for g, w in gram_map.items()),
            key=lambda kv: kv[0],
        )
        ids = np.asarray([i for i, _ in items], dtype=np.int64)
        L = len(languages)
        weights = (
            np.stack([w for _, w in items])
            if items
            else np.zeros((0, L), dtype=np.float64)
        )
        return GramProfile(
            spec=spec, languages=tuple(languages), ids=ids, weights=weights
        )
