"""GramProfile: the trained model state, host + device views.

The reference's model state is ``Map[Seq[Byte], Array[Double]]`` — a JVM map
from gram bytes to per-language log-weights
(``/root/reference/src/main/.../LanguageDetectorModel.scala:179``). The
TPU-native state is columnar: a sorted id vector plus a dense weight matrix
(exact mode), or just a dense ``[V, L]`` bucket table (hashed mode). The map
view is still offered for API/test parity (``gram_probabilities``).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import cached_property

import jax.numpy as jnp
import numpy as np

from ..ops.vocab import EXACT, HASHED, VocabSpec


@dataclass(frozen=True)
class GramProfile:
    """Immutable trained profile.

    ``ids``: int64 [G] ascending gram ids (exact mode; empty for hashed).
    ``weights``: float [G, L] (exact) or [V, L] (hashed) — no miss row; the
    scoring-time zeros row is appended in the device view.
    ``languages``: decision order — index i ⇒ ``languages[i]`` (the reference's
    ``supportedLanguages(argmax)``).
    """

    spec: VocabSpec
    languages: tuple[str, ...]
    ids: np.ndarray
    weights: np.ndarray

    def __post_init__(self):
        if self.spec.mode == EXACT:
            if self.ids.shape[0] != self.weights.shape[0]:
                raise ValueError(
                    f"ids/weights mismatch: {self.ids.shape} vs {self.weights.shape}"
                )
            if len(self.ids) > 1 and not bool(np.all(np.diff(self.ids) > 0)):
                raise ValueError("exact profile ids must be strictly ascending")
        else:
            if self.weights.shape[0] != self.spec.id_space_size:
                raise ValueError(
                    f"hashed weights must have {self.spec.id_space_size} rows, "
                    f"got {self.weights.shape[0]}"
                )
        if self.weights.shape[1] != len(self.languages):
            raise ValueError(
                f"weights have {self.weights.shape[1]} columns for "
                f"{len(self.languages)} languages"
            )

    @property
    def num_languages(self) -> int:
        return len(self.languages)

    @property
    def num_grams(self) -> int:
        return int(self.ids.shape[0]) if self.spec.mode == EXACT else int(
            self.weights.shape[0]
        )

    # -- device view -----------------------------------------------------------
    def device_arrays(self, dtype=jnp.float32) -> tuple[jnp.ndarray, jnp.ndarray | None]:
        """(weights_dev, sorted_ids_dev) ready for ``ops.score.score_batch``.

        Exact mode appends the zeros miss-row; ids go to int32 (the exact id
        space is ≤ 2^25, int32-safe by VocabSpec's construction).
        """
        if self.spec.mode == EXACT:
            w = np.concatenate(
                [self.weights, np.zeros((1, self.num_languages), self.weights.dtype)]
            )
            return (
                jnp.asarray(w, dtype=dtype),
                jnp.asarray(self.ids.astype(np.int32)),
            )
        return jnp.asarray(self.weights, dtype=dtype), None

    # -- map view (reference API parity) --------------------------------------
    @cached_property
    def gram_probabilities(self) -> dict[bytes, np.ndarray]:
        """``Map[gram bytes → weight vector]`` — exact mode only."""
        if self.spec.mode != EXACT:
            raise ValueError(
                "hashed profiles store bucket weights, not gram byte maps"
            )
        return {
            self.spec.id_to_gram(int(i)): self.weights[r]
            for r, i in enumerate(self.ids)
        }

    @staticmethod
    def from_gram_map(
        gram_map: dict[bytes, "np.ndarray | list[float]"],
        languages: tuple[str, ...] | list[str],
        gram_lengths: tuple[int, ...] | list[int],
    ) -> "GramProfile":
        """Build an exact profile from a hand-written gram→weights map — the
        reference tests' oracle pattern (LanguageDetectorModelSpecs.scala:26-35).
        """
        spec = VocabSpec(EXACT, tuple(gram_lengths))
        items = sorted(
            ((spec.gram_to_id(g), np.asarray(w, dtype=np.float64)) for g, w in gram_map.items()),
            key=lambda kv: kv[0],
        )
        ids = np.asarray([i for i, _ in items], dtype=np.int64)
        L = len(languages)
        weights = (
            np.stack([w for _, w in items])
            if items
            else np.zeros((0, L), dtype=np.float64)
        )
        return GramProfile(
            spec=spec, languages=tuple(languages), ids=ids, weights=weights
        )
