"""Text preprocessors: lower-casing and special-character cleanup.

TPU-native re-implementations of the reference's two Transformers
(``/root/reference/src/main/.../preprocessing/``). Both preserve the
reference's deliberate API quirks — documented because they are observable
behavior (SURVEY.md §2.9 Q8):

  * ``set_input_col`` sets the OUTPUT column (the transformers operate
    in-place on one column, reading and writing ``outputCol``);
  * ``transform_schema`` drops the column and re-appends it last.

The reference's *broken* behaviors are fixed, not replicated (SURVEY.md Q3/Q4:
its symbol regex is syntactically invalid and would throw on first use, and
its whitespace rule deletes every space): this implementation strips the
symbol set the reference *intended* (``"<[]>/\\`` plus the rest of the chars
in its regex literal) and squashes whitespace runs to a single space.
"""

from __future__ import annotations

import re

from ..api.params import HasLabelCol, HasOutputCol
from ..api.table import STRING, Schema, Table

# The characters the reference's regex literal tried to express
# (SpecialCharPreprocessor.scala:55): /_[]*()%^&@$#:|{}<>~`"\
_SYMBOL_RE = re.compile(r'[/_\[\]*()%^&@$#:|{}<>~`"\\]')
_WHITESPACE_RE = re.compile(r"\s+")

# Locale-sensitive lower-casing: Java's String.toLowerCase(Locale) differs
# from the root locale only for Turkish/Azerbaijani (dotted/dotless i) and
# Lithuanian (dot retention, which Python's str.lower matches closely enough
# for byte-profile purposes). The reference derives the locale from the
# row's *label* column (LowerCasePreprocessor.scala:60) — usable only on
# labeled training data; we mirror that.
_TURKIC = {"tr", "az"}


def _lower_locale(text: str, lang_tag: str) -> str:
    base = lang_tag.split("-")[0].lower() if lang_tag else ""
    if base in _TURKIC:
        # Java tr/az rules: I → ı, İ → i (combining-dot subtleties aside).
        text = text.replace("İ", "i").replace("I", "ı")
    return text.lower()


class _InPlaceColumnTransformer(HasOutputCol):
    """Shared shape: read ``outputCol``, rewrite it, move it last."""

    def set_input_col(self, value: str):
        # Reference quirk Q8: setInputCol sets outputCol
        # (LowerCasePreprocessor.scala:32, SpecialCharPreprocessor.scala:30).
        return self.set("outputCol", value)

    def transform_schema(self, schema: Schema) -> Schema:
        col = self.get_output_col()
        if col in schema:
            schema = schema.drop(col)
        return schema.append(col, STRING, nullable=True)

    def copy(self, extra=None):
        return super().copy(extra)


class LowerCasePreprocessor(_InPlaceColumnTransformer, HasLabelCol):
    """Locale-aware lower-casing using the row's label as locale tag.

    Reference: ``LowerCasePreprocessor`` (LowerCasePreprocessor.scala:19-77).
    """

    def __init__(self, uid: str | None = None):
        super().__init__(uid, uid_prefix="LowerCasePreprocessor")
        self.set_default(outputCol="fulltext", labelCol="lang")

    def transform(self, dataset: Table) -> Table:
        col, label_col = self.get_output_col(), self.get_label_col()
        texts = dataset.column(col)
        labels = dataset.column(label_col)
        lowered = [_lower_locale(t, l) for t, l in zip(texts, labels)]
        return dataset.replace_column(col, lowered, STRING)


class SpecialCharPreprocessor(_InPlaceColumnTransformer):
    """Strip symbols and squash whitespace runs to a single space.

    Reference: ``SpecialCharPreprocessor`` (SpecialCharPreprocessor.scala:19-71),
    implementing its *intended* behavior (its own regex is invalid — Q3/Q4).
    """

    def __init__(self, uid: str | None = None):
        super().__init__(uid, uid_prefix="SpecialCharPreprocessor")
        self.set_default(outputCol="fulltext")

    def transform(self, dataset: Table) -> Table:
        col = self.get_output_col()
        texts = dataset.column(col)
        cleaned = [
            _WHITESPACE_RE.sub(" ", _SYMBOL_RE.sub("", t)) for t in texts
        ]
        return dataset.replace_column(col, cleaned, STRING)
