"""Micro-batching runner: iterator-of-rows → padded device batches → rows.

The north star's execution contract (BASELINE.json): the reference's per-row
scoring UDF becomes a ``mapPartitions``-style sidecar that ships fixed-shape
micro-batches to the accelerator. This module is that sidecar, host side:

  * documents are grouped by (batch-size, padded-length) buckets so XLA sees
    a small, fixed set of [B, S] shapes (compile-once, reuse forever);
  * documents longer than the largest length bucket are chunked with
    ``max(gram_lengths) - 1`` overlap and their chunk scores summed — the
    bag-of-grams reduction is associative, so scores are exact, not truncated
    (SURVEY.md §5.7 long-context handling);
  * results are scattered back into input order; the output is a plain
    numpy array aligned with the input sequence.

Dispatch is double-buffered by construction: JAX's async dispatch queues each
micro-batch's computation while the host packs the next one; the only
synchronization is the final result fetch.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import score as score_ops
from ..ops.encoding import (
    DEFAULT_LENGTH_BUCKETS,
    bucket_length,
    chunk_document,
    pad_batch,
)
from ..ops.vocab import VocabSpec
from ..utils.logging import get_logger, log_event
from ..utils.metrics import Metrics

_log = get_logger("api.runner")

DEFAULT_BATCH_SIZE = 256


def resolve_device(backend: str):
    """Map a backend param value ('auto' | 'tpu' | 'cpu') to a jax device.

    'tpu' accepts any accelerator platform (tpu or a PJRT plugin exposing
    one); 'auto' is the process default (None ⇒ jax picks).
    """
    if backend == "auto":
        return None
    if backend == "cpu":
        return jax.devices("cpu")[0]
    for d in jax.devices():
        if d.platform != "cpu":
            return d
    raise RuntimeError(
        f"backend={backend!r} requested but no accelerator device is "
        f"visible (have {[d.platform for d in jax.devices()]})"
    )


@dataclass
class BatchRunner:
    """Scores arbitrary document collections through fixed-shape micro-batches.

    One runner per (profile, config); reuse it across calls to amortize
    compilation.
    """

    weights: jnp.ndarray
    lut: jnp.ndarray | None
    spec: VocabSpec
    batch_size: int = DEFAULT_BATCH_SIZE
    length_buckets: tuple[int, ...] = DEFAULT_LENGTH_BUCKETS
    block: int = score_ops.DEFAULT_BLOCK
    device: object | None = None  # jax device; None ⇒ process default
    strategy: str = "auto"  # 'auto' | 'gather' | 'onehot'
    metrics: Metrics = field(default_factory=Metrics)

    def __post_init__(self):
        if self.device is not None:
            self.weights = jax.device_put(self.weights, self.device)
            if self.lut is not None:
                self.lut = jax.device_put(self.lut, self.device)
        if self.strategy not in ("auto", "gather", "onehot"):
            raise ValueError(
                f"unknown strategy {self.strategy!r}; "
                "expected 'auto', 'gather', or 'onehot'"
            )
        if self.strategy == "auto":
            # One-hot MXU scoring (no gathers) when the vocab qualifies:
            # exact grams ⊆ {1,2} over the dense table.
            eligible = self.lut is None and score_ops.onehot_supported(
                self.spec, self.weights.shape[0]
            )
            self.strategy = "onehot" if eligible else "gather"
        if self.strategy == "onehot" and not score_ops.onehot_supported(
            self.spec, self.weights.shape[0]
        ):
            raise ValueError(
                "strategy='onehot' needs an exact vocab with gram lengths <= "
                f"{score_ops.ONEHOT_MAX_N} and the dense weight table"
            )
        # Trigger the one-time native-library build here, not inside the
        # first score() call's timed hot loop.
        from .. import native

        native.available()

    @property
    def max_chunk(self) -> int:
        return self.length_buckets[-1]

    @staticmethod
    def _pack(batch_docs, pad_to: int):
        """Padded packing: native C++ loader (falls back to numpy internally)."""
        from .. import native

        return native.pack_batch(batch_docs, pad_to)

    def score(self, byte_docs: Sequence[bytes]) -> np.ndarray:
        """float32 [N, L] scores in input order (exact over any doc length)."""
        N = len(byte_docs)
        L = self.weights.shape[1]
        out = np.zeros((N, L), dtype=np.float32)
        if N == 0:
            return out

        overlap = max(self.spec.gram_lengths) - 1
        stride = self.max_chunk - overlap

        # Expand long docs into chunks; each work item is
        # (doc_index, chunk_bytes, owned_window_starts).
        doc_idx: list[int] = []
        chunks: list[bytes] = []
        limits: list[int] = []
        for i, doc in enumerate(byte_docs):
            if len(doc) <= self.max_chunk:
                doc_idx.append(i)
                chunks.append(doc)
                limits.append(self.max_chunk)  # no-op limit
            else:
                parts = chunk_document(doc, self.max_chunk, overlap)
                for j, part in enumerate(parts):
                    doc_idx.append(i)
                    chunks.append(part)
                    # Non-final chunks own starts [0, stride); final owns all.
                    limits.append(stride if j < len(parts) - 1 else self.max_chunk)

        # Bucket by padded length, then emit fixed-size batches per bucket.
        order = np.argsort([len(c) for c in chunks], kind="stable")
        pending: list[tuple[np.ndarray, object]] = []
        with self.metrics.timer("score_s"):
            for start in range(0, len(order), self.batch_size):
                sel = order[start : start + self.batch_size]
                batch_docs = [chunks[k] for k in sel]
                pad_to = bucket_length(
                    max((len(d) for d in batch_docs), default=1),
                    self.length_buckets,
                )
                batch, lengths = self._pack(batch_docs, pad_to)
                batch_limits = [limits[k] for k in sel]
                # Batches without chunked docs (the common case) skip the
                # window-limit array entirely — one fewer host→device
                # transfer and a simpler compiled program.
                if all(lim == self.max_chunk for lim in batch_limits):
                    window_limit = None
                else:
                    window_limit = np.asarray(batch_limits, dtype=np.int32)
                if self.device is not None:
                    batch = jax.device_put(batch, self.device)
                    lengths = jax.device_put(lengths, self.device)
                    if window_limit is not None:
                        window_limit = jax.device_put(window_limit, self.device)
                elif window_limit is not None:
                    window_limit = jnp.asarray(window_limit)
                if self.strategy == "onehot":
                    scores = score_ops.score_batch_onehot(
                        batch,
                        lengths,
                        self.weights,
                        spec=self.spec,
                        block=min(self.block, 1024),
                        window_limit=window_limit,
                    )
                else:
                    scores = score_ops.score_batch(
                        batch,
                        lengths,
                        self.weights,
                        self.lut,
                        spec=self.spec,
                        block=self.block,
                        window_limit=window_limit,
                    )
                # Async dispatch: keep packing while the device works — and
                # start the device→host copy as soon as the compute finishes
                # (a cold fetch over a tunneled device costs ~100ms; the
                # async prefetch overlaps it with the remaining batches).
                scores.copy_to_host_async()
                pending.append((sel, scores))
                self.metrics.incr("chunks_scored", len(sel))

            doc_idx_arr = np.asarray(doc_idx, dtype=np.int64)
            for sel, scores in pending:
                np.add.at(out, doc_idx_arr[sel], np.asarray(scores))

        self.metrics.incr("docs_scored", N)
        log_event(
            _log,
            "runner.score",
            docs=N,
            chunks=len(chunks),
            batches=-(-len(chunks) // self.batch_size),
        )
        return out

    def predict(self, byte_docs: Sequence[bytes], languages: Sequence[str]) -> list[str]:
        scores = self.score(byte_docs)
        return [languages[i] for i in np.argmax(scores, axis=1)]
