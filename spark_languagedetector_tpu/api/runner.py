"""Micro-batching runner: iterator-of-rows → padded device batches → rows.

The north star's execution contract (BASELINE.json): the reference's per-row
scoring UDF becomes a ``mapPartitions``-style sidecar that ships fixed-shape
micro-batches to the accelerator. This module is that sidecar, host side:

  * documents are grouped by (batch-size, padded-length) buckets so XLA sees
    a small, fixed set of [B, S] shapes (compile-once, reuse forever);
  * documents longer than the largest length bucket are chunked with
    ``max(gram_lengths) - 1`` overlap and their chunk scores summed — the
    bag-of-grams reduction is associative, so scores are exact, not truncated
    (SURVEY.md §5.7 long-context handling);
  * results are scattered back into input order; the output is a plain
    numpy array aligned with the input sequence.

Dispatch is double-buffered by construction: JAX's async dispatch queues each
micro-batch's computation while the host packs the next one; the only
synchronization is the final result fetch.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..exec import config as exec_config
from ..exec.core import (
    dedup_counted,
    guarded_dispatch,
    plan_micro_batches,
    rows_under_byte_budget,
    run_ordered,
)
from ..ops import score as score_ops
from ..ops import score_fused
from ..ops import score_hist
from ..ops import score_pallas
from ..ops.encode_device import (
    DocBlock,
    chunk_table,
    encode_batch,
    gather_wire,
    utf8_safe_lengths,
    wire_capacity,
    wire_from_docs,
)
from ..ops.encoding import (
    ENCODINGS,
    RAGGED_CHUNK,
    UTF8,
    chunk_document,
    truncate_utf8,
    unpack_ragged_jit,
)
from ..ops.vocab import VocabSpec
from ..resilience import faults
from ..resilience.policy import CLOSED, CircuitBreaker, RetryPolicy
from ..telemetry import REGISTRY, flightrec, span, trace_request
from ..utils.logging import get_logger, log_event
from ..utils.metrics import Metrics

_log = get_logger("api.runner")

# Legacy shorthand for "transient-shaped" exceptions, kept for the cheap
# inline guards below (async-copy kickoff). Real replay decisions go
# through the runner's RetryPolicy classifier
# (resilience.policy.is_retryable), which additionally refuses
# RuntimeError subclasses that are programming errors
# (NotImplementedError, RecursionError).
RETRYABLE = (RuntimeError, OSError)

DEFAULT_BATCH_SIZE = 256
# The fused pallas kernel keeps per-document state in VMEM scratch (no
# O(B·vocab) HBM buffers), so its sweet spot is much larger micro-batches —
# fewer dispatches amortize the per-call host/tunnel overhead.
DEFAULT_PALLAS_BATCH_SIZE = 4096
# Hybrid strategy micro-batches: the pallas histogram part wants the same
# large batches as the pure pallas strategy (measured 2.2× over gather at
# 4096 rows vs 1.2× at 1024); the n ≥ 3 gather's scan block is capped at
# 256 windows so its [B, block, L] buffer stays bounded (~1.4GB at L=176).
DEFAULT_HYBRID_BATCH_SIZE = 4096
# Compute-heavy profiles (gram lengths >= 4 => three long-gram membership
# passes per doc) pipeline better with smaller micro-batches: the per-batch
# compute (~tens of ms) overlaps the wire at finer grain and the tail batch
# is smaller. A/B on the config-3 corpus (8k docs, tunneled v5e):
# 4096 -> 14.8k docs/s, 2048 -> 20.7k, 1024 -> 24.6k end-to-end.
DEFAULT_HEAVY_BATCH_SIZE = 1024
# Default concurrent dispatch threads for single-device batch execution
# (BatchRunner.dispatch_workers=None). Measured on the tunneled v5e
# (interleaved A/B, 6-8 rounds/config, docs/PERFORMANCE.md §4): the serial
# async-dispatch pipeline already saturates the wire — 3 workers landed at
# 0.93-0.95× the serial median on configs 1/2/3 — so the default stays 1;
# the knob remains for other link profiles (e.g. co-located PCIe).
DISPATCH_WORKERS = 1
# Default segmentation cell width in bytes (docs/SEGMENTATION.md): one
# per-cell score vector per `SEGMENT_CELL` window start positions. Must be
# a multiple of 128 — the fused segment kernel's window block IS the cell
# (lane tiling), and one rule for every strategy keeps fused/gather parity
# exact. 256B is fine-grained enough that a code-switch span of a sentence
# or two is visible, while the [B, C, L] result stays small.
SEGMENT_CELL = 256
# Default cap on a single micro-batch's padded bytes (= the `batch_bytes`
# config knob's built-in default; a tuning profile or LANGDETECT_BATCH_BYTES
# overrides it per deployment). Once a program has executed, h2d transfers
# ride the real device link (a tunneled relay here: ~30-90MB/s, bursty;
# pre-execution puts only stage locally and measure misleadingly fast).
# End-to-end A/B on the config-1 bench: 4096×2048 = 8MB batches beat both
# many smaller puts (per-transfer overhead) and 16MB batches (coarser
# transfer/compute overlap) — 0.37s vs 0.48-0.71s per 20k-doc pass.
MAX_BATCH_BYTES = 8 << 20


def rows_for_bucket(
    pad_to: int, batch_size: int, byte_budget: int | None = None
) -> int:
    """Micro-batch row count for a padded width: ``batch_size`` halved until
    the padded transfer fits the byte budget (64-row floor; ``byte_budget``
    None ⇒ the resolved `batch_bytes` knob). The single policy site —
    `BatchRunner._execute` plans with it and `bench.py`'s compute-only
    measurement reuses it so the timed shape can't drift from what the
    runner actually dispatches. The halving itself is the execution core's
    `exec.core.rows_under_byte_budget`, shared with the fit pipeline."""
    if byte_budget is None:
        byte_budget = int(exec_config.resolve("batch_bytes"))
    return rows_under_byte_budget(pad_to, byte_budget, batch_size)


def resolve_device(backend: str):
    """Map a backend param value ('auto' | 'tpu' | 'cpu') to a jax device.

    'tpu' accepts any accelerator platform (tpu or a PJRT plugin exposing
    one); 'auto' is the process default (None ⇒ jax picks).
    """
    if backend == "auto":
        return None
    # Single-device backends pick a LOCAL device: under jax.distributed,
    # jax.devices() leads with process 0's devices, which other processes
    # cannot copy to — only the mesh backends ever span processes.
    if backend == "cpu":
        return jax.local_devices(backend="cpu")[0]
    for d in jax.local_devices():
        if d.platform != "cpu":
            return d
    raise RuntimeError(
        f"backend={backend!r} requested but no accelerator device is "
        f"visible (have {[d.platform for d in jax.local_devices()]})"
    )


def resolve_mesh(backend: str, table_bytes: int | None = None):
    """Device mesh for a backend param value, or None for single-device.

    'mesh' always builds a data-parallel mesh over every visible device of
    the preferred platform (accelerators when present, else host CPUs —
    e.g. the 8-virtual-device test substrate). 'mesh:vocab' additionally
    carves a vocab axis so the dense weight table shards across devices
    instead of replicating: the axis is sized to the smallest power of two
    whose per-shard table fits the single-device replication budget
    (``table_bytes`` hint; 2 when unknown), the rest stays data-parallel.
    'auto' builds a mesh only when MORE than one accelerator is visible, so
    single-chip and CPU-test behavior keep the simple single-device
    dispatch path. The reference's ``transform`` is cluster-parallel by
    default (LanguageDetectorModel.scala:219-240 — ``Dataset.map`` over
    partitions); this is that default, TPU-native.
    """
    from ..models.profile import DENSE_TABLE_BUDGET_BYTES
    from ..parallel.mesh import build_mesh

    accel = [d for d in jax.devices() if d.platform != "cpu"]
    if backend == "mesh:vocab":
        devices = accel or jax.devices("cpu")
        n = len(devices)
        vocab = 2
        if table_bytes is not None:
            while vocab * 2 <= n and table_bytes / vocab > DENSE_TABLE_BUDGET_BYTES:
                vocab *= 2
        vocab = min(vocab, n)
        return build_mesh(data=n // vocab, vocab=vocab, devices=devices)
    if backend == "mesh":
        devices = accel or jax.devices("cpu")
        return build_mesh(data=len(devices), vocab=1, devices=devices)
    if backend == "auto" and len(accel) > 1:
        return build_mesh(data=len(accel), vocab=1, devices=accel)
    return None


def resolve_fit_mesh():
    """Mesh for ``fitBackend="device"``: every visible device when more than
    one (accelerators preferred, else the CPU test substrate), None on a
    single device. One policy site shared with :func:`resolve_mesh`'s device
    preference so the fit and transform paths can't drift."""
    from ..parallel.mesh import build_mesh

    devices = [d for d in jax.devices() if d.platform != "cpu"]
    if len(devices) < 2:
        devices = jax.devices()
    if len(devices) < 2:
        return None
    return build_mesh(data=len(devices), vocab=1, devices=devices)


@dataclass
class BatchRunner:
    """Scores arbitrary document collections through fixed-shape micro-batches.

    One runner per (profile, config); reuse it across calls to amortize
    compilation.

    Concurrent-caller contract (the online batcher and any threaded host
    rely on it, pinned by ``tests/test_serve.py``): ``score`` /
    ``predict_ids`` may be called from any number of threads at once on a
    single-device runner and return results bit-identical to serial
    calls. Each call plans, packs, and scatters into its own local state;
    the lazily-built shared caches (pallas/hybrid/hist/host state, the
    window-limit cache) are guarded by ``_state_lock``; metrics and the
    telemetry registry lock internally. Multi-process meshes are the
    exception — their collective schedule requires one call at a time,
    process-wide.
    """

    weights: jnp.ndarray
    lut: jnp.ndarray | None
    spec: VocabSpec
    batch_size: int | None = None  # None ⇒ auto per strategy
    # Padded-length bucket lattice. None ⇒ resolved through exec.config at
    # construction: LANGDETECT_LENGTH_BUCKETS, else the active tuning
    # profile's measured lattice, else the built-in default — the runner
    # loads the autotuner's output at startup.
    length_buckets: tuple[int, ...] | None = None
    # Byte budget per micro-batch transfer. None ⇒ exec.config resolution
    # (env LANGDETECT_BATCH_BYTES > tuning profile > MAX_BATCH_BYTES).
    batch_bytes: int | None = None
    # Window-axis scan block for the XLA strategies (gather/onehot) only;
    # the pallas kernel's window block is `pallas_block` (None ⇒ the kernel's
    # own default).
    block: int = score_ops.DEFAULT_BLOCK
    pallas_block: int | None = None
    device: object | None = None  # jax device; None ⇒ process default
    # Data-parallel device mesh (jax.sharding.Mesh). When set, micro-batches
    # are sharded over the mesh's "data" axis and the weight table is
    # replicated; GSPMD partitions the jitted scorer across all devices.
    # Mutually exclusive with `device`.
    mesh: object | None = None
    # 'auto'|'gather'|'onehot'|'pallas'|'hybrid'|'hist'|'fused'
    strategy: str = "auto"
    # Weight-table quantization for the fused strategy ('int8' | 'int16';
    # None ⇒ f32 tiles). Implies strategy='fused' under 'auto'; the scores
    # carry per-language dequantize scales (f32 accumulation — see the
    # quantized tolerance class in docs/ARCHITECTURE.md).
    quantization: str | None = None
    # VMEM budget per streamed fused-table tile (None ⇒ the kernel default;
    # docs/PERFORMANCE.md §7 knob table). Pallas double-buffers the tiles,
    # so live VMEM is 2x this.
    fused_tile_bytes: int | None = None
    # Ragged h2d transfer (chunk-aligned flat buffer + device-side unpack
    # gather; see ops.encoding.pack_ragged_numpy). None ⇒ on for
    # single-device dispatch. Ignored on a mesh — even if set True — since
    # the data-axis sharding of the padded batch is what GSPMD partitions;
    # a replicated flat buffer would forfeit the sharded transfer.
    ragged_transfer: bool | None = None
    # Device-side encode (docs/PERFORMANCE.md §11): ship each batch as raw
    # concatenated document bytes + int32 offsets and rebuild the padded
    # [B, S] plane inside the same jit as the scorer (one XLA gather), so
    # the host never materializes a padded or chunk-aligned buffer. None ⇒
    # exec.config resolution (LANGDETECT_DEVICE_ENCODE, default off — the
    # tuner stamps it on from a measured capture). Forced off on a mesh
    # (the data-axis sharding partitions the padded plane, not the wire);
    # DocBlock inputs always encode — that is the input form's point.
    device_encode: bool | None = None
    # Cuckoo membership (ops.cuckoo.CuckooTable, host arrays) for exact
    # vocabs with gram lengths > 3 — routed through the gather-style
    # dispatch with packed-key lookups instead of a LUT.
    cuckoo: object | None = None
    # Concurrent dispatch threads for the batch path: one worker's
    # pack+device_put hides another's tunnel round-trip, the same overlap
    # the streaming engine's transform workers buy (stream.microbatch).
    # None ⇒ auto: DISPATCH_WORKERS on single-device dispatch, 1 on a mesh —
    # in a multi-process mesh every process must enqueue collective programs
    # in the same order, and concurrent workers would make that order racy.
    dispatch_workers: int | None = None
    # Score only the first N bytes of each document (UTF-8-boundary-safe;
    # ops.encoding.truncate_utf8). None ⇒ score everything. Language
    # identity saturates within a few hundred bytes, so a ~256B cap cuts
    # the h2d wire bytes ~len/cap× on long-doc corpora at near-zero
    # accuracy cost — the wire is the binding wall for short-gram configs
    # (docs/PERFORMANCE.md §1).
    max_score_bytes: int | None = None
    # How the caller produced the byte docs (ops.encoding.ENCODINGS). Only
    # the truncation semantics of max_score_bytes depend on it: UTF-8 docs
    # back the cap off continuation bytes so no character is split, but in
    # low_byte docs 0x80-0xBF are ordinary characters — treating them as
    # continuations could back the cap off arbitrarily far below
    # max_score_bytes, so non-UTF-8 docs take a hard byte slice instead.
    score_encoding: str = UTF8
    # Failure handling (docs/RESILIENCE.md). ``retry_policy`` replays
    # transient dispatch/fetch failures with backoff (None ⇒ the env-tuned
    # default: replay-once). ``breaker`` trips after consecutive device
    # failures and gates the compiled fast path; while it is open (and
    # ``degraded_fallback`` is on — None ⇒ env ``LANGDETECT_DEGRADED`` not
    # "0"), scoring rides the degradation ladder (device gather escape
    # hatch → host scoring) instead of failing the call. Both are disabled
    # on a multi-process mesh: a fallback taken by one process alone would
    # desynchronize the process-wide collective schedule.
    retry_policy: RetryPolicy | None = None
    breaker: CircuitBreaker | None = None
    degraded_fallback: bool | None = None
    # In-flight content dedup (docs/PERFORMANCE.md §10): duplicate
    # documents in one call are planned, shipped, and scored ONCE — the
    # wire and the kernel see unique rows only — and every duplicate is
    # satisfied by a deterministic scatter-back of the fetched result
    # (``out = unique_out[inverse]``, so input order is exact). Scores of
    # the surviving unique rows may ride a different batch geometry than
    # an undeduped call's, which on matmul strategies can flip the last
    # f32 bit (the reduction-order class in docs/ARCHITECTURE.md);
    # gather/fused stay bit-exact. None ⇒ exec.config resolution
    # (``LANGDETECT_DEDUP``, default on).
    dedup: bool | None = None
    metrics: Metrics = field(default_factory=Metrics)

    def __post_init__(self):
        # Created first: strategy auto-selection below may already resolve
        # lazy state through the lock.
        self._state_lock = threading.Lock()
        # Execution-core knob resolution (explicit ctor > env > tuning
        # profile > default): the runner's shape lattice and transfer
        # budget come from one audited config site, so a tuned profile
        # lands here without any per-call-site plumbing.
        self.length_buckets = tuple(
            exec_config.resolve("length_buckets", self.length_buckets)
        )
        self.batch_bytes = int(
            exec_config.resolve("batch_bytes", self.batch_bytes)
        )
        if self.dispatch_workers is None:
            self.dispatch_workers = exec_config.resolve("dispatch_workers")
        if self.retry_policy is None:
            self.retry_policy = RetryPolicy.from_env()
        if self.breaker is None:
            self.breaker = CircuitBreaker.from_env(name="score")
        if self.degraded_fallback is None:
            # Through the audited config site so /varz's effective_config
            # and the live behavior can't disagree ("false"/"off"/"no"
            # now disable it too, not just "0").
            self.degraded_fallback = bool(exec_config.resolve("degraded"))
        if self.dedup is None:
            self.dedup = bool(exec_config.resolve("dedup"))
        # True while the last dispatch rode the degradation ladder; drives
        # the langdetect_degraded gauge's reset on fast-path recovery.
        self._degraded_mode = False
        if self.ragged_transfer is None:
            self.ragged_transfer = self.mesh is None
        if self.device_encode is None:
            self.device_encode = bool(exec_config.resolve("device_encode"))
        if self.mesh is not None:
            self.device_encode = False
        # Per-(has_limit) jitted encode+score closures (the device-encode
        # dispatch); built lazily under _state_lock, compiled per bucketed
        # (wire, B, S) shape like every other strategy program.
        self._encode_fns: dict = {}
        if self.mesh is not None:
            if self.device is not None:
                raise ValueError("pass either device or mesh, not both")
            from ..parallel.mesh import (
                DATA_AXIS,
                VOCAB_AXIS,
                replicated,
                vocab_sharding,
            )

            self._ndata = int(self.mesh.shape[DATA_AXIS])
            placement = replicated(self.mesh)
            # A mesh with a vocab axis shards the dense weight table across
            # devices row-wise instead of replicating ~O(V*L) bytes per
            # device; GSPMD turns the row gather into local gather + psum.
            # Only the dense direct-indexed table shards (its row count is
            # the pow2 id space); LUT/compact forms stay replicated.
            w_placement = placement
            if (
                int(self.mesh.shape[VOCAB_AXIS]) > 1
                and self.lut is None
                and self.weights.shape[0] == self.spec.id_space_size
            ):
                w_placement = vocab_sharding(self.mesh)
            self.weights = jax.device_put(self.weights, w_placement)
            if self.lut is not None:
                self.lut = jax.device_put(self.lut, placement)
        else:
            placement = self.device
            if placement is not None:
                self.weights = jax.device_put(self.weights, placement)
                if self.lut is not None:
                    self.lut = jax.device_put(self.lut, placement)
        if self.cuckoo is not None:
            entries = jnp.asarray(self.cuckoo.entries())
            if placement is not None:
                entries = jax.device_put(entries, placement)
            self._cuckoo_entries = entries
        if self.score_encoding not in ENCODINGS:
            raise ValueError(
                f"unknown score_encoding {self.score_encoding!r}; expected "
                f"one of {ENCODINGS}"
            )
        if self.strategy not in (
            "auto", "gather", "onehot", "pallas", "hybrid", "hist", "fused"
        ):
            raise ValueError(
                f"unknown strategy {self.strategy!r}; expected 'auto', "
                "'gather', 'onehot', 'pallas', 'hybrid', 'hist', or 'fused'"
            )
        if self.quantization not in (None, *score_fused.QUANT_DTYPES):
            raise ValueError(
                f"unknown quantization {self.quantization!r}; expected one "
                f"of {tuple(score_fused.QUANT_DTYPES)} or None"
            )
        pallas_ok = self.lut is None and score_pallas.pallas_supported(
            self.spec, self.weights.shape[0], self.weights.shape[1]
        )
        hybrid_ok = self._hybrid_supported()
        fused_ok = score_fused.fused_supported(
            self.spec, self.weights.shape[0], self.weights.shape[1],
            lut=self.lut, cuckoo=self.cuckoo,
        )
        if self.quantization is not None and self.strategy not in (
            "auto", "fused"
        ):
            raise ValueError(
                "quantization applies to the fused strategy only; got "
                f"strategy={self.strategy!r}"
            )
        if self.strategy == "auto":
            self.strategy, self.strategy_reason = self._auto_select(
                self._target_device().platform, fused_ok, pallas_ok,
                hybrid_ok,
            )
        else:
            self.strategy_reason = "explicit"
        # The auto branch used to be silent; a deployment debugging "why
        # did this land on gather?" now gets the answer in the log AND on
        # every score span (telemetry/report shows span attrs).
        log_event(
            _log,
            "runner.strategy",
            strategy=self.strategy,
            reason=self.strategy_reason,
            platform=self._target_device().platform,
            quantization=self.quantization,
        )
        if self.strategy == "fused" and not fused_ok:
            raise ValueError(
                "strategy='fused' needs dense or LUT membership (exact "
                "gram lengths <= 3, or a hashed vocab); packed-key cuckoo "
                "profiles use the hybrid/hist strategies"
            )
        if self.strategy == "onehot" and not score_ops.onehot_supported(
            self.spec, self.weights.shape[0]
        ):
            raise ValueError(
                "strategy='onehot' needs an exact vocab with gram lengths <= "
                f"{score_ops.ONEHOT_MAX_N} and the dense weight table"
            )
        if self.strategy == "pallas" and not pallas_ok:
            raise ValueError(
                "strategy='pallas' needs an exact vocab with gram lengths "
                "<= 2 and the dense weight table"
            )
        if self.strategy == "hybrid" and not hybrid_ok:
            raise ValueError(
                "strategy='hybrid' needs exact short-gram ids (exact vocab or "
                "hashed 'exact12' scheme) with gram lengths both <= 2 and > 2"
            )
        if self.strategy == "hist" and not self._hist_supported():
            raise ValueError(
                "strategy='hist' needs compact-row membership (a cuckoo "
                "table or an id->row LUT)"
            )
        if self.batch_size is None:
            if self.strategy in ("pallas", "fused"):
                self.batch_size = DEFAULT_PALLAS_BATCH_SIZE
            elif self.strategy in ("hybrid", "hist"):
                heavy = any(n >= 4 for n in self.spec.gram_lengths)
                self.batch_size = (
                    DEFAULT_HEAVY_BATCH_SIZE if heavy
                    else DEFAULT_HYBRID_BATCH_SIZE
                )
            else:
                self.batch_size = DEFAULT_BATCH_SIZE
        # Trigger the one-time native-library build here, not inside the
        # first score() call's timed hot loop.
        from .. import native

        native.available()

    @property
    def max_chunk(self) -> int:
        return self.length_buckets[-1]

    def _target_device(self):
        """The device this runner's programs actually run on: a mesh's
        devices decide the platform (not the process default — a CPU mesh
        with a TPU default backend must still count as CPU), else the
        explicit device, else the process default."""
        if self.mesh is not None:
            return self.mesh.devices.flat[0]
        return self.device or jax.devices()[0]

    def _hybrid_supported(self) -> bool:
        """Vocab with both short (≤ 2) and long (> 2) gram lengths whose
        short-gram ids are exact polynomial ids: the short lengths score
        through the pallas histogram kernel over a dense sub-table, the long
        ones through the gather path. True for exact vocabs and for hashed
        vocabs under the ``exact12`` scheme (whose buckets [0, 65792) are
        exactly the short-gram polynomial ids)."""
        from ..ops.vocab import EXACT, EXACT12, HASHED

        glens = self.spec.gram_lengths
        ids_exact12 = self.spec.mode == EXACT or (
            self.spec.mode == HASHED and self.spec.hash_scheme == EXACT12
        )
        return (
            ids_exact12
            and any(n <= 2 for n in glens)
            and any(n > 2 for n in glens)
        )

    def _auto_select(
        self, platform: str, fused_ok: bool, pallas_ok: bool,
        hybrid_ok: bool,
    ) -> tuple[str, str]:
        """(strategy, reason) for strategy='auto'.

        On a TPU backend the fused megakernel is preferred wherever it
        covers the profile form (ROADMAP item 3): one program, no
        intermediate HBM round-trips, quantized table tiles. The previous
        ranking (pallas → hybrid → hist) stays as the ladder beneath it —
        and remains reachable explicitly for A/B. CPU keeps the XLA
        strategies: interpret-mode pallas is for tests, not serving.
        """
        if self.quantization is not None:
            if not fused_ok:
                raise ValueError(
                    "quantization needs the fused strategy, which does not "
                    "support this profile form (cuckoo membership?)"
                )
            return "fused", "quantization requested ⇒ fused table tiles"
        if platform == "tpu":
            if fused_ok:
                return "fused", (
                    "tpu + dense/LUT membership ⇒ fused megakernel"
                )
            if pallas_ok:
                return "pallas", "tpu + exact short-gram dense table"
            if hybrid_ok:
                return "hybrid", (
                    "tpu + exact short-gram ids with long grams ⇒ pallas "
                    "histogram for n<=2, gather/hist for the rest"
                )
            if self._hist_supported():
                return "hist", (
                    "tpu + compact-row membership ⇒ row-histogram MXU path"
                )
        if self.lut is None and score_ops.onehot_supported(
            self.spec, self.weights.shape[0]
        ):
            return "onehot", (
                f"{platform} + exact short-gram dense table ⇒ one-hot MXU "
                "via XLA (pallas interpret mode is test-only off-TPU)"
            )
        return "gather", f"{platform} fallback: gather/LUT dispatch"

    def _fused_state(self):
        """(interpret, tables) for the fused strategy — the quantized tile
        layout is real relayout work, built once per runner."""
        state = getattr(self, "_fused_cache", None)
        if state is None:
            with self._state_lock:
                return self._fused_state_locked()
        return state

    def _fused_state_locked(self):
        state = getattr(self, "_fused_cache", None)
        if state is None:
            # Re-validate: strategy may have been mutated post-construction.
            if not score_fused.fused_supported(
                self.spec, self.weights.shape[0], self.weights.shape[1],
                lut=self.lut, cuckoo=self.cuckoo,
            ):
                raise ValueError(
                    "strategy='fused' needs dense or LUT membership (exact "
                    "gram lengths <= 3, or a hashed vocab)"
                )
            ft = score_fused.build_fused_tables(
                np.asarray(self.weights),
                None if self.lut is None else np.asarray(self.lut),
                self.spec,
                quantization=self.quantization,
                tile_bytes=(
                    self.fused_tile_bytes or score_fused.DEFAULT_TILE_BYTES
                ),
            )
            wq = jnp.asarray(ft.wq)
            scales = jnp.asarray(ft.scales)
            lut_f = None if ft.lut is None else jnp.asarray(ft.lut)
            if self.mesh is not None:
                from ..parallel.mesh import replicated

                placement = replicated(self.mesh)
            else:
                placement = self.device
            if placement is not None:
                wq = jax.device_put(wq, placement)
                scales = jax.device_put(scales, placement)
                if lut_f is not None:
                    lut_f = jax.device_put(lut_f, placement)
            interpret = self._target_device().platform != "tpu"
            state = self._fused_cache = (
                interpret, ft.layout, wq, scales, lut_f, ft.table_bytes,
                ft.f32_bytes,
            )
        return state

    def _mesh_fused_fn(self, interpret: bool):
        """shard_map wrapper running the fused kernel per data shard
        (pallas_call has no GSPMD partitioning rule; tables replicated,
        batch split over the data axis — the same compiled program scales
        across the mesh unchanged)."""
        fn = getattr(self, "_mesh_fused_cache", None)
        if fn is None:
            from jax.sharding import PartitionSpec as P

            from ..parallel.mesh import DATA_AXIS, shard_map_compat

            _, layout, _, _, lut_f, _, _ = self._fused_state()
            block = self.pallas_block or score_fused.DEFAULT_BLOCK
            has_lut = lut_f is not None

            def local(batch, lengths, wq, scales, lut, lim):
                return score_fused.score_batch_fused(
                    batch, lengths, wq, scales,
                    lut if has_lut else None, lim,
                    spec=self.spec, layout=layout, block=block,
                    interpret=interpret,
                )

            fn = self._mesh_fused_cache = jax.jit(
                shard_map_compat(
                    local,
                    mesh=self.mesh,
                    in_specs=(
                        P(DATA_AXIS), P(DATA_AXIS), P(), P(), P(),
                        P(DATA_AXIS),
                    ),
                    out_specs=P(DATA_AXIS),
                    check_vma=False,
                )
            )
        return fn

    def _fused_scores(self, batch, lengths, window_limit, placement):
        """Fused-megakernel scoring on one packed batch — single device,
        or per data shard under shard_map on a mesh."""
        interpret, layout, wq, scales, lut_f, _, _ = self._fused_state()
        if self.mesh is not None:
            if window_limit is None:
                window_limit = self._full_limit(batch.shape[0], placement)
            lut_arg = (
                lut_f if lut_f is not None
                else jnp.zeros(0, jnp.int32)  # shard_map needs a leaf
            )
            return self._mesh_fused_fn(interpret)(
                batch, lengths, wq, scales, lut_arg, window_limit
            )
        return score_fused.score_batch_fused(
            batch, lengths, wq, scales, lut_f, window_limit,
            spec=self.spec, layout=layout,
            block=self.pallas_block or score_fused.DEFAULT_BLOCK,
            interpret=interpret,
        )

    def table_bytes(self) -> int:
        """Resident weight-side bytes of the active strategy's device form
        (the telemetry ``langdetect_table_bytes`` gauge; the compare guard
        tracks it so a change that silently de-quantizes or re-balloons
        table traffic fails the diff)."""
        if self.strategy == "fused":
            _, _, _, _, _, table_bytes, _ = self._fused_state()
            return int(table_bytes)
        total = int(np.prod(self.weights.shape)) * int(
            np.dtype(self.weights.dtype).itemsize
        )
        if self.lut is not None:
            total += int(self.lut.size) * 4
        if self.cuckoo is not None:
            total += int(self._cuckoo_entries.size) * 4
        return total

    def _hybrid_state(self):
        """(interpret, spec12, w1, w2, rest_lengths) for the hybrid strategy.

        The dense n ≤ 2 sub-table is materialized once from the profile
        (via the LUT for compact profiles — exact n ≥ 3 id spaces are far
        too large for a dense table, so ``lut`` is the expected form). The
        sub-spec's id layout matches the full exact spec's first rows
        (1-gram ids, then 2-gram ids — ``exact_offsets`` stacks lengths
        ascending), so slicing is exact.
        """
        state = getattr(self, "_hybrid_cache", None)
        if state is None:
            with self._state_lock:
                return self._hybrid_state_locked()
        return state

    def _hybrid_state_locked(self):
        state = getattr(self, "_hybrid_cache", None)
        if state is None:
            if not self._hybrid_supported():
                raise ValueError(
                    "strategy='hybrid' needs exact short-gram ids (exact vocab "
                    "or hashed 'exact12' scheme) with gram lengths both "
                    "<= 2 and > 2"
                )
            from ..ops.vocab import EXACT, VocabSpec

            sub = tuple(n for n in self.spec.gram_lengths if n <= 2)
            rest = tuple(n for n in self.spec.gram_lengths if n > 2)
            spec12 = VocabSpec(EXACT, sub)
            V12 = spec12.id_space_size
            if self.cuckoo is not None:
                # Look every short-gram key up in the cuckoo table (host) to
                # materialize the dense sub-table rows.
                from ..ops.cuckoo import lookup_numpy

                v1 = np.arange(256, dtype=np.uint32)
                lo1 = (v1 << 24).astype(np.int32)
                hi1 = np.full(256, 1 << 8, np.int32)
                a = np.repeat(np.arange(256, dtype=np.uint32), 256)
                b = np.tile(np.arange(256, dtype=np.uint32), 256)
                lo2 = ((a << 24) | (b << 16)).astype(np.int32)
                hi2 = np.full(65536, 2 << 8, np.int32)
                rows = lookup_numpy(
                    self.cuckoo,
                    np.concatenate([lo1, lo2]),
                    np.concatenate([hi1, hi2]),
                )[:V12]
                dense12 = jnp.asarray(self.weights)[jnp.asarray(rows)]
            elif self.lut is not None:
                dense12 = jnp.asarray(self.weights)[jnp.asarray(self.lut)[:V12]]
            else:
                dense12 = jnp.asarray(self.weights)[:V12]
            w1, w2 = score_pallas.weight_views(dense12, spec12)
            interpret = self._target_device().platform != "tpu"
            if self.device is not None:
                w1 = jax.device_put(w1, self.device)
                w2 = jax.device_put(w2, self.device)
            state = self._hybrid_cache = (interpret, spec12, w1, w2, rest)
        return state

    def _pallas_state(self):
        """(interpret, w1, w2) for the pallas strategy, built lazily so the
        strategy can be selected after construction too."""
        state = getattr(self, "_pallas_cache", None)
        if state is None:
            with self._state_lock:
                return self._pallas_state_locked()
        return state

    def _pallas_state_locked(self):
        state = getattr(self, "_pallas_cache", None)
        if state is None:
            # Re-validate here: __post_init__ only checks the strategy it saw
            # at construction, and strategy may have been mutated since.
            if self.lut is not None or not score_pallas.pallas_supported(
                self.spec, self.weights.shape[0], self.weights.shape[1]
            ):
                raise ValueError(
                    "strategy='pallas' needs an exact vocab with gram "
                    "lengths <= 2 and the dense weight table"
                )
            # Mosaic only lowers on TPU; anywhere else (CPU tests, GPU) the
            # explicit pallas strategy runs in interpret mode.
            interpret = self._target_device().platform != "tpu"
            w1, w2 = score_pallas.weight_views(self.weights, self.spec)
            if self.device is not None:
                w1 = jax.device_put(w1, self.device)
                w2 = jax.device_put(w2, self.device)
            state = self._pallas_cache = (interpret, w1, w2)
        return state

    def _full_limit(self, rows: int, placement):
        """Cached no-op window-limit device array (mesh-pallas needs the
        operand even when no doc is chunked; only a handful of distinct row
        counts exist, so don't pay a h2d transfer per micro-batch)."""
        cache = getattr(self, "_full_limit_cache", None)
        if cache is None:
            cache = self._full_limit_cache = {}
        arr = cache.get(rows)
        if arr is None:
            arr = cache[rows] = jax.device_put(
                np.full(rows, self.max_chunk, np.int32), placement
            )
        return arr

    def _mesh_pallas_fn(self, interpret: bool, spec=None):
        """shard_map wrapper running the pallas kernel on each data shard.
        ``spec`` defaults to the runner's vocab; the hybrid strategy passes
        its n ≤ 2 sub-spec."""
        spec = spec or self.spec
        cache = getattr(self, "_mesh_pallas_cache", None)
        if cache is None:
            cache = self._mesh_pallas_cache = {}
        fn = cache.get((spec, interpret))
        if fn is None:
            from jax.sharding import PartitionSpec as P

            from ..parallel.mesh import DATA_AXIS, shard_map_compat

            block = self.pallas_block or score_pallas.DEFAULT_BLOCK

            def local(batch, lengths, w1, w2, lim):
                return score_pallas.score_batch_pallas(
                    batch, lengths, w1, w2, lim,
                    spec=spec, block=block, interpret=interpret,
                )

            fn = cache[(spec, interpret)] = jax.jit(
                shard_map_compat(
                    local,
                    mesh=self.mesh,
                    in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(), P(), P(DATA_AXIS)),
                    out_specs=P(DATA_AXIS),
                    # pallas_call's out_shape carries no varying-mesh-axes
                    # info; the kernel is per-shard pure, so skip the check.
                    check_vma=False,
                )
            )
        return fn

    def _hist_supported(self) -> bool:
        """True when the row-histogram strategy applies: every window can be
        resolved to a compact weight row (a single-probe bucket table built
        from the cuckoo keys or the id->row LUT; hashed vocabs keep the LUT
        itself as membership when no zero-overflow bucket seed exists). On a
        mesh the scorer runs per data shard under shard_map with the tables
        replicated (vocab-sharded dense tables keep the GSPMD gather path —
        they have no compact membership)."""
        return self._hist_state() is not None

    def _hist_state(self):
        """(weights_pad_dev, rhi, interpret, bucket_dev, bucket_seed, kind)
        for the row-histogram strategy, built once per runner — or None when
        the strategy can't apply (no membership, or an exact vocab whose
        bucket build found no zero-overflow seed). ``bucket_dev`` None ⇒ LUT
        membership; ``kind`` is the bucket's key form ('exact' = packed gram
        keys from the cuckoo, 'hashed' = int32 window ids from the LUT —
        note an EXACT vocab with gram lengths <= 3 ships a LUT, so its
        bucket is id-keyed: the vocab mode does not decide the key form)."""
        state = getattr(self, "_hist_cache", "unset")
        if not isinstance(state, str):
            return state
        with self._state_lock:
            return self._hist_state_locked()

    def _hist_state_locked(self):
        state = getattr(self, "_hist_cache", "unset")
        if not isinstance(state, str):
            return state
        from ..ops import bucket as bucket_ops

        lut_ok = self.lut is not None and self.lut.size > 0
        table = None
        if self.cuckoo is not None:
            table = bucket_ops.build_buckets_exact(
                self.cuckoo.keys_lo[:-1], self.cuckoo.keys_hi[:-1]
            )
            if table is None:  # exact membership has no LUT to fall back on
                log_event(_log, "runner.hist_bucket_build_failed")
                self._hist_cache = None
                return None
        elif lut_ok:
            lut_np = np.asarray(self.lut)
            miss = self.weights.shape[0] - 1
            ids = np.nonzero(lut_np != miss)[0].astype(np.int32)
            table = bucket_ops.build_buckets_hashed(ids, lut_np[ids])
        else:
            self._hist_cache = None
            return None
        wp, rhi = score_hist.pad_weights(np.asarray(self.weights))
        wp = jnp.asarray(wp)
        bucket_dev = None if table is None else jnp.asarray(table.rows)
        if self.mesh is not None:
            from ..parallel.mesh import replicated

            placement = replicated(self.mesh)
        else:
            placement = self.device
        if placement is not None:
            wp = jax.device_put(wp, placement)
            if bucket_dev is not None:
                bucket_dev = jax.device_put(bucket_dev, placement)
        interpret = self._target_device().platform != "tpu"
        state = self._hist_cache = (
            wp, rhi, interpret, bucket_dev,
            0 if table is None else table.seed,
            "hashed" if table is None else table.kind,
        )
        return state

    def _mesh_hist_fn(self, gram_lengths_subset):
        """shard_map wrapper running the hist scorer on each data shard
        (the pallas hist kernel has no GSPMD partitioning rule; tables are
        replicated, the batch splits over the data axis)."""
        cache = getattr(self, "_mesh_hist_cache", None)
        if cache is None:
            cache = self._mesh_hist_cache = {}
        fn = cache.get(gram_lengths_subset)
        if fn is None:
            from jax.sharding import PartitionSpec as P

            from ..parallel.mesh import DATA_AXIS, shard_map_compat

            wp, rhi, interpret, bucket_dev, bucket_seed, kind = (
                self._hist_state()
            )
            has_bucket = bucket_dev is not None

            def local(batch, lengths, member, lim):
                return score_hist.score_batch_hist(
                    batch, lengths, wp,
                    lut=None if has_bucket else member,
                    bucket=member if has_bucket else None,
                    window_limit=lim,
                    spec=self.spec,
                    rhi=rhi,
                    bucket_seed=bucket_seed,
                    bucket_kind=kind,
                    gram_lengths_subset=gram_lengths_subset,
                    interpret=interpret,
                )

            fn = cache[gram_lengths_subset] = jax.jit(
                shard_map_compat(
                    local,
                    mesh=self.mesh,
                    in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(), P(DATA_AXIS)),
                    out_specs=P(DATA_AXIS),
                    check_vma=False,
                )
            )
        return fn

    def _hist_scores(self, batch, lengths, window_limit, gram_lengths_subset):
        """Row-histogram scoring (ops.score_hist): single-probe bucket (or
        LUT) membership resolves rows, a pallas kernel builds per-doc row
        histograms on the MXU, one batch matmul contracts them with the
        weight table. On a mesh the whole scorer runs per data shard under
        shard_map."""
        wp, rhi, interpret, bucket_dev, bucket_seed, kind = self._hist_state()
        has_bucket = bucket_dev is not None
        member = bucket_dev if has_bucket else self.lut
        if self.mesh is not None:
            from ..parallel.mesh import batch_sharding

            if window_limit is None:
                window_limit = self._full_limit(
                    batch.shape[0], batch_sharding(self.mesh)
                )
            return self._mesh_hist_fn(gram_lengths_subset)(
                batch, lengths, member, window_limit
            )
        return score_hist.score_batch_hist(
            batch, lengths, wp,
            window_limit=window_limit,
            spec=self.spec,
            rhi=rhi,
            bucket_seed=bucket_seed,
            bucket_kind=kind,
            gram_lengths_subset=gram_lengths_subset,
            interpret=interpret,
            **{"bucket" if has_bucket else "lut": member},
        )

    def _gather_scores(
        self, batch, lengths, window_limit, gram_lengths_subset, *, block
    ):
        """Gather-style scoring on one packed batch: the row-histogram
        reformulation when explicitly selected (or for hybrid's long-gram
        segment on a real TPU), else LUT/dense id gathers, or packed-key
        cuckoo membership when the profile's gram lengths exceed the int32
        id space. An explicit ``strategy='gather'`` always runs the gather
        path — it is the escape hatch and the A/B reference."""
        if (
            self.strategy == "hist"
            or (
                self.strategy == "hybrid"
                and self._target_device().platform == "tpu"
            )
        ) and self._hist_supported():
            return self._hist_scores(
                batch, lengths, window_limit, gram_lengths_subset
            )
        if self.cuckoo is not None:
            return score_ops.score_batch_cuckoo(
                batch,
                lengths,
                self.weights,
                self._cuckoo_entries,
                seed1=self.cuckoo.seed1,
                seed2=self.cuckoo.seed2,
                spec=self.spec,
                block=block,
                window_limit=window_limit,
                gram_lengths_subset=gram_lengths_subset,
            )
        return score_ops.score_batch(
            batch,
            lengths,
            self.weights,
            self.lut,
            spec=self.spec,
            block=block,
            window_limit=window_limit,
            gram_lengths_subset=gram_lengths_subset,
        )

    def _pallas_dispatch(
        self, batch, lengths, window_limit, placement, interpret, spec, w1, w2
    ):
        """Run the pallas scorer on one packed batch — directly on a single
        device, or per-shard under shard_map on a mesh (pallas_call has no
        GSPMD partitioning rule; weights replicated, batch split over the
        data axis)."""
        if self.mesh is not None:
            if window_limit is None:
                window_limit = self._full_limit(batch.shape[0], placement)
            return self._mesh_pallas_fn(interpret, spec)(
                batch, lengths, w1, w2, window_limit
            )
        return score_pallas.score_batch_pallas(
            batch,
            lengths,
            w1,
            w2,
            window_limit,
            spec=spec,
            block=self.pallas_block or score_pallas.DEFAULT_BLOCK,
            interpret=interpret,
        )

    def _fetch(self, arr) -> np.ndarray:
        """Host numpy value of one result array.

        On a multi-process mesh (jax.distributed — SURVEY §2.3's multi-host
        leg) the data-axis shards of a result live on other processes'
        devices, so plain ``np.asarray`` would raise on non-addressable
        shards; ``process_allgather`` assembles the global value on every
        process instead (every process calls it for every batch in the same
        plan order, so the collective schedule is identical process-wide).
        Single-process: a plain copy."""
        if self.mesh is not None and jax.process_count() > 1:
            from jax.experimental import multihost_utils

            host = np.asarray(
                multihost_utils.process_allgather(arr, tiled=True)
            )
        else:
            host = np.asarray(arr)
        # The d2h audit trail (docs/PERFORMANCE.md §10): every result byte
        # the runner pulls off the device goes through here, so a label
        # request silently re-fetching the full [B, L] score matrix shows
        # up as a counter jump the tests pin (4·B ids + the chunked docs'
        # few score rows is the contract on every strategy and ladder
        # rung).
        REGISTRY.incr("score/fetch_bytes", int(host.nbytes))
        return host

    @staticmethod
    def _pack(batch_docs, pad_to: int):
        """Padded packing: native C++ loader (falls back to numpy internally)."""
        from .. import native

        return native.pack_batch(batch_docs, pad_to)

    def _dispatch_batch(self, batch_np, lengths_np, limit_np, placement):
        """Transfer one packed batch and dispatch its scoring computation
        (async — errors may defer to the result fetch).

        Explicit async device_put: passing numpy operands straight into the
        jitted call makes the h2d copy synchronous on the dispatch path
        (~8.7ms/batch over a tunneled TPU, measured), while device_put
        returns immediately and overlaps the copy with packing the next
        batch (~0.2ms dispatch). On a mesh the same put carries the
        data-axis sharding and GSPMD partitions the jitted scorer across
        devices.
        """
        batch = jax.device_put(batch_np, placement)
        lengths = jax.device_put(lengths_np, placement)
        window_limit = (
            None if limit_np is None else jax.device_put(limit_np, placement)
        )
        return self._dispatch_device(batch, lengths, window_limit, placement)

    def _dispatch_ragged(self, flat_np, offs_np, lengths_np, limit_np,
                         placement, pad_to: int):
        """Ragged-transfer dispatch: ship the chunk-aligned flat buffer
        (raw bytes + ~64B/doc alignment, vs bucket-width rows — ~15-20%
        fewer wire bytes at typical fill factors) and rebuild the exact
        padded batch on device with one lane-width row gather. Downstream
        scoring sees a batch bit-identical to the padded path's."""
        flat = jax.device_put(flat_np, placement)
        offs = jax.device_put(offs_np, placement)
        lengths = jax.device_put(lengths_np, placement)
        window_limit = (
            None if limit_np is None else jax.device_put(limit_np, placement)
        )
        # Shared jitted unpack (ops.encoding) — one compile cache with the
        # fit pipeline's ragged ingest.
        batch = unpack_ragged_jit(flat, offs, lengths, pad_to)
        return self._dispatch_device(batch, lengths, window_limit, placement)

    def _encode_fn(self, has_limit: bool):
        """The device-encode program: encode gather + strategy scorer under
        ONE jit (``pad_to`` static), so XLA fuses the padded-plane rebuild
        into the scoring program — no intermediate host form, and for the
        kernel strategies the pallas call simply inlines after the gather.
        Lazily built per limit arity; jax.jit traces at first call, outside
        the state lock."""
        # Materialize lazy strategy state (quantized tables, membership
        # planes) eagerly: letting the first touch happen under the trace
        # would cache tracers in the state slots.
        if self.strategy == "fused":
            self._fused_state()
        elif self.strategy == "pallas":
            self._pallas_state()
        elif self.strategy == "hybrid":
            self._hybrid_state()
            self._hist_supported()
        elif self.strategy == "hist":
            self._hist_supported()
        fn = self._encode_fns.get(has_limit)
        if fn is None:
            with self._state_lock:
                fn = self._encode_fns.get(has_limit)
                if fn is None:
                    if has_limit:
                        def encode_and_score(
                            wire, starts, lengths, window_limit, pad_to
                        ):
                            batch = encode_batch(wire, starts, lengths, pad_to)
                            return self._strategy_scores(
                                batch, lengths, window_limit, None
                            )
                    else:
                        def encode_and_score(wire, starts, lengths, pad_to):
                            batch = encode_batch(wire, starts, lengths, pad_to)
                            return self._strategy_scores(
                                batch, lengths, None, None
                            )
                    fn = jax.jit(
                        encode_and_score, static_argnames=("pad_to",)
                    )
                    self._encode_fns[has_limit] = fn
        return fn

    def _dispatch_encoded(self, wire_np, starts_np, lengths_np, limit_np,
                          placement, pad_to: int):
        """Device-encode dispatch: ship raw concatenated bytes + int32
        offsets only (docs/PERFORMANCE.md §11 — no host padding, no
        chunk-row alignment; the wire is the documents) and run the fused
        encode+score program. The rebuilt batch is bit-identical to the
        padded path's, so scores are exact on every strategy."""
        wire = jax.device_put(wire_np, placement)
        starts = jax.device_put(starts_np, placement)
        lengths = jax.device_put(lengths_np, placement)
        fn = self._encode_fn(limit_np is not None)
        if limit_np is None:
            return fn(wire, starts, lengths, pad_to=pad_to)
        window_limit = jax.device_put(limit_np, placement)
        return fn(wire, starts, lengths, window_limit, pad_to=pad_to)

    def _dispatch_device(self, batch, lengths, window_limit, placement):
        # Chaos hook: an armed FaultPlan can fail/delay this attempt (the
        # compiled fast path and the degraded ladder's device level both
        # count as device dispatches).
        faults.inject("score/dispatch")
        return self._strategy_scores(batch, lengths, window_limit, placement)

    def _strategy_scores(self, batch, lengths, window_limit, placement):
        """The strategy lattice's pure dispatch: one packed batch to the
        configured scorer, no host side effects — safe to trace under the
        device-encode jit (the encode gather and the scorer then compile
        into one program) and shared verbatim by the eager dispatches."""
        if self.strategy == "fused":
            return self._fused_scores(batch, lengths, window_limit, placement)
        if self.strategy == "pallas":
            interpret, w1, w2 = self._pallas_state()
            return self._pallas_dispatch(
                batch, lengths, window_limit, placement,
                interpret, self.spec, w1, w2,
            )
        if self.strategy == "hybrid":
            # n ≤ 2 through the pallas histogram kernel over the dense
            # sub-table; n ≥ 3 through the gather path. Both parts see the
            # same window limits; each handles its own lengths'
            # partial-window rules, so the sum is exact.
            interpret, spec12, w1, w2, rest = self._hybrid_state()
            return self._pallas_dispatch(
                batch, lengths, window_limit, placement,
                interpret, spec12, w1, w2,
            ) + self._gather_scores(
                batch, lengths, window_limit, rest,
                block=min(self.block, 256),
            )
        if self.strategy == "onehot":
            return score_ops.score_batch_onehot(
                batch,
                lengths,
                self.weights,
                spec=self.spec,
                block=min(self.block, 1024),
                window_limit=window_limit,
            )
        return self._gather_scores(
            batch, lengths, window_limit, None, block=self.block
        )

    # ------------------------------------------- degraded-mode fallback -----
    def _gather_escape(self, batch, lengths, window_limit):
        """The strategy lattice's escape hatch, callable regardless of the
        configured strategy: plain gather/cuckoo scoring on the operands'
        device. Exact for every profile form (dense table, LUT, cuckoo),
        so degraded results are bit-identical to ``strategy='gather'``."""
        if self.cuckoo is not None:
            return score_ops.score_batch_cuckoo(
                batch,
                lengths,
                self.weights,
                self._cuckoo_entries,
                seed1=self.cuckoo.seed1,
                seed2=self.cuckoo.seed2,
                spec=self.spec,
                block=min(self.block, 256),
                window_limit=window_limit,
            )
        return score_ops.score_batch(
            batch,
            lengths,
            self.weights,
            self.lut,
            spec=self.spec,
            block=min(self.block, 256),
            window_limit=window_limit,
        )

    def _host_state(self):
        """(cpu_device, weights, lut, cuckoo_entries) with every array on
        the host CPU backend — the degradation ladder's last rung. Built
        lazily at first degraded use (keeping permanent host copies would
        double resident table memory for a path that normally never runs);
        if the device is so far gone that even the d2h copy fails, the
        ladder's final raise carries that error."""
        state = getattr(self, "_host_cache", None)
        if state is not None:
            return state
        with self._state_lock:
            state = getattr(self, "_host_cache", None)
            if state is None:
                cpu = jax.local_devices(backend="cpu")[0]
                w = jax.device_put(np.asarray(self.weights), cpu)
                lut = (
                    None
                    if self.lut is None
                    else jax.device_put(np.asarray(self.lut), cpu)
                )
                entries = (
                    None
                    if self.cuckoo is None
                    else jax.device_put(np.asarray(self._cuckoo_entries), cpu)
                )
                state = self._host_cache = (cpu, w, lut, entries)
        return state

    def _host_scores(self, batch_np, lengths_np, limit_np):
        """Host-interpret scoring: the gather program executed on the CPU
        backend with host-resident tables — immune to accelerator/tunnel
        state, and exact (same program, same operands)."""
        cpu, w, lut, entries = self._host_state()
        batch = jax.device_put(batch_np, cpu)
        lengths = jax.device_put(lengths_np, cpu)
        window_limit = (
            None if limit_np is None else jax.device_put(limit_np, cpu)
        )
        with jax.default_device(cpu):
            if self.cuckoo is not None:
                return score_ops.score_batch_cuckoo(
                    batch,
                    lengths,
                    w,
                    entries,
                    seed1=self.cuckoo.seed1,
                    seed2=self.cuckoo.seed2,
                    spec=self.spec,
                    block=min(self.block, 256),
                    window_limit=window_limit,
                )
            return score_ops.score_batch(
                batch,
                lengths,
                w,
                lut,
                spec=self.spec,
                block=min(self.block, 256),
                window_limit=window_limit,
            )

    def _degraded_scores(
        self, batch_docs, batch_limits, pad_to, placement, cause=None
    ):
        """Run one batch down the degradation ladder after the compiled
        fast path failed (or while the breaker holds it open):

          1. ``gather`` — the device escape hatch, only meaningful when
             the fast path is a different program (pallas/hybrid/hist);
          2. ``host``   — the same gather program on the CPU backend.

        Each level is fenced before it counts as a success, so deferred
        execution errors surface inside the ladder instead of poisoning
        the caller's fetch loop. Exact scores at every level — degraded
        mode trades throughput, never correctness.
        """
        if all(lim == self.max_chunk for lim in batch_limits):
            limit_np = None
        else:
            limit_np = np.asarray(batch_limits, dtype=np.int32)
        batch_np, lengths_np = self._pack(batch_docs, pad_to)
        levels = ["host"]
        if self.strategy in ("fused", "pallas", "hybrid", "hist"):
            # The fused megakernel sits at the top of the ladder: a
            # retryable kernel failure falls fused → device gather → host,
            # exact at every rung (the gather escape reads the runner's
            # original f32 weights/LUT, so degraded results never carry
            # quantization error).
            levels.insert(0, "gather")
        last = cause
        for level in levels:
            try:
                with span(
                    "score/degraded", rows=len(batch_docs), pad_to=pad_to,
                    level=level, degraded=True,
                ) as sp:
                    if level == "gather":
                        faults.inject("score/dispatch")
                        batch = jax.device_put(batch_np, placement)
                        lengths = jax.device_put(lengths_np, placement)
                        window_limit = (
                            None
                            if limit_np is None
                            else jax.device_put(limit_np, placement)
                        )
                        scores = self._gather_escape(
                            batch, lengths, window_limit
                        )
                    else:
                        scores = self._host_scores(
                            batch_np, lengths_np, limit_np
                        )
                    jax.block_until_ready(scores)
                    sp.fence(scores)
            except Exception as e:
                if not self.retry_policy.classify(e):
                    raise
                last = e
                continue
            self._degraded_mode = True
            self.metrics.incr("degraded_batches")
            REGISTRY.incr("resilience/degraded_batches")
            REGISTRY.incr(f"resilience/degraded_{level}")
            REGISTRY.set_gauge("langdetect_degraded", 1.0)
            log_event(
                _log,
                "runner.degraded",
                level=level,
                rows=len(batch_docs),
                pad_to=pad_to,
                breaker=self.breaker.state,
                cause=repr(cause) if cause is not None else None,
            )
            return scores
        raise last if last is not None else RuntimeError(
            "degraded ladder exhausted with no recorded cause"
        )

    def score(self, byte_docs) -> np.ndarray:
        """float32 [N, L] scores in input order (exact over any doc length).

        ``byte_docs`` is a sequence of ``bytes`` — or an
        ``ops.encode_device.DocBlock`` (one byte plane + offsets), the
        zero-copy all-unique lane: no per-document Python objects, the
        wire ships raw bytes + int32 offsets, and the padded batch is
        rebuilt on device inside the scoring jit
        (docs/PERFORMANCE.md §11)."""
        return self._execute(byte_docs, want_labels=False)

    def predict_ids(self, byte_docs) -> np.ndarray:
        """int32 [N] argmax language indices in input order.

        The label path fetches per-doc int32 ids instead of [N, L] float
        scores — the d2h payload drops from N*L*4 bytes to N*4 (config-5
        scale: 4.2MB -> 24KB per pass, a 50-140ms saving on the tunneled
        wire). Argmax runs on device per micro-batch; chunked long docs
        still fetch their few full score rows so cross-chunk sums stay
        exact before their argmax.
        """
        return self._execute(byte_docs, want_labels=True)

    def _execute(self, byte_docs, *, want_labels: bool):
        # Flight-recorder hook: a raising score call dumps the recent
        # telemetry ring (when LANGDETECT_FLIGHT_RECORDER armed it) before
        # propagating — the post-mortem shows the batches leading up to
        # the failure, not just the exception.
        try:
            return self._execute_traced(byte_docs, want_labels=want_labels)
        except Exception as e:
            flightrec.record_crash("score", e)
            raise

    def _execute_traced(self, byte_docs, *, want_labels: bool):
        # The zero-copy tier (docs/PERFORMANCE.md §11): a DocBlock input
        # keeps the corpus as one byte plane + offsets end to end —
        # vectorized truncation, chunk arithmetic instead of chunk bytes,
        # one wire gather per batch, device-side encode. A mesh still
        # needs per-row padded sharding, so block inputs materialize docs
        # there (exact, just not zero-copy).
        block = byte_docs if isinstance(byte_docs, DocBlock) else None
        if block is not None and self.mesh is not None:
            byte_docs = [block.doc(i) for i in range(len(block))]
            block = None
        inverse = None
        if block is not None:
            doc_starts = block.starts()
            doc_lens = block.lengths()
            if self.max_score_bytes:
                cap = self.max_score_bytes
                if self.score_encoding == UTF8:
                    doc_lens = utf8_safe_lengths(
                        block.flat, doc_starts, doc_lens, cap
                    )
                else:
                    doc_lens = np.minimum(doc_lens, cap)
            # The block lane is the all-unique lane (the traffic shape it
            # exists for); content dedup would re-materialize per-doc
            # bytes just to key on them, un-doing the zero-copy win, so
            # it is skipped here regardless of the dedup setting.
            N_in = N = len(block)
        else:
            if self.max_score_bytes:
                cap = self.max_score_bytes
                if self.score_encoding == UTF8:
                    byte_docs = [truncate_utf8(d, cap) for d in byte_docs]
                else:
                    byte_docs = [d[:cap] for d in byte_docs]
            N_in = len(byte_docs)
            # In-flight dedup (docs/PERFORMANCE.md §10), keyed on the
            # encoded, truncated bytes — the exact content the kernel
            # would see. Unique rows ride the wire and the dispatch;
            # duplicates are satisfied by the scatter-back at the very
            # end (``out = out[inverse]``). The dict build is the whole
            # all-unique overhead.
            if self.dedup and N_in > 1:
                d = dedup_counted(byte_docs)
                if d is not None:
                    first_idx, inverse, _ = d
                    byte_docs = [byte_docs[int(i)] for i in first_idx]
            N = len(byte_docs)
        L = self.weights.shape[1]
        if want_labels:
            out = np.zeros(N, dtype=np.int32)
        else:
            out = np.zeros((N, L), dtype=np.float32)
        if N == 0:
            return out

        overlap = max(self.spec.gram_lengths) - 1
        stride = self.max_chunk - overlap
        # Loop-invariant placement: a NamedSharding on the mesh (GSPMD
        # partitions the jitted scorer from it) or the single target device.
        if self.mesh is not None:
            from ..parallel.mesh import batch_sharding, pad_rows_for_mesh

            placement = batch_sharding(self.mesh)
        else:
            placement = self.device

        # Expand long docs into chunks; each work item is
        # (doc_index, chunk_bytes-or-span, owned_window_starts). The block
        # tier never cuts chunk bytes — chunks are (start, length) spans
        # into the byte plane (ops.encode_device.chunk_table, the same
        # expansion in (doc, rank) order).
        if block is not None:
            doc_idx_arr, chunk_starts, chunk_lens, limits_arr = chunk_table(
                doc_starts, doc_lens, self.max_chunk, overlap
            )
            chunks = None
            sizes = chunk_lens
            n_chunks = int(chunk_lens.size)
        else:
            doc_idx: list[int] = []
            chunks: list[bytes] = []
            limits: list[int] = []
            for i, doc in enumerate(byte_docs):
                if len(doc) <= self.max_chunk:
                    doc_idx.append(i)
                    chunks.append(doc)
                    limits.append(self.max_chunk)  # no-op limit
                else:
                    parts = chunk_document(doc, self.max_chunk, overlap)
                    for j, part in enumerate(parts):
                        doc_idx.append(i)
                        chunks.append(part)
                        # Non-final chunks own starts [0, stride); final
                        # owns all.
                        limits.append(
                            stride if j < len(parts) - 1 else self.max_chunk
                        )
            doc_idx_arr = np.asarray(doc_idx, dtype=np.int64)
            limits_arr = np.asarray(limits, dtype=np.int64)
            chunk_starts = chunk_lens = None
            sizes = [len(c) for c in chunks]
            n_chunks = len(chunks)

        def chunk_bytes(sel):
            """Materialized chunk bytes for one planned batch — the
            padded/ragged/degraded packers' input; the encode path never
            calls it."""
            if chunks is not None:
                return [chunks[k] for k in sel]
            flat = block.flat
            return [
                flat[s : s + ln].tobytes()
                for s, ln in zip(chunk_starts[sel], chunk_lens[sel])
            ]

        # Micro-batch plan through the shared execution core
        # (exec.core.plan_micro_batches): chunks grouped by padded-length
        # bucket, rows capped so no single transfer exceeds the resolved
        # batch_bytes budget — a batch of 8192-wide rows at the full pallas
        # batch size would be a 32MB transfer, past the h2d bandwidth cliff.
        # A bucket's ragged remainder is carried into the next (wider)
        # bucket instead of becoming its own under-filled batch, so the
        # whole call ends with at most one ragged tail batch.
        plan = plan_micro_batches(
            sizes,
            length_buckets=self.length_buckets,
            rows_for=lambda pad_to: rows_under_byte_budget(
                pad_to, self.batch_bytes, self.batch_size
            ),
        )
        # Tuner signal (exec.tune): the chunk-length distribution at 64-byte
        # granularity, as counters so it rides every snapshot event. This is
        # the exact population the bucket-width solver replays — recorded
        # here, after truncation and chunking, because this is the
        # population the lattice actually pads.
        if len(sizes):
            edges = np.minimum(
                -(-np.maximum(np.asarray(sizes, dtype=np.int64), 1) // 64)
                * 64,
                self.max_chunk,
            )
            for edge, cnt in zip(*np.unique(edges, return_counts=True)):
                REGISTRY.incr(f"exec/len/{int(edge)}", int(cnt))
        from ..utils.profiling import trace

        use_encode = self.mesh is None and (
            self.device_encode or block is not None
        )

        def encode_and_dispatch(sel: np.ndarray, pad_to: int):
            """The device-encode rung: assemble the batch's wire form (raw
            concatenated bytes + int32 offsets, bucketed capacity) and run
            the fused encode+score program. The block tier gathers spans
            straight off the byte plane; the list tier joins the chunk
            bytes once — either way no padded or chunk-aligned host buffer
            ever exists. An injected ``score/pack`` fault (or a real wire-
            build failure) rides the shared retry/degraded wiring, whose
            ladder re-packs on the host — the exact fallback."""
            rows = len(sel)
            blim = limits_arr[sel]
            limit_np = (
                None if bool((blim == self.max_chunk).all())
                else blim.astype(np.int32)
            )
            if block is not None:
                real_bytes = int(chunk_lens[sel].sum())
            else:
                batch_docs = [chunks[k] for k in sel]
                real_bytes = sum(len(d) for d in batch_docs)
            capacity = wire_capacity(real_bytes, rows, pad_to)
            with span("score/pack", parent=score_span, rows=rows,
                      pad_to=pad_to, wire=True):
                # Chaos hook: the wire build is this path's pack stage.
                faults.inject("score/pack")
                if block is not None:
                    wire_np, starts_np, lengths_np = gather_wire(
                        block.flat, chunk_starts[sel], chunk_lens[sel],
                        capacity,
                    )
                else:
                    wire_np, starts_np, lengths_np = wire_from_docs(
                        batch_docs, capacity
                    )
            # Observed after the wire build succeeds, so chaos retries
            # never double-count shipped bytes.
            fill = real_bytes / capacity if capacity else 1.0
            REGISTRY.observe("score/batch_fill_ratio", fill)
            REGISTRY.observe("score/padding_waste", 1.0 - fill)
            REGISTRY.incr("score/real_bytes", real_bytes)
            REGISTRY.incr("score/capacity_bytes", capacity)
            index_bytes = starts_np.nbytes + lengths_np.nbytes + (
                0 if limit_np is None else limit_np.nbytes
            )
            REGISTRY.incr("score/wire_bytes", capacity + index_bytes)
            REGISTRY.incr("score/wire_docs", rows)
            # The tuner's evidence that the encode path is live (and the
            # smoke gates' A/B discriminator).
            REGISTRY.incr("score/encoded_batches")
            with span("score/dispatch", parent=score_span, rows=rows,
                      pad_to=pad_to, wire=True) as sp:
                scores = self._dispatch_encoded(
                    wire_np, starts_np, lengths_np, limit_np, placement,
                    pad_to,
                )
                sp.fence(scores)
            return scores

        def build_and_dispatch(sel: np.ndarray, pad_to: int):
            """Pack one planned batch from the retained chunks and dispatch
            it. Re-invocable: scoring is stateless, so a transient failure is
            retried by replaying the batch verbatim — the micro-batch analog
            of the streaming loop's replay-once (SURVEY.md §5.3)."""
            if use_encode:
                return encode_and_dispatch(sel, pad_to)
            batch_docs = chunk_bytes(sel)
            batch_limits = [int(x) for x in limits_arr[sel]]
            if self.mesh is not None:
                # Sharded dispatch needs the row count divisible by the
                # data axis; empty-doc pad rows score zero and are
                # dropped below (scatter uses only the first len(sel)).
                batch_docs, batch_limits = pad_rows_for_mesh(
                    batch_docs,
                    self._ndata,
                    (batch_limits, self.max_chunk),
                )
            # Batches without chunked docs (the common case) skip the
            # window-limit array entirely — one fewer host→device
            # transfer and a simpler compiled program.
            if all(lim == self.max_chunk for lim in batch_limits):
                limit_np = None
            else:
                limit_np = np.asarray(batch_limits, dtype=np.int32)
            # Fill/waste distributions: what fraction of the transferred
            # buffer is real bytes — observed per path below, against the
            # capacity that actually rides the wire (padded [B, S], or the
            # ragged path's bucketed flat buffer). Mesh pad rows count as
            # waste like any other padding.
            real_bytes = sum(len(d) for d in batch_docs)

            def observe_fill(capacity: int, index_bytes: int) -> None:
                fill = real_bytes / capacity if capacity else 1.0
                REGISTRY.observe("score/batch_fill_ratio", fill)
                REGISTRY.observe("score/padding_waste", 1.0 - fill)
                # Aggregate padding-tax counters: whole-run fill is exactly
                # real/capacity (the histograms are sampled reservoirs) —
                # what the tune smoke gate and the compare guard read.
                REGISTRY.incr("score/real_bytes", real_bytes)
                REGISTRY.incr("score/capacity_bytes", capacity)
                # Wire-shrink accounting (docs/PERFORMANCE.md §11): every
                # byte this dispatch ships — buffer plus index arrays — on
                # every transfer form, so compare's score/wire_bytes_per_doc
                # guard sees a silent fallback to a fatter form.
                REGISTRY.incr("score/wire_bytes", capacity + index_bytes)
                REGISTRY.incr("score/wire_docs", len(batch_docs))

            if (
                self.ragged_transfer
                and self.mesh is None
                and pad_to % RAGGED_CHUNK == 0
            ):
                from .. import native
                from ..ops.encoding import round_chunks

                # Flat sizes rounded to 1/16 of this geometry's padded
                # chunk count: stable-fill batches land on 1-3 compiled
                # C shapes per (B, S) at ~3% mean bucket waste.
                step = (len(batch_docs) * pad_to // RAGGED_CHUNK) // 16
                # Size-only precheck: ragged only wins when the bucketed
                # flat buffer is actually smaller than the padded batch —
                # narrow buckets (pad_to <= 2 chunks), high-fill batches,
                # and tiny tails below the 256-chunk floor all lose.
                total = 1 + sum(
                    -(-min(len(d), pad_to) // RAGGED_CHUNK)
                    for d in batch_docs
                )
                if (
                    round_chunks(total, step) * RAGGED_CHUNK
                    < len(batch_docs) * pad_to
                ):
                    observe_fill(
                        round_chunks(total, step) * RAGGED_CHUNK,
                        8 * len(batch_docs),
                    )
                    with span("score/pack", parent=score_span,
                              rows=len(batch_docs), pad_to=pad_to):
                        flat_np, offs_np, lengths_np = native.pack_ragged(
                            batch_docs, pad_to, flat_step=step
                        )
                    with span("score/dispatch", parent=score_span,
                              rows=len(batch_docs), pad_to=pad_to) as sp:
                        scores = self._dispatch_ragged(
                            flat_np, offs_np, lengths_np, limit_np, placement,
                            pad_to,
                        )
                        sp.fence(scores)
                    return scores
            observe_fill(len(batch_docs) * pad_to, 4 * len(batch_docs))
            with span("score/pack", parent=score_span,
                      rows=len(batch_docs), pad_to=pad_to):
                batch_np, lengths_np = self._pack(batch_docs, pad_to)
            with span("score/dispatch", parent=score_span,
                      rows=len(batch_docs), pad_to=pad_to) as sp:
                scores = self._dispatch_batch(
                    batch_np, lengths_np, limit_np, placement
                )
                sp.fence(scores)
            return scores

        # Chunked docs (len > max_chunk) need their full score rows fetched
        # and summed across chunks before argmax; everything else fetches
        # one int32 per doc in label mode.
        chunk_rank: dict[int, int] = {}
        chunk_acc = None
        if want_labels:
            if block is not None:
                for i in np.flatnonzero(doc_lens > self.max_chunk):
                    chunk_rank[int(i)] = len(chunk_rank)
            else:
                for i, doc in enumerate(byte_docs):
                    if len(doc) > self.max_chunk:
                        chunk_rank.setdefault(i, len(chunk_rank))
            if chunk_rank:
                chunk_acc = np.zeros((len(chunk_rank), L), dtype=np.float32)

        _no_pos = np.zeros(0, dtype=np.int64)

        def project(sel, scores):
            """Per-batch device-side projection for the label path:
            (argmax ids [rows], chunk-row scores or None, chunk positions)."""
            am = jnp.argmax(scores, axis=1).astype(jnp.int32)
            if not chunk_rank:  # common case: skip the per-row host scan
                return am, None, _no_pos
            pos = np.asarray(
                [
                    p for p, k in enumerate(sel)
                    if int(doc_idx_arr[k]) in chunk_rank
                ],
                dtype=np.int64,
            )
            sub = scores[jnp.asarray(pos)] if pos.size else None
            return am, sub, pos

        multiproc = self.mesh is not None and jax.process_count() > 1

        def on_retry(attempt_no, delay_s, exc):
            """Per-retry bookkeeping shared by the dispatch and fetch
            sites (the structured attempt/backoff/trace_id log line is
            emitted by RetryPolicy.run itself). List append is
            GIL-atomic, so dispatch workers need no extra lock."""
            self.metrics.incr("retries")
            REGISTRY.incr("score/retries")
            call_retries.append(1)

        def degraded_for(sel, pad_to, cause):
            """Assemble the planned batch's docs/limits (mesh pad rows
            included) and run them down the degradation ladder. Block-fed
            batches materialize their chunk bytes here — the ladder's
            host-pack rung is the exact fallback either way."""
            batch_docs = chunk_bytes(sel)
            batch_limits = [int(x) for x in limits_arr[sel]]
            if self.mesh is not None:
                batch_docs, batch_limits = pad_rows_for_mesh(
                    batch_docs, self._ndata, (batch_limits, self.max_chunk)
                )
            return self._degraded_scores(
                batch_docs, batch_limits, pad_to, placement, cause
            )

        def on_recovered():
            if self._degraded_mode and self.breaker.state == CLOSED:
                # Fast path healthy again AND the breaker agrees (a
                # success that only half-opened a multi-probe breaker
                # isn't recovery yet): leave degraded mode and say so on
                # the gauge.
                self._degraded_mode = False
                REGISTRY.set_gauge("langdetect_degraded", 0.0)
                log_event(_log, "runner.degraded_recovered")

        def dispatch_recover(sel, pad_to):
            """The execution core's shared failure wiring
            (exec.core.guarded_dispatch): breaker-gated fast path under
            the retry policy, then the degradation ladder. On a
            multi-process mesh (or with the fallback disabled) only the
            policy replay applies: the chaos plan and the policy are
            deterministic, so every process replays together and the
            collective schedule stays aligned — but a per-process
            fallback would not."""
            fallback_ok = not multiproc and self.degraded_fallback
            return guarded_dispatch(
                lambda: build_and_dispatch(sel, pad_to),
                policy=self.retry_policy,
                site="score/dispatch",
                breaker=self.breaker if fallback_ok else None,
                degraded=(
                    (lambda cause: degraded_for(sel, pad_to, cause))
                    if fallback_ok else None
                ),
                on_retry=on_retry,
                on_recovered=on_recovered,
                log_fields={"rows": len(sel)},
            )

        def run_one(item):
            """Pack, dispatch, and project one planned batch (transient
            failures replay under the retry policy; a tripped breaker
            reroutes to the degradation ladder). Async dispatch: the
            device works while other batches pack. Only (sel, pad_to) is
            retained for replay — the padded arrays are rebuilt from
            `chunks` in the rare fetch-failure path, so peak host RSS
            stays O(workers × batch), not O(corpus)."""
            sel, pad_to = item
            t0 = time.perf_counter()
            scores = dispatch_recover(sel, pad_to)
            self.metrics.incr("chunks_scored", len(sel))
            REGISTRY.observe(
                "score/batch_latency_s", time.perf_counter() - t0
            )
            if want_labels:
                return (sel, project(sel, scores), pad_to)
            return (sel, scores, pad_to)

        # Concurrent dispatch: pack/put/dispatch are dominated by
        # GIL-releasing work (native packer, PJRT transfer round-trips), so
        # a few workers overlap one batch's wire latency with another's
        # packing — the batch-path analog of the streaming engine's
        # transform workers. Results keep plan order (ex.map). Mesh
        # dispatch stays single-threaded by default: multi-process GSPMD
        # requires identical collective enqueue order across processes.
        workers = self.dispatch_workers
        if workers is None:
            workers = DISPATCH_WORKERS if self.mesh is None else 1
        workers = max(1, min(workers, len(plan)))
        # Per-call retry tally (list append is GIL-atomic, so dispatch
        # workers need no extra lock); the registry counter is lifetime.
        call_retries: list[int] = []
        # One request trace per score call: reuses the ambient trace when
        # the call rides inside a larger request (a stream batch), mints a
        # fresh id otherwise. Every span below — including the dispatch
        # workers' cross-thread pack/dispatch spans, which inherit through
        # parent=score_span — stamps this id onto its JSONL record, so one
        # slow request can be isolated from the aggregate percentiles.
        with trace_request() as req_id, trace(label="score"), \
                self.metrics.timer("score_s"), span(
            "score", docs=N_in, unique=N, batches=len(plan),
            strategy=self.strategy,
            strategy_reason=getattr(self, "strategy_reason", "explicit"),
        ) as score_span:
            # The core's plan executor: serial, or a few threads
            # overlapping one batch's pack/put with another's round-trip.
            pending = run_ordered(plan, run_one, workers)

            # Results stream back asynchronously: each batch's d2h copy is
            # started as soon as every batch is dispatched (payloads are tiny
            # — [B, L] floats, or [B] ids in label mode — it's all latency),
            # so result transfer overlaps the remaining compute instead of
            # serializing after it. A blocking per-batch np.asarray here
            # would instead pay the full device-sync latency once per batch
            # (measured ~8ms over a tunneled TPU). Multi-process meshes skip
            # the prefetch: results are assembled via process_allgather in
            # _fetch, and a host copy of non-addressable shards can't start.
            with span("score/fetch", batches=len(plan)):
                for _, s, _ in (pending if not multiproc else ()):
                    arrays = (s,) if not want_labels else (s[0], s[1])
                    for a in arrays:
                        if a is None:
                            continue
                        try:
                            a.copy_to_host_async()
                        except (AttributeError, *RETRYABLE):
                            # AttributeError: non-jax array (numpy test
                            # doubles). Runtime errors: a batch whose
                            # deferred execution error surfaces here — the
                            # fetch loop retries it.
                            pass
                for sel, s, pad_to in pending:
                    try:
                        faults.inject("score/fetch")
                        if want_labels:
                            am, sub, pos = s
                            am_host = self._fetch(am)
                            sub_host = None if sub is None else self._fetch(sub)
                        else:
                            host = self._fetch(s)
                    except Exception as e:
                        # A failure surfacing only at fetch time (async
                        # dispatch defers execution errors here): replay
                        # the batch synchronously under the retry policy,
                        # then fall to the degradation ladder. NOT on a
                        # multi-process mesh: a replay enqueues fresh
                        # collectives on this process alone,
                        # desynchronizing the process-wide collective
                        # schedule _fetch depends on — propagate instead
                        # (the caller's whole call is replayable on every
                        # process together). Deterministic errors
                        # propagate with their original traceback.
                        if multiproc or not self.retry_policy.classify(e):
                            raise

                        def replay(sel=sel, pad_to=pad_to):
                            faults.inject("score/fetch")
                            scores = build_and_dispatch(sel, pad_to)
                            if want_labels:
                                am_r, sub_r, pos_r = project(sel, scores)
                                return (
                                    self._fetch(am_r),
                                    None if sub_r is None
                                    else self._fetch(sub_r),
                                    pos_r,
                                )
                            return (self._fetch(scores),)

                        try:
                            fetched = self.retry_policy.run(
                                replay,
                                site="score/fetch",
                                breaker=self.breaker,
                                on_retry=on_retry,
                                initial_error=e,
                                log_fields={"rows": len(sel)},
                            )
                        except Exception as e2:
                            if (
                                not self.degraded_fallback
                                or not self.retry_policy.classify(e2)
                            ):
                                raise
                            scores = degraded_for(sel, pad_to, e2)
                            if want_labels:
                                am, sub, pos = project(sel, scores)
                                am_host = self._fetch(am)
                                sub_host = (
                                    None if sub is None else self._fetch(sub)
                                )
                            else:
                                host = self._fetch(scores)
                        else:
                            if want_labels:
                                am_host, sub_host, pos = fetched
                            else:
                                (host,) = fetched
                    # Rows beyond len(sel) are mesh pad rows — dropped here.
                    if want_labels:
                        docs_of = doc_idx_arr[sel]
                        whole = np.ones(len(sel), dtype=bool)
                        if pos.size:
                            whole[pos] = False
                            rows = [
                                chunk_rank[int(doc_idx_arr[sel[p]])]
                                for p in pos
                            ]
                            np.add.at(chunk_acc, rows, sub_host)
                        out[docs_of[whole]] = am_host[: len(sel)][whole]
                    else:
                        np.add.at(out, doc_idx_arr[sel], host[: len(sel)])

        if want_labels and chunk_rank:
            for i, r in chunk_rank.items():
                out[i] = int(np.argmax(chunk_acc[r]))

        if inverse is not None:
            # Deterministic scatter-back: every duplicate reads its unique
            # row's stored result — per-call parity with an undeduped run
            # is bit-exact on geometry-stable strategies (gather/fused) and
            # the usual reduction-order class on matmul strategies.
            out = out[inverse]

        self.metrics.incr("docs_scored", N_in)
        REGISTRY.observe("score/retries_per_call", len(call_retries))
        log_event(
            _log,
            "runner.score",
            docs=N_in,
            unique=N,
            chunks=n_chunks,
            batches=len(plan),
            trace_id=req_id,
        )
        # Roofline gauges, once per runner: XLA's cost model for this
        # runner's dispatch program at a shape it actually ran, so
        # stage_summary can state achieved-vs-peak utilization. Pure
        # diagnostics, so they run off the dispatch path: the analysis
        # re-lowers (and on CPU re-compiles) the dispatch program, which
        # would otherwise stall the first post-spawn dispatch for seconds
        # (docs/PERFORMANCE.md §12). Join ``_cost_thread`` to wait for
        # the gauges.
        if plan and not getattr(self, "_cost_recorded", False):
            self._cost_recorded = True
            rows, pad_to = len(plan[0][0]), plan[0][1]

            def _record():
                try:
                    from ..resilience import faults
                    from ..telemetry import cost as cost_mod

                    # Shielded: the analysis re-traces _dispatch_device,
                    # whose chaos hook would otherwise consume a fault
                    # plan's call budget in this fault-swallowing thread.
                    with faults.shield():
                        cost_mod.record_runner_cost(self, rows, pad_to)
                except Exception:
                    pass

            # Non-daemon on purpose: a daemon thread killed mid-XLA-compile
            # at interpreter exit aborts the process (C++ terminate); the
            # table-size guard in telemetry/cost bounds how long exit can
            # wait on the join.
            t = threading.Thread(
                target=_record, name="runner-cost-gauges", daemon=False
            )
            self._cost_thread = t
            t.start()
        return out

    def predict(self, byte_docs: Sequence[bytes], languages: Sequence[str]) -> list[str]:
        return [languages[i] for i in self.predict_ids(byte_docs)]

    # ---------------------------------------- segmentation (per-cell) path ---
    def _window_scores_device(self, batch, lengths, window_limit, cell):
        """Gather-formulation per-cell scorer on the operands' device — the
        segment mode's exactness oracle, and the dispatch for every
        non-fused strategy (pallas/hybrid/hist/onehot have no per-cell
        kernel; their segment requests ride this exact program)."""
        if self.cuckoo is not None:
            return score_ops.window_scores_batch_cuckoo(
                batch,
                lengths,
                self.weights,
                self._cuckoo_entries,
                seed1=self.cuckoo.seed1,
                seed2=self.cuckoo.seed2,
                spec=self.spec,
                cell=cell,
                block=min(self.block, 1024),
                window_limit=window_limit,
            )
        return score_ops.window_scores_batch(
            batch,
            lengths,
            self.weights,
            self.lut,
            spec=self.spec,
            cell=cell,
            block=min(self.block, 1024),
            window_limit=window_limit,
        )

    def _segment_dispatch_device(self, batch, lengths, window_limit, cell):
        """One packed batch → [B, ceil(pad_to/cell), L] raw cell scores.
        Fused runners use the per-cell megakernel variant (single-device;
        a mesh keeps the GSPMD gather program — exact either way); every
        other strategy rides the gather cell program."""
        faults.inject("score/dispatch")
        if self.strategy == "fused" and self.mesh is None:
            interpret, layout, wq, scales, lut_f, _, _ = self._fused_state()
            return score_fused.segment_batch_fused(
                batch, lengths, wq, scales, lut_f, window_limit,
                spec=self.spec, layout=layout, cell=cell,
                interpret=interpret,
            )
        if not getattr(self, "_segment_route_logged", False):
            self._segment_route_logged = True
            if self.strategy not in ("gather", "fused"):
                log_event(
                    _log, "runner.segment_route", strategy=self.strategy,
                    route="gather",
                    reason="per-cell output exists for fused and gather "
                    "programs only",
                )
        return self._window_scores_device(batch, lengths, window_limit, cell)

    def _host_window_scores(self, batch_np, lengths_np, limit_np, cell):
        """Host-interpret per-cell scoring: the gather cell program on the
        CPU backend with host-resident tables — the segment ladder's last
        rung, exact like every other rung."""
        cpu, w, lut, entries = self._host_state()
        batch = jax.device_put(batch_np, cpu)
        lengths = jax.device_put(lengths_np, cpu)
        window_limit = (
            None if limit_np is None else jax.device_put(limit_np, cpu)
        )
        with jax.default_device(cpu):
            if self.cuckoo is not None:
                return score_ops.window_scores_batch_cuckoo(
                    batch,
                    lengths,
                    w,
                    entries,
                    seed1=self.cuckoo.seed1,
                    seed2=self.cuckoo.seed2,
                    spec=self.spec,
                    cell=cell,
                    block=min(self.block, 1024),
                    window_limit=window_limit,
                )
            return score_ops.window_scores_batch(
                batch,
                lengths,
                w,
                lut,
                spec=self.spec,
                cell=cell,
                block=min(self.block, 1024),
                window_limit=window_limit,
            )

    def _segment_degraded(
        self, batch_docs, batch_limits, pad_to, placement, cell, cause=None
    ):
        """The degradation ladder in segment mode — fused → device gather
        cells → host gather cells, exact at every rung (the gather rungs
        read the original f32 tables, so degraded segment batches never
        carry quantization error), same fencing/telemetry story as
        :meth:`_degraded_scores`."""
        if all(lim == self.max_chunk for lim in batch_limits):
            limit_np = None
        else:
            limit_np = np.asarray(batch_limits, dtype=np.int32)
        batch_np, lengths_np = self._pack(batch_docs, pad_to)
        levels = ["host"]
        if self.strategy == "fused":
            # Only the fused strategy has a DIFFERENT device program to
            # fall back from; every other strategy's segment dispatch is
            # already the gather cell program.
            levels.insert(0, "gather")
        last = cause
        for level in levels:
            try:
                with span(
                    "score/degraded", rows=len(batch_docs), pad_to=pad_to,
                    level=level, degraded=True, segment=True,
                ) as sp:
                    if level == "gather":
                        faults.inject("score/dispatch")
                        batch = jax.device_put(batch_np, placement)
                        lengths = jax.device_put(lengths_np, placement)
                        window_limit = (
                            None
                            if limit_np is None
                            else jax.device_put(limit_np, placement)
                        )
                        cells = self._window_scores_device(
                            batch, lengths, window_limit, cell
                        )
                    else:
                        cells = self._host_window_scores(
                            batch_np, lengths_np, limit_np, cell
                        )
                    jax.block_until_ready(cells)
                    sp.fence(cells)
            except Exception as e:
                if not self.retry_policy.classify(e):
                    raise
                last = e
                continue
            self._degraded_mode = True
            self.metrics.incr("degraded_batches")
            REGISTRY.incr("resilience/degraded_batches")
            REGISTRY.incr(f"resilience/degraded_{level}")
            REGISTRY.set_gauge("langdetect_degraded", 1.0)
            log_event(
                _log,
                "runner.degraded",
                level=level,
                rows=len(batch_docs),
                pad_to=pad_to,
                segment=True,
                breaker=self.breaker.state,
                cause=repr(cause) if cause is not None else None,
            )
            return cells
        raise last if last is not None else RuntimeError(
            "segment degraded ladder exhausted with no recorded cause"
        )

    def segment_cells(
        self, byte_docs: Sequence[bytes], *, cell: int | None = None
    ) -> tuple[list[np.ndarray], list[bytes]]:
        """Raw per-cell scores for span-level decoding (docs/SEGMENTATION.md).

        Returns ``(cells, scored_docs)``: ``cells[i]`` is float32
        ``[C_i, L]`` with ``C_i = max(1, ceil(len_i / cell))`` — entry
        ``[c]`` sums every window (every gram length) whose start byte
        lies in ``[c·cell, (c+1)·cell)`` of the document — and
        ``scored_docs[i]`` is the byte string the cells describe (the
        input after ``max_score_bytes`` truncation), so the host span
        decoder snaps boundaries on the content that was actually scored.

        Long documents chunk on a CELL-ALIGNED stride (the whole-doc
        path's overlap rule rounded so chunk ownership boundaries land on
        cell boundaries), so every global cell is owned by exactly one
        chunk and the assembled cells are exact — no cross-chunk blending.
        Transient dispatch failures replay under the retry policy and
        ride the degradation ladder (fused → device gather cells → host),
        exact at every rung. The whole-doc ``score``/``predict_ids``
        paths share none of this method's dispatch programs and stay
        bit-identical to their pre-segmentation behavior.
        """
        cell = int(cell or SEGMENT_CELL)
        if cell < 128 or cell % 128:
            raise ValueError(
                f"segment cell must be a positive multiple of 128, got {cell}"
            )
        if cell > self.max_chunk:
            raise ValueError(
                f"segment cell {cell} exceeds the largest length bucket "
                f"{self.max_chunk}"
            )
        try:
            return self._segment_traced(byte_docs, cell)
        except Exception as e:
            flightrec.record_crash("segment", e)
            raise

    def _segment_traced(self, byte_docs, cell):
        if self.max_score_bytes:
            cap = self.max_score_bytes
            if self.score_encoding == UTF8:
                byte_docs = [truncate_utf8(d, cap) for d in byte_docs]
            else:
                byte_docs = [d[:cap] for d in byte_docs]
        else:
            byte_docs = list(byte_docs)
        N_in = len(byte_docs)
        inverse = None
        if self.dedup and N_in > 1:
            d = dedup_counted(byte_docs)
            if d is not None:
                first_idx, inverse, _ = d
                byte_docs = [byte_docs[int(i)] for i in first_idx]
        L = self.weights.shape[1]
        out: list[np.ndarray | None] = [None] * len(byte_docs)
        if not byte_docs:
            return [], []

        overlap = max(self.spec.gram_lengths) - 1
        # Cell-aligned chunk stride: ownership boundaries must land on
        # cell edges so each global cell belongs to exactly one chunk.
        stride = ((self.max_chunk - overlap) // cell) * cell
        if self.mesh is not None:
            from ..parallel.mesh import batch_sharding, pad_rows_for_mesh

            placement = batch_sharding(self.mesh)
        else:
            placement = self.device

        # Work items: (doc index, chunk bytes, window limit, global cell
        # offset, owned cell count).
        doc_idx: list[int] = []
        chunks: list[bytes] = []
        limits: list[int] = []
        cell_offs: list[int] = []
        takes: list[int] = []
        for i, doc in enumerate(byte_docs):
            n_cells = max(1, -(-len(doc) // cell))
            out[i] = np.zeros((n_cells, L), dtype=np.float32)
            if len(doc) <= self.max_chunk:
                doc_idx.append(i)
                chunks.append(doc)
                limits.append(self.max_chunk)
                cell_offs.append(0)
                takes.append(n_cells)
            else:
                if stride < cell:
                    # Only a document that actually needs chunking needs
                    # the stride; single-chunk docs segment fine even
                    # when max_chunk leaves no room for one.
                    raise ValueError(
                        f"document of {len(doc)} bytes needs chunking, but "
                        f"segment cell {cell} leaves no cell-aligned chunk "
                        f"stride under max_chunk {self.max_chunk} "
                        f"(overlap {overlap})"
                    )
                parts = chunk_document(doc, stride + overlap, overlap)
                for j, part in enumerate(parts):
                    doc_idx.append(i)
                    chunks.append(part)
                    off = j * stride // cell
                    cell_offs.append(off)
                    if j < len(parts) - 1:
                        limits.append(stride)
                        takes.append(stride // cell)
                    else:
                        limits.append(self.max_chunk)
                        takes.append(n_cells - off)

        sizes = [len(c) for c in chunks]
        plan = plan_micro_batches(
            sizes,
            length_buckets=self.length_buckets,
            rows_for=lambda pad_to: rows_under_byte_budget(
                pad_to, self.batch_bytes, self.batch_size
            ),
        )
        multiproc = self.mesh is not None and jax.process_count() > 1

        def on_retry(attempt_no, delay_s, exc):
            self.metrics.incr("retries")
            REGISTRY.incr("score/retries")

        def build_and_dispatch(sel, pad_to):
            batch_docs = [chunks[k] for k in sel]
            batch_limits = [limits[k] for k in sel]
            if self.mesh is not None:
                batch_docs, batch_limits = pad_rows_for_mesh(
                    batch_docs, self._ndata, (batch_limits, self.max_chunk)
                )
            if all(lim == self.max_chunk for lim in batch_limits):
                limit_np = None
            else:
                limit_np = np.asarray(batch_limits, dtype=np.int32)
            with span("score/pack", parent=seg_span,
                      rows=len(batch_docs), pad_to=pad_to):
                batch_np, lengths_np = self._pack(batch_docs, pad_to)
            with span("score/dispatch", parent=seg_span,
                      rows=len(batch_docs), pad_to=pad_to) as sp:
                batch = jax.device_put(batch_np, placement)
                lengths = jax.device_put(lengths_np, placement)
                window_limit = (
                    None if limit_np is None
                    else jax.device_put(limit_np, placement)
                )
                cells = self._segment_dispatch_device(
                    batch, lengths, window_limit, cell
                )
                sp.fence(cells)
            return cells

        def degraded_for(sel, pad_to, cause):
            batch_docs = [chunks[k] for k in sel]
            batch_limits = [limits[k] for k in sel]
            if self.mesh is not None:
                batch_docs, batch_limits = pad_rows_for_mesh(
                    batch_docs, self._ndata, (batch_limits, self.max_chunk)
                )
            return self._segment_degraded(
                batch_docs, batch_limits, pad_to, placement, cell, cause
            )

        def run_one(item):
            sel, pad_to = item
            fallback_ok = not multiproc and self.degraded_fallback
            cells = guarded_dispatch(
                lambda: build_and_dispatch(sel, pad_to),
                policy=self.retry_policy,
                site="score/dispatch",
                breaker=self.breaker if fallback_ok else None,
                degraded=(
                    (lambda cause: degraded_for(sel, pad_to, cause))
                    if fallback_ok else None
                ),
                on_retry=on_retry,
                log_fields={"rows": len(sel), "segment": True},
            )
            return (sel, pad_to, cells)

        workers = self.dispatch_workers
        if workers is None:
            workers = DISPATCH_WORKERS if self.mesh is None else 1
        workers = max(1, min(workers, len(plan)))
        with trace_request(), self.metrics.timer("score_s"), span(
            "score", docs=N_in, unique=len(byte_docs), batches=len(plan),
            strategy=self.strategy, segment=True, cell=cell,
        ) as seg_span:
            pending = run_ordered(plan, run_one, workers)
            with span("score/fetch", batches=len(plan)):
                # Start every batch's d2h copy before draining any — the
                # same prefetch the whole-doc fetch loop does, and worth
                # strictly more here: segment payloads are [B, C, L]
                # floats, C cells per chunk wider than the whole-doc
                # [B, L] rows. Multi-process meshes skip it (results
                # assemble via process_allgather in _fetch; a host copy
                # of non-addressable shards can't start).
                for _, _, c in (pending if not multiproc else ()):
                    try:
                        c.copy_to_host_async()
                    except (AttributeError, *RETRYABLE):
                        # AttributeError: non-jax array (numpy test
                        # doubles). Runtime errors: a deferred execution
                        # error surfacing early — the fetch loop below
                        # retries it.
                        pass
                for sel, pad_to, cells in pending:
                    try:
                        faults.inject("score/fetch")
                        host = self._fetch(cells)
                    except Exception as e:
                        # Async dispatch defers execution errors to the
                        # fetch: replay the batch under the policy, then
                        # the ladder — never on a multi-process mesh,
                        # where a lone replay would desynchronize the
                        # collective schedule.
                        if multiproc or not self.retry_policy.classify(e):
                            raise
                        try:
                            host = self.retry_policy.run(
                                lambda sel=sel, pad_to=pad_to: self._fetch(
                                    build_and_dispatch(sel, pad_to)
                                ),
                                site="score/fetch",
                                breaker=self.breaker,
                                on_retry=on_retry,
                                initial_error=e,
                                log_fields={"rows": len(sel)},
                            )
                        except Exception as e2:
                            if (
                                not self.degraded_fallback
                                or not self.retry_policy.classify(e2)
                            ):
                                raise
                            host = self._fetch(degraded_for(
                                sel, pad_to, e2
                            ))
                    for r, k in enumerate(sel):
                        i = doc_idx[k]
                        off, take = cell_offs[k], takes[k]
                        out[i][off:off + take] = host[r, :take]

        self.metrics.incr("docs_scored", N_in)
        log_event(
            _log, "runner.segment", docs=N_in, unique=len(byte_docs),
            chunks=len(chunks), batches=len(plan), cell=cell,
        )
        if inverse is not None:
            return (
                [out[int(j)] for j in inverse],
                [byte_docs[int(j)] for j in inverse],
            )
        return list(out), list(byte_docs)
