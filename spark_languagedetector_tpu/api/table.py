"""Columnar table: the framework's DataFrame-in/DataFrame-out currency.

The reference's public API is Spark ``Dataset``/``DataFrame`` in and out
(``/root/reference/src/main/.../LanguageDetectorModel.scala:219-240``). A TPU
pipeline wants *columnar* data — padded device batches are built from
contiguous column arrays, not per-row objects — so the native analog is a thin
immutable columnar table with a typed schema, cheap column selection, and
append-column semantics (the reference's ``SchemaUtils.appendColumn``).

Columns are numpy object/primitive arrays; string columns are numpy arrays of
Python str (object dtype) so slicing/fancy-indexing are vectorized. Interop:
``from_pandas``/``to_pandas`` and pyarrow round-trip for the persistence layer.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterator, Mapping, Sequence

import numpy as np

# Minimal type vocabulary; mirrors what the reference's schemas actually use.
STRING = "string"
INT = "int"
LONG = "long"
DOUBLE = "double"
BINARY = "binary"
ARRAY_DOUBLE = "array<double>"

_NUMPY_KINDS = {
    "U": STRING,
    "O": STRING,  # object arrays of str (or bytes → BINARY, resolved per value)
    "i": LONG,
    "u": LONG,
    "f": DOUBLE,
    "b": INT,
}


def _infer_type(values: np.ndarray) -> str:
    kind = values.dtype.kind
    if kind == "O" and len(values) > 0:
        v = values[0]
        if isinstance(v, (bytes, bytearray)):
            return BINARY
        if isinstance(v, (list, np.ndarray)):
            return ARRAY_DOUBLE
    return _NUMPY_KINDS.get(kind, STRING)


def _to_object_column(values) -> np.ndarray:
    """Coerce a python sequence to a 1-D object array without numpy collapsing
    nested equal-length lists into a 2-D array (needed for array<double>
    columns like per-language probability vectors)."""
    if isinstance(values, np.ndarray):
        return values
    arr = np.empty(len(values), dtype=object)
    for i, v in enumerate(values):
        arr[i] = v
    return arr


@dataclass(frozen=True)
class Field:
    name: str
    dtype: str
    nullable: bool = True


class Schema:
    """Ordered collection of fields; supports the reference's schema ops."""

    def __init__(self, fields: Sequence[Field]):
        self.fields = list(fields)
        names = [f.name for f in self.fields]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate column names in schema: {names}")

    @property
    def names(self) -> list[str]:
        return [f.name for f in self.fields]

    def __contains__(self, name: str) -> bool:
        return name in self.names

    def __getitem__(self, name: str) -> Field:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)

    def __len__(self) -> int:
        return len(self.fields)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Schema) and self.fields == other.fields

    def __repr__(self) -> str:
        inner = ", ".join(f"{f.name}:{f.dtype}" for f in self.fields)
        return f"Schema({inner})"

    def append(self, name: str, dtype: str, nullable: bool = True) -> "Schema":
        """Append a column; errors if present (Spark's appendColumn contract)."""
        if name in self:
            raise ValueError(f"column {name!r} already exists")
        return Schema(self.fields + [Field(name, dtype, nullable)])

    def drop(self, name: str) -> "Schema":
        return Schema([f for f in self.fields if f.name != name])


class Table:
    """Immutable columnar table."""

    def __init__(
        self,
        columns: Mapping[str, Sequence[Any] | np.ndarray],
        schema: Schema | None = None,
    ):
        self._columns: dict[str, np.ndarray] = {}
        lengths = set()
        for name, values in columns.items():
            arr = _to_object_column(values)
            if arr.ndim != 1:
                raise ValueError(f"column {name!r} must be 1-D, got {arr.shape}")
            self._columns[name] = arr
            lengths.add(len(arr))
        if len(lengths) > 1:
            raise ValueError(f"ragged columns: lengths {sorted(lengths)}")
        self._num_rows = lengths.pop() if lengths else 0
        if schema is None:
            schema = Schema(
                [Field(n, _infer_type(c)) for n, c in self._columns.items()]
            )
        if set(schema.names) != set(self._columns):
            raise ValueError(
                f"schema names {schema.names} != column names {list(self._columns)}"
            )
        self.schema = schema

    # -- constructors ----------------------------------------------------------
    @staticmethod
    def from_rows(rows: Sequence[Mapping[str, Any]], names: Sequence[str] | None = None) -> "Table":
        if not rows:
            return Table({})
        names = list(names or rows[0].keys())
        return Table({n: [r[n] for r in rows] for n in names})

    @staticmethod
    def from_pandas(df) -> "Table":
        return Table({c: df[c].to_numpy() for c in df.columns})

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame({n: self._columns[n] for n in self.schema.names})

    # -- access ----------------------------------------------------------------
    @property
    def num_rows(self) -> int:
        return self._num_rows

    def __len__(self) -> int:
        return self._num_rows

    def column(self, name: str) -> np.ndarray:
        if name not in self._columns:
            raise KeyError(
                f"column {name!r} not in table (have {self.schema.names})"
            )
        return self._columns[name]

    def __getitem__(self, name: str) -> np.ndarray:
        return self.column(name)

    def rows(self) -> Iterator[dict[str, Any]]:
        names = self.schema.names
        cols = [self._columns[n] for n in names]
        for i in range(self._num_rows):
            yield {n: c[i] for n, c in zip(names, cols)}

    def to_rows(self) -> list[dict[str, Any]]:
        return list(self.rows())

    # -- transforms ------------------------------------------------------------
    def select(self, *names: str) -> "Table":
        return Table(
            {n: self._columns[n] for n in names},
            Schema([self.schema[n] for n in names]),
        )

    def with_column(self, name: str, values: Sequence[Any] | np.ndarray, dtype: str | None = None) -> "Table":
        """Append a column (new table); name must not already exist."""
        arr = _to_object_column(values)
        if len(arr) != self._num_rows:
            raise ValueError(f"length {len(arr)} != num_rows {self._num_rows}")
        cols = dict(self._columns)
        cols[name] = arr
        return Table(cols, self.schema.append(name, dtype or _infer_type(arr)))

    def replace_column(self, name: str, values: Sequence[Any] | np.ndarray, dtype: str | None = None) -> "Table":
        """Drop ``name`` then re-append it last — the reference preprocessors'
        in-place column-replace schema semantics
        (``LowerCasePreprocessor.scala:38-42``)."""
        arr = _to_object_column(values)
        cols = {n: v for n, v in self._columns.items() if n != name}
        cols[name] = arr
        schema = self.schema.drop(name).append(name, dtype or _infer_type(arr))
        return Table(cols, schema)

    def take(self, n: int) -> "Table":
        return Table(
            {k: v[:n] for k, v in self._columns.items()}, self.schema
        )

    def __repr__(self) -> str:
        return f"Table(num_rows={self._num_rows}, schema={self.schema})"


def require_string_column(schema: Schema, name: str) -> None:
    """Reference's transformSchema check (``LanguageDetectorModel.scala:206-209``)."""
    if name not in schema:
        raise KeyError(f"column {name!r} not found in schema {schema.names}")
    dtype = schema[name].dtype
    if dtype != STRING:
        raise TypeError(f"Input type must be {STRING} but got {dtype}.")
