"""Uniform hyper-parameter system for all pipeline stages.

TPU-native re-design of Spark ML's ``Param``/``Params`` framework as used by
the reference (``/root/reference/src/main/.../LanguageDetector.scala:195-205``,
``LanguageDetectorModel.scala:200-203``). Differences by design (SURVEY.md
§5.6): the reference splits configuration between ML Params (columns,
``saveGramsToHDFS``) and constructor arguments not covered by ``copy`` or
persistence metadata (``supportedLanguages``/``gramLengths``/
``languageProfileSize``). Here *every* hyper-parameter is a ``Param`` so that
``copy()`` and model persistence cover all of them uniformly, including the
``backend`` switch ("tpu" | "cpu") called for by BASELINE's north star.
"""

from __future__ import annotations

import copy as _copy
from typing import Any, Callable, Generic, TypeVar

from ..utils.identifiable import Identifiable

T = TypeVar("T")


class Param(Generic[T]):
    """A named, documented parameter slot declared on a ``Params`` class."""

    def __init__(
        self,
        name: str,
        doc: str,
        validator: Callable[[Any], bool] | None = None,
    ):
        self.name = name
        self.doc = doc
        self.validator = validator

    def __repr__(self) -> str:
        return f"Param({self.name})"


class Params(Identifiable):
    """Base class for anything configurable: estimators, models, transformers.

    Semantics mirror the Spark ML contract the reference relies on:
    - class-level ``Param`` declarations, discovered via the MRO;
    - ``set_default`` values overridable per-instance with ``set``;
    - ``get_or_default`` raising if neither set nor default exists;
    - ``copy(extra)`` producing a same-uid deep copy with overrides applied.
    """

    def __init__(self, uid: str | None = None, *, uid_prefix: str | None = None):
        super().__init__(uid, uid_prefix=uid_prefix)
        self._param_values: dict[str, Any] = {}
        self._param_defaults: dict[str, Any] = {}

    # -- declaration discovery -------------------------------------------------
    @classmethod
    def params(cls) -> dict[str, Param]:
        out: dict[str, Param] = {}
        for klass in reversed(cls.__mro__):
            for key, value in vars(klass).items():
                if isinstance(value, Param):
                    out[value.name] = value
        return out

    def _resolve(self, param: Param | str) -> Param:
        name = param.name if isinstance(param, Param) else param
        declared = type(self).params()
        if name not in declared:
            raise KeyError(f"{type(self).__name__} has no param {name!r}")
        return declared[name]

    # -- get/set ---------------------------------------------------------------
    def set(self, param: Param | str, value: Any):
        p = self._resolve(param)
        if p.validator is not None and not p.validator(value):
            raise ValueError(f"Invalid value for param {p.name}: {value!r}")
        self._param_values[p.name] = value
        return self

    def set_default(self, **kwargs: Any):
        for name, value in kwargs.items():
            p = self._resolve(name)
            self._param_defaults[p.name] = value
        return self

    def is_set(self, param: Param | str) -> bool:
        return self._resolve(param).name in self._param_values

    def has_default(self, param: Param | str) -> bool:
        return self._resolve(param).name in self._param_defaults

    def is_defined(self, param: Param | str) -> bool:
        return self.is_set(param) or self.has_default(param)

    def get_or_default(self, param: Param | str) -> Any:
        p = self._resolve(param)
        if p.name in self._param_values:
            return self._param_values[p.name]
        if p.name in self._param_defaults:
            return self._param_defaults[p.name]
        raise KeyError(f"Param {p.name!r} is neither set nor has a default")

    def get(self, param: Param | str) -> Any:
        return self.get_or_default(param)

    def explain_params(self) -> str:
        lines = []
        for name, p in sorted(type(self).params().items()):
            current = (
                repr(self._param_values.get(name, self._param_defaults.get(name)))
                if self.is_defined(name)
                else "undefined"
            )
            lines.append(f"{name}: {p.doc} (current: {current})")
        return "\n".join(lines)

    # -- copy ------------------------------------------------------------------
    def copy(self, extra: dict[str, Any] | None = None):
        """Deep copy preserving uid, then apply ``extra`` overrides.

        Matches the reference's ``defaultCopy`` behavior
        (``LanguageDetector.scala:208``) but covers all params because all
        hyper-parameters live in the params system here.
        """
        new = _copy.deepcopy(self)
        for name, value in (extra or {}).items():
            new.set(name, value)
        return new

    # -- persistence support ---------------------------------------------------
    def param_metadata(self) -> dict[str, Any]:
        """JSON-serializable map of explicitly-set params (+ defaults map)."""
        return {
            "params": dict(self._param_values),
            "defaultParams": dict(self._param_defaults),
        }

    def _set_params_from_metadata(self, metadata: dict[str, Any]) -> None:
        for name, value in metadata.get("defaultParams", {}).items():
            if name in type(self).params():
                self._param_defaults[name] = value
        for name, value in metadata.get("params", {}).items():
            if name in type(self).params():
                self.set(name, value)


# --- shared column traits (Spark ML's HasInputCol/HasLabelCol/HasOutputCol) ---


class HasInputCol(Params):
    input_col = Param("inputCol", "name of the input text column")

    def set_input_col(self, value: str):
        return self.set(HasInputCol.input_col, value)

    def get_input_col(self) -> str:
        return self.get_or_default(HasInputCol.input_col)


class HasLabelCol(Params):
    label_col = Param("labelCol", "name of the label (language) column")

    def set_label_col(self, value: str):
        return self.set(HasLabelCol.label_col, value)

    def get_label_col(self) -> str:
        return self.get_or_default(HasLabelCol.label_col)


class HasOutputCol(Params):
    output_col = Param("outputCol", "name of the output column")

    def set_output_col(self, value: str):
        return self.set(HasOutputCol.output_col, value)

    def get_output_col(self) -> str:
        return self.get_or_default(HasOutputCol.output_col)
