"""Pipeline: chain preprocessors and an estimator into one fit/transform unit.

The reference's classes are built to the Spark ML ``Pipeline`` contract —
SURVEY.md §1 places them under "user code / Spark ML `Pipeline`" — but Spark
supplies the chaining itself. A reference user migrating here gets the same
composition surface: a ``Pipeline`` of transformer stages (anything with
``transform``) and at most-any estimator stages (anything with ``fit``);
``Pipeline.fit`` runs transformers forward, fits each estimator on the
running dataset, and returns a ``PipelineModel`` of the fitted stages whose
``transform`` replays the whole chain.

Mirrors Spark's semantics: stages run in declaration order; only stages
strictly before the last estimator transform the training data during fit
(the last estimator's model and any stages after it are collected into the
``PipelineModel`` without running on the training table); ``copy``
deep-copies the stage list.

Pipelines persist — both fitted (``PipelineModel.write().save(path)`` /
``load``) and unfitted (``Pipeline.write().save(path)`` / ``load``) —
mirroring the Spark ML pipeline persistence the reference inherits for free
(the same MLWritable machinery as its model — LanguageDetectorModel.scala:22-25):
a ``metadata/`` JSON names the stages in order and each stage saves under
``stages/<idx>_<uid>/`` — MLWritable stages (the detector model) through
their own writer, params-only stages (the preprocessors, and the estimator,
whose every hyper-parameter is a Param by design) as a metadata-only
directory.
"""

from __future__ import annotations

import json
import re
import shutil
import time
from pathlib import Path
from typing import Sequence

from ..utils.identifiable import Identifiable

_PIPELINE_CLASS = "spark_languagedetector_tpu.api.pipeline.Pipeline"
_PIPELINE_MODEL_CLASS = "spark_languagedetector_tpu.api.pipeline.PipelineModel"
# Stage classes are resolved by import at load time; restrict to this
# package so pipeline metadata can't be used to import arbitrary modules
# (the analog of Spark's DefaultParamsReader class check).
_STAGE_CLASS_PREFIX = "spark_languagedetector_tpu."


def _write_metadata(stage_dir: Path, payload: dict) -> None:
    """``<dir>/metadata/part-00000`` single-line JSON, Spark-style."""
    meta_dir = stage_dir / "metadata"
    meta_dir.mkdir(parents=True)
    (meta_dir / "part-00000").write_text(json.dumps(payload) + "\n")


def _read_metadata(stage_dir: Path) -> dict:
    return json.loads(
        (stage_dir / "metadata" / "part-00000").read_text().splitlines()[0]
    )


class Pipeline(Identifiable):
    """Estimator over an ordered list of stages (transformers/estimators)."""

    def __init__(self, stages: Sequence[object], uid: str | None = None):
        super().__init__(uid, uid_prefix="Pipeline")
        for s in stages:
            if not hasattr(s, "transform") and not hasattr(s, "fit"):
                raise TypeError(
                    f"pipeline stage {s!r} has neither transform nor fit"
                )
        self.stages = list(stages)

    def fit(self, dataset) -> "PipelineModel":
        # Spark parity (org.apache.spark.ml.Pipeline.fit): only stages
        # strictly BEFORE the last estimator transform the training data
        # inside fit. The last estimator is fitted but its model never runs on
        # the training table (which usually already carries the label column
        # the model's transform would append), and stages after it are plain
        # transformers collected into the PipelineModel without being applied.
        last_fit = max(
            (i for i, s in enumerate(self.stages) if hasattr(s, "fit")),
            default=-1,
        )
        fitted = []
        current = dataset
        for i, stage in enumerate(self.stages):
            if hasattr(stage, "fit"):
                model = stage.fit(current)
                fitted.append(model)
                if i < last_fit:
                    current = model.transform(current)
            else:
                fitted.append(stage)
                if i < last_fit:
                    current = stage.transform(current)
        return PipelineModel(fitted)

    def copy(self, extra=None):
        import copy as _copy

        return Pipeline([_copy.deepcopy(s) for s in self.stages], uid=self.uid)

    # -- persistence (unfitted pipeline, Spark Pipeline.write parity) ----------
    def write(self) -> "_PipelineModelWriter":
        return _PipelineModelWriter(self, class_name=_PIPELINE_CLASS)

    def save(self, path: str) -> None:
        self.write().overwrite().save(path)

    @staticmethod
    def load(path: str) -> "Pipeline":
        meta, stages = _load_pipeline_dir(Path(path), _PIPELINE_CLASS)
        return Pipeline(stages, uid=meta["uid"])


class PipelineModel(Identifiable):
    """Transformer chaining the fitted stages of a :class:`Pipeline`."""

    def __init__(self, stages: Sequence[object], uid: str | None = None):
        super().__init__(uid, uid_prefix="PipelineModel")
        self.stages = list(stages)

    def transform(self, dataset):
        current = dataset
        for stage in self.stages:
            current = stage.transform(current)
        return current

    def copy(self, extra=None):
        import copy as _copy

        return PipelineModel(
            [_copy.deepcopy(s) for s in self.stages], uid=self.uid
        )

    # -- persistence -----------------------------------------------------------
    def write(self) -> "_PipelineModelWriter":
        return _PipelineModelWriter(self)

    def save(self, path: str) -> None:
        """Overwrite semantics (like the detector model's ``save``); use
        ``write().save(path)`` for the fail-if-exists contract."""
        self.write().overwrite().save(path)

    @staticmethod
    def load(path: str) -> "PipelineModel":
        meta, stages = _load_pipeline_dir(Path(path), _PIPELINE_MODEL_CLASS)
        return PipelineModel(stages, uid=meta["uid"])


def _load_pipeline_dir(root: Path, expected_class: str):
    """(metadata, reconstructed stages) for a saved pipeline directory."""
    meta = _read_metadata(root)
    if meta.get("class") != expected_class:
        raise ValueError(
            f"metadata class mismatch: expected {expected_class}, "
            f"got {meta.get('class')}"
        )
    stages = []
    for info in meta["stages"]:
        cls = _import_stage_class(info["class"])
        # The dir name comes from the metadata file — confine it to a
        # direct child of stages/ (same trust boundary as the class
        # check above). Allowlist, not denylist: the empty string,
        # backslashes (a separator on Windows), and anything outside
        # [A-Za-z0-9._-] are rejected along with "." / "..".
        dir_name = info["dir"]
        if (
            not re.fullmatch(r"[A-Za-z0-9._-]+", dir_name)
            or dir_name in ("..", ".")
        ):
            raise ValueError(
                f"refusing stage directory name {dir_name!r}: must be a "
                "plain name under stages/"
            )
        sdir = root / "stages" / dir_name
        if info.get("writable"):
            stage = cls.load(str(sdir))
        else:
            smeta = _read_metadata(sdir)
            pmeta = smeta.get("paramMap", {})
            if hasattr(cls, "_from_param_metadata"):
                # Stages whose constructor takes required arguments (the
                # estimator) rebuild themselves from their params.
                stage = cls._from_param_metadata(smeta["uid"], pmeta)
            else:
                stage = cls(uid=smeta["uid"])
                stage._set_params_from_metadata(pmeta)
        stages.append(stage)
    return meta, stages


def _import_stage_class(name: str):
    if not name.startswith(_STAGE_CLASS_PREFIX):
        raise ValueError(
            f"refusing to import pipeline stage class {name!r}: not part of "
            f"{_STAGE_CLASS_PREFIX.rstrip('.')}"
        )
    import importlib

    module_name, _, cls_name = name.rpartition(".")
    return getattr(importlib.import_module(module_name), cls_name)


class _PipelineModelWriter:
    """``pipeline.write().save(path)`` — MLWritable shape, delegating to
    each stage's own writer where one exists (serves both ``Pipeline`` and
    ``PipelineModel``; the metadata class name tells the loaders apart)."""

    def __init__(self, model, class_name: str = _PIPELINE_MODEL_CLASS):
        self._model = model
        self._class_name = class_name
        self._overwrite = False

    def overwrite(self) -> "_PipelineModelWriter":
        self._overwrite = True
        return self

    def save(self, path: str) -> None:
        import os

        root = Path(path)
        if root.exists() and not self._overwrite:
            raise FileExistsError(f"{root} already exists")
        # Build the whole tree under a temp sibling, then swap it in: a
        # mid-save failure (disk full, a stage writer raising) must never
        # destroy an existing good save.
        tmp = root.parent / f".{root.name}.tmp.{os.getpid()}"
        if tmp.exists():
            shutil.rmtree(tmp)
        tmp.mkdir(parents=True)
        try:
            self._write_tree(tmp)
        except BaseException:
            # Mid-build failure: nothing was swapped, the old save (if
            # any) is untouched — only the partial temp tree goes.
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        # Swap phase: at every instant at least one complete save exists
        # on disk. The old root is renamed aside (atomic, same parent),
        # the new tree replaces it, and the old tree is deleted only
        # after the replace succeeded. A failed replace restores the old
        # root and leaves ``tmp`` on disk — the new save must not be
        # destroyed just because the rename failed.
        backup = None
        if root.exists():
            backup = root.parent / f".{root.name}.old.{os.getpid()}"
            if backup.exists():
                shutil.rmtree(backup)
            os.replace(root, backup)
        try:
            os.replace(tmp, root)
        except BaseException:
            if backup is not None:
                os.replace(backup, root)
            raise
        if backup is not None:
            shutil.rmtree(backup)

    def _write_tree(self, tmp: Path) -> None:
        """Write the stage tree + pipeline metadata under ``tmp``."""
        stage_info = []
        for i, stage in enumerate(self._model.stages):
            cls = type(stage)
            cls_name = f"{cls.__module__}.{cls.__qualname__}"
            writable = hasattr(stage, "write")
            dir_name = f"{i:02d}_{stage.uid}"
            sdir = tmp / "stages" / dir_name
            if writable:
                sdir.parent.mkdir(parents=True, exist_ok=True)
                stage.write().save(str(sdir))
            else:
                if not hasattr(stage, "param_metadata"):
                    raise TypeError(
                        f"pipeline stage {stage!r} has neither write() "
                        "nor params — cannot persist it"
                    )
                _write_metadata(
                    sdir,
                    {
                        "class": cls_name,
                        "uid": stage.uid,
                        "timestamp": int(time.time() * 1000),
                        "paramMap": stage.param_metadata(),
                    },
                )
            stage_info.append(
                {"class": cls_name, "uid": stage.uid, "dir": dir_name,
                 "writable": writable}
            )
        _write_metadata(
            tmp,
            {
                "class": self._class_name,
                "uid": self._model.uid,
                "timestamp": int(time.time() * 1000),
                "stages": stage_info,
            },
        )
