"""Pipeline: chain preprocessors and an estimator into one fit/transform unit.

The reference's classes are built to the Spark ML ``Pipeline`` contract —
SURVEY.md §1 places them under "user code / Spark ML `Pipeline`" — but Spark
supplies the chaining itself. A reference user migrating here gets the same
composition surface: a ``Pipeline`` of transformer stages (anything with
``transform``) and at most-any estimator stages (anything with ``fit``);
``Pipeline.fit`` runs transformers forward, fits each estimator on the
running dataset, and returns a ``PipelineModel`` of the fitted stages whose
``transform`` replays the whole chain.

Mirrors Spark's semantics: stages run in declaration order; only stages
strictly before the last estimator transform the training data during fit
(the last estimator's model and any stages after it are collected into the
``PipelineModel`` without running on the training table); ``copy``
deep-copies the stage list.
"""

from __future__ import annotations

from typing import Sequence

from ..utils.identifiable import Identifiable


class Pipeline(Identifiable):
    """Estimator over an ordered list of stages (transformers/estimators)."""

    def __init__(self, stages: Sequence[object], uid: str | None = None):
        super().__init__(uid, uid_prefix="Pipeline")
        for s in stages:
            if not hasattr(s, "transform") and not hasattr(s, "fit"):
                raise TypeError(
                    f"pipeline stage {s!r} has neither transform nor fit"
                )
        self.stages = list(stages)

    def fit(self, dataset) -> "PipelineModel":
        # Spark parity (org.apache.spark.ml.Pipeline.fit): only stages
        # strictly BEFORE the last estimator transform the training data
        # inside fit. The last estimator is fitted but its model never runs on
        # the training table (which usually already carries the label column
        # the model's transform would append), and stages after it are plain
        # transformers collected into the PipelineModel without being applied.
        last_fit = max(
            (i for i, s in enumerate(self.stages) if hasattr(s, "fit")),
            default=-1,
        )
        fitted = []
        current = dataset
        for i, stage in enumerate(self.stages):
            if hasattr(stage, "fit"):
                model = stage.fit(current)
                fitted.append(model)
                if i < last_fit:
                    current = model.transform(current)
            else:
                fitted.append(stage)
                if i < last_fit:
                    current = stage.transform(current)
        return PipelineModel(fitted)

    def copy(self, extra=None):
        import copy as _copy

        return Pipeline([_copy.deepcopy(s) for s in self.stages], uid=self.uid)


class PipelineModel(Identifiable):
    """Transformer chaining the fitted stages of a :class:`Pipeline`."""

    def __init__(self, stages: Sequence[object], uid: str | None = None):
        super().__init__(uid, uid_prefix="PipelineModel")
        self.stages = list(stages)

    def transform(self, dataset):
        current = dataset
        for stage in self.stages:
            current = stage.transform(current)
        return current

    def copy(self, extra=None):
        import copy as _copy

        return PipelineModel(
            [_copy.deepcopy(s) for s in self.stages], uid=self.uid
        )
