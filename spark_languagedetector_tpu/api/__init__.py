"""api subpackage."""
