"""Native runtime extensions (C++ via ctypes; no pybind11 in this image).

Builds ``packer.cpp`` into ``libpacker.so`` on first use (g++ -O3, cached
next to the source) and exposes:

  * :func:`pack_batch` — multithreaded ragged-bytes → padded uint8 [B, S]
    packing (drop-in replacement for ``ops.encoding.pad_batch``'s Python
    loop; the host-side hot path at benchmark throughput);
  * :func:`clean_bytes` — byte-level strip+squash (ASCII whitespace only;
    the str-level ``SpecialCharPreprocessor`` additionally squashes Unicode
    whitespace like NBSP and remains the semantics owner);
  * :func:`ascii_lower` — ASCII-range lowercasing.

Every entry point has a pure-Python fallback: if no compiler is available or
the build fails, ``available()`` is False and callers transparently use the
numpy paths (correctness never depends on the native library; tests assert
equivalence whenever it is present).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

import numpy as np

from ..utils.logging import get_logger, log_event

_log = get_logger("native")

_HERE = Path(__file__).resolve().parent
_SRC = _HERE / "packer.cpp"
_REF_SRC = _HERE / "refscorer.cpp"
_SO = _HERE / "libpacker.so"

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    # Compile to a process-unique temp name and rename into place: rename is
    # atomic on POSIX, so a concurrent process never dlopens a half-written
    # .so (it either sees the old file, nothing, or the complete new one).
    tmp = _SO.with_suffix(f".tmp.{os.getpid()}.so")
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
        "-o", str(tmp), str(_SRC), str(_REF_SRC), "-lpthread",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (subprocess.CalledProcessError, FileNotFoundError, subprocess.TimeoutExpired) as e:
        detail = getattr(e, "stderr", b"")
        log_event(
            _log, "native.build_failed",
            error=str(e), stderr=detail.decode() if detail else "",
        )
        tmp.unlink(missing_ok=True)
        return False


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        # A missing bench-only source (partial artifact restore) must not
        # break the production path: treat it as mtime 0 — the build itself
        # would fail and fall back, but an existing .so still loads.
        src_mtime = max(
            _SRC.stat().st_mtime,
            _REF_SRC.stat().st_mtime if _REF_SRC.exists() else 0.0,
        )
        if not _SO.exists() or _SO.stat().st_mtime < src_mtime:
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(str(_SO))
        except OSError as e:
            log_event(_log, "native.load_failed", error=str(e))
            return None
        # A cached .so whose mtime defeats the staleness check (build-cache
        # restore, rsync -t) can predate newer entry points: rebuild once if
        # any expected symbol is missing, else fall back to numpy — symbol
        # skew must never break the transparent-fallback contract. Only the
        # PRODUCTION symbols gate acceptance: the bench-only ref_* entry
        # points must not disable the packing hot path on a compiler-less
        # host with an older prebuilt .so (RefScorer checks for them itself).
        expected = ("pack_batch", "pack_ragged", "clean_bytes", "ascii_lower")
        if not all(hasattr(lib, s) for s in expected):
            log_event(_log, "native.symbols_missing", path=str(_SO))
            # ctypes never dlcloses, so the stale mapping stays alive in this
            # process; the rebuild relies on POSIX inode replacement —
            # os.replace() writes a new inode and the fresh dlopen below maps
            # it, while the old mapping keeps its (unused) inode. Not portable
            # to Windows, where a loaded DLL file cannot be replaced; this
            # module is POSIX-only (g++ -shared, .so suffix).
            del lib
            if not _build():
                return None
            try:
                lib = ctypes.CDLL(str(_SO))
            except OSError as e:
                log_event(_log, "native.load_failed", error=str(e))
                return None
            if not all(hasattr(lib, s) for s in expected):
                return None
        lib.pack_batch.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
        ]
        lib.pack_batch.restype = None
        lib.pack_ragged.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int32,
        ]
        lib.pack_ragged.restype = None
        lib.clean_bytes.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ]
        lib.clean_bytes.restype = ctypes.c_int64
        lib.ascii_lower.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.ascii_lower.restype = None
        if all(hasattr(lib, s) for s in ("ref_build", "ref_free", "ref_score")):
            lib.ref_build.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
            ]
            lib.ref_build.restype = ctypes.c_void_p
            lib.ref_free.argtypes = [ctypes.c_void_p]
            lib.ref_free.restype = None
            lib.ref_score.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p),
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_int32, ctypes.c_void_p, ctypes.c_int32,
            ]
            lib.ref_score.restype = None
        _lib = lib
        log_event(_log, "native.loaded", path=str(_SO))
        return _lib


def available() -> bool:
    return _load() is not None


def default_pack_threads() -> int:
    """Worker threads for the packing loops when the caller passes None —
    min(8, cores), overridable via ``LANGDETECT_PACK_THREADS`` (e.g. to
    leave cores free for a consumer thread pipelined against the packer,
    or to pin single-threaded packing in latency-sensitive tests). One
    policy site for both the padded and ragged loaders; the value resolves
    through ``exec.config`` so it matches what ``/varz`` reports — but a
    malformed env value logs and falls back here instead of raising: the
    packer sits on the fit/score hot path, and a typo'd tuning knob must
    never take scoring down."""
    try:
        from ..exec import config as exec_config

        threads = exec_config.resolve("pack_threads")
    except ValueError:
        log_event(
            _log, "native.bad_pack_threads",
            value=exec_config.raw_env("pack_threads"),
        )
        threads = None
    if threads is not None:
        return max(1, int(threads))
    return min(8, os.cpu_count() or 1)


def pack_batch(
    byte_docs, pad_to: int, n_threads: int | None = None
) -> tuple[np.ndarray, np.ndarray]:
    """Native padded packing: list[bytes] → (uint8 [B, pad_to], int32 [B]).

    Falls back to the numpy implementation when the library is unavailable.
    A :class:`~..ops.encode_device.DocBlock` (one byte plane + offsets)
    packs via a single vectorized scatter — no per-document Python bytes
    are ever materialized (docs/PERFORMANCE.md §11).
    """
    from ..ops.encode_device import DocBlock, pad_block

    if isinstance(byte_docs, DocBlock):
        return pad_block(byte_docs, pad_to)
    lib = _load()
    if lib is None:
        from ..ops.encoding import pad_batch as py_pad

        return py_pad(byte_docs, pad_to=pad_to)

    n = len(byte_docs)
    # Hand each bytes object's buffer to C directly (no staging concatenation
    # copy): the C-side memcpy is the only host copy of the data.
    ptrs = (ctypes.c_char_p * n)(*byte_docs)
    lens = np.fromiter((len(d) for d in byte_docs), dtype=np.int64, count=n)
    out = np.empty((n, pad_to), dtype=np.uint8)
    out_lens = np.empty(n, dtype=np.int32)
    if n_threads is None:
        n_threads = default_pack_threads()
    lib.pack_batch(
        ptrs,
        lens.ctypes.data_as(ctypes.c_void_p),
        n,
        pad_to,
        out.ctypes.data_as(ctypes.c_void_p),
        out_lens.ctypes.data_as(ctypes.c_void_p),
        n_threads,
    )
    return out, out_lens


def pack_ragged(
    byte_docs, pad_to: int, flat_step: int | None = None,
    n_threads: int | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Native ragged packing: list[bytes] → (flat uint8 [C, 128], offs
    int32 [B], lengths int32 [B]) — the wire-efficient transfer form (see
    ``ops.encoding.pack_ragged_numpy``, its host mirror and fallback).

    Offset/size bookkeeping is vectorized numpy either way; the native
    library only replaces the per-document copy loop. A
    :class:`~..ops.encode_device.DocBlock` fills the flat buffer with one
    vectorized scatter instead (docs/PERFORMANCE.md §11).
    """
    from ..ops.encode_device import DocBlock, ragged_block
    from ..ops.encoding import RAGGED_CHUNK, pack_ragged_numpy, ragged_layout

    if isinstance(byte_docs, DocBlock):
        return ragged_block(byte_docs, pad_to, flat_step)
    lib = _load()
    if lib is None:
        return pack_ragged_numpy(byte_docs, pad_to, flat_step)

    n = len(byte_docs)
    flat, offs, lengths = ragged_layout(byte_docs, pad_to, flat_step)
    if n:
        ptrs = (ctypes.c_char_p * n)(*byte_docs)
        lens64 = np.fromiter(
            (len(d) for d in byte_docs), dtype=np.int64, count=n
        )
        if n_threads is None:
            n_threads = default_pack_threads()
        lib.pack_ragged(
            ptrs,
            lens64.ctypes.data_as(ctypes.c_void_p),
            n,
            pad_to,
            RAGGED_CHUNK,
            offs.ctypes.data_as(ctypes.c_void_p),
            flat.ctypes.data_as(ctypes.c_void_p),
            # C writes the same clamp ragged_layout already computed —
            # hand it lengths' own buffer rather than a throwaway array.
            lengths.ctypes.data_as(ctypes.c_void_p),
            n_threads,
        )
    return flat, offs, lengths


def clean_bytes(data: bytes) -> bytes:
    """Byte-level strip+squash (ASCII whitespace only — Unicode whitespace
    such as NBSP passes through; use ``SpecialCharPreprocessor`` for full
    str-level semantics). Falls back to a Python byte-regex when unbuilt."""
    lib = _load()
    if lib is None:
        import re

        sym = re.compile(rb'[/_\[\]*()%^&@$#:|{}<>~`"\\]')
        ws = re.compile(rb"\s+")
        return ws.sub(b" ", sym.sub(b"", data))
    src = np.frombuffer(data, dtype=np.uint8)
    out = np.empty(len(data), dtype=np.uint8)
    n = lib.clean_bytes(
        src.ctypes.data_as(ctypes.c_void_p), len(data),
        out.ctypes.data_as(ctypes.c_void_p),
    )
    return out[:n].tobytes()


def ascii_lower(data: bytes) -> bytes:
    """Native ASCII lowercasing; multi-byte UTF-8 untouched."""
    lib = _load()
    buf = np.frombuffer(data, dtype=np.uint8).copy()
    if lib is None:
        mask = (buf >= 65) & (buf <= 90)
        buf[mask] += 32
        return buf.tobytes()
    lib.ascii_lower(buf.ctypes.data_as(ctypes.c_void_p), len(buf))
    return buf.tobytes()


class RefScorer:
    """Compiled per-row baseline: the reference hot loop's shape in C++.

    One hash-map probe per sliding window + double-precision accumulate +
    first-max-wins argmax (see ``refscorer.cpp`` — the compiled stand-in for
    the reference's JVM UDF, LanguageDetectorModel.scala:139-155). Used by
    ``bench.py`` as the ``vs_cpp`` baseline denominator and by tests as an
    independent semantics cross-check.

    Raises ``RuntimeError`` when the native library is unavailable — this is
    a measurement tool, not a production path, so it has no Python fallback
    (a fallback would silently time the wrong baseline).
    """

    def __init__(self, keys, vecs: np.ndarray):
        lib = _load()
        if lib is None or not hasattr(lib, "ref_build"):
            raise RuntimeError(
                "native library (or its ref_* entry points) unavailable; "
                "the C++ baseline cannot run"
            )
        self._lib = lib
        vecs = np.ascontiguousarray(vecs, dtype=np.float64)
        if vecs.ndim != 2 or vecs.shape[0] != len(keys):
            raise ValueError(
                f"vecs must be [len(keys), L]; got {vecs.shape} for "
                f"{len(keys)} keys"
            )
        self.num_grams = len(keys)
        self.num_languages = int(vecs.shape[1])
        n = len(keys)
        ptrs = (ctypes.c_char_p * n)(*keys)
        lens = np.fromiter((len(k) for k in keys), dtype=np.int64, count=n)
        self._handle = lib.ref_build(
            ptrs,
            lens.ctypes.data_as(ctypes.c_void_p),
            n,
            vecs.ctypes.data_as(ctypes.c_void_p),
            self.num_languages,
        )

    def score(self, byte_docs, gram_lengths, n_threads: int = 1) -> np.ndarray:
        """int32 [N] first-max-wins argmax labels. ``n_threads=1`` is the
        per-row baseline measurement; more threads model multi-core
        executors (the map is read-only and shared)."""
        if not self._handle:
            # After close() the C handle is gone; ref_score would
            # dereference NULL and segfault rather than raise.
            raise RuntimeError("RefScorer is closed")
        n = len(byte_docs)
        out = np.empty(n, dtype=np.int32)
        if n == 0:
            return out
        ptrs = (ctypes.c_char_p * n)(*byte_docs)
        lens = np.fromiter((len(d) for d in byte_docs), dtype=np.int64, count=n)
        # Caller order preserved: the exact-agreement contract with the
        # per-row Python baseline requires the same accumulation order.
        gl = np.asarray(list(gram_lengths), dtype=np.int32)
        self._lib.ref_score(
            self._handle,
            ptrs,
            lens.ctypes.data_as(ctypes.c_void_p),
            n,
            gl.ctypes.data_as(ctypes.c_void_p),
            len(gl),
            out.ctypes.data_as(ctypes.c_void_p),
            n_threads,
        )
        return out

    def close(self):
        if self._handle:
            self._lib.ref_free(self._handle)
            self._handle = None

    def __del__(self):  # best-effort; close() is the explicit path
        try:
            self.close()
        except Exception:
            pass
