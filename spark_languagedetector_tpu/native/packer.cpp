// Native host-side data loader: padded-batch packing and byte preprocessing.
//
// Role: the reference delegates its host-side data plumbing to Spark's JVM
// runtime (row iterators, Tungsten buffers). Here, the equivalent service —
// turning ragged UTF-8 documents into the zero-padded uint8 [B, S] batches
// the device scorer consumes, plus the byte-level text cleanup — is native
// C++ behind a C ABI, loaded via ctypes (no pybind11 in this image). At the
// ≥50×-throughput target the Python per-document copy loop becomes the
// bottleneck long before the TPU does; these routines are memcpy-bound and
// multithreaded.
//
// Functions are pure C ABI: no exceptions across the boundary, caller owns
// all buffers, sizes given explicitly.

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

namespace {

// Run fn(i) for i in [0, n) across up to n_threads threads (contiguous
// range partition; joins before returning).
template <typename Fn>
void parallel_for(int64_t n, int32_t n_threads, Fn fn) {
  int threads = std::max(1, n_threads);
  threads = static_cast<int>(std::min<int64_t>(threads, n));
  std::vector<std::thread> pool;
  int64_t per = (n + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    int64_t lo = t * per;
    int64_t hi = std::min(n, lo + per);
    if (lo >= hi) break;
    pool.emplace_back([=]() {
      for (int64_t i = lo; i < hi; ++i) fn(i);
    });
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Pack n_docs ragged byte strings into out[n_docs * pad_to] (zero-padded)
// and out_lens[n_docs] (clamped to pad_to). docs[i] points at document i's
// bytes (lens[i] bytes) — pointer array, so Python hands over each bytes
// object's buffer directly with zero staging copies; the memcpy below is the
// only copy of the data on the host.
void pack_batch(const uint8_t* const* docs,
                const int64_t* lens,
                int64_t n_docs,
                int64_t pad_to,
                uint8_t* out,
                int32_t* out_lens,
                int32_t n_threads) {
  if (n_docs <= 0) return;
  std::memset(out, 0, static_cast<size_t>(n_docs) * pad_to);
  parallel_for(n_docs, n_threads, [=](int64_t i) {
    int64_t n = std::min<int64_t>(lens[i], pad_to);
    if (n > 0) std::memcpy(out + i * pad_to, docs[i], n);
    out_lens[i] = static_cast<int32_t>(n);
  });
}

// Ragged packing for the wire-efficient transfer path: copy each document
// into a flat chunk-aligned buffer at a caller-computed chunk offset
// (offs[i] is document i's first chunk index; chunk row 0 is reserved as
// the all-zeros miss row the device-side unpack gather reads for
// out-of-range chunks). The caller zeroes `flat` and sizes it to the
// bucketed chunk count; this routine is the memcpy loop only.
void pack_ragged(const uint8_t* const* docs,
                 const int64_t* lens,
                 int64_t n_docs,
                 int64_t pad_to,
                 int64_t chunk,
                 const int32_t* offs,
                 uint8_t* flat,
                 int32_t* out_lens,
                 int32_t n_threads) {
  if (n_docs <= 0) return;
  parallel_for(n_docs, n_threads, [=](int64_t i) {
    int64_t n = std::min<int64_t>(lens[i], pad_to);
    if (n > 0) std::memcpy(flat + static_cast<int64_t>(offs[i]) * chunk,
                           docs[i], n);
    out_lens[i] = static_cast<int32_t>(n);
  });
}

// Byte-level special-character strip + ASCII-whitespace squash. Multi-byte
// UTF-8 sequences — including Unicode whitespace like NBSP — pass through
// untouched; the str-level SpecialCharPreprocessor owns full semantics.
// Writes cleaned bytes to out (caller sizes out >= len) and returns the
// cleaned length. Strip set: /_[]*()%^&@$#:|{}<>~`"\  — whitespace runs
// collapse to one 0x20.
namespace {
struct StripTable {
  bool strip[256] = {false};
  StripTable() {
    const char* set = "/_[]*()%^&@$#:|{}<>~`\"\\";
    for (const char* p = set; *p; ++p) strip[static_cast<uint8_t>(*p)] = true;
  }
};
const StripTable kStrip;  // thread-safe static init at load time
}  // namespace

int64_t clean_bytes(const uint8_t* in, int64_t len, uint8_t* out) {
  const bool* strip = kStrip.strip;
  int64_t o = 0;
  bool in_space = false;
  for (int64_t i = 0; i < len; ++i) {
    uint8_t c = in[i];
    if (strip[c]) continue;
    bool is_space = (c == ' ' || c == '\t' || c == '\n' || c == '\r' ||
                     c == '\f' || c == '\v');
    if (is_space) {
      in_space = true;
      continue;
    }
    if (in_space) {
      out[o++] = ' ';
      in_space = false;
    }
    out[o++] = c;
  }
  if (in_space) out[o++] = ' ';
  return o;
}

// ASCII lowercase in place (A-Z only; multi-byte UTF-8 untouched). The
// Unicode/locale-sensitive cases stay in Python; this covers the dominant
// byte range at native speed.
void ascii_lower(uint8_t* buf, int64_t len) {
  for (int64_t i = 0; i < len; ++i) {
    uint8_t c = buf[i];
    if (c >= 'A' && c <= 'Z') buf[i] = c + 32;
  }
}

}  // extern "C"
