// Compiled per-row baseline scorer: the reference hot loop's shape, -O3.
//
// Role: BASELINE.md's north star is ">=50x docs/sec over the Scala-UDF
// baseline", whose hot loop is a JVM hash-map probe per sliding window plus
// a dense vector accumulate and an argmax (reference
// LanguageDetectorModel.scala:139-155: ngrams -> Map.get -> BLAS.axpy ->
// Breeze argmax). No JVM exists in this image, so this file is the faithful
// compiled stand-in: one hash-map probe per window (std::unordered_map over
// arena-backed string_views — no per-window allocation, stronger than the
// JVM's per-window String slice), a double-precision axpy accumulate, and a
// first-max-wins argmax. bench.py times it per config as the `vs_cpp`
// denominator, bracketed by the pure-Python per-row baseline (flattering)
// and the vectorized-numpy baseline (sandbagging).
//
// Pure C ABI like packer.cpp: no exceptions across the boundary, caller owns
// all buffers, sizes explicit, documents passed as pointer+length (embedded
// NULs allowed).

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct RefModel {
  // One contiguous arena owns every key's bytes; the map's string_view keys
  // point into it. Weight rows live in one flat [n_keys, L] copy.
  std::vector<char> key_arena;
  std::vector<double> weight_arena;
  std::unordered_map<std::string_view, const double*> grams;
  int64_t L = 0;
};

// Contiguous range partition across up to n_threads threads (same helper
// shape as packer.cpp's parallel_for; duplicated because the two files
// compile as independent translation units into one .so).
template <typename Fn>
void ref_parallel_for(int64_t n, int32_t n_threads, Fn fn) {
  int threads = std::max(1, n_threads);
  threads = static_cast<int>(std::min<int64_t>(threads, n));
  if (threads == 1) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  std::vector<std::thread> pool;
  int64_t per = (n + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    int64_t lo = t * per;
    int64_t hi = std::min(n, lo + per);
    if (lo >= hi) break;
    pool.emplace_back([=]() {
      for (int64_t i = lo; i < hi; ++i) fn(i);
    });
  }
  for (auto& th : pool) th.join();
}

// Probe one window; on hit, accumulate its weight row (the axpy).
inline void probe_accumulate(const RefModel* m, const char* p, int64_t n,
                             double* acc) {
  auto it = m->grams.find(std::string_view(p, static_cast<size_t>(n)));
  if (it != m->grams.end()) {
    const double* v = it->second;
    for (int64_t j = 0; j < m->L; ++j) acc[j] += v[j];
  }
}

}  // namespace

extern "C" {

// Build the gram map: n_keys byte strings (pointer + length each; duplicates
// keep the first occurrence) with weight rows vecs[[n_keys, L]] (row-major
// doubles, copied). Returns an opaque handle for ref_score/ref_free.
void* ref_build(const uint8_t* const* keys,
                const int64_t* key_lens,
                int64_t n_keys,
                const double* vecs,
                int64_t L) {
  auto* m = new RefModel;
  m->L = L;
  int64_t total = 0;
  for (int64_t i = 0; i < n_keys; ++i) total += key_lens[i];
  m->key_arena.reserve(static_cast<size_t>(total));
  m->weight_arena.assign(vecs, vecs + n_keys * L);
  m->grams.reserve(static_cast<size_t>(n_keys) * 2);
  for (int64_t i = 0; i < n_keys; ++i) {
    size_t off = m->key_arena.size();
    m->key_arena.insert(m->key_arena.end(),
                        reinterpret_cast<const char*>(keys[i]),
                        reinterpret_cast<const char*>(keys[i]) + key_lens[i]);
    m->grams.emplace(
        std::string_view(m->key_arena.data() + off,
                         static_cast<size_t>(key_lens[i])),
        m->weight_arena.data() + i * L);
  }
  return m;
}

void ref_free(void* handle) { delete static_cast<RefModel*>(handle); }

// Score n_docs documents: per gram length, slide every full window through
// the map (documents shorter than the gram length contribute one partial
// window of the whole document — the reference's `sliding` emits a partial
// final group, LanguageDetectorModel.scala:143); accumulate hits into a
// per-document double vector; write the first-max-wins argmax (Breeze
// argmax semantics) to out_labels[i]. n_threads = 1 is the per-row baseline
// measurement; more threads model multi-core executors.
void ref_score(const void* handle,
               const uint8_t* const* docs,
               const int64_t* lens,
               int64_t n_docs,
               const int32_t* gram_lens,
               int32_t n_gl,
               int32_t* out_labels,
               int32_t n_threads) {
  const auto* m = static_cast<const RefModel*>(handle);
  const int64_t L = m->L;
  ref_parallel_for(n_docs, n_threads, [=](int64_t d) {
    std::vector<double> acc(static_cast<size_t>(L), 0.0);
    const char* data = reinterpret_cast<const char*>(docs[d]);
    const int64_t len = lens[d];
    for (int32_t gi = 0; gi < n_gl; ++gi) {
      const int64_t n = gram_lens[gi];
      if (len >= n) {
        for (int64_t i = 0; i + n <= len; ++i)
          probe_accumulate(m, data + i, n, acc.data());
      } else if (len > 0) {
        probe_accumulate(m, data, len, acc.data());
      }
    }
    int32_t best = 0;
    for (int64_t j = 1; j < L; ++j)
      if (acc[j] > acc[best]) best = static_cast<int32_t>(j);
    out_labels[d] = best;
  });
}

}  // extern "C"
