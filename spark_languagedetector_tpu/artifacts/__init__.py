"""The cold-start plane: persistent compile cache + mmap-baked artifacts.

``artifacts/`` makes the Nth spawn of a geometry compile nothing and
parse nothing (docs/PERFORMANCE.md §12): :mod:`.compile_cache` wires
JAX's persistent compilation cache and traces the bounded shape lattice;
:mod:`.bake` lays the trained tables out as raw little-endian blocks a
replica loads with ``np.memmap`` instead of a parquet parse.
"""

from .bake import (
    ArtifactError,
    artifact_path_for,
    bake_artifact,
    bake_model,
    load_artifact,
    load_baked_model,
    maybe_load_baked,
    recover_artifact,
)
from .compile_cache import enable_compile_cache, prewarm_lattice

__all__ = [
    "ArtifactError",
    "artifact_path_for",
    "bake_artifact",
    "bake_model",
    "enable_compile_cache",
    "load_artifact",
    "load_baked_model",
    "maybe_load_baked",
    "prewarm_lattice",
    "recover_artifact",
]
