"""Baked model artifacts: mmap-ready tables for the cold-start plane.

The parquet model tree (persist/io) is the durable interchange format —
Spark-readable, schema-checked, quantization-coded — but loading it is a
*parse*: every row round-trips through Arrow into Python lists, ids are
re-sorted, and the device membership tables (dense table / LUT / cuckoo)
are rebuilt from scratch in every process. A fleet scale-up pays that per
replica; a 32-tenant zoo pays it per cold load.

A baked artifact is the same model pre-laid-out for *page-in*: raw
little-endian numpy blocks (quantized int8/int16 rows or f64 weights,
sorted ids, the LUT or cuckoo state, the f32 device table) behind one JSON
header. Loading is ``np.memmap`` — no parse, no table rebuild, and N
replicas on one host share the page cache because they map the same file.

Layout (a directory, like the parquet tree it shadows)::

    <name>.baked/
      header.json   format/version, class/uid/paramMap/vocab/languages,
                    calibration, quantization scales, device form,
                    cuckoo seeds, the block table, file_bytes
      blocks.bin    4096-aligned little-endian blocks + 8-byte end magic

Crash-atomicity follows ``persist.io.save_model`` exactly: the tree is
built under a ``.<name>.tmp.<pid>`` sibling and swapped in with the
two-rename protocol; :func:`recover_artifact` mirrors
``persist.io.recover_fit_state`` — when the root is missing it promotes
the newest sibling that FULLY validates (a SIGKILL mid-build leaves a torn
tmp whose header parses but whose blocks are truncated; the
``file_bytes``/end-magic check refuses it), and deletes other siblings
only after a successful promotion.

Bit-parity contract: a quantized bake stores the same integer rows and
per-language f32 scales as the parquet quantization codec, and the loader
reconstructs weights with the identical exact-f64 product — so a baked
model scores bit-identically to the parquet-loaded one (pinned by
tests/test_artifacts.py).
"""

from __future__ import annotations

import json
import os
import shutil
from pathlib import Path

import numpy as np

from ..models.profile import (
    DENSE_TABLE_BUDGET_BYTES,
    GramProfile,
    quantize_weights,
)
from ..ops.vocab import EXACT, VocabSpec
from ..telemetry.registry import REGISTRY
from ..utils.logging import get_logger, log_event

_log = get_logger("artifacts.bake")

FORMAT = "ldbake"
FORMAT_VERSION = 1
HEADER_NAME = "header.json"
BLOCKS_NAME = "blocks.bin"
ARTIFACT_SUFFIX = ".baked"
# Every block starts on an mmap page boundary so a reader faults exactly
# the pages it touches — no block straddles another's tail page.
_ALIGN = 4096
# Written after the last block, once everything before it is on disk. A
# truncated blocks.bin (the SIGKILL-mid-build shape) cannot carry it, so
# presence + the header's file_bytes is the torn-write detector — cheap
# enough to keep load a page-in (a content checksum would read every page
# up front and defeat lazy faulting).
MAGIC = b"LDBAKED1"


class ArtifactError(ValueError):
    """A baked artifact that must not be loaded (torn, foreign, or from a
    different format version)."""


# ----------------------------------------------------------------- paths ----
def artifact_path_for(
    model_path: str | Path, artifact_dir: str | None = None, env=os.environ
) -> Path:
    """Where the baked artifact for ``model_path`` lives.

    ``LANGDETECT_ARTIFACT_DIR`` (or the explicit ``artifact_dir``) names a
    directory holding ``<model name>.baked`` trees; unset, the artifact is
    a ``<model>.baked`` sibling of the model tree — so a model directory
    copied with its siblings carries its artifact along.
    """
    from ..exec import config as exec_config

    resolved = exec_config.resolve("artifact_dir", artifact_dir, env)
    base = Path(model_path)
    if resolved:
        return Path(resolved) / (base.name + ARTIFACT_SUFFIX)
    return base.parent / (base.name + ARTIFACT_SUFFIX)


# ------------------------------------------------------------------ bake ----
def _device_form(compact: GramProfile, budget: int):
    """(form, blocks, cuckoo_meta): the numpy mirror of
    ``GramProfile.device_membership`` at f32, so the baked tables are
    bit-identical to what ``LanguageDetectorModel.load(...)._get_runner()``
    would build from the parquet tree."""
    from ..ops.cuckoo import build_cuckoo
    from ..ops.vocab import MAX_DEVICE_ID_GRAM_LEN, gram_key

    spec = compact.spec
    L = compact.num_languages
    if spec.mode == EXACT and max(spec.gram_lengths) > MAX_DEVICE_ID_GRAM_LEN:
        keys = [gram_key(spec.id_to_gram(int(i))) for i in compact.ids]
        keys_lo = np.asarray([k[0] for k in keys], dtype=np.int32)
        keys_hi = np.asarray([k[1] for k in keys], dtype=np.int32)
        table = build_cuckoo(keys_lo, keys_hi)
        w = np.concatenate(
            [compact.weights, np.zeros((1, L), compact.weights.dtype)]
        ).astype(np.float32)
        blocks = [
            ("dev_weights", w),
            ("cuckoo_slots", table.slots),
            ("cuckoo_keys_lo", table.keys_lo),
            ("cuckoo_keys_hi", table.keys_hi),
        ]
        return "cuckoo", blocks, {"seed1": table.seed1, "seed2": table.seed2}
    V = spec.id_space_size
    dense_bytes = V * L * 4
    compact_bytes = V * 4 + (compact.num_grams + 1) * L * 4
    use_dense = dense_bytes <= budget and (
        (spec.mode == EXACT and max(spec.gram_lengths) <= 2)
        or dense_bytes <= 4 * compact_bytes
    )
    if use_dense:
        return "dense", [("dev_dense", compact._dense_table(np.float32))], None
    G = compact.num_grams
    w = np.concatenate(
        [compact.weights, np.zeros((1, L), compact.weights.dtype)]
    ).astype(np.float32)
    lut = np.full(V, G, dtype=np.int32)
    lut[compact.ids] = np.arange(G, dtype=np.int32)
    return "lut", [("dev_weights", w), ("dev_lut", lut)], None


def bake_artifact(
    path: str | Path,
    profile: GramProfile,
    uid: str,
    params: dict,
    *,
    calibration: dict | None = None,
    quantize: str | None = None,
    dense_budget_bytes: int = DENSE_TABLE_BUDGET_BYTES,
) -> str:
    """Write the baked artifact directory for one model (overwrite
    semantics, crash-atomic).

    ``quantize`` ('int8' | 'int16') stores integer rows + per-language f32
    scales — the exact codec ``persist.io.save_model(quantize=...)`` uses,
    so both paths reconstruct the identical f64 weight matrix. None bakes
    the raw f64 rows.
    """
    compact = profile.compacted()
    arrays: list[tuple[str, np.ndarray]] = [
        ("ids", np.ascontiguousarray(compact.ids, dtype=np.int64))
    ]
    quant_meta = None
    if quantize is not None:
        q, scales = quantize_weights(compact.weights, quantize)
        quant_meta = {
            "dtype": quantize,
            "scales": [float(s) for s in scales],
        }
        arrays.append(("weights_q", q))
        # The device tables must mirror what a parquet load of this same
        # codec would build — the dequantized q*scale product, NOT the
        # pre-quantization weights — or baked scores drift from the
        # parquet-loaded quantized model by one rounding step.
        compact = GramProfile(
            spec=compact.spec,
            languages=compact.languages,
            ids=compact.ids,
            weights=q.astype(np.float64)
            * np.asarray(scales, dtype=np.float64),
        )
    else:
        arrays.append(
            ("weights_f64", np.ascontiguousarray(compact.weights, np.float64))
        )
    form, dev_blocks, cuckoo_meta = _device_form(compact, dense_budget_bytes)
    arrays.extend(dev_blocks)

    blocks = []
    offset = 0
    for name, arr in arrays:
        arr = np.ascontiguousarray(arr)
        if arr.dtype.byteorder == ">":
            arr = arr.astype(arr.dtype.newbyteorder("<"))
        offset = -(-offset // _ALIGN) * _ALIGN
        blocks.append(
            {
                "name": name,
                "dtype": arr.dtype.str,
                "shape": list(arr.shape),
                "offset": offset,
                "nbytes": int(arr.nbytes),
            }
        )
        offset += int(arr.nbytes)
    file_bytes = offset + len(MAGIC)

    header = {
        "format": FORMAT,
        "version": FORMAT_VERSION,
        "class": "spark_languagedetector_tpu.models.estimator."
        "LanguageDetectorModel",
        "uid": uid,
        "paramMap": params,
        "vocab": {
            "mode": compact.spec.mode,
            "gramLengths": list(compact.spec.gram_lengths),
            "hashBits": compact.spec.hash_bits,
            "hashScheme": compact.spec.hash_scheme,
        },
        "languages": list(compact.languages),
        "calibration": calibration,
        "quantization": quant_meta,
        "device_form": form,
        "dense_budget_bytes": int(dense_budget_bytes),
        "cuckoo": cuckoo_meta,
        "blocks": blocks,
        "file_bytes": file_bytes,
    }

    root = Path(path)
    tmp = root.parent / f".{root.name}.tmp.{os.getpid()}"
    if tmp.exists():
        shutil.rmtree(tmp)
    tmp.mkdir(parents=True)
    try:
        with open(tmp / BLOCKS_NAME, "wb") as fh:
            for spec_row, (_, arr) in zip(blocks, arrays):
                fh.seek(spec_row["offset"])
                fh.write(np.ascontiguousarray(arr).tobytes())
            fh.seek(file_bytes - len(MAGIC))
            fh.write(MAGIC)
        (tmp / HEADER_NAME).write_text(json.dumps(header) + "\n")
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise

    # Two-rename swap + stale-sibling sweep, same as persist.io.save_model.
    backup = None
    if root.exists():
        backup = root.parent / f".{root.name}.old.{os.getpid()}"
        if backup.exists():
            shutil.rmtree(backup)
        os.replace(root, backup)
    try:
        os.replace(tmp, root)
    except BaseException:
        if backup is not None:
            os.replace(backup, root)
        raise
    if backup is not None:
        shutil.rmtree(backup)
    for stale in list(root.parent.glob(f".{root.name}.tmp.*")) + list(
        root.parent.glob(f".{root.name}.old.*")
    ):
        shutil.rmtree(stale, ignore_errors=True)
    log_event(
        _log, "artifact.baked", path=str(root), grams=compact.num_grams,
        device_form=form, quantize=quantize, bytes=file_bytes,
    )
    return str(root)


def bake_model(model, path: str | Path, *, quantize: str | None = None) -> str:
    """Bake a fitted/loaded :class:`LanguageDetectorModel` (convenience
    over :func:`bake_artifact`)."""
    calibration = model.calibration
    return bake_artifact(
        path,
        model.profile,
        model.uid,
        model.param_metadata(),
        calibration=None if calibration is None else calibration.to_dict(),
        quantize=quantize,
    )


# ------------------------------------------------------------------ load ----
# One live mapping per blocks file: every reader's block views slice the
# same buffer, so concurrent loads in one process share pages by
# construction (and across processes via the OS page cache). Keyed on
# (realpath, size, mtime_ns) so a re-baked artifact maps fresh.
_MMAP_CACHE: dict[tuple, np.memmap] = {}


def _mapped(blocks_path: Path) -> np.memmap:
    st = os.stat(blocks_path)
    key = (os.path.realpath(blocks_path), st.st_size, st.st_mtime_ns)
    mm = _MMAP_CACHE.get(key)
    if mm is None:
        mm = np.memmap(blocks_path, dtype=np.uint8, mode="r")
        _MMAP_CACHE[key] = mm
    return mm


class BakedArtifact:
    """A validated, mapped artifact: ``header`` + zero-copy block views."""

    def __init__(self, path: Path, header: dict, buf: np.memmap):
        self.path = path
        self.header = header
        self._buf = buf
        self._blocks = {b["name"]: b for b in header["blocks"]}

    def block(self, name: str) -> np.ndarray:
        spec = self._blocks.get(name)
        if spec is None:
            raise ArtifactError(
                f"{self.path}: no block {name!r}; artifact carries "
                f"{sorted(self._blocks)}"
            )
        off, nbytes = spec["offset"], spec["nbytes"]
        view = self._buf[off : off + nbytes].view(np.dtype(spec["dtype"]))
        return view.reshape(tuple(spec["shape"]))


def load_artifact(path: str | Path) -> BakedArtifact:
    """Map + validate one baked artifact; raises :class:`ArtifactError`
    on anything torn or foreign (the caller falls back to parquet)."""
    root = Path(path)
    header_path = root / HEADER_NAME
    blocks_path = root / BLOCKS_NAME
    try:
        header = json.loads(header_path.read_text())
    except (OSError, ValueError) as e:
        raise ArtifactError(f"{root}: unreadable header: {e}") from e
    if header.get("format") != FORMAT or header.get("version") != FORMAT_VERSION:
        raise ArtifactError(
            f"{root}: format {header.get('format')!r} v"
            f"{header.get('version')!r}; this build reads {FORMAT} "
            f"v{FORMAT_VERSION}"
        )
    file_bytes = header.get("file_bytes")
    try:
        actual = os.stat(blocks_path).st_size
    except OSError as e:
        raise ArtifactError(f"{root}: missing {BLOCKS_NAME}: {e}") from e
    if actual != file_bytes:
        # The SIGKILL-mid-build shape: header parses, blocks truncated.
        raise ArtifactError(
            f"{root}: {BLOCKS_NAME} holds {actual} bytes, header promises "
            f"{file_bytes} — torn write, refusing to load"
        )
    buf = _mapped(blocks_path)
    if bytes(buf[-len(MAGIC):]) != MAGIC:
        raise ArtifactError(f"{root}: end magic missing — torn write")
    for spec_row in header.get("blocks", ()):
        end = spec_row["offset"] + spec_row["nbytes"]
        if spec_row["offset"] % _ALIGN or end > file_bytes - len(MAGIC):
            raise ArtifactError(
                f"{root}: block {spec_row['name']!r} lies outside the "
                f"mapped region"
            )
    return BakedArtifact(root, header, buf)


def recover_artifact(path: str | Path) -> bool:
    """Finish a bake swap a crash interrupted; True when recovered.

    Mirrors ``persist.io.recover_fit_state``: when ``path`` is missing,
    promote the newest ``.tmp``/``.old`` sibling that FULLY validates
    (:func:`load_artifact` is the guard — a torn tmp's header parses but
    its blocks fail the size/magic check), deleting the other siblings
    only after a successful promotion. No-op when ``path`` exists.
    """
    root = Path(path)
    if root.exists():
        return False
    candidates = list(root.parent.glob(f".{root.name}.tmp.*")) + list(
        root.parent.glob(f".{root.name}.old.*")
    )
    candidates.sort(key=lambda p: p.stat().st_mtime, reverse=True)
    for cand in candidates:
        try:
            load_artifact(cand)
        except Exception:
            continue  # torn/foreign candidate: never promote it
        os.replace(cand, root)
        for stale in list(root.parent.glob(f".{root.name}.tmp.*")) + list(
            root.parent.glob(f".{root.name}.old.*")
        ):
            shutil.rmtree(stale, ignore_errors=True)
        log_event(
            _log, "artifact.recovered", path=str(root), source=cand.name
        )
        return True
    return False


def load_baked_model(path: str | Path):
    """Artifact directory → ready :class:`LanguageDetectorModel`.

    The host profile's weights come from the identical exact-f64
    ``q * scale`` product the parquet loader computes, and the device
    membership tables are attached pre-built (mmap views) so
    ``_get_runner`` skips the LUT/cuckoo rebuild entirely.
    """
    art = load_artifact(path)
    h = art.header
    spec = VocabSpec(
        h["vocab"]["mode"],
        tuple(int(n) for n in h["vocab"]["gramLengths"]),
        hash_bits=h["vocab"].get("hashBits", 20),
        hash_scheme=h["vocab"].get("hashScheme", "fnv1a"),
    )
    ids = art.block("ids")
    quant = h.get("quantization")
    if quant is not None:
        weights = art.block("weights_q").astype(np.float64) * np.asarray(
            quant["scales"], dtype=np.float64
        )
    else:
        weights = art.block("weights_f64")
    profile = GramProfile(
        spec=spec, languages=tuple(h["languages"]), ids=ids, weights=weights
    )

    from ..models.estimator import LanguageDetectorModel

    model = LanguageDetectorModel(profile, uid=h["uid"])
    model._set_params_from_metadata(h.get("paramMap", {}))
    if h.get("calibration") is not None:
        from ..segment.calibrate import Calibration

        model.calibration = Calibration.from_dict(h["calibration"])

    form = h["device_form"]
    if form == "dense":
        weights_dev, lut, cuckoo = art.block("dev_dense"), None, None
    elif form == "lut":
        weights_dev, lut, cuckoo = (
            art.block("dev_weights"), art.block("dev_lut"), None,
        )
    else:
        from ..ops.cuckoo import CuckooTable

        weights_dev, lut = art.block("dev_weights"), None
        cuckoo = CuckooTable(
            slots=art.block("cuckoo_slots"),
            keys_lo=art.block("cuckoo_keys_lo"),
            keys_hi=art.block("cuckoo_keys_hi"),
            seed1=int(h["cuckoo"]["seed1"]),
            seed2=int(h["cuckoo"]["seed2"]),
        )
    model._prebuilt_membership = {
        "dense_budget_bytes": int(h["dense_budget_bytes"]),
        "weights": weights_dev,
        "lut": lut,
        "cuckoo": cuckoo,
    }
    REGISTRY.incr("artifacts/baked_loads")
    return model


def maybe_load_baked(
    model_path: str | Path,
    artifact: str | Path | None = None,
    env=os.environ,
):
    """The cold-load fast path: the baked model when a valid artifact
    exists for ``model_path``, else None (caller parses parquet).

    Runs sibling-promotion recovery first, and treats every artifact
    failure as a fallback, not an error — a torn or stale bake must never
    take down a load the parquet tree can serve.
    """
    cand = (
        Path(artifact)
        if artifact is not None
        else artifact_path_for(model_path, env=env)
    )
    try:
        recover_artifact(cand)
    except OSError:
        pass
    if not cand.exists():
        return None
    try:
        return load_baked_model(cand)
    except Exception as e:
        REGISTRY.incr("artifacts/load_errors")
        log_event(
            _log, "artifact.load_failed", path=str(cand), error=str(e),
            fallback="parquet",
        )
        return None
