"""Persistent compile cache + shape-lattice prewarm for the cold-start plane.

Every process that jits the scoring programs pays XLA compilation again —
on a tunneled TPU a single batch shape costs a 20-40s remote compile
(docs/PERFORMANCE.md §5), and even on CPU the per-bucket programs dominate
a replica's spawn-to-READY time. Both halves of the fix live here:

* :func:`enable_compile_cache` turns on JAX's persistent compilation
  cache keyed by a directory resolved through ``exec/config``
  (``LANGDETECT_COMPILE_CACHE_DIR``) — the Nth process to compile a given
  (program, shape) reads the cache entry instead. The min-compile-time
  and min-entry-size floors are zeroed: this framework's CPU programs
  compile in milliseconds and would otherwise never be admitted, leaving
  the cache warm only for the shapes that least need it.
* :func:`prewarm_lattice` traces the bounded padded-length bucket lattice
  the runner dispatches over (``exec/tune``'s closed compile-shape set,
  resolvable from a :class:`TuningProfile`) — so a worker reaches READY
  with every geometry it can serve either freshly compiled into the
  shared cache (first spawn) or verified cache-warm (every spawn after:
  a signature manifest written by the first full trace lets later spawns
  prove the cache with one sentinel dispatch instead of re-tracing the
  whole lattice — see :func:`prewarm_lattice`).

Cache traffic is observable, not inferred from wall time: the
``telemetry/gauges`` jax.monitoring hooks count ``compile_cache/hits``
and ``compile_cache/misses`` — :func:`enable_compile_cache` installs them.
"""

from __future__ import annotations

import hashlib
import json
import os
import time

from ..telemetry.registry import REGISTRY, Registry
from ..utils.logging import get_logger, log_event

_log = get_logger("artifacts.compile_cache")


def enable_compile_cache(
    cache_dir: str | None = None, env=os.environ
) -> str | None:
    """Point JAX's persistent compilation cache at the resolved directory.

    Resolution follows the audited precedence (explicit > env > default):
    an unset knob returns None and leaves caching off — the status quo,
    never a surprise tmpdir. Returns the live cache dir otherwise.
    Idempotent; safe to call before or after the first jit.
    """
    from ..exec import config as exec_config

    path = exec_config.resolve("compile_cache_dir", cache_dir, env)
    if not path:
        return None
    os.makedirs(path, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", str(path))
    # Admission floors default to "only slow, large compiles" — tuned for
    # multi-minute TPU programs. This framework's lattice is a handful of
    # small programs per geometry; admit everything or the cache stays
    # cold exactly where the spawn path needs it. Option names drift
    # across jax releases, so each update degrades independently.
    for opt, val in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(opt, val)
        except Exception:
            pass
    # jax binds the persistent-cache handle lazily at the FIRST compile
    # and latches the result — a process that jitted anything before this
    # call keeps the no-cache handle forever, so every later compile
    # bypasses the directory just configured (and emits no hit/miss
    # events, which would also blind the prewarm sentinel). Reset so the
    # next compile re-initializes against the new configuration.
    try:
        from jax._src import compilation_cache as _jax_cc

        _jax_cc.reset_cache()
    except Exception:
        pass
    from ..telemetry.gauges import install_jax_hooks

    install_jax_hooks()
    log_event(_log, "compile_cache.enabled", path=str(path))
    return str(path)


def _lattice_signature(runner, buckets: tuple[int, ...]) -> dict:
    """Everything the lattice's program set is keyed by, runner-side.

    The persistent cache's true key is the optimized HLO hash; this
    signature conservatively names the inputs that shape that HLO for the
    dispatch programs — geometry knobs, table shapes/dtypes (values are
    runtime args, so a weight refresh of identical shape legitimately
    reuses the programs), and the jax/backend pair. A dimension this
    misses degrades gracefully: the sentinel dispatch observes a cache
    miss and the prewarm falls back to the full trace.
    """
    import jax

    w = runner.weights
    lut = runner.lut
    return {
        "schema": 1,
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "buckets": [int(b) for b in buckets],
        "strategy": runner.strategy,
        "quantization": runner.quantization,
        "block": int(runner.block),
        "batch_bytes": int(runner.batch_bytes),
        "device_encode": bool(runner.device_encode),
        "ragged_transfer": bool(runner.ragged_transfer),
        "weights": [list(map(int, w.shape)), str(w.dtype)],
        "lut": (
            None if lut is None
            else [list(map(int, lut.shape)), str(lut.dtype)]
        ),
        "cuckoo": runner.cuckoo is not None,
        "vocab": [
            runner.spec.mode, list(runner.spec.gram_lengths),
            int(runner.spec.hash_bits),
        ],
    }


def _manifest_path(cache_dir: str, sig: dict) -> str:
    digest = hashlib.sha256(
        json.dumps(sig, sort_keys=True).encode()
    ).hexdigest()[:16]
    return os.path.join(cache_dir, f"lattice-{digest}.manifest.json")


def _hits() -> int:
    return int(
        REGISTRY.snapshot()["counters"].get("compile_cache/hits", 0)
    )


def prewarm_lattice(
    runner,
    profile=None,
    registry: Registry | None = None,
    cache_dir: str | None = None,
) -> dict:
    """Trace the padded-length bucket lattice the runner dispatches over.

    One synthetic document is pinned to each bucket ceiling and scored in
    its **own** call: a single batched call would let the planner coalesce
    the chunked docs into one shared micro-batch geometry, tracing two or
    three programs where serving traffic will hit one per bucket. Issuing
    them separately compiles (or cache-hits) each bucket's dispatch
    geometry before real traffic arrives. ``profile`` (a
    :class:`~..exec.profile.TuningProfile`) overrides the bucket source;
    by default the runner's own resolved lattice — which already consulted
    the active profile through ``exec/config`` — is what gets traced.

    **The verified-warm fast path.** Re-tracing N buckets whose programs
    already sit in the persistent cache costs pure Python trace+lower
    time per program — on a small host that tracing, not compilation, is
    the warm spawn's floor. So a completed full trace records the
    lattice's signature as a manifest next to the cache
    (``lattice-<digest>.manifest.json``), and a later prewarm whose
    signature matches traces ONE sentinel bucket and checks the
    ``compile_cache/hits`` counter actually moved — an end-to-end proof
    the cache serves this exact program set, not an mtime guess. The
    remaining buckets defer to first touch, each a bounded trace +
    cache-hit, never an XLA compile. A sentinel that misses (evicted or
    foreign cache behind a stale manifest) self-heals: the full trace
    runs and the manifest is rewritten. ``cache_dir`` is the live cache
    directory (:func:`enable_compile_cache`'s return); None disables the
    manifest path entirely and always traces the full lattice.

    Returns ``{"buckets": [...], "seconds": ..., "mode": "full" |
    "sentinel", "verified_hit": bool | None}`` and records the wall cost
    as the ``artifacts/prewarm_s`` histogram: a warm cache shows up as
    this distribution collapsing, not as a guess from spawn timing.
    """
    reg = registry if registry is not None else REGISTRY
    buckets = None
    if profile is not None:
        buckets = profile.get("length_buckets")
    if buckets is None:
        buckets = runner.length_buckets
    buckets = tuple(int(b) for b in buckets)

    mode = "full"
    verified_hit: bool | None = None
    manifest = None
    if cache_dir:
        sig = _lattice_signature(runner, buckets)
        manifest = _manifest_path(str(cache_dir), sig)
        try:
            with open(manifest, "r", encoding="utf-8") as f:
                if json.load(f) == sig:
                    mode = "sentinel"
        except (OSError, ValueError):
            pass

    t0 = time.perf_counter()
    if mode == "sentinel":
        before = _hits()
        runner.score([b"a" * buckets[0]])
        verified_hit = _hits() > before
        if not verified_hit:
            # The manifest promised programs the cache no longer serves
            # (eviction, a wiped dir, a foreign cache mounted at the same
            # path). Fall back to the full trace — buckets[0] is already
            # compiled by the sentinel — and re-earn the manifest below.
            mode = "full"
            for b in buckets[1:]:
                runner.score([b"a" * b])
    else:
        for b in buckets:
            runner.score([b"a" * b])
    seconds = time.perf_counter() - t0

    if mode == "full" and manifest is not None:
        # Atomic (tmp + rename): the manifest is a promise later spawns
        # skip work on — a torn one must parse as garbage, not as a
        # plausible signature.
        tmp = f"{manifest}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(sig, f, sort_keys=True)
            os.replace(tmp, manifest)
        except OSError:
            pass
    reg.observe("artifacts/prewarm_s", seconds)
    log_event(
        _log, "compile_cache.prewarmed", buckets=list(buckets),
        seconds=round(seconds, 4), mode=mode, verified_hit=verified_hit,
    )
    return {
        "buckets": list(buckets), "seconds": seconds, "mode": mode,
        "verified_hit": verified_hit,
    }
