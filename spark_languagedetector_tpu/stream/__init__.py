"""stream subpackage: micro-batch scoring (:mod:`.microbatch`) and the
continuous-learning auto-refit driver (:mod:`.refit`)."""

from .refit import AutoRefit, RefitProgress  # noqa: F401
