"""stream subpackage."""
