"""Streaming micro-batch scoring loop.

BASELINE config 4 is "Structured Streaming micro-batch langid over a Kafka
text source". The reference has no streaming code of its own — Spark
Structured Streaming would drive its Transformer per micro-batch. The
TPU-native equivalent is an explicit loop: a pluggable source yields batches
of rows, the model's runner scores them on device, a sink consumes the
annotated rows, and per-batch/lifetime metrics are tracked.

Sources are any ``Iterable[Table]``; adapters below wrap an in-memory list
(tests/bench) and a Kafka consumer (gated on ``kafka-python`` being
installed — not baked into this image, so it degrades to a clear error, the
same way Spark requires the kafka connector JAR on the classpath).

Failure handling (docs/RESILIENCE.md) supplies the durability Structured
Streaming provided for free:

  * transient transform failures replay under a :class:`RetryPolicy`
    (classified — deterministic errors are never futilely replayed, and
    ``KeyboardInterrupt``/``SystemExit`` are never swallowed);
  * with a ``checkpoint_path``, every sunk batch commits a resume token
    (atomic JSON via :mod:`..persist.checkpoint`); a restarted run
    fast-forwards the source past committed batches and re-emits nothing;
  * with a ``dlq``, a batch that fails *deterministically* is bisected to
    the poison rows — healthy rows are scored and sunk in order, the
    poison rows are quarantined with full context — instead of killing
    the query.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from itertools import islice
from typing import Callable, Iterable, Iterator, Sequence

from ..api.table import Table
from ..exec import config as exec_config
from ..exec.core import ordered_prefetch
from ..resilience import faults
from ..resilience.dlq import DeadLetterQueue
from ..resilience.policy import CircuitBreaker, RetryPolicy
from ..telemetry import REGISTRY, flightrec, new_trace_id, span, trace_request
from ..utils.logging import get_logger, log_event
from ..utils.metrics import Metrics

_log = get_logger("stream.microbatch")


# ------------------------------------------------------------- sources ------
def memory_source(rows: Sequence[dict], batch_rows: int) -> Iterator[Table]:
    """Replay an in-memory row list as micro-batches (columns = dict keys)."""
    for start in range(0, len(rows), batch_rows):
        yield Table.from_rows(rows[start : start + batch_rows])


def kafka_source(
    topic: str,
    batch_rows: int,
    input_col: str = "fulltext",
    poll_timeout_s: float = 1.0,
    **consumer_kwargs,
) -> Iterator[Table]:
    """Kafka topic → micro-batches of single-column tables.

    Requires a Kafka client library; raises a clear error when absent
    (mirrors Spark's requirement of the kafka-sql connector package).
    """
    try:
        from kafka import KafkaConsumer  # type: ignore[import-not-found]
    except ImportError as e:
        raise RuntimeError(
            "kafka_source requires the 'kafka-python' package; install it or "
            "use memory_source/your own Iterable[Table]"
        ) from e

    # The loop below runs in the default suite against a stubbed consumer
    # (tests/test_stream.py::fake_kafka); only a live broker needs the real
    # dependency.
    consumer = KafkaConsumer(topic, **consumer_kwargs)
    buf: list[str] = []
    while True:
        records = consumer.poll(timeout_ms=int(poll_timeout_s * 1000))
        for batch in records.values():
            for rec in batch:
                buf.append(
                    rec.value.decode("utf-8", errors="replace")
                    if isinstance(rec.value, bytes)
                    else str(rec.value)
                )
                if len(buf) >= batch_rows:
                    yield Table({input_col: buf})
                    buf = []
        if buf:
            yield Table({input_col: buf})
            buf = []


# --------------------------------------------------------------- engine -----
@dataclass
class StreamingQuery:
    """Progress handle for a running (or finished) micro-batch loop."""

    metrics: Metrics = field(default_factory=Metrics)
    batches: int = 0
    rows: int = 0
    last_batch_rows: int = 0
    last_batch_seconds: float = 0.0
    # Request id of the batch the three last_batch_* fields describe —
    # lets an on_progress hook tie a slow batch back to its spans in the
    # JSONL capture (bench records the slowest one per config).
    last_batch_trace_id: str | None = None
    # Resilience accounting: source batches skipped because a checkpoint
    # said they were already committed; batches routed through the
    # quarantine/bisect path; rows this run handed to the DLQ.
    resumed_from: int = 0
    quarantined_batches: int = 0
    dlq_rows: int = 0

    @property
    def rows_per_second(self) -> float:
        return self.metrics.throughput("rows", "total_s")


def _slice_table(table: Table, lo: int, hi: int) -> Table:
    return Table(
        {n: table.column(n)[lo:hi] for n in table.schema.names}, table.schema
    )


def run_stream(
    model,
    source: Iterable[Table],
    sink: Callable[[Table], None],
    *,
    max_batches: int | None = None,
    on_progress: Callable[[StreamingQuery], None] | None = None,
    prefetch: int | None = None,
    workers: int | None = None,
    retry_policy: RetryPolicy | None = None,
    breaker: CircuitBreaker | None = None,
    dlq: DeadLetterQueue | None = None,
    checkpoint_path: str | None = None,
) -> StreamingQuery:
    """Drive the micro-batch loop: for each source batch, transform on the
    accelerator and hand the annotated table to the sink.

    Scoring is stateless, so failure recovery is replay: a batch whose
    transform raises a *retryable* error (``resilience.policy.is_retryable``
    — device/tunnel runtime errors, host I/O) is re-submitted under
    ``retry_policy`` (default: the env-tuned ``RetryPolicy.from_env()``,
    replay-once with backoff) before any further handling. Deterministic
    errors (a bad column's ``ValueError``, a poison row) are never
    replayed: with a ``dlq`` they take the quarantine path below, without
    one they propagate at once. ``KeyboardInterrupt``/``SystemExit`` are
    never swallowed anywhere in the loop.

    ``dlq``: a batch that still fails after classification/retries is
    bisected — halves that transform cleanly are sunk (in source order;
    the sink may therefore see several sub-batches for one source batch),
    rows that fail alone are quarantined with their batch/row index and
    error. The query keeps running; ``query.dlq_rows`` counts the damage.

    ``checkpoint_path``: after each batch is fully sunk (or settled via
    the DLQ), a resume token ``{"committed": seq + 1}`` is atomically
    persisted. A later ``run_stream`` with the same path fast-forwards
    the (replayed-from-the-start) source past the committed batches, so a
    mid-stream kill re-emits nothing already sunk. The commit happens
    *after* the sink returns: a batch whose sink raised replays on
    resume — at-least-once for the crashing batch, exactly-once for
    everything committed.

    ``breaker``: optional health monitor — per-batch transform outcomes
    are recorded on it (the degraded-mode *gating* lives in the model
    runner's own breaker; see docs/RESILIENCE.md §6).

    ``prefetch > 0`` overlaps batch N+1's transform with batch N's result
    fetch and sink via the execution core's ordered pipeline
    (``exec.core.ordered_prefetch`` — the same machinery under the fit
    ingest); sinks always run in the caller's thread, in source order.
    ``prefetch``/``workers`` left ``None`` resolve through ``exec.config``
    (env ``LANGDETECT_STREAM_PREFETCH`` / ``LANGDETECT_STREAM_WORKERS``;
    defaults 0 and ``min(2, prefetch)``). ``workers`` is the transform
    concurrency: with one worker, transforms serialize — batch N+1's
    host->device transfer cannot start until batch N's result fetch
    returns, which on a high-latency link (tunneled TPU here) leaves the
    wire idle for the whole fetch round-trip. A second worker keeps the
    wire busy during fetches (measured ~2x stream throughput on a wire-
    bound model); batches stay independent, and the device executes queued
    programs in order, so results are unchanged. Caveat: with a *consuming*
    source (e.g. Kafka with auto-commit), an error that terminates the loop
    can discard up to ``prefetch`` batches that were already pulled from
    the source but not yet sunk — use the default ``prefetch=0`` when the
    source cannot replay.
    """
    query = StreamingQuery()
    it = iter(source)
    policy = retry_policy if retry_policy is not None else RetryPolicy.from_env()
    input_col = getattr(model, "get_input_col", lambda: None)()
    prefetch = int(exec_config.resolve("stream_prefetch", prefetch))
    if workers is None:
        workers = exec_config.resolve("stream_workers")

    # Resume: fast-forward past batches a previous run already committed.
    committed = 0
    if checkpoint_path is not None:
        from ..persist.checkpoint import load_checkpoint

        state = load_checkpoint(checkpoint_path) or {}
        committed = max(0, int(state.get("committed", 0)))
        skipped = 0
        while skipped < committed:
            try:
                next(it)
            except StopIteration:
                break
            skipped += 1
        query.resumed_from = skipped
        if skipped:
            log_event(_log, "stream.resume", committed=skipped)

    def transform_once(batch: Table, seq: int, trace_id: str) -> Table:
        # Runs on a prefetch worker thread when the pipeline is deep: the
        # explicit parent pins the span under this run's "stream" root (a
        # fresh thread has no ambient span to inherit), so concurrent
        # workers all aggregate under stream/transform. The per-batch
        # trace id (minted when the batch was pulled) is rebound here so
        # the nested runner score spans attribute to this batch's request
        # rather than the stream root.
        def attempt():
            faults.inject("stream/batch")
            return model.transform(batch)

        def on_retry(attempt_no, delay_s, exc):
            # May run on the worker thread concurrently with the caller's
            # counter writes — Metrics serializes internally.
            query.metrics.incr("retries")
            REGISTRY.incr("stream/retries")
            log_event(
                _log, "stream.retry", batch=seq, attempt=attempt_no,
                backoff_s=round(delay_s, 6), trace_id=trace_id,
                error=repr(exc),
            )

        with trace_request(trace_id), span(
            "stream/transform", parent=stream_span, batch=seq,
            rows=batch.num_rows,
        ):
            return policy.run(
                attempt,
                site="stream/batch",
                breaker=breaker,
                on_retry=on_retry,
            )

    def settle(tbl: Table, seq: int, base: int, error: BaseException) -> None:
        """Bisect a deterministically-failing batch: sink the rows that
        score cleanly (in order), quarantine the rows that fail alone.
        Probes call ``model.transform`` directly (under the retry policy
        for stray transients) — deliberately bypassing the ``stream/batch``
        chaos site, so an injected transient cannot masquerade as poison
        during isolation. Only *deterministic* failures recurse toward the
        DLQ: a retryable error that exhausts the policy mid-bisection is
        an outage, not poison — it propagates (crashing the batch; its
        commit never happens, so a resume replays it whole) instead of
        quarantining healthy rows."""
        if policy.classify(error):
            raise error
        if tbl.num_rows <= 1:
            for i, row in enumerate(tbl.to_rows()):
                dlq.put(
                    batch=seq, row_index=base + i, row=row, error=repr(error)
                )
                query.dlq_rows += 1
                query.metrics.incr("dlq_rows")
            return
        mid = tbl.num_rows // 2
        for lo, hi in ((0, mid), (mid, tbl.num_rows)):
            sub = _slice_table(tbl, lo, hi)
            try:
                out = policy.run(
                    lambda sub=sub: model.transform(sub),
                    site="stream/bisect",
                )
            except Exception as sub_error:
                settle(sub, seq, base + lo, sub_error)
            else:
                with span("sink", rows=sub.num_rows):
                    sink(out)

    def quarantine(tbl: Table, seq: int, trace_id: str,
                   error: BaseException) -> None:
        query.quarantined_batches += 1
        query.metrics.incr("quarantined_batches")
        REGISTRY.incr("resilience/quarantined_batches")
        log_event(
            _log, "stream.quarantine", batch=seq, rows=tbl.num_rows,
            error=repr(error), trace_id=trace_id,
        )
        with span("quarantine", batch=seq, rows=tbl.num_rows):
            settle(tbl, seq, 0, error)  # nests as stream/batch/quarantine

    n_workers = workers if workers is not None else min(2, max(prefetch, 1))
    seq_box = [committed]

    def pulled() -> Iterator[tuple[Table, int, str]]:
        """Source batches with the per-pull work stamped at pull time:
        chaos row corruption (deterministic per batch count), the batch's
        trace id, and its sequence number. The execution core's pipeline
        pulls from this lazily — at most ``prefetch + 1`` ahead of the
        drain — so a consuming source (Kafka auto-commit) never loses
        more than the pipeline depth on a crash."""
        while True:
            try:
                batch = next(it)
            except StopIteration:
                return
            batch, _ = faults.corrupt_batch(batch, input_col)
            tid = new_trace_id()
            s = seq_box[0]
            seq_box[0] += 1
            yield batch, s, tid

    # Budget BEFORE pulling: total pulls (drained + in flight) never
    # exceed max_batches, so an over-pulled batch is never silently lost.
    src_iter: Iterable = pulled()
    if max_batches is not None:
        src_iter = islice(src_iter, max(0, max_batches))
    pipeline = None
    try:
        with span(
            "stream", prefetch=prefetch, workers=n_workers
        ) as stream_span:
            # The shared ordered pipeline (exec.core): transforms run on
            # worker threads up to ``prefetch`` batches ahead, results
            # drain in source order; prefetch=0 keeps the synchronous
            # semantics (the thunk transforms inline, in this thread).
            pipeline = ordered_prefetch(
                src_iter,
                lambda item: transform_once(*item),
                depth=prefetch,
                workers=n_workers,
            )
            for (src, src_seq, src_tid), thunk, prefetched, pending in pipeline:
                REGISTRY.observe("stream/queue_depth", pending)
                REGISTRY.set_gauge("stream/queue_depth", pending)
                t0 = time.perf_counter()
                # The timer covers processing (transform-or-wait + sink)
                # only, never idle source polling, matching the synchronous
                # loop's throughput semantics.
                with trace_request(src_tid), query.metrics.timer(
                    "total_s"
                ), span(
                    "stream/batch", batch=src_seq, rows=src.num_rows
                ):
                    try:
                        if not prefetched:
                            out = thunk()
                        else:
                            # Sink-visible stall: how long the drain sat
                            # waiting on the prefetch worker — the signal
                            # separating "wire is behind" from "sink is
                            # behind" when stream throughput drops.
                            t_wait = time.perf_counter()
                            out = thunk()
                            REGISTRY.observe(
                                "stream/prefetch_stall_s",
                                time.perf_counter() - t_wait,
                            )
                    except Exception as e:
                        # Retryable errors already exhausted the policy
                        # inside transform_once; what reaches here is
                        # either deterministic (→ quarantine when a DLQ
                        # is wired) or a device outage the runner's
                        # degraded ladder could not absorb (→ propagate:
                        # quarantining healthy data during an outage
                        # would turn downtime into data loss).
                        if dlq is None or policy.classify(e):
                            raise
                        quarantine(src, src_seq, src_tid, e)
                    else:
                        with span("sink", rows=src.num_rows):
                            sink(out)  # nests as stream/batch/sink
                dt = time.perf_counter() - t0
                query.batches += 1
                query.rows += src.num_rows
                query.last_batch_rows = src.num_rows
                query.last_batch_seconds = dt
                query.last_batch_trace_id = src_tid
                query.metrics.incr("rows", src.num_rows)
                query.metrics.incr("batches")
                if checkpoint_path is not None:
                    # Commit AFTER the sink (or quarantine) settled the
                    # batch: the resume token only ever names batches
                    # whose effects are fully externalized.
                    from ..persist.checkpoint import save_checkpoint

                    save_checkpoint(
                        checkpoint_path,
                        {
                            "committed": src_seq + 1,
                            "rows": query.rows,
                            "dlq_rows": query.dlq_rows,
                        },
                    )
                if on_progress is not None:
                    on_progress(query)
                log_event(
                    _log,
                    "stream.batch",
                    n=query.batches,
                    rows=src.num_rows,
                    seconds=dt,
                    trace_id=src_tid,
                )
    except Exception as e:
        # Post-mortem: dump the flight-recorder ring (when armed) before
        # the loop unwinds — a consuming source may make this failure
        # unreplayable, so the recent-batch timeline is all there is.
        flightrec.record_crash("stream", e)
        raise
    finally:
        if pipeline is not None:
            # Don't wait for transforms of batches this run will never
            # sink; closing the pipeline cancels them and joins the pool.
            pipeline.close()
    return query
