"""Streaming micro-batch scoring loop.

BASELINE config 4 is "Structured Streaming micro-batch langid over a Kafka
text source". The reference has no streaming code of its own — Spark
Structured Streaming would drive its Transformer per micro-batch. The
TPU-native equivalent is an explicit loop: a pluggable source yields batches
of rows, the model's runner scores them on device, a sink consumes the
annotated rows, and per-batch/lifetime metrics are tracked.

Sources are any ``Iterable[Table]``; adapters below wrap an in-memory list
(tests/bench) and a Kafka consumer (gated on ``kafka-python`` being
installed — not baked into this image, so it degrades to a clear error, the
same way Spark requires the kafka connector JAR on the classpath).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Iterable, Iterator, Sequence

from ..api.table import Table
from ..utils.logging import get_logger, log_event
from ..utils.metrics import Metrics

_log = get_logger("stream.microbatch")


# ------------------------------------------------------------- sources ------
def memory_source(rows: Sequence[dict], batch_rows: int) -> Iterator[Table]:
    """Replay an in-memory row list as micro-batches (columns = dict keys)."""
    for start in range(0, len(rows), batch_rows):
        yield Table.from_rows(rows[start : start + batch_rows])


def kafka_source(
    topic: str,
    batch_rows: int,
    input_col: str = "fulltext",
    poll_timeout_s: float = 1.0,
    **consumer_kwargs,
) -> Iterator[Table]:
    """Kafka topic → micro-batches of single-column tables.

    Requires a Kafka client library; raises a clear error when absent
    (mirrors Spark's requirement of the kafka-sql connector package).
    """
    try:
        from kafka import KafkaConsumer  # type: ignore[import-not-found]
    except ImportError as e:  # pragma: no cover - kafka not in test image
        raise RuntimeError(
            "kafka_source requires the 'kafka-python' package; install it or "
            "use memory_source/your own Iterable[Table]"
        ) from e

    consumer = KafkaConsumer(topic, **consumer_kwargs)  # pragma: no cover
    buf: list[str] = []  # pragma: no cover
    while True:  # pragma: no cover
        records = consumer.poll(timeout_ms=int(poll_timeout_s * 1000))
        for batch in records.values():
            for rec in batch:
                buf.append(
                    rec.value.decode("utf-8", errors="replace")
                    if isinstance(rec.value, bytes)
                    else str(rec.value)
                )
                if len(buf) >= batch_rows:
                    yield Table({input_col: buf})
                    buf = []
        if buf:
            yield Table({input_col: buf})
            buf = []


# --------------------------------------------------------------- engine -----
@dataclass
class StreamingQuery:
    """Progress handle for a running (or finished) micro-batch loop."""

    metrics: Metrics = field(default_factory=Metrics)
    batches: int = 0
    rows: int = 0
    last_batch_rows: int = 0
    last_batch_seconds: float = 0.0

    @property
    def rows_per_second(self) -> float:
        return self.metrics.throughput("rows", "total_s")


def run_stream(
    model,
    source: Iterable[Table],
    sink: Callable[[Table], None],
    *,
    max_batches: int | None = None,
    on_progress: Callable[[StreamingQuery], None] | None = None,
) -> StreamingQuery:
    """Drive the micro-batch loop: for each source batch, transform on the
    accelerator and hand the annotated table to the sink.

    Scoring is stateless, so failure recovery is replay: a batch that raises
    can be re-submitted verbatim (SURVEY.md §5.3) — the engine retries once
    before propagating, covering transient device/tunnel hiccups.
    """
    query = StreamingQuery()
    it = iter(source)
    while True:
        # Check the budget BEFORE pulling: a source like Kafka consumes (and
        # may auto-commit) records on next(), so an over-pulled batch would
        # be silently lost.
        if max_batches is not None and query.batches >= max_batches:
            break
        try:
            batch = next(it)
        except StopIteration:
            break
        t0 = time.perf_counter()
        with query.metrics.timer("total_s"):
            try:
                out = model.transform(batch)
            except Exception:  # transient failure: replay once (stateless)
                log_event(_log, "stream.retry", batch=query.batches)
                query.metrics.incr("retries")
                out = model.transform(batch)
            sink(out)
        dt = time.perf_counter() - t0
        query.batches += 1
        query.rows += batch.num_rows
        query.last_batch_rows = batch.num_rows
        query.last_batch_seconds = dt
        query.metrics.incr("rows", batch.num_rows)
        query.metrics.incr("batches")
        if on_progress is not None:
            on_progress(query)
        log_event(
            _log, "stream.batch", n=query.batches, rows=batch.num_rows, seconds=dt
        )
    return query
