"""Streaming micro-batch scoring loop.

BASELINE config 4 is "Structured Streaming micro-batch langid over a Kafka
text source". The reference has no streaming code of its own — Spark
Structured Streaming would drive its Transformer per micro-batch. The
TPU-native equivalent is an explicit loop: a pluggable source yields batches
of rows, the model's runner scores them on device, a sink consumes the
annotated rows, and per-batch/lifetime metrics are tracked.

Sources are any ``Iterable[Table]``; adapters below wrap an in-memory list
(tests/bench) and a Kafka consumer (gated on ``kafka-python`` being
installed — not baked into this image, so it degrades to a clear error, the
same way Spark requires the kafka connector JAR on the classpath).
"""

from __future__ import annotations

import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Iterable, Iterator, Sequence

from ..api.table import Table
from ..telemetry import REGISTRY, flightrec, new_trace_id, span, trace_request
from ..utils.logging import get_logger, log_event
from ..utils.metrics import Metrics

_log = get_logger("stream.microbatch")


# ------------------------------------------------------------- sources ------
def memory_source(rows: Sequence[dict], batch_rows: int) -> Iterator[Table]:
    """Replay an in-memory row list as micro-batches (columns = dict keys)."""
    for start in range(0, len(rows), batch_rows):
        yield Table.from_rows(rows[start : start + batch_rows])


def kafka_source(
    topic: str,
    batch_rows: int,
    input_col: str = "fulltext",
    poll_timeout_s: float = 1.0,
    **consumer_kwargs,
) -> Iterator[Table]:
    """Kafka topic → micro-batches of single-column tables.

    Requires a Kafka client library; raises a clear error when absent
    (mirrors Spark's requirement of the kafka-sql connector package).
    """
    try:
        from kafka import KafkaConsumer  # type: ignore[import-not-found]
    except ImportError as e:
        raise RuntimeError(
            "kafka_source requires the 'kafka-python' package; install it or "
            "use memory_source/your own Iterable[Table]"
        ) from e

    # The loop below runs in the default suite against a stubbed consumer
    # (tests/test_stream.py::fake_kafka); only a live broker needs the real
    # dependency.
    consumer = KafkaConsumer(topic, **consumer_kwargs)
    buf: list[str] = []
    while True:
        records = consumer.poll(timeout_ms=int(poll_timeout_s * 1000))
        for batch in records.values():
            for rec in batch:
                buf.append(
                    rec.value.decode("utf-8", errors="replace")
                    if isinstance(rec.value, bytes)
                    else str(rec.value)
                )
                if len(buf) >= batch_rows:
                    yield Table({input_col: buf})
                    buf = []
        if buf:
            yield Table({input_col: buf})
            buf = []


# --------------------------------------------------------------- engine -----
@dataclass
class StreamingQuery:
    """Progress handle for a running (or finished) micro-batch loop."""

    metrics: Metrics = field(default_factory=Metrics)
    batches: int = 0
    rows: int = 0
    last_batch_rows: int = 0
    last_batch_seconds: float = 0.0
    # Request id of the batch the three last_batch_* fields describe —
    # lets an on_progress hook tie a slow batch back to its spans in the
    # JSONL capture (bench records the slowest one per config).
    last_batch_trace_id: str | None = None

    @property
    def rows_per_second(self) -> float:
        return self.metrics.throughput("rows", "total_s")


def run_stream(
    model,
    source: Iterable[Table],
    sink: Callable[[Table], None],
    *,
    max_batches: int | None = None,
    on_progress: Callable[[StreamingQuery], None] | None = None,
    prefetch: int = 0,
    workers: int | None = None,
) -> StreamingQuery:
    """Drive the micro-batch loop: for each source batch, transform on the
    accelerator and hand the annotated table to the sink.

    Scoring is stateless, so failure recovery is replay: a batch that raises
    can be re-submitted verbatim (SURVEY.md §5.3) — the engine retries once
    before propagating, covering transient device/tunnel hiccups.

    ``prefetch > 0`` overlaps batch N+1's transform with batch N's result
    fetch and sink; sinks always run in the caller's thread, in source
    order. ``workers`` (default ``min(2, prefetch)``) is the transform
    concurrency: with one worker, transforms serialize — batch N+1's
    host->device transfer cannot start until batch N's result fetch
    returns, which on a high-latency link (tunneled TPU here) leaves the
    wire idle for the whole fetch round-trip. A second worker keeps the
    wire busy during fetches (measured ~2x stream throughput on a wire-
    bound model); batches stay independent, and the device executes queued
    programs in order, so results are unchanged. Caveat: with a *consuming*
    source (e.g. Kafka with auto-commit), an error that terminates the loop
    can discard up to ``prefetch`` batches that were already pulled from
    the source but not yet sunk — use the default ``prefetch=0`` when the
    source cannot replay.
    """
    query = StreamingQuery()
    it = iter(source)

    def transform_once(batch: Table, seq: int, trace_id: str) -> Table:
        # Runs on a prefetch worker thread when the pipeline is deep: the
        # explicit parent pins the span under this run's "stream" root (a
        # fresh thread has no ambient span to inherit), so concurrent
        # workers all aggregate under stream/transform. The per-batch
        # trace id (minted when the batch was pulled) is rebound here so
        # the nested runner score spans attribute to this batch's request
        # rather than the stream root.
        with trace_request(trace_id), span(
            "stream/transform", parent=stream_span, batch=seq,
            rows=batch.num_rows,
        ):
            try:
                return model.transform(batch)
            except Exception:  # transient failure: replay once (stateless)
                log_event(_log, "stream.retry", batch=seq, trace_id=trace_id)
                # May run on the worker thread concurrently with the
                # caller's counter writes — Metrics serializes internally.
                query.metrics.incr("retries")
                REGISTRY.incr("stream/retries")
                return model.transform(batch)

    n_workers = workers if workers is not None else min(2, max(prefetch, 1))
    executor = (
        ThreadPoolExecutor(max_workers=n_workers) if prefetch > 0 else None
    )
    in_flight: deque = deque()  # (batch, seq, trace_id, future-or-None)
    seq = 0
    try:
        with span(
            "stream", prefetch=prefetch, workers=n_workers
        ) as stream_span:
            while True:
                # Check the budget BEFORE pulling: a source like Kafka
                # consumes (and may auto-commit) records on next(), so an
                # over-pulled batch would be silently lost.
                want_more = (
                    max_batches is None
                    or query.batches + len(in_flight) < max_batches
                )
                batch = None
                if want_more:
                    try:
                        batch = next(it)
                    except StopIteration:
                        want_more = False
                if batch is not None:
                    # Each source batch is one request: its trace id is
                    # minted at pull time and travels with the batch
                    # through the prefetch worker and the drain loop.
                    tid = new_trace_id()
                    fut = (
                        None
                        if executor is None
                        else executor.submit(transform_once, batch, seq, tid)
                    )
                    in_flight.append((batch, seq, tid, fut))
                    seq += 1
                if not in_flight:
                    break
                # Drain when the pipeline is full or the source is done. The
                # timer covers processing (transform-or-wait + sink) only,
                # never idle source polling, matching the synchronous loop's
                # throughput semantics.
                if len(in_flight) > prefetch or not want_more or batch is None:
                    REGISTRY.observe("stream/queue_depth", len(in_flight))
                    REGISTRY.set_gauge("stream/queue_depth", len(in_flight))
                    src, src_seq, src_tid, fut = in_flight.popleft()
                    t0 = time.perf_counter()
                    with trace_request(src_tid), query.metrics.timer(
                        "total_s"
                    ), span(
                        "stream/batch", batch=src_seq, rows=src.num_rows
                    ):
                        if fut is None:
                            out = transform_once(src, src_seq, src_tid)
                        else:
                            # Sink-visible stall: how long the drain sat
                            # waiting on the prefetch worker — the signal
                            # separating "wire is behind" from "sink is
                            # behind" when stream throughput drops.
                            t_wait = time.perf_counter()
                            out = fut.result()
                            REGISTRY.observe(
                                "stream/prefetch_stall_s",
                                time.perf_counter() - t_wait,
                            )
                        with span("sink", rows=src.num_rows):
                            sink(out)  # nests as stream/batch/sink
                    dt = time.perf_counter() - t0
                    query.batches += 1
                    query.rows += src.num_rows
                    query.last_batch_rows = src.num_rows
                    query.last_batch_seconds = dt
                    query.last_batch_trace_id = src_tid
                    query.metrics.incr("rows", src.num_rows)
                    query.metrics.incr("batches")
                    if on_progress is not None:
                        on_progress(query)
                    log_event(
                        _log,
                        "stream.batch",
                        n=query.batches,
                        rows=src.num_rows,
                        seconds=dt,
                        trace_id=src_tid,
                    )
    except Exception as e:
        # Post-mortem: dump the flight-recorder ring (when armed) before
        # the loop unwinds — a consuming source may make this failure
        # unreplayable, so the recent-batch timeline is all there is.
        flightrec.record_crash("stream", e)
        raise
    finally:
        if executor is not None:
            # Don't wait for transforms of batches this run will never sink.
            executor.shutdown(wait=True, cancel_futures=True)
    return query
