"""Auto-refit driver: streaming batches → count accumulator → hot-swap.

The continuous-learning loop (ROADMAP item 2): a labeled micro-batch
source feeds an incremental :class:`~..models.refit.FitAccumulator`
through the same pipelined count path the from-scratch device fit uses;
every committed batch checkpoints the accumulator (crash-atomic, resume
token inside the state); and on a trigger — every N batches and/or every
N docs — the driver re-runs ONLY the on-device finalize and pushes the
new model through :class:`~..serve.registry.ModelRegistry` hot-swap, so
the serving path picks up everything learned so far with zero downtime
and the swap provenance (refit token, docs seen) stamped on the version.

Exactness contract: every refit model is bit-identical to a from-scratch
``LanguageDetector.fit`` over the concatenation of every batch consumed
so far (gated by ``bench.py --smoke-refit``; fuzzed in
``tests/test_refit.py``). A restart with the same ``state_path`` fast-
forwards the (replayed-from-the-start) source past the ``committed``
batches already inside the table — the same replayable-source contract
``run_stream``'s checkpointing has — so a kill mid-stream neither loses
nor double-counts a batch.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable

from ..telemetry import REGISTRY
from ..utils.logging import get_logger, log_event

_log = get_logger("stream.refit")


@dataclass
class RefitProgress:
    """Progress handle for a running (or finished) auto-refit loop."""

    batches: int = 0
    rows: int = 0
    refits: int = 0
    # Source batches skipped on start because the restored accumulator's
    # resume token said their counts were already committed.
    resumed_from: int = 0
    last_version: str | None = None
    last_refit_docs: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)


class AutoRefit:
    """Drive incremental refits from a labeled micro-batch source.

    ``estimator`` supplies the fit configuration (and builds each refit
    model); ``registry`` (optional) receives every refit via hot-swap
    ``install``. ``state_path`` (optional) checkpoints the accumulator
    after every consumed batch and resumes from it on construction — the
    state must match the estimator's fit configuration exactly, or
    construction refuses (a refit under different fit params would not be
    the model the token promises).

    Triggers: ``refit_every_batches`` / ``refit_every_docs`` (either, both,
    or neither — with neither, refits happen only via :meth:`refit_now`
    and the end-of-run ``final_refit``). Synchronous use: :meth:`run`.
    Background use: :meth:`start` / :meth:`stop` — the loop runs on a
    daemon thread, checkpoints and swaps exactly as in the foreground, and
    :meth:`stop` (or a source that ends) finishes cleanly.
    """

    def __init__(
        self,
        estimator,
        registry=None,
        *,
        state_path: str | None = None,
        refit_every_batches: int | None = None,
        refit_every_docs: int | None = None,
        final_refit: bool = True,
        prewarm: bool = True,
        source_name: str = "auto-refit",
        tenant: str | None = None,
    ):
        from ..models.refit import FitAccumulator

        self.estimator = estimator
        self.registry = registry
        self.state_path = state_path
        self.refit_every_batches = refit_every_batches
        self.refit_every_docs = refit_every_docs
        self.final_refit = final_refit
        self.prewarm = prewarm
        self.source_name = source_name
        # Tenant-scoped refit (docs/SERVING.md §12): the model zoo hands
        # this driver ONE tenant's registry (via its install proxy), so a
        # refit can only ever move that tenant's serving pointer; the
        # tenant rides the swap metadata/log so /varz says WHOSE corpus a
        # version was finalized from.
        self.tenant = tenant
        self.progress = RefitProgress()
        self.last_model = None
        self._since_refit_batches = 0
        self._since_refit_docs = 0
        self._dirty = False  # updates not yet reflected in a refit model
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._error: BaseException | None = None

        if state_path is not None:
            # Finish a checkpoint swap a crash may have interrupted (the
            # state would otherwise look absent and silently restart the
            # accumulator from zero).
            from ..persist.io import recover_fit_state

            recover_fit_state(state_path)
        if state_path is not None and Path(state_path).exists():
            self.acc = FitAccumulator.load(state_path)
            if not self.acc.matches_estimator(estimator):
                raise ValueError(
                    f"persisted fit state at {state_path} was accumulated "
                    "under a different fit configuration than this "
                    "estimator's (vocab spec / languages / weightMode / "
                    "languageProfileSize)"
                )
            self._dirty = self.acc.docs_seen > 0
        else:
            self.acc = estimator.accumulator()

    # -------------------------------------------------------------- loop ----
    def process_batch(self, table) -> int:
        """Consume one source batch: accumulate, checkpoint, maybe refit.
        Returns rows added."""
        added = self.acc.update(table)
        if self.state_path is not None:
            # The checkpoint carries the resume token INSIDE the counts
            # state, so commit is one atomic step — a kill between update
            # and save simply replays this batch into a pre-update state.
            self.acc.save(self.state_path)
        with self.progress._lock:
            self.progress.batches += 1
            self.progress.rows += added
        self._since_refit_batches += 1
        self._since_refit_docs += added
        if added:
            self._dirty = True
        REGISTRY.incr("refit/batches")
        REGISTRY.incr("refit/rows", added)
        REGISTRY.set_gauge(
            "langdetect_refit_committed", float(self.acc.committed)
        )
        if (
            self.refit_every_batches is not None
            and self._since_refit_batches >= self.refit_every_batches
        ) or (
            self.refit_every_docs is not None
            and self._since_refit_docs >= self.refit_every_docs
        ):
            self.refit_now()
        return added

    def refit_now(self) -> str | None:
        """Finalize the accumulator into a model and hot-swap it in.

        Returns the installed version name (None without a registry — the
        model is still built and kept as ``last_model``). Skips (returns
        None) while any supported language still has zero coverage: a
        refit that cannot validate is deferred, not fatal — the stream
        may simply not have reached that language yet.
        """
        if self.acc.coverage_gaps():
            log_event(
                _log, "refit.deferred",
                missing=self.acc.coverage_gaps(), batches=self.acc.committed,
            )
            return None
        # No wrapper span: fit_from_accumulator records the same "fit" /
        # "fit/finalize" / "fit/collect" stage paths as a from-scratch fit
        # (attr incremental=True distinguishes them), so the compare
        # guard's stage contract covers both without path forks.
        model = self.estimator.fit_from_accumulator(self.acc)
        self.last_model = model
        self._since_refit_batches = 0
        self._since_refit_docs = 0
        self._dirty = False
        REGISTRY.incr("refit/refits")
        version = None
        if self.registry is not None:
            metadata = {
                "refit_token": self.acc.committed,
                "docs_seen": self.acc.docs_seen,
            }
            if self.tenant is not None:
                metadata["tenant"] = self.tenant
            version = self.registry.install(
                model,
                prewarm=self.prewarm,
                source=f"{self.source_name}:{self.acc.committed}",
                metadata=metadata,
            )
        with self.progress._lock:
            self.progress.refits += 1
            self.progress.last_version = version
            self.progress.last_refit_docs = self.acc.docs_seen
        log_event(
            _log, "refit.swap", version=version, docs=self.acc.docs_seen,
            token=self.acc.committed, tenant=self.tenant,
        )
        return version

    def run(
        self, source: Iterable, max_batches: int | None = None
    ) -> RefitProgress:
        """Consume ``source`` (an ``Iterable[Table]`` replayed from the
        start, like ``run_stream``'s) until it ends, ``max_batches`` NEW
        batches were consumed, or :meth:`stop` is called; then run the
        final refit (when enabled and updates are pending)."""
        it = iter(source)
        skipped = 0
        while skipped < self.acc.committed:
            try:
                next(it)
            except StopIteration:
                # The replayed source ended before reaching the resume
                # token: this is NOT the source the state was built from
                # (rotated/truncated/wrong stream). Refusing loudly is
                # the only honest option — fast-forwarding less than
                # `committed` would desynchronize token and stream and
                # double-count every remaining batch.
                raise RuntimeError(
                    f"resume token says {self.acc.committed} batches are "
                    f"already committed, but the source replayed only "
                    f"{skipped} — the source does not match the "
                    "persisted accumulator state"
                )
            skipped += 1
        with self.progress._lock:
            self.progress.resumed_from = skipped
        if skipped:
            log_event(_log, "refit.resume", committed=skipped)
        consumed = 0
        while not self._stop.is_set():
            if max_batches is not None and consumed >= max_batches:
                break
            try:
                batch = next(it)
            except StopIteration:
                break
            self.process_batch(batch)
            consumed += 1
        if self.final_refit and (self._dirty or self.last_model is None):
            self.refit_now()
        return self.progress

    # -------------------------------------------------------- background ----
    def start(
        self, source: Iterable, max_batches: int | None = None
    ) -> "AutoRefit":
        """Run :meth:`run` on a background daemon thread (the auto-refit
        daemon: fits happen off the serving path; only the registry's
        pointer flip ever touches it)."""
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("auto-refit loop already running")
        self._stop.clear()
        self._error = None

        def body():
            try:
                self.run(source, max_batches=max_batches)
            except BaseException as e:  # surfaced by wait()/stop()
                self._error = e

        self._thread = threading.Thread(
            target=body, name="auto-refit", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 30.0) -> RefitProgress:
        """Signal the background loop to finish after the current batch and
        wait for it; re-raises an error the loop died on."""
        self._stop.set()
        return self.wait(timeout)

    def wait(self, timeout: float | None = None) -> RefitProgress:
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise TimeoutError("auto-refit loop did not stop in time")
        if self._error is not None:
            raise self._error
        return self.progress
