"""Structured logging for the framework.

The reference mixes in Spark's ``Logging`` trait (e.g.
``/root/reference/src/main/.../LanguageDetector.scala:17``) but emits almost
nothing; its tests force log4j to ERROR. Here logging is a first-class,
structured subsystem (SURVEY.md §5.5): a per-module logger with a shared
framework namespace, quiet by default, and a ``log_event`` helper that attaches
machine-readable key/value fields for throughput meters and test assertions.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any

_ROOT_NAME = "sparklangdetect_tpu"

_root = logging.getLogger(_ROOT_NAME)
if not _root.handlers:
    _handler = logging.StreamHandler()
    _handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
    )
    _root.addHandler(_handler)
    # Bootstrap read: exec/config's own imports log through this root, so
    # the audited knob table cannot exist yet. config.py re-applies the
    # level through the table (sync_level_from_config) the moment it
    # finishes importing; this direct read is the one allowlisted
    # exception (analysis/allowlist.py, docs/ANALYSIS.md §4). A bad
    # value keeps the default rather than making the package
    # unimportable — same tolerance as the post-config re-sync below.
    _level = os.environ.get("LANGDETECT_TPU_LOGLEVEL", "WARNING").upper()
    try:
        _root.setLevel(_level)
    except ValueError:
        _root.setLevel(logging.WARNING)
        _root.warning("LANGDETECT_TPU_LOGLEVEL ignored: unknown level %r", _level)
    _root.propagate = False


def get_logger(module: str) -> logging.Logger:
    """Logger namespaced under the framework root, e.g. ``ops.score``."""
    return logging.getLogger(f"{_ROOT_NAME}.{module}")


def set_level(level: str) -> None:
    _root.setLevel(level.upper())


def sync_level_from_config(resolve) -> None:
    """Re-resolve the root level through exec/config's audited table.

    Called by ``exec.config`` at the end of its own module body (the
    resolver is passed in, keeping this bootstrap module free of package
    imports): the pre-config bootstrap value above is replaced by the
    table-resolved one, so the live level always matches what ``/varz``
    ``effective_config`` reports for ``loglevel``.
    """
    try:
        level = resolve("loglevel")
        if level:
            _root.setLevel(str(level).upper())
    except ValueError as e:
        _root.warning("LANGDETECT_TPU_LOGLEVEL ignored: %s", e)


def log_event(logger: logging.Logger, event: str, **fields: Any) -> None:
    """Emit a structured (JSON-payload) INFO event; cheap when disabled."""
    if logger.isEnabledFor(logging.INFO):
        payload = {"event": event, "ts": time.time(), **fields}
        logger.info(json.dumps(payload, default=str))
