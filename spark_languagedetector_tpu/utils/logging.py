"""Structured logging for the framework.

The reference mixes in Spark's ``Logging`` trait (e.g.
``/root/reference/src/main/.../LanguageDetector.scala:17``) but emits almost
nothing; its tests force log4j to ERROR. Here logging is a first-class,
structured subsystem (SURVEY.md §5.5): a per-module logger with a shared
framework namespace, quiet by default, and a ``log_event`` helper that attaches
machine-readable key/value fields for throughput meters and test assertions.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any

_ROOT_NAME = "sparklangdetect_tpu"

_root = logging.getLogger(_ROOT_NAME)
if not _root.handlers:
    _handler = logging.StreamHandler()
    _handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
    )
    _root.addHandler(_handler)
    _root.setLevel(os.environ.get("LANGDETECT_TPU_LOGLEVEL", "WARNING").upper())
    _root.propagate = False


def get_logger(module: str) -> logging.Logger:
    """Logger namespaced under the framework root, e.g. ``ops.score``."""
    return logging.getLogger(f"{_ROOT_NAME}.{module}")


def set_level(level: str) -> None:
    _root.setLevel(level.upper())


def log_event(logger: logging.Logger, event: str, **fields: Any) -> None:
    """Emit a structured (JSON-payload) INFO event; cheap when disabled."""
    if logger.isEnabledFor(logging.INFO):
        payload = {"event": event, "ts": time.time(), **fields}
        logger.info(json.dumps(payload, default=str))
