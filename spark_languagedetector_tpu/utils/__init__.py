"""utils subpackage."""
