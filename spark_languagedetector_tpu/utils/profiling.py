"""jax.profiler trace hooks (SURVEY.md §5.1).

The reference has no tracing of its own beyond Spark's UI; here the
throughput meter (``utils.metrics``) is complemented by an opt-in
``jax.profiler`` trace so a scoring or fit region can be captured for
TensorBoard/XProf without touching call sites:

    with trace("/tmp/langdetect-trace"):
        model.transform(table)

or environment-driven (no code change): set ``LANGDETECT_TRACE_DIR`` and
every ``BatchRunner.score`` call traces itself.
"""

from __future__ import annotations

import os
from contextlib import contextmanager

from .logging import get_logger, log_event

_log = get_logger("utils.profiling")

TRACE_DIR_ENV = "LANGDETECT_TRACE_DIR"


@contextmanager
def trace(log_dir: str | None = None):
    """Profile the enclosed region to ``log_dir`` (or $LANGDETECT_TRACE_DIR).

    No-op when neither is set, so production call sites can wrap hot regions
    unconditionally.
    """
    log_dir = log_dir or os.environ.get(TRACE_DIR_ENV)
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        log_event(_log, "profiling.trace_start", dir=log_dir)
        yield
    log_event(_log, "profiling.trace_done", dir=log_dir)
