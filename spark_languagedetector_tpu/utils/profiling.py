"""jax.profiler trace hooks (SURVEY.md §5.1).

The reference has no tracing of its own beyond Spark's UI; here the
throughput meter (``utils.metrics``) is complemented by an opt-in
``jax.profiler`` trace so a scoring or fit region can be captured for
TensorBoard/XProf without touching call sites:

    with trace("/tmp/langdetect-trace"):
        model.transform(table)

or environment-driven (no code change): set ``LANGDETECT_TRACE_DIR`` and
every ``BatchRunner.score`` call traces itself. Call sites that pass a
``label`` get a per-call subdirectory (``score-0000/``, ``score-0001/``,
...) under the target, so repeated captures never clobber one another's
XProf dumps. Each active capture also records a ``profile/trace``
telemetry span, so profiler runs show up in stage trees alongside the
stages they were profiling.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from contextlib import contextmanager

from .logging import get_logger, log_event

_log = get_logger("utils.profiling")

TRACE_DIR_ENV = "LANGDETECT_TRACE_DIR"

# Process-wide capture sequence: labeled captures land in distinct
# subdirectories even across threads and call sites.
_TRACE_SEQ = itertools.count()


@contextmanager
def trace(log_dir: str | None = None, label: str | None = None):
    """Profile the enclosed region to ``log_dir`` (or $LANGDETECT_TRACE_DIR).

    No-op when neither is set, so production call sites can wrap hot regions
    unconditionally. ``label`` appends a per-call ``<label>-<seq>/``
    subdirectory so repeated captures keep their dumps apart. The
    ``trace_done`` event is emitted via try/finally — an exception in the
    traced region still marks the capture finished (and the telemetry span
    still records), instead of silently swallowing the event.
    """
    if log_dir is None:
        from ..exec import config as exec_config

        log_dir = exec_config.resolve("trace_dir")
    if not log_dir:
        yield
        return
    if label:
        log_dir = os.path.join(log_dir, f"{label}-{next(_TRACE_SEQ):04d}")
    import jax

    from ..telemetry import REGISTRY, current_trace_id

    t0 = time.perf_counter()
    try:
        with jax.profiler.trace(log_dir):
            log_event(_log, "profiling.trace_start", dir=log_dir)
            yield
    finally:
        # Recorded directly (not via the span() context manager): an
        # ambient profile/trace span would become the parent of every
        # stage span in the traced region and silently re-root the whole
        # tree (profile/trace/score/...), breaking the cost-gauge join
        # and cross-capture stage matching. Direct recording yields the
        # same root-level stage entry without touching the nesting.
        attrs = {"dir": log_dir, "tid": threading.get_ident()}
        tid = current_trace_id()
        if tid is not None:
            attrs["trace_id"] = tid
        try:
            REGISTRY.record_span(
                "profile/trace", time.perf_counter() - t0, None, attrs
            )
        except Exception:
            pass  # diagnostics never mask the traced region's error
        log_event(_log, "profiling.trace_done", dir=log_dir)
