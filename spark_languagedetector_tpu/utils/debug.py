"""Numerical sanitizers (SURVEY.md §5.2).

The reference's execution model (pure functions over Spark datasets) makes
data races structurally impossible, and JAX's functional model carries the
same property — so the remaining hazard class is *numerical*: NaN/Inf
weights from corrupt inputs or buggy kernels silently win or lose argmax
comparisons. Two defenses:

* :func:`nan_checks` — a scoped switch for JAX's debug-nans mode, which
  re-runs any jitted computation that produced a NaN in op-by-op mode and
  raises at the originating op. Expensive; for tests and debugging sessions.
* :func:`assert_finite` — a cheap explicit guard used at trust boundaries
  (profile construction from persisted artifacts).
"""

from __future__ import annotations

from contextlib import contextmanager

import numpy as np


@contextmanager
def nan_checks(enabled: bool = True):
    """Scoped ``jax_debug_nans``: any NaN produced under jit raises at the
    op that made it (op-by-op re-execution). Restores the prior setting."""
    import jax

    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", enabled)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


def assert_finite(arr, what: str) -> None:
    """Raise ValueError naming the artifact if ``arr`` has NaN/Inf."""
    a = np.asarray(arr)
    if a.size and not np.isfinite(a).all():
        bad = int(a.size - np.isfinite(a).sum())
        raise ValueError(
            f"{what} contains {bad} non-finite value(s) (NaN/Inf) — "
            "refusing to build a model from corrupt weights"
        )
