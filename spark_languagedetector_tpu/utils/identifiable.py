"""Unique-identifier helper for pipeline stages.

TPU-native analog of the reference's ``Identifiable.randomUID`` usage
(``/root/reference/src/main/scala/.../LanguageDetector.scala:189``): every
estimator/transformer instance carries a ``uid`` of the form ``<prefix>_<hex>``
used in persistence metadata and error messages.
"""

from __future__ import annotations

import uuid


def random_uid(prefix: str) -> str:
    """Return a fresh uid like ``LanguageDetector_1a2b3c4d5e6f``."""
    return f"{prefix}_{uuid.uuid4().hex[:12]}"


class Identifiable:
    """Mixin giving an object an immutable ``uid``."""

    def __init__(self, uid: str | None = None, *, uid_prefix: str | None = None):
        if uid is None:
            uid = random_uid(uid_prefix or type(self).__name__)
        self._uid = uid

    @property
    def uid(self) -> str:
        return self._uid

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"{type(self).__name__}(uid={self._uid!r})"
