"""Lightweight counters/timers for observability (SURVEY.md §5.5).

The reference has no metrics at all; the BASELINE target (docs/sec/chip) makes
a throughput meter mandatory. These counters are process-local and lock-free
(CPython atomic int ops) — device-side timing uses ``block_until_ready``
explicitly at the call sites that care.
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager
from dataclasses import dataclass, field


@dataclass
class Metrics:
    counters: dict[str, int] = field(default_factory=lambda: defaultdict(int))
    timers: dict[str, float] = field(default_factory=lambda: defaultdict(float))

    def incr(self, name: str, value: int = 1) -> None:
        self.counters[name] += value

    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.timers[name] += time.perf_counter() - t0

    def throughput(self, counter: str, timer: str) -> float:
        """counter/sec over accumulated timer time; 0.0 if never timed."""
        elapsed = self.timers.get(timer, 0.0)
        return self.counters.get(counter, 0) / elapsed if elapsed > 0 else 0.0

    def snapshot(self) -> dict:
        return {"counters": dict(self.counters), "timers": dict(self.timers)}

    def reset(self) -> None:
        self.counters.clear()
        self.timers.clear()


# Framework-global registry (scorers attach their own Metrics too).
GLOBAL = Metrics()
