"""Lightweight counters/timers for observability (SURVEY.md §5.5).

The reference has no metrics at all; the BASELINE target (docs/sec/chip) makes
a throughput meter mandatory. These counters are process-local; writes take a
lock so producers on other threads (e.g. the streaming engine's prefetch
worker) can update counters concurrently with the caller's thread — the cost
is per-batch, not per-row, so it never shows in a profile. Device-side timing
uses ``block_until_ready`` explicitly at the call sites that care.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from dataclasses import dataclass, field


@dataclass
class Metrics:
    counters: dict[str, int] = field(default_factory=lambda: defaultdict(int))
    timers: dict[str, float] = field(default_factory=lambda: defaultdict(float))
    # Invocations per timer, so mean latency is derivable (total alone can't
    # distinguish "one slow call" from "many fast ones").
    timer_counts: dict[str, int] = field(
        default_factory=lambda: defaultdict(int)
    )
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    # Locks can't be pickled/deepcopied — models deepcopy themselves (and the
    # runner's Metrics with them) in Params.copy. Copies get a fresh lock;
    # counter values transfer as plain dicts.
    def __getstate__(self):
        state = self.__dict__.copy()
        del state["_lock"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()

    def incr(self, name: str, value: int = 1) -> None:
        with self._lock:
            self.counters[name] += value

    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.timers[name] += dt
                self.timer_counts[name] += 1

    def throughput(self, counter: str, timer: str) -> float:
        """counter/sec over accumulated timer time; 0.0 if never timed."""
        elapsed = self.timers.get(timer, 0.0)
        return self.counters.get(counter, 0) / elapsed if elapsed > 0 else 0.0

    def mean_seconds(self, timer: str) -> float:
        """Mean duration of one timed region; 0.0 if never timed."""
        n = self.timer_counts.get(timer, 0)
        return self.timers.get(timer, 0.0) / n if n else 0.0

    def snapshot(self) -> dict:
        # Shape-compatible superset: "counters"/"timers" keep their original
        # {name: number} form; "timer_counts" rides alongside.
        return {
            "counters": dict(self.counters),
            "timers": dict(self.timers),
            "timer_counts": dict(self.timer_counts),
        }

    def reset(self) -> None:
        self.counters.clear()
        self.timers.clear()
        self.timer_counts.clear()


# Framework-global registry (scorers attach their own Metrics too).
GLOBAL = Metrics()
