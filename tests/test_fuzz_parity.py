"""Randomized cross-strategy parity sweep.

Fixed-seed fuzz over vocab shapes x strategies: every device scoring
strategy must agree with the float64 numpy host scorer (the oracle bridge)
on scores (tolerance) and argmax (exactly) for random byte corpora that
include empty docs, sub-gram docs, NUL/0xFF bytes, and chunk-length docs.
This is the generalization of the per-strategy parity tests: one sweep per
(spec, strategy) pair the auto-selector can produce.
"""

import numpy as np
import pytest

from spark_languagedetector_tpu.api.runner import BatchRunner
from spark_languagedetector_tpu.models.profile import GramProfile
from spark_languagedetector_tpu.ops.encode_device import DocBlock
from spark_languagedetector_tpu.ops.score import score_batch_numpy
from spark_languagedetector_tpu.ops.vocab import EXACT, HASHED, VocabSpec

CASES = [
    # (spec, strategies that must handle it). "fused" appears wherever
    # the megakernel covers the form: dense tables (in-kernel ids/FNV)
    # and LUT membership — everywhere except packed-key cuckoo profiles
    # (exact gram lengths 4..5).
    (VocabSpec(EXACT, (2,)), ("gather", "onehot", "pallas", "fused")),
    (VocabSpec(EXACT, (1, 2)), ("gather", "onehot", "pallas", "fused")),
    (VocabSpec(EXACT, (1, 2, 3)), ("gather", "hybrid", "hist", "fused")),
    (VocabSpec(EXACT, (1, 3, 5)), ("gather", "hist")),
    (VocabSpec(EXACT, (4,)), ("gather", "hist")),
    (VocabSpec(EXACT, (1, 2, 3, 4, 5)), ("gather", "hybrid", "hist")),
    # Small hashed vocabs ship the DENSE table (no LUT/cuckoo), so hist
    # does not apply; fnv1a bucket ids are not exact short-gram ids, so
    # hybrid doesn't either — gather (and fused, whose FNV runs
    # in-kernel over the dense bucket table) cover this shape.
    (VocabSpec(HASHED, (1, 2, 3), hash_bits=11), ("gather", "fused")),
    (VocabSpec(HASHED, (1, 2, 3, 4, 5), hash_bits=17, hash_scheme="exact12"),
     ("gather", "hybrid", "fused")),
]


def _profile(spec, rng, n_langs=4, n_grams=250):
    """Random trained-profile-shaped GramProfile for the spec."""
    grams = set()
    lo, hi = min(spec.gram_lengths), max(spec.gram_lengths)
    while len(grams) < n_grams:
        n = int(rng.integers(lo, hi + 1))
        grams.add(bytes(rng.integers(95, 115, n).tolist()))
    gram_map = {
        g: rng.normal(size=n_langs).astype(np.float64) for g in sorted(grams)
    }
    if spec.mode == EXACT:
        return GramProfile.from_gram_map(
            gram_map, tuple(f"l{i}" for i in range(n_langs)),
            spec.gram_lengths,
        )
    # hashed: accumulate gram weights into buckets like the fit does
    ids = {}
    for g, v in gram_map.items():
        ids.setdefault(spec.gram_to_id(g), np.zeros(n_langs)).__iadd__(v)
    sorted_ids = np.asarray(sorted(ids), dtype=np.int64)
    weights = np.stack([ids[i] for i in sorted_ids])
    return GramProfile(
        spec=spec, languages=tuple(f"l{i}" for i in range(n_langs)),
        ids=sorted_ids, weights=weights,
    )


def _docs(rng):
    docs = [
        bytes(rng.integers(90, 120, int(rng.integers(0, 150))).tolist())
        for _ in range(17)
    ]
    docs += [b"", b"a", b"ab", b"abc", b"\x00\xff" * 20,
             bytes(rng.integers(0, 256, 700).tolist())]  # chunked at 256
    return docs


@pytest.mark.parametrize(
    "case_idx", range(len(CASES)), ids=[str(c[0]) for c in CASES]
)
def test_all_strategies_match_host_scorer(case_idx):
    spec, strategies = CASES[case_idx]
    rng = np.random.default_rng(1000 + case_idx)
    profile = _profile(spec, rng)
    docs = _docs(rng)
    host_w, host_ids = profile.host_arrays()
    want = score_batch_numpy(docs, host_w, host_ids, spec)
    weights, lut, cuckoo = profile.device_membership()
    for strategy in strategies:
        runner = BatchRunner(
            weights=weights, lut=lut, cuckoo=cuckoo, spec=spec,
            strategy=strategy, length_buckets=(128, 256), batch_size=8,
        )
        got = runner.score(docs)
        np.testing.assert_allclose(
            got, want, rtol=1e-4, atol=1e-3,
            err_msg=f"{spec} strategy={strategy}",
        )
        np.testing.assert_array_equal(
            runner.predict_ids(docs), np.argmax(got, axis=1),
            err_msg=f"{spec} strategy={strategy} labels",
        )


def _encode_docs(rng):
    """The device-encode hazard corpus: random docs, empties, chunked
    oversized docs, and UTF-8 continuation bytes (0x80-0xBF) straddling
    the truncation cap so the safe-truncation backscan has work to do."""
    docs = _docs(rng)
    docs += [
        b"x" * 254 + "€".encode() * 40,   # 3-byte seq split at cap 256
        b"\x80" * 300,                     # continuation-only, every cap
        b"\xc3" + b"\xa9" * 299,           # one lead byte then tail
        "é".encode() * 200,                # 2-byte seqs, cap lands mid-seq
    ]
    return docs


# Tier-1 runs a representative subset (dense-exact gather+fused, the
# widest exact gram span, hashed with in-kernel FNV); the remaining
# cases are slow-marked — jit programs compile per runner instance and
# the full sweep costs minutes the tier-1 budget doesn't have.
_ENCODE_TIER1 = {0, 5, 6}


@pytest.mark.parametrize(
    "case_idx",
    [
        pytest.param(
            i,
            marks=() if i in _ENCODE_TIER1 else (pytest.mark.slow,),
            id=str(CASES[i][0]),
        )
        for i in range(len(CASES))
    ],
)
def test_device_encode_matches_host_pack_bit_exact(case_idx):
    """Device-encode parity fuzz (PERFORMANCE.md §11): the wire path —
    raw concatenated bytes + int32 offsets, padded batch rebuilt inside
    the scoring jit — must be BIT-identical to the host-pack path, on
    both the list[bytes] tier (knob on) and the DocBlock tier, for every
    (spec, strategy) the lattice can produce that covers gather + fused.
    """
    spec, strategies = CASES[case_idx]
    rng = np.random.default_rng(2000 + case_idx)
    profile = _profile(spec, rng)
    docs = _encode_docs(rng)
    block = DocBlock.from_bytes(docs)
    weights, lut, cuckoo = profile.device_membership()
    for strategy in strategies:
        if strategy not in ("gather", "fused"):
            continue
        def runner(**kw):
            return BatchRunner(
                weights=weights, lut=lut, cuckoo=cuckoo, spec=spec,
                strategy=strategy, length_buckets=(128, 256), batch_size=8,
                **kw,
            )
        # One host-pack runner serves both references: jit programs
        # compile per runner INSTANCE, and a DocBlock input engages the
        # wire path structurally even with the knob off — so the same
        # instance covers the host oracle AND the zero-copy tier.
        base = runner()
        want = base.score(docs)
        got_knob = runner(device_encode=True).score(docs)
        np.testing.assert_array_equal(
            got_knob, want, err_msg=f"{spec} strategy={strategy} knob tier"
        )
        got_block = base.score(block)
        np.testing.assert_array_equal(
            got_block, want, err_msg=f"{spec} strategy={strategy} block tier"
        )
