"""Fleet observability plane: mergeable scrapes, stitching, SLO gates (ISSUE 16).

The acceptance contract (docs/OBSERVABILITY.md §14–15): histogram merges
keep count/sum/min/max exact and reservoirs deterministic; the
``/telemetryz`` wire form round-trips through :func:`merge_snapshots`
with counters summed exactly and gauges relabelled per replica; the
collector's aggregate is monotone across supervised restarts, terminal
(retire) scrapes, and scrape failures — which are counted, never
propagated, including under the ``fleet/scrape`` fault site; the SLO
evaluator's multi-window burn-rate alert trips and clears
deterministically under explicit clocks; cross-process captures stitch
onto the coordinator's clock with request flows joined by ``trace_id``
and non-negative nesting slack; and the trimmed ``--smoke-obs`` bench
gate holds end to end over a real 2-replica subprocess fleet.
"""

import json

import pytest

from spark_languagedetector_tpu.resilience import faults
from spark_languagedetector_tpu.resilience.faults import FaultPlan
from spark_languagedetector_tpu.telemetry import stitch
from spark_languagedetector_tpu.telemetry.aggregate import (
    SNAPSHOT_SCHEMA,
    FleetCollector,
    install_process_identity,
    merge_snapshots,
    process_identity,
)
from spark_languagedetector_tpu.telemetry.registry import Histogram, Registry
from spark_languagedetector_tpu.telemetry.slo import (
    SloEvaluator,
    default_objectives,
)


# ------------------------------------------------------ histogram merging ---
def test_histogram_merge_exact_moments():
    """Count/sum/min/max of a merge equal recording everything into one
    histogram — the exact half of the sketch is exact, full stop."""
    left, right, oracle = Histogram(), Histogram(), Histogram()
    for i in range(700):
        v = (i * 37 % 101) / 7.0
        (left if i % 2 else right).record(v)
        oracle.record(v)
    merged = Histogram().merge(left).merge(right)
    assert merged.count == oracle.count == 700
    assert merged.total == pytest.approx(oracle.total, abs=1e-9)
    assert merged.min == oracle.min
    assert merged.max == oracle.max


def test_histogram_merge_deterministic_reservoir():
    """Two merges of the same scrape states produce byte-identical
    reservoirs (and hence identical percentiles) even past capacity —
    fleet-aggregate percentiles stay diffable run to run."""
    a, b = Histogram(), Histogram()
    for i in range(900):
        a.record(float(i))
        b.record(float(i) + 0.5)
    sa, sb = a.state(), b.state()

    def build():
        return Histogram().merge(sa).merge(sb)

    one, two = build(), build()
    assert one._res == two._res
    assert len(one._res) <= 512
    for p in (50, 90, 99):
        assert one.percentile(p) == two.percentile(p)
    # Both populations survive the proportional thinning.
    assert any(v == int(v) for v in one._res)
    assert any(v != int(v) for v in one._res)


def test_histogram_state_roundtrip_and_empty_merge():
    h = Histogram()
    for v in (1.0, 2.0, 3.0):
        h.record(v)
    back = Histogram.from_state(json.loads(json.dumps(h.state())))
    assert (back.count, back.total, back.min, back.max) == (3, 6.0, 1.0, 3.0)
    assert back.percentile(50) == 2.0
    # Merging an empty state is a no-op, not a corruption.
    before = back.state()
    back.merge(Histogram().state())
    assert back.state() == before


# ------------------------------------------------- mergeable wire form ------
def _registry_with(replica, counters, hist_values, gauges=()):
    reg = Registry()
    install_process_identity(reg, replica=replica, pid=1000, platform="cpu")
    for name, n in counters.items():
        reg.incr(name, n)
    for v in hist_values:
        reg.observe("fleet/request_s", v)
    for name, val, labels in gauges:
        reg.set_gauge(name, val, **labels)
    return reg


def test_mergeable_snapshot_roundtrip_through_merge():
    r0 = _registry_with(
        "r0", {"serve/requests": 5, "serve/shed_requests": 1}, [0.1, 0.2],
        gauges=[("langdetect_serve_queue_rows", 7.0, {})],
    )
    r1 = _registry_with(
        "r1", {"serve/requests": 8}, [0.3],
        gauges=[("langdetect_serve_queue_rows", 3.0, {})],
    )
    snaps = [
        ("r0", json.loads(json.dumps(r0.mergeable_snapshot()))),
        ("r1", json.loads(json.dumps(r1.mergeable_snapshot()))),
    ]
    for _, snap in snaps:
        assert snap["schema"] == SNAPSHOT_SCHEMA
        assert set(snap["identity"]) == {"replica", "pid", "platform"}
    merged = merge_snapshots(snaps)
    assert merged["counters"]["serve/requests"] == 13
    assert merged["counters"]["serve/shed_requests"] == 1
    hist = merged["histograms"]["fleet/request_s"]
    assert hist.count == 3
    assert hist.total == pytest.approx(0.6)
    assert (hist.min, hist.max) == (0.1, 0.3)
    # Gauges are labelled per replica, never summed.
    series = merged["gauges"]["langdetect_serve_queue_rows"]
    assert series == {"replica=r0": 7.0, "replica=r1": 3.0}


def test_process_identity_fallback():
    reg = Registry()
    assert set(process_identity(reg)) == {"pid"}
    install_process_identity(reg, replica="rX", pid=42, platform="cpu")
    assert process_identity(reg) == {
        "replica": "rX", "pid": 42, "platform": "cpu",
    }


# ---------------------------------------------------------- the collector ---
def _snap(replica, pid, counters, hist_values=()):
    reg = Registry()
    install_process_identity(reg, replica=replica, pid=pid, platform="cpu")
    for name, n in counters.items():
        reg.incr(name, n)
    for v in hist_values:
        reg.observe("fleet/request_s", v)
    return reg.mergeable_snapshot()


def test_collector_monotone_across_restart_and_retire():
    """The aggregate counter never decreases: a pid change folds the dead
    generation, retire() retains the terminal scrape, and per-replica
    views ride the same bases."""
    local = Registry()
    col = FleetCollector(registry=local, local_name="router")
    assert col.scrape("r0", lambda: _snap("r0", 1, {"serve/requests": 5}))
    assert col.counter("serve/requests") == 5
    # Supervised restart: the replica's odometer resets, the aggregate
    # must not (generation folding).
    assert col.scrape("r0", lambda: _snap("r0", 2, {"serve/requests": 3}))
    assert col.counter("serve/requests") == 8
    view = col.per_replica()["r0"]
    assert view["state"] == "live" and view["generations"] == 2
    assert view["counters"]["serve/requests"] == 8
    # Terminal retention: the drained member's counters survive.
    col.retire("r0")
    assert col.counter("serve/requests") == 8
    view = col.per_replica()["r0"]
    assert view["state"] == "retired" and view["generations"] == 2
    agg = col.aggregate()
    assert agg["counters"]["serve/requests"] == 8
    assert agg["members"]["r0"]["state"] == "retired"
    # retire is idempotent; a never-scraped name is a no-op.
    col.retire("r0")
    col.retire("ghost")
    assert col.counter("serve/requests") == 8


def test_collector_aggregate_includes_local_and_merges_histograms():
    local = Registry()
    local.incr("fleet/shed_requests", 2)
    col = FleetCollector(registry=local, local_name="router")
    col.record("r0", _snap("r0", 1, {"serve/requests": 4}, [0.25, 0.75]))
    col.record("r1", _snap("r1", 2, {"serve/requests": 6}, [0.5]))
    assert col.counter("serve/requests") == 10
    assert col.counter("fleet/shed_requests") == 2
    assert col.counter("fleet/shed_requests", include_local=False) == 0
    agg = col.aggregate()
    hist = agg["histograms"]["fleet/request_s"]
    assert hist["count"] == 3 and hist["sum"] == pytest.approx(1.5)
    # The collector's own scrape odometer rides the local registry.
    assert local.counters["fleet/agg_scrapes"] == 2


def test_collector_scrape_failures_counted_never_raised():
    local = Registry()
    col = FleetCollector(registry=local, local_name="router")

    def boom():
        raise ConnectionError("mid-death member")

    assert col.scrape("r0", boom) is False
    # A wrong wire schema is a failure too — never merged as garbage.
    assert col.scrape("r0", lambda: {"schema": 99}) is False
    assert col.scrape_failures == 2
    assert local.counters["fleet/agg_scrape_failures"] == 2
    assert "r0" not in col.per_replica()


def test_fleet_scrape_fault_site_contained():
    """An injected ``fleet/scrape`` error is counted like a real scrape
    miss and contained by the collector — the elastic tick loop's call
    pattern (fetch wraps the inject) never sees the raise."""
    local = Registry()
    col = FleetCollector(registry=local, local_name="router")
    good = _snap("r0", 1, {"serve/requests": 5})

    def fetch():
        faults.inject("fleet/scrape")
        return good

    with faults.plan_scope(FaultPlan.parse("fleet/scrape:error@2")):
        assert col.scrape("r0", fetch) is True
        assert col.scrape("r0", fetch) is False  # call 2 fires
        assert col.scrape("r0", fetch) is True
    assert col.scrape_failures == 1
    assert local.counters["fleet/agg_scrape_failures"] == 1
    # The retained data is the last GOOD scrape; the aggregate survived.
    assert col.counter("serve/requests") == 5


def test_collector_freshness_gauge():
    local = Registry()
    col = FleetCollector(registry=local, local_name="router")
    assert col.freshness_s() == 0.0  # empty fleet is vacuously fresh
    col.record("r0", _snap("r0", 1, {}))
    age = col.freshness_s()
    assert 0.0 <= age < 5.0
    series = local.snapshot()["gauges"]["langdetect_fleet_scrape_age_s"]
    assert series[""] == age


# ------------------------------------------------------------- SLO gates ----
def _agg(requests, sheds, *, p99=None, age=None):
    out = {
        "counters": {
            "fleet/requests": requests,
            "fleet/shed_requests": sheds,
            "serve/shed_requests": 0,
        },
        "histograms": {}, "gauges": {},
    }
    if p99 is not None:
        # The sketch is cumulative: count tracks total completions so the
        # evaluator's new-traffic gate sees a positive delta per ingest.
        out["histograms"]["fleet/request_s"] = {"count": requests, "p99": p99}
    if age is not None:
        out["gauges"]["langdetect_fleet_scrape_age_s"] = {"": age}
    return out


def test_slo_availability_trip_and_clear_deterministic():
    """The multi-window latch under an explicit clock: trips only when
    BOTH windows burn, holds while the short window burns, clears when
    the short window drains — and ``slo/alerts`` counts the rising edge
    exactly once."""
    reg = Registry()
    ev = SloEvaluator(
        default_objectives(), registry=reg,
        short_window_s=10.0, long_window_s=30.0,
    )
    st = ev.ingest(_agg(100, 0), now=0.0)
    assert not st["burning"]
    # Shed burst: 50 sheds against 100 new requests — burn >> 1 on both
    # windows, the alert fires.
    st = ev.ingest(_agg(200, 50), now=1.0)
    assert st["burning"] and st["reasons"] == ["slo_availability_burn"]
    assert reg.counters["slo/alerts"] == 1
    avail = st["objectives"]["availability"]
    assert avail["alerting"]
    assert avail["burn_short"] >= 1.0 and avail["burn_long"] >= 1.0
    # Still inside the short window: the latch holds, no second alert.
    st = ev.ingest(_agg(300, 50), now=2.0)
    assert st["burning"]
    assert reg.counters["slo/alerts"] == 1
    # The bad sample ages out of the short window: clean traffic clears.
    st = ev.ingest(_agg(400, 50), now=20.0)
    assert not st["burning"] and not ev.burning()
    assert st["objectives"]["availability"]["burn_short"] == 0.0
    assert reg.counters["slo/alerts"] == 1
    # Worst burn rode the upward-regressing histogram every evaluation.
    assert reg.histograms["slo/burn_rate"].count == 4
    series = reg.snapshot()["gauges"]["langdetect_slo_burn_rate"]
    assert series["objective=availability"] == 0.0


def test_slo_counter_reset_clamped():
    reg = Registry()
    ev = SloEvaluator(
        default_objectives(), registry=reg,
        short_window_s=10.0, long_window_s=30.0,
    )
    ev.ingest(_agg(100, 0), now=0.0)
    # A collector reset (counters drop) must read as fresh traffic, not
    # negative deltas.
    st = ev.ingest(_agg(40, 0), now=1.0)
    assert not st["burning"]
    assert st["objectives"]["availability"]["burn_short"] == 0.0


def test_slo_latency_and_freshness_objectives():
    reg = Registry()
    ev = SloEvaluator(
        default_objectives(latency_p99_ms=250.0, freshness_s=10.0),
        registry=reg, short_window_s=10.0, long_window_s=30.0,
    )
    st = ev.ingest(_agg(10, 0, p99=0.1, age=1.0), now=0.0)
    assert not st["burning"]
    st = ev.ingest(_agg(20, 0, p99=0.9, age=99.0), now=1.0)
    assert st["burning"]
    assert set(st["reasons"]) == {
        "slo_latency_p99_burn", "slo_freshness_burn",
    }
    # Recovered p99/age past the short window: both clear.
    st = ev.ingest(_agg(30, 0, p99=0.1, age=1.0), now=20.0)
    assert not st["burning"]


def test_slo_latency_alert_clears_in_silence():
    """The merged sketch is cumulative, so its p99 never forgets a slow
    burst — the latency objective must only record verdicts over NEW
    completions, or one burst latches the alert (and the autoscaler's
    pressure input) forever."""
    reg = Registry()
    ev = SloEvaluator(
        default_objectives(latency_p99_ms=250.0), registry=reg,
        short_window_s=10.0, long_window_s=30.0,
    )
    ev.ingest(_agg(10, 0, p99=0.9), now=0.0)
    st = ev.ingest(_agg(20, 0, p99=0.9), now=1.0)
    assert st["reasons"] == ["slo_latency_p99_burn"]
    # Dead silence: the histogram count stops moving while its p99 stays
    # over threshold. No new evidence → no new samples → the burst ages
    # out of the short window and the alert clears.
    st = ev.ingest(_agg(20, 0, p99=0.9), now=20.0)
    assert not st["burning"]
    assert st["objectives"]["latency_p99"]["burn_short"] == 0.0


def test_slo_quiet_windows_do_not_alert():
    """No traffic at all (total 0 in every window) is burn 0 — an idle
    fleet never pages."""
    ev = SloEvaluator(
        default_objectives(), registry=Registry(),
        short_window_s=10.0, long_window_s=30.0,
    )
    for t in range(5):
        st = ev.ingest(_agg(0, 0), now=float(t))
    assert not st["burning"]
    assert st["objectives"]["availability"]["burn_short"] == 0.0


# ------------------------------------------------------------- stitching ----
def _span(ts, path, wall_s, trace_id=None, **ident):
    ev = {
        "event": "telemetry.span", "ts": ts, "path": path, "wall_s": wall_s,
    }
    if trace_id is not None:
        ev["trace_id"] = trace_id
    ev.update(ident)
    return ev


def _write_jsonl(path, events):
    with open(path, "w", encoding="utf-8") as fh:
        for ev in events:
            fh.write(json.dumps(ev) + "\n")
    return str(path)


def test_stitch_clock_alignment_and_flow_join(tmp_path):
    """Synthetic two-process capture: the replica clock runs 2 s behind
    the coordinator, the handshake offset realigns it, and the request
    flow joins across captures by trace_id with the expected slack."""
    router_events = [
        {
            "event": stitch.CLOCK_SYNC_EVENT, "ts": 90.0, "replica": "r0",
            "pid": 123, "platform": "cpu", "offset_s": 2.0,
        },
        # Span events stamp ts at EXIT: this dispatch ran [99.0, 100.0].
        _span(100.0, "fleet/dispatch", 1.0, trace_id="t1"),
    ]
    replica_events = [
        _span(
            98.35, "serve/dispatch", 0.8, trace_id="t1",
            replica="r0", pid=123, platform="cpu",
        ),
        _span(
            98.2, "serve/dispatch/score", 0.5, trace_id="t1",
            replica="r0", pid=123, platform="cpu",
        ),
        # An untraced span never joins a flow.
        _span(98.0, "score/pack", 0.01, replica="r0", pid=123),
    ]
    paths = [
        _write_jsonl(tmp_path / "router.jsonl", router_events),
        _write_jsonl(tmp_path / "replica-r0.jsonl", replica_events),
    ]
    caps = stitch.load_captures(paths)
    by_label = {c["label"]: c for c in caps}
    assert by_label["router"]["offset_s"] == 0.0
    assert by_label["r0"]["offset_s"] == 2.0
    assert by_label["r0"]["identity"]["pid"] == 123

    flows = stitch.trace_flows(caps)
    assert set(flows) == {"t1"}
    spans = flows["t1"]
    assert [s["path"] for s in spans] == [
        "fleet/dispatch", "serve/dispatch", "serve/dispatch/score",
    ]
    # Aligned starts: replica start 98.35+2.0-0.8 = 99.55, after the
    # router's 99.0 — the 2 s skew is gone.
    assert spans[0]["start_s"] == pytest.approx(99.0)
    assert spans[1]["start_s"] == pytest.approx(99.55)
    slack = stitch.nesting_slack_s(spans)
    assert slack == pytest.approx(0.2)
    # An incomplete chain is None, not a fake pass.
    assert stitch.nesting_slack_s(spans[:2]) is None


def test_stitch_last_handshake_wins():
    events = [
        {"event": stitch.CLOCK_SYNC_EVENT, "replica": "r0", "offset_s": 1.0},
        {"event": stitch.CLOCK_SYNC_EVENT, "replica": "r0", "offset_s": 3.5},
        {"event": stitch.CLOCK_SYNC_EVENT, "replica": "r1", "offset_s": -0.5},
        {"event": stitch.CLOCK_SYNC_EVENT, "replica": None, "offset_s": 9.0},
    ]
    assert stitch.clock_offsets(events) == {"r0": 3.5, "r1": -0.5}


def test_stitch_cli_writes_perfetto_trace(tmp_path):
    router = _write_jsonl(tmp_path / "router.jsonl", [
        {
            "event": stitch.CLOCK_SYNC_EVENT, "ts": 1.0, "replica": "r0",
            "offset_s": 0.25,
        },
        _span(10.0, "fleet/dispatch", 0.5, trace_id="t1", tid=1),
    ])
    replica = _write_jsonl(tmp_path / "replica-r0.jsonl", [
        _span(
            9.9, "serve/dispatch", 0.3, trace_id="t1",
            replica="r0", pid=7, platform="cpu", tid=2,
        ),
    ])
    out = tmp_path / "out" / "stitched.json"
    assert stitch.main([router, replica, "-o", str(out)]) == 0
    trace = json.loads(out.read_text())
    names = {
        ev["args"]["name"] for ev in trace["traceEvents"]
        if ev.get("name") == "process_name"
    }
    assert names == {"router", "r0 (pid 7)"}
    spans = [
        ev for ev in trace["traceEvents"] if ev.get("cat") == "span"
    ]
    assert {ev["name"] for ev in spans} == {
        "fleet/dispatch", "serve/dispatch",
    }
    # Distinct pids per capture; timestamps non-negative microseconds.
    assert len({ev["pid"] for ev in spans}) == 2
    assert all(ev["ts"] >= 0 for ev in spans)
    # trace_id survives into args — the Perfetto flow-query handle.
    assert all(ev["args"].get("trace_id") == "t1" for ev in spans)
    # Usage errors exit 2, never raise.
    assert stitch.main([]) == 2
    assert stitch.main(["-o"]) == 2


# ------------------------------------------------------- bench smoke gate ---
def test_bench_smoke_obs_trimmed(tmp_path):
    """Tier-1-sized observability smoke over a real 2-replica subprocess
    fleet: aggregate exactness (incl. the drained member), a stitched
    cross-process flow with non-negative slack, burn-rate trip AND
    clear, zero scrape failures — hard-gated exactly like the CI gate."""
    import bench

    result = bench.smoke_obs(str(tmp_path / "obs.jsonl"), trimmed=True)
    assert result["ok"], result
    assert result["dropped_responses"] == 0
    assert result["argmax_parity"] == 1.0
    assert result["aggregate_exact"] and result["retained_members"]
    assert result["agg_scrape_failures"] == 0
    assert result["slo_alerts"] >= 1 and result["burn_cleared"]
    assert "slo_availability_burn" in result["burn_reasons"]
    assert not result["final_burning"]
    assert result["cross_process_flows"] >= 1
    assert result["nesting_slack_s"] is not None
    assert result["nesting_slack_s"] >= 0.0
    assert result["server_timing_sample"] is not None
    assert set(result["server_timing_sample"]) >= {
        "queue_wait_ms", "dispatch_ms", "rows_coalesced",
    }
    assert result["server_identity_sample"]["replica"]


@pytest.mark.slow
def test_bench_smoke_obs_full(tmp_path):
    import bench

    result = bench.smoke_obs(str(tmp_path / "obs_full.jsonl"))
    assert result["ok"], result
    assert result["scale_downs"] >= 1
