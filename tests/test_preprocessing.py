"""Preprocessor tests — coverage the reference never had (SURVEY.md §4:
'Not tested at all: both preprocessors')."""

from spark_languagedetector_tpu import (
    LowerCasePreprocessor,
    SpecialCharPreprocessor,
    Table,
)


def test_lowercase_basic():
    t = Table({"lang": ["en"], "fulltext": ["Hello WORLD"]})
    out = LowerCasePreprocessor().transform(t)
    assert out.column("fulltext").tolist() == ["hello world"]


def test_lowercase_uses_label_locale_turkish():
    """Java Locale('tr') semantics: dotted/dotless i."""
    t = Table({"lang": ["tr", "en"], "fulltext": ["IŞIK İstanbul", "III"]})
    out = LowerCasePreprocessor().transform(t)
    assert out.column("fulltext").tolist() == ["ışık istanbul", "iii"]


def test_lowercase_set_input_col_sets_output_col_quirk():
    """Q8: setInputCol actually sets outputCol (LowerCasePreprocessor.scala:32)."""
    p = LowerCasePreprocessor().set_input_col("body")
    assert p.get_output_col() == "body"
    t = Table({"lang": ["en"], "body": ["ABC"]})
    assert p.transform(t).column("body").tolist() == ["abc"]


def test_lowercase_schema_moves_column_last():
    """In-place column replace re-appends the column last
    (LowerCasePreprocessor.scala:38-42)."""
    t = Table({"fulltext": ["A"], "lang": ["en"], "id": [1]})
    out = LowerCasePreprocessor().transform(t)
    assert out.schema.names == ["lang", "id", "fulltext"]


def test_specialchar_strips_intended_symbols():
    """Q3 fixed: the symbol set the reference's invalid regex intended."""
    t = Table({"fulltext": ['a/b_c[d]e*f(g)h%i^j&k@l$m#n:o|p{q}r<s>t~u`v"w\\x']})
    out = SpecialCharPreprocessor().transform(t)
    assert out.column("fulltext").tolist() == ["abcdefghijklmnopqrstuvwx"]


def test_specialchar_squashes_whitespace():
    """Q4 fixed: whitespace runs squash to one space (not deleted)."""
    t = Table({"fulltext": ["hello   world  again"]})
    out = SpecialCharPreprocessor().transform(t)
    assert out.column("fulltext").tolist() == ["hello world again"]


def test_preprocessing_pipeline_chains():
    t = Table({"lang": ["de"], "fulltext": ["Das  ist  (sehr)  SCHÖN!"]})
    out = LowerCasePreprocessor().transform(SpecialCharPreprocessor().transform(t))
    assert out.column("fulltext").tolist() == ["das ist sehr schön!"]
