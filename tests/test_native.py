"""Native C++ packer: builds in this image and matches the Python paths."""

import numpy as np
import pytest

from spark_languagedetector_tpu import native
from spark_languagedetector_tpu.ops.encoding import pad_batch


@pytest.fixture(scope="module", autouse=True)
def require_native():
    assert native.available(), "native library must build in this image (g++ baked in)"


def test_pack_batch_matches_python():
    rng = np.random.default_rng(0)
    docs = [bytes(rng.integers(0, 256, rng.integers(0, 50), dtype=np.uint8)) for _ in range(37)]
    docs.append(b"")
    got_b, got_l = native.pack_batch(docs, pad_to=64)
    want_b, want_l = pad_batch(docs, pad_to=64)
    np.testing.assert_array_equal(got_b, want_b)
    np.testing.assert_array_equal(got_l, want_l)


def test_pack_batch_truncates_to_pad():
    got_b, got_l = native.pack_batch([b"x" * 100], pad_to=10)
    assert got_l.tolist() == [10]
    assert got_b.shape == (1, 10)
    assert bytes(got_b[0]) == b"x" * 10


def test_clean_bytes_matches_preprocessor():
    from spark_languagedetector_tpu import SpecialCharPreprocessor, Table

    raw = 'a/b_c [d]  e\t\tf(g) "h"\\'
    native_out = native.clean_bytes(raw.encode()).decode()
    table_out = (
        SpecialCharPreprocessor()
        .transform(Table({"fulltext": [raw]}))
        .column("fulltext")[0]
    )
    assert native_out == table_out


def test_clean_bytes_edge_cases():
    assert native.clean_bytes(b"") == b""
    assert native.clean_bytes(b"   ") == b" "
    assert native.clean_bytes(b"abc") == b"abc"
    # multi-byte UTF-8 passes through (all stripped chars < 0x80)
    s = "schön grüß".encode("utf-8")
    assert native.clean_bytes(s) == s


def test_ascii_lower():
    assert native.ascii_lower(b"Hello WORLD 123") == b"hello world 123"
    s = "ÄÖÜ".encode("utf-8")
    assert native.ascii_lower(s) == s  # non-ASCII untouched


def test_refscorer_matches_oracle():
    """The compiled baseline scorer (bench.py's vs_cpp denominator) is the
    same computation as the reference-semantics oracle: identical map,
    identical window rules (incl. the partial-window-per-length rule),
    identical double accumulation order, first-max-wins argmax."""
    from .oracle import detect_oracle

    rng = np.random.default_rng(7)
    langs = ["aa", "bb", "cc"]
    alphabet = b"abcd"
    gram_lengths = [1, 2, 3]
    gram_map = {}
    for _ in range(60):
        n = int(rng.integers(1, 4))
        g = bytes(rng.choice(list(alphabet), n))
        gram_map[g] = [float(x) for x in rng.normal(size=len(langs))]
    keys = list(gram_map)
    rs = native.RefScorer(keys, np.asarray([gram_map[k] for k in keys]))
    try:
        docs = [
            bytes(rng.choice(list(alphabet), int(rng.integers(0, 30))))
            for _ in range(200)
        ]
        docs += [b"", b"a", b"ab"]  # partial-window and empty edges
        got = rs.score(docs, gram_lengths)
        for d, label in zip(docs, got.tolist()):
            want = detect_oracle(
                d.decode("latin-1"), gram_map, langs, gram_lengths,
                encoding="lowbyte",
            )
            assert langs[label] == want, d
    finally:
        rs.close()


def test_refscorer_multithreaded_matches_single():
    rng = np.random.default_rng(8)
    keys = [b"ab", b"bc", b"c", b"abc"]
    vecs = rng.normal(size=(4, 5))
    rs = native.RefScorer(keys, vecs)
    try:
        docs = [
            bytes(rng.choice(list(b"abc"), int(rng.integers(0, 40))))
            for _ in range(300)
        ]
        np.testing.assert_array_equal(
            rs.score(docs, [1, 2, 3]), rs.score(docs, [1, 2, 3], n_threads=4)
        )
    finally:
        rs.close()
