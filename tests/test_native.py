"""Native C++ packer: builds in this image and matches the Python paths."""

import numpy as np
import pytest

from spark_languagedetector_tpu import native
from spark_languagedetector_tpu.ops.encoding import pad_batch


@pytest.fixture(scope="module", autouse=True)
def require_native():
    assert native.available(), "native library must build in this image (g++ baked in)"


def test_pack_batch_matches_python():
    rng = np.random.default_rng(0)
    docs = [bytes(rng.integers(0, 256, rng.integers(0, 50), dtype=np.uint8)) for _ in range(37)]
    docs.append(b"")
    got_b, got_l = native.pack_batch(docs, pad_to=64)
    want_b, want_l = pad_batch(docs, pad_to=64)
    np.testing.assert_array_equal(got_b, want_b)
    np.testing.assert_array_equal(got_l, want_l)


def test_pack_batch_truncates_to_pad():
    got_b, got_l = native.pack_batch([b"x" * 100], pad_to=10)
    assert got_l.tolist() == [10]
    assert got_b.shape == (1, 10)
    assert bytes(got_b[0]) == b"x" * 10


def test_clean_bytes_matches_preprocessor():
    from spark_languagedetector_tpu import SpecialCharPreprocessor, Table

    raw = 'a/b_c [d]  e\t\tf(g) "h"\\'
    native_out = native.clean_bytes(raw.encode()).decode()
    table_out = (
        SpecialCharPreprocessor()
        .transform(Table({"fulltext": [raw]}))
        .column("fulltext")[0]
    )
    assert native_out == table_out


def test_clean_bytes_edge_cases():
    assert native.clean_bytes(b"") == b""
    assert native.clean_bytes(b"   ") == b" "
    assert native.clean_bytes(b"abc") == b"abc"
    # multi-byte UTF-8 passes through (all stripped chars < 0x80)
    s = "schön grüß".encode("utf-8")
    assert native.clean_bytes(s) == s


def test_ascii_lower():
    assert native.ascii_lower(b"Hello WORLD 123") == b"hello world 123"
    s = "ÄÖÜ".encode("utf-8")
    assert native.ascii_lower(s) == s  # non-ASCII untouched


def test_refscorer_matches_oracle():
    """The compiled baseline scorer (bench.py's vs_cpp denominator) is the
    same computation as the reference-semantics oracle: identical map,
    identical window rules (incl. the partial-window-per-length rule),
    identical double accumulation order, first-max-wins argmax."""
    from .oracle import detect_oracle

    rng = np.random.default_rng(7)
    langs = ["aa", "bb", "cc"]
    alphabet = b"abcd"
    gram_lengths = [1, 2, 3]
    gram_map = {}
    for _ in range(60):
        n = int(rng.integers(1, 4))
        g = bytes(rng.choice(list(alphabet), n))
        gram_map[g] = [float(x) for x in rng.normal(size=len(langs))]
    keys = list(gram_map)
    rs = native.RefScorer(keys, np.asarray([gram_map[k] for k in keys]))
    try:
        docs = [
            bytes(rng.choice(list(alphabet), int(rng.integers(0, 30))))
            for _ in range(200)
        ]
        docs += [b"", b"a", b"ab"]  # partial-window and empty edges
        got = rs.score(docs, gram_lengths)
        for d, label in zip(docs, got.tolist()):
            want = detect_oracle(
                d.decode("latin-1"), gram_map, langs, gram_lengths,
                encoding="lowbyte",
            )
            assert langs[label] == want, d
    finally:
        rs.close()


def test_refscorer_multithreaded_matches_single():
    rng = np.random.default_rng(8)
    keys = [b"ab", b"bc", b"c", b"abc"]
    vecs = rng.normal(size=(4, 5))
    rs = native.RefScorer(keys, vecs)
    try:
        docs = [
            bytes(rng.choice(list(b"abc"), int(rng.integers(0, 40))))
            for _ in range(300)
        ]
        np.testing.assert_array_equal(
            rs.score(docs, [1, 2, 3]), rs.score(docs, [1, 2, 3], n_threads=4)
        )
    finally:
        rs.close()


def test_refscorer_score_after_close_raises():
    rs = native.RefScorer([b"ab"], np.ones((1, 2)))
    rs.close()
    with pytest.raises(RuntimeError, match="closed"):
        rs.score([b"abab"], [2])
    rs.close()  # idempotent


def test_bench_cpp_key_vecs_hashed_reconstruction():
    """bench._cpp_key_vecs reconstructs a string-keyed map for hashed
    profiles from the training corpus: every harvested gram's bucket id is
    in the profile, its vector is that bucket's row, and every training
    gram whose bucket survived selection is present (no silent drops)."""
    import bench
    from spark_languagedetector_tpu import LanguageDetector, Table

    cfg = dict(
        n_langs=3, gram_lengths=[1, 2, 3], k=50, vocab="hashed",
        train_per_lang=4, label="t",
    )
    langs = bench.language_names(cfg["n_langs"])
    docs, labels = bench.make_corpus(
        langs, cfg["train_per_lang"] * len(langs), seed=1
    )
    det = LanguageDetector(langs, cfg["gram_lengths"], cfg["k"]) \
        .set_vocab_mode("hashed").set_hash_bits(20)
    model = det.fit(Table({"lang": labels, "fulltext": docs}))
    keys, vecs = bench._cpp_key_vecs(model, cfg)
    assert len(keys) == len(set(keys)) == vecs.shape[0] > 0

    prof = model.profile
    spec = prof.spec
    row_of = {int(i): r for r, i in enumerate(prof.ids)}
    for k_, v in zip(keys, vecs):
        r = row_of[spec.gram_to_id(k_)]  # KeyError = harvested a non-member
        np.testing.assert_array_equal(v, prof.weights[r])

    # Completeness: every member gram of the training corpus is harvested.
    want = set()
    for d in docs:
        b = d.encode("utf-8")
        for n in spec.gram_lengths:
            for i in range(max(len(b) - n + 1, 0)):
                g = b[i : i + n]
                if spec.gram_to_id(g) in row_of:
                    want.add(g)
    assert want == set(keys)
