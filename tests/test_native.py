"""Native C++ packer: builds in this image and matches the Python paths."""

import numpy as np
import pytest

from spark_languagedetector_tpu import native
from spark_languagedetector_tpu.ops.encoding import pad_batch


@pytest.fixture(scope="module", autouse=True)
def require_native():
    assert native.available(), "native library must build in this image (g++ baked in)"


def test_pack_batch_matches_python():
    rng = np.random.default_rng(0)
    docs = [bytes(rng.integers(0, 256, rng.integers(0, 50), dtype=np.uint8)) for _ in range(37)]
    docs.append(b"")
    got_b, got_l = native.pack_batch(docs, pad_to=64)
    want_b, want_l = pad_batch(docs, pad_to=64)
    np.testing.assert_array_equal(got_b, want_b)
    np.testing.assert_array_equal(got_l, want_l)


def test_pack_batch_truncates_to_pad():
    got_b, got_l = native.pack_batch([b"x" * 100], pad_to=10)
    assert got_l.tolist() == [10]
    assert got_b.shape == (1, 10)
    assert bytes(got_b[0]) == b"x" * 10


def test_clean_bytes_matches_preprocessor():
    from spark_languagedetector_tpu import SpecialCharPreprocessor, Table

    raw = 'a/b_c [d]  e\t\tf(g) "h"\\'
    native_out = native.clean_bytes(raw.encode()).decode()
    table_out = (
        SpecialCharPreprocessor()
        .transform(Table({"fulltext": [raw]}))
        .column("fulltext")[0]
    )
    assert native_out == table_out


def test_clean_bytes_edge_cases():
    assert native.clean_bytes(b"") == b""
    assert native.clean_bytes(b"   ") == b" "
    assert native.clean_bytes(b"abc") == b"abc"
    # multi-byte UTF-8 passes through (all stripped chars < 0x80)
    s = "schön grüß".encode("utf-8")
    assert native.clean_bytes(s) == s


def test_ascii_lower():
    assert native.ascii_lower(b"Hello WORLD 123") == b"hello world 123"
    s = "ÄÖÜ".encode("utf-8")
    assert native.ascii_lower(s) == s  # non-ASCII untouched
