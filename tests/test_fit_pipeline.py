"""Pipelined device-fit ingest: planning, knobs, ragged transfer,
oversized-doc chunk-splitting, and bit-identical parity with the host fit
across the single-device, split, and mesh paths — including chaos replay
with batches in flight (ISSUE 4)."""

import numpy as np
import pytest

from spark_languagedetector_tpu import LanguageDetector, Table
from spark_languagedetector_tpu.ops import fit_pipeline as fp
from spark_languagedetector_tpu.ops.encoding import DEFAULT_LENGTH_BUCKETS
from spark_languagedetector_tpu.ops.fit import COUNTS, PARITY, fit_profile_numpy
from spark_languagedetector_tpu.ops.fit_tpu import (
    fit_profile_device,
    fit_profile_device_split,
)
from spark_languagedetector_tpu.ops.vocab import EXACT, HASHED, VocabSpec
from spark_languagedetector_tpu.resilience import faults
from spark_languagedetector_tpu.resilience.faults import FaultPlan, InjectedFault
from spark_languagedetector_tpu.telemetry import REGISTRY

MAX_BUCKET = DEFAULT_LENGTH_BUCKETS[-1]


def _corpus(rng, n_docs, n_langs, max_len=120):
    docs, langs = [], []
    for i in range(n_docs):
        ln = int(rng.integers(0, max_len))
        docs.append(bytes(rng.integers(97, 105, ln, dtype=np.uint8)))
        langs.append(i % n_langs)
    return docs, np.asarray(langs)


# ------------------------------------------------------------ planning -----
def test_plan_adaptive_rows_respect_byte_budget():
    rng = np.random.default_rng(7)
    docs = [
        bytes(rng.integers(97, 120, int(rng.integers(1, 4000)), dtype=np.uint8))
        for _ in range(300)
    ]
    langs = np.arange(300) % 4
    spec = VocabSpec(HASHED, (1, 2), hash_bits=10)
    budget = 1 << 18  # 256KB: forces halving on the wide buckets
    items, item_langs, plan, straddle, _ = fp.plan_fit_batches(
        docs, langs, spec, byte_budget=budget
    )
    assert straddle is None  # nothing oversized
    covered = np.concatenate([sel for sel, _ in plan])
    assert sorted(covered.tolist()) == list(range(len(items)))
    for sel, pad_to in plan:
        assert pad_to in DEFAULT_LENGTH_BUCKETS
        assert max(len(items[i]) for i in sel) <= pad_to
        # Budget honored unless already at the row floor.
        assert len(sel) * pad_to <= budget or len(sel) <= fp.MIN_FIT_ROWS
        assert len(sel) == fp.rows_for_fit_bucket(pad_to, budget) or (
            sel is plan[-1][0]  # the single ragged tail batch
        )


def test_plan_fixed_rows_slices_sorted_order():
    rng = np.random.default_rng(9)
    docs, langs = _corpus(rng, 41, 3)
    spec = VocabSpec(EXACT, (1, 2))
    items, item_langs, plan, _, _ = fp.plan_fit_batches(
        docs, langs, spec, batch_rows=16
    )
    assert [len(sel) for sel, _ in plan] == [16, 16, 9]
    # Length-sorted walk: per-batch max length is non-decreasing.
    maxes = [max(len(items[i]) for i in sel) for sel, _ in plan]
    assert maxes == sorted(maxes)


def test_resolve_fit_batching_env_overrides(monkeypatch):
    monkeypatch.delenv(fp.ROWS_ENV, raising=False)
    monkeypatch.delenv(fp.BYTES_ENV, raising=False)
    assert fp.resolve_fit_batching(None) == (None, fp.DEFAULT_FIT_BATCH_BYTES)
    assert fp.resolve_fit_batching(128) == (128, fp.DEFAULT_FIT_BATCH_BYTES)
    monkeypatch.setenv(fp.ROWS_ENV, "32")
    monkeypatch.setenv(fp.BYTES_ENV, str(1 << 20))
    assert fp.resolve_fit_batching(None) == (32, 1 << 20)
    # Explicit batch_rows beats the env row override.
    assert fp.resolve_fit_batching(8) == (8, 1 << 20)
    monkeypatch.setenv(fp.ROWS_ENV, "zero")
    with pytest.raises(ValueError):
        fp.resolve_fit_batching(None)
    monkeypatch.setenv(fp.ROWS_ENV, "-4")
    with pytest.raises(ValueError):
        fp.resolve_fit_batching(None)


def test_split_bounds_tail_never_shorter_than_gram():
    for doc_len in (
        MAX_BUCKET + 1,
        MAX_BUCKET + 4,
        2 * MAX_BUCKET,
        2 * MAX_BUCKET + 1,
        3 * MAX_BUCKET + 2,
        20000,
    ):
        for min_tail in (2, 3, 5):
            bounds = fp.split_bounds(doc_len, MAX_BUCKET, min_tail)
            assert bounds, doc_len
            edges = [0] + bounds + [doc_len]
            sizes = [b - a for a, b in zip(edges, edges[1:])]
            assert all(min_tail <= s <= MAX_BUCKET for s in sizes), (
                doc_len, min_tail, sizes,
            )
    assert fp.split_bounds(MAX_BUCKET, MAX_BUCKET, 5) == []


def test_plan_pins_compiled_shapes_for_oversized_docs():
    """The recompile fix (ISSUE 4 satellite): oversized docs used to force a
    per-distinct-width padded shape (-(-longest // 2048) * 2048); after
    chunk-splitting every planned pad_to is a member of the bucket set, so
    the compiled-shape lattice is closed."""
    rng = np.random.default_rng(3)
    docs, langs = _corpus(rng, 20, 3)
    for extra in (9001, 12345, 20000, MAX_BUCKET + 1):
        docs.append(bytes(rng.integers(97, 105, extra, dtype=np.uint8)))
        langs = np.concatenate([langs, [0]])
    spec = VocabSpec(HASHED, (1, 2, 3), hash_bits=12)
    items, _, plan, straddle, _ = fp.plan_fit_batches(docs, langs, spec)
    assert all(pad_to in DEFAULT_LENGTH_BUCKETS for _, pad_to in plan)
    assert max(len(it) for it in items) <= MAX_BUCKET
    assert straddle is not None and straddle[2].sum() > 0


# ------------------------------------------------- parity (single device) --
@pytest.mark.parametrize("weight_mode", [PARITY, COUNTS])
def test_oversized_docs_fit_parity(weight_mode):
    """Chunk-split + straddle-window injection is exactly count-preserving:
    the device fit of a corpus with documents far beyond the largest length
    bucket stays bit-identical to the host fit."""
    rng = np.random.default_rng(11)
    docs, langs = _corpus(rng, 12, 3)
    for ln, lang in ((9001, 0), (MAX_BUCKET + 1, 1), (20000, 2)):
        docs.append(bytes(rng.integers(97, 105, ln, dtype=np.uint8)))
        langs = np.concatenate([langs, [lang]])
    for spec in (
        VocabSpec(EXACT, (1, 2)),
        VocabSpec(HASHED, (1, 2, 3), hash_bits=12),
    ):
        want_ids, want_w = fit_profile_numpy(
            docs, langs, 3, spec, 40, weight_mode
        )
        got_ids, got_w = fit_profile_device(
            docs, langs, 3, spec, 40, weight_mode
        )
        np.testing.assert_array_equal(got_ids, want_ids)
        np.testing.assert_allclose(got_w, want_w, rtol=1e-6, atol=1e-7)


def test_split_fit_oversized_doc_parity():
    """Exact n=1..5 split fit with an oversized doc: the device half
    chunk-splits (straddles counted for gram lengths <= 3), the host half
    counts the original uncut documents — still bit-identical overall."""
    rng = np.random.default_rng(13)
    docs, langs = _corpus(rng, 20, 3, max_len=60)
    docs += [b"", b"x", b"xy", b"wxyz"]
    langs = np.concatenate([langs, [0, 1, 2, 0]])
    docs.append(bytes(rng.integers(97, 103, 9000, dtype=np.uint8)))
    langs = np.concatenate([langs, [1]])
    spec = VocabSpec(EXACT, (1, 2, 3, 4, 5))
    want_ids, want_w = fit_profile_numpy(docs, langs, 3, spec, 30, PARITY)
    got_ids, got_w = fit_profile_device_split(docs, langs, 3, spec, 30, PARITY)
    np.testing.assert_array_equal(got_ids, want_ids)
    np.testing.assert_allclose(got_w, want_w, rtol=1e-6, atol=1e-7)


def test_ragged_transfer_taken_and_parity():
    """A sparse-fill batch (many short docs carried into a wide bucket) must
    ride the ragged wire form — and stay bit-identical to the host fit."""
    rng = np.random.default_rng(17)
    docs = [
        bytes(rng.integers(97, 105, int(rng.integers(20, 90)), dtype=np.uint8))
        for _ in range(255)
    ]
    docs.append(bytes(rng.integers(97, 105, 600, dtype=np.uint8)))
    langs = np.arange(256) % 3
    spec = VocabSpec(EXACT, (1, 2))
    before = REGISTRY.snapshot()["counters"].get("fit/ragged_batches", 0)
    want_ids, want_w = fit_profile_numpy(docs, langs, 3, spec, 30, PARITY)
    got_ids, got_w = fit_profile_device(docs, langs, 3, spec, 30, PARITY)
    after = REGISTRY.snapshot()["counters"].get("fit/ragged_batches", 0)
    assert after > before, "expected at least one ragged fit batch"
    np.testing.assert_array_equal(got_ids, want_ids)
    np.testing.assert_allclose(got_w, want_w, rtol=1e-6, atol=1e-7)


def test_device_encode_fit_taken_and_parity(monkeypatch):
    """The wire rung (PERFORMANCE.md §11): with LANGDETECT_DEVICE_ENCODE
    on, fit ingest ships raw bytes + int32 offsets and rebuilds the
    padded plane inside the jit — and the fitted profile stays
    bit-identical to the host-pack fit, chunk-split oversized docs
    included."""
    rng = np.random.default_rng(23)
    docs = [
        bytes(rng.integers(97, 105, int(rng.integers(20, 90)), dtype=np.uint8))
        for _ in range(255)
    ]
    docs.append(bytes(rng.integers(97, 105, 600, dtype=np.uint8)))
    langs = np.arange(256) % 3
    spec = VocabSpec(EXACT, (1, 2))
    want_ids, want_w = fit_profile_device(docs, langs, 3, spec, 30, PARITY)
    monkeypatch.setenv("LANGDETECT_DEVICE_ENCODE", "1")
    before = REGISTRY.snapshot()["counters"].get("fit/encoded_batches", 0)
    got_ids, got_w = fit_profile_device(docs, langs, 3, spec, 30, PARITY)
    after = REGISTRY.snapshot()["counters"].get("fit/encoded_batches", 0)
    assert after > before, "expected at least one wire-form fit batch"
    np.testing.assert_array_equal(got_ids, want_ids)
    np.testing.assert_array_equal(got_w, want_w)


def test_fit_telemetry_spans_and_histograms():
    """Telemetry parity with the scoring path: fit/pack + fit/put spans and
    batch fill / padding-waste histograms are recorded by the device fit."""
    rng = np.random.default_rng(19)
    docs, langs = _corpus(rng, 60, 3)
    spec = VocabSpec(HASHED, (1, 2), hash_bits=10)
    snap = REGISTRY.snapshot()["histograms"]
    before = {
        k: snap.get(k, {}).get("count", 0)
        for k in ("span:fit/pack", "span:fit/put", "fit/batch_fill_ratio",
                  "fit/padding_waste")
    }
    wire_before = REGISTRY.snapshot()["counters"].get("fit/wire_bytes", 0)
    fit_profile_device(docs, langs, 3, spec, 25, PARITY, batch_rows=16)
    snap = REGISTRY.snapshot()
    for k, b in before.items():
        assert snap["histograms"].get(k, {}).get("count", 0) > b, k
    assert snap["counters"].get("fit/wire_bytes", 0) > wire_before
    # Fill ratio is a fraction of the capacity that rode the wire.
    fill = snap["histograms"]["fit/batch_fill_ratio"]
    assert 0.0 < fill["max"] <= 1.0


def test_estimator_fit_batch_rows_param_and_env(monkeypatch):
    rows = {
        "lang": ["de"] * 3 + ["en"] * 3,
        "fulltext": [
            "der schnelle braune fuchs",
            "das ist ja sehr schön",
            "noch ein deutscher satz",
            "the quick brown fox",
            "that is very nice",
            "one more english sentence",
        ],
    }
    det = lambda: LanguageDetector(["de", "en"], [1, 2], 100)  # noqa: E731
    cpu = det().fit(Table(rows))
    by_param = (
        det().set_fit_backend("device").set_fit_batch_rows(2).fit(Table(rows))
    )
    np.testing.assert_array_equal(by_param.profile.ids, cpu.profile.ids)
    np.testing.assert_allclose(
        by_param.profile.weights, cpu.profile.weights, rtol=1e-6, atol=1e-7
    )
    monkeypatch.setenv(fp.ROWS_ENV, "3")
    by_env = det().set_fit_backend("device").fit(Table(rows))
    np.testing.assert_array_equal(by_env.profile.ids, cpu.profile.ids)
    np.testing.assert_allclose(
        by_env.profile.weights, cpu.profile.weights, rtol=1e-6, atol=1e-7
    )


# -------------------------------------------------------------- mesh -------
def test_mesh_fit_pipeline_parity(eight_devices):
    """The pipelined ingest feeds the sharded mesh fit step (row padding
    folded into the packer thread) and the fitted profile stays
    bit-identical to the host fit — row count deliberately not divisible by
    the data axis."""
    from spark_languagedetector_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.build_mesh(data=8, vocab=1)
    rng = np.random.default_rng(23)
    docs, langs = _corpus(rng, 37, 4)
    docs += [b"", b"x"]
    langs = np.concatenate([langs, [0, 1]])
    spec = VocabSpec(HASHED, (1, 2, 3), hash_bits=11)
    want_ids, want_w = fit_profile_numpy(docs, langs, 4, spec, 30, PARITY)
    got_ids, got_w = fit_profile_device(
        docs, langs, 4, spec, 30, PARITY, batch_rows=12, mesh=mesh
    )
    np.testing.assert_array_equal(got_ids, want_ids)
    np.testing.assert_allclose(got_w, want_w, rtol=1e-6, atol=1e-7)


# -------------------------------------------------------------- chaos ------
def test_chaos_count_fault_with_batches_in_flight():
    """An injected count-step fault with the pipeline running (several
    batches packed/in flight) must propagate cleanly — no stuck packer
    thread — and an immediate replay from fresh accumulators must be exact,
    the property the estimator-level retry policy relies on."""
    rng = np.random.default_rng(29)
    docs, langs = _corpus(rng, 40, 3)
    spec = VocabSpec(EXACT, (1, 2))
    want_ids, want_w = fit_profile_device(
        docs, langs, 3, spec, 25, PARITY, batch_rows=8
    )
    with faults.plan_scope(FaultPlan.parse("fit/count:error@2")):
        with pytest.raises(InjectedFault):
            fit_profile_device(docs, langs, 3, spec, 25, PARITY, batch_rows=8)
        # Plan exhausted at call 2; the in-scope replay runs clean.
        got_ids, got_w = fit_profile_device(
            docs, langs, 3, spec, 25, PARITY, batch_rows=8
        )
    np.testing.assert_array_equal(got_ids, want_ids)
    np.testing.assert_allclose(got_w, want_w, rtol=0, atol=0)


def test_estimator_fit_replays_pipeline_fault():
    """End to end: the env-tuned retry policy replays a chaos-injected
    pipelined device fit and the fitted model equals the fault-free one."""
    rows = {
        "lang": ["a", "x"] * 6,
        "fulltext": ["abab cdcd", "xyxy zwzw"] * 6,
    }
    det = lambda: (  # noqa: E731
        LanguageDetector(["a", "x"], [1, 2], 50)
        .set_fit_backend("device")
        .set_fit_batch_rows(3)
    )
    want = det().fit(Table(rows)).profile
    with faults.plan_scope(FaultPlan.parse("fit/count:error@2")):
        got = det().fit(Table(rows)).profile
    np.testing.assert_array_equal(got.ids, want.ids)
    np.testing.assert_allclose(got.weights, want.weights, rtol=1e-12)
