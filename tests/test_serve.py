"""Online serving subsystem: coalescing parity, shedding, hot-swap, HTTP.

The acceptance contract (ISSUE 5): serving-path scores are bit-identical
to direct ``BatchRunner.score`` for the same documents — across threads,
across a hot-swap boundary, and end-to-end over the HTTP front end —
every request answered exactly once by exactly one model version, and
shed requests rejected explicitly, never hung.
"""

import threading
import time

import numpy as np
import pytest

from spark_languagedetector_tpu import LanguageDetectorModel
from spark_languagedetector_tpu.api.runner import BatchRunner
from spark_languagedetector_tpu.models.profile import GramProfile
from spark_languagedetector_tpu.ops.encoding import texts_to_bytes
from spark_languagedetector_tpu.resilience import faults
from spark_languagedetector_tpu.resilience.faults import FaultPlan
from spark_languagedetector_tpu.resilience.policy import CircuitBreaker
from spark_languagedetector_tpu.serve import (
    BULK,
    ContinuousBatcher,
    ModelRegistry,
    ServeClosed,
    ServeDeadlineExceeded,
    ServeOverloaded,
)
from spark_languagedetector_tpu.telemetry import REGISTRY

LANGS = ("x", "y")
GRAM_MAP = {
    b"ab": [1.0, 0.0],
    b"bc": [0.5, 0.5],
    b"zz": [0.0, 2.0],
    b"abc": [3.0, 0.0],
}


def _runner(**kw):
    profile = GramProfile.from_gram_map(GRAM_MAP, LANGS, (2, 3))
    weights, lut = profile.device_arrays()
    kw.setdefault("batch_size", 4)
    kw.setdefault("length_buckets", (16, 64))
    return BatchRunner(weights=weights, lut=lut, spec=profile.spec, **kw)


def _docs(rng, n, max_len=90):
    return [
        bytes(rng.integers(97, 123, rng.integers(0, max_len)).tolist())
        for _ in range(n)
    ]


class SpyRunner:
    """Delegating runner that records each coalesced dispatch's docs."""

    def __init__(self, runner, sleep_s: float = 0.0):
        self.runner = runner
        self.sleep_s = sleep_s
        self.calls: list[list[bytes]] = []

    @property
    def breaker(self):
        return self.runner.breaker

    def score(self, docs):
        self.calls.append(list(docs))
        if self.sleep_s:
            time.sleep(self.sleep_s)
        return self.runner.score(docs)

    def predict_ids(self, docs):
        self.calls.append(list(docs))
        return self.runner.predict_ids(docs)


# --------------------------------------------------------------- batcher ----
def test_batcher_bit_identical_to_direct_score():
    """Mixed-bucket docs (empty, short, chunked-long) through the batcher
    equal direct runner.score exactly, for scores and labels."""
    runner = _runner()
    rng = np.random.default_rng(7)
    docs = _docs(rng, 9) + [b"", b"ab" * 80]  # chunked at 160 > 64
    with ContinuousBatcher(runner, max_wait_ms=2, max_rows=64) as b:
        got = b.submit(docs).result(timeout=30)
        np.testing.assert_array_equal(got.values, runner.score(docs))
        assert got.version == "v0"
        ids = b.submit(docs, want_labels=True).result(timeout=30)
        np.testing.assert_array_equal(ids.values, runner.predict_ids(docs))


def test_batcher_concurrent_callers_bit_identical_and_coalesced():
    """N concurrent submitters: every response equals its direct score
    bit-for-bit, and the dispatcher demonstrably coalesces (fewer
    dispatches than requests)."""
    runner = _runner(batch_size=64)
    rng = np.random.default_rng(11)
    doc_sets = [_docs(rng, 4) for _ in range(12)]
    want = [runner.score(ds) for ds in doc_sets]
    spy = SpyRunner(runner)
    REGISTRY.reset()
    with ContinuousBatcher(spy, max_wait_ms=40, max_rows=256) as b:
        barrier = threading.Barrier(len(doc_sets))
        got: list = [None] * len(doc_sets)

        def work(i):
            barrier.wait(timeout=10)
            got[i] = b.submit(doc_sets[i]).result(timeout=30)

        threads = [
            threading.Thread(target=work, args=(i,))
            for i in range(len(doc_sets))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    for i in range(len(doc_sets)):
        np.testing.assert_array_equal(got[i].values, want[i])
    assert len(spy.calls) < len(doc_sets)  # coalesced
    snap = REGISTRY.snapshot()
    h = snap["histograms"]["serve/rows_per_dispatch"]
    assert h["mean"] > 4  # more than one request's rows per dispatch
    assert snap["counters"]["serve/coalesced_rows"] == 4 * len(doc_sets)
    # The three latency legs are present for the telemetry capture.
    for name in ("serve/queue_wait_s", "serve/dispatch_s", "serve/total_s"):
        assert snap["histograms"][name]["count"] > 0


def test_batcher_priority_lane_order():
    """Interactive requests ride ahead of earlier-admitted bulk requests
    in the coalesced dispatch."""
    runner = _runner()
    spy = SpyRunner(runner)
    bulk_docs = [b"zzzz", b"zz"]
    inter_docs = [b"abab"]
    with ContinuousBatcher(spy, max_wait_ms=300, max_rows=999) as b:
        f_bulk = b.submit(bulk_docs, priority=BULK)
        f_inter = b.submit(inter_docs)
        f_bulk.result(timeout=30)
        f_inter.result(timeout=30)
    assert spy.calls[0] == inter_docs + bulk_docs


def test_batcher_flushes_on_max_rows_without_waiting():
    """A full queue flushes immediately — well before max_wait."""
    runner = _runner()
    with ContinuousBatcher(runner, max_wait_ms=10_000, max_rows=4) as b:
        t0 = time.monotonic()
        out = b.submit([b"ab", b"bc", b"zz", b"abc"]).result(timeout=30)
        assert time.monotonic() - t0 < 5.0
        assert out.values.shape == (4, 2)


def test_batcher_deadline_rejected_explicitly():
    """A request whose deadline passes while queued gets
    ServeDeadlineExceeded — not a hang, not a stale response."""
    runner = _runner()
    spy = SpyRunner(runner, sleep_s=0.3)
    with ContinuousBatcher(spy, max_wait_ms=1, max_rows=8) as b:
        blocker = b.submit([b"ab"] * 4)  # occupies the dispatcher 0.3s
        for _ in range(200):  # wait until the dispatcher is actually busy
            if spy.calls:
                break
            time.sleep(0.005)
        doomed = b.submit([b"zz"], deadline_ms=1.0)
        blocker.result(timeout=30)
        with pytest.raises(ServeDeadlineExceeded):
            doomed.result(timeout=30)


def test_batcher_shed_queue_full():
    """Reject-newest: admissions past the queue bound shed with an
    explicit ServeOverloaded; queued work is answered."""
    runner = _runner()
    spy = SpyRunner(runner, sleep_s=0.2)
    REGISTRY.reset()
    with ContinuousBatcher(spy, max_wait_ms=1, max_rows=4,
                           max_queue_rows=8) as b:
        first = b.submit([b"ab"] * 4)  # heads into dispatch (sleeping)
        for _ in range(200):  # wait until the dispatcher picked it up
            if spy.calls:
                break
            time.sleep(0.005)
        queued = b.submit([b"bc"] * 8)  # fills the queue bound
        with pytest.raises(ServeOverloaded) as exc:
            b.submit([b"zz"])
        assert exc.value.reason == "queue_full"
        assert exc.value.retry_after_s > 0
        first.result(timeout=30)
        queued.result(timeout=30)
    assert REGISTRY.snapshot()["counters"]["serve/shed_queue_full"] == 1
    assert REGISTRY.snapshot()["counters"]["serve/shed_rows"] == 1


def test_batcher_shed_slo_estimated_wait():
    """With a throughput estimate established, an admission whose
    estimated wait exceeds the SLO sheds."""
    runner = _runner()
    with ContinuousBatcher(runner, max_wait_ms=500, max_rows=999,
                           slo_ms=100) as b:
        b._ema_rows_per_s = 10.0  # 10 rows/s measured
        b.submit([b"ab"] * 8)  # 8 rows queued => est wait 0.8s > 0.1s
        with pytest.raises(ServeOverloaded) as exc:
            b.submit([b"zz"])
        assert exc.value.reason == "slo"


def test_batcher_breaker_open_sheds_bulk_serves_interactive():
    """Breaker-open flows into shed decisions: bulk requests shed, while
    interactive requests are still served exactly (degraded ladder)."""
    runner = _runner()
    runner.breaker = CircuitBreaker(failure_threshold=1, cooldown_s=60.0,
                                    name="test")
    direct = _runner()
    docs = [b"abab", b"zz"]
    want = direct.score(docs)
    runner.breaker.record_failure()
    assert runner.breaker.state == "open"
    with ContinuousBatcher(runner, max_wait_ms=2, max_rows=8) as b:
        with pytest.raises(ServeOverloaded) as exc:
            b.submit(docs, priority=BULK)
        assert exc.value.reason == "degraded"
        got = b.submit(docs).result(timeout=30)  # interactive passes
        np.testing.assert_array_equal(got.values, want)


def test_chaos_serve_admit_site_forces_shed():
    """An injected serve/admit fault is converted into the shed path —
    deterministic rejection, next admission unaffected."""
    runner = _runner()
    REGISTRY.reset()
    with ContinuousBatcher(runner, max_wait_ms=2, max_rows=8) as b:
        with faults.plan_scope(FaultPlan.parse("seed=3;serve/admit:error@1")):
            with pytest.raises(ServeOverloaded) as exc:
                b.submit([b"ab"])
            assert exc.value.reason == "injected"
            out = b.submit([b"ab"]).result(timeout=30)
            np.testing.assert_array_equal(out.values, runner.score([b"ab"]))
    counters = REGISTRY.snapshot()["counters"]
    assert counters["serve/shed_injected"] == 1
    assert counters["resilience/faults_injected"] == 1


def test_batcher_close_drains_then_rejects():
    """close() answers everything already admitted, then new submissions
    fail fast with ServeClosed."""
    runner = _runner()
    spy = SpyRunner(runner, sleep_s=0.1)
    b = ContinuousBatcher(spy, max_wait_ms=50, max_rows=4)
    futures = [b.submit([b"ab", b"bc"]) for _ in range(3)]
    b.close()
    for f in futures:
        assert f.result(timeout=30).values.shape == (2, 2)
    with pytest.raises(ServeClosed):
        b.submit([b"zz"])


def test_batcher_dispatch_error_propagates_to_all_requests():
    """A dispatch that exhausts the runner's recovery fails every request
    in the batch with the error — explicit failure, not a hang."""

    class ExplodingRunner:
        breaker = None

        def score(self, docs):
            raise ValueError("deterministic scorer bug")

    with ContinuousBatcher(ExplodingRunner(), max_wait_ms=20,
                           max_rows=8) as b:
        f1 = b.submit([b"ab"])
        f2 = b.submit([b"bc"])
        with pytest.raises(ValueError, match="deterministic scorer bug"):
            f1.result(timeout=30)
        with pytest.raises(ValueError, match="deterministic scorer bug"):
            f2.result(timeout=30)


def test_batcher_survives_cancelled_future():
    """A caller cancelling its pending future must not kill the
    dispatcher: the cancelled request is dropped, coalesced neighbors
    and later requests are answered normally."""
    runner = _runner()
    spy = SpyRunner(runner, sleep_s=0.2)
    with ContinuousBatcher(spy, max_wait_ms=30, max_rows=16) as b:
        blocker = b.submit([b"ab"] * 2)  # occupies the dispatcher
        for _ in range(200):
            if spy.calls:
                break
            time.sleep(0.005)
        doomed = b.submit([b"zz"])
        neighbor = b.submit([b"bc"])
        assert doomed.cancel()  # still queued: cancel succeeds
        blocker.result(timeout=30)
        np.testing.assert_array_equal(
            neighbor.result(timeout=30).values, runner.score([b"bc"])
        )
        # Dispatcher thread alive: a fresh request still completes.
        after = b.submit([b"abc"]).result(timeout=30)
        np.testing.assert_array_equal(after.values, runner.score([b"abc"]))


def test_batcher_empty_request_answers_immediately():
    """A zero-document request resolves with the runner's own empty
    shape instead of hanging the never-woken dispatcher."""
    runner = _runner()
    with ContinuousBatcher(runner, max_wait_ms=5, max_rows=8) as b:
        res = b.submit([]).result(timeout=10)
        np.testing.assert_array_equal(res.values, runner.score([]))
        assert res.values.shape == (0, 2)
        ids = b.submit([], want_labels=True).result(timeout=10)
        assert ids.values.shape == (0,)
        # And a normal request afterwards still works.
        out = b.submit([b"ab"]).result(timeout=30)
        np.testing.assert_array_equal(out.values, runner.score([b"ab"]))


def test_registry_explicit_version_never_collides_with_auto():
    """An explicitly named 'vN' must not break later auto-named installs."""
    registry = ModelRegistry(drain_timeout_s=0.5)
    registry.install(_model(seed=11), version="v2")
    assert registry.install(_model(seed=12)) == "v1"
    assert registry.install(_model(seed=13)) == "v3"  # skips taken v2
    assert registry.current_version() == "v3"


def test_batcher_matmul_strategy_labels_exact_scores_close():
    """Bit-identity is pinned on the geometry-stable gather strategy
    (tests above). Matmul strategies (onehot on CPU, MXU kernels on TPU)
    may flip the last f32 bit when a doc rides a different coalesce
    geometry — XLA's gemm reduction order varies with batch shape
    (ARCHITECTURE.md's reduction-order class). The serving contract
    there: argmax labels exact, scores within reduction-order tolerance
    (the batcher itself adds no numeric step either way)."""
    from spark_languagedetector_tpu import LanguageDetector, Table

    langs = ["aa", "bb"]
    model = LanguageDetector(langs, [1, 2], 100).fit(Table({
        "lang": ["aa", "aa", "bb", "bb"],
        "fulltext": ["alpha aard apple", "ant arm area",
                     "bubble bob bay", "bin bone bulk"],
    }))
    runner = model._get_runner()
    assert runner.strategy == "onehot"
    texts = ["alpha arm", "bubble bin", "area bay zz"]
    docs = texts_to_bytes(texts)
    direct = runner.score(docs)
    direct_ids = runner.predict_ids(docs)
    with ContinuousBatcher(runner, max_wait_ms=40, max_rows=256) as b:
        barrier = threading.Barrier(6)
        got: list = [None] * 6
        got_ids: list = [None] * 6

        def work(i):
            barrier.wait(timeout=10)
            got[i] = b.submit(docs).result(timeout=30)
            got_ids[i] = b.submit(docs, want_labels=True).result(timeout=30)

        threads = [
            threading.Thread(target=work, args=(i,)) for i in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    for res, ids in zip(got, got_ids):
        np.testing.assert_allclose(res.values, direct, rtol=1e-4, atol=1e-5)
        np.testing.assert_array_equal(ids.values, direct_ids)


# -------------------------------------------------------------- registry ----
def _model(seed=0, k=200):
    rng = np.random.default_rng(seed)
    gram_map = {
        g: rng.normal(size=2).tolist() for g in GRAM_MAP
    }
    return LanguageDetectorModel.from_gram_map(gram_map, (2, 3), LANGS)


def test_registry_swap_exactly_one_version_zero_drops():
    """Concurrent requests across a hot-swap: every request answered
    exactly once, bit-identical to the direct scores of exactly one of
    the two versions; no drops, no errors."""
    model_a, model_b = _model(seed=1), _model(seed=2)
    runner_a, runner_b = model_a._get_runner(), model_b._get_runner()
    registry = ModelRegistry(drain_timeout_s=5.0)
    v_a = registry.install(model_a)
    rng = np.random.default_rng(23)
    doc_sets = [_docs(rng, 3, max_len=40) for _ in range(40)]
    results: list = [None] * len(doc_sets)
    swapped = threading.Event()

    with ContinuousBatcher(registry, max_wait_ms=2, max_rows=16) as b:
        def work(i):
            if i == 20:
                swapped.set()
            results[i] = b.submit(doc_sets[i]).result(timeout=30)

        threads = [
            threading.Thread(target=work, args=(i,))
            for i in range(len(doc_sets))
        ]
        for t in threads[:20]:
            t.start()
        v_b = registry.install(model_b)
        for t in threads[20:]:
            t.start()
        for t in threads:
            t.join()

    served = set()
    for i, res in enumerate(results):
        assert res is not None, f"request {i} dropped"
        served.add(res.version)
        runner = runner_a if res.version == v_a else runner_b
        np.testing.assert_array_equal(res.values, runner.score(doc_sets[i]))
    assert v_b in served  # the swap actually took traffic
    versions = registry.versions()
    assert [v["version"] for v in versions] == [v_a, v_b]
    assert versions[1]["active"] and not versions[0]["active"]
    assert versions[0]["retired"]


def test_registry_rollback_and_history():
    model_a, model_b = _model(seed=3), _model(seed=4)
    registry = ModelRegistry(drain_timeout_s=1.0)
    v_a = registry.install(model_a)
    v_b = registry.install(model_b)
    assert registry.current_version() == v_b
    assert registry.rollback() == v_a
    assert registry.current_version() == v_a
    with pytest.raises(Exception, match="no previous version"):
        registry.rollback()
    docs = [b"abab", b"zz"]
    with ContinuousBatcher(registry, max_wait_ms=2) as b:
        got = b.submit(docs).result(timeout=30)
    assert got.version == v_a
    np.testing.assert_array_equal(
        got.values, model_a._get_runner().score(docs)
    )


def test_registry_load_from_disk_and_duplicate_version(tmp_path):
    """load() goes through persist.load_model; duplicate names refuse."""
    model = _model(seed=5)
    model.save(str(tmp_path / "m"))
    registry = ModelRegistry()
    v1 = registry.load(str(tmp_path / "m"))
    assert registry.peek().source == str(tmp_path / "m")
    assert registry.peek().languages == LANGS
    with pytest.raises(Exception, match="already registered"):
        registry.load(str(tmp_path / "m"), version=v1)
    docs = [b"abab"]
    with ContinuousBatcher(registry, max_wait_ms=2) as b:
        got = b.submit(docs).result(timeout=30)
    np.testing.assert_array_equal(
        got.values, model._get_runner().score(docs)
    )


def test_registry_rollback_racing_concurrent_install():
    """Rollback racing a concurrent install: every in-flight request is
    answered by exactly one version (lease pinning), and the history
    walk stays consistent — exactly one active entry, every version name
    unique, the active pointer always inside the history."""
    model_a, model_b, model_c = _model(seed=31), _model(seed=32), _model(33)
    runners = {}
    registry = ModelRegistry(drain_timeout_s=0.2)
    runners["v1"] = model_a._get_runner()
    registry.install(model_a)  # v1
    runners["v2"] = model_b._get_runner()
    registry.install(model_b)  # v2
    docs = [b"abab", b"zz"]
    want = {v: r.score(docs) for v, r in runners.items()}

    stop = threading.Event()
    failures: list[str] = []

    def traffic():
        while not stop.is_set():
            with registry.lease() as entry:
                got = entry.runner.score(docs)
                if entry.version in want and not np.array_equal(
                    got, want[entry.version]
                ):
                    failures.append(
                        f"version {entry.version} answered foreign scores"
                    )

    def installer():
        runners["v3"] = model_c._get_runner()
        registry.install(model_c)

    def roller():
        try:
            registry.rollback()
        except Exception:
            pass  # racing a flip may leave nothing to roll back to

    workers = [threading.Thread(target=traffic) for _ in range(3)]
    for t in workers:
        t.start()
    racers = [threading.Thread(target=installer),
              threading.Thread(target=roller)]
    for t in racers:
        t.start()
    for t in racers:
        t.join(timeout=30)
    stop.set()
    for t in workers:
        t.join(timeout=30)

    want["v3"] = runners["v3"].score(docs)
    assert not failures, failures[:3]
    versions = registry.versions()
    names = [v["version"] for v in versions]
    assert len(names) == len(set(names))  # history never duplicates
    assert sum(v["active"] for v in versions) == 1  # exactly one active
    active = next(v for v in versions if v["active"])
    # The active version always answers its own scores after the dust
    # settles (whatever interleaving the race produced).
    with registry.lease() as entry:
        assert entry.version == active["version"]
        np.testing.assert_array_equal(
            entry.runner.score(docs), want[entry.version]
        )


def test_registry_lease_pins_version_during_swap():
    """A lease taken before a swap keeps serving its version; the next
    lease sees the new one."""
    model_a, model_b = _model(seed=6), _model(seed=7)
    registry = ModelRegistry(drain_timeout_s=0.2)
    v_a = registry.install(model_a)
    with registry.lease() as entry:
        v_b = registry.install(model_b)  # drain times out, swap proceeds
        assert entry.version == v_a
    with registry.lease() as entry:
        assert entry.version == v_b


# ------------------------------------------------------------------ http ----
@pytest.fixture()
def serving():
    from spark_languagedetector_tpu.serve.client import ServeClient
    from spark_languagedetector_tpu.serve.server import ServingServer

    model = _model(seed=8)
    registry = ModelRegistry()
    registry.install(model)
    server = ServingServer(
        registry, port=0, max_wait_ms=2, max_rows=64
    ).start()
    client = ServeClient(*server.address)
    try:
        yield model, registry, server, client
    finally:
        server.stop()


def test_http_score_bit_identical_and_detect(serving):
    model, registry, server, client = serving
    runner = model._get_runner()
    texts = ["abab", "zz", "", "abczz"]
    scores, meta = client.score(texts)
    np.testing.assert_array_equal(scores, runner.score(texts_to_bytes(texts)))
    assert meta["version"] == "v1"
    assert meta["trace_id"]
    labels, _ = client.detect(texts)
    want_ids = runner.predict_ids(texts_to_bytes(texts))
    assert labels == [LANGS[i] for i in want_ids]


def test_http_healthz_varz(serving):
    model, registry, server, client = serving
    client.score(["abab"])
    health = client.healthz()
    assert health["ok"] and health["version"] == "v1"
    assert health["breaker"] == "closed"
    assert "queued_rows" in health["batcher"]
    varz = client.varz()
    assert "serve/dispatch" in varz["stages"]
    assert any(k.startswith("serve/") for k in varz["histograms"])
    assert varz["versions"][0]["version"] == "v1"


def test_http_shed_is_503_with_retry_after(serving):
    from spark_languagedetector_tpu.serve.client import ServeHTTPError

    model, registry, server, client = serving
    server.batcher.max_queue_rows = 1
    # Occupy the dispatcher so the queue check actually sees a backlog.
    with faults.plan_scope(FaultPlan.parse("seed=1;serve/admit:error@1")):
        with pytest.raises(ServeHTTPError) as exc:
            client.score(["abab"])
    assert exc.value.status == 503
    assert exc.value.shed
    assert exc.value.retry_after_s > 0
    server.batcher.max_queue_rows = 4096


def test_http_bad_requests_are_400(serving):
    from spark_languagedetector_tpu.serve.client import ServeHTTPError

    model, registry, server, client = serving
    with pytest.raises(ServeHTTPError) as exc:
        client._request("POST", "/score", {"texts": "not-a-list"})
    assert exc.value.status == 400
    with pytest.raises(ServeHTTPError) as exc:
        client._request("POST", "/score", {"texts": ["a"], "priority": "vip"})
    assert exc.value.status == 400
    with pytest.raises(ServeHTTPError) as exc:
        client._request("POST", "/nope", {})
    assert exc.value.status == 404


def test_http_swap_and_rollback(serving, tmp_path):
    model, registry, server, client = serving
    model_b = _model(seed=9)
    model_b.save(str(tmp_path / "m2"))
    runner_b = model_b._get_runner()
    v2 = client.swap(str(tmp_path / "m2"))
    assert v2 == "v2"
    texts = ["abab", "zz"]
    scores, meta = client.score(texts)
    assert meta["version"] == v2
    np.testing.assert_array_equal(
        scores, runner_b.score(texts_to_bytes(texts))
    )
    assert client.rollback() == "v1"
    _, meta = client.score(texts)
    assert meta["version"] == "v1"


def test_http_low_byte_encoding_respected(tmp_path):
    """The server encodes texts with the active model's predictEncoding."""
    from spark_languagedetector_tpu.ops.encoding import LOW_BYTE
    from spark_languagedetector_tpu.serve.client import ServeClient
    from spark_languagedetector_tpu.serve.server import ServingServer

    model = _model(seed=10)
    model.set_predict_encoding(LOW_BYTE)
    runner = model._get_runner()
    with ServingServer(model, port=0, max_wait_ms=2) as server:
        client = ServeClient(*server.address)
        texts = ["abézz", "abab"]
        scores, _ = client.score(texts)
    np.testing.assert_array_equal(
        scores, runner.score(texts_to_bytes(texts, LOW_BYTE))
    )


# ------------------------------------------------------- compare guard ------
def _snapshot_events(total_p99, shed=0):
    hist = {
        "count": 50, "sum": 1.0, "min": 0.001, "max": total_p99,
        "mean": 0.01, "p50": 0.01, "p90": 0.012, "p99": total_p99,
    }
    return [
        {"event": "telemetry.span", "ts": 1.0, "path": "serve/dispatch",
         "wall_s": 0.01},
        {"event": "telemetry.snapshot", "ts": 2.0,
         "histograms": {"serve/total_s": hist},
         "counters": {"serve/shed_requests": shed,
                      "serve/coalesced_rows": 1000}},
    ]


def test_compare_flags_serve_latency_and_shed_regressions():
    """telemetry.compare: a serve/total_s p99 regression past threshold
    fails, and a shed counter appearing over a zero baseline fails —
    while the throughput counter serve/coalesced_rows never regresses."""
    from spark_languagedetector_tpu.telemetry.compare import (
        capture_stats,
        compare_captures,
    )

    base = capture_stats(_snapshot_events(0.012))
    assert "serve/coalesced_rows" not in base["counters"]
    good = capture_stats(_snapshot_events(0.013))
    _, regressions = compare_captures(base, good, threshold=0.25)
    assert regressions == []
    slow = capture_stats(_snapshot_events(0.050))
    _, regressions = compare_captures(base, slow, threshold=0.25)
    assert any("serve/total_s p99" in r for r in regressions)
    shedding = capture_stats(_snapshot_events(0.012, shed=5))
    _, regressions = compare_captures(base, shedding, threshold=0.25)
    assert any("serve/shed_requests" in r for r in regressions)
