"""Telemetry emit sites: the good names every consumer needs, plus
seeded R2 grammar violations."""


def emit(edge):
    REGISTRY.incr("good/counter")
    REGISTRY.incr("good/total")
    REGISTRY.incr("good/retries")
    REGISTRY.observe("good/hist", 1.0)
    REGISTRY.set_gauge("langdetect_fixture_gauge", 2.0)
    REGISTRY.incr(f"exec/len/{edge}")
    REGISTRY.incr("BadGrammarName")  # seeded R2: grammar
    REGISTRY.observe("no_slash_name", 1.0)  # seeded R2: grammar
    with span("score/pack"):
        pass
