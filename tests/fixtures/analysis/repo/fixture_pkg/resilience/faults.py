"""Fixture fault-site registry (never imported — the checker parses it)."""

SITES = (
    "score/dispatch",
    "ghost/site",  # seeded R3: no inject() call site, undocumented in §4
)
