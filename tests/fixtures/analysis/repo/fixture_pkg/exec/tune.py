"""Fixture tuner consumption (never imported — the checker parses it)."""

LEN_BIN_PREFIX = "exec/len/"


def signals(counters, hists):
    good = counters.get("good/counter")
    ghost = counters.get("ghost/tuner_counter")  # seeded R2: never emitted
    hist = hists.get("good/hist")
    return good, ghost, hist
