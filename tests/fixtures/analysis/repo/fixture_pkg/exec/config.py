"""Fixture knob table (never imported — the checker parses it)."""

KNOBS = _knobs(
    Knob("alpha", "LANGDETECT_ALPHA", "int", 1, "fixture alpha knob"),
    Knob("beta", "LANGDETECT_BETA", "str", None, "fixture beta knob"),
)
