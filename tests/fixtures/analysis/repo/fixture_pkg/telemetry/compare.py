"""Fixture compare contracts (never imported — the checker parses it)."""

_TRACKED_GAUGES = {
    "langdetect_fixture_gauge": "fixture_gauge",
    "langdetect_ghost_gauge": "ghost_gauge",  # seeded R2: never emitted
}

_TRACKED_RATIOS = {
    "good/ratio": ("good/counter", "good/total"),
    "bad/ratio": ("ghost/ratio_counter", "good/total"),  # seeded R2
}

_RELIABILITY_COUNTER_PREFIXES = ("ghostarea/",)  # seeded R2: no emits under it
_RELIABILITY_COUNTERS = (
    "good/retries",
    "ghost/retries",  # seeded R2: never emitted
)
