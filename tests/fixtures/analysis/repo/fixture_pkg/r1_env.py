"""Seeded R1 violations: direct knob reads outside exec/config."""

import os

GHOST_ENV = "LANGDETECT_GHOST_KNOB"  # seeded R1: no KNOBS row
ANN_ENV: str = "LANGDETECT_BETA"  # annotated-constant spelling


def bad_get():
    return os.environ.get("LANGDETECT_ALPHA")  # seeded R1: direct read


def bad_annassign_const():
    return os.environ.get(ANN_ENV)  # seeded R1: read via annotated constant


def bad_subscript():
    return os.environ[GHOST_ENV]  # seeded R1: direct read via constant


def bad_getenv():
    return os.getenv("LANGDETECT_BETA")  # seeded R1: direct read
