"""Seeded R4 violations: host-impure calls inside traced functions."""


@partial(jax.jit, static_argnames=("n",))
def traced_decorated(x, *, n):
    t = time.perf_counter()  # seeded R4: baked at trace time
    print(x)  # seeded R4: per-trace no-op
    return x + n + t


def wrapped_helper(x):
    v = np.random.rand()  # seeded R4: host RNG
    REGISTRY.incr("good/counter")  # seeded R4: telemetry emission
    return x * v


wrapped = jax.jit(wrapped_helper)


def kernel_fn(ref):
    home = os.environ.get("HOME")  # seeded R4: env read under pallas
    ref[...] = 0 if home else 1


kernel = pl.pallas_call(kernel_fn, out_shape=None)


def host_side_is_fine(x):
    # Not traced: the same calls are legal on the host.
    print(x)
    return time.perf_counter()
