"""Suppression fixtures: honored pragmas, a stale one, a bogus rule id."""

import os


def suppressed_inline():
    return os.environ.get("LANGDETECT_ALPHA")  # contract: ignore[R1] -- fixture: same-line suppression form


def suppressed_above():
    # contract: ignore[R1] -- fixture: pragma-above suppression form
    return os.environ.get("LANGDETECT_ALPHA")


# contract: ignore[R3] -- fixture: stale, suppresses nothing
def nothing_to_suppress():
    return 0


def wrong_rule_id():
    return os.environ.get("LANGDETECT_ALPHA")  # contract: ignore[R9] -- fixture: unknown rule id
