"""Seeded R3 violation: an inject literal outside the SITES registry."""


def work(faults):
    faults.inject("score/dispatch")
    faults.inject("not/a_site")  # seeded R3: not in SITES
