"""Device-side encode + zero-copy ingest (ISSUE 19, PERFORMANCE.md §11).

The wire-wall contract: the device-encode path's host half (UTF-8-safe
truncation, chunk windowing, wire gather) must be bit-identical to the
scalar host-pack oracles in ``ops.encoding``, the device half
(``encode_batch``) must rebuild exactly ``pad_batch``'s padded plane, and
the DocBlock zero-copy tier (numpy/Arrow-backed bytes viewed, never
re-materialized as Python objects) must feed every packer with the same
bits as the list[bytes] tier.
"""

import numpy as np
import pytest

from spark_languagedetector_tpu import native
from spark_languagedetector_tpu.ops import encode_device as ed
from spark_languagedetector_tpu.ops.encoding import (
    chunk_document,
    pack_ragged_numpy,
    pad_batch,
    truncate_utf8,
)


def _corpus(rng, n=40):
    docs = [
        bytes(rng.integers(0, 256, int(rng.integers(0, 300))).tolist())
        for _ in range(n)
    ]
    docs += [b"", b"a", b"\x80" * 130, b"\xc3" + b"\xa9" * 299,
             b"x" * 126 + "€".encode() * 3, "é".encode() * 100]
    return docs


# ---------------------------------------------------------------- DocBlock --
def test_docblock_from_bytes_round_trips():
    rng = np.random.default_rng(0)
    docs = _corpus(rng)
    block = ed.DocBlock.from_bytes(docs)
    assert len(block) == len(docs)
    assert block.total_bytes == sum(len(d) for d in docs)
    assert [block.doc(i) for i in range(len(docs))] == docs
    np.testing.assert_array_equal(
        block.lengths(), [len(d) for d in docs]
    )


def test_docblock_from_arrow_views_buffers_zero_copy():
    pa = pytest.importorskip("pyarrow")
    docs = [b"alpha", b"", b"\xc3\xa9" * 50, b"tail"]
    for typ in (pa.binary(), pa.large_binary()):
        arr = pa.array(docs, type=typ)
        block = ed.DocBlock.from_arrow(arr)
        assert [block.doc(i) for i in range(len(block))] == docs
    # sliced arrays honor the offset window
    arr = pa.array([b"drop"] + docs, type=pa.binary()).slice(1)
    block = ed.DocBlock.from_arrow(arr)
    assert [block.doc(i) for i in range(len(block))] == docs
    # nulls cannot ride the wire silently
    with pytest.raises(ValueError, match="null"):
        ed.DocBlock.from_arrow(pa.array([b"a", None], type=pa.binary()))


def test_docblock_from_arrow_string_chunked():
    pa = pytest.importorskip("pyarrow")
    chunked = pa.chunked_array([["héllo", "wörld"], ["x" * 200]])
    block = ed.DocBlock.from_arrow(chunked)
    assert [block.doc(i) for i in range(len(block))] == [
        "héllo".encode(), "wörld".encode(), b"x" * 200
    ]


# ------------------------------------------------------- vectorized oracles --
def test_utf8_safe_lengths_matches_truncate_utf8():
    rng = np.random.default_rng(1)
    docs = _corpus(rng, n=200)
    block = ed.DocBlock.from_bytes(docs)
    for cap in (1, 2, 7, 128, 256):
        got = ed.utf8_safe_lengths(
            block.flat, block.starts(), block.lengths(), cap
        )
        want = [len(truncate_utf8(d, cap)) for d in docs]
        np.testing.assert_array_equal(got, want, err_msg=f"cap={cap}")


def test_chunk_table_matches_chunk_document():
    rng = np.random.default_rng(2)
    lengths = np.array(
        [0, 1, 127, 128, 129, 255, 256, 257, 700, 1000]
        + list(rng.integers(0, 1200, 50)),
        dtype=np.int64,
    )
    starts = np.zeros(lengths.size, dtype=np.int64)
    np.cumsum(lengths[:-1], out=starts[1:])
    for chunk_size, overlap in ((256, 4), (256, 2), (128, 1)):
        doc_of, c_starts, c_lens, limits = ed.chunk_table(
            starts, lengths, chunk_size, overlap
        )
        e_doc, e_start, e_len, e_lim = [], [], [], []
        stride = chunk_size - overlap
        for i, (s, ln) in enumerate(zip(starts, lengths)):
            doc = b"\0" * int(ln)
            chunks = chunk_document(doc, chunk_size, overlap)
            for k, c in enumerate(chunks):
                e_doc.append(i)
                e_start.append(int(s) + k * stride)
                e_len.append(len(c))
                # the runner's rule: non-final chunks own window starts
                # [0, stride); the final chunk owns all of its starts
                e_lim.append(
                    stride if k < len(chunks) - 1 else chunk_size
                )
        np.testing.assert_array_equal(doc_of, e_doc)
        np.testing.assert_array_equal(c_starts, e_start)
        np.testing.assert_array_equal(c_lens, e_len)
        np.testing.assert_array_equal(limits, e_lim)


# ----------------------------------------------------------------- the wire --
def test_gather_wire_matches_wire_from_docs():
    rng = np.random.default_rng(3)
    docs = _corpus(rng)
    block = ed.DocBlock.from_bytes(docs)
    w1, s1, l1 = ed.gather_wire(block.flat, block.starts(), block.lengths())
    w2, s2, l2 = ed.wire_from_docs(docs)
    np.testing.assert_array_equal(w1, w2)
    np.testing.assert_array_equal(s1, s2)
    np.testing.assert_array_equal(l1, l2)
    assert s1.dtype == np.int32 and l1.dtype == np.int32
    with pytest.raises(ValueError, match="capacity"):
        ed.wire_from_docs(docs, capacity=3)


def test_wire_capacity_buckets_and_bounds():
    # never exceeds the padded size, always fits the real bytes
    for rows, pad_to in ((8, 128), (64, 256), (512, 512)):
        padded = rows * pad_to
        step = max(256, padded // 16)
        for total in (0, 1, 200, padded // 2, padded - 1, padded):
            cap = ed.wire_capacity(total, rows, pad_to)
            assert max(total, 1) <= cap <= padded
            assert cap == padded or cap % step == 0
    # the bucket lattice stays small: <= ~17 distinct sizes per geometry
    caps = {ed.wire_capacity(t, 64, 256) for t in range(0, 64 * 256 + 1, 37)}
    assert len(caps) <= 17


def test_encode_batch_jit_rebuilds_pad_batch_exactly():
    rng = np.random.default_rng(4)
    docs = [d[:256] for d in _corpus(rng)]
    for pad_to in (128, 256):
        capped = [d[:pad_to] for d in docs]
        wire, starts, lengths = ed.wire_from_docs(capped)
        got = np.asarray(ed.encode_batch_jit(wire, starts, lengths, pad_to))
        want, want_lens = pad_batch(capped, pad_to=pad_to)
        np.testing.assert_array_equal(got, want)
        np.testing.assert_array_equal(lengths, want_lens)


# ----------------------------------------------- zero-copy packer delegation --
def test_native_packers_accept_docblock_bit_exact():
    rng = np.random.default_rng(5)
    docs = _corpus(rng)
    block = ed.DocBlock.from_bytes(docs)
    for pad_to in (128, 256):
        for a, b in zip(
            native.pack_batch(docs, pad_to), native.pack_batch(block, pad_to)
        ):
            np.testing.assert_array_equal(a, b)
        for a, b in zip(
            native.pack_ragged(docs, pad_to), native.pack_ragged(block, pad_to)
        ):
            np.testing.assert_array_equal(a, b)
        for a, b in zip(
            pack_ragged_numpy(docs, pad_to), pack_ragged_numpy(block, pad_to)
        ):
            np.testing.assert_array_equal(a, b)


# --------------------------------------------------------------- the gate ---
def test_bench_smoke_wire_trimmed(tmp_path):
    """Tier-1-sized wire smoke: all-unique short docs A/B'd host-pack vs
    device-encode — bit-exact parity on gather + fused (knob and DocBlock
    tiers), >=2x wire bytes/doc reduction, degraded host-pack rung under a
    persistent score/pack fault, exactly like the CI gate (the wall-clock
    speedup gate runs full-size only)."""
    import bench

    result = bench.smoke_wire(str(tmp_path / "wire.jsonl"), trimmed=True)
    assert result["ok"], result
    assert result["parity"]["knob_bit_exact"]
    assert result["parity"]["block_bit_exact"]
    assert result["parity"]["fused_bit_exact"]
    assert result["parity"]["degraded_bit_exact"]
    assert result["parity"]["degraded_argmax"] == 1.0
    assert result["wire"]["reduction"] >= 2.0
    assert result["wire"]["encoded_batches"] > 0
    assert result["degraded_batches"] > 0


@pytest.mark.slow
def test_bench_smoke_wire_full(tmp_path):
    """Full-size wire smoke incl. the >=1.3x all-unique end-to-end
    wall-clock gate (slow-marked: CI runs it via
    ``bench.py --smoke-wire``)."""
    import bench

    result = bench.smoke_wire(str(tmp_path / "wire_full.jsonl"))
    assert result["ok"], result
    assert result["speedup_all_unique"] >= 1.3
