"""Exact gram membership beyond int32 ids: packed keys + cuckoo table.

VERDICT r1 #5: exact mode for gram lengths 4..5, parity-tested against the
pure-Python oracle with gram_lengths=(1..5), vocabMode="exact".
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_languagedetector_tpu import LanguageDetector, Table
from spark_languagedetector_tpu.ops import score as S
from spark_languagedetector_tpu.ops import vocab as V
from spark_languagedetector_tpu.ops.cuckoo import build_cuckoo, lookup_numpy
from spark_languagedetector_tpu.ops.encoding import pad_batch

from .oracle import detect_oracle, fit_oracle


def test_gram_key_bijective_and_matches_window_keys():
    rng = np.random.default_rng(5)
    grams = [bytes(rng.integers(0, 256, n, dtype=np.uint8)) for n in (1, 2, 3, 4, 5) for _ in range(20)]
    keys = {V.gram_key(g) for g in grams}
    assert len(keys) == len(set(grams))  # distinct grams ⇒ distinct keys
    # window_keys (device) and window_keys_numpy agree with gram_key
    doc = bytes(rng.integers(0, 256, 40, dtype=np.uint8))
    batch = np.frombuffer(doc, dtype=np.uint8)[None, :]
    for n in (1, 2, 3, 4, 5):
        lo_d, hi_d = (np.asarray(a) for a in V.window_keys(jnp.asarray(batch), n))
        lo_h, hi_h = V.window_keys_numpy(batch, n)
        np.testing.assert_array_equal(lo_d, lo_h)
        np.testing.assert_array_equal(hi_d, hi_h)
        for i in range(len(doc) - n + 1):
            assert (int(lo_h[0, i]), int(hi_h[0, i])) == V.gram_key(doc[i : i + n])


def test_mix32_host_device_lockstep():
    rng = np.random.default_rng(7)
    lo = rng.integers(-(2**31), 2**31, 1000).astype(np.int32)
    hi = rng.integers(0, 2**11, 1000).astype(np.int32)
    host = V.mix32(lo, hi, 12345)
    dev = np.asarray(V.mix32(jnp.asarray(lo), jnp.asarray(hi), 12345, xp=jnp))
    np.testing.assert_array_equal(host, dev)


def test_cuckoo_build_and_lookup_exact():
    rng = np.random.default_rng(11)
    grams = list({bytes(rng.integers(0, 256, int(n), dtype=np.uint8))
                  for n in rng.integers(1, 6, 5000)})
    keys = [V.gram_key(g) for g in grams]
    lo = np.asarray([k[0] for k in keys], np.int32)
    hi = np.asarray([k[1] for k in keys], np.int32)
    table = build_cuckoo(lo, hi)
    # every inserted key resolves to its own row
    rows = lookup_numpy(table, lo, hi)
    np.testing.assert_array_equal(rows, np.arange(len(grams)))
    # absent keys miss
    absent = [g for g in (bytes(rng.integers(0, 256, 5, dtype=np.uint8)) for _ in range(200))
              if g not in set(grams)]
    akeys = [V.gram_key(g) for g in absent]
    arows = lookup_numpy(
        table,
        np.asarray([k[0] for k in akeys], np.int32),
        np.asarray([k[1] for k in akeys], np.int32),
    )
    assert (arows == len(grams)).all()


def test_device_cuckoo_rows_match_host():
    rng = np.random.default_rng(13)
    grams = list({bytes(rng.integers(0, 256, int(n), dtype=np.uint8))
                  for n in rng.integers(4, 6, 500)})
    keys = [V.gram_key(g) for g in grams]
    lo = np.asarray([k[0] for k in keys], np.int32)
    hi = np.asarray([k[1] for k in keys], np.int32)
    table = build_cuckoo(lo, hi)
    probe_lo = np.concatenate([lo, rng.integers(-(2**31), 2**31, 300).astype(np.int32)])
    probe_hi = np.concatenate([hi, (rng.integers(4, 6, 300).astype(np.int32) << 8)])
    host = lookup_numpy(table, probe_lo, probe_hi)
    dev = np.asarray(
        S._cuckoo_rows(
            jnp.asarray(probe_lo), jnp.asarray(probe_hi),
            jnp.asarray(table.entries()), len(grams),
            table.seed1, table.seed2,
        )
    )
    np.testing.assert_array_equal(host, dev)


def test_exact_1to5_fit_transform_matches_oracle():
    """The VERDICT done-criterion: gram_lengths=(1..5), vocabMode='exact'."""
    train_pairs = [
        ("de", "der schnelle braune fuchs springt über den faulen hund"),
        ("de", "ein schöner tag im wald mit vielen bäumen und vögeln"),
        ("en", "the quick brown fox jumps over the lazy dog today"),
        ("en", "a beautiful day in the forest with many trees and birds"),
    ]
    langs = ["de", "en"]
    glens = [1, 2, 3, 4, 5]
    det = LanguageDetector(langs, glens, 40).set_vocab_mode("exact")
    model = det.fit(Table({
        "lang": [l for l, _ in train_pairs],
        "fulltext": [t for _, t in train_pairs],
    }))
    assert model.profile.spec.mode == V.EXACT
    assert model.profile.spec.gram_lengths == (1, 2, 3, 4, 5)
    # fit parity: same gram set and weights as the oracle
    gram_map = fit_oracle(train_pairs, langs, glens, 40)
    assert set(model.gram_probabilities) == set(gram_map)
    for g, w in gram_map.items():
        np.testing.assert_allclose(model.gram_probabilities[g], w, rtol=1e-12)
    # transform parity incl. short/empty/unseen docs (cuckoo membership path)
    probes = ["der hund", "the dog", "", "a", "ab", "abc", "abcd",
              "zzzz unrelated words", "schöne vögel fliegen"]
    got = model.transform(Table({"fulltext": probes})).column("lang")
    want = [detect_oracle(t, gram_map, langs, glens) for t in probes]
    assert list(got) == want


def test_exact_1to5_runner_uses_cuckoo_membership():
    det = LanguageDetector(["de", "en"], [1, 2, 3, 4, 5], 20).set_vocab_mode("exact")
    model = det.fit(Table({
        "lang": ["de", "en"],
        "fulltext": ["der schnelle fuchs", "the quick fox"],
    }))
    runner = model._get_runner()
    assert runner.cuckoo is not None
    assert runner.lut is None


def test_exact_long_grams_device_fit_matches_host():
    """Exact long-gram vocabs fit on device via the split path (device
    counts for gram lengths <= 3, exact host counting for the rest) —
    round 2 rejected this combination outright."""
    rows = Table({"lang": ["de", "en"], "fulltext": ["aaa bbb", "ccc ddd"]})

    def fit(backend):
        return (
            LanguageDetector(["de", "en"], [1, 4], 20)
            .set_vocab_mode("exact")
            .set_fit_backend(backend)
            .fit(rows)
        )

    host, dev = fit("cpu"), fit("device")
    np.testing.assert_array_equal(dev.profile.ids, host.profile.ids)
    np.testing.assert_allclose(dev.profile.weights, host.profile.weights)


def test_score_batch_cuckoo_window_limit():
    """Chunk-ownership masks apply to the cuckoo scorer too."""
    rng = np.random.default_rng(17)
    spec = V.VocabSpec(V.EXACT, (1, 4))
    docs = [bytes(rng.integers(97, 105, 60, dtype=np.uint8)) for _ in range(4)]
    grams = {d[i:i+4] for d in docs for i in range(len(d) - 3)}
    grams |= {d[i:i+1] for d in docs for i in range(len(d))}
    grams = sorted(grams)
    keys = [V.gram_key(g) for g in grams]
    table = build_cuckoo(
        np.asarray([k[0] for k in keys], np.int32),
        np.asarray([k[1] for k in keys], np.int32),
    )
    weights = np.concatenate([
        rng.normal(size=(len(grams), 3)), np.zeros((1, 3))
    ]).astype(np.float32)
    batch, lengths = pad_batch(docs, pad_to=64)
    kw = dict(seed1=table.seed1, seed2=table.seed2, spec=spec, block=128)
    args = (
        jnp.asarray(batch), jnp.asarray(lengths), jnp.asarray(weights),
        jnp.asarray(table.entries()),
    )
    full = np.asarray(S.score_batch_cuckoo(*args, **kw))
    limit = np.asarray([10, 60, 25, 1], np.int32)
    limited = np.asarray(S.score_batch_cuckoo(*args, window_limit=jnp.asarray(limit), **kw))
    # limited scores = scoring the owned prefix windows only
    host_w, host_ids = weights, np.asarray([spec.gram_to_id(g) for g in grams], np.int64)
    order = np.argsort(host_ids)
    sw = np.concatenate([weights[:len(grams)][order], np.zeros((1, 3), np.float32)])
    sids = host_ids[order]
    for i, doc in enumerate(docs):
        acc = np.zeros(3)
        for n in spec.gram_lengths:
            for s in range(len(doc) - n + 1):
                if s < limit[i]:
                    g = doc[s:s+n]
                    pos = np.searchsorted(sids, spec.gram_to_id(g))
                    if pos < len(sids) and sids[pos] == spec.gram_to_id(g):
                        acc += sw[pos]
        np.testing.assert_allclose(limited[i], acc, rtol=1e-4, atol=1e-4)
    assert not np.allclose(full, limited)
