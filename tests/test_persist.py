"""Persistence round-trip — the reference's it-spec pattern
(LanguageDetectionModelItSpecs.scala:15-47) plus hashed-mode coverage."""

import json
from pathlib import Path

import numpy as np
import pytest

from spark_languagedetector_tpu import LanguageDetector, LanguageDetectorModel, Table
from spark_languagedetector_tpu.ops.vocab import HASHED


def test_save_load_roundtrip_dummy_model(tmp_path):
    """The reference it-spec: dummy 1-gram/1-language model, save → exists →
    load → gram_lengths intact."""
    path = str(tmp_path / "model")
    model = LanguageDetectorModel.from_gram_map({b"a": [1.0]}, [1], ["aa"])
    model.write().save(path)
    assert Path(path).exists()
    loaded = LanguageDetectorModel.load(path)
    assert len(loaded.gram_lenghts) == 1  # reference-misspelled accessor
    assert loaded.supported_languages == ("aa",)
    assert loaded.gram_probabilities.keys() == {b"a"}


def test_roundtrip_preserves_weights_and_predictions(tmp_path):
    train = Table(
        {
            "lang": ["de", "de", "en", "en"],
            "fulltext": [
                "Dies ist ein deutscher Text, das ist ja sehr schön",
                "Dies ist ein andere deutscher Text, und der ist auch sehr schön",
                "This is a text in english, and that is very nice",
                "This is another text in english and that is also nice",
            ],
        }
    )
    model = LanguageDetector(["de", "en"], [2, 3], 15).fit(train)
    path = str(tmp_path / "model")
    model.save(path)
    loaded = LanguageDetectorModel.load(path)

    assert loaded.gram_probabilities.keys() == model.gram_probabilities.keys()
    for gram, vec in model.gram_probabilities.items():
        np.testing.assert_allclose(loaded.gram_probabilities[gram], vec)

    texts = ["Das ist sehr schön", "this is very nice"]
    out_a = model.transform(Table({"fulltext": texts})).column("lang").tolist()
    out_b = loaded.transform(Table({"fulltext": texts})).column("lang").tolist()
    assert out_a == out_b


def test_roundtrip_hashed_model(tmp_path):
    train = Table(
        {
            "lang": ["de", "en"],
            "fulltext": ["Dies ist ein deutscher Text schön", "this is very nice"],
        }
    )
    model = (
        LanguageDetector(["de", "en"], [1, 2, 3, 4], 30)
        .set_vocab_mode(HASHED)
        .set_hash_bits(14)
        .fit(train)
    )
    path = str(tmp_path / "model")
    model.save(path)
    loaded = LanguageDetectorModel.load(path)
    np.testing.assert_allclose(loaded.profile.weights, model.profile.weights)
    assert loaded.profile.spec == model.profile.spec


def test_roundtrip_hashed_exact12_scheme(tmp_path):
    train = Table(
        {
            "lang": ["de", "en"],
            "fulltext": ["Dies ist ein deutscher Text schön", "this is very nice"],
        }
    )
    model = (
        LanguageDetector(["de", "en"], [1, 2, 3, 4], 30)
        .set_vocab_mode(HASHED)
        .set_hash_bits(18)
        .fit(train)
    )
    assert model.profile.spec.hash_scheme == "exact12"
    path = str(tmp_path / "model")
    model.save(path)
    loaded = LanguageDetectorModel.load(path)
    assert loaded.profile.spec == model.profile.spec


def test_load_pre_scheme_metadata_defaults_to_fnv1a(tmp_path):
    """Models persisted before bucket schemes existed must keep FNV ids."""
    train = Table({"lang": ["de", "en"], "fulltext": ["schön öä", "nice day"]})
    model = (
        LanguageDetector(["de", "en"], [1, 2, 3], 30)
        .set_vocab_mode(HASHED)
        .set_hash_bits(18)
        .set_hash_scheme("fnv1a")
        .fit(train)
    )
    path = tmp_path / "model"
    model.save(str(path))
    meta_file = path / "metadata" / "part-00000"
    meta = json.loads(meta_file.read_text())
    del meta["vocab"]["hashScheme"]  # simulate a pre-scheme artifact
    meta_file.write_text(json.dumps(meta) + "\n")
    loaded = LanguageDetectorModel.load(str(path))
    assert loaded.profile.spec.hash_scheme == "fnv1a"
    assert loaded.profile.spec == model.profile.spec


def test_metadata_layout_and_class_check(tmp_path):
    path = tmp_path / "model"
    model = LanguageDetectorModel.from_gram_map({b"ab": [1.0]}, [2], ["de"])
    model.save(str(path))

    # Reference directory layout.
    assert (path / "metadata" / "part-00000").exists()
    assert list((path / "probabilities").glob("*.parquet"))
    assert list((path / "supportedLanguages").glob("*.parquet"))
    assert list((path / "gramLengths").glob("*.parquet"))

    meta = json.loads((path / "metadata" / "part-00000").read_text())
    assert meta["uid"] == model.uid
    assert "LanguageDetectorModel" in meta["class"]

    # Class-name check on load (LanguageDetectorModel.scala:66,72).
    meta["class"] = "something.Else"
    (path / "metadata" / "part-00000").write_text(json.dumps(meta))
    with pytest.raises(ValueError, match="class mismatch"):
        LanguageDetectorModel.load(str(path))


def test_save_overwrites_existing(tmp_path):
    """model.save: SaveMode.Overwrite semantics (LanguageDetectorModel.scala:43)."""
    path = str(tmp_path / "model")
    m1 = LanguageDetectorModel.from_gram_map({b"a": [1.0]}, [1], ["aa"])
    m1.save(path)
    m2 = LanguageDetectorModel.from_gram_map({b"b": [1.0, 0.0]}, [1], ["bb", "cc"])
    m2.save(path)
    loaded = LanguageDetectorModel.load(path)
    assert loaded.supported_languages == ("bb", "cc")


def test_writer_without_overwrite_refuses_existing_path(tmp_path):
    """MLWriter contract: write().save is non-destructive unless .overwrite()."""
    path = str(tmp_path / "model")
    m1 = LanguageDetectorModel.from_gram_map({b"a": [1.0]}, [1], ["aa"])
    m1.write().save(path)  # fresh path: fine
    with pytest.raises(FileExistsError):
        m1.write().save(path)
    m1.write().overwrite().save(path)  # explicit overwrite: fine


# ---------------------------------------------------------- interop ---------
def _fit_small_model(vocab="exact"):
    train = Table(
        {
            "lang": ["de", "de", "en", "en"],
            "fulltext": [
                "Dies ist ein deutscher Text, das ist ja sehr sch\u00f6n",
                "Dies ist ein andere deutscher Text, der ist auch sch\u00f6n",
                "This is a text in english, and that is very nice",
                "This is another text in english and that is also nice",
            ],
        }
    )
    det = LanguageDetector(["de", "en"], [2, 3], 20)
    if vocab == "hashed":
        det = det.set_vocab_mode("hashed").set_hash_bits(12)
    return det.fit(train)


def _reference_layout_dir(tmp_path, gram_map, languages, gram_lengths):
    """Hand-build a model directory exactly as the Scala writer lays it out
    (LanguageDetectorModel.scala:28-58): tuple-column probabilities parquet
    (signed JVM bytes), value-column languages/gramLengths, JVM metadata."""
    import json

    import pyarrow as pa
    import pyarrow.parquet as pq

    root = tmp_path / "scala_model"
    (root / "metadata").mkdir(parents=True)
    meta = {
        "class": (
            "org.apache.spark.ml.feature.languagedetection."
            "LanguageDetectorModel"
        ),
        "timestamp": 1500000000000,
        "sparkVersion": "2.2.0",
        "uid": "LanguageDetectorModel_4a1b2c3d",
        "paramMap": {"inputCol": "fulltext", "outputCol": "language"},
    }
    (root / "metadata" / "part-00000").write_text(json.dumps(meta) + "\n")
    signed = [
        np.frombuffer(g, np.uint8).astype(np.int8).tolist() for g in gram_map
    ]
    pq_dir = root / "probabilities"
    pq_dir.mkdir()
    pq.write_table(
        pa.table({
            "_1": pa.array(signed, type=pa.list_(pa.int8())),
            "_2": pa.array(
                [list(v) for v in gram_map.values()],
                type=pa.list_(pa.float64()),
            ),
        }),
        pq_dir / "part-00000-abc.snappy.parquet",
    )
    for sub, vals, typ in (
        ("supportedLanguages", list(languages), pa.string()),
        ("gramLengths", list(gram_lengths), pa.int32()),
    ):
        d = root / sub
        d.mkdir()
        pq.write_table(
            pa.table({"value": pa.array(vals, type=typ)}),
            d / "part-00000-abc.snappy.parquet",
        )
    return root


def test_load_reference_layout_model(tmp_path):
    """A model saved by the actual Scala implementation loads here: tuple
    columns decode to gram bytes (signed-byte wrap included) and params
    carry over."""
    gram_map = {
        b"Die": [1.0, 0.0],
        b"Thi": [0.0, 1.0],
        bytes([0xC3, 0xA9, 0x20]): [0.5, 0.25],  # high bytes -> signed JVM
    }
    root = _reference_layout_dir(tmp_path, gram_map, ["de", "en"], [3])
    model = LanguageDetectorModel.load(str(root))
    assert model.uid == "LanguageDetectorModel_4a1b2c3d"
    assert model.get_output_col() == "language"
    assert model.supported_languages == ("de", "en")
    assert model.gram_lengths == (3,)
    got = model.gram_probabilities
    assert set(got) == set(gram_map)
    for g, v in gram_map.items():
        np.testing.assert_allclose(got[g], v)
    out = model.transform(Table({"fulltext": ["Dies ist schön", "This is"]}))
    assert list(out.column("language")) == ["de", "en"]


def test_reference_layout_write_roundtrip(tmp_path):
    """save in reference layout -> load back; the probabilities parquet
    really carries the Scala tuple columns."""
    import pyarrow.parquet as pq

    model = _fit_small_model()
    path = tmp_path / "interop"
    model.write().overwrite().reference_layout().save(str(path))
    cols = pq.read_table(
        sorted((path / "probabilities").glob("*.parquet"))[0]
    ).column_names
    assert cols == ["_1", "_2"]
    back = LanguageDetectorModel.load(str(path))
    assert back.supported_languages == model.supported_languages
    assert set(back.gram_probabilities) == set(model.gram_probabilities)


def test_reference_layout_rejects_hashed(tmp_path):
    model = _fit_small_model(vocab="hashed")
    with pytest.raises(ValueError, match="exact"):
        model.write().overwrite().reference_layout().save(
            str(tmp_path / "nope")
        )
