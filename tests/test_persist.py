"""Persistence round-trip — the reference's it-spec pattern
(LanguageDetectionModelItSpecs.scala:15-47) plus hashed-mode coverage."""

import json
from pathlib import Path

import numpy as np
import pytest

from spark_languagedetector_tpu import LanguageDetector, LanguageDetectorModel, Table
from spark_languagedetector_tpu.ops.vocab import HASHED


def test_save_load_roundtrip_dummy_model(tmp_path):
    """The reference it-spec: dummy 1-gram/1-language model, save → exists →
    load → gram_lengths intact."""
    path = str(tmp_path / "model")
    model = LanguageDetectorModel.from_gram_map({b"a": [1.0]}, [1], ["aa"])
    model.write().save(path)
    assert Path(path).exists()
    loaded = LanguageDetectorModel.load(path)
    assert len(loaded.gram_lenghts) == 1  # reference-misspelled accessor
    assert loaded.supported_languages == ("aa",)
    assert loaded.gram_probabilities.keys() == {b"a"}


def test_roundtrip_preserves_weights_and_predictions(tmp_path):
    train = Table(
        {
            "lang": ["de", "de", "en", "en"],
            "fulltext": [
                "Dies ist ein deutscher Text, das ist ja sehr schön",
                "Dies ist ein andere deutscher Text, und der ist auch sehr schön",
                "This is a text in english, and that is very nice",
                "This is another text in english and that is also nice",
            ],
        }
    )
    model = LanguageDetector(["de", "en"], [2, 3], 15).fit(train)
    path = str(tmp_path / "model")
    model.save(path)
    loaded = LanguageDetectorModel.load(path)

    assert loaded.gram_probabilities.keys() == model.gram_probabilities.keys()
    for gram, vec in model.gram_probabilities.items():
        np.testing.assert_allclose(loaded.gram_probabilities[gram], vec)

    texts = ["Das ist sehr schön", "this is very nice"]
    out_a = model.transform(Table({"fulltext": texts})).column("lang").tolist()
    out_b = loaded.transform(Table({"fulltext": texts})).column("lang").tolist()
    assert out_a == out_b


def test_roundtrip_hashed_model(tmp_path):
    train = Table(
        {
            "lang": ["de", "en"],
            "fulltext": ["Dies ist ein deutscher Text schön", "this is very nice"],
        }
    )
    model = (
        LanguageDetector(["de", "en"], [1, 2, 3, 4], 30)
        .set_vocab_mode(HASHED)
        .set_hash_bits(14)
        .fit(train)
    )
    path = str(tmp_path / "model")
    model.save(path)
    loaded = LanguageDetectorModel.load(path)
    np.testing.assert_allclose(loaded.profile.weights, model.profile.weights)
    assert loaded.profile.spec == model.profile.spec


def test_roundtrip_hashed_exact12_scheme(tmp_path):
    train = Table(
        {
            "lang": ["de", "en"],
            "fulltext": ["Dies ist ein deutscher Text schön", "this is very nice"],
        }
    )
    model = (
        LanguageDetector(["de", "en"], [1, 2, 3, 4], 30)
        .set_vocab_mode(HASHED)
        .set_hash_bits(18)
        .fit(train)
    )
    assert model.profile.spec.hash_scheme == "exact12"
    path = str(tmp_path / "model")
    model.save(path)
    loaded = LanguageDetectorModel.load(path)
    assert loaded.profile.spec == model.profile.spec


def test_load_pre_scheme_metadata_defaults_to_fnv1a(tmp_path):
    """Models persisted before bucket schemes existed must keep FNV ids."""
    train = Table({"lang": ["de", "en"], "fulltext": ["schön öä", "nice day"]})
    model = (
        LanguageDetector(["de", "en"], [1, 2, 3], 30)
        .set_vocab_mode(HASHED)
        .set_hash_bits(18)
        .set_hash_scheme("fnv1a")
        .fit(train)
    )
    path = tmp_path / "model"
    model.save(str(path))
    meta_file = path / "metadata" / "part-00000"
    meta = json.loads(meta_file.read_text())
    del meta["vocab"]["hashScheme"]  # simulate a pre-scheme artifact
    meta_file.write_text(json.dumps(meta) + "\n")
    loaded = LanguageDetectorModel.load(str(path))
    assert loaded.profile.spec.hash_scheme == "fnv1a"
    assert loaded.profile.spec == model.profile.spec


def test_metadata_layout_and_class_check(tmp_path):
    path = tmp_path / "model"
    model = LanguageDetectorModel.from_gram_map({b"ab": [1.0]}, [2], ["de"])
    model.save(str(path))

    # Reference directory layout.
    assert (path / "metadata" / "part-00000").exists()
    assert list((path / "probabilities").glob("*.parquet"))
    assert list((path / "supportedLanguages").glob("*.parquet"))
    assert list((path / "gramLengths").glob("*.parquet"))

    meta = json.loads((path / "metadata" / "part-00000").read_text())
    assert meta["uid"] == model.uid
    assert "LanguageDetectorModel" in meta["class"]

    # Class-name check on load (LanguageDetectorModel.scala:66,72).
    meta["class"] = "something.Else"
    (path / "metadata" / "part-00000").write_text(json.dumps(meta))
    with pytest.raises(ValueError, match="class mismatch"):
        LanguageDetectorModel.load(str(path))


def test_save_overwrites_existing(tmp_path):
    """model.save: SaveMode.Overwrite semantics (LanguageDetectorModel.scala:43)."""
    path = str(tmp_path / "model")
    m1 = LanguageDetectorModel.from_gram_map({b"a": [1.0]}, [1], ["aa"])
    m1.save(path)
    m2 = LanguageDetectorModel.from_gram_map({b"b": [1.0, 0.0]}, [1], ["bb", "cc"])
    m2.save(path)
    loaded = LanguageDetectorModel.load(path)
    assert loaded.supported_languages == ("bb", "cc")


def test_writer_without_overwrite_refuses_existing_path(tmp_path):
    """MLWriter contract: write().save is non-destructive unless .overwrite()."""
    path = str(tmp_path / "model")
    m1 = LanguageDetectorModel.from_gram_map({b"a": [1.0]}, [1], ["aa"])
    m1.write().save(path)  # fresh path: fine
    with pytest.raises(FileExistsError):
        m1.write().save(path)
    m1.write().overwrite().save(path)  # explicit overwrite: fine
