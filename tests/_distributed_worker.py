"""Worker for the two-process jax.distributed test (test_resilience.py).

Run as: python tests/_distributed_worker.py <coordinator> <n_procs> <pid>
        python tests/_distributed_worker.py <coordinator> <n_procs> <pid> --probe

Each process pins JAX to CPU with two virtual devices, joins the
coordination service through the framework's own ``parallel.distributed``
entry points, then runs a real cross-process computation: host-sharded
rows assembled into one globally-sharded array, reduced under jit (XLA
inserts the cross-process collective), verified against the full-data
answer on every process.

``--probe`` runs ONLY the capability probe: distributed bring-up plus one
jit reduction over a cross-process array, built EXCLUSIVELY from jax
public APIs — it imports nothing from this framework, so a probe failure
can only indicate the substrate (jaxlib, coordination service, process
spawning), never a framework regression. Some jaxlib builds cannot
execute multi-process computations on the CPU backend at all
("Multiprocess computations aren't implemented on the CPU backend"); the
probe lets the test skip those hosts with the real reason instead of
failing, and the full worker runs only once the probe proved the
substrate works.
"""
import os
import sys

# Two local CPU devices per process -> a 4-device global mesh across the
# two processes. Must be set before the backend initializes.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2"
)

import jax  # noqa: E402

# The axon sitecustomize force-sets jax_platforms programmatically; the
# programmatic update below (not the env var) is what actually wins.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def probe(coordinator: str, n_procs: int, pid: int) -> None:
    """Capability probe: PURE jax/jaxlib surface only — distributed
    bring-up, a globally-sharded array assembled with
    ``jax.make_array_from_single_device_arrays``, and one jit reduction
    crossing processes. Deliberately imports nothing from this framework:
    a probe failure can only mean the substrate (jaxlib/coordination
    service/process spawning) cannot do two-process CPU collectives, never
    that framework code regressed."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=n_procs,
        process_id=pid,
    )
    assert jax.process_count() == n_procs, jax.process_count()
    mesh = Mesh(np.asarray(jax.devices()).reshape(-1, 1), ("data", "vocab"))
    sharding = NamedSharding(mesh, P("data"))
    rows, cols = 4 * n_procs, 3
    full = np.arange(rows * cols, dtype=np.float32).reshape(rows, cols)
    imap = sharding.addressable_devices_indices_map((rows, cols))
    garr = jax.make_array_from_single_device_arrays(
        (rows, cols), sharding,
        [jax.device_put(full[idx], d) for d, idx in imap.items()],
    )
    total = float(jax.jit(lambda x: x.sum())(garr))
    assert total == float(full.sum()), (total, float(full.sum()))
    print(f"DIST_PROBE_OK pid={pid}", flush=True)


def main() -> None:
    coordinator, n_procs, pid = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    )
    if "--probe" in sys.argv[4:]:
        probe(coordinator, n_procs, pid)
        return
    from spark_languagedetector_tpu.parallel import distributed as D
    from spark_languagedetector_tpu.parallel.mesh import (
        batch_sharding,
        build_mesh,
    )

    D.initialize(
        coordinator_address=coordinator,
        num_processes=n_procs,
        process_id=pid,
    )
    assert jax.process_count() == n_procs, jax.process_count()
    assert jax.process_index() == pid
    assert len(jax.devices()) == 2 * n_procs  # global device view

    mesh = build_mesh(data=2 * n_procs, vocab=1)
    rows, cols = 4 * n_procs, 3
    full = np.arange(rows * cols, dtype=np.float32).reshape(rows, cols)
    shard = D.host_shard(rows)
    local = full[shard]
    garr = D.global_batch(local, batch_sharding(mesh))
    assert garr.shape == (rows, cols)

    # Cross-process reduction: every process must see the full-data sum.
    total = float(jax.jit(lambda x: x.sum())(garr))
    expect = float(full.sum())
    assert total == expect, (total, expect)

    # Weighted reduction exercises a non-trivial collective too.
    w = np.linspace(0.5, 1.5, cols).astype(np.float32)
    got = float(jax.jit(lambda x: (x @ w).sum())(garr))
    expect2 = float((full @ w).sum())
    assert abs(got - expect2) < 1e-3, (got, expect2)

    # -- public API across process boundaries (VERDICT r3 item 9) ---------
    # Every process fits through the PUBLIC estimator with
    # fitBackend="device": resolve_fit_mesh() sees the 4-device GLOBAL mesh,
    # so the count psum crosses processes; the fitted profile must be
    # bit-identical to the single-process host fit. Then transform through
    # backend="mesh" (global data-parallel mesh; results assembled with
    # process_allgather in BatchRunner._fetch) and compare labels against
    # the local cpu-backend run.
    from spark_languagedetector_tpu import LanguageDetector, Table

    langs = ["aa", "bb"]
    train = Table({
        "lang": ["aa"] * 3 + ["bb"] * 3,
        "fulltext": ["abab cdcd abab", "ababab", "ab cd ab"]
        + ["xyxy zwzw xyxy", "xyxyxy", "xy zw xy"],
    })
    dev_model = (
        LanguageDetector(langs, [1, 2], 20)
        .set_fit_backend("device")
        .fit(train)
    )
    cpu_model = LanguageDetector(langs, [1, 2], 20).fit(train)
    assert np.array_equal(dev_model.profile.ids, cpu_model.profile.ids)
    assert np.allclose(
        dev_model.profile.weights, cpu_model.profile.weights, atol=1e-6
    )

    probes = Table({"fulltext": ["abab abab", "xy zw", "", "ab xyxy xy"]})
    dev_model.set("backend", "mesh")
    mesh_labels = list(
        dev_model.transform(probes).column(dev_model.get_output_col())
    )
    cpu_model.set("backend", "cpu")
    cpu_labels = list(
        cpu_model.transform(probes).column(cpu_model.get_output_col())
    )
    assert mesh_labels == cpu_labels, (mesh_labels, cpu_labels)

    print(f"DIST_OK pid={pid} total={total} labels={mesh_labels}", flush=True)


if __name__ == "__main__":
    main()
