"""Span-level code-switch segmentation (spark_languagedetector_tpu.segment).

Coverage map (ISSUE 12 acceptance):
  * span-merge property fuzz — the returned spans partition the document
    exactly, respect min-span, snap to UTF-8 boundaries;
  * device parity fuzz — ``BatchRunner.segment_cells`` against the
    float64 host oracle (``ops.score.window_scores_numpy``) on the
    gather strategy (dense AND cuckoo membership, chunked long docs
    included), and fused-vs-gather per-cell parity in interpret mode;
  * whole-doc pinning — ``score`` bytes are bit-identical around any
    amount of segment traffic (the new output mode must not perturb the
    old one);
  * chaos — segment dispatches ride the degraded ladder at
    ``score/dispatch`` and stay exact;
  * the estimator/model vertical — ``resultMode="segment"`` transform/
    detect, ``calibrate`` determinism + improvement, calibration
    persistence (bit-exact temperatures, explicit uncalibrated);
  * serving — batcher segment mode, knob/calibration-version cache
    isolation, the ``/detect?mode=segment`` HTTP surface, stream parity;
  * the ``--smoke-segment`` bench gate (trimmed in tier-1, full slow).
"""

import functools
import json

import numpy as np
import pytest

from spark_languagedetector_tpu import LanguageDetector, Table
from spark_languagedetector_tpu.api.runner import SEGMENT_CELL, BatchRunner
from spark_languagedetector_tpu.models.estimator import LanguageDetectorModel
from spark_languagedetector_tpu.ops import score as S
from spark_languagedetector_tpu.ops.encoding import texts_to_bytes
from spark_languagedetector_tpu.ops.vocab import EXACT, VocabSpec
from spark_languagedetector_tpu.resilience import faults
from spark_languagedetector_tpu.resilience.faults import FaultPlan
from spark_languagedetector_tpu.resilience.policy import (
    CircuitBreaker,
    RetryPolicy,
)
from spark_languagedetector_tpu.segment import (
    UNKNOWN,
    Calibration,
    SegmentOptions,
    fit_calibration,
    segment_documents,
    topk_decode,
)
from spark_languagedetector_tpu.segment.calibrate import (
    calibrated_probs,
    expected_calibration_error,
    normalize_scores,
)
from spark_languagedetector_tpu.segment.spans import (
    decode_cells,
    merge_spans,
    smooth_cells,
    snap_utf8,
)
from spark_languagedetector_tpu.telemetry import REGISTRY

RNG = np.random.default_rng(7)
LANGS = ("en", "de", "fr")


def _counter(name):
    return int(REGISTRY.snapshot()["counters"].get(name, 0))


@functools.lru_cache(maxsize=None)
def _fitted(seed=3, k=200):
    """Shared fitted 3-language model (runner jit programs compile per
    instance — share objects, pay the compiles once)."""
    import bench

    docs, labels = bench.make_corpus(list(LANGS), 45, mean_len=300,
                                     seed=seed)
    return LanguageDetector(list(LANGS), [1, 2, 3], k).fit(
        Table({"lang": labels, "fulltext": docs})
    )


@functools.lru_cache(maxsize=None)
def _heldout():
    import bench

    return bench.make_corpus(list(LANGS), 60, mean_len=250, seed=77)


def _calibrated(seed=3):
    model = _fitted(seed)
    if model.calibration is None:
        hd, hl = _heldout()
        model.calibrate(Table({"fulltext": hd, "lang": hl}))
    return model


# ------------------------------------------------------------- options ------
def test_segment_options_validation_and_key():
    with pytest.raises(ValueError):
        SegmentOptions(cell=200)  # not a multiple of 128
    with pytest.raises(ValueError):
        SegmentOptions(cell=0)
    with pytest.raises(ValueError):
        SegmentOptions(smooth=0)
    with pytest.raises(ValueError):
        SegmentOptions(top_k=0)
    with pytest.raises(ValueError):
        SegmentOptions(reject_threshold=1.0)
    with pytest.raises(ValueError):
        SegmentOptions(min_span_bytes=0)
    base = SegmentOptions()
    assert base.key() == SegmentOptions().key()
    # Every knob must move the key — the cache/coalesce isolation rides it.
    for other in (
        SegmentOptions(cell=512),
        SegmentOptions(smooth=5),
        SegmentOptions(top_k=1),
        SegmentOptions(reject_threshold=0.25),
        SegmentOptions(min_span_bytes=4),
    ):
        assert other.key() != base.key()


# ------------------------------------------------------ span decoding -------
def test_smooth_cells_is_clipped_box_mean():
    cells = np.array([[0.0, 3.0], [3.0, 0.0], [6.0, 3.0]])
    out = smooth_cells(cells, 3)
    np.testing.assert_allclose(out[0], [1.5, 1.5])   # rows 0..1
    np.testing.assert_allclose(out[1], [3.0, 2.0])   # rows 0..2
    np.testing.assert_allclose(out[2], [4.5, 1.5])   # rows 1..2
    np.testing.assert_array_equal(smooth_cells(cells, 1), cells)


def test_decode_cells_winner_and_margin():
    winners, margins = decode_cells(np.array([[1.0, 3.0, 2.0],
                                              [2.0, 2.0, 0.0]]))
    np.testing.assert_array_equal(winners, [1, 0])  # first-max tie rule
    np.testing.assert_allclose(margins, [1.0, 0.0])
    w1, m1 = decode_cells(np.array([[4.0], [2.0]]))
    np.testing.assert_array_equal(w1, [0, 0])
    np.testing.assert_array_equal(m1, [0.0, 0.0])


def test_snap_utf8_backs_off_continuation_bytes():
    doc = "aé京b".encode()  # 1 + 2 + 3 + 1 bytes
    assert snap_utf8(doc, 2) == 1   # inside é
    assert snap_utf8(doc, 4) == 3   # inside 京
    assert snap_utf8(doc, 5) == 3
    assert snap_utf8(doc, 3) == 3   # already a boundary
    assert snap_utf8(doc, 0) == 0
    # Arbitrary bytes can't walk the boundary more than 4 steps.
    junk = bytes([0x80] * 10)
    assert snap_utf8(junk, 9) == 5


def test_merge_spans_heals_lone_cell():
    cell = 128
    winners = np.array([0, 0, 0, 1, 0, 0])
    margins = np.array([2.0, 2.0, 2.0, 0.1, 2.0, 2.0])
    spans = merge_spans(
        winners, margins, cell=cell, doc_len=6 * cell,
        doc=b"x" * (6 * cell), min_span_bytes=256,
    )
    assert len(spans) == 1
    assert (spans[0].start, spans[0].end, spans[0].lang_id) == (0, 768, 0)


def test_merge_spans_property_fuzz():
    """Invariants: exact partition of [0, doc_len), min-span respected
    (single-span docs exempt), interior boundaries are UTF-8 character
    starts, adjacent spans differ in language."""
    rng = np.random.default_rng(5)
    alphabet = "ab é京ü"  # multi-byte characters on purpose
    for _ in range(60):
        cell = int(rng.choice([128, 256]))
        text = "".join(
            rng.choice(list(alphabet), size=rng.integers(1, 900))
        )
        doc = text.encode()
        n_cells = max(1, -(-len(doc) // cell))
        winners = rng.integers(0, 3, n_cells)
        margins = rng.random(n_cells)
        min_span = int(rng.choice([1, 16, 64, 300]))
        spans = merge_spans(
            winners, margins, cell=cell, doc_len=len(doc), doc=doc,
            min_span_bytes=min_span,
        )
        assert spans[0].start == 0
        assert spans[-1].end == len(doc)
        for a, b in zip(spans, spans[1:]):
            assert a.end == b.start          # no gap, no overlap
            assert a.lang_id != b.lang_id    # canonical
            assert (doc[b.start] & 0xC0) != 0x80  # char start
        if len(spans) > 1:
            for s in spans:
                # A snapped boundary can shave at most 3 bytes off the
                # nominal min-span (a UTF-8 char is ≤ 4 bytes).
                assert s.end - s.start >= min(min_span, cell) - 3


def test_topk_decode_order_reject_and_validation():
    probs = np.array([0.2, 0.5, 0.2, 0.1])
    langs = ["a", "b", "c", "d"]
    entries, label, rejected = topk_decode(probs, langs, 3, 0.0)
    assert [e["lang"] for e in entries] == ["b", "a", "c"]  # tie: index order
    assert label == "b" and not rejected
    entries, label, rejected = topk_decode(probs, langs, 99, 0.6)
    assert len(entries) == 4
    assert label == UNKNOWN and rejected
    with pytest.raises(ValueError):
        topk_decode(probs, ["a", "b"], 2, 0.0)


# ----------------------------------------------------------- calibration ----
def _synthetic_heldout(n=400, L=4, seed=9, scale=25.0):
    """Over-confident synthetic logits: true class biased, large scale so
    the T=1 softmax is ~one-hot while real accuracy is ~75%."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, L, n)
    s = rng.normal(size=(n, L))
    s[np.arange(n), y] += 0.7
    return s * scale, y


def test_fit_calibration_deterministic_and_improves():
    s, y = _synthetic_heldout()
    norm = normalize_scores(s, np.ones(len(s)))
    a = fit_calibration(norm, y, 4)
    b = fit_calibration(norm, y, 4)
    np.testing.assert_array_equal(a.temperatures, b.temperatures)
    assert a.version == b.version
    assert a.calibrated and a.meta["heldout_docs"] == len(y)
    assert a.meta["nll_after"] < a.meta["nll_before"]
    assert a.meta["ece_after"] < a.meta["ece_before"]
    assert a.meta["ece_after"] <= 0.10
    with pytest.raises(ValueError):
        fit_calibration(norm[:0], y[:0], 4)
    with pytest.raises(ValueError):
        fit_calibration(norm, np.full(len(y), 7), 4)


def test_calibration_identity_and_dict_roundtrip():
    ident = Calibration.identity(3)
    assert not ident.calibrated
    np.testing.assert_array_equal(ident.temperatures, 1.0)
    s, y = _synthetic_heldout(L=3)
    cal = fit_calibration(normalize_scores(s, np.ones(len(s))), y, 3)
    back = Calibration.from_dict(cal.to_dict())
    np.testing.assert_array_equal(back.temperatures, cal.temperatures)
    assert back.version == cal.version and back.meta == cal.meta
    tampered = cal.to_dict()
    tampered["temperatures"][0] *= 2.0
    with pytest.raises(ValueError):
        Calibration.from_dict(tampered)
    with pytest.raises(ValueError):
        Calibration(np.array([1.0, -1.0]))


def test_expected_calibration_error_hand_case():
    # Two perfectly-confident correct + two 0.6-confident wrong answers.
    probs = np.array([[1.0, 0.0], [1.0, 0.0], [0.6, 0.4], [0.6, 0.4]])
    y = np.array([0, 0, 1, 1])
    assert expected_calibration_error(probs, y, bins=10) == pytest.approx(
        0.5 * 0.0 + 0.5 * 0.6
    )


# --------------------------------------------------------- device parity ----
def _oracle_cells(runner, model, byte_docs, cell):
    """float64 host mirror of segment_cells on the runner's own tables."""
    w = np.asarray(runner.weights, dtype=np.float64)
    if runner.lut is None and runner.cuckoo is None:
        sorted_ids = None
    else:
        sorted_ids = np.asarray(model.profile.compacted().ids)
    return S.window_scores_numpy(byte_docs, w, sorted_ids, runner.spec, cell)


def _parity_docs(model):
    import bench

    docs, _ = bench.make_corpus(list(LANGS), 12, mean_len=300, seed=21)
    byte_docs = texts_to_bytes(docs)
    byte_docs += [
        b"", b"a", "köln 京都".encode(),
        bytes(RNG.integers(0, 256, 700).tolist()),
        b"x" * 9000,  # > max_chunk: exercises cell-aligned chunking
    ]
    return byte_docs


def test_segment_cells_matches_host_oracle_gather():
    model = _fitted()
    runner = model._get_runner()
    byte_docs = _parity_docs(model)
    for cell in (SEGMENT_CELL, 512):
        cells, scored = runner.segment_cells(byte_docs, cell=cell)
        assert scored == byte_docs  # no cap configured
        oracle = _oracle_cells(runner, model, byte_docs, cell)
        assert len(cells) == len(byte_docs)
        for got, want, doc in zip(cells, oracle, byte_docs):
            assert got.shape == (max(1, -(-len(doc) // cell)),
                                 len(LANGS))
            np.testing.assert_allclose(got, want, atol=1e-3)
    # Summing a doc's cells restores the whole-doc score (reduction-order
    # class).
    scores = runner.score(byte_docs)
    cells, _ = runner.segment_cells(byte_docs)
    np.testing.assert_allclose(
        np.stack([c.sum(axis=0) for c in cells]), scores,
        rtol=1e-4, atol=1e-2,
    )


def test_segment_cells_cuckoo_matches_host_oracle():
    det = LanguageDetector(["de", "en"], [1, 2, 3, 4, 5], 60).set_vocab_mode(
        "exact"
    )
    model = det.fit(Table({
        "lang": ["de", "en"],
        "fulltext": ["der schnelle braune fuchs springt über den hund",
                     "the quick brown fox jumps over the lazy dog"],
    }))
    runner = model._get_runner()
    assert runner.cuckoo is not None
    byte_docs = texts_to_bytes([
        "der hund", "the dog", "", "a", "ab", "abcd",
        "schöne vögel fliegen", "zzzz unrelated",
    ])
    cells, _ = runner.segment_cells(byte_docs)
    oracle = _oracle_cells(runner, model, byte_docs, SEGMENT_CELL)
    for got, want in zip(cells, oracle):
        np.testing.assert_allclose(got, want, atol=1e-3)


def test_segment_cells_fused_matches_gather():
    model = _fitted()
    gr = model._get_runner()
    fr = BatchRunner(
        weights=gr.weights, lut=gr.lut, cuckoo=gr.cuckoo, spec=gr.spec,
        strategy="fused",
    )
    assert fr.strategy == "fused"
    byte_docs = _parity_docs(model)[:8] + [b"", b"zz"]
    fused, _ = fr.segment_cells(byte_docs)
    gather, _ = gr.segment_cells(byte_docs)
    for a, b in zip(fused, gather):
        assert a.shape == b.shape
        np.testing.assert_allclose(a, b, atol=1e-3)


def test_segment_cells_validation_and_dedup_order():
    runner = _fitted()._get_runner()
    with pytest.raises(ValueError):
        runner.segment_cells([b"x"], cell=200)
    with pytest.raises(ValueError):
        runner.segment_cells([b"x"], cell=runner.max_chunk * 2)
    docs = [b"abab", b"zz", b"abab", b"", b"zz"]
    cells, scored = runner.segment_cells(docs)
    assert scored == docs  # duplicates restored in input order
    np.testing.assert_array_equal(cells[0], cells[2])
    np.testing.assert_array_equal(cells[1], cells[4])
    # A runner whose largest bucket equals the cell width has no
    # cell-aligned chunk stride (overlap eats it) — docs that fit in one
    # chunk still segment; only a doc that actually needs chunking is
    # refused.
    tight = BatchRunner(
        weights=runner.weights, lut=runner.lut, cuckoo=runner.cuckoo,
        spec=runner.spec, strategy="gather", length_buckets=(256,),
    )
    tight_cells, _ = tight.segment_cells([b"abab", b"z" * 256])
    assert [c.shape for c in tight_cells] == [(1, len(LANGS))] * 2
    np.testing.assert_allclose(tight_cells[0], cells[0], atol=1e-3)
    with pytest.raises(ValueError, match="needs chunking"):
        tight.segment_cells([b"x" * 300])


def test_whole_doc_mode_pinned_around_segment_traffic():
    """The acceptance pin: whole-doc scoring shares none of the segment
    dispatch programs — its bytes are identical before and after any
    segment traffic (gather strategy)."""
    model = _fitted()
    runner = model._get_runner()
    docs = _parity_docs(model)
    before = runner.score(docs)
    labels_before = model.transform(
        Table({"fulltext": [d.decode("utf-8", "ignore") for d in docs[:6]]})
    ).column("lang")
    runner.segment_cells(docs)
    segment_documents(runner, docs, LANGS)
    after = runner.score(docs)
    np.testing.assert_array_equal(np.asarray(before), np.asarray(after))
    labels_after = model.transform(
        Table({"fulltext": [d.decode("utf-8", "ignore") for d in docs[:6]]})
    ).column("lang")
    assert list(labels_before) == list(labels_after)


def test_segment_chaos_rides_degraded_ladder():
    """Transient dispatch faults in segment mode replay/degrade and stay
    exact — same contract as whole-doc scoring."""
    model = _fitted()
    base = model._get_runner()
    runner = BatchRunner(
        weights=base.weights, lut=base.lut, cuckoo=base.cuckoo,
        spec=base.spec,
        retry_policy=RetryPolicy(max_attempts=1, base_delay_s=0.0),
        breaker=CircuitBreaker(failure_threshold=3, cooldown_s=0.05),
    )
    docs = texts_to_bytes(["der hund läuft", "the dog runs", "chien"])
    want, _ = runner.segment_cells(docs)
    d0 = _counter("resilience/degraded_batches")
    with faults.plan_scope(FaultPlan.parse("score/dispatch:error@1")):
        got, _ = runner.segment_cells(docs)
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)  # host rung reads same tables
    assert _counter("resilience/degraded_batches") > d0
    assert runner._degraded_mode
    runner._degraded_mode = False


# ------------------------------------------------- decode orchestration -----
def test_segment_documents_result_shape_and_telemetry():
    model = _fitted()
    runner = model._get_runner()
    import bench

    seg_docs, truth = bench.make_codeswitch_corpus(list(LANGS), 4, seed=31)
    byte_docs = texts_to_bytes(seg_docs)
    d0, s0 = _counter("segment/docs"), _counter("segment/spans")
    results = segment_documents(runner, byte_docs, LANGS)
    assert _counter("segment/docs") == d0 + len(byte_docs)
    assert _counter("segment/spans") >= s0 + len(byte_docs)
    for r, doc in zip(results, byte_docs):
        assert set(r) == {"label", "rejected", "calibrated", "topk", "spans"}
        assert r["calibrated"] is False  # no calibration passed
        assert r["label"] in LANGS
        spans = r["spans"]
        assert spans[0]["start"] == 0 and spans[-1]["end"] == len(doc)
        for a, b in zip(spans, spans[1:]):
            assert a["end"] == b["start"]
        for sp in spans:
            assert 0.0 <= sp["confidence"] <= 1.0
    # The corpus is block-switched: the decode must actually find spans.
    assert sum(len(r["spans"]) for r in results) > len(results)


def test_segment_documents_reject_and_topk_knobs():
    model = _fitted()
    runner = model._get_runner()
    docs = texts_to_bytes(["the quick brown fox jumps over the lazy dog"])
    # Uncalibrated 3-language probs sit near 1/3 — a 0.9 floor rejects.
    rej = segment_documents(
        runner, docs, LANGS,
        options=SegmentOptions(reject_threshold=0.9),
    )[0]
    assert rej["label"] == UNKNOWN and rej["rejected"]
    assert len(rej["topk"]) == 3  # candidates still reported
    assert all(s["lang"] == UNKNOWN for s in rej["spans"])
    k1 = segment_documents(
        runner, docs, LANGS, options=SegmentOptions(top_k=1)
    )[0]
    assert len(k1["topk"]) == 1 and not k1["rejected"]
    with pytest.raises(ValueError):
        segment_documents(runner, docs, ["only-one"])  # language mismatch


# ------------------------------------------------------ estimator vertical --
def test_model_segment_transform_detect_and_defaults():
    model = _calibrated()
    seg = model.copy().set_result_mode("segment").set_top_k(2)
    seg.calibration = model.calibration
    texts = ["the quick brown fox", "der schnelle braune fuchs"]
    out = seg.transform(Table({"fulltext": texts}))
    parsed = [json.loads(v) for v in out.column("lang")]
    assert parsed == seg.segment(texts)
    assert all(len(r["topk"]) == 2 and r["calibrated"] for r in parsed)
    d = seg.detect(texts[0])
    assert isinstance(d, dict) and d["label"] == "en"
    # Label mode untouched by the segment params existing.
    assert model.detect(texts[1]) == "de"
    # Estimator stamps the params onto fitted models.
    import bench

    docs, labels = bench.make_corpus(list(LANGS), 9, mean_len=120, seed=2)
    det = LanguageDetector(list(LANGS), [1, 2], 50).set_result_mode(
        "segment"
    ).set_top_k(2).set_reject_threshold(0.1)
    fitted = det.fit(Table({"lang": labels, "fulltext": docs}))
    assert fitted.get("resultMode") == "segment"
    assert fitted.get("topK") == 2
    assert fitted.get("rejectThreshold") == 0.1
    with pytest.raises(ValueError):
        det.set_result_mode("nonsense")
    with pytest.raises(ValueError):
        det.set_reject_threshold(1.5)


def test_model_calibrate_deterministic_and_improves():
    model = _fitted(seed=5)
    hd, hl = _heldout()
    heldout = Table({"fulltext": hd, "lang": hl})
    model.calibrate(heldout)
    first = model.calibration
    model.calibrate(heldout)
    np.testing.assert_array_equal(
        first.temperatures, model.calibration.temperatures
    )
    assert model.calibration.meta["ece_after"] < (
        model.calibration.meta["ece_before"]
    )
    with pytest.raises(ValueError):
        model.calibrate(Table({"fulltext": ["x"], "lang": ["martian"]}))


def test_calibration_persists_with_model(tmp_path):
    model = _calibrated()
    seg = model.copy().set_result_mode("segment")
    seg.calibration = model.calibration
    path = str(tmp_path / "model")
    seg.save(path)
    back = LanguageDetectorModel.load(path)
    assert back.calibration is not None
    np.testing.assert_array_equal(
        back.calibration.temperatures, seg.calibration.temperatures
    )
    assert back.calibration.version == seg.calibration.version
    assert back.calibration.meta == seg.calibration.meta
    assert back.get("resultMode") == "segment"
    texts = ["the quick brown fox and der hund"]
    assert back.segment(texts) == seg.segment(texts)
    # Overwrite save (the two-rename swap path) stays loadable.
    seg.save(path)
    again = LanguageDetectorModel.load(path)
    assert again.calibration.version == seg.calibration.version


def test_uncalibrated_model_is_explicit_never_silent(tmp_path):
    model = _fitted(seed=11, k=60)
    assert model.calibration is None
    path = str(tmp_path / "uncal")
    model.save(path)
    back = LanguageDetectorModel.load(path)
    assert back.calibration is None
    r = segment_documents(
        back._get_runner(), texts_to_bytes(["hello there"]), LANGS
    )[0]
    assert r["calibrated"] is False


def test_save_model_crash_leaves_previous_tree(tmp_path, monkeypatch):
    """A save that dies mid-build must leave the PREVIOUS model intact
    at the path (tmp-tree + rename-aside, like api.pipeline saves)."""
    from spark_languagedetector_tpu.persist import io as pio

    model = _calibrated()
    path = str(tmp_path / "m")
    model.save(path)
    v0 = LanguageDetectorModel.load(path).calibration.version

    real = pio._write_parquet
    calls = {"n": 0}

    def dying(path_, table):
        calls["n"] += 1
        raise RuntimeError("disk died mid-save")

    monkeypatch.setattr(pio, "_write_parquet", dying)
    with pytest.raises(RuntimeError):
        model.save(path)
    monkeypatch.undo()
    assert calls["n"] == 1
    back = LanguageDetectorModel.load(path)  # old tree fully intact
    assert back.calibration.version == v0
    assert not list(tmp_path.glob(".m.tmp.*"))  # tmp cleaned up


def test_reference_layout_drops_calibration_explicitly(tmp_path):
    model = _calibrated()
    path = str(tmp_path / "ref")
    model.write().overwrite().reference_layout().save(path)
    back = LanguageDetectorModel.load(path)
    assert back.calibration is None  # dropped, never invented


# ----------------------------------------------------------------- serve ----
def test_batcher_segment_mode_and_knob_isolation():
    from spark_languagedetector_tpu.serve import ContinuousBatcher, ModelRegistry
    from spark_languagedetector_tpu.serve.cache import ScoreCache

    model = _calibrated()
    reg = ModelRegistry()
    reg.install(model)
    docs = texts_to_bytes(["the quick fox", "der hund", "chien et chat"])
    direct = segment_documents(
        model._get_runner(), docs, LANGS,
        options=SegmentOptions(), calibration=model.calibration,
    )
    direct_k1 = segment_documents(
        model._get_runner(), docs, LANGS,
        options=SegmentOptions(top_k=1), calibration=model.calibration,
    )
    cache = ScoreCache(max_rows=256, max_bytes=1 << 20)
    with ContinuousBatcher(
        reg, max_wait_ms=2, max_rows=64, cache=cache
    ) as b:
        assert b.segment(docs) == direct
        h0 = cache.stats()["hits"]
        assert b.segment(docs) == direct            # cache hit, identical
        assert cache.stats()["hits"] >= h0 + len(docs)
        assert b.segment(docs, SegmentOptions(top_k=1)) == direct_k1
        assert b.segment(docs) == direct            # k=1 didn't cross-answer
        # Numeric modes interleave cleanly with segment traffic.
        np.testing.assert_array_equal(
            b.submit(docs, want_labels=True).result().values,
            model._get_runner().predict_ids(docs),
        )
        np.testing.assert_array_equal(
            b.submit(docs).result().values, model._get_runner().score(docs)
        )
        # Zero-doc segment request answers immediately.
        assert b.submit(
            [], segment_options=SegmentOptions()
        ).result().values == []
        with pytest.raises(ValueError):
            b.submit(docs, want_labels=True,
                     segment_options=SegmentOptions())


def test_recalibration_changes_cache_version():
    """Same model object, new temperatures ⇒ new calibration version ⇒
    old cache entries unreachable (fresh misses, fresh results)."""
    from spark_languagedetector_tpu.serve import ContinuousBatcher, ModelRegistry
    from spark_languagedetector_tpu.serve.cache import ScoreCache

    model = _fitted(seed=13, k=80)
    hd, hl = _heldout()
    model.calibrate(Table({"fulltext": hd[:30], "lang": hl[:30]}))
    v_first = model.calibration.version
    reg = ModelRegistry()
    reg.install(model)
    docs = texts_to_bytes(["the quick fox jumps"])
    cache = ScoreCache(max_rows=64, max_bytes=1 << 20)
    with ContinuousBatcher(
        reg, max_wait_ms=2, max_rows=64, cache=cache
    ) as b:
        b.segment(docs)
        m0 = cache.stats()["misses"]
        model.calibrate(Table({"fulltext": hd, "lang": hl}))  # new temps
        assert model.calibration.version != v_first
        after = b.segment(docs)
        # New calibration version ⇒ the old entry is unreachable: the
        # lookup MISSES and recomputes under the new temperatures (the
        # decoded dicts may still coincide after rounding — the key
        # isolation, not the value, is the contract here).
        assert cache.stats()["misses"] > m0
        assert after == segment_documents(
            model._get_runner(), docs, LANGS,
            options=SegmentOptions(), calibration=model.calibration,
        )


def test_serve_http_segment_endpoint_and_defaults():
    from spark_languagedetector_tpu.serve.client import ServeClient, ServeHTTPError
    from spark_languagedetector_tpu.serve.registry import ModelRegistry
    from spark_languagedetector_tpu.serve.server import ServingServer

    model = _calibrated()
    reg = ModelRegistry()
    reg.install(model)
    srv = ServingServer(reg, port=0, max_wait_ms=2, max_rows=64).start()
    try:
        client = ServeClient(*srv.address)
        texts = ["the quick fox", "der schnelle fuchs"]
        res, meta = client.segment(texts)
        assert meta["mode"] == "segment"
        assert res == model.segment(texts)
        res1, _ = client.segment(texts, top_k=1)
        assert all(len(r["topk"]) == 1 for r in res1)
        rej, _ = client.segment(texts, reject_threshold=0.0)
        assert all(not r["rejected"] for r in rej)
        # Plain /detect keeps label mode for a label-mode model...
        labels, meta2 = client.detect(texts)
        assert labels == ["en", "de"] and "mode" not in meta2
        # ...and a bad knob is a 400, never a dispatch.
        with pytest.raises(ServeHTTPError) as ei:
            client.segment(texts, top_k=0)
        assert ei.value.status == 400
        with pytest.raises(ServeHTTPError) as ei:
            client.segment(texts, reject_threshold=2.0)
        assert ei.value.status == 400
    finally:
        srv.stop()
    # A segment-default model answers plain /detect with results dicts.
    seg = model.copy().set_result_mode("segment")
    seg.calibration = model.calibration
    reg2 = ModelRegistry()
    reg2.install(seg)
    srv2 = ServingServer(reg2, port=0, max_wait_ms=2, max_rows=64).start()
    try:
        client2 = ServeClient(*srv2.address)
        out, meta = client2.detect(["the quick fox"])
        assert meta["mode"] == "segment" and isinstance(out[0], dict)
    finally:
        srv2.stop()


def test_stream_segment_parity_with_batch():
    from spark_languagedetector_tpu.stream.microbatch import (
        memory_source,
        run_stream,
    )

    model = _calibrated()
    seg = model.copy().set_result_mode("segment")
    seg.calibration = model.calibration
    import bench

    seg_docs, _ = bench.make_codeswitch_corpus(list(LANGS), 6, seed=41)
    want = seg.transform(Table({"fulltext": seg_docs})).column("lang")
    got_tables = []
    query = run_stream(
        seg, memory_source([{"fulltext": t} for t in seg_docs], 2),
        got_tables.append,
    )
    got = [v for t in got_tables for v in t.column("lang").tolist()]
    assert got == list(want)
    assert query.batches == 3


# ------------------------------------------------------ regression guard ----
def test_compare_tracks_segment_reject_rate():
    from spark_languagedetector_tpu.telemetry.compare import (
        capture_stats,
        compare_captures,
    )

    def capture(docs, rejects):
        return [
            {"event": "telemetry.span", "path": "segment/merge",
             "wall_s": 0.01},
            {"event": "telemetry.snapshot",
             "counters": {"segment/docs": docs, "segment/rejects": rejects},
             "gauges": {}, "histograms": {}},
        ]

    base = capture_stats(capture(100, 0))
    worse = capture_stats(capture(100, 20))
    assert base["tracked"]["segment/reject_rate"] == 0.0
    # 0 -> 0.2: the appearance itself regresses (zero baseline).
    _, regressions = compare_captures(base, worse, threshold=0.25)
    assert any("segment/reject_rate" in r for r in regressions)
    # Drift up past threshold regresses; drift down never does.
    b2 = capture_stats(capture(100, 10))
    w2 = capture_stats(capture(100, 20))
    _, regressions = compare_captures(b2, w2, threshold=0.25)
    assert any("segment/reject_rate" in r for r in regressions)
    _, regressions = compare_captures(w2, b2, threshold=0.25)
    assert not any("segment/reject_rate" in r for r in regressions)


# ------------------------------------------------------- bench smoke gate ---
def test_bench_smoke_segment_trimmed(tmp_path):
    """Tier-1-sized segmentation smoke: span F1, calibration ECE, top-k,
    stream parity, fleet hot-swap staleness, and the whole-doc pin — all
    five gates hard even in the trimmed size."""
    import bench

    result = bench.smoke_segment(str(tmp_path / "segment.jsonl"),
                                 trimmed=True)
    assert result["ok"], result["errors"]
    assert result["span_f1"] >= 0.85
    assert result["topk_hit"] >= 0.98
    assert result["calibration"]["ece_calibrated"] <= 0.10
    assert result["calibration"]["ece_calibrated"] < (
        result["calibration"]["ece_uncalibrated"]
    )
    assert result["fleet"]["stale_or_cross_mode"] == 0
    assert result["fleet"]["cache_hits"] > 0
    assert result["stream"]["parity"] == 1.0
    assert result["whole_doc_bit_identical"]
    assert result["segment_counters"]["docs"] > 0


@pytest.mark.slow
def test_bench_smoke_segment_full(tmp_path):
    """The full CI gate (slow-marked: tier-1 runs the trimmed variant)."""
    import bench

    result = bench.smoke_segment(str(tmp_path / "segment_full.jsonl"))
    assert result["ok"], result["errors"]
