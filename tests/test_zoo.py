"""Multi-tenant model zoo (ISSUE 14, docs/SERVING.md §12).

The acceptance contract: tenant routing answers every request from
exactly the named tenant's model (no tenant ⇒ the default tenant,
bit-identically to the single-model surface), residency stays under its
budgets by paging LRU *idle* tenants (never a leased version) with cold
reloads preserving per-tenant parity, a noisy tenant's burst sheds that
tenant only, a failed cold load is a 503 + Retry-After for that tenant
only (deterministic replay via the ``zoo/load`` fault site), and a
tenant-scoped refit moves exactly one tenant's serving pointer.
"""

import itertools
import json
import threading

import numpy as np
import pytest

from spark_languagedetector_tpu import LanguageDetectorModel
from spark_languagedetector_tpu.exec.core import AdmissionQueue
from spark_languagedetector_tpu.ops.encoding import texts_to_bytes
from spark_languagedetector_tpu.resilience import faults
from spark_languagedetector_tpu.resilience.faults import FaultPlan
from spark_languagedetector_tpu.serve.client import ServeClient, ServeHTTPError
from spark_languagedetector_tpu.serve.server import ServingServer
from spark_languagedetector_tpu.telemetry import REGISTRY
from spark_languagedetector_tpu.zoo import (
    ModelZoo,
    TenantLoadShed,
    TenantQuota,
    UnknownTenant,
    ZooError,
)

LANGS = ("l0", "l1", "l2")
TEXTS = ["abab", "xyxy", "ddcc", "axdy"]


def _model(seed: int) -> LanguageDetectorModel:
    """A small, tenant-distinct model: same spec (one compile cache
    entry across every tenant), seeded weights."""
    rng = np.random.default_rng(seed)
    gram_map = {
        (a + b).encode(): rng.random(len(LANGS)).tolist()
        for a, b in itertools.product("abcdxyz", repeat=2)
    }
    return LanguageDetectorModel.from_gram_map(gram_map, [2], LANGS)


def _zoo(**kw) -> ModelZoo:
    kw.setdefault("max_wait_ms", 2)
    kw.setdefault("max_rows", 64)
    return ModelZoo(**kw)


def _labels(model, texts=TEXTS):
    return model._get_runner().predict_ids(texts_to_bytes(texts))


def _counter(name: str) -> int:
    return int(REGISTRY.snapshot()["counters"].get(name, 0))


# ------------------------------------------------------------- routing ------
def test_zoo_routes_each_tenant_to_its_own_model():
    zoo = _zoo()
    models = {f"t{i}": _model(i) for i in range(3)}
    for name, model in models.items():
        zoo.add_tenant(name, model)
    zoo.add_tenant("default", _model(9))
    try:
        for name, model in models.items():
            entry, rt = zoo.runtime(name)
            assert entry.name == name
            got = rt.batcher.submit(
                texts_to_bytes(TEXTS), want_labels=True
            ).result()
            np.testing.assert_array_equal(got.values, _labels(model))
            assert got.version == "v1"
        # No tenant ⇒ the default tenant.
        entry, rt = zoo.runtime(None)
        assert entry.name == "default"
    finally:
        zoo.close()


def test_zoo_unknown_tenant_and_name_validation():
    zoo = _zoo()
    zoo.add_tenant("default", _model(0))
    try:
        with pytest.raises(UnknownTenant):
            zoo.runtime("nope")
        # UnknownTenant is a ValueError: the HTTP surface answers 400.
        assert issubclass(UnknownTenant, ValueError)
        for bad in ("Tenant", "a b", "", "x" * 65, 7):
            with pytest.raises((UnknownTenant, ValueError)):
                zoo.add_tenant(bad, _model(1))
        with pytest.raises(ValueError):
            zoo.add_tenant("default", _model(1))  # duplicate
        with pytest.raises(ValueError):
            zoo.add_tenant("both")  # neither model nor path
    finally:
        zoo.close()


def test_cross_tenant_mapping_corruption_is_rejected_and_counted():
    """The structural guard: a runtime that is not the named tenant's is
    never allowed to answer — rejected + counted, so the compare guard
    sees any appearance as a regression."""
    zoo = _zoo()
    zoo.add_tenant("a", _model(1))
    zoo.add_tenant("b", _model(2))
    try:
        _, rt_b = zoo.runtime("b")
        before = _counter("zoo/cross_tenant_rejects")
        zoo._entries["a"].runtime = rt_b  # simulated bookkeeping bug
        with pytest.raises(ZooError):
            zoo.runtime("a")
        assert _counter("zoo/cross_tenant_rejects") == before + 1
        zoo._entries["a"].runtime = None
    finally:
        zoo.close()


# ----------------------------------------------------------- residency ------
def test_residency_budget_evicts_lru_and_cold_reload_keeps_parity():
    REGISTRY.reset()
    zoo = _zoo(resident_models=2)
    models = {f"t{i}": _model(i) for i in range(4)}
    for name, model in models.items():
        zoo.add_tenant(name, model)
    try:
        docs = texts_to_bytes(TEXTS)
        for name in ("t0", "t1", "t2", "t3"):
            zoo.runtime(name)
        # LRU under a 2-model budget: only the last two stay resident.
        assert list(zoo.resident()) == ["t2", "t3"]
        assert _counter("zoo/evictions") == 2
        # Cold reload of an evicted tenant: parity preserved, version
        # preserved, loads counted.
        entry, rt = zoo.runtime("t0")
        assert entry.loads == 2 and entry.version == "v1"
        got = rt.batcher.submit(docs, want_labels=True).result().values
        np.testing.assert_array_equal(got, _labels(models["t0"]))
        assert _counter("zoo/cold_loads") == 5
        gauges = REGISTRY.snapshot()["gauges"]
        assert gauges["langdetect_zoo_resident_models"][""] == 2.0
        assert gauges["langdetect_zoo_resident_bytes"][""] > 0
    finally:
        zoo.close()


def test_byte_budget_evicts_and_single_tenant_may_exceed_it():
    one = _model(0)
    bytes_of_one = None
    zoo = _zoo()
    zoo.add_tenant("probe", one)
    try:
        _, rt = zoo.runtime("probe")
        bytes_of_one = rt.table_bytes
    finally:
        zoo.close()
    assert bytes_of_one and bytes_of_one > 0
    # Budget fits ~1.5 models: the second admit must evict the first;
    # a single over-budget tenant still serves (transient over-budget
    # beats an unservable tenant).
    zoo = _zoo(resident_bytes=int(bytes_of_one * 1.5))
    zoo.add_tenant("a", _model(1))
    zoo.add_tenant("b", _model(2))
    try:
        zoo.runtime("a")
        zoo.runtime("b")
        assert list(zoo.resident()) == ["b"]
        zoo.runtime("a")
        assert list(zoo.resident()) == ["a"]
    finally:
        zoo.close()


def test_eviction_never_touches_a_leased_version():
    """A held lease pins its tenant: the LRU must skip it (transiently
    over budget) and evict it only after release."""
    zoo = _zoo(resident_models=1)
    zoo.add_tenant("leased", _model(1))
    zoo.add_tenant("other", _model(2))
    try:
        _, rt = zoo.runtime("leased")
        with rt.registry.lease():
            zoo.runtime("other")
            # Over budget, but the leased tenant stayed resident.
            assert set(zoo.resident()) == {"leased", "other"}
            assert zoo._entries["leased"].runtime is rt
        # Lease released: the next admit can finally page it out.
        zoo.add_tenant("third", _model(3))
        zoo.runtime("third")
        assert "leased" not in zoo.resident()
    finally:
        zoo.close()


def test_preload_warms_tenants_off_the_serving_path():
    zoo = _zoo()
    for i in range(3):
        zoo.add_tenant(f"t{i}", _model(i))
    try:
        assert zoo.resident() == {}
        loaded = zoo.preload()
        assert loaded == ["t0", "t1", "t2"]
        assert set(zoo.resident()) == {"t0", "t1", "t2"}
        assert zoo.preload() == []  # idempotent
    finally:
        zoo.close()


# ------------------------------------------------------------ cold load -----
def test_zoo_load_fault_degrades_to_tenant_only_shed_and_replays():
    """An injected ``zoo/load`` error fails ONE tenant's cold load as an
    explicit shed (503 semantics: ServeOverloaded subclass with a
    Retry-After) while other tenants keep serving; the call counter
    advances, so the schedule replays deterministically."""
    for _ in range(2):  # identical plan ⇒ identical outcome
        zoo = _zoo()
        zoo.add_tenant("cold", _model(1))
        zoo.add_tenant("warm", _model(2))
        try:
            with faults.plan_scope(FaultPlan.parse("zoo/load:error@1")):
                before = _counter("zoo/load_errors")
                with pytest.raises(TenantLoadShed) as exc:
                    zoo.runtime("cold")
                assert exc.value.retry_after_s > 0
                assert exc.value.tenant == "cold"
                assert exc.value.reason == "cold_load"
                assert _counter("zoo/load_errors") == before + 1
                # Neighbor unaffected (its load is call 2 of the site).
                entry, _ = zoo.runtime("warm")
                assert entry.name == "warm"
                # The failed tenant's retry reloads cleanly (call 3).
                entry, rt = zoo.runtime("cold")
                got = rt.batcher.submit(
                    texts_to_bytes(TEXTS), want_labels=True
                ).result().values
                np.testing.assert_array_equal(got, _labels(_model(1)))
        finally:
            zoo.close()


def test_disk_backed_tenant_pages_fully_and_reloads(tmp_path):
    model = _model(5)
    path = str(tmp_path / "m")
    model.save(path)
    zoo = _zoo(resident_models=1)
    zoo.add_tenant("disk", path=path)
    zoo.add_tenant("mem", _model(6))
    try:
        entry, rt = zoo.runtime("disk")
        want = rt.batcher.submit(
            texts_to_bytes(TEXTS), want_labels=True
        ).result().values
        zoo.runtime("mem")  # evicts "disk"
        # Clean disk-backed tenant paged out model and all.
        assert entry.runtime is None and entry.model is None
        _, rt2 = zoo.runtime("disk")
        got = rt2.batcher.submit(
            texts_to_bytes(TEXTS), want_labels=True
        ).result().values
        np.testing.assert_array_equal(got, want)
    finally:
        zoo.close()


# ------------------------------------------------------- quota lanes --------
def test_noisy_tenant_sheds_itself_never_neighbors():
    zoo = _zoo(
        max_wait_ms=40, max_rows=8,
        resident_models=None,
    )
    zoo.add_tenant(
        "noisy", _model(1), quota=TenantQuota(max_queue_rows=8)
    )
    zoo.add_tenant("victim", _model(2))
    try:
        from spark_languagedetector_tpu.serve.batcher import (
            BULK,
            ServeOverloaded,
        )

        _, noisy = zoo.runtime("noisy")
        _, victim = zoo.runtime("victim")
        docs = texts_to_bytes(["abab"] * 4)
        sheds = 0
        futs = []
        for _ in range(10):  # 40 rows into an 8-row quota lane
            try:
                futs.append(noisy.batcher.submit(docs, priority=BULK))
            except ServeOverloaded as e:
                assert e.reason == "queue_full"
                sheds += 1
        assert sheds >= 1
        # The victim's lane is untouched: admits fine, zero sheds.
        v = victim.batcher.submit(docs).result()
        assert v.values.shape == (4, len(LANGS))
        assert victim.batcher.stats()["shed_requests"] == 0
        noisy_stats = noisy.batcher.stats()
        assert noisy_stats["shed_requests"] == sheds
        assert noisy_stats["shed_reasons"].get("queue_full") == sheds
        assert _counter("zoo/shed/noisy") >= sheds
        assert _counter("zoo/shed/victim") == 0
        for f in futs:
            f.result()
    finally:
        zoo.close()


def test_admission_queue_local_shed_accounting():
    """The exec-core half: every shed (except lifecycle "closed") lands
    in the queue's own tallies, so a multi-queue front end can attribute
    rejections without global counters."""
    q = AdmissionQueue(max_rows=4, max_wait_s=0.01, max_queue_rows=4)
    assert q.admit("a", 4, "interactive") == (None, 0.0)
    reason, _ = q.admit("b", 4, "interactive")
    assert reason == "queue_full"
    stats = q.stats()
    assert stats["shed_requests"] == 1
    assert stats["shed_rows"] == 4
    assert stats["shed_reasons"] == {"queue_full": 1}
    q.close(drain=False)
    assert q.admit("c", 1, "interactive")[0] == "closed"
    assert q.stats()["shed_requests"] == 1  # closed is not a shed


# -------------------------------------------------------- tenant refit ------
def test_tenant_scoped_refit_swaps_exactly_one_tenant():
    from spark_languagedetector_tpu import LanguageDetector, Table

    zoo = _zoo()
    zoo.add_tenant("learn", _model(1))
    zoo.add_tenant("still", _model(2))
    try:
        zoo.preload()
        est = LanguageDetector(list(LANGS), [1, 2], 80)
        docs = ["aaa bab"] * 5 + ["xxy yxy"] * 5 + ["dcd cdd"] * 5
        labs = ["l0"] * 5 + ["l1"] * 5 + ["l2"] * 5
        ar = zoo.auto_refit(
            "learn", est, refit_every_batches=1, final_refit=False
        )
        ar.run([Table({"lang": labs, "fulltext": docs})], max_batches=1)
        assert zoo.version("learn") == "v2"
        assert zoo.version("still") == "v1"
        entry, rt = zoo.runtime("learn")
        served = rt.registry.peek()
        assert served.version == "v2"
        meta = served.describe()["metadata"]
        assert meta["tenant"] == "learn"
        assert meta["refit_token"] == 1
        got = rt.batcher.submit(
            texts_to_bytes(TEXTS), want_labels=True
        ).result()
        np.testing.assert_array_equal(
            got.values, _labels(ar.last_model)
        )
        # The neighbor still answers from its own v1.
        _, rt2 = zoo.runtime("still")
        got2 = rt2.batcher.submit(
            texts_to_bytes(TEXTS), want_labels=True
        ).result()
        assert got2.version == "v1"
        np.testing.assert_array_equal(got2.values, _labels(_model(2)))
    finally:
        zoo.close()


def test_rollback_then_eviction_reloads_the_rolled_back_model():
    """The paged state must resync on rollback: after install(v2) +
    rollback(v1), an eviction + cold reload has to rebuild the v1 MODEL
    under the v1 name — never the v2 model wearing v1's version."""
    m1, m2 = _model(1), _model(7)
    zoo = _zoo(resident_models=1)
    zoo.add_tenant("a", m1)
    zoo.add_tenant("b", _model(2))
    try:
        zoo.runtime("a")
        assert zoo.install("a", m2) == "v2"
        assert zoo.rollback("a") == "v1"
        zoo.runtime("b")  # pages "a" out
        assert zoo._entries["a"].runtime is None
        _, rt = zoo.runtime("a")  # cold reload of the rolled-back state
        got = rt.batcher.submit(
            texts_to_bytes(TEXTS), want_labels=True
        ).result()
        assert got.version == "v1"
        np.testing.assert_array_equal(got.values, _labels(m1))
        # The next install still gets a fresh name (v2 is burnt).
        assert zoo.install("a", _model(8)) == "v3"
    finally:
        zoo.close()


def test_install_on_paged_out_tenant_lands_on_next_cold_load():
    zoo = _zoo(resident_models=1)
    zoo.add_tenant("a", _model(1))
    zoo.add_tenant("b", _model(2))
    try:
        zoo.runtime("a")
        zoo.runtime("b")  # pages "a" out
        assert list(zoo.resident()) == ["b"]
        new_model = _model(7)
        version = zoo.install("a", new_model)
        assert version == "v2" and zoo.version("a") == "v2"
        entry, rt = zoo.runtime("a")  # cold load builds v2 directly
        got = rt.batcher.submit(
            texts_to_bytes(TEXTS), want_labels=True
        ).result()
        assert got.version == "v2"
        np.testing.assert_array_equal(got.values, _labels(new_model))
    finally:
        zoo.close()


# ---------------------------------------------------------- HTTP surface ----
@pytest.fixture
def zoo_http():
    zoo = _zoo()
    zoo.add_tenant("default", _model(0))
    zoo.add_tenant("acme", _model(1))
    server = ServingServer(zoo, port=0).start()
    try:
        yield zoo, server, ServeClient(*server.address)
    finally:
        server.stop()


def test_http_tenant_routing_and_response_stamp(zoo_http):
    zoo, server, client = zoo_http
    labels, meta = client.detect(TEXTS, tenant="acme")
    assert meta["tenant"] == "acme" and meta["version"] == "v1"
    want = [LANGS[int(i)] for i in _labels(_model(1))]
    assert labels == want
    # A tenant-pinned client stamps every call.
    pinned = ServeClient(*server.address, tenant="acme")
    labels2, meta2 = pinned.detect(TEXTS)
    assert labels2 == want and meta2["tenant"] == "acme"
    scores, meta3 = pinned.score(TEXTS)
    np.testing.assert_array_equal(
        scores, _model(1)._get_runner().score(texts_to_bytes(TEXTS))
    )
    with pytest.raises(ServeHTTPError) as exc:
        client.detect(TEXTS, tenant="nope")
    assert exc.value.status == 400


def test_http_no_tenant_resolves_default_bit_identically(zoo_http):
    """Backward compat: the tenant-less wire against a zoo answers from
    the default tenant with EXACTLY the single-model server's bytes."""
    zoo, server, client = zoo_http
    single = ServingServer(_model(0), port=0).start()
    try:
        sclient = ServeClient(*single.address)
        scores_zoo, meta_zoo = client.score(TEXTS)
        scores_single, _ = sclient.score(TEXTS)
        np.testing.assert_array_equal(scores_zoo, scores_single)
        assert meta_zoo["tenant"] == "default"
        labels_zoo, _ = client.detect(TEXTS)
        labels_single, _ = sclient.detect(TEXTS)
        assert labels_zoo == labels_single
    finally:
        single.stop()


def test_http_single_model_server_rejects_tenant_loudly():
    server = ServingServer(_model(0), port=0).start()
    try:
        client = ServeClient(*server.address)
        with pytest.raises(ServeHTTPError) as exc:
            client.detect(TEXTS, tenant="acme")
        assert exc.value.status == 400
        # Admin surface too: a tenant-named swap/rollback against a
        # single-model server must 400, never mutate the one model.
        with pytest.raises(ServeHTTPError) as exc:
            client.swap("/nowhere", tenant="acme")
        assert exc.value.status == 400
        with pytest.raises(ServeHTTPError) as exc:
            client.rollback(tenant="acme")
        assert exc.value.status == 400
    finally:
        server.stop()


def test_http_healthz_varz_carry_per_tenant_blocks(zoo_http):
    zoo, server, client = zoo_http
    client.detect(TEXTS, tenant="acme")
    h = client.healthz()
    assert set(h["zoo"]["tenants"]) == {"default", "acme"}
    acme = h["zoo"]["tenants"]["acme"]
    assert acme["resident"] is True and acme["version"] == "v1"
    assert "shed_requests" in acme["batcher"]
    assert h["zoo"]["residency"]["resident_models"] >= 1
    ready = client.readyz()
    assert ready["ready"] is True and ready["tenants"] == 2
    v = client.varz()
    assert v["zoo"]["tenants"]["acme"]["versions"][0]["version"] == "v1"
    assert "langdetect_zoo_resident_models" in v["gauges"]


def test_http_admin_swap_and_rollback_tenant_scoped(zoo_http, tmp_path):
    zoo, server, client = zoo_http
    m = _model(7)
    m.save(str(tmp_path / "m"))
    # Make "acme" resident first: rollback walks the LIVE registry's
    # history, which (documented) does not survive paging.
    client.detect(TEXTS, tenant="acme")
    version = client.swap(str(tmp_path / "m"), tenant="acme")
    assert version == "v2"
    assert zoo.version("acme") == "v2" and zoo.version("default") == "v1"
    labels, meta = client.detect(TEXTS, tenant="acme")
    assert meta["version"] == "v2"
    assert labels == [LANGS[int(i)] for i in _labels(m)]
    assert client.rollback(tenant="acme") == "v1"
    assert zoo.version("acme") == "v1" and zoo.version("default") == "v1"


def test_router_front_forwards_tenant_to_replica(zoo_http):
    """The fleet tier carries the tenant name untouched: a RouterServer
    over a zoo-backed replica answers per tenant like a direct client."""
    from spark_languagedetector_tpu.serve.router import (
        FleetRouter,
        RouterServer,
    )

    zoo, server, client = zoo_http
    router = FleetRouter([server.address], probe_interval_ms=50.0)
    front = RouterServer(router, port=0)
    router.start(probe=False)
    front.start()
    try:
        rclient = ServeClient(*front.address, tenant="acme")
        labels, meta = rclient.detect(TEXTS)
        assert meta["tenant"] == "acme"
        assert labels == [LANGS[int(i)] for i in _labels(_model(1))]
        labels0, meta0 = ServeClient(*front.address).detect(TEXTS)
        assert meta0["tenant"] == "default"
        assert labels0 == [LANGS[int(i)] for i in _labels(_model(0))]
        # The fleet admin surface is whole-fleet: a tenant-named
        # swap/rollback is a loud 400, never a silent default-model
        # mutation.
        with pytest.raises(ServeHTTPError) as exc:
            rclient.swap("/nowhere")  # tenant-pinned client stamps it
        assert exc.value.status == 400
        with pytest.raises(ServeHTTPError) as exc:
            rclient.rollback()
        assert exc.value.status == 400
    finally:
        front.stop()
        router.close()


def test_http_eviction_race_is_answered_not_dropped(zoo_http):
    """A request racing its tenant's eviction re-resolves through the
    cold-load path — answered, never dropped or cross-answered."""
    zoo, server, client = zoo_http
    stop = threading.Event()
    errors = []

    def churn():
        # Evict whenever the tenant is idle — paced like a real admit-
        # driven eviction, not a pathological lock-spin (a request that
        # loses the race re-resolves through the cold-load path).
        import time as _time

        while not stop.is_set():
            with zoo._lock:
                if zoo._evictable_locked("acme"):
                    zoo._evict_locked("acme")
                    zoo._residency.drop("acme")
            zoo._finish_evictions()
            _time.sleep(0.001)

    t = threading.Thread(target=churn, daemon=True)
    t.start()
    try:
        want = [LANGS[int(i)] for i in _labels(_model(1))]
        for _ in range(20):
            labels, meta = client.detect(TEXTS, tenant="acme")
            if labels != want:
                errors.append(labels)
        assert not errors
    finally:
        stop.set()
        t.join(timeout=5)


# ------------------------------------------------------- compare guard ------
def _zoo_capture(path, counters):
    events = [
        {
            "event": "telemetry.span", "ts": 1.0, "path": "serve/dispatch",
            "wall_s": 0.01,
        },
        {
            "event": "telemetry.snapshot", "ts": 2.0,
            "counters": counters, "gauges": {}, "histograms": {},
        },
    ]
    path.write_text("".join(json.dumps(ev) + "\n" for ev in events))


def test_compare_cross_tenant_reject_appearance_regresses(tmp_path, capsys):
    """Fixture-pinned direction (like the fleet counters): a
    cross-tenant reject appearing against a zero baseline IS the
    regression — no threshold can excuse it."""
    from spark_languagedetector_tpu.telemetry.compare import main as c_main

    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    _zoo_capture(a, {})
    _zoo_capture(b, {"zoo/cross_tenant_rejects": 1})
    assert c_main([str(a), str(b)]) == 1
    assert "zoo/cross_tenant_rejects" in capsys.readouterr().out
    capsys.readouterr()
    assert c_main([str(b), str(b)]) == 0  # steady state passes


def test_compare_per_tenant_shed_family_regresses(tmp_path, capsys):
    from spark_languagedetector_tpu.telemetry.compare import main as c_main

    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    _zoo_capture(a, {})
    _zoo_capture(b, {"zoo/shed/noisy": 12})
    assert c_main([str(a), str(b)]) == 1
    assert "zoo/shed/noisy" in capsys.readouterr().out


def test_compare_evictions_and_cold_loads_are_informational(tmp_path, capsys):
    """Paging under a budget is normal life: evictions/cold loads are
    shown in the diff but NEVER gate — in either direction, from any
    baseline."""
    from spark_languagedetector_tpu.telemetry.compare import main as c_main

    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    _zoo_capture(a, {"zoo/evictions": 2, "zoo/cold_loads": 4})
    _zoo_capture(b, {"zoo/evictions": 40, "zoo/cold_loads": 80})
    assert c_main([str(a), str(b)]) == 0
    out = capsys.readouterr().out
    assert "zoo/evictions" in out and "informational" in out
    capsys.readouterr()
    empty = tmp_path / "empty.jsonl"
    _zoo_capture(empty, {})
    assert c_main([str(empty), str(a)]) == 0  # appearance: still fine
    assert "informational" in capsys.readouterr().out


# ------------------------------------------------------------- bench --------
def test_bench_smoke_zoo_trimmed(tmp_path):
    """The tier-1-sized --smoke-zoo: every hard gate (parity, zero
    cross-tenant answers, evictions + cold reloads with leases
    respected, noisy-neighbor isolation, tenant-scoped refit) holds at
    the trimmed tenant count."""
    import bench

    result = bench.smoke_zoo(str(tmp_path / "zoo.jsonl"), trimmed=True)
    assert result["ok"], result
    assert result["argmax_parity"] == 1.0
    assert result["cross_tenant_rejects"] == 0
    assert result["evictions"] >= 1
    assert result["cold_reloads"] >= 1
    assert result["noisy"]["noisy_sheds"] >= 1
    assert result["noisy"]["victim_sheds"] == 0
    assert result["refit"]["swapped_tenant_versions"] == 1


@pytest.mark.slow
def test_bench_smoke_zoo_full(tmp_path):
    """The full ~32-tenant CI gate (slow-marked: tier-1 runs trimmed)."""
    import bench

    result = bench.smoke_zoo(str(tmp_path / "zoo_full.jsonl"))
    assert result["ok"], result
    assert result["tenants"] >= 32
