"""Device fit parity: streaming dense-count fit == host numpy fit."""

import numpy as np
import pytest

from spark_languagedetector_tpu import LanguageDetector, Table
from spark_languagedetector_tpu.ops.fit import PARITY, COUNTS, fit_profile_numpy
from spark_languagedetector_tpu.ops.fit_tpu import fit_profile_device
from spark_languagedetector_tpu.ops.vocab import EXACT, HASHED, VocabSpec


def _corpus(rng, n_docs, n_langs, max_len=120):
    docs, langs = [], []
    for i in range(n_docs):
        ln = int(rng.integers(0, max_len))
        docs.append(bytes(rng.integers(97, 105, ln, dtype=np.uint8)))
        langs.append(i % n_langs)
    return docs, np.asarray(langs)


@pytest.mark.parametrize(
    "spec,weight_mode",
    [
        (VocabSpec(EXACT, (1, 2)), PARITY),
        (VocabSpec(EXACT, (2,)), COUNTS),
        (VocabSpec(HASHED, (1, 2, 3), hash_bits=12), PARITY),
    ],
)
def test_matches_numpy_fit(spec, weight_mode):
    rng = np.random.default_rng(3)
    docs, langs = _corpus(rng, 40, 3)
    docs += [b"", b"x"]  # empty + shorter-than-gram docs
    langs = np.concatenate([langs, [0, 1]])
    want_ids, want_w = fit_profile_numpy(docs, langs, 3, spec, 25, weight_mode)
    got_ids, got_w = fit_profile_device(
        docs, langs, 3, spec, 25, weight_mode, batch_rows=16
    )
    np.testing.assert_array_equal(got_ids, want_ids)
    np.testing.assert_allclose(got_w, want_w, rtol=1e-6, atol=1e-7)


def test_profile_size_larger_than_vocab():
    """profile_size > #occurring grams keeps exactly the occurring grams."""
    spec = VocabSpec(EXACT, (1,))
    docs = [b"ab", b"ba", b"c"]
    langs = np.asarray([0, 0, 1])
    got_ids, _ = fit_profile_device(docs, langs, 2, spec, 10_000)
    want_ids, _ = fit_profile_numpy(docs, langs, 2, spec, 10_000)
    np.testing.assert_array_equal(got_ids, want_ids)
    assert set(got_ids.tolist()) == {ord("a"), ord("b"), ord("c")}


def test_estimator_fit_backend_device_end_to_end():
    rows = {
        "lang": ["de"] * 3 + ["en"] * 3,
        "fulltext": [
            "der schnelle braune fuchs",
            "das ist ja sehr schön",
            "noch ein deutscher satz",
            "the quick brown fox",
            "that is very nice",
            "one more english sentence",
        ],
    }
    cpu = LanguageDetector(["de", "en"], [2], 100).fit(Table(rows))
    dev = (
        LanguageDetector(["de", "en"], [2], 100)
        .set_fit_backend("device")
        .fit(Table(rows))
    )
    assert set(dev.gram_probabilities) == set(cpu.gram_probabilities)
    for g, v in cpu.gram_probabilities.items():
        np.testing.assert_allclose(dev.gram_probabilities[g], v, rtol=1e-6)
    out = dev.transform(Table({"fulltext": ["ein schöner deutscher text"]}))
    assert list(out.column("lang")) == ["de"]


@pytest.mark.parametrize("weight_mode", [PARITY, COUNTS])
def test_split_fit_matches_numpy_exact_long_grams(weight_mode):
    """Exact n=1..5 device fit (split: device n<=3 + host n>=4) must equal
    the pure host fit bit-for-bit — including short docs whose partial
    windows straddle the split (1..4-byte docs)."""
    from spark_languagedetector_tpu.ops.fit_tpu import fit_profile_device_split

    spec = VocabSpec(EXACT, (1, 2, 3, 4, 5))
    rng = np.random.default_rng(9)
    docs, langs = _corpus(rng, 50, 4, max_len=90)
    docs += [b"", b"x", b"xy", b"xyz", b"wxyz"]  # the straddling partials
    langs = np.concatenate([langs, [0, 1, 2, 3, 0]])
    want_ids, want_w = fit_profile_numpy(docs, langs, 4, spec, 30, weight_mode)
    got_ids, got_w = fit_profile_device_split(
        docs, langs, 4, spec, 30, weight_mode
    )
    np.testing.assert_array_equal(got_ids, want_ids)
    np.testing.assert_allclose(got_w, want_w, rtol=1e-6, atol=1e-7)


def test_estimator_device_fit_exact_long_grams_matches_cpu():
    """fitBackend='device' now works for the config-3-style exact n=1..5
    vocab and produces the same model as the host fit (VERDICT r2 #9)."""
    rows = {
        "lang": ["de"] * 3 + ["en"] * 3,
        "fulltext": [
            "der schnelle braune fuchs springt",
            "das ist ja sehr schön heute",
            "noch ein deutscher satz hier",
            "the quick brown fox jumps",
            "that is very nice today",
            "another english sentence here",
        ],
    }
    det = lambda: LanguageDetector(  # noqa: E731
        ["de", "en"], [1, 2, 3, 4, 5], 200
    ).set_vocab_mode("exact")
    cpu = det().set_fit_backend("cpu").fit(Table(rows))
    dev = det().set_fit_backend("device").fit(Table(rows))
    np.testing.assert_array_equal(dev.profile.ids, cpu.profile.ids)
    np.testing.assert_allclose(
        dev.profile.weights, cpu.profile.weights, rtol=1e-6, atol=1e-7
    )
    texts = ["der fuchs springt schön", "the fox jumps nicely"]
    assert (
        dev.transform(Table({"fulltext": texts})).column("lang").tolist()
        == cpu.transform(Table({"fulltext": texts})).column("lang").tolist()
    )


def test_top_k_rows_breaks_ties_by_lowest_id():
    """The boundary tie plateau must resolve lowest-id-first on EVERY
    backend. The TPU lowering of lax.top_k does not honor lowest-index
    ties (found by on-chip fit fuzzing: host and device fits selected
    different members of the log(2) parity plateau), so top_k_rows
    re-ranks the plateau explicitly with integer keys."""
    import jax.numpy as jnp

    from spark_languagedetector_tpu.ops.fit_tpu import top_k_rows

    rng = np.random.default_rng(5)
    V, L, k = 512, 3, 20
    w = np.full((V, L), -np.inf, dtype=np.float32)
    for lang in range(L):
        # 5 strictly-above winners at distinct weights, scattered high ids
        strong = rng.choice(np.arange(200, V), size=5, replace=False)
        w[strong, lang] = 10.0 + np.arange(5)
        # a 100-member tie plateau crossing the boundary (only 15 slots left)
        plateau = rng.choice(np.arange(V), size=100, replace=False)
        plateau = plateau[~np.isin(plateau, strong)]
        w[plateau, lang] = np.float32(0.6931472)
        rows = np.asarray(top_k_rows(jnp.asarray(w), k=k))[lang]
        want = set(strong.tolist()) | set(sorted(plateau.tolist())[: k - 5])
        assert set(rows.tolist()) == want, f"lang {lang}"


def test_top_k_rows_blocked_matches_single_stage():
    """The two-stage (vocab-blocked) top-k selects the exact same row SET
    as the single-stage one under the (value desc, id asc) order — the
    OOM-proof path config-3-scale device fits take. Adversarial cases:
    plateaus crossing both block and selection boundaries, plateaus
    spanning multiple blocks, languages with fewer candidates than k,
    block sizes that do and do not divide V."""
    import jax.numpy as jnp

    from spark_languagedetector_tpu.ops.fit_tpu import (
        top_k_rows,
        top_k_rows_blocked,
    )

    rng = np.random.default_rng(11)
    for trial in range(4):
        V = int(rng.integers(300, 1200))
        L = int(rng.integers(1, 5))
        k = int(rng.integers(2, 40))
        # Few distinct values => giant tie plateaus (the parity weight
        # formula's regime), randomly placed across the whole vocab axis.
        levels = np.asarray([-np.inf, 0.0, 0.3, 0.6931472, 1.1], np.float32)
        w = levels[rng.integers(0, len(levels), size=(V, L))].astype(np.float32)
        # One language nearly empty (fewer real candidates than k).
        w[:, 0] = -np.inf
        w[rng.choice(V, size=max(k // 2, 1), replace=False), 0] = 0.5
        single = np.asarray(top_k_rows(jnp.asarray(w), k=k))
        for block in (64, V // 2 + 1):  # non-dividing and dividing widths
            blocked = np.asarray(
                top_k_rows_blocked(jnp.asarray(w), k=k, block=block)
            )
            for lang in range(L):
                assert set(blocked[lang]) == set(single[lang]), (
                    trial, block, lang,
                )


def test_fit_profile_device_blocked_topk_route_matches():
    """Force the blocked-top-k route through a tiny budget and check the
    full device fit still bit-matches the host fit."""
    from spark_languagedetector_tpu.ops import fit_tpu

    docs = [t.encode() for t in [
        "abcabc", "bcabca", "cabcab", "aabbcc", "abccba", "cbaabc",
    ]]
    langs = np.asarray([0, 0, 1, 1, 2, 2])
    spec = VocabSpec(EXACT, (1, 2))
    want_ids, want_w = fit_profile_numpy(docs, langs, 3, spec, 5, PARITY)
    budget = fit_tpu.TOPK_SORT_BUDGET_ELEMS
    fit_tpu.TOPK_SORT_BUDGET_ELEMS = 1  # force the blocked route
    try:
        got_ids, got_w = fit_profile_device(docs, langs, 3, spec, 5, PARITY)
    finally:
        fit_tpu.TOPK_SORT_BUDGET_ELEMS = budget
    np.testing.assert_array_equal(got_ids, want_ids)
    np.testing.assert_allclose(got_w, want_w, rtol=1e-6, atol=1e-7)


def test_finalize_topk_blocked_matches_naive():
    """The scanned count→top-k finalize (no full weight table) selects the
    same row set as masked weights + single-stage top-k, across weight
    modes, with zero-count pad rows never surfacing for languages that
    have >= k real candidates (and filtered by the id < V rule otherwise)."""
    import jax.numpy as jnp

    from spark_languagedetector_tpu.ops.fit_tpu import (
        finalize_topk_blocked,
        masked_candidate_weights,
        top_k_rows,
    )

    rng = np.random.default_rng(23)
    for mode in (PARITY, COUNTS):
        V, L, k = 700, 4, 25
        counts = rng.integers(0, 4, size=(V, L)).astype(np.int32)
        counts[rng.random((V, L)) < 0.7] = 0  # sparse, big tie plateaus
        counts[:, 2] = 0  # a language with zero occurrences anywhere
        masked = masked_candidate_weights(
            jnp.asarray(counts), weight_mode=mode
        )
        single = np.asarray(top_k_rows(masked, k=k))
        for block in (96, 350, 701):
            got = np.asarray(finalize_topk_blocked(
                jnp.asarray(counts), weight_mode=mode, k=k, block=block
            ))
            for lang in range(L):
                g = {i for i in got[lang] if i < V}
                s = set(single[lang].tolist())
                # Compare the REAL-candidate selections: below-k languages
                # pad arbitrarily in both paths, so intersect with occurred.
                occ = {i for i in range(V) if counts[i].sum() > 0}
                assert g & occ == s & occ, (mode, block, lang)
