"""Cold-start plane (docs/PERFORMANCE.md §12): baked mmap artifacts,
crash-atomic bake/recovery, the persistent-compile-cache manifest, and the
spawn-handshake smoke gate.

The bit-parity contract under test: a quantized bake stores the same
integer rows + per-language f32 scales as the parquet quantization codec,
so a baked model and a parquet-loaded model reconstruct the identical f64
weight matrix and score bit-identically. The crash-atomicity contract
mirrors persist/io: a SIGKILL mid-bake leaves a torn tmp whose header
parses but whose blocks are truncated — the loader must refuse it and the
sibling-promotion recovery must never promote it.
"""

import json
import os
import shutil
import time
from pathlib import Path

import numpy as np
import pytest

from spark_languagedetector_tpu.artifacts.bake import (
    BLOCKS_NAME,
    ArtifactError,
    artifact_path_for,
    bake_model,
    load_artifact,
    load_baked_model,
    maybe_load_baked,
    recover_artifact,
)
from spark_languagedetector_tpu.models.estimator import LanguageDetectorModel
from spark_languagedetector_tpu.telemetry import REGISTRY

LANGS = ("de", "en", "fr")


def _model(seed=0, gram_lengths=(1, 2)):
    rng = np.random.default_rng(seed)
    grams = {}
    for n in gram_lengths:
        for _ in range(120):
            g = bytes(rng.integers(97, 123, size=n).tolist())
            grams[g] = rng.random(len(LANGS)).tolist()
    return LanguageDetectorModel.from_gram_map(grams, gram_lengths, LANGS)


def _counter(name: str) -> int:
    return int(REGISTRY.snapshot()["counters"].get(name, 0))


# ------------------------------------------------------------- bit parity --
def test_baked_bit_identical_to_parquet_quantized(tmp_path):
    """baked→load reconstructs the exact arrays the parquet quantization
    codec reconstructs: same f64 weights (q * scale product), same ids,
    same device membership tables, bit-identical scores."""
    model = _model()
    md = tmp_path / "model"
    model.write().overwrite().quantized("int8").save(str(md))
    loaded = LanguageDetectorModel.load(str(md))

    art = bake_model(model, artifact_path_for(md), quantize="int8")
    baked = load_baked_model(art)

    assert np.array_equal(
        np.asarray(loaded.profile.weights), np.asarray(baked.profile.weights)
    )
    assert np.array_equal(
        np.asarray(loaded.profile.ids), np.asarray(baked.profile.ids)
    )
    lw, llut, lck = loaded.profile.device_membership()
    pb = baked._prebuilt_membership
    assert np.array_equal(np.asarray(lw), np.asarray(pb["weights"]))
    if llut is None:
        assert pb["lut"] is None
    else:
        assert np.array_equal(np.asarray(llut), np.asarray(pb["lut"]))
    assert (lck is None) == (pb["cuckoo"] is None)

    docs = [b"abc abc xyz", b"qqrrss", b"the quick brown fox", b"zz"]
    s_parquet = np.asarray(loaded._get_runner().score(docs))
    s_baked = np.asarray(baked._get_runner().score(docs))
    assert np.array_equal(s_parquet, s_baked)


def test_baked_cuckoo_form_round_trips(tmp_path):
    """Gram lengths > 3 overflow the device-id LUT, so membership bakes
    as cuckoo state; the loader must rebuild the identical table."""
    model = _model(seed=3, gram_lengths=(2, 4))
    md = tmp_path / "model"
    model.save(str(md))
    art = bake_model(model, artifact_path_for(md))
    baked = load_baked_model(art)
    ck = baked._prebuilt_membership["cuckoo"]
    assert ck is not None
    lw, llut, lck = model.profile.device_membership()
    assert np.array_equal(np.asarray(lck.slots), np.asarray(ck.slots))
    assert np.array_equal(np.asarray(lck.keys_lo), np.asarray(ck.keys_lo))
    assert np.array_equal(np.asarray(lck.keys_hi), np.asarray(ck.keys_hi))
    docs = [b"abcd efgh", b"wxyz"]
    assert np.array_equal(
        np.asarray(model._get_runner().score(docs)),
        np.asarray(baked._get_runner().score(docs)),
    )


# ------------------------------------------------------- torn-write shapes --
def test_torn_blocks_refused_and_parquet_fallback(tmp_path):
    """The SIGKILL-mid-build shape: header parses, blocks truncated.
    load_artifact refuses; maybe_load_baked counts the failure and falls
    back (returns None so the caller parses parquet)."""
    model = _model()
    md = tmp_path / "model"
    model.save(str(md))
    art = Path(bake_model(model, artifact_path_for(md)))
    blocks = art / BLOCKS_NAME
    data = blocks.read_bytes()
    blocks.write_bytes(data[: len(data) // 2])

    with pytest.raises(ArtifactError, match="torn write"):
        load_artifact(art)
    before = _counter("artifacts/load_errors")
    assert maybe_load_baked(md) is None
    assert _counter("artifacts/load_errors") == before + 1


def test_missing_end_magic_refused(tmp_path):
    """Same byte count but the end magic overwritten: a plausible-length
    file that never finished its final write is still refused."""
    model = _model()
    md = tmp_path / "model"
    model.save(str(md))
    art = Path(bake_model(model, artifact_path_for(md)))
    blocks = art / BLOCKS_NAME
    data = bytearray(blocks.read_bytes())
    data[-8:] = b"\x00" * 8
    blocks.write_bytes(bytes(data))
    with pytest.raises(ArtifactError, match="magic"):
        load_artifact(art)


def test_recover_promotes_valid_sibling_never_torn(tmp_path):
    """recover_artifact promotes the newest FULLY-validating sibling: a
    torn tmp with a newer mtime is skipped, the older complete tree wins,
    and the torn sibling is swept only after the promotion."""
    model = _model()
    md = tmp_path / "model"
    model.save(str(md))
    root = artifact_path_for(md)
    bake_model(model, root)

    good = root.parent / f".{root.name}.tmp.111"
    os.replace(root, good)  # root gone: the crashed-mid-swap shape
    torn = root.parent / f".{root.name}.tmp.222"
    shutil.copytree(good, torn)
    tb = torn / BLOCKS_NAME
    tb.write_bytes(tb.read_bytes()[:-16])
    future = time.time() + 60
    os.utime(torn, (future, future))

    assert recover_artifact(root) is True
    baked = load_baked_model(root)  # the promoted tree fully validates
    assert np.array_equal(
        np.asarray(baked.profile.weights), np.asarray(model.profile.weights)
    )
    assert not list(root.parent.glob(f".{root.name}.tmp.*"))


def test_recover_refuses_when_only_torn_candidates(tmp_path):
    model = _model()
    md = tmp_path / "model"
    model.save(str(md))
    root = artifact_path_for(md)
    bake_model(model, root)
    torn = root.parent / f".{root.name}.tmp.9"
    os.replace(root, torn)
    tb = torn / BLOCKS_NAME
    tb.write_bytes(tb.read_bytes()[: 100])
    assert recover_artifact(root) is False
    assert not root.exists()
    assert maybe_load_baked(md) is None  # parquet fallback, no crash


# ------------------------------------------------------------ mmap sharing --
def test_concurrent_readers_share_one_mapping(tmp_path):
    """Two loads of one artifact must view the SAME buffer — zero-copy by
    construction, so N replicas on a host share the page cache."""
    model = _model()
    md = tmp_path / "model"
    model.save(str(md))
    art = bake_model(model, artifact_path_for(md))

    a1, a2 = load_artifact(art), load_artifact(art)
    assert a1._buf is a2._buf
    m1, m2 = load_baked_model(art), load_baked_model(art)
    w1 = np.asarray(m1._prebuilt_membership["weights"])
    w2 = np.asarray(m2._prebuilt_membership["weights"])
    assert np.shares_memory(w1, w2)
    # A re-bake is a different file generation: it must map fresh, not
    # serve stale pages through the old key.
    bake_model(model, art)
    a3 = load_artifact(art)
    assert a3._buf is not a1._buf


# ------------------------------------------------------------- knob routing --
def test_artifact_dir_knob_routes_through_exec_config(tmp_path, monkeypatch):
    monkeypatch.setenv("LANGDETECT_ARTIFACT_DIR", str(tmp_path / "arts"))
    got = artifact_path_for("/nowhere/model")
    assert got == tmp_path / "arts" / "model.baked"
    monkeypatch.delenv("LANGDETECT_ARTIFACT_DIR")
    assert artifact_path_for("/nowhere/model") == Path("/nowhere/model.baked")


def test_bake_on_save_knob(tmp_path, monkeypatch):
    """LANGDETECT_BAKE_ON_SAVE=1: every native save also bakes, with the
    same quantization codec the save used."""
    monkeypatch.setenv("LANGDETECT_BAKE_ON_SAVE", "1")
    model = _model()
    md = tmp_path / "model"
    model.write().overwrite().quantized("int8").save(str(md))
    art = artifact_path_for(md)
    assert art.exists()
    baked = load_baked_model(art)
    loaded = LanguageDetectorModel.load(str(md))
    assert np.array_equal(
        np.asarray(loaded.profile.weights), np.asarray(baked.profile.weights)
    )


# ----------------------------------------------- prewarm manifest mechanics --
def _runner(model, buckets=(128, 256)):
    from spark_languagedetector_tpu.api.runner import BatchRunner

    w, lut, ck = model.profile.device_membership()
    return BatchRunner(
        weights=w, lut=lut, cuckoo=ck, spec=model.profile.spec,
        strategy="gather", ragged_transfer=False, length_buckets=buckets,
    )


@pytest.fixture
def compile_cache_dir(tmp_path):
    import jax

    from spark_languagedetector_tpu.artifacts.compile_cache import (
        enable_compile_cache,
    )

    live = enable_compile_cache(str(tmp_path / "cc"))
    yield Path(live)
    jax.config.update("jax_compilation_cache_dir", None)


def test_prewarm_full_trace_writes_manifest(compile_cache_dir):
    from spark_languagedetector_tpu.artifacts.compile_cache import (
        _lattice_signature, prewarm_lattice,
    )

    model = _model()
    runner = _runner(model)
    runner._cost_recorded = True
    out = prewarm_lattice(runner, cache_dir=str(compile_cache_dir))
    assert out["mode"] == "full"
    assert out["buckets"] == [128, 256]
    manifests = list(compile_cache_dir.glob("lattice-*.manifest.json"))
    assert len(manifests) == 1
    sig = json.loads(manifests[0].read_text())
    assert sig == _lattice_signature(runner, (128, 256))


def test_prewarm_sentinel_self_heals_on_cache_miss(compile_cache_dir):
    """A manifest whose cache no longer serves (wiped entries — or, as
    here, an in-process jit cache absorbing the sentinel trace so no
    persistent-cache hit fires) must fall back to the full trace rather
    than declare the lattice warm on faith. Cross-process sentinel
    SUCCESS is gated end-to-end by the spawn smoke below."""
    from spark_languagedetector_tpu.artifacts.compile_cache import (
        prewarm_lattice,
    )

    model = _model()
    r1 = _runner(model)
    r1._cost_recorded = True
    assert prewarm_lattice(r1, cache_dir=str(compile_cache_dir))["mode"] == "full"
    r2 = _runner(model)
    r2._cost_recorded = True
    out = prewarm_lattice(r2, cache_dir=str(compile_cache_dir))
    assert out["verified_hit"] is False
    assert out["mode"] == "full"  # self-healed: every bucket traced


def test_prewarm_signature_mismatch_forces_full_trace(compile_cache_dir):
    """A different lattice (or any signature dimension) maps to a
    different manifest: no sentinel shortcut across geometries."""
    from spark_languagedetector_tpu.artifacts.compile_cache import (
        prewarm_lattice,
    )

    model = _model()
    r1 = _runner(model, buckets=(128,))
    r1._cost_recorded = True
    prewarm_lattice(r1, cache_dir=str(compile_cache_dir))
    r2 = _runner(model, buckets=(128, 256))
    r2._cost_recorded = True
    out = prewarm_lattice(r2, cache_dir=str(compile_cache_dir))
    assert out["mode"] == "full"
    assert out["verified_hit"] is None  # sentinel never attempted
    assert len(list(compile_cache_dir.glob("lattice-*.manifest.json"))) == 2


def test_prewarm_without_cache_dir_never_writes_manifest(tmp_path):
    from spark_languagedetector_tpu.artifacts.compile_cache import (
        prewarm_lattice,
    )

    model = _model()
    runner = _runner(model, buckets=(128,))
    runner._cost_recorded = True
    out = prewarm_lattice(runner)
    assert out["mode"] == "full" and out["verified_hit"] is None


# --------------------------------------------------------- bench smoke gate --
def test_bench_smoke_spawn_trimmed(tmp_path):
    """Tier-1-sized cold-start smoke: bake, spawn cold (full lattice
    trace earns the manifest), spawn warm (sentinel-verified cache),
    hard-gated exactly like the CI gate."""
    import bench

    result = bench.smoke_spawn(str(tmp_path / "spawn.jsonl"), trimmed=True)
    assert result["ok"], result
    assert result["cold"]["prewarm_mode"] == "full"
    assert result["warm"]["prewarm_mode"] == "sentinel"
    assert result["cold"]["compile_cache_misses"] > 0
    assert result["warm"]["compile_cache_hits"] > 0
    assert result["cold"]["first_dispatch_parity"] == 1.0
    assert result["warm"]["first_dispatch_parity"] == 1.0
    assert result["spawn_failures"] == 0


@pytest.mark.slow
def test_bench_smoke_spawn_full(tmp_path):
    import bench

    result = bench.smoke_spawn(str(tmp_path / "spawn_full.jsonl"))
    assert result["ok"], result
    assert result["warmup_ratio"] >= 3.0
    assert result["lattice_buckets"] == 16
