"""Resilience subsystem: policy/breaker units + seeded chaos property tests.

The contract under test (ISSUE 3 acceptance criteria): under a seeded
``FaultPlan`` injecting transient dispatch faults and one poison batch, a
streaming run completes with outputs identical to the fault-free run, the
poison rows land in the DLQ, the breaker opens and re-closes, and resuming
from a checkpoint after a mid-stream kill re-emits no committed batch —
all on CPU.
"""

import time

import numpy as np
import pytest

import jax.numpy as jnp

from spark_languagedetector_tpu import LanguageDetectorModel
from spark_languagedetector_tpu.api.runner import BatchRunner
from spark_languagedetector_tpu.api.table import Table
from spark_languagedetector_tpu.ops.vocab import EXACT, VocabSpec
from spark_languagedetector_tpu.persist.checkpoint import (
    load_checkpoint,
    save_checkpoint,
)
from spark_languagedetector_tpu.resilience import faults
from spark_languagedetector_tpu.resilience.dlq import DeadLetterQueue
from spark_languagedetector_tpu.resilience.faults import (
    FaultPlan,
    InjectedFault,
    PoisonRowError,
    PoisonText,
)
from spark_languagedetector_tpu.resilience.policy import (
    BreakerOpen,
    CircuitBreaker,
    DeadlineExceeded,
    RetryPolicy,
    is_retryable,
)
from spark_languagedetector_tpu.stream.microbatch import (
    memory_source,
    run_stream,
)
from spark_languagedetector_tpu.telemetry import REGISTRY


def _fast_policy(**kw):
    kw.setdefault("max_attempts", 2)
    kw.setdefault("base_delay_s", 0.0)
    return RetryPolicy(**kw)


# ------------------------------------------------------ classifier unit -----
def test_classifier_retryable_vs_deterministic():
    assert is_retryable(RuntimeError("device lost"))
    assert is_retryable(OSError("tunnel reset"))
    assert is_retryable(TimeoutError("deadline"))
    assert is_retryable(InjectedFault("chaos"))
    assert not is_retryable(ValueError("bad column"))
    assert not is_retryable(TypeError("bad type"))
    assert not is_retryable(PoisonRowError("poison"))
    # RuntimeError subclasses that are programming errors — the old bare
    # (RuntimeError, OSError) tuple replayed both.
    assert not is_retryable(NotImplementedError("todo"))
    assert not is_retryable(RecursionError("loop"))
    # BaseExceptions that aren't Exceptions are never retryable.
    assert not is_retryable(KeyboardInterrupt())
    assert not is_retryable(SystemExit(1))


# ------------------------------------------------------ retry policy --------
def test_backoff_deterministic_and_bounded():
    p = RetryPolicy(base_delay_s=0.1, multiplier=2.0, max_delay_s=1.0,
                    jitter=0.5, seed=7)
    delays = [p.backoff_s(a) for a in range(1, 8)]
    assert delays == [p.backoff_s(a) for a in range(1, 8)]  # deterministic
    for a, d in enumerate(delays, start=1):
        base = min(1.0, 0.1 * 2.0 ** (a - 1))
        assert base * 0.5 <= d <= base
    # A different seed jitters differently (same envelope).
    other = RetryPolicy(base_delay_s=0.1, multiplier=2.0, max_delay_s=1.0,
                        jitter=0.5, seed=8)
    assert [other.backoff_s(a) for a in range(1, 8)] != delays
    # jitter=0 is the pure exponential schedule.
    flat = RetryPolicy(base_delay_s=0.1, multiplier=2.0, max_delay_s=1.0,
                       jitter=0.0)
    assert flat.backoff_s(1) == pytest.approx(0.1)
    assert flat.backoff_s(4) == pytest.approx(0.8)
    assert flat.backoff_s(10) == pytest.approx(1.0)  # capped


def test_run_recovers_transient_and_reports_retries():
    calls = {"n": 0}
    seen = []

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise RuntimeError("transient")
        return 42

    slept = []
    p = RetryPolicy(max_attempts=3, base_delay_s=0.01, jitter=0.0)
    out = p.run(
        flaky,
        site="unit",
        on_retry=lambda a, d, e: seen.append((a, d)),
        sleep=slept.append,
    )
    assert out == 42 and calls["n"] == 3
    assert [a for a, _ in seen] == [1, 2]
    assert slept == [p.backoff_s(1), p.backoff_s(2)]


def test_run_exhausts_attempts_then_raises():
    p = _fast_policy(max_attempts=3)
    calls = {"n": 0}

    def always():
        calls["n"] += 1
        raise OSError("down")

    with pytest.raises(OSError):
        p.run(always, site="unit")
    assert calls["n"] == 3


def test_run_deterministic_error_never_replayed():
    calls = {"n": 0}

    def bad():
        calls["n"] += 1
        raise ValueError("schema")

    with pytest.raises(ValueError):
        _fast_policy(max_attempts=5).run(bad, site="unit")
    assert calls["n"] == 1


def test_run_never_swallows_fatal_exceptions():
    calls = {"n": 0}

    def interrupted():
        calls["n"] += 1
        raise KeyboardInterrupt()

    with pytest.raises(KeyboardInterrupt):
        _fast_policy(max_attempts=5).run(interrupted, site="unit")
    assert calls["n"] == 1


def test_run_attempt_deadline_converts_to_deadline_exceeded():
    p = RetryPolicy(max_attempts=5, base_delay_s=0.0,
                    attempt_deadline_s=0.005)

    def slow_fail():
        time.sleep(0.02)
        raise RuntimeError("slow transient")

    with pytest.raises(DeadlineExceeded):
        p.run(slow_fail, site="unit")


def test_run_initial_error_counts_as_first_attempt():
    # Replay-once policy: a failure the caller already observed (async
    # fetch) leaves exactly one replay.
    p = _fast_policy(max_attempts=2)
    calls = {"n": 0}

    def replay():
        calls["n"] += 1
        return "ok"

    assert p.run(replay, initial_error=RuntimeError("x"), site="u") == "ok"
    assert calls["n"] == 1
    # max_attempts=1: the initial error already exhausted the budget.
    with pytest.raises(RuntimeError):
        _fast_policy(max_attempts=1).run(
            replay, initial_error=RuntimeError("x"), site="u"
        )
    # A deterministic initial error propagates without any replay.
    calls["n"] = 0
    with pytest.raises(ValueError):
        p.run(replay, initial_error=ValueError("x"), site="u")
    assert calls["n"] == 0


def test_from_env_reads_knobs(monkeypatch):
    monkeypatch.setenv("LANGDETECT_RETRY_MAX_ATTEMPTS", "4")
    monkeypatch.setenv("LANGDETECT_RETRY_BASE_DELAY_S", "0.25")
    monkeypatch.setenv("LANGDETECT_RETRY_JITTER", "0")
    monkeypatch.setenv("LANGDETECT_RETRY_ATTEMPT_DEADLINE_S", "9")
    p = RetryPolicy.from_env()
    assert p.max_attempts == 4
    assert p.base_delay_s == 0.25
    assert p.jitter == 0.0
    assert p.attempt_deadline_s == 9.0
    # Overrides win over the env.
    assert RetryPolicy.from_env(max_attempts=1).max_attempts == 1


# ------------------------------------------------------ circuit breaker -----
def test_breaker_lifecycle_closed_open_halfopen_closed():
    clk = {"t": 0.0}
    br = CircuitBreaker(failure_threshold=2, cooldown_s=10.0,
                        clock=lambda: clk["t"], name="unit")
    assert br.state == "closed" and br.allow()
    br.record_failure()
    assert br.state == "closed"  # one failure below threshold
    br.record_failure()
    assert br.state == "open"
    assert not br.allow()  # cooldown not elapsed
    clk["t"] = 11.0
    assert br.allow()  # admits the probe
    assert br.state == "half_open"
    br.record_success()
    assert br.state == "closed"
    # Success resets the consecutive count: 1 failure + success + 1
    # failure never trips a threshold-2 breaker.
    br.record_failure()
    br.record_success()
    br.record_failure()
    assert br.state == "closed"


def test_breaker_success_while_open_heals():
    """A success landing while the breaker is OPEN (a retry inside one
    policy run succeeding after the probe attempt re-opened it) is live
    evidence the path works — it must heal the breaker, not strand a
    proven-healthy path behind the next cooldown."""
    clk = {"t": 0.0}
    br = CircuitBreaker(failure_threshold=1, cooldown_s=5.0,
                        clock=lambda: clk["t"])
    br.record_failure()
    assert br.state == "open"
    br.record_success()
    assert br.state == "closed"
    # With a multi-probe breaker, one success only half-opens it.
    br2 = CircuitBreaker(failure_threshold=1, cooldown_s=5.0,
                         probe_successes=2, clock=lambda: clk["t"])
    br2.record_failure()
    br2.record_success()
    assert br2.state == "half_open"
    br2.record_success()
    assert br2.state == "closed"


def test_breaker_probe_failure_reopens():
    clk = {"t": 0.0}
    br = CircuitBreaker(failure_threshold=1, cooldown_s=5.0,
                        clock=lambda: clk["t"])
    br.record_failure()
    assert br.state == "open"
    clk["t"] = 6.0
    assert br.allow()
    br.record_failure()  # probe failed
    assert br.state == "open"
    assert not br.allow()  # cooldown restarted at t=6
    clk["t"] = 12.0
    assert br.allow()


def test_breaker_state_gauge_exported():
    REGISTRY.reset()
    br = CircuitBreaker(failure_threshold=1, name="gaugetest")
    br.record_failure()
    series = REGISTRY.gauge_series()["langdetect_breaker_state"]
    values = {tuple(sorted(l.items())): v for l, v in series}
    assert values[(("breaker", "gaugetest"),)] == 2.0


def test_policy_run_gated_by_open_breaker():
    br = CircuitBreaker(failure_threshold=1, cooldown_s=1000.0)
    br.record_failure()
    calls = {"n": 0}

    def fn():
        calls["n"] += 1

    with pytest.raises(BreakerOpen):
        _fast_policy().run(fn, site="u", breaker=br, breaker_gates=True)
    assert calls["n"] == 0


# ------------------------------------------------------ fault plan ----------
def test_fault_plan_parse_grammar():
    plan = FaultPlan.parse(
        "seed=42;score/dispatch:error@2,5-7;score/fetch:delay=0.01@1;"
        "stream/batch:poison=2@4;shard_step:error%0.25"
    )
    assert plan.seed == 42
    kinds = {(s.site, s.kind) for s in plan.specs}
    assert ("score/dispatch", "error") in kinds
    assert ("stream/batch", "poison") in kinds
    err = next(s for s in plan.specs if s.site == "score/dispatch")
    assert err.calls == ((2, 2), (5, 7))
    assert err.fires(6, plan.seed) and not err.fires(4, plan.seed)
    poison = next(s for s in plan.specs if s.kind == "poison")
    assert poison.value == 2.0
    prob = next(s for s in plan.specs if s.prob is not None)
    fires = [prob.fires(c, plan.seed) for c in range(1, 200)]
    assert fires == [prob.fires(c, plan.seed) for c in range(1, 200)]
    assert 0 < sum(fires) < 199  # fires sometimes, not always


def test_fault_plan_prob_schedule_is_process_independent():
    """%prob schedules must not depend on the builtin salted ``hash()``:
    every process of a multi-host mesh (and every rerun) must fire on the
    same calls. Pinned against the FNV-1a site hash — if this test starts
    failing, the schedule just changed meaning for persisted plans."""
    from spark_languagedetector_tpu.resilience.faults import _fnv1a

    assert _fnv1a("shard_step") == 0x106C1B6B59E3862E
    plan = FaultPlan.parse("seed=42;shard_step:error%0.3")
    spec = plan.specs[0]
    fired = [c for c in range(1, 21) if spec.fires(c, plan.seed)]
    assert fired == [1, 3, 7, 8, 11, 17, 18, 19]


@pytest.mark.parametrize(
    "bad",
    [
        "nosuchsite:error@1",
        "score/dispatch:explode@1",
        "score/dispatch:error@0",
        "score/dispatch:error@3-1",
        "score/dispatch:error@1%0.5",
        "score/dispatch",
    ],
)
def test_fault_plan_parse_rejects(bad):
    with pytest.raises(ValueError):
        FaultPlan.parse(bad)


def test_inject_counts_calls_and_fires_deterministically():
    with faults.plan_scope(FaultPlan.parse("score/dispatch:error@2")):
        faults.inject("score/dispatch")  # call 1: clean
        with pytest.raises(InjectedFault):
            faults.inject("score/dispatch")  # call 2: fires
        faults.inject("score/dispatch")  # call 3: clean again
        faults.inject("score/fetch")  # other sites unaffected
    faults.inject("score/dispatch")  # no plan: no-op


def test_inject_delay_sleeps():
    with faults.plan_scope(FaultPlan.parse("score/fetch:delay=0.02@1")):
        t0 = time.perf_counter()
        faults.inject("score/fetch")
        assert time.perf_counter() - t0 >= 0.015


def test_install_from_env(monkeypatch):
    faults.uninstall()
    monkeypatch.setenv("LANGDETECT_FAULT_PLAN", "seed=3;fit/count:error@1")
    plan = faults.install_from_env()
    assert plan is not None and plan.seed == 3
    assert faults.active() is plan
    faults.uninstall()
    assert faults.active() is None
    monkeypatch.setenv("LANGDETECT_FAULT_PLAN", "garbage")
    with pytest.raises(ValueError):
        faults.install_from_env()


def test_corrupt_batch_poisons_deterministic_rows():
    table = Table({"fulltext": [f"doc{i}" for i in range(6)], "k": range(6)})
    plan = FaultPlan.parse("seed=5;stream/batch:poison=2@1")
    with faults.plan_scope(plan):
        out, rows = faults.corrupt_batch(table, "fulltext")
    assert rows == plan.poison_rows(1, 6) and len(rows) == 2
    for i in range(6):
        v = out.column("fulltext")[i]
        assert v == f"doc{i}"  # str value preserved (str subclass)
        if i in rows:
            assert isinstance(v, PoisonText)
            with pytest.raises(PoisonRowError):
                v.encode("utf-8")
        else:
            assert v.encode("utf-8") == f"doc{i}".encode()
    # Untouched column and row count survive.
    assert list(out.column("k")) == list(range(6))


# ------------------------------------------------------ DLQ + checkpoint ----
def test_dlq_records_and_jsonl_roundtrip(tmp_path):
    path = str(tmp_path / "dead" / "letters.jsonl")
    dlq = DeadLetterQueue(path)
    dlq.put(batch=3, row_index=1, row={"fulltext": "bad"}, error="boom")
    dlq.put(batch=4, row_index=0, row={"fulltext": "worse"}, error="boom2")
    assert len(dlq) == 2
    assert dlq.rows() == [{"fulltext": "bad"}, {"fulltext": "worse"}]
    dlq.close()
    records = DeadLetterQueue.load(path)
    assert [r["batch"] for r in records] == [3, 4]
    assert records[0]["row"] == {"fulltext": "bad"}
    assert records[0]["event"] == "dlq.row"


def test_checkpoint_atomic_roundtrip(tmp_path):
    path = tmp_path / "ck" / "stream.json"
    assert load_checkpoint(path) is None
    save_checkpoint(path, {"committed": 5, "rows": 50})
    state = load_checkpoint(path)
    assert state["committed"] == 5 and state["rows"] == 50
    assert state["version"] == 1 and "ts" in state
    save_checkpoint(path, {"committed": 6})  # overwrite in place
    assert load_checkpoint(path)["committed"] == 6
    assert not path.with_name(path.name + ".tmp").exists()


# ------------------------------------------------------ runner chaos --------
def _runner(**kw):
    spec = VocabSpec(EXACT, (1, 2))
    rng = np.random.default_rng(3)
    weights = rng.normal(size=(spec.id_space_size, 3)).astype(np.float32)
    kw.setdefault("retry_policy", _fast_policy())
    return BatchRunner(
        weights=jnp.asarray(weights), lut=None, spec=spec,
        batch_size=8, strategy="gather", **kw,
    )


def _docs(n=20, length=100):
    rng = np.random.default_rng(5)
    return [
        bytes(rng.integers(0, 256, length, dtype=np.uint8)) for _ in range(n)
    ]


def test_runner_injected_dispatch_fault_recovers():
    runner = _runner()
    docs = _docs()
    want = runner.score(docs)
    with faults.plan_scope(FaultPlan.parse("score/dispatch:error@2")):
        got = runner.score(docs)
    np.testing.assert_allclose(got, want, rtol=1e-6)
    assert runner.metrics.snapshot()["counters"]["retries"] == 1
    assert runner.breaker.state == "closed"  # one blip never trips


def test_runner_injected_pack_fault_falls_to_host_rung():
    """score/pack chaos (PERFORMANCE.md §11): a persistent fault in the
    device-encode wire build drops that dispatch to the degraded ladder's
    host-pack rung — scores stay bit-identical to the fault-free padded
    path, and the degraded counters record the fallback."""
    REGISTRY.reset()
    want = _runner().score(_docs())
    runner = _runner(device_encode=True, degraded_fallback=True)
    with faults.plan_scope(FaultPlan.parse("score/pack:error")):
        got = runner.score(_docs())
    np.testing.assert_array_equal(got, want)
    counters = REGISTRY.snapshot()["counters"]
    assert counters.get("resilience/degraded_batches", 0) > 0
    assert counters.get("resilience/degraded_host", 0) > 0
    # The persistent fault tripped the breaker, so THIS runner keeps
    # serving exact scores from the ladder; a fresh runner (closed
    # breaker, no fault plan) takes the wire path again, bit-exact.
    np.testing.assert_array_equal(runner.score(_docs()), want)
    np.testing.assert_array_equal(
        _runner(device_encode=True).score(_docs()), want
    )
    assert REGISTRY.snapshot()["counters"].get("score/encoded_batches", 0) > 0


def test_runner_injected_pack_fault_transient_retries_in_lane():
    """A one-shot score/pack blip is retryable in the fast lane (the
    wire build replays under the retry policy before any ladder step),
    so a transient never costs the wire format."""
    runner = _runner(device_encode=True)
    docs = _docs()
    want = _runner().score(docs)
    with faults.plan_scope(FaultPlan.parse("score/pack:error@1")):
        got = runner.score(docs)
    np.testing.assert_array_equal(got, want)
    assert runner.metrics.snapshot()["counters"]["retries"] == 1


def test_runner_injected_fetch_fault_replays():
    runner = _runner()
    docs = _docs()
    want = runner.score(docs)
    with faults.plan_scope(FaultPlan.parse("score/fetch:error@1")):
        got = runner.score(docs)
    np.testing.assert_allclose(got, want, rtol=1e-6)
    assert runner.metrics.snapshot()["counters"]["retries"] == 1


def test_runner_injected_fault_label_path():
    runner = _runner()
    docs = _docs()
    want = runner.predict_ids(docs)
    with faults.plan_scope(
        FaultPlan.parse("score/dispatch:error@1;score/fetch:error@2")
    ):
        got = runner.predict_ids(docs)
    np.testing.assert_array_equal(got, want)


def test_runner_breaker_opens_degrades_and_recovers():
    """The acceptance criterion's breaker leg: persistent dispatch faults
    open the breaker, scoring continues exactly via the degradation
    ladder (host level — the fast path IS the gather program here), and
    once the faults stop the half-open probe re-closes the breaker."""
    REGISTRY.reset()
    clk = {"t": 0.0}
    breaker = CircuitBreaker(failure_threshold=1, cooldown_s=10.0,
                             clock=lambda: clk["t"], name="score")
    runner = _runner(
        retry_policy=_fast_policy(max_attempts=1), breaker=breaker
    )
    docs = _docs()
    want = runner.score(docs)  # fault-free oracle (3 batches of 8/8/4)

    with faults.plan_scope(FaultPlan.parse("score/dispatch:error@1")):
        got = runner.score(docs)
        np.testing.assert_allclose(got, want, rtol=1e-6)
        assert breaker.state == "open"
        snap = REGISTRY.snapshot()
        assert snap["counters"]["resilience/degraded_batches"] >= 1
        assert snap["counters"]["resilience/breaker_opened"] == 1
        # Batches after the trip skipped the fast path entirely.
        assert snap["counters"]["resilience/breaker_short_circuit"] >= 1
        gauges = REGISTRY.gauge_series()
        assert gauges["langdetect_degraded"][0][1] == 1.0

        # Still inside the plan scope (spec @1 is spent): cooldown elapses,
        # the half-open probe succeeds, the breaker re-closes and scoring
        # recovers to the fast path.
        clk["t"] = 11.0
        got2 = runner.score(docs)
    np.testing.assert_allclose(got2, want, rtol=1e-6)
    assert breaker.state == "closed"
    assert REGISTRY.gauge_series()["langdetect_degraded"][0][1] == 0.0
    assert runner.metrics.snapshot()["counters"]["degraded_batches"] >= 1


def test_runner_degraded_ladder_from_fast_strategy():
    """A pallas-family fast path degrades through the device-gather rung:
    results stay exact because every rung computes the same scores."""
    spec = VocabSpec(EXACT, (1, 2))
    rng = np.random.default_rng(3)
    weights = rng.normal(size=(spec.id_space_size, 3)).astype(np.float32)
    docs = _docs(12)
    oracle = BatchRunner(
        weights=jnp.asarray(weights), lut=None, spec=spec,
        batch_size=8, strategy="gather",
    ).score(docs)

    clk = {"t": 0.0}
    runner = BatchRunner(
        weights=jnp.asarray(weights), lut=None, spec=spec,
        batch_size=8, strategy="pallas",
        retry_policy=_fast_policy(max_attempts=1),
        breaker=CircuitBreaker(failure_threshold=1, cooldown_s=1e9,
                               clock=lambda: clk["t"]),
    )
    # Fail the pallas dispatch AND the ladder's device-gather rung (both
    # count at score/dispatch): the host rung must carry the batch.
    with faults.plan_scope(FaultPlan.parse("score/dispatch:error@1-2")):
        got = runner.score(docs[:8])
    np.testing.assert_allclose(got, np.asarray(oracle)[:8], rtol=1e-5)
    snap = REGISTRY.snapshot()
    assert snap["counters"].get("resilience/degraded_host", 0) >= 1


def test_runner_deterministic_error_propagates_unretried(monkeypatch):
    runner = _runner()
    calls = {"n": 0}
    orig = BatchRunner._dispatch_device

    def bad(self, *a, **kw):
        calls["n"] += 1
        raise ValueError("programming error")

    monkeypatch.setattr(BatchRunner, "_dispatch_device", bad)
    with pytest.raises(ValueError):
        runner.score(_docs(4))
    assert calls["n"] == 1  # no futile replay, no fallback
    monkeypatch.setattr(BatchRunner, "_dispatch_device", orig)


# ------------------------------------------------------ stream chaos --------
def _model():
    return LanguageDetectorModel.from_gram_map(
        {b"ab": [1.0, 0.0], b"xy": [0.0, 1.0]}, [2], ["a", "x"]
    )


def _stream_rows(n=40):
    return [
        {"fulltext": "ababab" if i % 2 == 0 else "xyxy"} for i in range(n)
    ]


def test_stream_chaos_matches_fault_free_oracle():
    """THE acceptance test: transient stream + dispatch faults and one
    poison batch; the query completes, output equals the fault-free run
    minus exactly the poison rows, and those rows sit in the DLQ."""
    rows = _stream_rows(40)
    model = _model()
    oracle: list[str] = []
    run_stream(
        model, memory_source(rows, 5),
        sink=lambda t: oracle.extend(t.column("lang").tolist()),
    )

    plan = FaultPlan.parse(
        "seed=11;stream/batch:error@2;score/dispatch:error@5;"
        "stream/batch:poison=2@4"
    )
    poison_rows = plan.poison_rows(4, 5)  # row indices inside batch 4
    assert len(poison_rows) == 2
    outputs: list[str] = []
    dlq = DeadLetterQueue()
    model2 = _model()
    with faults.plan_scope(plan):
        query = run_stream(
            model2,
            memory_source(rows, 5),
            sink=lambda t: outputs.extend(t.column("lang").tolist()),
            retry_policy=_fast_policy(max_attempts=3),
            dlq=dlq,
        )

    # The query never died: all 8 batches processed.
    assert query.batches == 8
    assert query.quarantined_batches == 1
    assert query.dlq_rows == 2
    # Output = oracle minus the poison rows (batch 4 == seq 3, rows 15-19).
    poisoned_global = {15 + r for r in poison_rows}
    expected = [
        lang for i, lang in enumerate(oracle) if i not in poisoned_global
    ]
    assert outputs == expected
    # The DLQ holds exactly the poison rows, with full context.
    assert len(dlq) == 2
    for record, r in zip(dlq.records, poison_rows):
        assert record["batch"] == 3 and record["row_index"] == r
        assert record["row"]["fulltext"] == rows[15 + r]["fulltext"]
        assert "PoisonRowError" in record["error"]
    # Transients were retried, not quarantined.
    assert query.metrics.counters["retries"] >= 1


def test_stream_deterministic_error_skips_replay_and_raises_without_dlq():
    rows = _stream_rows(4)
    model = _model()
    calls = {"n": 0}
    real = model.transform

    def bad(batch):
        calls["n"] += 1
        raise ValueError("deterministic: bad column")

    model.transform = bad
    with pytest.raises(ValueError):
        run_stream(
            model, memory_source(rows, 2), sink=lambda t: None,
            retry_policy=_fast_policy(max_attempts=4),
        )
    assert calls["n"] == 1  # straight out: no futile replay
    model.transform = real


def test_stream_deterministic_error_quarantines_with_dlq():
    rows = _stream_rows(4)
    model = _model()
    real = model.transform

    def flaky(batch):
        # Only full batches (2 rows) fail: the bisect halves succeed, so
        # nothing is actually poisoned — quarantine sinks everything.
        if batch.num_rows > 1:
            raise ValueError("batch-shaped deterministic failure")
        return real(batch)

    model.transform = flaky
    outputs = []
    dlq = DeadLetterQueue()
    query = run_stream(
        model, memory_source(rows, 2),
        sink=lambda t: outputs.extend(t.column("lang").tolist()),
        retry_policy=_fast_policy(max_attempts=2),
        dlq=dlq,
    )
    model.transform = real
    assert query.batches == 2
    assert query.quarantined_batches == 2
    assert len(dlq) == 0  # every row scored once isolated
    assert outputs == ["a", "x", "a", "x"]


def test_stream_bisect_outage_propagates_instead_of_quarantining():
    """An outage striking mid-bisection is not poison: retryable failures
    that exhaust the policy during isolation must crash the batch (it
    replays whole on resume) rather than DLQ-ing healthy rows."""
    rows = _stream_rows(4)
    model = _model()
    real = model.transform
    state = {"batch_failed": False}

    def flaky(batch):
        if batch.num_rows > 1:
            state["batch_failed"] = True
            raise ValueError("deterministic batch failure")  # enter bisect
        raise RuntimeError("device lost mid-bisection")  # outage

    model.transform = flaky
    dlq = DeadLetterQueue()
    with pytest.raises(RuntimeError):
        run_stream(
            model, memory_source(rows, 2), sink=lambda t: None,
            retry_policy=_fast_policy(max_attempts=2), dlq=dlq,
        )
    model.transform = real
    assert state["batch_failed"]
    assert len(dlq) == 0  # no healthy row was quarantined


def test_stream_fatal_exceptions_never_swallowed():
    rows = _stream_rows(4)
    model = _model()
    calls = {"n": 0}

    def interrupted(batch):
        calls["n"] += 1
        raise KeyboardInterrupt()

    model.transform = interrupted
    with pytest.raises(KeyboardInterrupt):
        run_stream(
            model, memory_source(rows, 2), sink=lambda t: None,
            retry_policy=_fast_policy(max_attempts=5),
            dlq=DeadLetterQueue(),  # even the DLQ path must not absorb it
        )
    assert calls["n"] == 1


def test_stream_checkpoint_commits_per_batch(tmp_path):
    ck = str(tmp_path / "stream.ckpt")
    rows = _stream_rows(12)
    seen = []
    run_stream(
        _model(), memory_source(rows, 4),
        sink=lambda t: seen.append(t.num_rows),
        checkpoint_path=ck,
    )
    state = load_checkpoint(ck)
    assert state["committed"] == 3
    assert state["rows"] == 12


def test_stream_checkpoint_resume_reemits_no_committed_batch(tmp_path):
    """Mid-stream kill: the sink dies on the 4th batch; the resumed run
    replays only the uncommitted tail, so each row is sunk exactly once
    across the two runs (the acceptance criterion's resume leg)."""
    ck = str(tmp_path / "stream.ckpt")
    rows = _stream_rows(24)
    model = _model()
    oracle: list[str] = []
    run_stream(
        model, memory_source(rows, 4),
        sink=lambda t: oracle.extend(t.column("lang").tolist()),
    )

    first_run: list[str] = []

    def dying_sink(table):
        if len(first_run) >= 12:  # batches 0-2 sunk, batch 3 kills
            raise ValueError("sink crashed mid-stream")
        first_run.extend(table.column("lang").tolist())

    with pytest.raises(ValueError):
        run_stream(
            model, memory_source(rows, 4), sink=dying_sink,
            checkpoint_path=ck,
        )
    assert load_checkpoint(ck)["committed"] == 3

    second_run: list[str] = []
    query = run_stream(
        model, memory_source(rows, 4),
        sink=lambda t: second_run.extend(t.column("lang").tolist()),
        checkpoint_path=ck,
    )
    assert query.resumed_from == 3
    assert query.batches == 3  # only the uncommitted tail
    assert first_run + second_run == oracle  # exactly once, in order
    assert load_checkpoint(ck)["committed"] == 6


def test_stream_resume_with_chaos_and_dlq(tmp_path):
    """Checkpoint + DLQ compose: a resumed run under a fault plan still
    matches the oracle for everything it re-emits."""
    ck = str(tmp_path / "stream.ckpt")
    rows = _stream_rows(20)
    model = _model()
    oracle: list[str] = []
    run_stream(
        model, memory_source(rows, 5),
        sink=lambda t: oracle.extend(t.column("lang").tolist()),
    )
    save_checkpoint(ck, {"committed": 2})  # batches 0-1 already sunk

    outputs: list[str] = []
    with faults.plan_scope(FaultPlan.parse("seed=2;stream/batch:error@1")):
        query = run_stream(
            model, memory_source(rows, 5),
            sink=lambda t: outputs.extend(t.column("lang").tolist()),
            retry_policy=_fast_policy(max_attempts=2),
            checkpoint_path=ck,
            dlq=DeadLetterQueue(),
        )
    assert query.resumed_from == 2 and query.batches == 2
    assert outputs == oracle[10:]
    assert query.metrics.counters["retries"] == 1


# ------------------------------------------------------ fit + shard chaos ---
def test_fit_recovers_from_injected_count_fault():
    from spark_languagedetector_tpu import LanguageDetector

    table = Table({
        "lang": ["a", "x", "a", "x"],
        "fulltext": ["abab", "xyxy", "abab", "xyxy"],
    })
    det = LanguageDetector(["a", "x"], [1, 2], 50)
    want = det.fit(table).profile
    with faults.plan_scope(FaultPlan.parse("fit/count:error@1")):
        got = det.fit(table).profile
    np.testing.assert_array_equal(got.ids, want.ids)
    np.testing.assert_allclose(got.weights, want.weights, rtol=1e-12)


def test_shard_step_fault_site(eight_devices):
    from spark_languagedetector_tpu.ops.encoding import (
        pad_batch,
        texts_to_bytes,
    )
    from spark_languagedetector_tpu.ops.vocab import HASHED
    from spark_languagedetector_tpu.parallel import mesh as mesh_lib
    from spark_languagedetector_tpu.parallel import sharded as sharded_lib

    mesh = mesh_lib.build_mesh(data=4, vocab=2)
    spec = VocabSpec(HASHED, (1, 2), hash_bits=8)
    fit_step = sharded_lib.make_sharded_fit_step(mesh, spec, 2)
    batch, lengths = pad_batch(
        texts_to_bytes(["abab", "bcbc", "xyxy", "zz"]), pad_to=8
    )
    lang_ids = np.asarray([0, 0, 1, 1], dtype=np.int32)
    acc = jnp.zeros((spec.id_space_size, 2), dtype=jnp.int32)
    with faults.plan_scope(FaultPlan.parse("shard_step:error@1")):
        with pytest.raises(InjectedFault):
            fit_step(batch, lengths, lang_ids, acc)
        # The fault fired BEFORE any collective was enqueued, so the
        # immediate replay (what the estimator-level policy does on every
        # process) runs clean.
        got = np.asarray(fit_step(batch, lengths, lang_ids, acc))
    from spark_languagedetector_tpu.ops import fit_tpu

    want = np.asarray(
        fit_tpu.gram_counts_dense(
            batch, lengths, lang_ids, spec=spec, num_langs=2
        )
    )
    np.testing.assert_array_equal(got, want)


# ------------------------------------------------------ telemetry wiring ----
def test_resilience_metrics_flow_through_registry_and_prometheus():
    from spark_languagedetector_tpu.telemetry import render_prometheus

    REGISTRY.reset()
    runner = _runner()
    docs = _docs(8)
    with faults.plan_scope(FaultPlan.parse("score/dispatch:error@1")):
        runner.score(docs)
    snap = REGISTRY.snapshot()
    assert snap["counters"]["resilience/retries"] >= 1
    assert snap["counters"]["resilience/faults_injected"] >= 1
    assert snap["histograms"]["resilience/retry_backoff_s"]["count"] >= 1
    text = render_prometheus(REGISTRY)
    assert 'langdetect_gauge{name="langdetect_retry_attempts"' in text
    assert 'name="resilience/retries"' in text


def test_report_cli_renders_resilience_section(tmp_path, capsys):
    import json

    from spark_languagedetector_tpu.telemetry.report import main as report_main

    events = [
        {"event": "telemetry.span", "ts": 1.0, "path": "score",
         "wall_s": 0.5},
        {"event": "telemetry.snapshot", "ts": 2.0,
         "counters": {"resilience/retries": 3, "resilience/dlq_rows": 2,
                      "resilience/breaker_opened": 1, "score/retries": 3},
         "gauges": {"langdetect_breaker_state": {"breaker=score": 2.0},
                    "langdetect_degraded": {"": 1.0}},
         "histograms": {}},
    ]
    path = tmp_path / "cap.jsonl"
    path.write_text("\n".join(json.dumps(e) for e in events) + "\n")
    assert report_main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "resilience" in out
    assert "retries" in out and "breaker" in out


def test_compare_flags_resilience_counter_regressions(tmp_path):
    import json

    from spark_languagedetector_tpu.telemetry.compare import main as cmp_main

    def write(path, retries):
        events = [
            {"event": "telemetry.span", "ts": 1.0, "path": "score",
             "wall_s": 0.5},
            {"event": "telemetry.snapshot", "ts": 2.0,
             "counters": {"resilience/retries": retries}, "gauges": {},
             "histograms": {}},
        ]
        path.write_text("\n".join(json.dumps(e) for e in events) + "\n")

    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    write(a, 2)
    write(b, 20)
    assert cmp_main([str(a), str(b), "--threshold", "0.5"]) == 1
    write(b, 2)
    assert cmp_main([str(a), str(b), "--threshold", "0.5"]) == 0
    # Zero baseline: the counter *appearing* is the regression — a clean
    # baseline (0 retries) vs a candidate that retries must fail.
    write(a, 0)
    write(b, 5)
    assert cmp_main([str(a), str(b), "--threshold", "0.5"]) == 1
    # ...and disappearing (5 -> 0) is an improvement, not a regression.
    write(a, 5)
    write(b, 0)
    assert cmp_main([str(a), str(b), "--threshold", "0.5"]) == 0


# ------------------------------------------------------ bench smoke ---------
def test_bench_smoke_chaos_reports_recoveries(tmp_path):
    import bench

    jsonl = str(tmp_path / "chaos.jsonl")
    result = bench.smoke_chaos(jsonl)
    assert result["smoke_chaos"] is True
    assert result["oracle_match"] is True
    rec = result["recoveries"]
    assert rec["retries"] >= 1
    assert rec["dlq_rows"] >= 1
    assert rec["breaker_opened"] >= 1
    assert rec["degraded_batches"] >= 1
    assert 0.0 <= result["degraded_time_share"] <= 1.0
    assert result["telemetry"]["jsonl"] == jsonl
    # The chaos capture renders through the stage-tree CLI like any other.
    stages = result["telemetry"]["stages"]
    assert any("degraded" in p for p in stages)
