"""Content-addressed scoring cache + in-flight dedup (ISSUE 10).

The acceptance contract: duplicate documents ride the wire and the kernel
once — the runner's in-flight dedup scatters unique results back to input
order bit-exactly on geometry-stable strategies (label-exact within the
reduction-order tolerance class on matmul strategies) — and the serve
cache answers repeats from the bit-stored prior result of exactly the
leased version, so a hot-swap can never serve a stale answer (new version
⇒ new keys, structurally). Injected ``serve/cache`` faults degrade to
miss-and-recompute, never to a wrong answer, and replay deterministically.
Labels-only requests fetch ids, never the ``[B, L]`` score matrix
(``score/fetch_bytes`` pins the d2h contract on every strategy and
degraded-ladder rung).
"""

import functools

import numpy as np
import pytest

from spark_languagedetector_tpu import Table
from spark_languagedetector_tpu.api.runner import BatchRunner, resolve_mesh
from spark_languagedetector_tpu.exec import config as exec_config
from spark_languagedetector_tpu.exec.core import dedup_items
from spark_languagedetector_tpu.ops.encoding import texts_to_bytes
from spark_languagedetector_tpu.ops.vocab import EXACT, VocabSpec
from spark_languagedetector_tpu.resilience import faults
from spark_languagedetector_tpu.resilience.faults import FaultPlan
from spark_languagedetector_tpu.serve import ContinuousBatcher, ModelRegistry
from spark_languagedetector_tpu.serve.cache import ScoreCache
from spark_languagedetector_tpu.telemetry import REGISTRY

SPEC12 = VocabSpec(EXACT, (1, 2))
L = 5  # languages: keeps the ids-vs-scores fetch contrast unmistakable
LANGS = tuple(f"l{i}" for i in range(L))


@functools.lru_cache(maxsize=None)
def _runner(strategy="gather", seed=0):
    """Random dense-table runner — no fit, compiles once per (strategy,
    seed) thanks to the cache (jit programs compile per runner instance)."""
    rng = np.random.default_rng(seed)
    weights = rng.normal(size=(SPEC12.id_space_size, L)).astype(np.float32)
    return BatchRunner(
        weights=np.asarray(weights), lut=None, spec=SPEC12,
        strategy=strategy, batch_size=64,
    )


# Shared with tests/test_fleet.py (same lru cache): every distinct model
# instance costs a ~3s runner compile, and this module runs first in
# alphabetical order, so using the fleet suite's seeds means the compiles
# are paid once for both modules. Different seeds fit different weights,
# which is what makes a stale cached answer detectable as a bit mismatch.
from tests.test_fleet import _model  # noqa: E402


def _docs_with_dups(rng, n=64, dup_frac=0.6):
    pool = [
        bytes(rng.integers(97, 105, int(rng.integers(0, 40)), dtype=np.uint8))
        for _ in range(max(2, int(n * (1 - dup_frac))))
    ]
    return [pool[int(i)] for i in rng.integers(0, len(pool), n)]


def _counter(name):
    return int(REGISTRY.snapshot()["counters"].get(name, 0))


# ------------------------------------------------------------ dedup core ----
def test_dedup_items_mapping_and_mult():
    keys = [b"a", b"b", b"a", b"", b"b", b"a", b""]
    first, inverse, mult = dedup_items(keys)
    assert first.tolist() == [0, 1, 3]
    assert [keys[i] for i in first] == [b"a", b"b", b""]
    assert mult.tolist() == [3, 2, 2]
    rebuilt = [keys[first[j]] for j in inverse]
    assert rebuilt == keys


def test_dedup_items_all_unique_returns_none():
    assert dedup_items([b"a", b"b", b"c"]) is None
    assert dedup_items([]) is None
    # Tuple keys (the fit's (doc, lang) form): same doc, different lang
    # stays distinct.
    assert dedup_items([(b"a", 0), (b"a", 1)]) is None
    assert dedup_items([(b"a", 0), (b"a", 0)]) is not None


# --------------------------------------------------------- runner dedup -----
def test_runner_dedup_bit_exact_on_gather_fuzz():
    runner = _runner("gather")
    rng = np.random.default_rng(42)
    try:
        for trial in range(4):
            docs = _docs_with_dups(rng)
            if trial == 3:
                # Chunked long docs, duplicated: scatter-back must compose
                # with the cross-chunk score summation.
                big = bytes(rng.integers(97, 105, 9000, dtype=np.uint8))
                docs += [big, big]
            runner.dedup = True
            s_on = runner.score(docs)
            ids_on = runner.predict_ids(docs)
            runner.dedup = False
            s_off = runner.score(docs)
            ids_off = runner.predict_ids(docs)
            np.testing.assert_array_equal(s_on, s_off)
            np.testing.assert_array_equal(ids_on, ids_off)
    finally:
        runner.dedup = True


def test_runner_dedup_label_exact_on_matmul():
    """onehot rides the MXU matmul: the deduped call's batch geometry may
    differ, so scores carry the reduction-order tolerance class — labels
    must still be exact against argmax-of-scores."""
    runner = _runner("onehot")
    rng = np.random.default_rng(7)
    docs = _docs_with_dups(rng, n=48)
    try:
        runner.dedup = True
        s_on = runner.score(docs)
        ids_on = runner.predict_ids(docs)
        runner.dedup = False
        s_off = runner.score(docs)
    finally:
        runner.dedup = True
    np.testing.assert_allclose(s_on, s_off, rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(ids_on, np.argmax(s_off, axis=1))


def test_runner_dedup_identical_rows_share_result():
    """Every duplicate reads the unique row's stored bits — the scattered
    rows are identical, not merely close."""
    runner = _runner("gather")
    docs = [b"abab", b"zzq", b"abab", b"abab", b"zzq"]
    scores = runner.score(docs)
    np.testing.assert_array_equal(scores[0], scores[2])
    np.testing.assert_array_equal(scores[0], scores[3])
    np.testing.assert_array_equal(scores[1], scores[4])


def test_runner_dedup_counters_and_knob(monkeypatch):
    runner = _runner("gather")
    docs = [b"dup", b"dup", b"dup", b"solo"]
    before_in, before_uniq = _counter("dedup/rows_in"), _counter(
        "dedup/rows_unique"
    )
    runner.score(docs)
    assert _counter("dedup/rows_in") - before_in == 4
    assert _counter("dedup/rows_unique") - before_uniq == 2
    # The env knob resolves at construction: LANGDETECT_DEDUP=0 builds
    # runners with the eliminator off.
    monkeypatch.setenv("LANGDETECT_DEDUP", "0")
    off = BatchRunner(
        weights=np.zeros((SPEC12.id_space_size, 2), np.float32), lut=None,
        spec=SPEC12,
    )
    assert off.dedup is False
    monkeypatch.setenv("LANGDETECT_DEDUP", "junk")
    with pytest.raises(ValueError):
        BatchRunner(
            weights=np.zeros((SPEC12.id_space_size, 2), np.float32),
            lut=None, spec=SPEC12,
        )


def test_runner_dedup_empty_and_zero_docs():
    runner = _runner("gather")
    assert runner.score([]).shape == (0, L)
    docs = [b"", b"", b"x"]
    runner.dedup = True
    s_on = runner.score(docs)
    runner.dedup = False
    s_off = runner.score(docs)
    runner.dedup = True
    np.testing.assert_array_equal(s_on, s_off)
    np.testing.assert_array_equal(s_on[0], s_on[1])


# ------------------------------------------------------------- d2h audit ----
def test_labels_fetch_ids_not_score_matrix():
    runner = _runner("gather", seed=3)
    rng = np.random.default_rng(9)
    docs = list({bytes(rng.integers(97, 105, 30, dtype=np.uint8)): None
                 for _ in range(64)})  # all unique: N == fetched rows
    n = len(docs)
    before = _counter("score/fetch_bytes")
    runner.predict_ids(docs)
    ids_bytes = _counter("score/fetch_bytes") - before
    before = _counter("score/fetch_bytes")
    runner.score(docs)
    score_bytes = _counter("score/fetch_bytes") - before
    assert ids_bytes == 4 * n
    assert score_bytes == 4 * n * L
    assert ids_bytes * L <= score_bytes


def test_labels_fetch_chunked_docs_fetch_only_their_rows():
    runner = _runner("gather", seed=3)
    big = bytes(np.random.default_rng(1).integers(97, 105, 9000, dtype=np.uint8))
    docs = [b"short one", big, b"another short"]
    before = _counter("score/fetch_bytes")
    runner.predict_ids(docs)
    delta = _counter("score/fetch_bytes") - before
    # 4 bytes per scored row (chunks included) + one full [chunks, L] score
    # row set for the single chunked doc — nowhere near all rows × L.
    chunks = 2 + -(-len(big) // runner.max_chunk) + 1
    assert delta <= 4 * chunks + 4 * L * chunks
    assert delta < 4 * L * 64


def test_labels_fetch_ids_on_mesh(eight_devices):
    runner = BatchRunner(
        weights=np.random.default_rng(2).normal(
            size=(SPEC12.id_space_size, L)
        ).astype(np.float32),
        lut=None, spec=SPEC12, mesh=resolve_mesh("mesh"), batch_size=64,
    )
    docs = [f"doc number {i}".encode() for i in range(40)]
    before = _counter("score/fetch_bytes")
    ids = runner.predict_ids(docs)
    delta = _counter("score/fetch_bytes") - before
    assert ids.shape == (40,)
    # Mesh pad rows may fetch a few extra ids, never the score matrix.
    assert delta <= 4 * (40 + 8)
    single = _runner("gather", seed=2)
    np.testing.assert_array_equal(ids, np.argmax(runner.score(docs), axis=1))
    del single


def test_labels_fetch_ids_on_degraded_ladder():
    """The ladder rungs honor the d2h contract too: a batch that falls to
    the host rung still fetches [B] ids in label mode."""
    runner = _runner("gather", seed=5)
    docs = [b"degraded fetch probe %d" % i for i in range(16)]
    want = runner.predict_ids(docs)
    before_deg = _counter("resilience/degraded_batches")
    plan = FaultPlan.parse("score/dispatch:error@1-2")  # attempt + replay
    with faults.plan_scope(plan):
        before = _counter("score/fetch_bytes")
        got = runner.predict_ids(docs)
        delta = _counter("score/fetch_bytes") - before
    np.testing.assert_array_equal(got, want)
    assert _counter("resilience/degraded_batches") == before_deg + 1
    assert delta == 4 * len(docs)
    runner.breaker.record_success()
    runner._degraded_mode = False


# ------------------------------------------------------------ score cache ---
def test_score_cache_roundtrip_and_version_keying():
    cache = ScoreCache(max_rows=64, max_bytes=1 << 20)
    row = np.arange(L, dtype=np.float32)
    cache.put("v1", "scores", "utf-8", b"doc", row)
    got = cache.get("v1", "scores", "utf-8", b"doc")
    np.testing.assert_array_equal(got, row)
    # Stored bits are decoupled from the caller's buffer.
    row[0] = 99.0
    np.testing.assert_array_equal(
        cache.get("v1", "scores", "utf-8", b"doc"),
        np.asarray([0, 1, 2, 3, 4], np.float32),
    )
    # A different version / mode / encoding is a different key space.
    assert cache.get("v2", "scores", "utf-8", b"doc") is None
    assert cache.get("v1", "labels", "utf-8", b"doc") is None
    assert cache.get("v1", "scores", "low_byte", b"doc") is None
    stats = cache.stats()
    assert stats["hits"] == 2 and stats["misses"] == 3
    assert stats["rows"] == 1 and stats["bytes"] > 0


def test_score_cache_lru_eviction_by_rows_and_bytes():
    cache = ScoreCache(max_rows=8, max_bytes=1 << 20, shards=1)
    for i in range(12):
        cache.put("v1", "labels", "utf-8", b"d%d" % i, np.int32(i))
    assert cache.rows == 8
    assert cache.get("v1", "labels", "utf-8", b"d0") is None  # evicted
    assert int(cache.get("v1", "labels", "utf-8", b"d11")) == 11
    assert cache.stats()["evictions"] == 4
    # Byte bound: large values evict down to fit.
    small = ScoreCache(max_rows=1000, max_bytes=4096, shards=1)
    for i in range(8):
        small.put(
            "v1", "scores", "utf-8", b"k%d" % i,
            np.zeros(128, np.float32),  # 512B + overhead each
        )
    assert small.bytes <= 4096
    assert small.rows < 8
    # An entry larger than a whole shard is refused, not cycled through.
    small.put("v1", "scores", "utf-8", b"huge", np.zeros(4096, np.float32))
    assert small.get("v1", "scores", "utf-8", b"huge") is None


def test_score_cache_gauges_track_occupancy():
    cache = ScoreCache(max_rows=16, max_bytes=1 << 20)
    cache.put("v1", "labels", "utf-8", b"g", np.int32(1))
    gauges = REGISTRY.snapshot()["gauges"]
    assert any(
        k == "langdetect_cache_rows" and any(
            v >= 1 for v in series.values()
        )
        for k, series in gauges.items()
        if isinstance(series, dict)
    )


# -------------------------------------------------------- batcher + cache ---
def test_batcher_cache_answers_repeat_without_rescoring():
    runner = _runner("gather")
    with ContinuousBatcher(runner, max_wait_ms=2, max_rows=64) as b:
        docs = texts_to_bytes(["abab", "zz", "abczz"])
        first = b.submit(docs).result()
        scored_after_first = runner.metrics.snapshot().get("docs_scored", 0)
        second = b.submit(docs).result()
        scored_after_second = runner.metrics.snapshot().get("docs_scored", 0)
        np.testing.assert_array_equal(first.values, second.values)
        np.testing.assert_array_equal(
            first.values, runner.score(docs)
        )
        assert scored_after_second == scored_after_first  # pure cache hits
        assert b.cache.stats()["hits"] >= len(docs)


def test_batcher_concurrent_requests_dedup_in_one_dispatch():
    """Two concurrent requests with the same documents coalesce into one
    dispatch whose runner call sees the duplicate rows ONCE (level-1 dedup
    across requests), and both callers get the same bits."""
    runner = _runner("gather")
    docs = texts_to_bytes(["abab", "zzzz"])
    with ContinuousBatcher(
        runner, max_wait_ms=60, max_rows=256, cache_enable=False
    ) as b:
        before_in = _counter("dedup/rows_in")
        before_uniq = _counter("dedup/rows_unique")
        f1 = b.submit(docs)
        f2 = b.submit(docs)
        r1, r2 = f1.result(timeout=30), f2.result(timeout=30)
    np.testing.assert_array_equal(r1.values, r2.values)
    assert _counter("dedup/rows_in") - before_in == 4
    assert _counter("dedup/rows_unique") - before_uniq == 2
    assert _counter("serve/dispatches") >= 1


def test_swap_under_cached_traffic_never_serves_stale():
    """The structural-invalidation contract: after a hot-swap, the same
    documents must be answered by the NEW version's runner — bit-equal to
    it, and not to the old version's cached rows."""
    m1, m2 = _model(1), _model(2)
    registry = ModelRegistry()
    registry.install(m1, version="v1")
    docs = texts_to_bytes(["abab", "abczz", "zz"])
    with ContinuousBatcher(registry, max_wait_ms=2, max_rows=64) as b:
        r1 = b.submit(docs).result()
        r1b = b.submit(docs).result()  # warm: answered from cache
        assert r1b.version == "v1"
        registry.install(m2, version="v2")
        r2 = b.submit(docs).result()
    assert r1.version == "v1" and r2.version == "v2"
    np.testing.assert_array_equal(r1.values, m1._get_runner().score(docs))
    np.testing.assert_array_equal(r2.values, m2._get_runner().score(docs))
    assert not np.array_equal(r2.values, r1.values)


def test_shared_cache_does_not_leak_across_models():
    """One ScoreCache shared by batchers over DIFFERENT models: version
    names alone collide (every static source pins "v0", every registry
    auto-names "v1", ...), so the batcher scopes keys by model uid /
    static-source token — each model must be answered from its own
    entries, never the other's."""
    m1, m2 = _model(1), _model(2)
    r1, r2 = m1._get_runner(), m2._get_runner()
    docs = texts_to_bytes(["abab", "zz"])
    shared = ScoreCache(max_rows=64, max_bytes=1 << 20)
    with ContinuousBatcher(r1, max_wait_ms=2, max_rows=64, cache=shared) as b1:
        with ContinuousBatcher(
            r2, max_wait_ms=2, max_rows=64, cache=shared
        ) as b2:
            a1 = b1.submit(docs).result()
            a2 = b2.submit(docs).result()  # same "v0" version name
            a1b = b1.submit(docs).result()  # warm repeat stays per-model
    np.testing.assert_array_equal(a1.values, r1.score(docs))
    np.testing.assert_array_equal(a2.values, r2.score(docs))
    np.testing.assert_array_equal(a1b.values, a1.values)
    assert not np.array_equal(a1.values, a2.values)
    # Registry-backed sources: two independent registries both auto-name
    # "v1" — the model uid in the key keeps them apart too.
    reg1, reg2 = ModelRegistry(), ModelRegistry()
    assert reg1.install(m1) == reg2.install(m2) == "v1"
    shared2 = ScoreCache(max_rows=64, max_bytes=1 << 20)
    with ContinuousBatcher(
        reg1, max_wait_ms=2, max_rows=64, cache=shared2
    ) as b1:
        with ContinuousBatcher(
            reg2, max_wait_ms=2, max_rows=64, cache=shared2
        ) as b2:
            a1 = b1.submit(docs).result()
            a2 = b2.submit(docs).result()
    np.testing.assert_array_equal(a1.values, r1.score(docs))
    np.testing.assert_array_equal(a2.values, r2.score(docs))


def test_shared_cache_does_not_leak_across_tenants():
    """Tenant scope (ISSUE 14 satellite): two tenants sharing ONE
    ScoreCache through the model zoo, with same-named versions ("v1"),
    can never cross-answer — before and after an eviction/reload cycle.
    The batcher's tenant prefix partitions the key space, and an evicted
    tenant reloads into the SAME scope (tenant + model uid + version),
    so its own warm entries stay valid while the neighbor's stay
    unreachable."""
    from spark_languagedetector_tpu import LanguageDetectorModel
    from spark_languagedetector_tpu.zoo import ModelZoo

    # Dedicated 1-gram models (256-row dense tables): runner builds are
    # O(ms), and the zoo's eviction (which drops the cached runner) never
    # touches the module's shared fleet-seed models.
    def tiny_model(seed):
        rng = np.random.default_rng(seed)
        gram_map = {
            bytes([b]): rng.random(2).tolist() for b in range(97, 123)
        }
        return LanguageDetectorModel.from_gram_map(gram_map, [1], ("x", "y"))

    m1, m2 = tiny_model(31), tiny_model(32)
    docs = texts_to_bytes(["abab", "zz", "bcbc"])
    want1 = m1._get_runner().score(docs)
    want2 = m2._get_runner().score(docs)
    assert not np.array_equal(want1, want2)  # distinct, so leaks show
    shared = ScoreCache(max_rows=256, max_bytes=1 << 20)
    zoo = ModelZoo(
        cache=shared, resident_models=1, max_wait_ms=2, max_rows=64,
    )
    zoo.add_tenant("ta", m1)
    zoo.add_tenant("tb", m2)
    try:
        _, rta = zoo.runtime("ta")
        a1 = rta.batcher.submit(docs).result()
        assert a1.version == "v1"
        np.testing.assert_array_equal(a1.values, want1)
        # Same docs, same version name, other tenant: its own answer —
        # and under a 1-model budget this load also evicts "ta".
        _, rtb = zoo.runtime("tb")
        a2 = rtb.batcher.submit(docs).result()
        assert a2.version == "v1"
        np.testing.assert_array_equal(a2.values, want2)
        assert list(zoo.resident()) == ["tb"]
        # Cold reload of "ta": same tenant scope ⇒ its prior entries are
        # legal hits, the neighbor's remain structurally unreachable.
        _, rta2 = zoo.runtime("ta")
        a1b = rta2.batcher.submit(docs).result()
        np.testing.assert_array_equal(a1b.values, want1)
        _, rtb2 = zoo.runtime("tb")
        a2b = rtb2.batcher.submit(docs).result()
        np.testing.assert_array_equal(a2b.values, want2)
        assert shared.stats()["hits"] >= len(docs)  # warm repeats hit
    finally:
        zoo.close()


def test_segment_cache_does_not_leak_across_knobs_or_models():
    """Segment-mode cache-key completeness (ISSUE 12 satellite): the mode
    string carries every decode knob (k, reject threshold, cell, smooth,
    min-span) plus the calibration version, and the model scope applies
    exactly as in label/score mode — so two segment requests with
    different knobs, or against different models through ONE shared
    cache, can never cross-answer."""
    from spark_languagedetector_tpu.segment import (
        SegmentOptions,
        segment_documents,
    )

    m1, m2 = _model(1), _model(2)
    langs = list(m1.profile.languages)
    docs = texts_to_bytes(["abab", "zz", "abczz"])
    shared = ScoreCache(max_rows=256, max_bytes=1 << 20)

    def direct(m, opts):
        return segment_documents(
            m._get_runner(), docs, langs, options=opts,
            calibration=m.calibration,
        )

    opts = SegmentOptions()
    opts_rej = SegmentOptions(top_k=1, reject_threshold=0.9)
    with ContinuousBatcher(
        _reg(m1), max_wait_ms=2, max_rows=64, cache=shared,
    ) as b1, ContinuousBatcher(
        _reg(m2), max_wait_ms=2, max_rows=64, cache=shared,
    ) as b2:
        a1 = b1.segment(docs, opts)
        a2 = b2.segment(docs, opts)      # same version name, other model
        assert a1 == direct(m1, opts)
        assert a2 == direct(m2, opts)
        # Knob flip on the same model: keyed separately, both exact.
        r1 = b1.segment(docs, opts_rej)
        assert r1 == direct(m1, opts_rej)
        assert all(x["rejected"] for x in r1)  # 0.9 floor on 2-lang probs
        # Warm repeats answer from each scope's own entries.
        assert b1.segment(docs, opts) == a1
        assert b2.segment(docs, opts) == a2
    assert shared.stats()["hits"] >= 2 * len(docs)


def _reg(model):
    reg = ModelRegistry()
    reg.install(model)
    return reg


def test_get_many_put_many_match_per_doc_calls():
    """The batched entry points (what the dispatch loop uses) must be
    observationally identical to a loop of get/put — counters included."""
    c = ScoreCache(max_rows=64, max_bytes=1 << 20)
    docs = [b"a", b"b", b"a", b"c"]
    vals = [np.int32(i) for i in range(4)]
    before = {k: _counter(f"cache/{k}") for k in ("lookups", "hits", "misses")}
    assert c.get_many("v1", "labels", "utf-8", docs) == [None] * 4
    c.put_many("v1", "labels", "utf-8", docs, vals)
    got = c.get_many("v1", "labels", "utf-8", docs)
    # b"a" stored twice: last write wins, both positions see it.
    assert [int(g) for g in got] == [2, 1, 2, 3]
    assert _counter("cache/lookups") - before["lookups"] == 8
    assert _counter("cache/misses") - before["misses"] == 4
    assert _counter("cache/hits") - before["hits"] == 4
    assert c.stats()["hits"] == 4 and c.stats()["misses"] == 4


def test_cache_disabled_by_knob(monkeypatch):
    monkeypatch.setenv("LANGDETECT_CACHE_ENABLE", "0")
    runner = _runner("gather")
    with ContinuousBatcher(runner, max_wait_ms=2) as b:
        assert b.cache is None
        docs = texts_to_bytes(["abab"])
        out = b.submit(docs).result()
        np.testing.assert_array_equal(out.values, runner.score(docs))


def test_cache_knob_resolution(monkeypatch):
    monkeypatch.setenv("LANGDETECT_CACHE_ROWS", "128")
    monkeypatch.setenv("LANGDETECT_CACHE_BYTES", str(1 << 16))
    cache = ScoreCache()
    assert cache.max_rows == 128 and cache.max_bytes == 1 << 16
    monkeypatch.setenv("LANGDETECT_CACHE_ROWS", "-1")
    with pytest.raises(ValueError):
        ScoreCache()


# ------------------------------------------------------- chaos: serve/cache -
def test_injected_cache_faults_degrade_to_miss_and_recompute():
    runner = _runner("gather")
    docs = texts_to_bytes(["abab", "zz"])
    direct = runner.score(docs)
    plan = FaultPlan.parse("seed=7;serve/cache:error%0.5")
    with ContinuousBatcher(runner, max_wait_ms=2, max_rows=64) as b:
        with faults.plan_scope(plan):
            before = _counter("cache/faults")
            for _ in range(6):
                got = b.submit(docs).result()
                np.testing.assert_array_equal(got.values, direct)
            faulted = _counter("cache/faults") - before
    assert faulted > 0  # the plan demonstrably fired ...
    # ... and every answer above was still bit-exact (asserted in-loop).


def test_cache_fault_replay_is_deterministic():
    """Same plan, same op sequence ⇒ the same calls fault — the fleet/*
    replay discipline applied to the cache site."""
    plan_text = "seed=11;serve/cache:error%0.4"

    def run_once():
        fired = []
        cache = ScoreCache(max_rows=32, max_bytes=1 << 20)
        with faults.plan_scope(FaultPlan.parse(plan_text)):
            for i in range(20):
                before = _counter("cache/faults")
                if i % 2:
                    cache.put(
                        "v1", "labels", "utf-8", b"k%d" % i, np.int32(i)
                    )
                else:
                    cache.get("v1", "labels", "utf-8", b"k%d" % i)
                fired.append(_counter("cache/faults") - before)
        return fired

    assert run_once() == run_once()


# ------------------------------------------------------------ stream path ---
def test_stream_checkpoint_resume_with_dedup(tmp_path):
    """Kill-and-resume with duplicated rows and dedup on: nothing is
    re-emitted, nothing is lost, outputs match the direct transform."""
    from spark_languagedetector_tpu.stream.microbatch import (
        memory_source,
        run_stream,
    )

    model = _model(1)
    rows = [
        {"fulltext": t}
        for t in ["abab", "zz", "abab", "abczz", "zz", "abab", "bcbc", "zz"]
    ]
    ck = str(tmp_path / "resume.json")
    sunk: list = []
    q1 = run_stream(
        model, memory_source(rows, 2), sunk.append,
        checkpoint_path=ck, max_batches=2,
    )
    assert q1.batches == 2
    q2 = run_stream(
        model, memory_source(rows, 2), sunk.append, checkpoint_path=ck
    )
    assert q2.resumed_from == 2
    got = [v for t in sunk for v in t.column("lang").tolist()]
    want = model.transform(
        Table({"fulltext": [r["fulltext"] for r in rows]})
    ).column("lang").tolist()
    assert got == want
    assert q1.batches + q2.batches == 4


def test_stream_poison_rows_quarantine_with_dedup(tmp_path):
    """A poisoned duplicate fails alone: its healthy twin (same text,
    clean encode) still scores, and only the poison row lands in the DLQ."""
    from spark_languagedetector_tpu.resilience.dlq import DeadLetterQueue
    from spark_languagedetector_tpu.stream.microbatch import (
        memory_source,
        run_stream,
    )

    model = _model(1)
    rows = [{"fulltext": t} for t in ["abab", "abab", "zz", "zz"]]
    dlq = DeadLetterQueue(str(tmp_path / "dlq"))
    sunk: list = []
    plan = FaultPlan.parse("seed=3;stream/batch:poison=1@1")
    with faults.plan_scope(plan):
        q = run_stream(
            model, memory_source(rows, 4), sunk.append, dlq=dlq
        )
    assert q.dlq_rows == 1
    healthy = sum(t.num_rows for t in sunk)
    assert healthy == 3
    want = model.transform(
        Table({"fulltext": ["abab", "abab", "zz", "zz"]})
    ).column("lang").tolist()
    got = [v for t in sunk for v in t.column("lang").tolist()]
    # The three healthy rows keep source order and exact values.
    assert all(v in want for v in got)


# ------------------------------------------------------------- fit dedup ----
def test_fit_dedup_bit_identical_to_host_fit():
    from spark_languagedetector_tpu.ops import fit as fit_ops
    from spark_languagedetector_tpu.ops import fit_tpu
    from spark_languagedetector_tpu.ops.vocab import HASHED

    rng = np.random.default_rng(13)
    base = [
        bytes(rng.integers(97, 107, int(rng.integers(2, 40)), dtype=np.uint8))
        for _ in range(10)
    ]
    docs = [base[int(i)] for i in rng.integers(0, 10, 60)]
    langs = np.asarray([i % 3 for i in range(60)], dtype=np.int32)
    spec = VocabSpec(HASHED, (1, 2), hash_bits=10)
    ids_h, w_h = fit_ops.fit_profile_numpy(docs, langs, 3, spec, 40, "parity")
    ids_d, w_d = fit_tpu.fit_profile_device(docs, langs, 3, spec, 40, "parity")
    np.testing.assert_array_equal(ids_h, ids_d)
    np.testing.assert_array_equal(w_h, w_d)


def test_plan_fit_batches_dedup_mult():
    from spark_languagedetector_tpu.ops import fit_pipeline as fp

    docs = [b"aa", b"bb", b"aa", b"aa", b"bb", b"cc"]
    langs = np.asarray([0, 1, 0, 0, 1, 0], dtype=np.int32)
    items, item_langs, plan, straddle, mult = fp.plan_fit_batches(
        docs, langs, SPEC12
    )
    assert mult is not None
    assert sorted(zip(items, mult.tolist())) == [
        (b"aa", 3), (b"bb", 2), (b"cc", 1)
    ]
    # Same doc under different langs stays distinct.
    items2, _, _, _, mult2 = fp.plan_fit_batches(
        [b"aa", b"aa"], np.asarray([0, 1]), SPEC12
    )
    assert mult2 is None and len(items2) == 2
    # Knob off: no dedup, no mult.
    items3, _, _, _, mult3 = fp.plan_fit_batches(
        docs, langs, SPEC12, dedup=False
    )
    assert mult3 is None and len(items3) == 6


# --------------------------------------------------------- compare guard ----
def _capture(hits, lookups, uniq, rows_in):
    return [
        {"event": "telemetry.span", "path": "score", "wall_s": 0.5},
        {
            "event": "telemetry.snapshot",
            "counters": {
                "cache/hits": hits, "cache/lookups": lookups,
                "dedup/rows_unique": uniq, "dedup/rows_in": rows_in,
            },
            "histograms": {}, "gauges": {},
        },
    ]


def test_compare_tracks_cache_hit_rate_downward():
    from spark_languagedetector_tpu.telemetry import compare

    base = compare.capture_stats(_capture(80, 100, 30, 100))
    assert base["tracked"]["cache/hit_rate"] == pytest.approx(0.8)
    assert base["tracked"]["dedup/unique_ratio"] == pytest.approx(0.3)
    worse = compare.capture_stats(_capture(20, 100, 30, 100))
    _, regressions = compare.compare_captures(base, worse)
    assert any("cache/hit_rate" in r for r in regressions)
    better = compare.capture_stats(_capture(95, 100, 30, 100))
    _, regressions = compare.compare_captures(base, better)
    assert not any("cache/hit_rate" in r for r in regressions)


def test_compare_tracks_dedup_unique_ratio_upward():
    from spark_languagedetector_tpu.telemetry import compare

    base = compare.capture_stats(_capture(80, 100, 30, 100))
    worse = compare.capture_stats(_capture(80, 100, 90, 100))  # dedup broke
    _, regressions = compare.compare_captures(base, worse)
    assert any("dedup/unique_ratio" in r for r in regressions)
    better = compare.capture_stats(_capture(80, 100, 20, 100))
    _, regressions = compare.compare_captures(base, better)
    assert not any("dedup/unique_ratio" in r for r in regressions)


# ------------------------------------------------------------- autotuner ----
def _tune_events(with_cache=True):
    counters = {"exec/len/128": 50, "exec/len/256": 20}
    if with_cache:
        counters.update({
            "cache/lookups": 1000, "cache/hits": 700,
            "cache/bytes_saved": 119000,  # 700 hits x 170B served docs
            "dedup/rows_in": 2000, "dedup/rows_unique": 600,
        })
    return [
        {"ts": 100.0, "event": "telemetry.snapshot", "counters": counters,
         "histograms": {}, "gauges": {}},
    ]


def test_tune_solves_cache_sizing_from_duplicate_mass():
    from spark_languagedetector_tpu.exec import tune

    prof = tune.solve(_tune_events())
    assert prof.tuned["cache_rows"] >= 1024
    assert prof.tuned["cache_rows"] & (prof.tuned["cache_rows"] - 1) == 0
    assert prof.tuned["cache_bytes"] >= 1 << 20
    assert prof.source["duplicate_mass"] == pytest.approx(0.7)
    # Deterministic: same capture, same profile version.
    assert tune.solve(_tune_events()).version == prof.version
    # No cache traffic observed: nothing recorded as tuned.
    bare = tune.solve(_tune_events(with_cache=False))
    assert "cache_rows" not in bare.tuned
    assert "cache_bytes" not in bare.tuned


def test_tune_solves_cache_sizing_from_hits_alone():
    """Steady-state serve capture: cross-dispatch repeats are absorbed as
    cache HITS and never reach the runner, so the dedup counters read
    all-unique — the hit evidence alone must still size the cache."""
    from spark_languagedetector_tpu.exec import tune

    counters = {
        "exec/len/128": 50,
        "cache/lookups": 1000, "cache/hits": 700,
        "cache/bytes_saved": 119000,
        "dedup/rows_in": 300, "dedup/rows_unique": 300,
    }
    events = [
        {"ts": 100.0, "event": "telemetry.snapshot", "counters": counters,
         "histograms": {}, "gauges": {}},
    ]
    prof = tune.solve(events)
    assert prof.tuned["cache_rows"] >= 1024
    assert prof.tuned["cache_bytes"] >= 1 << 20
    assert prof.source["duplicate_mass"] == 0.0  # dedup saw none


def test_cache_knobs_resolve_from_profile(tmp_path, monkeypatch):
    from spark_languagedetector_tpu.exec.profile import TuningProfile

    prof = TuningProfile(tuned={"cache_rows": 2048, "cache_bytes": 1 << 21})
    path = str(tmp_path / "prof.json")
    prof.save(path)
    monkeypatch.setenv(exec_config.PROFILE_ENV, path)
    exec_config.reload_profile()
    try:
        value, source = exec_config.resolve_with_source("cache_rows")
        assert (value, source) == (2048, "profile")
        cache = ScoreCache()
        assert cache.max_rows == 2048 and cache.max_bytes == 1 << 21
        # env still beats the profile
        monkeypatch.setenv("LANGDETECT_CACHE_ROWS", "4096")
        assert exec_config.resolve("cache_rows") == 4096
    finally:
        monkeypatch.delenv(exec_config.PROFILE_ENV)
        exec_config.reload_profile()


# --------------------------------------------------------------- the gate ---
def test_bench_smoke_cache_trimmed(tmp_path):
    """Tier-1-sized redundancy smoke: Zipf-duplicated corpus through
    batch, stream, and the 2-replica fleet with a mid-run hot-swap —
    parity/staleness/hit-rate hard gates exactly like the CI gate (the
    two wall-clock gates run full-size only)."""
    import bench

    result = bench.smoke_cache(str(tmp_path / "cache.jsonl"), trimmed=True)
    assert result["ok"], result
    assert result["batch"]["bit_exact"] and result["batch"]["argmax_parity"] == 1.0
    assert result["stream"]["parity"] == 1.0
    assert result["fleet"]["per_version_parity"] == 1.0
    assert result["fleet"]["stale_answers"] == 0
    assert result["cache"]["hits"] > 0
    assert result["dedup"]["rows_unique"] < result["dedup"]["rows_in"]
    assert result["wire_bytes_saved"] > 0


@pytest.mark.slow
def test_bench_smoke_cache_full(tmp_path):
    """Full-size smoke incl. the >=1.5x duplicated-corpus and <=3%
    all-unique wall-clock gates (slow-marked: CI runs it via
    ``bench.py --smoke-cache``)."""
    import bench

    result = bench.smoke_cache(str(tmp_path / "cache_full.jsonl"))
    assert result["ok"], result
    assert result["batch"]["speedup_duplicated"] >= 1.5
    assert result["batch"]["overhead_all_unique"] <= 0.03
