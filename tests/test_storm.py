"""Storm defense: deadline decay, retry budgets, hedging, quarantine.

The acceptance contract (ISSUE 18): a request's deadline budget *decays*
into every failover attempt (the old bug re-sent the original verbatim,
so attempt N promised time the client no longer had), dispatch is
refused outright below the deadline floor, client retry sleeps never
outlive the request's own deadline, every extra attempt — router
failover, client re-send, hedge — withdraws from a shared token-bucket
:class:`RetryBudget` whose exhaustion is an explicit shed, a hedged
dispatch races one speculative send against a straggling primary with
the first answer winning, and a query of death is quarantined (422 +
serve DLQ) after at most K correlated replica deaths. The ``fleet/hedge``
and ``fleet/quarantine`` chaos sites replay deterministically — same
plan + seed, same outcome sequence — and the quarantine table degrades
*open* under injected faults.
"""

import functools
import time

import numpy as np
import pytest

from spark_languagedetector_tpu import LanguageDetectorModel
from spark_languagedetector_tpu.ops.encoding import texts_to_bytes
from spark_languagedetector_tpu.resilience import faults
from spark_languagedetector_tpu.resilience.faults import FaultPlan
from spark_languagedetector_tpu.resilience.policy import RetryBudget, RetryPolicy
from spark_languagedetector_tpu.serve.batcher import ServeDeadlineExceeded
from spark_languagedetector_tpu.serve.client import ServeClient, ServeHTTPError
from spark_languagedetector_tpu.serve.fleet import ServeFleet
from spark_languagedetector_tpu.serve.quarantine import (
    QuarantineTable,
    QueryQuarantined,
    signature_of,
)
from spark_languagedetector_tpu.serve.router import FleetRouter, FleetSaturated
from spark_languagedetector_tpu.telemetry import REGISTRY

LANGS = ("x", "y")
GRAM_KEYS = (b"ab", b"bc", b"zz", b"abc")
TEXTS = ["abab", "zz", "abczz"]


@functools.lru_cache(maxsize=None)
def _model(seed=0):
    rng = np.random.default_rng(seed)
    gram_map = {g: rng.normal(size=2).tolist() for g in GRAM_KEYS}
    return LanguageDetectorModel.from_gram_map(gram_map, (2, 3), LANGS)


def _counter(name):
    return int(REGISTRY.snapshot()["counters"].get(name, 0))


# ------------------------------------------------------- retry budget -------
def test_retry_budget_token_bucket_semantics():
    """Burst is the starting balance, each spend withdraws one whole
    token, each success deposits ``fraction`` capped at burst."""
    b = RetryBudget(0.5, 2.0, name="t")
    assert b.enabled
    assert b.try_spend() and b.try_spend()
    assert not b.try_spend()  # drained: the bucket never goes negative
    b.record_success()
    assert not b.try_spend()  # 0.5 tokens: a retry costs a WHOLE token
    b.record_success()
    assert b.try_spend()
    d = b.describe()
    assert d["successes"] == 2 and d["spent"] == 3 and d["denied"] == 2
    for _ in range(100):
        b.record_success()
    assert b.describe()["tokens"] == 2.0  # capped at burst


def test_retry_budget_fraction_zero_disables():
    b = RetryBudget(0.0, 5.0, name="off")
    assert not b.enabled
    for _ in range(50):
        assert b.try_spend()  # disabled: never denies, never counts


def test_retry_budget_exhaustion_counts_and_recovers():
    REGISTRY.reset()
    b = RetryBudget(1.0, 1.0, name="tiny")
    assert b.try_spend()
    base = _counter("fleet/retry_budget_exhausted")
    assert not b.try_spend()
    assert _counter("fleet/retry_budget_exhausted") == base + 1
    b.record_success()  # fraction 1.0: one success refills one retry
    assert b.try_spend()


# ------------------------------------------- router over fake replicas ------
class _FakeReplicaClient:
    """Stands in for a handle's ServeClient: records each dispatch's
    deadline_ms and either answers or dies like a severed connection."""

    def __init__(self, name, *, fail_first=0, sleep_s=0.0):
        self.name = name
        self.deadlines = []
        self.calls = 0
        self.fail_first = fail_first
        self.sleep_s = sleep_s

    def detect(self, texts, *, priority=None, deadline_ms=None,
               trace_id=None, tenant=None):
        self.calls += 1
        self.deadlines.append(deadline_ms)
        if self.sleep_s:
            time.sleep(self.sleep_s)
        if self.calls <= self.fail_first:
            raise ConnectionResetError(f"{self.name} died mid-flight")
        return ["x"] * len(texts), {"version": "v1"}

    score = segment = detect


def _fake_router(fakes, **router_kw):
    """A FleetRouter whose handles talk to in-memory fakes: no sockets,
    no probes — the failover/deadline/budget logic under test, alone."""
    router_kw.setdefault("breaker_threshold", 99)
    router_kw.setdefault("dispatch_attempts", 3)
    router = FleetRouter(
        [("127.0.0.1", 1 + i) for i in range(len(fakes))], **router_kw
    )
    for h, fake in zip(router._handles, fakes):
        h.client = fake
        h.ready = True
        h.reasons = []
    return router


def test_failover_decays_remaining_deadline_not_original():
    """THE deadline re-send regression (ISSUE 18 satellite): each
    failover attempt must carry the *remaining* budget. Two replicas
    each burn ~30ms dying; the deadlines recorded downstream must be
    strictly decreasing by at least that burn, never the original."""
    REGISTRY.reset()
    fakes = [
        _FakeReplicaClient("r0", fail_first=9, sleep_s=0.03),
        _FakeReplicaClient("r1", fail_first=9, sleep_s=0.03),
        _FakeReplicaClient("r2"),
    ]
    router = _fake_router(fakes)
    labels, meta = router.detect(TEXTS, deadline_ms=500.0)
    assert labels == ["x"] * 3 and meta["replica"] == "r2"
    seen = [f.deadlines[0] for f in fakes]
    assert seen[0] < 500.0  # even attempt 1 carries elapsed admission time
    assert seen[1] <= seen[0] - 25.0  # r0 burned ~30ms before dying
    assert seen[2] <= seen[1] - 25.0
    assert all(d > 0 for d in seen)


def test_dispatch_refused_below_deadline_floor():
    """A remaining budget under the floor is a 504 *before* any replica
    is burned — no fake must ever see the request."""
    REGISTRY.reset()
    fakes = [_FakeReplicaClient("r0")]
    router = _fake_router(fakes, deadline_floor_ms=50.0)
    with pytest.raises(ServeDeadlineExceeded):
        router.detect(TEXTS, deadline_ms=40.0)
    assert fakes[0].calls == 0
    assert _counter("fleet/deadline_rejects") == 1


def test_router_failovers_draw_from_retry_budget():
    """Attempt 1 is free; every later attempt withdraws a token. With
    burst=1 the second failover is denied: an explicit budget shed."""
    REGISTRY.reset()
    fakes = [_FakeReplicaClient(f"r{i}", fail_first=9) for i in range(3)]
    router = _fake_router(
        fakes, retry_budget=RetryBudget(0.1, 1.0, name="t")
    )
    with pytest.raises(FleetSaturated) as exc:
        router.detect(TEXTS)
    assert exc.value.reason == "retry_budget_exhausted"
    assert exc.value.retry_after_s > 0
    # r0 died free, r1 cost the only token, r2 was never tried.
    assert fakes[0].calls == 1 and fakes[1].calls == 1
    assert fakes[2].calls == 0
    assert _counter("fleet/retry_budget_exhausted") == 1
    assert _counter("fleet/shed_requests") == 1


def test_router_quarantines_query_of_death_after_k_deaths():
    """K=2 correlated deaths quarantine the signature: the next send is
    refused before any dispatch, with the request in the serve DLQ."""
    REGISTRY.reset()
    table = QuarantineTable(2, name="t")
    fakes = [_FakeReplicaClient("r0", fail_first=2)] + [
        _FakeReplicaClient(f"r{i}") for i in (1, 2)
    ]
    router = _fake_router(fakes, quarantine=table)
    for _ in range(2):  # each request: r0 dies on it, failover answers
        labels, _meta = router.detect(TEXTS)
        assert labels == ["x"] * 3
    assert table.describe()["quarantined"] == [signature_of(TEXTS)]
    with pytest.raises(QueryQuarantined) as exc:
        router.detect(TEXTS)
    assert exc.value.signature == signature_of(TEXTS)
    assert sum(f.calls for f in fakes) == 4  # the 422 burned no replica
    assert _counter("fleet/quarantine_rejects") == 1
    assert _counter("fleet/quarantined_signatures") == 1
    # Different content keeps flowing: the table keys on signatures.
    labels, _meta = router.detect(["zz"])
    assert labels == ["x"]


# ------------------------------------------------------- quarantine table ---
def test_quarantine_table_thresholds_dlq_and_lru(tmp_path):
    dlq_path = str(tmp_path / "dlq.jsonl")
    t = QuarantineTable(2, 2, dlq_path=dlq_path, name="t")
    sig = signature_of(["boom"])
    assert not t.record_death(sig, replica="r0", texts=["boom"])
    assert not t.check(sig)
    assert t.record_death(sig, replica="r1", texts=["boom"])
    assert t.check(sig)
    rows = t.dlq.records
    assert len(rows) == 1 and rows[0]["row"]["signature"] == sig
    assert rows[0]["row"]["replicas"] == ["router:r0", "router:r1"]
    assert rows[0]["error"] == "query_of_death"
    # Suspect map is LRU-bounded at max_entries=2.
    for i in range(4):
        t.record_death(f"sig{i}")
    assert t.describe()["suspects"] == 2


def test_supervisor_death_report_charges_last_signature():
    """The out-of-band path (scale/elastic feeds this): a supervisor
    noticing a replica die charges whatever was last routed there —
    once per dispatch, so the router's own mid-flight charge and the
    supervisor's report can't double-count a single death event."""
    t = QuarantineTable(2, name="t")
    sig = signature_of(["killer"])
    t.note_dispatch("r7", sig, ["killer"])
    assert not t.replica_died("r7")
    # Same death event reported again (router already charged it): the
    # pending signature was consumed, nothing further to charge.
    assert not t.replica_died("r7")
    assert t.describe()["suspects"] == 1 and not t.check(sig)
    # The replica restarts, serves the query again, dies again: that IS
    # a second correlated death.
    t.note_dispatch("r7", sig, ["killer"])
    assert t.replica_died("r7", source="supervisor")
    assert t.check(sig)
    assert not t.replica_died("r8")  # nothing ever routed there


def test_quarantine_deaths_zero_disables():
    """deaths<=0 turns the table off (mirrors RetryBudget fraction=0):
    the opt-out for drills that kill replicas under benign traffic."""
    t = QuarantineTable(0, name="off")
    assert not t.enabled
    sig = signature_of(["boom"])
    for _ in range(5):
        assert not t.record_death(sig, replica="r0")
    assert not t.check(sig)
    assert t.describe()["suspects"] == 0


def test_signature_is_order_sensitive_and_stable():
    assert signature_of(["a", "b"]) != signature_of(["b", "a"])
    assert signature_of(["a", "b"]) == signature_of(["a", "b"])
    assert len(signature_of([])) == 16


# ------------------------------------------------------- client deadline ----
class _Always503Client(ServeClient):
    def __init__(self, *, retry_after_s, **kw):
        super().__init__("127.0.0.1", 1, **kw)
        self.attempts = 0
        self._retry_after_s = retry_after_s

    def _request_once(self, method, path, payload=None):
        self.attempts += 1
        raise ServeHTTPError(
            503, {"error": "shed", "shed": True},
            {"Retry-After": str(self._retry_after_s)},
        )


def test_client_retry_sleep_never_outlives_deadline():
    """The retry-sleep regression (ISSUE 18 satellite): a 30s Retry-After
    against a 150ms deadline must surface the error immediately instead
    of sleeping into a dead response."""
    REGISTRY.reset()
    client = _Always503Client(
        retry_after_s=30.0,
        retry_policy=RetryPolicy(
            max_attempts=6, base_delay_s=0.01, max_delay_s=0.05, seed=1
        ),
    )
    t0 = time.monotonic()
    with pytest.raises(ServeHTTPError):
        client.detect(["a"], deadline_ms=150.0)
    assert time.monotonic() - t0 < 2.0  # not the 30s the server asked for
    assert client.attempts == 1
    assert _counter("serve/client_deadline_gaveups") == 1


def test_client_without_deadline_still_retries():
    client = _Always503Client(
        retry_after_s=0.0,
        retry_policy=RetryPolicy(
            max_attempts=3, base_delay_s=0.001, max_delay_s=0.002, seed=1
        ),
    )
    with pytest.raises(ServeHTTPError):
        client.detect(["a"])
    assert client.attempts == 3


def test_client_retries_draw_from_budget():
    """A drained budget turns the client's own retry loop off: the herd
    cannot amplify an outage beyond its successful-traffic fraction."""
    budget = RetryBudget(0.1, 1.0, name="t")
    assert budget.try_spend()  # drain
    client = _Always503Client(
        retry_after_s=0.0,
        retry_policy=RetryPolicy(
            max_attempts=5, base_delay_s=0.001, max_delay_s=0.002, seed=1
        ),
        retry_budget=budget,
    )
    with pytest.raises(ServeHTTPError):
        client.detect(["a"])
    assert client.attempts == 1  # denied before the first re-send


# ------------------------------------------------- chaos replay: hedge ------
ROUTER_KW = dict(
    probe_interval_ms=30.0, probe_timeout_s=2.0, dispatch_attempts=3,
    breaker_threshold=5, breaker_cooldown_s=30.0, drain_timeout_s=5.0,
    hedge_enable=True, hedge_min_ms=30.0,
)


def _hedge_sequence(plan):
    """4 hedged requests under ``plan`` on a fresh fleet; returns the
    per-request (labels-right, hedges, wins, failovers) tuples."""
    fl = ServeFleet(
        [_model(1)] * 3,
        router_kw={
            **ROUTER_KW,
            "retry_budget": RetryBudget(1.0, 10.0, name="hedge-test"),
        },
        max_wait_ms=2, max_rows=64,
    )
    fl.start(probe=False)
    fl.router.probe_once()  # deterministic readiness, no probe thread
    try:
        runner = fl.replicas[0].registry.peek().runner
        want = [
            LANGS[int(i)] for i in runner.predict_ids(texts_to_bytes(TEXTS))
        ]
        # The first dispatch defers cost-gauge analysis to a background
        # thread (docs/PERFORMANCE.md §12); its AOT compile would add
        # CPU noise to run `a` but not run `b` of the replay pair.
        # Quiesce it before the latency-sensitive hedge schedule.
        t = getattr(runner, "_cost_thread", None)
        if t is not None:
            t.join(timeout=120)
        out = []
        with faults.plan_scope(FaultPlan.parse(plan)):
            for _ in range(4):
                labels, _meta = fl.router.detect(TEXTS)
                out.append((
                    labels == want,
                    _counter("fleet/hedges"),
                    _counter("fleet/hedge_wins"),
                    _counter("fleet/failovers"),
                ))
        return out
    finally:
        fl.close()


def test_chaos_hedge_prob_replays_deterministically():
    """%prob stragglers on fleet/dispatch: the same plan + seed produces
    the identical hedge/win sequence on a fresh fleet, every answer
    stays right, and the injected tail demonstrably arms hedges."""
    REGISTRY.reset()
    a = _hedge_sequence("seed=7;fleet/dispatch:delay=0.08%0.5")
    REGISTRY.reset()
    b = _hedge_sequence("seed=7;fleet/dispatch:delay=0.08%0.5")
    assert a == b
    assert all(right for right, *_ in a)
    assert a[-1][1] >= 1  # at least one straggler armed a hedge
    assert a[-1][2] >= 1  # ...and the hedge answered first


def test_chaos_hedge_error_kills_hedge_not_answer():
    """An @calls error at fleet/hedge kills that hedge attempt only: the
    straggling primary still answers, the loser's failure feeds the
    failover bookkeeping, and the schedule replays exactly."""
    plan = "seed=3;fleet/dispatch:delay=0.08@1;fleet/hedge:error@1"
    REGISTRY.reset()
    a = _hedge_sequence(plan)
    REGISTRY.reset()
    b = _hedge_sequence(plan)
    assert a == b
    assert all(right for right, *_ in a)
    # Request 1: primary straggles, the hedge is armed and injected dead
    # — the primary's (delayed) answer still serves the request.
    assert a[0][1] == 1 and a[0][2] == 0
    assert a[0][3] == 1  # the dead hedge counted as a failover
    # No further stragglers: no further hedges.
    assert a[-1][1] == 1


# -------------------------------------------- chaos replay: quarantine ------
def _quarantine_sequence(plan):
    """12 death records over 4 signatures under ``plan``; returns the
    (crossed-threshold, suspects, quarantined) tuple per op."""
    t = QuarantineTable(3, name="t")
    out = []
    with faults.plan_scope(FaultPlan.parse(plan)):
        for i in range(12):
            newly = t.record_death(f"sig{i % 4}")
            d = t.describe()
            out.append((newly, d["suspects"], len(d["quarantined"])))
    return out


def test_chaos_quarantine_prob_replays_deterministically():
    """%prob faults at fleet/quarantine drop death observations — the
    table degrades OPEN (protection delayed, nothing rejected) and the
    dropped-op schedule replays exactly per seed."""
    plan = "seed=9;fleet/quarantine:error%0.4"
    a = _quarantine_sequence(plan)
    b = _quarantine_sequence(plan)
    assert a == b
    clean = _quarantine_sequence("seed=9")
    # The faulted run dropped observations: strictly behind the clean run.
    assert a[-1][2] < clean[-1][2]


def test_chaos_quarantine_check_degrades_open():
    """An injected fault on the lookup admits the request (answers "not
    quarantined") rather than rejecting healthy traffic — and the next
    clean lookup enforces again."""
    t = QuarantineTable(1, name="t")
    sig = signature_of(["boom"])
    t.record_death(sig)
    assert t.check(sig)
    with faults.plan_scope(FaultPlan.parse("seed=1;fleet/quarantine:error@1")):
        assert not t.check(sig)  # degraded open
        assert t.check(sig)      # @1 exhausted: enforcement resumes


# ------------------------------------------------------- bench smoke gate ---
def test_bench_smoke_storm_trimmed(tmp_path):
    """Tier-1-sized storm smoke: poison quarantine, budget-bounded
    outage, hedged straggler rescue, overload self-disable — hard-gated
    exactly like the CI gate."""
    import bench

    result = bench.smoke_storm(str(tmp_path / "storm.jsonl"), trimmed=True)
    assert result["ok"], result["errors"] or result
    assert result["argmax_parity"] == 1.0
    assert result["poison"]["status"] == 422
    assert result["outage"]["amplification"] <= result["outage"][
        "amplification_bound"
    ]
    assert result["overload"]["hedges"] == 0
    assert min(result["survival_checks"]) >= 1


@pytest.mark.slow
def test_bench_smoke_storm_full(tmp_path):
    import bench

    result = bench.smoke_storm(str(tmp_path / "storm_full.jsonl"))
    assert result["ok"], result["errors"] or result
    assert result["hedge"]["wins"] >= 1
    assert result["hedge"]["p99_on_ms"] <= 0.75 * result["hedge"]["p99_off_ms"]
    assert len(result["health"]["ready_replicas"]) == 3
