"""Row-histogram scorer (ops.score_hist) parity vs the gather scorers.

The hist strategy must be bit-compatible in argmax and score-close (same
counts, different summation order) with score_batch / score_batch_cuckoo
across membership forms, partial windows, window limits, and subsets. Runs
in pallas interpret mode on the CPU test substrate (tests/conftest.py); the
Mosaic lowering is exercised by the opt-in real-TPU suite (test_tpu_hw).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_languagedetector_tpu.api.runner import BatchRunner
from spark_languagedetector_tpu.models.profile import GramProfile
from spark_languagedetector_tpu.ops import score as S
from spark_languagedetector_tpu.ops import score_hist as SH
from spark_languagedetector_tpu.ops.bucket import (
    build_buckets_exact,
    build_buckets_hashed,
)
from spark_languagedetector_tpu.ops.cuckoo import build_cuckoo
from spark_languagedetector_tpu.ops.encoding import pad_batch
from spark_languagedetector_tpu.ops.vocab import (
    EXACT,
    HASHED,
    VocabSpec,
    gram_key,
)

RNG = np.random.default_rng(7)
L = 5


def _random_docs(n, lo=97, hi=112, max_len=60):
    docs = [
        bytes(RNG.integers(lo, hi, RNG.integers(0, max_len)).tolist())
        for _ in range(n)
    ]
    docs += [b"", b"a", b"ab", bytes(RNG.integers(0, 256, 200).tolist())]
    return docs


def _cuckoo_fixture(gram_lengths=(1, 2, 3, 4, 5), n_grams=400):
    spec = VocabSpec(EXACT, gram_lengths)
    grams = set()
    while len(grams) < n_grams:
        n = int(RNG.integers(min(gram_lengths), max(gram_lengths) + 1))
        grams.add(bytes(RNG.integers(97, 110, n).tolist()))
    grams = sorted(grams)
    weights = np.zeros((len(grams) + 1, L), np.float32)
    weights[:-1] = RNG.normal(size=(len(grams), L)).astype(np.float32)
    keys = [gram_key(g) for g in grams]
    table = build_cuckoo(
        np.asarray([k[0] for k in keys], np.int32),
        np.asarray([k[1] for k in keys], np.int32),
    )
    return spec, weights, table


def _lut_fixture(gram_lengths=(1, 2, 3), bits=12, n_rows=150):
    spec = VocabSpec(HASHED, gram_lengths, hash_bits=bits)
    V = 1 << bits
    lut = np.full(V, n_rows, np.int32)
    learned = RNG.choice(V, n_rows, replace=False)
    lut[learned] = np.arange(n_rows)
    weights = np.zeros((n_rows + 1, L), np.float32)
    weights[:-1] = RNG.normal(size=(n_rows, L)).astype(np.float32)
    return spec, weights, jnp.asarray(lut)


def _batch(docs, pad_to=256):
    b, l = pad_batch(docs, pad_to)
    return jnp.asarray(b), jnp.asarray(l)


@pytest.mark.parametrize("subset", [None, (3, 4, 5)])
def test_hist_matches_cuckoo_gather(subset):
    spec, weights, table = _cuckoo_fixture()
    batch, lengths = _batch(_random_docs(17))
    entries = jnp.asarray(table.entries())
    bt = build_buckets_exact(table.keys_lo[:-1], table.keys_hi[:-1])
    ref = S.score_batch_cuckoo(
        batch, lengths, jnp.asarray(weights), entries,
        seed1=table.seed1, seed2=table.seed2, spec=spec,
        gram_lengths_subset=subset,
    )
    wp, rhi = SH.pad_weights(weights)
    got = SH.score_batch_hist(
        batch, lengths, jnp.asarray(wp), bucket=jnp.asarray(bt.rows),
        bucket_seed=bt.seed, spec=spec, rhi=rhi,
        gram_lengths_subset=subset, interpret=True, block=128,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-3)


def test_hist_respects_window_limit():
    spec, weights, table = _cuckoo_fixture()
    docs = _random_docs(9)
    batch, lengths = _batch(docs)
    entries = jnp.asarray(table.entries())
    bt = build_buckets_exact(table.keys_lo[:-1], table.keys_hi[:-1])
    limit = jnp.asarray(RNG.integers(1, 40, len(docs)).astype(np.int32))
    ref = S.score_batch_cuckoo(
        batch, lengths, jnp.asarray(weights), entries,
        seed1=table.seed1, seed2=table.seed2, spec=spec, window_limit=limit,
    )
    wp, rhi = SH.pad_weights(weights)
    got = SH.score_batch_hist(
        batch, lengths, jnp.asarray(wp), bucket=jnp.asarray(bt.rows),
        bucket_seed=bt.seed, window_limit=limit, spec=spec, rhi=rhi,
        interpret=True, block=128,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-3)


def test_hist_matches_lut_gather_hashed():
    spec, weights, lut = _lut_fixture()
    batch, lengths = _batch(_random_docs(13))
    ref = S.score_batch(batch, lengths, jnp.asarray(weights), lut, spec=spec)
    wp, rhi = SH.pad_weights(weights)
    got = SH.score_batch_hist(
        batch, lengths, jnp.asarray(wp), lut=lut, spec=spec, rhi=rhi,
        interpret=True, block=128,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-3)


def test_hist_bucket_matches_lut_gather_hashed():
    """Hashed vocab through the single-probe bucket membership."""
    spec, weights, lut = _lut_fixture()
    lut_np = np.asarray(lut)
    miss = weights.shape[0] - 1
    ids = np.nonzero(lut_np != miss)[0].astype(np.int32)
    bt = build_buckets_hashed(ids, lut_np[ids])
    batch, lengths = _batch(_random_docs(13))
    ref = S.score_batch(batch, lengths, jnp.asarray(weights), lut, spec=spec)
    wp, rhi = SH.pad_weights(weights)
    got = SH.score_batch_hist(
        batch, lengths, jnp.asarray(wp), bucket=jnp.asarray(bt.rows),
        bucket_seed=bt.seed, bucket_kind=bt.kind, spec=spec, rhi=rhi,
        interpret=True, block=128,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-3)


def test_hist_requires_exactly_one_membership():
    spec, weights, lut = _lut_fixture()
    batch, lengths = _batch([b"abc"])
    wp, rhi = SH.pad_weights(weights)
    with pytest.raises(ValueError, match="exactly one"):
        SH.score_batch_hist(
            batch, lengths, jnp.asarray(wp), spec=spec, rhi=rhi,
            interpret=True,
        )


def test_pad_weights_shapes():
    w = np.ones((45241, 50), np.float32)
    wp, rhi = SH.pad_weights(w)
    assert rhi == 184 and wp.shape == (184 * 256, 50)
    np.testing.assert_array_equal(wp[:45241], w)
    assert not wp[45241:].any()


def test_runner_hist_strategy_matches_gather():
    """End-to-end through BatchRunner: strategy='hist' (interpret on CPU)
    vs strategy='gather' on the same cuckoo profile, incl. long-doc
    chunking (window limits through the public scoring path)."""
    spec, weights, table = _cuckoo_fixture()
    docs = _random_docs(11) + [bytes(b"abcde" * 300)]  # forces chunking
    ref = BatchRunner(
        weights=jnp.asarray(weights), lut=None, spec=spec,
        cuckoo=table, strategy="gather", length_buckets=(128, 512),
    ).score(docs)
    got = BatchRunner(
        weights=jnp.asarray(weights), lut=None, spec=spec,
        cuckoo=table, strategy="hist", length_buckets=(128, 512),
    ).score(docs)
    np.testing.assert_allclose(got, ref, atol=1e-3)


def test_runner_hist_requires_membership():
    spec = VocabSpec(EXACT, (1, 2))
    w = np.zeros((spec.id_space_size, L), np.float32)
    with pytest.raises(ValueError, match="hist"):
        BatchRunner(
            weights=jnp.asarray(w), lut=None, spec=spec, strategy="hist"
        )


def test_hist_bucket_scan_blocked_membership(monkeypatch):
    """Wide batches resolve membership through the window-axis scan
    (MEMBER_BLOCK shrunk so the scan path runs at test sizes)."""
    monkeypatch.setattr(SH, "MEMBER_BLOCK", 64)
    spec, weights, table = _cuckoo_fixture()
    batch, lengths = _batch(_random_docs(9))
    entries = jnp.asarray(table.entries())
    bt = build_buckets_exact(table.keys_lo[:-1], table.keys_hi[:-1])
    ref = S.score_batch_cuckoo(
        batch, lengths, jnp.asarray(weights), entries,
        seed1=table.seed1, seed2=table.seed2, spec=spec,
    )
    wp, rhi = SH.pad_weights(weights)
    got = SH.score_batch_hist(
        batch, lengths, jnp.asarray(wp), bucket=jnp.asarray(bt.rows),
        bucket_seed=bt.seed, spec=spec, rhi=rhi,
        interpret=True, block=128,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-3)


def test_runner_hist_exact_lut_profile_matches_gather():
    """Regression: an EXACT vocab with gram lengths <= 3 ships LUT
    membership — its bucket table is id-keyed ('hashed' kind) even though
    the vocab mode is exact. Probing it with packed gram keys scored
    everything zero."""
    from spark_languagedetector_tpu.models.profile import GramProfile

    gm = {}
    while len(gm) < 120:
        n = int(RNG.integers(1, 4))
        gm[bytes(RNG.integers(97, 110, n).tolist())] = RNG.normal(size=L)
    profile = GramProfile.from_gram_map(gm, tuple("abcde"), (1, 2, 3))
    weights, lut, cuckoo = profile.device_membership()
    assert lut is not None and cuckoo is None
    docs = list(gm)[:40] + [b"abcabcghi", b"", b"a", bytes(range(250, 256))]
    ref = BatchRunner(
        weights=weights, lut=lut, spec=profile.spec, strategy="gather",
        length_buckets=(128, 256),
    ).score(docs)
    got = BatchRunner(
        weights=weights, lut=lut, spec=profile.spec, strategy="hist",
        length_buckets=(128, 256),
    ).score(docs)
    assert np.abs(ref).max() > 0  # fixture actually hits
    np.testing.assert_allclose(got, ref, atol=1e-3)


def test_runner_explicit_gather_never_reroutes(monkeypatch):
    """strategy='gather' is the escape hatch: it must not silently route
    into the hist path even where hist is supported."""
    spec, weights, table = _cuckoo_fixture()
    r = BatchRunner(
        weights=jnp.asarray(weights), lut=None, spec=spec,
        cuckoo=table, strategy="gather", length_buckets=(128,),
    )
    called = {"hist": False}
    monkeypatch.setattr(
        r, "_hist_scores",
        lambda *a, **k: called.__setitem__("hist", True),
    )
    r.score([b"abcdefgh"])
    assert not called["hist"]
