"""Host fit parity: numpy fit vs the pure-Python reference oracle."""

import numpy as np

from spark_languagedetector_tpu.ops import fit as F
from spark_languagedetector_tpu.ops.encoding import texts_to_bytes
from spark_languagedetector_tpu.ops.vocab import EXACT, HASHED, VocabSpec

from .oracle import fit_oracle

LANGS = ["de", "en"]
TRAIN = [
    ("de", "Dies ist ein deutscher Text, das ist ja sehr schön"),
    ("de", "Dies ist ein andere deutscher Text, und der ist auch sehr schön"),
    ("en", "This is a text in english, and that is very nice"),
    ("en", "This is another text in english and that is also nice"),
]


def _fit(train, langs, gram_lengths, k, weight_mode="parity", spec=None):
    spec = spec or VocabSpec(EXACT, tuple(gram_lengths))
    docs = texts_to_bytes([t for _, t in train])
    lang_idx = np.asarray([langs.index(l) for l, _ in train])
    ids, weights = F.fit_profile_numpy(
        docs, lang_idx, len(langs), spec, k, weight_mode
    )
    return spec, ids, weights


def test_fit_profile_cardinality_matches_reference_spec():
    """Reference fit unit test (LanguageDetectorSpecs.scala:15-40): trigram,
    k=5, 2 languages ⇒ 10 grams, length-2 weight vectors (no shared winners
    in this corpus)."""
    spec, ids, weights = _fit(TRAIN, LANGS, [3], 5)
    assert len(ids) == 10
    assert weights.shape == (10, 2)


def test_fit_matches_oracle_gram_set_and_weights():
    for gram_lengths, k in [([3], 5), ([1, 2], 7), ([2, 3], 4)]:
        spec, ids, weights = _fit(TRAIN, LANGS, gram_lengths, k)
        expected = fit_oracle(TRAIN, LANGS, gram_lengths, k)
        got = {spec.id_to_gram(int(i)): weights[r] for r, i in enumerate(ids)}
        assert set(got) == set(expected), (
            sorted(set(got) - set(expected)),
            sorted(set(expected) - set(got)),
        )
        for gram, vec in expected.items():
            np.testing.assert_allclose(got[gram], vec, rtol=1e-12)


def test_fit_counts_mode_matches_oracle():
    spec, ids, weights = _fit(TRAIN, LANGS, [2], 6, weight_mode="counts")
    expected = fit_oracle(TRAIN, LANGS, [2], 6, weight_mode="counts")
    got = {spec.id_to_gram(int(i)): weights[r] for r, i in enumerate(ids)}
    assert set(got) == set(expected)
    for gram, vec in expected.items():
        np.testing.assert_allclose(got[gram], vec, rtol=1e-12)


def test_fit_learns_partial_grams_from_short_docs():
    """A training doc shorter than the gram length contributes one partial
    gram (Scala sliding parity in fit, LanguageDetector.scala:39)."""
    train = [("de", "ab"), ("en", "xyz")]
    spec, ids, weights = _fit(train, LANGS, [3], 5)
    grams = {spec.id_to_gram(int(i)) for i in ids}
    assert b"ab" in grams
    assert b"xyz" in grams


def test_fit_shared_grams_get_split_weights():
    """A gram present in both languages: parity weight log1p(1/2) for both."""
    train = [("de", "aaa"), ("en", "aaa"), ("de", "bbb"), ("en", "ccc")]
    spec, ids, weights = _fit(train, LANGS, [3], 5)
    got = {spec.id_to_gram(int(i)): weights[r] for r, i in enumerate(ids)}
    np.testing.assert_allclose(got[b"aaa"], [np.log1p(0.5)] * 2)
    np.testing.assert_allclose(got[b"bbb"], [np.log1p(1.0), 0.0])
    np.testing.assert_allclose(got[b"ccc"], [0.0, np.log1p(1.0)])


def test_fit_hashed_mode_runs():
    spec = VocabSpec(HASHED, (1, 2, 3, 4, 5), hash_bits=14)
    docs = texts_to_bytes([t for _, t in TRAIN])
    lang_idx = np.asarray([LANGS.index(l) for l, _ in TRAIN])
    counts = F.extract_gram_counts(docs, lang_idx, 2, spec)
    assert counts.ids.max() < spec.id_space_size
    unique_ids, weights = F.compute_weights(counts)
    ids, w = F.select_top_grams(unique_ids, weights, 10)
    assert len(ids) <= 20 and w.shape[1] == 2


def test_gram_counts_total_equals_window_count():
    """Total counted occurrences == Σ per-doc window counts (incl. partials)."""
    spec = VocabSpec(EXACT, (2,))
    docs = texts_to_bytes(["abcd", "a", ""])
    counts = F.extract_gram_counts(docs, np.asarray([0, 0, 1]), 2, spec)
    # "abcd" → 3 windows, "a" → 1 partial, "" → 0.
    assert counts.counts.sum() == 4
