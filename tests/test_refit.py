"""Incremental refit engine: refit ≡ from-scratch fit, bit for bit.

The count table is the fit's sufficient statistic; these tests pin the
whole contract chain: accumulator updates over ANY corpus split equal one
from-scratch fit (single-device and mesh, divisible and non-divisible
geometries, exact and hashed vocabs), the collective sharded top-k keeps
the host fit's lowest-index tie order, persisted state resumes exactly,
and the auto-refit driver feeds the serving registry's hot-swap.
"""

import numpy as np
import pytest

from spark_languagedetector_tpu import LanguageDetector, Table
from spark_languagedetector_tpu.models.refit import FitAccumulator
from spark_languagedetector_tpu.ops.fit import COUNTS, PARITY, fit_profile_numpy
from spark_languagedetector_tpu.ops.fit_tpu import fit_profile_device
from spark_languagedetector_tpu.ops.vocab import EXACT, HASHED, VocabSpec
from spark_languagedetector_tpu.parallel import mesh as mesh_lib


def _corpus(rng, n_docs, n_langs, max_len=90):
    docs, langs = [], []
    for i in range(n_docs):
        ln = int(rng.integers(0, max_len))
        docs.append(bytes(rng.integers(97, 105, ln, dtype=np.uint8)))
        langs.append(i % n_langs)
    return docs, np.asarray(langs, dtype=np.int32)


def _random_splits(rng, n, pieces):
    cuts = sorted(rng.choice(np.arange(1, n), size=pieces - 1, replace=False))
    return list(zip([0, *cuts], [*cuts, n]))


@pytest.fixture(scope="module")
def mesh8(eight_devices):
    return mesh_lib.build_mesh(data=8, vocab=1)


@pytest.fixture(scope="module")
def mesh42(eight_devices):
    return mesh_lib.build_mesh(data=4, vocab=2)


# --------------------------------------------------- refit ≡ from-scratch ----
@pytest.mark.parametrize(
    "spec,weight_mode",
    [
        (VocabSpec(EXACT, (1, 2)), PARITY),
        (VocabSpec(EXACT, (2,)), COUNTS),
        (VocabSpec(HASHED, (1, 2, 3), hash_bits=12), PARITY),
    ],
)
@pytest.mark.parametrize("mesh_name", [None, "mesh8", "mesh42"])
def test_incremental_equals_from_scratch_fuzz(
    request, spec, weight_mode, mesh_name
):
    """Random corpus splits across refit steps must finalize bit-identical
    to the from-scratch fit (and to the HOST fit) — on a single device, a
    data-parallel 8-mesh (table striped over data), and a 4×2 mesh (table
    striped over the vocab axis). Doc counts are deliberately odd, so mesh
    rows are non-divisible and ride the pad-row path."""
    mesh = request.getfixturevalue(mesh_name) if mesh_name else None
    rng = np.random.default_rng(7)
    for trial in range(3):
        n = int(rng.integers(23, 61))  # odd sizes: non-divisible shards
        docs, langs = _corpus(rng, n, 3)
        docs += [b"", b"x"]
        langs = np.concatenate([langs, [0, 1]]).astype(np.int32)
        acc = FitAccumulator(
            spec, ("aa", "bb", "cc"), profile_size=25,
            weight_mode=weight_mode, mesh=mesh,
        )
        for lo, hi in _random_splits(rng, len(docs), int(rng.integers(2, 5))):
            acc.update_raw(docs[lo:hi], langs[lo:hi])
        got_ids, got_w = acc.finalize()
        want_ids, want_w = fit_profile_device(
            docs, langs, 3, spec, 25, weight_mode, mesh=mesh
        )
        host_ids, host_w = fit_profile_numpy(
            docs, langs, 3, spec, 25, weight_mode
        )
        np.testing.assert_array_equal(got_ids, want_ids)
        np.testing.assert_array_equal(got_w, want_w)
        np.testing.assert_array_equal(got_ids, host_ids)
        np.testing.assert_allclose(got_w, host_w, rtol=1e-6, atol=1e-7)


def test_non_dividing_table_axis_falls_back_exact(eight_devices):
    """V=4096 over a 3-device data axis doesn't stripe evenly; the fit
    must fall back to the replicated finalize and stay bit-exact."""
    mesh = mesh_lib.build_mesh(data=3, vocab=1, devices=eight_devices[:3])
    spec = VocabSpec(HASHED, (1, 2), hash_bits=12)
    rng = np.random.default_rng(11)
    docs, langs = _corpus(rng, 31, 2)
    acc = FitAccumulator(spec, ("aa", "bb"), profile_size=20, mesh=mesh)
    assert not acc._ctx.table_sharded
    acc.update_raw(docs, langs)
    got_ids, got_w = acc.finalize()
    want_ids, want_w = fit_profile_numpy(docs, langs, 2, spec, 20)
    np.testing.assert_array_equal(got_ids, want_ids)
    np.testing.assert_allclose(got_w, want_w, rtol=1e-6, atol=1e-7)


# ------------------------------------------------- collective top-k order ----
def test_sharded_topk_preserves_host_tie_order(mesh8):
    """The cross-shard collective merge must select the same rows as the
    single-device top_k_rows (itself pinned to the host fit's lowest-id
    tie rule) — under giant plateaus crossing shard boundaries, languages
    with fewer candidates than k, and every shard geometry the mesh has."""
    import jax.numpy as jnp

    from spark_languagedetector_tpu.ops.fit_tpu import (
        masked_candidate_weights,
        top_k_rows,
    )
    from spark_languagedetector_tpu.parallel.mesh import table_sharding
    from spark_languagedetector_tpu.parallel.sharded import (
        make_sharded_finalize_topk,
    )
    import jax

    rng = np.random.default_rng(13)
    V, L, k = 512, 3, 24
    counts = rng.integers(0, 3, size=(V, L)).astype(np.int32)
    counts[rng.random((V, L)) < 0.8] = 0  # sparse → plateau-heavy weights
    counts[:, 1] = 0
    counts[rng.choice(V, size=k // 3, replace=False), 1] = 1  # < k rows
    single = np.asarray(
        top_k_rows(masked_candidate_weights(jnp.asarray(counts),
                                            weight_mode="parity"), k=k)
    )
    sharded_counts = jax.device_put(
        jnp.asarray(counts), table_sharding(mesh8)
    )
    topk = make_sharded_finalize_topk(mesh8, profile_size=k)
    got = np.asarray(topk(sharded_counts))
    occ = {i for i in range(V) if counts[i].sum() > 0}
    for lang in range(L):
        assert set(got[lang]) & occ == set(single[lang]) & occ, lang


# ----------------------------------------------------- estimator surface ----
def _rows():
    return {
        "lang": ["de"] * 4 + ["en"] * 4,
        "fulltext": [
            "der schnelle braune fuchs", "das ist ja sehr schön",
            "noch ein deutscher satz", "wo ist der bahnhof bitte",
            "the quick brown fox", "that is very nice",
            "one more english sentence", "where is the station please",
        ],
    }


def test_estimator_accumulator_matches_fit():
    rows = _rows()
    det = lambda: LanguageDetector(["de", "en"], [1, 2], 120)  # noqa: E731
    scratch = det().set_fit_backend("device").fit(Table(rows))
    acc = det().accumulator()
    acc.update(Table({k: v[:3] for k, v in rows.items()}))
    acc.update(Table({k: v[3:] for k, v in rows.items()}))
    model = det().fit_from_accumulator(acc)
    np.testing.assert_array_equal(model.profile.ids, scratch.profile.ids)
    np.testing.assert_array_equal(
        model.profile.weights, scratch.profile.weights
    )
    out = model.transform(Table({"fulltext": ["ein schöner deutscher text"]}))
    assert list(out.column("lang")) == ["de"]


def test_accumulator_validations():
    det = LanguageDetector(["de", "en"], [1, 2], 50)
    acc = det.accumulator()
    # Validation A: unknown label, reference message verbatim.
    with pytest.raises(ValueError, match="contians fr"):
        acc.update(Table({"lang": ["fr"], "fulltext": ["bonjour"]}))
    # Validation B: coverage checked cumulatively at finalize.
    acc.update(Table({"lang": ["de"], "fulltext": ["hallo welt"]}))
    assert acc.coverage_gaps() == ["en"]
    with pytest.raises(ValueError, match="No training examples .* en"):
        acc.finalize()
    # Estimator/accumulator config mismatch refuses the refit.
    other = LanguageDetector(["de", "en"], [1, 2, 3], 50)
    with pytest.raises(ValueError, match="does not match"):
        other.fit_from_accumulator(acc)
    # trainEncoding is part of the statistic: the same corpus under a
    # different encoding counts different grams.
    low = LanguageDetector(["de", "en"], [1, 2], 50).set(
        "trainEncoding", "low_byte"
    )
    with pytest.raises(ValueError, match="does not match"):
        low.fit_from_accumulator(acc)


def test_split_vocab_refused():
    det = (
        LanguageDetector(["de", "en"], [1, 2, 3, 4, 5], 50)
        .set_vocab_mode("exact")
    )
    with pytest.raises(ValueError, match="split"):
        det.accumulator()


def test_empty_update_commits_token():
    det = LanguageDetector(["de", "en"], [1, 2], 50)
    acc = det.accumulator()
    assert acc.update(Table({"lang": [], "fulltext": []})) == 0
    assert acc.committed == 1 and acc.docs_seen == 0


# --------------------------------------------------------- persistence ------
def test_save_load_resume_bit_exact(tmp_path):
    rows = _rows()
    det = lambda: LanguageDetector(["de", "en"], [1, 2], 120)  # noqa: E731
    scratch = det().set_fit_backend("device").fit(Table(rows))
    acc = det().accumulator()
    acc.update(Table({k: v[:5] for k, v in rows.items()}))
    state = tmp_path / "state"
    acc.save(state)
    # Overwriting checkpoint (the per-batch cadence) must stay atomic-safe.
    acc.save(state)
    restored = FitAccumulator.load(state)
    assert restored.committed == 1 and restored.docs_seen == 5
    assert restored.matches_estimator(det())
    restored.update(Table({k: v[5:] for k, v in rows.items()}))
    model = det().fit_from_accumulator(restored)
    np.testing.assert_array_equal(model.profile.ids, scratch.profile.ids)
    np.testing.assert_array_equal(
        model.profile.weights, scratch.profile.weights
    )


def test_save_load_keeps_custom_columns(tmp_path):
    """labelCol/inputCol (and batch rows) are plumbing the restored
    accumulator must keep — a resumed driver reads the same columns its
    counts were accumulated from."""
    det = (
        LanguageDetector(["de", "en"], [1, 2], 60)
        .set("labelCol", "language")
        .set("inputCol", "body")
        .set_fit_batch_rows(32)
    )
    acc = det.accumulator()
    acc.update(Table({"language": ["de", "en"], "body": ["hallo", "hello"]}))
    acc.save(tmp_path / "state")
    restored = FitAccumulator.load(tmp_path / "state")
    assert restored.label_col == "language"
    assert restored.input_col == "body"
    assert restored.batch_rows == 32
    restored.update(Table({"language": ["de"], "body": ["welt"]}))
    assert restored.docs_seen == 3


def test_recover_fit_state_after_interrupted_swap(tmp_path):
    """Killed between the swap's two renames, the checkpoint path holds
    nothing — the state lives complete in the .tmp/.old sibling. The
    driver must recover it instead of silently restarting from zero."""
    import os

    from spark_languagedetector_tpu.persist.io import recover_fit_state
    from spark_languagedetector_tpu.stream import AutoRefit

    _, batches = _stream_fixture()
    det = lambda: LanguageDetector(["de", "en"], [1, 2], 80)  # noqa: E731
    state = tmp_path / "state"
    AutoRefit(det(), state_path=str(state), final_refit=False).run(
        batches, max_batches=3
    )
    # Simulate the crash window: root renamed aside, tmp never renamed in.
    aside = tmp_path / f".state.old.{os.getpid()}"
    os.replace(state, aside)
    assert not state.exists()
    # A torn sibling must never be promoted over the complete one — even
    # one whose metadata parses (a SIGKILL mid-build leaves exactly that:
    # metadata written, counts parquet missing) and that is NEWER than
    # the complete candidate. Full-load validation is the guard.
    import json as _json
    import shutil as _shutil

    torn = tmp_path / ".state.tmp.99999"
    _shutil.copytree(aside, torn)
    _shutil.rmtree(torn / "counts")
    torn2 = tmp_path / ".state.tmp.99998"
    (torn2 / "metadata").mkdir(parents=True)
    (torn2 / "metadata" / "part-00000").write_text("{not json")
    # Sanity: the torn candidate's metadata alone looks legitimate.
    assert _json.loads(
        (torn / "metadata" / "part-00000").read_text()
    )["committed"] == 3
    resumed = AutoRefit(det(), state_path=str(state))
    assert resumed.acc.committed == 3
    assert state.exists() and not aside.exists()
    assert not torn.exists() and not torn2.exists()
    # Idempotent: with a good state in place it is a no-op.
    assert recover_fit_state(state) is False


def test_resume_refuses_short_source(tmp_path):
    """A replayed source that ends before the resume token is a
    token/stream mismatch — fast-forwarding less than `committed` would
    double-count every remaining batch, so the driver refuses loudly."""
    from spark_languagedetector_tpu.stream import AutoRefit

    _, batches = _stream_fixture()
    state = str(tmp_path / "state")
    det = lambda: LanguageDetector(["de", "en"], [1, 2], 80)  # noqa: E731
    AutoRefit(det(), state_path=state, final_refit=False).run(
        batches, max_batches=4
    )
    with pytest.raises(RuntimeError, match="source does not match"):
        AutoRefit(det(), state_path=state).run(batches[:2])


def test_load_rejects_foreign_directory(tmp_path):
    (tmp_path / "metadata").mkdir()
    (tmp_path / "metadata" / "part-00000").write_text('{"class": "nope"}\n')
    with pytest.raises(ValueError, match="class mismatch"):
        FitAccumulator.load(tmp_path)


def test_poisoned_accumulator_refuses(tmp_path, monkeypatch):
    """A raising update may have donated/partially-updated the device
    table: the in-memory state must refuse further use (reload from the
    checkpoint is the recovery path)."""
    det = LanguageDetector(["de", "en"], [1, 2], 50)
    acc = det.accumulator()
    acc.update(Table({"lang": ["de", "en"], "fulltext": ["hallo", "hello"]}))
    import spark_languagedetector_tpu.models.refit as refit_mod

    def boom(*a, **k):
        raise RuntimeError("injected mid-update failure")

    monkeypatch.setattr(refit_mod, "accumulate_counts", boom)
    with pytest.raises(RuntimeError, match="injected"):
        acc.update(Table({"lang": ["de"], "fulltext": ["welt"]}))
    monkeypatch.undo()
    with pytest.raises(RuntimeError, match="invalidated"):
        acc.update(Table({"lang": ["de"], "fulltext": ["welt"]}))
    with pytest.raises(RuntimeError, match="invalidated"):
        acc.finalize()


# ------------------------------------------------------- auto-refit loop ----
def _stream_fixture():
    rng = np.random.default_rng(23)
    de = ["der alte %d hund schläft %d" % (i, i) for i in range(24)]
    en = ["the old %d dog sleeps %d" % (i, i) for i in range(24)]
    rows = {
        "lang": ["de", "en"] * 24,
        "fulltext": [t for pair in zip(de, en) for t in pair],
    }
    batches = [
        Table({k: v[lo:lo + 8] for k, v in rows.items()})
        for lo in range(0, 48, 8)
    ]
    return rows, batches


def test_auto_refit_hot_swaps_bit_exact(tmp_path):
    from spark_languagedetector_tpu.serve import ModelRegistry
    from spark_languagedetector_tpu.stream import AutoRefit

    rows, batches = _stream_fixture()
    det = lambda: LanguageDetector(  # noqa: E731
        ["de", "en"], [1, 2], 150
    ).set_fit_backend("device")
    registry = ModelRegistry(drain_timeout_s=1.0)
    driver = AutoRefit(
        det(), registry, state_path=str(tmp_path / "state"),
        refit_every_batches=2,
    )
    progress = driver.run(batches)
    assert progress.batches == 6 and progress.refits == 3
    assert registry.current_version() == progress.last_version
    served = registry.peek()
    meta = served.describe()["metadata"]
    assert meta["refit_token"] == 6 and meta["docs_seen"] == 48
    scratch = det().fit(Table(rows))
    np.testing.assert_array_equal(
        served.model.profile.ids, scratch.profile.ids
    )
    np.testing.assert_array_equal(
        served.model.profile.weights, scratch.profile.weights
    )


def test_auto_refit_resumes_from_state(tmp_path):
    from spark_languagedetector_tpu.serve import ModelRegistry
    from spark_languagedetector_tpu.stream import AutoRefit

    rows, batches = _stream_fixture()
    det = lambda: LanguageDetector(  # noqa: E731
        ["de", "en"], [1, 2], 150
    ).set_fit_backend("device")
    state = str(tmp_path / "state")
    registry = ModelRegistry(drain_timeout_s=1.0)
    AutoRefit(det(), registry, state_path=state, final_refit=False).run(
        batches, max_batches=2
    )
    # "Kill": new driver, same state — fast-forwards, re-counts nothing.
    second = AutoRefit(det(), registry, state_path=state)
    progress = second.run(batches)
    assert progress.resumed_from == 2
    assert progress.batches == 4  # only the uncommitted tail
    scratch = det().fit(Table(rows))
    served = registry.peek().model
    np.testing.assert_array_equal(served.profile.ids, scratch.profile.ids)
    np.testing.assert_array_equal(
        served.profile.weights, scratch.profile.weights
    )
    # A driver with a DIFFERENT fit config must refuse the state.
    with pytest.raises(ValueError, match="different fit configuration"):
        AutoRefit(
            LanguageDetector(["de", "en"], [1, 2, 3], 150),
            state_path=state,
        )


def test_auto_refit_defers_until_coverage(tmp_path):
    from spark_languagedetector_tpu.stream import AutoRefit

    only_de = Table({"lang": ["de"] * 4, "fulltext": ["hallo welt %d" % i
                                                      for i in range(4)]})
    both = Table({"lang": ["de", "en"], "fulltext": ["noch ein satz",
                                                     "one more sentence"]})
    driver = AutoRefit(
        LanguageDetector(["de", "en"], [1, 2], 80), refit_every_batches=1
    )
    progress = driver.run([only_de, both])
    # First trigger lacked 'en' coverage → deferred, not fatal; the next
    # one (and the final) succeed.
    assert progress.refits >= 1
    assert driver.last_model is not None
    assert driver.acc.coverage_gaps() == []


def test_auto_refit_background_start_stop():
    import itertools

    from spark_languagedetector_tpu.stream import AutoRefit

    _, batches = _stream_fixture()
    driver = AutoRefit(
        LanguageDetector(["de", "en"], [1, 2], 80).set_fit_backend("device"),
        refit_every_batches=100,  # only the final refit
    )
    # A finite source: background loop consumes it and finishes.
    driver.start(itertools.chain(batches))
    progress = driver.wait(timeout=120)
    assert progress.batches == 6
    assert driver.last_model is not None
    # stop() after completion is a no-op that still returns progress.
    assert driver.stop().batches == 6
