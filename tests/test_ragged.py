"""Ragged (wire-efficient) transfer path: pack/unpack parity and runner
equivalence.

The ragged form exists purely to shrink the h2d transfer; its contract is
that the device-side unpack reconstructs the padded batch *bit-exactly*
(``ops.encoding.pack_ragged_numpy`` docstring), so every scoring strategy
is untouched downstream. These tests pin that contract.
"""

import numpy as np
import pytest

from spark_languagedetector_tpu import native
from spark_languagedetector_tpu.api.runner import BatchRunner
from spark_languagedetector_tpu.ops.encoding import (
    RAGGED_CHUNK,
    pack_ragged_numpy,
    pad_batch,
    round_chunks,
    unpack_ragged,
)
from spark_languagedetector_tpu.ops.vocab import VocabSpec


def _fuzz_docs(rng, n):
    docs = []
    for _ in range(n):
        kind = rng.integers(0, 5)
        if kind == 0:
            docs.append(b"")
        elif kind == 1:  # exact chunk multiples (boundary)
            docs.append(bytes(rng.integers(0, 256, RAGGED_CHUNK * int(rng.integers(1, 4)), dtype=np.uint8)))
        elif kind == 2:  # longer than pad_to (truncation)
            docs.append(bytes(rng.integers(0, 256, 3000, dtype=np.uint8)))
        else:
            docs.append(bytes(rng.integers(0, 256, int(rng.integers(1, 1000)), dtype=np.uint8)))
    return docs


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("pad_to", [128, 1024, 8192])
def test_unpack_reconstructs_padded_batch_bit_exactly(seed, pad_to):
    rng = np.random.default_rng(seed)
    docs = _fuzz_docs(rng, 37)
    want, want_lens = pad_batch(docs, pad_to=pad_to)
    flat, offs, lens = pack_ragged_numpy(docs, pad_to)
    np.testing.assert_array_equal(lens, want_lens)
    got = np.asarray(unpack_ragged(flat, offs, lens, pad_to))
    np.testing.assert_array_equal(got, want)


def test_native_pack_ragged_matches_numpy():
    if not native.available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(3)
    docs = _fuzz_docs(rng, 53)
    pad_to = 512
    f_np, o_np, l_np = pack_ragged_numpy(docs, pad_to)
    f_c, o_c, l_c = native.pack_ragged(docs, pad_to)
    np.testing.assert_array_equal(o_c, o_np)
    np.testing.assert_array_equal(l_c, l_np)
    np.testing.assert_array_equal(f_c, f_np)


def test_round_chunks_buckets():
    assert round_chunks(1) == 256
    assert round_chunks(256) == 256
    for c in [257, 1000, 5000, 65536]:
        assert round_chunks(c) >= c
    # with a step, sizes are multiples of it and waste is bounded by it
    assert round_chunks(1000, step=4096) == 4096
    assert round_chunks(5000, step=4096) == 8192
    assert round_chunks(100, step=10) == 256  # floor at the base bucket


def _small_runner(ragged):
    rng = np.random.default_rng(7)
    spec = VocabSpec(mode="hashed", gram_lengths=(1, 2, 3), hash_bits=12)
    weights = rng.normal(size=(spec.id_space_size, 5)).astype(np.float32)
    return BatchRunner(
        weights=weights, lut=None, spec=spec, ragged_transfer=ragged
    )


def test_runner_scores_identical_with_and_without_ragged():
    rng = np.random.default_rng(11)
    docs = _fuzz_docs(rng, 64)
    want = _small_runner(False).score(docs)
    got = _small_runner(True).score(docs)
    np.testing.assert_array_equal(got, want)


def test_narrow_buckets_keep_padded_path(monkeypatch):
    """Docs in the 128/256 buckets need pad_to/128 chunks each — ragged can
    never ship fewer bytes there, so the size precheck must route them
    through the padded transfer."""
    calls = {"ragged": 0}
    orig = BatchRunner._dispatch_ragged

    def counting(self, *a, **kw):
        calls["ragged"] += 1
        return orig(self, *a, **kw)

    monkeypatch.setattr(BatchRunner, "_dispatch_ragged", counting)
    rng = np.random.default_rng(17)
    short = [bytes(rng.integers(0, 256, 100, dtype=np.uint8)) for _ in range(64)]
    _small_runner(True).score(short)  # all land in the 128 bucket
    assert calls["ragged"] == 0
    # sanity: low-fill wide-bucket docs DO take the ragged path
    # (1100B in the 1536 bucket: 9 chunks = 1152B shipped vs 1536 padded)
    wide = [bytes(rng.integers(0, 256, 1100, dtype=np.uint8)) for _ in range(256)]
    _small_runner(True).score(wide)
    assert calls["ragged"] > 0


def test_runner_labels_identical_with_and_without_ragged():
    rng = np.random.default_rng(13)
    docs = _fuzz_docs(rng, 40)
    langs = ["a", "b", "c", "d", "e"]
    want = _small_runner(False).predict_ids(docs)
    got = _small_runner(True).predict_ids(docs)
    np.testing.assert_array_equal(got, want)
