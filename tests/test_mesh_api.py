"""Public-API mesh execution: fit + transform over the 8-virtual-device mesh.

VERDICT r1 #1/#3: multi-chip execution must be reachable from the public API
(the reference's transform is cluster-parallel by default,
LanguageDetectorModel.scala:219-240), and must be bit-identical to the
single-device path.
"""

import numpy as np
import pytest

from spark_languagedetector_tpu import LanguageDetector, Table
from spark_languagedetector_tpu.api.runner import BatchRunner, resolve_mesh

LANGS = ["de", "en", "fr"]
ROWS = {
    "lang": ["de"] * 3 + ["en"] * 3 + ["fr"] * 3,
    "fulltext": [
        "Dies ist ein deutscher Text über Sprache",
        "Das Wetter ist heute sehr schön und warm",
        "Der schnelle braune Fuchs springt über den Hund",
        "This is an english text about language",
        "The weather today is very nice and warm",
        "The quick brown fox jumps over the lazy dog",
        "Ceci est un texte français sur la langue",
        "Le temps est très beau et chaud aujourd'hui",
        "Le renard brun rapide saute par dessus le chien",
    ],
}
EVAL = [
    "Der Hund springt über den Fuchs und das ist schön",
    "The dog jumps over the fox and that is nice",
    "Le chien saute par dessus le renard aujourd'hui",
    "",  # all-miss ⇒ first language (Q6)
    "Das Wetter ist warm " * 400,  # long doc: chunked + mesh-padded path
]


def _fit(backend="cpu", **det_kwargs):
    det = LanguageDetector(LANGS, [1, 2], 300)
    for k, v in det_kwargs.items():
        det.set(k, v)
    return det.fit(Table(ROWS))


def test_resolve_mesh_uses_all_devices(eight_devices):
    mesh = resolve_mesh("mesh")
    assert int(np.prod(list(mesh.shape.values()))) == len(eight_devices)
    # auto on a CPU-only host stays single-device (deterministic tests).
    assert resolve_mesh("auto") is None
    assert resolve_mesh("cpu") is None


def test_transform_mesh_matches_single_device(eight_devices):
    model = _fit()
    single = model.transform(Table({"fulltext": EVAL}))
    model.set_backend("mesh")
    runner = model._get_runner()
    assert runner.mesh is not None
    meshed = model.transform(Table({"fulltext": EVAL}))
    assert list(meshed.column("lang")) == list(single.column("lang"))
    assert list(single.column("lang"))[:4] == ["de", "en", "fr", "de"]


def test_mesh_scores_match_single_device(eight_devices):
    model = _fit()
    from spark_languagedetector_tpu.ops.encoding import texts_to_bytes

    docs = texts_to_bytes(EVAL)
    single = model._get_runner().score(docs)
    model.set_backend("mesh")
    meshed = model._get_runner().score(docs)
    # The sharded and unsharded programs are separate XLA compilations, which
    # may reassociate the f32 hist @ W contraction differently — scores agree
    # to float tolerance, argmax labels exactly (asserted in the test above).
    np.testing.assert_allclose(single, meshed, rtol=1e-5, atol=1e-4)


def test_mesh_batch_not_divisible_by_data_axis(eight_devices):
    """Ragged tail batches are padded with empty rows and un-padded."""
    model = _fit()
    model.set_backend("mesh")
    model.set_batch_size(8)
    docs = [t.encode() for t in EVAL[:3]] * 3  # 9 docs, batch 8 ⇒ tail of 1
    runner = model._get_runner()
    scores = runner.score(docs)
    assert scores.shape == (9, 3)
    np.testing.assert_array_equal(scores[:3], scores[3:6])


def test_fit_device_mesh_matches_host_fit(eight_devices):
    host = _fit()
    dev = _fit(fitBackend="device")
    assert host.profile.spec == dev.profile.spec
    np.testing.assert_array_equal(host.profile.ids, dev.profile.ids)
    np.testing.assert_array_equal(host.profile.weights, dev.profile.weights)


def test_mesh_pallas_shard_map(eight_devices):
    """Explicit pallas strategy on a mesh runs per-shard under shard_map
    (interpret mode on the CPU substrate) and matches the GSPMD path."""
    model = _fit()
    model.set_backend("mesh")
    gspmd = model._get_runner().score([t.encode() for t in EVAL])
    weights, lut = model.profile.device_arrays()
    runner = BatchRunner(
        weights=weights,
        lut=lut,
        spec=model.profile.spec,
        batch_size=8,
        mesh=resolve_mesh("mesh"),
        strategy="pallas",
    )
    pallas = runner.score([t.encode() for t in EVAL])
    np.testing.assert_allclose(gspmd, pallas, rtol=1e-4, atol=1e-3)


def test_mesh_runner_gather_strategy_with_lut(eight_devices):
    """Compact-table (LUT) profiles also run sharded."""
    model = _fit(vocabMode="hashed", hashBits=12)
    single = model._get_runner().score([t.encode() for t in EVAL])
    model.set_backend("mesh")
    runner = model._get_runner()
    assert runner.mesh is not None and runner.strategy == "gather"
    np.testing.assert_array_equal(
        single, runner.score([t.encode() for t in EVAL])
    )


def test_mesh_cuckoo_membership_matches_single_device(eight_devices):
    """Exact gram lengths 4..5 (cuckoo membership) under GSPMD: entries
    replicate, batches shard over the data axis."""
    det = LanguageDetector(LANGS, [1, 4], 200).set_vocab_mode("exact")
    model = det.fit(Table(ROWS))
    runner = model._get_runner()
    assert runner.cuckoo is not None
    docs = [t.encode() for t in EVAL]
    single = runner.score(docs)
    model.set_backend("mesh")
    meshed_runner = model._get_runner()
    assert meshed_runner.mesh is not None
    np.testing.assert_allclose(
        single, meshed_runner.score(docs), rtol=1e-5, atol=1e-4
    )


def test_mesh_hybrid_strategy_matches_single_device(eight_devices):
    """Hybrid (pallas n<=2 under shard_map + gather n>=3 under GSPMD) on a
    mesh, including a chunked long doc."""
    det = LanguageDetector(LANGS, [1, 2, 3], 300).set_vocab_mode("exact")
    model = det.fit(Table(ROWS))
    weights, lut, cuckoo = model.profile.device_membership()
    docs = [t.encode() for t in EVAL]
    single = BatchRunner(
        weights=weights, lut=lut, cuckoo=cuckoo,
        spec=model.profile.spec, batch_size=8, strategy="hybrid",
    ).score(docs)
    meshed = BatchRunner(
        weights=weights, lut=lut, cuckoo=cuckoo,
        spec=model.profile.spec, batch_size=8, strategy="hybrid",
        mesh=resolve_mesh("mesh"),
    ).score(docs)
    np.testing.assert_allclose(single, meshed, rtol=1e-4, atol=1e-3)


# ------------------------------------------------ vocab sharding (r2 #8) ----
def test_resolve_mesh_vocab_axis(eight_devices):
    mesh = resolve_mesh("mesh:vocab")
    assert mesh.shape["vocab"] == 2 and mesh.shape["data"] == 4
    # Axis grows until the per-shard table fits the replication budget.
    big = 8 * 256 * 1024 * 1024  # 8x the budget -> vocab axis = 8
    mesh = resolve_mesh("mesh:vocab", table_bytes=big)
    assert mesh.shape["vocab"] == 8 and mesh.shape["data"] == 1


def test_vocab_sharded_scores_bit_match_replicated(eight_devices):
    """Dense hashed table sharded over the vocab axis scores bit-identically
    to the replicated mesh (GSPMD local-gather + psum vs plain gather)."""
    from spark_languagedetector_tpu.ops.vocab import HASHED, VocabSpec

    rng = np.random.default_rng(5)
    spec = VocabSpec(HASHED, (1, 2, 3), hash_bits=12)
    V, L = spec.id_space_size, 5
    weights = rng.normal(size=(V, L)).astype(np.float32)
    docs = [
        bytes(rng.integers(97, 122, rng.integers(0, 120)).tolist())
        for _ in range(19)
    ] + [b""]

    rep = BatchRunner(
        weights=weights, lut=None, spec=spec,
        mesh=resolve_mesh("mesh"), length_buckets=(64, 128),
    )
    shard = BatchRunner(
        weights=weights, lut=None, spec=spec,
        mesh=resolve_mesh("mesh:vocab"), length_buckets=(64, 128),
    )
    assert "vocab" in str(shard.weights.sharding.spec)
    np.testing.assert_array_equal(shard.score(docs), rep.score(docs))


def test_public_api_mesh_vocab_backend(eight_devices):
    """set_backend('mesh:vocab') is reachable end-to-end and label-identical
    to the replicated mesh backend."""
    model = _fit()
    model.set_backend("mesh")
    want = model.transform(Table({"fulltext": EVAL})).column("lang").tolist()
    model.set_backend("mesh:vocab")
    runner = model._get_runner()
    assert runner.mesh is not None and runner.mesh.shape["vocab"] == 2
    got = model.transform(Table({"fulltext": EVAL})).column("lang").tolist()
    assert got == want


def test_mesh_vocab_falls_back_for_compact_profiles(eight_devices):
    """A cuckoo/LUT profile can't vocab-shard: 'mesh:vocab' must keep the
    full data axis instead of carving a useless vocab axis (which would
    duplicate compute on every device)."""
    det = LanguageDetector(LANGS, [1, 2, 3, 4, 5], 100)
    model = det.set_vocab_mode("exact").fit(Table(ROWS))
    model.set_backend("mesh:vocab")
    runner = model._get_runner()
    assert runner.cuckoo is not None  # compact membership form
    assert runner.mesh.shape["vocab"] == 1
    assert runner.mesh.shape["data"] == len(eight_devices)


def test_mesh_hist_strategy_matches_single_device(eight_devices):
    """strategy='hist' under a data-parallel mesh (shard_map around the
    pallas hist kernel) bit-matches the single-device gather scorer."""
    from spark_languagedetector_tpu.ops.cuckoo import build_cuckoo
    from spark_languagedetector_tpu.ops.vocab import EXACT, VocabSpec, gram_key

    rng = np.random.default_rng(23)
    spec = VocabSpec(EXACT, (1, 2, 3, 4, 5))
    grams = sorted(
        {bytes(rng.integers(97, 110, int(rng.integers(1, 6))).tolist())
         for _ in range(300)}
    )
    L = 6
    weights = np.zeros((len(grams) + 1, L), np.float32)
    weights[:-1] = rng.normal(size=(len(grams), L)).astype(np.float32)
    keys = [gram_key(g) for g in grams]
    cuckoo = build_cuckoo(
        np.asarray([k[0] for k in keys], np.int32),
        np.asarray([k[1] for k in keys], np.int32),
    )
    docs = [
        bytes(rng.integers(97, 112, rng.integers(0, 100)).tolist())
        for _ in range(21)
    ] + [b"", b"ab", bytes(b"abcde" * 120)]  # mesh pad rows + chunking

    ref = BatchRunner(
        weights=weights, lut=None, spec=spec, cuckoo=cuckoo,
        strategy="gather", length_buckets=(128, 256),
    ).score(docs)
    got = BatchRunner(
        weights=weights, lut=None, spec=spec, cuckoo=cuckoo,
        strategy="hist", mesh=resolve_mesh("mesh"),
        length_buckets=(128, 256),
    ).score(docs)
    np.testing.assert_allclose(got, ref, atol=1e-3)
