"""Single-probe bucket membership table (ops.bucket)."""

import numpy as np

from spark_languagedetector_tpu.ops.bucket import (
    HI_BITS,
    HI_SENTINEL,
    SLOTS,
    build_buckets_exact,
    build_buckets_hashed,
    lookup_numpy,
)
from spark_languagedetector_tpu.ops.cuckoo import build_cuckoo, lookup_numpy as cuckoo_lookup
from spark_languagedetector_tpu.ops.vocab import gram_key

RNG = np.random.default_rng(11)


def _keys(n=5000):
    grams = sorted(
        {bytes(RNG.integers(97, 123, int(RNG.integers(1, 6))).tolist())
         for _ in range(n)}
    )
    ks = [gram_key(g) for g in grams]
    return (np.asarray([k[0] for k in ks], np.int32),
            np.asarray([k[1] for k in ks], np.int32))


def test_exact_build_and_lookup_matches_cuckoo():
    lo, hi = _keys()
    G = len(lo)
    bt = build_buckets_exact(lo, hi)
    assert bt is not None and bt.kind == "exact"
    # every learned key resolves to its own row
    got = lookup_numpy(bt, lo, hi, miss=G)
    np.testing.assert_array_equal(got, np.arange(G))
    # random probe keys agree with the cuckoo table's membership answer
    ct = build_cuckoo(lo, hi)
    qlo = np.concatenate([lo[:200], RNG.integers(-2**31, 2**31 - 1, 500).astype(np.int32)])
    qhi = np.concatenate([hi[:200], RNG.integers(256, 1536, 500).astype(np.int32)])
    np.testing.assert_array_equal(
        lookup_numpy(bt, qlo, qhi, miss=G), cuckoo_lookup(ct, qlo, qhi)
    )


def test_exact_empty_slots_cannot_match():
    lo, hi = _keys(100)
    bt = build_buckets_exact(lo, hi)
    empties = bt.rows[:, SLOTS:] == HI_SENTINEL
    assert empties.any()
    assert HI_SENTINEL > 1535  # larger than any real packed hi


def test_hashed_build_and_lookup():
    V = 1 << 16
    ids = np.sort(RNG.choice(V, 3000, replace=False)).astype(np.int32)
    rows = RNG.permutation(3000).astype(np.int32)
    bt = build_buckets_hashed(ids, rows)
    assert bt is not None and bt.kind == "hashed"
    got = lookup_numpy(bt, ids, np.zeros_like(ids), miss=3000)
    np.testing.assert_array_equal(got, rows)
    # misses stay misses
    others = np.setdiff1d(np.arange(V, dtype=np.int32), ids)[:500]
    got = lookup_numpy(bt, others, np.zeros_like(others), miss=3000)
    assert (got == 3000).all()


def test_payload_packing_roundtrip():
    lo, hi = _keys(2000)
    G = len(lo)
    bt = build_buckets_exact(lo, hi)
    occupied = bt.rows[:, SLOTS:] != HI_SENTINEL
    payloads = bt.rows[:, SLOTS:][occupied]
    rows = payloads >> HI_BITS
    assert rows.min() >= 0 and rows.max() < G
    assert len(np.unique(rows)) == G  # every row placed exactly once
