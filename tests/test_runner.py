"""BatchRunner: bucketing, long-document chunking exactness, order recovery."""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_languagedetector_tpu.api.runner import BatchRunner
from spark_languagedetector_tpu.models.profile import GramProfile
from spark_languagedetector_tpu.ops.encoding import texts_to_bytes
from spark_languagedetector_tpu.ops.score import score_batch_numpy
from spark_languagedetector_tpu.ops.vocab import EXACT, VocabSpec

from .oracle import scores_oracle

LANGS = ("x", "y")
GRAM_MAP = {
    b"ab": [1.0, 0.0],
    b"bc": [0.5, 0.5],
    b"zz": [0.0, 2.0],
    b"abc": [3.0, 0.0],
}


def _runner(max_chunk=64, batch_size=4):
    profile = GramProfile.from_gram_map(GRAM_MAP, LANGS, (2, 3))
    weights, lut = profile.device_arrays()
    return profile, BatchRunner(
        weights=weights,
        lut=lut,
        spec=profile.spec,
        batch_size=batch_size,
        length_buckets=(16, max_chunk),
    )


def test_scores_in_input_order_across_buckets():
    profile, runner = _runner()
    texts = ["ab" * 20, "zz", "abc", "", "bc" * 3]
    docs = texts_to_bytes(texts)
    scores = runner.score(docs)
    for row, text in zip(scores, texts):
        expected = scores_oracle(text, GRAM_MAP, 2, [2, 3])
        np.testing.assert_allclose(row, expected, rtol=1e-5, atol=1e-7)


def test_long_document_chunking_is_exact():
    """A doc far longer than the largest bucket must score identically to an
    unchunked reference computation — overlap windows counted exactly once."""
    profile, runner = _runner(max_chunk=64)
    rng = np.random.default_rng(7)
    # Random text over a small alphabet so profile grams occur often,
    # including across chunk boundaries.
    text = "".join(rng.choice(list("abcz")) for _ in range(1000))
    scores = runner.score(texts_to_bytes([text]))
    expected = scores_oracle(text, GRAM_MAP, 2, [2, 3])
    np.testing.assert_allclose(scores[0], expected, rtol=1e-5)
    assert runner.metrics.counters["chunks_scored"] > 1  # really chunked


def test_chunking_matches_numpy_scorer_on_many_docs():
    profile, runner = _runner(max_chunk=32, batch_size=3)
    rng = np.random.default_rng(11)
    texts = [
        "".join(rng.choice(list("abcz ")) for _ in range(int(n)))
        for n in rng.integers(0, 200, size=17)
    ]
    docs = texts_to_bytes(texts)
    scores = runner.score(docs)
    weights, sorted_ids = profile.host_arrays()
    host = score_batch_numpy(docs, weights, sorted_ids, profile.spec)
    np.testing.assert_allclose(scores, host, rtol=1e-5, atol=1e-6)


def test_throughput_metrics_populated():
    profile, runner = _runner()
    runner.score(texts_to_bytes(["abc", "zz"]))
    assert runner.metrics.counters["docs_scored"] == 2
    assert runner.metrics.timers["score_s"] > 0
    assert runner.metrics.throughput("docs_scored", "score_s") > 0


def test_predict_ids_matches_host_argmax_with_chunked_docs():
    """The device-argmax label path must agree with np.argmax over score()
    for every doc — including chunked long docs (cross-chunk sums happen
    before argmax), empty docs (index 0), and tie rows (first max wins)."""
    rng = np.random.default_rng(31)
    spec = VocabSpec(EXACT, (1, 2))
    V, L = spec.id_space_size, 4
    weights = rng.normal(size=(V, L)).astype(np.float32)
    runner = BatchRunner(
        weights=jnp.asarray(weights), lut=None, spec=spec,
        strategy="gather", length_buckets=(64, 128), batch_size=8,
    )
    docs = [
        bytes(rng.integers(97, 122, rng.integers(0, 100)).tolist())
        for _ in range(23)
    ] + [b"", b"a", bytes(b"tie" * 200)]  # chunked doc at 600 > 128
    scores = runner.score(docs)
    ids = runner.predict_ids(docs)
    np.testing.assert_array_equal(ids, np.argmax(scores, axis=1))
    assert ids[len(docs) - 3] == 0  # empty doc -> first language (Q6)


def test_dispatch_workers_bitwise_identical():
    """Concurrent dispatch (dispatch_workers > 1) must return exactly what
    serial dispatch returns — same plan, same batches, plan-ordered
    results — for both the score and label paths, chunked docs included."""
    rng = np.random.default_rng(41)
    spec = VocabSpec(EXACT, (1, 2))
    weights = rng.normal(size=(spec.id_space_size, 4)).astype(np.float32)

    def make(workers):
        return BatchRunner(
            weights=jnp.asarray(weights), lut=None, spec=spec,
            strategy="gather", length_buckets=(32, 64), batch_size=4,
            dispatch_workers=workers,
        )

    docs = [
        bytes(rng.integers(97, 122, rng.integers(0, 80)).tolist())
        for _ in range(37)
    ] + [b"", bytes(b"xy" * 200)]  # empty + chunked (> 64)
    serial, threaded = make(1), make(4)
    np.testing.assert_array_equal(serial.score(docs), threaded.score(docs))
    np.testing.assert_array_equal(
        serial.predict_ids(docs), threaded.predict_ids(docs)
    )


def test_predict_ids_mesh(eight_devices):
    """Label path under a data-parallel mesh (pad rows dropped)."""
    rng = np.random.default_rng(33)
    spec = VocabSpec(EXACT, (1, 2))
    weights = rng.normal(size=(spec.id_space_size, 3)).astype(np.float32)
    from spark_languagedetector_tpu.api.runner import resolve_mesh

    docs = [
        bytes(rng.integers(97, 122, rng.integers(0, 60)).tolist())
        for _ in range(11)
    ]
    single = BatchRunner(
        weights=jnp.asarray(weights), lut=None, spec=spec,
        strategy="gather", length_buckets=(64,), batch_size=8,
    )
    meshed = BatchRunner(
        weights=jnp.asarray(weights), lut=None, spec=spec,
        strategy="gather", length_buckets=(64,), batch_size=8,
        mesh=resolve_mesh("mesh"),
    )
    np.testing.assert_array_equal(
        meshed.predict_ids(docs), np.argmax(single.score(docs), axis=1)
    )


def test_max_score_bytes_truncation():
    """maxScoreBytes: scoring a capped runner equals scoring pre-truncated
    docs on an uncapped one; docs at or under the cap are untouched; the
    cap never splits a UTF-8 character (ops.encoding.truncate_utf8)."""
    from spark_languagedetector_tpu.ops.encoding import truncate_utf8

    profile = GramProfile.from_gram_map(GRAM_MAP, LANGS, (2, 3))
    weights, lut = profile.device_arrays()

    def runner(cap=None):
        return BatchRunner(
            weights=weights, lut=lut, spec=profile.spec, batch_size=4,
            length_buckets=(16, 64), max_score_bytes=cap,
        )

    texts = ["ab" * 40, "zz", "abc", "", "bc" * 3, "ab" * 7 + "é" * 10]
    docs = texts_to_bytes(texts)
    capped = runner(cap=15).score(docs)
    manual = runner().score([truncate_utf8(d, 15) for d in docs])
    np.testing.assert_array_equal(capped, manual)
    # under-cap docs identical to uncapped scoring
    uncapped = runner().score(docs)
    for i, d in enumerate(docs):
        if len(d) <= 15:
            np.testing.assert_array_equal(capped[i], uncapped[i])

    # boundary safety: é is 2 bytes; a cut landing mid-char backs up
    b = "é" * 10
    enc = b.encode("utf-8")  # 20 bytes
    assert truncate_utf8(enc, 5) == ("é" * 2).encode()  # 5 -> 4 bytes
    assert truncate_utf8(enc, 4) == ("é" * 2).encode()
    assert truncate_utf8(b"abc", 2) == b"ab"
    assert truncate_utf8(b"abc", 3) == b"abc"
    assert truncate_utf8(b"\x80\x80\x80", 2) == b"\x80\x80"  # pathological


def test_model_max_score_bytes_param():
    """Model-level maxScoreBytes: capped transform equals transforming the
    truncated texts, and the param round-trips through persistence."""
    import tempfile

    from spark_languagedetector_tpu import LanguageDetectorModel, Table

    model = LanguageDetectorModel.from_gram_map(GRAM_MAP, (2, 3), LANGS)
    texts = ["ab" * 50, "zz" * 3, "abc"]
    plain = list(model.transform(Table({"fulltext": texts})).column("lang"))
    model.set_max_score_bytes(8)
    capped = list(model.transform(Table({"fulltext": texts})).column("lang"))
    ref = LanguageDetectorModel.from_gram_map(GRAM_MAP, (2, 3), LANGS)
    want = list(
        ref.transform(Table({"fulltext": [t[:8] for t in texts]})).column("lang")
    )
    assert capped == want
    assert plain[1:] == capped[1:]  # short docs unaffected

    with tempfile.TemporaryDirectory() as d:
        model.save(d + "/m")
        loaded = LanguageDetectorModel.load(d + "/m")
        assert loaded.get("maxScoreBytes") == 8


def test_max_score_bytes_low_byte_encoding_hard_slice():
    """With a non-UTF-8 encoding the cap is a hard byte slice: low_byte
    docs full of 0x80-0xBF bytes (ordinary characters there) must not be
    mistaken for UTF-8 continuations — the old behavior backed the cap
    off arbitrarily far below maxScoreBytes (ADVICE r5)."""
    from spark_languagedetector_tpu.ops.encoding import (
        LOW_BYTE,
        truncate_utf8,
    )

    profile = GramProfile.from_gram_map(GRAM_MAP, LANGS, (2, 3))
    weights, lut = profile.device_arrays()

    def runner(cap=None, encoding=LOW_BYTE):
        return BatchRunner(
            weights=weights, lut=lut, spec=profile.spec, batch_size=4,
            length_buckets=(16, 64), max_score_bytes=cap,
            score_encoding=encoding,
        )

    # A doc whose bytes past index 3 are all in 0x80-0xBF: utf-8
    # backtracking walks from the cap down to the first non-continuation
    # byte and keeps 3 bytes of 15; the hard slice keeps all 15.
    pathological = b"abc" + b"\xa0" * 40
    assert len(truncate_utf8(pathological, 15)) == 2  # the old misread
    docs = [pathological, b"ab" * 20, b"zz", b""]
    capped = runner(cap=15).score(docs)
    manual = runner().score([d[:15] for d in docs])
    np.testing.assert_array_equal(capped, manual)

    # UTF-8 runners keep the boundary-safe behavior.
    utf8_capped = runner(cap=15, encoding="utf8").score(docs)
    utf8_manual = runner(encoding="utf8").score(
        [truncate_utf8(d, 15) for d in docs]
    )
    np.testing.assert_array_equal(utf8_capped, utf8_manual)

    with pytest.raises(ValueError, match="score_encoding"):
        runner(encoding="latin1")


def test_model_low_byte_encoding_plumbs_to_runner():
    """predictEncoding reaches the runner: a low_byte model with a cap
    scores like hard-sliced low_byte docs (not utf-8 backtracked ones)."""
    from spark_languagedetector_tpu import LanguageDetectorModel, Table
    from spark_languagedetector_tpu.ops.encoding import (
        LOW_BYTE,
        text_to_bytes,
    )

    model = LanguageDetectorModel.from_gram_map(GRAM_MAP, (2, 3), LANGS)
    model.set_predict_encoding(LOW_BYTE)
    model.set_max_score_bytes(8)
    assert model._get_runner().score_encoding == LOW_BYTE

    # U+00A0 encodes to the single byte 0xA0 under low_byte.
    texts = ["ab       ab", "abzz"]
    got = list(model.transform(Table({"fulltext": texts})).column("lang"))
    ref = LanguageDetectorModel.from_gram_map(GRAM_MAP, (2, 3), LANGS)
    ref_runner = ref._get_runner()
    want_ids = ref_runner.predict_ids(
        [text_to_bytes(t, LOW_BYTE)[:8] for t in texts]
    )
    assert got == [LANGS[i] for i in want_ids]


def test_concurrent_score_callers_bitwise_identical():
    """The batcher's contract: N threads calling score()/predict_ids()
    concurrently on ONE runner get results bit-identical to serial calls
    — including under a chaos plan at score/dispatch (transients replay
    exactly)."""
    import threading

    from spark_languagedetector_tpu.resilience import faults
    from spark_languagedetector_tpu.resilience.faults import FaultPlan

    rng = np.random.default_rng(53)
    spec = VocabSpec(EXACT, (1, 2))
    weights = rng.normal(size=(spec.id_space_size, 4)).astype(np.float32)
    runner = BatchRunner(
        weights=jnp.asarray(weights), lut=None, spec=spec,
        strategy="gather", length_buckets=(32, 64), batch_size=4,
    )
    doc_sets = [
        [
            bytes(rng.integers(97, 122, rng.integers(0, 90)).tolist())
            for _ in range(7)
        ] + [b"", bytes(b"xy" * 100)]  # empty + chunked (> 64)
        for _ in range(8)
    ]
    serial_scores = [runner.score(ds) for ds in doc_sets]
    serial_ids = [runner.predict_ids(ds) for ds in doc_sets]

    def run_threads():
        out_scores = [None] * len(doc_sets)
        out_ids = [None] * len(doc_sets)

        def work(i):
            out_scores[i] = runner.score(doc_sets[i])
            out_ids[i] = runner.predict_ids(doc_sets[i])

        threads = [
            threading.Thread(target=work, args=(i,))
            for i in range(len(doc_sets))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return out_scores, out_ids

    got_scores, got_ids = run_threads()
    for want, got in zip(serial_scores, got_scores):
        np.testing.assert_array_equal(want, got)
    for want, got in zip(serial_ids, got_ids):
        np.testing.assert_array_equal(want, got)

    # Same contract with injected dispatch transients: the policy replays
    # the failed batch verbatim, so results stay exact.
    with faults.plan_scope(
        FaultPlan.parse("seed=11;score/dispatch:error@2,7,13")
    ):
        chaos_scores, chaos_ids = run_threads()
    for want, got in zip(serial_scores, chaos_scores):
        np.testing.assert_array_equal(want, got)
    for want, got in zip(serial_ids, chaos_ids):
        np.testing.assert_array_equal(want, got)
