"""The bench's incremental on-chip-suite runner (bench.run_tpu_hw_tests).

Exercised against fake pytest files so its contract — one streamed JSON
verdict per finished test, a {passed: k, of: n} summary, partial results
on budget expiry, and loud suite errors — is pinned without needing the
chip. Round 4's defect (an all-or-nothing subprocess timeout voiding the
whole suite's results) is the regression these guard against.
"""

import json
import sys

import pytest

import bench


def _run(capsys, monkeypatch, path, budget=60.0, timeout=None):
    monkeypatch.setenv("SLD_TPU_TESTS", "1")
    if timeout is not None:
        monkeypatch.setenv("SLD_TPU_TESTS_TIMEOUT_S", str(timeout))
    else:
        monkeypatch.delenv("SLD_TPU_TESTS_TIMEOUT_S", raising=False)
    bench.run_tpu_hw_tests(budget, test_path=str(path))
    err = capsys.readouterr().err
    lines = [json.loads(l) for l in err.splitlines() if l.startswith("{")]
    per_test = [l for l in lines if "tpu_hw_test" in l]
    summaries = [l for l in lines if "tpu_hw_tests" in l]
    assert len(summaries) == 1, err
    return per_test, summaries[0]["tpu_hw_tests"]


def test_streams_per_test_verdicts_and_summary(tmp_path, capsys, monkeypatch):
    f = tmp_path / "test_fakehw.py"
    f.write_text(
        "import pytest\n"
        "def test_ok(): pass\n"
        "def test_also_ok(): pass\n"
        "def test_bad(): assert False\n"
        "@pytest.mark.skip\n"
        "def test_skipped(): pass\n"
    )
    per_test, summary = _run(capsys, monkeypatch, f)
    assert {t["tpu_hw_test"]: t["status"] for t in per_test} == {
        "test_ok": "passed", "test_also_ok": "passed",
        "test_bad": "failed", "test_skipped": "skipped",
    }
    assert summary["passed"] == 2
    assert summary["of"] == 4
    assert summary["failed"] == 1
    assert summary["skipped"] == 1
    assert summary.get("pytest_exit") == 1  # pytest exits 1 on failures
    assert "budget_expired" not in summary


def test_budget_expiry_keeps_finished_results(tmp_path, capsys, monkeypatch):
    f = tmp_path / "test_fakehw.py"
    f.write_text(
        "import time\n"
        "def test_fast(): pass\n"
        "def test_slow(): time.sleep(300)\n"
    )
    # Generous pre-kill window: pytest-in-pytest startup on a loaded
    # single-CPU host can take several seconds before the fast verdict.
    per_test, summary = _run(capsys, monkeypatch, f, timeout=25)
    # The fast test's verdict survived the kill; the slow one never reports.
    assert {t["tpu_hw_test"] for t in per_test} == {"test_fast"}
    assert summary["passed"] == 1
    assert summary["of"] == 2
    assert summary["budget_expired"] is True


def test_collection_error_is_loud(tmp_path, capsys, monkeypatch):
    f = tmp_path / "test_fakehw.py"
    f.write_text("import nonexistent_module_xyz\n")
    per_test, summary = _run(capsys, monkeypatch, f)
    assert per_test == []
    assert summary["passed"] == 0
    assert summary["suite_error"] is True
    assert summary["pytest_exit"] != 0


def test_directory_and_selector_targets(tmp_path, capsys, monkeypatch):
    """The runner's verdict matching survives non-file targets: a directory
    (generic <file>.py::name matching) and a ::selector node id."""
    f = tmp_path / "test_fakehw.py"
    f.write_text("def test_one(): pass\ndef test_two(): pass\n")
    per_test, summary = _run(capsys, monkeypatch, tmp_path)  # directory
    assert summary["passed"] == 2 and summary["of"] == 2
    assert {t["tpu_hw_test"] for t in per_test} == {"test_one", "test_two"}
    per_test, summary = _run(capsys, monkeypatch, f"{f}::test_two")
    assert summary["passed"] == 1 and summary["of"] == 1
    assert per_test[0]["tpu_hw_test"] == "test_two"


def test_smoke_telemetry_emits_breakdown_block(tmp_path):
    """The bench's telemetry contract: its smoke path (same emission code
    the real configs use) writes a telemetry JSONL and returns the result
    with a per-stage breakdown block covering >= 4 distinct stages that
    span both fit and score — and the report CLI renders that tree."""
    import io
    from contextlib import redirect_stdout

    from spark_languagedetector_tpu.telemetry.report import main as report_main

    jsonl = str(tmp_path / "telemetry.jsonl")
    result = bench.smoke_telemetry(jsonl)
    tele = result["telemetry"]
    assert tele["jsonl"] == jsonl
    stages = tele["stages"]
    assert len(stages) >= 4
    assert any(p == "fit" or p.startswith("fit/") for p in stages)
    assert any(p == "score" or p.startswith("score/") for p in stages)
    for stats in stages.values():
        assert stats["count"] >= 1 and stats["total_s"] >= 0
        assert "p50" in stats and "p99" in stats
    # The JSONL the block points at feeds the report CLI.
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert report_main([jsonl]) == 0
    out = buf.getvalue()
    rendered = [
        p for p in stages
        if "/" not in p or p.rsplit("/", 1)[-1] in out
    ]
    assert len(rendered) >= 4
    assert "fit" in out and "score" in out


def test_opt_out_and_low_budget_skip(tmp_path, capsys, monkeypatch):
    monkeypatch.setenv("SLD_TPU_TESTS", "0")
    bench.run_tpu_hw_tests(9999.0, test_path=str(tmp_path / "none.py"))
    assert capsys.readouterr().err == ""
    # Opportunistic mode with <60s of budget left: don't start the suite.
    monkeypatch.setenv("SLD_TPU_TESTS", "")
    bench.run_tpu_hw_tests(10.0, test_path=str(tmp_path / "none.py"))
    assert capsys.readouterr().err == ""
