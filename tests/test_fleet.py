"""Replicated serving fleet: router health/failover, two-phase fleet swap.

The acceptance contract (ISSUE 9): a replica killed mid-traffic causes
zero dropped responses (mid-flight failures are retried on a surviving
replica, never on the replica the request just watched die), the killed
replica is ejected and re-admitted through a half-open probe, the
fleet-wide hot-swap is two-phase (any prepare failure aborts everywhere;
a mid-commit crash rolls back to one consistent version) with every
response answered by exactly one version and no client stream ever
interleaving versions, and the ``fleet/*`` chaos sites replay
deterministically — same seed, same failover/ejection sequence.
"""

import functools
import threading
import time

import numpy as np
import pytest

from spark_languagedetector_tpu import LanguageDetectorModel
from spark_languagedetector_tpu.ops.encoding import texts_to_bytes
from spark_languagedetector_tpu.resilience import faults
from spark_languagedetector_tpu.resilience.faults import FaultPlan
from spark_languagedetector_tpu.resilience.policy import CircuitBreaker, RetryPolicy
from spark_languagedetector_tpu.serve import ContinuousBatcher, ModelRegistry
from spark_languagedetector_tpu.serve.batcher import ServeOverloaded
from spark_languagedetector_tpu.serve.client import ServeClient, ServeHTTPError
from spark_languagedetector_tpu.serve.fleet import ServeFleet
from spark_languagedetector_tpu.serve.quarantine import QuarantineTable
from spark_languagedetector_tpu.serve.router import (
    FleetSaturated,
    FleetSwapError,
    NoReadyReplica,
    RouterServer,
)
from spark_languagedetector_tpu.serve.server import ServingServer
from spark_languagedetector_tpu.telemetry import REGISTRY

LANGS = ("x", "y")
GRAM_KEYS = (b"ab", b"bc", b"zz", b"abc")
TEXTS = ["abab", "zz", "abczz"]


@functools.lru_cache(maxsize=None)
def _model(seed=0):
    # Cached per seed: a runner's jit programs compile per instance, so
    # sharing model objects across tests is what keeps this module
    # inside the tier-1 budget. Tests that mutate runner state (breaker,
    # degraded flag) restore it before returning.
    rng = np.random.default_rng(seed)
    gram_map = {g: rng.normal(size=2).tolist() for g in GRAM_KEYS}
    return LanguageDetectorModel.from_gram_map(gram_map, (2, 3), LANGS)


def _models(seed, n=3):
    # The shared-object form ServeFleet.from_path uses: one copy of the
    # weights per process, replicas isolating serving state.
    return [_model(seed)] * n


ROUTER_KW = dict(
    probe_interval_ms=30.0, probe_timeout_s=2.0, dispatch_attempts=3,
    breaker_threshold=2, breaker_cooldown_s=0.15, drain_timeout_s=5.0,
    # These drills kill replicas under the same three TEXTS over and
    # over; an active quarantine table would (correctly) flag them as
    # queries of death and 422 the failover behavior being pinned.
    # tests/test_storm.py drills quarantine with its own tables.
    quarantine=QuarantineTable(0, name="fleet-test-off"),
)


def _fleet(seed=1, *, router_kw=None, **batcher_kw):
    batcher_kw.setdefault("max_wait_ms", 2)
    batcher_kw.setdefault("max_rows", 64)
    return ServeFleet(
        _models(seed), router_kw={**ROUTER_KW, **(router_kw or {})},
        **batcher_kw,
    )


@pytest.fixture()
def fleet():
    fl = _fleet()
    fl.start(probe=False)  # tests drive probe_once() deterministically
    try:
        yield fl
    finally:
        fl.close()


def _counter(name):
    return int(REGISTRY.snapshot()["counters"].get(name, 0))


# ----------------------------------------------------- liveness/readiness ---
def test_healthz_split_liveness_vs_readiness():
    """/healthz/live answers 200 whenever the process is up; /healthz/ready
    flips to 503 (with reasons) on breaker-open, degraded, and draining —
    the states a router must not route to."""
    registry = ModelRegistry()
    registry.install(_model(5))
    runner = registry.peek().runner
    with ServingServer(registry, port=0, max_wait_ms=2) as server:
        client = ServeClient(*server.address)
        assert client.livez()["live"]
        ready = client.readyz()
        assert ready["ready"] and ready["reasons"] == []
        assert ready["version"] == "v1"

        # Breaker open: live, NOT ready, and the raw status is 503.
        old_breaker = runner.breaker
        runner.breaker = CircuitBreaker(
            failure_threshold=1, cooldown_s=60.0, name="t"
        )
        runner.breaker.record_failure()
        ready = client.readyz()
        assert not ready["ready"] and "breaker_open" in ready["reasons"]
        with pytest.raises(ServeHTTPError) as exc:
            client._request_once("GET", "/healthz/ready")
        assert exc.value.status == 503
        assert client.livez()["live"]  # liveness unaffected
        runner.breaker = old_breaker

        # Degraded ladder active: live, not ready.
        runner._degraded_mode = True
        assert "degraded" in client.readyz()["reasons"]
        runner._degraded_mode = False

        # Draining: live, not ready; the combined /healthz reports both.
        server._draining = True
        ready = client.readyz()
        assert not ready["ready"] and "draining" in ready["reasons"]
        health = client.healthz()
        assert health["ok"] and not health["ready"] and health["draining"]
        server._draining = False
        assert client.readyz()["ready"]


def test_server_stop_drains_inflight_zero_loss():
    """A stop() issued mid-burst answers every accepted request before
    tearing down the batcher — zero accepted requests lost."""

    class SlowRunner:
        def __init__(self, runner):
            self.runner = runner
            self.calls = 0
            self.breaker = None

        def score(self, docs):
            self.calls += 1
            time.sleep(0.1)
            return self.runner.score(docs)

        def predict_ids(self, docs):
            return self.runner.predict_ids(docs)

    registry = ModelRegistry()
    registry.install(_model(6))
    runner = registry.peek().runner
    slow = SlowRunner(runner)
    batcher = ContinuousBatcher(slow, max_wait_ms=1, max_rows=4)
    server = ServingServer(registry, port=0, batcher=batcher).start()
    client = ServeClient(*server.address)
    texts = ["abab", "zz"]
    want = runner.score(texts_to_bytes(texts))
    n = 8
    results: list = [None] * n
    errors: list = []

    def work(i):
        try:
            scores, meta = client.score(texts)
            results[i] = scores
        except Exception as e:  # noqa: BLE001 - the test asserts none
            errors.append(f"request {i}: {e!r}")

    REGISTRY.reset()
    threads = [threading.Thread(target=work, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    # Wait until every request is ACCEPTED (admitted into the batcher),
    # so the zero-loss claim is unambiguous — then stop mid-burst, with
    # the earliest dispatches still in flight on the slow runner.
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if _counter("serve/admitted_requests") >= n:
            break
        time.sleep(0.005)
    assert _counter("serve/admitted_requests") >= n
    server.stop()  # drains: every accepted request answered
    for t in threads:
        t.join(timeout=30)
    batcher.close()
    assert not errors, errors[:3]
    for i, scores in enumerate(results):
        assert scores is not None, f"request {i} dropped"
        np.testing.assert_array_equal(scores, want)


# ------------------------------------------------------- client retries -----
def test_client_retries_503_with_retry_after_bounded():
    """ServeClient with a retry policy absorbs a transient shed (sleeping
    max(Retry-After, seeded backoff)), stays bounded under a persistent
    shed, and never retries 400."""
    registry = ModelRegistry()
    registry.install(_model(7))
    runner = registry.peek().runner
    with ServingServer(registry, port=0, max_wait_ms=2) as server:
        host, port = server.address
        client = ServeClient(host, port, retry_policy=RetryPolicy(
            max_attempts=3, base_delay_s=0.01, seed=7,
        ))
        REGISTRY.reset()
        with faults.plan_scope(FaultPlan.parse("seed=3;serve/admit:error@1")):
            scores, meta = client.score(TEXTS)  # shed once, then served
        np.testing.assert_array_equal(
            scores, runner.score(texts_to_bytes(TEXTS))
        )
        assert _counter("serve/client_retries") == 1

        # Bounded attempts: a persistent shed still raises, after
        # max_attempts - 1 retries.
        REGISTRY.reset()
        with faults.plan_scope(
            faults.FaultPlan.parse("seed=3;serve/admit:error@1-999")
        ):
            with pytest.raises(ServeHTTPError) as exc:
                client.score(TEXTS)
        assert exc.value.status == 503 and exc.value.shed
        assert _counter("serve/client_retries") == 2

        # 400 is the caller's bug: never retried.
        REGISTRY.reset()
        with pytest.raises(ServeHTTPError) as exc:
            client._request(
                "POST", "/score", {"texts": "not-a-list"}, idempotent=True
            )
        assert exc.value.status == 400
        assert _counter("serve/client_retries") == 0


def test_client_never_retries_504_deadline():
    class SleepyRunner:
        def __init__(self, runner):
            self.runner = runner
            self.calls = 0
            self.breaker = None

        def score(self, docs):
            self.calls += 1
            time.sleep(0.3)
            return self.runner.score(docs)

        def predict_ids(self, docs):
            self.calls += 1
            return self.runner.predict_ids(docs)

    registry = ModelRegistry()
    registry.install(_model(8))
    slow = SleepyRunner(registry.peek().runner)
    batcher = ContinuousBatcher(slow, max_wait_ms=1, max_rows=8)
    server = ServingServer(registry, port=0, batcher=batcher).start()
    try:
        host, port = server.address
        client = ServeClient(host, port, retry_policy=RetryPolicy(
            max_attempts=4, base_delay_s=0.01, seed=1,
        ))
        blocker = threading.Thread(
            target=lambda: ServeClient(host, port).score(["abab"] * 4)
        )
        REGISTRY.reset()
        blocker.start()
        for _ in range(400):  # wait until the dispatcher is actually busy
            if slow.calls:
                break
            time.sleep(0.005)
        with pytest.raises(ServeHTTPError) as exc:
            client.score(["zz"], deadline_ms=1.0)
        blocker.join(timeout=30)
        assert exc.value.status == 504
        assert _counter("serve/client_retries") == 0  # 504 is final
    finally:
        server.stop()
        batcher.close()


# ------------------------------------------------- registry two-phase -------
def test_registry_prepare_commit_two_phase():
    """prepare() is serving-invisible; commit() is the only flip; a
    version-name conflict is caught at commit time."""
    registry = ModelRegistry()
    v1 = registry.install(_model(1))
    prep = registry.prepare(_model(2))
    assert registry.current_version() == v1  # nothing flipped yet
    v2 = registry.commit(prep)
    assert registry.current_version() == v2 == "v2"
    dup = registry.prepare(_model(3), version="v2")
    with pytest.raises(Exception, match="already registered"):
        registry.commit(dup)
    assert registry.current_version() == v2  # failed commit changed nothing


# ------------------------------------------------------------- routing -----
def test_router_least_outstanding_with_deterministic_tie_break(fleet):
    router = fleet.router
    assert router.eligible() == ["r0", "r1", "r2"]
    h0 = router._pick(4, set())
    assert h0.name == "r0"  # all-idle tie: lowest replica index
    h1 = router._pick(4, set())
    assert h1.name == "r1"  # r0 now carries 4 outstanding rows
    h2 = router._pick(2, set())
    assert h2.name == "r2"
    h3 = router._pick(1, set())
    assert h3.name == "r2"  # 2 rows < 4: still the least loaded
    for h, rows in ((h0, 4), (h1, 4), (h2, 2), (h3, 1)):
        router._release(h, rows)
    assert router.outstanding("r2") == 0
    # The per-request exclusion set is honored even at a tie.
    h = router._pick(1, {"r0"})
    assert h.name == "r1"
    router._release(h, 1)


def test_router_http_parity_and_failover_on_killed_replica(fleet):
    """End-to-end over sockets: scores bit-identical to the direct
    runner; after an abrupt replica kill the next request (which the
    idle-fleet tie-break MUST route to the dead replica first) fails
    over to a survivor — answered exactly once, never on the dead one."""
    front = RouterServer(fleet.router, fleet=fleet, port=0).start()
    try:
        client = ServeClient(*front.address)
        runner = fleet.replicas[0].registry.peek().runner
        want = runner.score(texts_to_bytes(TEXTS))
        scores, meta = client.score(TEXTS)
        np.testing.assert_array_equal(scores, want)
        assert meta["version"] == "v1" and meta["replica"] == "r0"

        # A caller-side 400 answers as 400 through the front tier (never
        # flattened to 500, never a failover — the answer is final).
        REGISTRY.reset()
        with pytest.raises(ServeHTTPError) as exc:
            client._request("POST", "/score", {"texts": ["a"],
                                               "priority": "vip"})
        assert exc.value.status == 400
        assert _counter("fleet/failovers") == 0

        fleet.replica("r0").kill()
        scores, meta = client.score(TEXTS)  # routed r0 -> dies -> failover
        np.testing.assert_array_equal(scores, want)
        assert meta["replica"] != "r0"
        assert _counter("fleet/failovers") >= 1
        labels, meta = client.detect(TEXTS)
        ids = runner.predict_ids(texts_to_bytes(TEXTS))
        assert labels == [LANGS[int(i)] for i in ids]
    finally:
        front.stop()


def test_router_ejection_then_half_open_readmission(fleet):
    """A dead replica is ejected after `breaker_threshold` failed probes,
    stays ejected through the cooldown, and is re-admitted by exactly one
    successful half-open probe after revival."""
    REGISTRY.reset()
    fleet.replica("r0").kill()
    ev1 = fleet.router.probe_once()
    assert "r0:unreachable" in ev1
    ev2 = fleet.router.probe_once()
    assert "r0:unreachable:ejected" in ev2  # threshold=2
    assert "r0" not in fleet.router.eligible()
    assert _counter("fleet/ejections") == 1
    # Cooling down: no probe reaches the replica, it stays ejected.
    ev3 = fleet.router.probe_once()
    assert not any(e.startswith("r0") for e in ev3)
    assert "r0" not in fleet.router.eligible()

    # A FAILED half-open probe (still dead past the cooldown) re-opens
    # the breaker but is the same outage continuing — the ejection
    # counter must not inflate with outage length.
    time.sleep(0.2)
    ev_fail = fleet.router.probe_once()
    assert "r0:unreachable" in ev_fail  # half-open probe failed...
    assert _counter("fleet/ejections") == 1  # ...but no new ejection

    fleet.replica("r0").revive()
    time.sleep(0.2)  # cooldown 0.15s: the next probe is the half-open one
    ev4 = fleet.router.probe_once()
    assert "r0:readmitted" in ev4
    assert fleet.router.eligible() == ["r0", "r1", "r2"]
    assert _counter("fleet/readmissions") == 1


def test_router_sheds_fleet_wide_only_when_every_replica_saturated(fleet):
    """A single saturated replica is routed around; only when EVERY ready
    replica sheds does the router answer with a fleet-wide 503."""
    REGISTRY.reset()
    # One replica sheds (the first one tried): the request lands on r1.
    with faults.plan_scope(FaultPlan.parse("seed=2;serve/admit:error@1")):
        scores, meta = fleet.router.score(TEXTS)
    assert meta["replica"] == "r1"
    assert _counter("fleet/replica_saturated") == 1
    assert _counter("fleet/shed_requests") == 0
    # Every replica sheds: explicit fleet-wide rejection with Retry-After.
    with faults.plan_scope(FaultPlan.parse("seed=2;serve/admit:error@1-999")):
        with pytest.raises(FleetSaturated) as exc:
            fleet.router.score(TEXTS)
    assert exc.value.reason == "fleet_saturated"
    assert exc.value.retry_after_s > 0
    assert _counter("fleet/shed_requests") == 1


def test_router_no_ready_replica_is_explicit(fleet):
    for rep in fleet.replicas:
        rep.kill()
    for _ in range(2):  # threshold=2: both rounds fail every replica
        fleet.router.probe_once()
    assert fleet.router.eligible() == []
    with pytest.raises(NoReadyReplica) as exc:
        fleet.router.score(TEXTS)
    assert exc.value.reason == "no_ready_replica"
    assert exc.value.retry_after_s > 0


# ------------------------------------------------------ two-phase swap ------
def test_fleet_swap_atomic_under_concurrent_traffic(fleet):
    """Concurrent traffic across a fleet-wide swap: zero drops, every
    response answered by exactly one version with that version's exact
    scores, and no client stream ever sees the old version after its
    first new-version response."""
    fleet.router.start()  # background prober for this live test
    runner_v1 = _model(1)._get_runner()
    runner_v2 = _model(2)._get_runner()
    want = {
        "v1": runner_v1.score(texts_to_bytes(TEXTS)),
        "v2": runner_v2.score(texts_to_bytes(TEXTS)),
    }
    n_threads = 4
    streams: list[list] = [[] for _ in range(n_threads)]
    errors: list[str] = []
    started = threading.Barrier(n_threads + 1)
    stop = threading.Event()

    def work(i):
        started.wait(timeout=10)
        while not stop.is_set():
            try:
                scores, meta = fleet.router.score(TEXTS)
            except ServeOverloaded:
                time.sleep(0.01)  # transient: retry like a real client
                continue
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))
                return
            streams[i].append((meta["version"], scores))
            time.sleep(0.005)

    threads = [
        threading.Thread(target=work, args=(i,)) for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    started.wait(timeout=10)
    time.sleep(0.05)  # let old-version traffic land first
    v2 = fleet.swap(models=_models(2))
    time.sleep(0.2)  # and new-version traffic after
    stop.set()
    for t in threads:
        t.join(timeout=60)

    assert not errors, errors[:3]
    assert v2 == "v2"
    assert fleet.versions() == {"r0": "v2", "r1": "v2", "r2": "v2"}
    served = set()
    for i, stream in enumerate(streams):
        seen_new = False
        for version, scores in stream:
            served.add(version)
            np.testing.assert_array_equal(scores, want[version])
            if version == "v2":
                seen_new = True
            else:
                assert not seen_new, (
                    f"stream {i} interleaved v1 after v2"
                )
    assert "v2" in served  # the swap took traffic


def test_fleet_swap_refuses_concurrent_coordinator(fleet):
    """One swap/rollback at a time: a second coordinator fails fast
    instead of interleaving flips (two racing swaps could wedge the pin
    on a version no replica serves)."""
    assert fleet._swap_lock.acquire(blocking=False)
    try:
        with pytest.raises(FleetSwapError, match="in progress"):
            fleet.swap(models=_models(2))
        with pytest.raises(FleetSwapError, match="in progress"):
            fleet.rollback()
    finally:
        fleet._swap_lock.release()
    assert fleet.versions() == {"r0": "v1", "r1": "v1", "r2": "v1"}


def test_fleet_swap_phase1_failure_aborts_everywhere(fleet):
    """Any prepare failure aborts the swap on EVERY replica: nothing
    flips, the old version keeps serving."""
    REGISTRY.reset()
    with faults.plan_scope(FaultPlan.parse("seed=1;fleet/swap:error@2")):
        with pytest.raises(FleetSwapError, match="phase 1"):
            fleet.swap(models=_models(2))
    assert fleet.versions() == {"r0": "v1", "r1": "v1", "r2": "v1"}
    assert fleet.router.pinned_version == "v1"
    assert _counter("fleet/swap_aborts") == 1
    scores, meta = fleet.router.score(TEXTS)
    assert meta["version"] == "v1"
    np.testing.assert_array_equal(
        scores, _model(1)._get_runner().score(texts_to_bytes(TEXTS))
    )


def _crash_phase2(fl):
    """Run the deterministic mid-phase-2 crash: 3 prepares (calls 1-3),
    commit r0 (call 4) succeeds, commit r1 (call 5) crashes."""
    with faults.plan_scope(FaultPlan.parse("seed=1;fleet/swap:error@5")):
        with pytest.raises(FleetSwapError, match="phase 2") as exc:
            fl.swap(models=_models(2))
    return str(exc.value), fl.versions(), fl.router.pinned_version


def test_fleet_swap_phase2_crash_rolls_back_and_replays(fleet):
    """A crash mid-phase-2 rolls every flipped replica back — the fleet
    converges to ONE version on either side of the failure — and the
    same plan/seed replays to the identical outcome. A later clean swap
    then succeeds (the fleet is not wedged)."""
    REGISTRY.reset()
    msg_a, versions_a, pin_a = _crash_phase2(fleet)
    assert versions_a == {"r0": "v1", "r1": "v1", "r2": "v1"}
    assert pin_a == "v1"
    assert "rolled back" in msg_a
    # r0 flipped and rolled back: v2 sits retired in its history.
    r0_hist = [v["version"] for v in fleet.replicas[0].registry.versions()]
    assert r0_hist == ["v1", "v2"]
    scores, meta = fleet.router.score(TEXTS)
    assert meta["version"] == "v1"

    # Deterministic replay: same seed => same crash point, same outcome.
    msg_b, versions_b, pin_b = _crash_phase2(fleet)
    assert (msg_b, versions_b, pin_b) == (msg_a, versions_a, pin_a)
    assert _counter("fleet/swap_aborts") == 2

    # And the fleet is not wedged: a clean swap lands everywhere.
    v_next = fleet.swap(models=_models(3))
    assert set(fleet.versions().values()) == {v_next}
    assert fleet.router.pinned_version == v_next


def test_fleet_http_swap_rollback_and_healthz(fleet, tmp_path):
    """Admin swap/rollback through the router's HTTP front end, fleet
    health visible over the wire."""
    front = RouterServer(fleet.router, fleet=fleet, port=0).start()
    try:
        client = ServeClient(*front.address)
        model_b = _model(9)
        model_b.save(str(tmp_path / "m2"))
        runner_b = model_b._get_runner()
        v2 = client.swap(str(tmp_path / "m2"))
        assert v2 == "v2"
        scores, meta = client.score(TEXTS)
        assert meta["version"] == "v2"
        np.testing.assert_array_equal(
            scores, runner_b.score(texts_to_bytes(TEXTS))
        )
        health = client.healthz()
        assert health["pinned_version"] == "v2"
        assert [r["replica"] for r in health["replicas"]] == [
            "r0", "r1", "r2"
        ]
        assert all(r["version"] == "v2" for r in health["replicas"])
        assert client.readyz()["ready"]
        assert client.rollback() == "v1"
        _, meta = client.score(TEXTS)
        assert meta["version"] == "v1"
    finally:
        front.stop()


# -------------------------------------------------- deterministic chaos -----
def _probe_sequence():
    fl = _fleet(router_kw=dict(breaker_cooldown_s=30.0))
    try:
        seqs = []
        with faults.plan_scope(
            FaultPlan.parse("seed=7;fleet/probe:error%0.4")
        ):
            for _ in range(6):
                seqs.append(tuple(fl.router.probe_once()))
        return seqs
    finally:
        fl.close()


def test_chaos_fleet_probe_replays_deterministically():
    """Same %prob plan + seed on a fresh fleet => the identical
    unreachable/ejection sequence (the schedule hashes (seed, site,
    call), not wall-clock or process state)."""
    a = _probe_sequence()
    b = _probe_sequence()
    assert a == b
    flat = [e for s in a for e in s]
    assert any("unreachable" in e for e in flat)  # the plan actually fired
    assert any("ejected" in e for e in flat)


def _dispatch_sequence():
    fl = _fleet(router_kw=dict(breaker_threshold=5, breaker_cooldown_s=30.0))
    fl.start(probe=False)
    try:
        served = []
        with faults.plan_scope(
            FaultPlan.parse("seed=7;fleet/dispatch:error@1,4")
        ):
            for _ in range(4):
                scores, meta = fl.router.score(TEXTS)
                served.append(
                    (meta["replica"], _counter("fleet/failovers"))
                )
        return served
    finally:
        fl.close()


def test_chaos_fleet_dispatch_replays_deterministically():
    """fleet/dispatch faults at fixed call numbers produce the identical
    failover sequence on a fresh fleet: attempt 1 dies on r0 -> served by
    r1; later the counter schedule hits r0 again."""
    REGISTRY.reset()
    a = _dispatch_sequence()
    REGISTRY.reset()
    b = _dispatch_sequence()
    assert a == b
    # Request 1: dispatch call 1 fires on r0 -> failover -> r1 serves.
    assert a[0] == ("r1", 1)
    # Request 2: call 3 clean on the (idle-tie) r0.
    assert a[1][0] == "r0"
    # Request 3: call 4 fires on r0 again -> r1 serves, failovers == 2.
    assert a[2] == ("r1", 2)
    assert a[3][1] == 2  # request 4 clean


# ------------------------------------------------- dynamic membership -------
def test_router_dynamic_membership_add_remove(fleet):
    """Membership changes mid-flight: a drained-out member stops being
    routed to, a newly added member becomes eligible after its admission
    probe, and routing/least-outstanding composes unchanged on the new
    set (ISSUE 15)."""
    router = fleet.router
    router.probe_once()
    assert router.eligible() == ["r0", "r1", "r2"]
    assert router.remove_replica("r2", drain=True)
    assert router.eligible() == ["r0", "r1"]
    labels, meta = router.detect(TEXTS)
    assert meta["replica"] in ("r0", "r1")
    with pytest.raises(ValueError):
        router.remove_replica("r2")  # already detached: loud, not silent

    # Grow back through the fleet (registry + batcher + server + router
    # admission in one step): the joiner installs the pinned version.
    rep = fleet.add_replica(model=_model(1))
    assert rep.name == "r3"
    assert rep.registry.current_version() == "v1"
    assert sorted(router.eligible()) == ["r0", "r1", "r3"]
    runner = fleet.replicas[0].registry.peek().runner
    want = [LANGS[int(i)] for i in runner.predict_ids(texts_to_bytes(TEXTS))]
    for _ in range(4):
        labels, _meta = router.detect(TEXTS)
        assert labels == want
    # A duplicate name is refused loudly.
    with pytest.raises(ValueError):
        router.add_replica(rep, name="r3")


def test_remove_replica_midflight_strands_nothing(fleet):
    """The satellite hardening pin: removing a replica with requests
    still outstanding (drain timeout expires) must not strand the
    outstanding-rows accounting — the straggler's release lands on the
    detached handle, the zeroed gauge series stays zeroed, and a later
    re-add of the same (host, port) starts from clean accounting."""
    router = fleet.router
    router.probe_once()
    h = router._pick(5, {"r1", "r2"})
    assert h.name == "r0" and router.outstanding("r0") == 5
    # Drain cannot complete (5 rows outstanding): bounded, then detach.
    assert router.remove_replica("r0", drain=True, timeout_s=0.05) is False
    assert "r0" not in router.eligible()
    gauges = REGISTRY.snapshot()["gauges"]
    assert gauges["langdetect_fleet_outstanding_rows"]["replica=r0"] == 0.0
    # The straggler finishes: release updates the detached handle only —
    # no error, and the zeroed series is not resurrected.
    router._release(h, 5)
    assert h.outstanding_rows == 0
    gauges = REGISTRY.snapshot()["gauges"]
    assert gauges["langdetect_fleet_outstanding_rows"]["replica=r0"] == 0.0

    # Same (host, port) re-admitted: fresh handle, clean accounting.
    rep = fleet.replica("r0")  # still alive: only routing was detached
    router.add_replica(rep, name="r0")
    assert router.outstanding("r0") == 0
    assert "r0" in router.eligible()


def test_readd_same_address_gets_fresh_breaker(fleet):
    """The other half of the satellite pin: a member ejected (breaker
    open) and then REMOVED must not leave a breaker entry that blocks a
    later re-add on the same (host, port) — the joiner gets a fresh
    CLOSED breaker and is eligible on its admission probe, no cooldown
    owed."""
    router = fleet.router
    fleet.replica("r0").kill()
    router.probe_once()
    router.probe_once()  # threshold=2: ejected, breaker open
    assert router._handle("r0").breaker.state == "open"
    assert "r0" not in router.eligible()
    router.remove_replica("r0", drain=False)

    fleet.replica("r0").revive()  # same pinned port
    router.add_replica(fleet.replica("r0"), name="r0")
    # No sleep anywhere: were the open breaker inherited, eligibility
    # would owe the 0.15s cooldown + a half-open probe round.
    assert router._handle("r0").breaker.state == "closed"
    assert "r0" in router.eligible()
    labels, meta = router.detect(TEXTS, priority="interactive")
    assert meta["replica"] in router.eligible()


def test_fleet_membership_composes_with_swap(fleet):
    """The two-phase swap and rollback operate on whatever the
    membership is NOW: a post-construction joiner flips with the fleet,
    and a member that left is simply not part of the next protocol
    round."""
    fleet.add_replica(model=_model(1))  # -> r3
    assert len(fleet.replicas) == 4
    version = fleet.swap(models=_models(2, 4))
    assert version == "v2"
    assert set(fleet.versions().values()) == {"v2"}
    assert fleet.versions()["r3"] == "v2"

    # A joiner admitted AFTER the swap installs the pinned new version.
    rep = fleet.add_replica(model=_model(2))
    assert rep.registry.current_version() == "v2"

    fleet.remove_replica(rep.name)
    assert rep.name not in fleet.router.eligible()
    fleet.remove_replica("r3", drain=False)
    assert len(fleet.replicas) == 3
    target = fleet.rollback()
    assert target == "v1"
    assert set(fleet.versions().values()) == {"v1"}


def test_remove_last_replica_refused():
    fl = _fleet(seed=3)
    fl.start(probe=False)
    try:
        fl.router.probe_once()
        for name in ("r1", "r2"):
            fl.remove_replica(name, drain=False)
        with pytest.raises(ValueError):
            fl.remove_replica("r0")
        assert fl.router.eligible() == ["r0"]
    finally:
        fl.close()


# ------------------------------------------------------- bench smoke gate ---
def test_bench_smoke_fleet_trimmed(tmp_path):
    """Tier-1-sized fleet smoke: the full kill/eject/readmit/swap drill
    with trimmed load, hard-gated exactly like the CI gate."""
    import bench

    result = bench.smoke_fleet(str(tmp_path / "fleet.jsonl"), trimmed=True)
    assert result["ok"], result
    assert result["dropped_responses"] == 0
    assert result["argmax_parity"] == 1.0
    assert result["failovers"] >= 1
    assert result["ejections"] >= 1 and result["readmissions"] >= 1
    assert result["swap"]["interleaved_streams"] == 0


@pytest.mark.slow
def test_bench_smoke_fleet_full(tmp_path):
    import bench

    result = bench.smoke_fleet(str(tmp_path / "fleet_full.jsonl"))
    assert result["ok"], result
    assert sorted(result["swap"]["versions_served"]) == ["v1", "v2"]
    assert len(result["health"]["ready_replicas"]) == 3
