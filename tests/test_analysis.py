"""The static contract checker (spark_languagedetector_tpu/analysis).

Two halves:

* **The tier-1 gate** — ``test_shipped_tree_is_clean`` runs the checker
  over the real package + docs and fails on any unsuppressed violation.
  This is the enforcement surface every future PR inherits: a stray
  ``LANGDETECT_*`` read outside exec/config, a counter `compare`/`tune`
  consume that nothing emits, an unregistered fault site, host-impure
  code inside a traced function, or a doc table drifting from the code
  all fail here, with file:line and a fix hint.

* **Mutation-style rule coverage** — a fixture tree
  (tests/fixtures/analysis/) seeds at least one violation per rule
  family; each test proves its rule demonstrably *fires* (a checker that
  silently stopped checking would pass the gate forever). Plus pragma /
  allowlist suppression semantics, staleness detection, the pinned
  ``--json`` schema, and the CLI contract.

Pure AST work — no jax import, no device, fast enough for tier-1.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from spark_languagedetector_tpu.analysis import run_checks
from spark_languagedetector_tpu.analysis.allowlist import ALLOWLIST, Allow
from spark_languagedetector_tpu.analysis.check import (
    JSON_SCHEMA_VERSION,
    RULE_IDS,
    main as check_main,
)

REPO = Path(__file__).resolve().parent.parent
PACKAGE = REPO / "spark_languagedetector_tpu"
FIXTURE_ROOT = Path(__file__).resolve().parent / "fixtures" / "analysis" / "repo"
FIXTURE_PKG = FIXTURE_ROOT / "fixture_pkg"


@pytest.fixture(scope="module")
def fixture_report():
    return run_checks(
        package_dir=FIXTURE_PKG, repo_root=FIXTURE_ROOT, allowlist=()
    )


def _find(report, rule, file_part, message_part):
    return [
        v for v in report.violations
        if v.rule == rule and file_part in v.file and message_part in v.message
    ]


# ---------------------------------------------------------------- the gate --
def test_shipped_tree_is_clean():
    """THE tier-1 gate: zero unsuppressed violations over package + docs.

    If this fails, read the messages — each carries file:line and a fix
    hint; fix the contract drift (or, for a genuine exception, add a
    pragma/allowlist entry with a reason — docs/ANALYSIS.md §4).
    """
    report = run_checks(package_dir=PACKAGE, repo_root=REPO)
    rendered = "\n".join(
        f"{v.rule} {v.file}:{v.line}  {v.message}" for v in report.violations
    )
    assert report.ok, f"contract violations in the shipped tree:\n{rendered}"


def test_shipped_tree_suppressions_are_live():
    """Every checked-in allowlist entry still suppresses something (the
    staleness rule would otherwise fire inside the gate test; this one
    localizes the diagnosis)."""
    report = run_checks(package_dir=PACKAGE, repo_root=REPO)
    allow_used = {
        s["reason"] for s in report.suppressed if s["via"] == "allowlist"
    }
    assert {a.reason for a in ALLOWLIST} == allow_used


def test_gate_catches_reverted_knob_fix(tmp_path):
    """Acceptance pin: re-introducing a raw LANGDETECT_* env read outside
    exec/config (reverting the satellite fix) fails the gate."""
    pkg = tmp_path / "spark_languagedetector_tpu"
    shutil.copytree(
        PACKAGE, pkg, ignore=shutil.ignore_patterns("__pycache__")
    )
    target = pkg / "parallel" / "distributed.py"
    target.write_text(
        target.read_text(encoding="utf-8")
        + "\nimport os\n_RAW = os.environ.get('LANGDETECT_TPU_COORDINATOR')\n",
        encoding="utf-8",
    )
    report = run_checks(package_dir=pkg, repo_root=None)
    hits = _find(report, "R1", "parallel/distributed.py", "direct env read")
    assert hits, "the reverted raw env read must fail the analysis gate"


# ------------------------------------------------------------ R1 fixtures ---
def test_r1_direct_env_reads_fire(fixture_report):
    assert _find(fixture_report, "R1", "r1_env.py", "LANGDETECT_ALPHA")
    assert _find(fixture_report, "R1", "r1_env.py", "LANGDETECT_BETA")
    # subscript form, resolved through a module-level constant
    assert _find(
        fixture_report, "R1", "r1_env.py",
        "direct env read of LANGDETECT_GHOST_KNOB",
    )
    # .get form, resolved through an ANNOTATED module-level constant
    # (VAR: str = "LANGDETECT_…") — a missed assign spelling is an R1
    # bypass, so both forms are pinned
    assert _find(
        fixture_report, "R1", "r1_env.py",
        "direct env read of LANGDETECT_BETA",
    )


def test_r1_unknown_knob_literal_fires(fixture_report):
    assert _find(
        fixture_report, "R1", "r1_env.py",
        "knob literal LANGDETECT_GHOST_KNOB has no exec/config.KNOBS row",
    )


def test_r1_env_table_coverage_fires(fixture_report):
    assert _find(
        fixture_report, "R1", "docs/OBSERVABILITY.md",
        "LANGDETECT_BETA missing from the environment-variable table",
    )


# ------------------------------------------------------------ R2 fixtures ---
def test_r2_consumed_but_never_emitted_fires(fixture_report):
    assert _find(
        fixture_report, "R2", "telemetry/compare.py", "langdetect_ghost_gauge"
    )
    assert _find(
        fixture_report, "R2", "telemetry/compare.py", "ghost/ratio_counter"
    )
    assert _find(
        fixture_report, "R2", "telemetry/compare.py", "ghost/retries"
    )
    assert _find(fixture_report, "R2", "telemetry/compare.py", "ghostarea/")
    assert _find(
        fixture_report, "R2", "exec/tune.py", "ghost/tuner_counter"
    )


def test_r2_grammar_fires(fixture_report):
    assert _find(fixture_report, "R2", "r2_names.py", "BadGrammarName")
    assert _find(fixture_report, "R2", "r2_names.py", "no_slash_name")


def test_r2_doc_metric_sync_fires(fixture_report):
    assert _find(
        fixture_report, "R2", "docs/OBSERVABILITY.md", "ghost/counter"
    )
    assert _find(
        fixture_report, "R2", "docs/OBSERVABILITY.md",
        "ghost/span_nobody_emits",
    )
    assert _find(
        fixture_report, "R2", "docs/OBSERVABILITY.md",
        "langdetect_ghost_doc_gauge",
    )
    # sharing only the LEAF segment with a real span (ghost/pack vs the
    # emitted score/pack) must not satisfy the nesting allowance
    assert _find(
        fixture_report, "R2", "docs/OBSERVABILITY.md", "'ghost/pack'"
    )


def test_r2_good_names_pass(fixture_report):
    """Emitted + consumed + doc'd names that agree produce no noise —
    including the f-string family (exec/len/<edge>) and the derived
    tracked-ratio name (good/ratio)."""
    for good in (
        "good/counter", "good/hist", "good/retries", "good/ratio",
        "langdetect_fixture_gauge", "exec/len",
    ):
        bad = [
            v for v in fixture_report.violations if f"'{good}" in v.message
        ]
        assert not bad, bad


# ------------------------------------------------------------ R3 fixtures ---
def test_r3_fires_all_three_ways(fixture_report):
    assert _find(
        fixture_report, "R3", "r3_sites.py",
        "site 'not/a_site' is not in resilience/faults.SITES",
    )
    assert _find(
        fixture_report, "R3", "resilience/faults.py",
        "SITES entry 'ghost/site' has no inject() call site",
    )
    assert _find(
        fixture_report, "R3", "docs/RESILIENCE.md",
        "fault site 'ghost/site' is undocumented",
    )


# ------------------------------------------------------------ R4 fixtures ---
def test_r4_fires_per_impurity_class(fixture_report):
    for marker in (
        "time.perf_counter()",
        "print()",
        "np.random.rand()",
        "REGISTRY.incr() emission",
        "os.environ.get() read",
    ):
        assert _find(fixture_report, "R4", "r4_trace.py", marker), marker


def test_r4_host_side_code_not_flagged(fixture_report):
    lines = {
        v.line for v in fixture_report.violations if v.file == "r4_trace.py"
    }
    # host_side_is_fine's print/time calls sit on the last lines of the
    # fixture; no R4 violation may anchor there.
    text = (FIXTURE_PKG / "r4_trace.py").read_text(encoding="utf-8")
    start = text.splitlines().index("def host_side_is_fine(x):") + 1
    assert not {ln for ln in lines if ln >= start}


# ------------------------------------------------------------ R5 fixtures ---
def test_r5_pragma_suppression_honored(fixture_report):
    via_pragma = [
        s for s in fixture_report.suppressed
        if s["via"] == "pragma" and s["file"] == "r5_pragmas.py"
    ]
    assert len(via_pragma) == 2  # same-line and pragma-above forms
    suppressed_lines = {s["line"] for s in via_pragma}
    leaked = [
        v for v in fixture_report.violations
        if v.file == "r5_pragmas.py" and v.rule == "R1"
        and v.line in suppressed_lines
    ]
    assert not leaked


def test_r5_stale_pragma_fires(fixture_report):
    assert _find(
        fixture_report, "R5", "r5_pragmas.py", "stale suppression pragma"
    )


def test_r5_unknown_rule_id_fires_and_does_not_suppress(fixture_report):
    assert _find(fixture_report, "R5", "r5_pragmas.py", "unknown rule id")
    # the R1 under the bogus pragma still stands
    assert [
        v for v in fixture_report.violations
        if v.file == "r5_pragmas.py" and v.rule == "R1"
    ]


def test_r5_allowlist_suppression_and_staleness():
    live = Allow(
        "R1", "r1_env.py", "LANGDETECT_ALPHA", "fixture: live entry"
    )
    stale = Allow(
        "R1", "no_such_file.py", "never matches", "fixture: stale entry"
    )
    report = run_checks(
        package_dir=FIXTURE_PKG, repo_root=FIXTURE_ROOT,
        allowlist=(live, stale),
    )
    assert any(
        s["via"] == "allowlist" and s["reason"] == live.reason
        for s in report.suppressed
    )
    assert _find(report, "R5", "analysis/allowlist.py", "stale allowlist")
    assert not _find(report, "R1", "r1_env.py", "LANGDETECT_ALPHA")


def test_r5_allowlist_suppression_is_bounded():
    """An entry absorbs at most ``count`` matches (default 1): a SECOND
    read matching the documented exception's pattern is a new regression
    and must surface, not ride the allowlist."""
    broad = Allow("R1", "r1_env.py", "direct env read", "fixture: broad")
    report = run_checks(
        package_dir=FIXTURE_PKG, repo_root=FIXTURE_ROOT, allowlist=(broad,)
    )
    suppressed = [
        s for s in report.suppressed
        if s["via"] == "allowlist" and s["file"] == "r1_env.py"
    ]
    assert len(suppressed) == 1  # not every matching read
    assert _find(report, "R1", "r1_env.py", "direct env read")  # rest stand
    # raising count widens the budget, and a live entry is not stale
    wide = Allow(
        "R1", "r1_env.py", "direct env read", "fixture: wide", count=2
    )
    report2 = run_checks(
        package_dir=FIXTURE_PKG, repo_root=FIXTURE_ROOT, allowlist=(wide,)
    )
    assert len([
        s for s in report2.suppressed if s["via"] == "allowlist"
    ]) == 2
    assert not _find(report2, "R5", "analysis/allowlist.py", "stale")


# -------------------------------------------------------------- JSON + CLI --
def test_json_schema_pinned(fixture_report):
    doc = fixture_report.to_json()
    assert set(doc) == {
        "schema", "package", "ok", "total", "counts", "violations",
        "suppressed",
    }
    assert doc["schema"] == JSON_SCHEMA_VERSION
    assert doc["ok"] is False
    assert doc["total"] == len(doc["violations"]) > 0
    assert set(doc["counts"]) == set(RULE_IDS)
    assert sum(doc["counts"].values()) == doc["total"]
    for v in doc["violations"]:
        assert set(v) == {"rule", "file", "line", "message", "hint"}
        assert v["rule"] in RULE_IDS
        assert isinstance(v["line"], int) and v["line"] >= 1
    for s in doc["suppressed"]:
        assert s["via"] in ("pragma", "allowlist")
        assert s["reason"]
    json.dumps(doc)  # must be serializable as-is


def test_cli_clean_tree_exits_zero_without_jax():
    """The external-CI contract: ``python -m …analysis.check --json``
    exits 0 on the shipped tree, emits the pinned schema, and never
    imports jax (pure AST, cold-CI-host safe)."""
    code = (
        "import sys, json\n"
        "from spark_languagedetector_tpu.analysis.check import main\n"
        "rc = main(['--json'])\n"
        "assert 'jax' not in sys.modules, 'checker must not import jax'\n"
        "sys.exit(rc)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=REPO, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["ok"] is True and doc["schema"] == JSON_SCHEMA_VERSION


def test_cli_violations_exit_one(capsys):
    rc = check_main(["--root", str(FIXTURE_ROOT)])
    # fixture root has no spark_languagedetector_tpu dir -> usage error
    assert rc == 2
    rc = check_main(["--no-such-flag"])
    assert rc == 2


def test_cli_root_with_violations(tmp_path, capsys):
    pkg = tmp_path / "spark_languagedetector_tpu"
    pkg.mkdir()
    (pkg / "bad.py").write_text(
        "import os\nX = os.environ.get('LANGDETECT_WHATEVER')\n",
        encoding="utf-8",
    )
    rc = check_main(["--root", str(tmp_path), "--json"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] is False
    assert any(
        v["rule"] == "R1" and v["file"] == "bad.py"
        for v in doc["violations"]
    )
