"""Mesh/sharding layer on the 8-virtual-device CPU backend: distributed
scoring and fit must agree bit-for-bit with the single-device ops."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_languagedetector_tpu.models.profile import GramProfile
from spark_languagedetector_tpu.ops import fit_tpu
from spark_languagedetector_tpu.ops.encoding import pad_batch, texts_to_bytes
from spark_languagedetector_tpu.ops.score import score_batch
from spark_languagedetector_tpu.ops.vocab import EXACT, HASHED, VocabSpec
from spark_languagedetector_tpu.parallel import mesh as mesh_lib
from spark_languagedetector_tpu.parallel import sequence as seq_lib
from spark_languagedetector_tpu.parallel import sharded as sharded_lib

from .oracle import scores_oracle

LANGS = ("de", "en")
GRAM_MAP = {b"ab": [1.0, 0.0], b"bc": [0.5, 0.5], b"abc": [0.0, 2.0]}


@pytest.fixture(scope="module")
def mesh8(eight_devices):
    return mesh_lib.build_mesh(data=4, vocab=2)


def _profile():
    return GramProfile.from_gram_map(GRAM_MAP, LANGS, (2, 3))


def test_build_mesh_shapes(eight_devices):
    m = mesh_lib.build_mesh()
    assert m.shape[mesh_lib.DATA_AXIS] == 8
    m2 = mesh_lib.build_mesh(data=2, vocab=4)
    assert m2.shape == {"data": 2, "vocab": 4}
    with pytest.raises(ValueError):
        mesh_lib.build_mesh(data=16, vocab=1)


def test_sharded_scorer_matches_single_device(mesh8):
    profile = _profile()
    weights, sorted_ids = profile.device_arrays()
    scorer = sharded_lib.make_sharded_scorer(mesh8, profile.spec)
    texts = ["abcabc", "bcbc", "zzz", "", "ab", "abcbcab", "b", "cab"]
    batch, lengths = pad_batch(texts_to_bytes(texts), pad_to=16)
    got = np.asarray(scorer(batch, lengths, weights, sorted_ids))
    want = np.asarray(
        score_batch(batch, lengths, weights, sorted_ids, spec=profile.spec)
    )
    np.testing.assert_allclose(got, want, rtol=1e-6)
    for t, row in zip(texts, got):
        np.testing.assert_allclose(
            row, scores_oracle(t, GRAM_MAP, 2, [2, 3]), rtol=1e-5, atol=1e-7
        )


def test_sharded_fit_step_matches_single_device(mesh8):
    spec = VocabSpec(HASHED, (1, 2), hash_bits=8)
    fit_step = sharded_lib.make_sharded_fit_step(mesh8, spec, 2)
    texts = ["abab", "bcbc", "xyxy", "zz", "a", "", "abc", "bca"]
    batch, lengths = pad_batch(texts_to_bytes(texts), pad_to=8)
    lang_ids = np.asarray([0, 0, 1, 1, 0, 1, 0, 1], dtype=np.int32)
    acc = jnp.zeros((spec.id_space_size, 2), dtype=jnp.int32)
    got = np.asarray(fit_step(batch, lengths, lang_ids, acc))
    want = np.asarray(
        fit_tpu.gram_counts_dense(batch, lengths, lang_ids, spec=spec, num_langs=2)
    )
    np.testing.assert_array_equal(got, want)


def test_full_training_step_on_mesh(mesh8):
    spec = VocabSpec(HASHED, (1, 2), hash_bits=8)
    step = sharded_lib.training_step(mesh8, spec, 2, profile_size=4)
    texts = ["abab", "bcbc", "xyxy", "zz", "a", "q", "abc", "bca"]
    batch, lengths = pad_batch(texts_to_bytes(texts), pad_to=8)
    lang_ids = np.asarray([0, 0, 1, 1, 0, 1, 0, 1], dtype=np.int32)
    acc = jnp.zeros((spec.id_space_size, 2), dtype=jnp.int32)
    counts, weights, top_rows = step(batch, lengths, lang_ids, acc)
    assert counts.shape == (256, 2)
    assert weights.shape == (256, 2)
    assert top_rows.shape == (2, 4)
    # Weight formula parity on the dense path.
    w_host = np.asarray(weights)
    c_host = np.asarray(counts)
    present = c_host > 0
    nlangs = present.sum(axis=1, keepdims=True)
    expected = np.log1p(np.where(nlangs > 0, present / np.maximum(nlangs, 1), 0))
    np.testing.assert_allclose(w_host, expected, rtol=1e-6)


def test_dense_fit_matches_host_fit_exact_small():
    """Device dense fit == host sparse fit on an exact bigram vocab."""
    from spark_languagedetector_tpu.ops import fit as fit_host

    spec = VocabSpec(EXACT, (1, 2))
    texts = ["abab", "bcbc", "xy", "z"]
    docs = texts_to_bytes(texts)
    lang_idx = np.asarray([0, 0, 1, 1])
    batch, lengths = pad_batch(docs, pad_to=8)
    dense = np.asarray(
        fit_tpu.gram_counts_dense(
            batch, lengths, lang_idx.astype(np.int32), spec=spec, num_langs=2
        )
    )
    sparse = fit_host.extract_gram_counts(docs, lang_idx, 2, spec)
    dense_from_sparse = np.zeros_like(dense)
    dense_from_sparse[sparse.ids, sparse.langs] = sparse.counts
    np.testing.assert_array_equal(dense, dense_from_sparse)


def test_score_long_document_across_mesh(mesh8):
    profile = _profile()
    weights, sorted_ids = profile.device_arrays()
    rng = np.random.default_rng(3)
    text = "".join(rng.choice(list("abcz")) for _ in range(3000))
    doc = text.encode("utf-8")
    got = seq_lib.score_long_document(
        doc, weights, sorted_ids, profile.spec, mesh8, chunk_size=256
    )
    expected = scores_oracle(text, GRAM_MAP, 2, [2, 3])
    np.testing.assert_allclose(got, expected, rtol=1e-5)


def test_ring_scoring_matches_psum_path(mesh8):
    profile = _profile()
    weights, sorted_ids = profile.device_arrays()
    rng = np.random.default_rng(5)
    text = "".join(rng.choice(list("abc")) for _ in range(2000))
    doc = text.encode("utf-8")
    batch, lengths, limits = seq_lib.chunk_grid(
        doc, mesh8.shape["data"], 256, profile.spec.gram_lengths
    )
    total = np.asarray(
        seq_lib.ring_score_chunks(
            jnp.asarray(batch),
            jnp.asarray(lengths),
            jnp.asarray(limits),
            weights,
            sorted_ids,
            profile.spec,
            mesh8,
        )
    )
    expected = scores_oracle(text, GRAM_MAP, 2, [2, 3])
    np.testing.assert_allclose(total, expected, rtol=1e-5)


def test_host_shard_covers_everything():
    from spark_languagedetector_tpu.parallel.distributed import host_shard

    s = host_shard(10)
    assert s == slice(0, 10)  # single-process: everything
