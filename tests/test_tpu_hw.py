"""Real-TPU parity tests (opt-in: SLD_TPU_TESTS=1).

The rest of the suite pins JAX to the CPU backend (conftest), so the Mosaic
lowering of the pallas kernels — 128-aligned lane slices, rank-2
intermediates, SMEM scalar arrays — is never exercised in-process. These
tests spawn a subprocess WITHOUT the CPU pin and compare the compiled pallas
kernel against the gather strategy on the real device (ADVICE round 1: a
Mosaic regression must not first surface at runtime on hardware).

Opt-in rather than auto-detected because probing a tunneled TPU can block for
minutes when the tunnel is unhealthy; CI with local chips sets SLD_TPU_TESTS=1.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("SLD_TPU_TESTS") != "1",
    reason="real-TPU tests are opt-in: set SLD_TPU_TESTS=1",
)

_PARITY_SCRIPT = r"""
import json, sys
import numpy as np
import jax, jax.numpy as jnp

if jax.default_backend() == "cpu":
    print(json.dumps({"skip": "no accelerator"}))
    sys.exit(0)

from spark_languagedetector_tpu.ops import score as S
from spark_languagedetector_tpu.ops import score_pallas as SP
from spark_languagedetector_tpu.ops.encoding import pad_batch
from spark_languagedetector_tpu.ops.vocab import EXACT, VocabSpec

spec = VocabSpec(EXACT, (1, 2))
rng = np.random.default_rng(23)
weights = rng.normal(size=(spec.id_space_size, 5)).astype(np.float32)
docs = [b"", b"a", b"ab"] + [
    bytes(rng.integers(0, 256, int(rng.integers(1, 700)), dtype=np.uint8))
    for _ in range(29)
]
batch, lengths = pad_batch(docs, pad_to=1024)
batch, lengths = jnp.asarray(batch), jnp.asarray(lengths)
w = jnp.asarray(weights)
w1, w2 = SP.weight_views(w, spec)

got = np.asarray(
    SP.score_batch_pallas(batch, lengths, w1, w2, None, spec=spec)
)
want = np.asarray(S.score_batch(batch, lengths, w, None, spec=spec))
err = float(np.abs(got - want).max())
print(json.dumps({"max_abs_err": err, "backend": jax.default_backend()}))
"""


def _run_on_device(script: str) -> dict:
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env["XLA_FLAGS"] = ""  # no virtual-device forcing
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=900,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, (
        f"device subprocess failed:\nstdout: {proc.stdout[-1000:]}\n"
        f"stderr: {proc.stderr[-4000:]}"
    )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_pallas_matches_gather_on_hardware():
    result = _run_on_device(_PARITY_SCRIPT)
    if "skip" in result:
        pytest.skip(result["skip"])
    assert result["max_abs_err"] < 1e-2


_HIST_SCRIPT = r"""
import json, sys
import numpy as np
import jax, jax.numpy as jnp

if jax.default_backend() == "cpu":
    print(json.dumps({"skip": "no accelerator"}))
    sys.exit(0)

from spark_languagedetector_tpu.ops import score as S
from spark_languagedetector_tpu.ops import score_pallas as SP
from spark_languagedetector_tpu.ops.encoding import pad_batch
from spark_languagedetector_tpu.ops.vocab import EXACT, VocabSpec

spec = VocabSpec(EXACT, (1, 2))
rng = np.random.default_rng(29)
L = SP.MAX_PALLAS_LANGS + 9  # histogram path (non-fused)
weights = rng.normal(size=(spec.id_space_size, L)).astype(np.float32)
docs = [b"", b"a"] + [
    bytes(rng.integers(0, 256, int(rng.integers(1, 700)), dtype=np.uint8))
    for _ in range(30)
]
batch, lengths = pad_batch(docs, pad_to=1024)
batch, lengths = jnp.asarray(batch), jnp.asarray(lengths)
w = jnp.asarray(weights)
w1, w2 = SP.weight_views(w, spec)
assert w2.ndim == 2
got = np.asarray(SP.score_batch_pallas(batch, lengths, w1, w2, None, spec=spec))
want = np.asarray(S.score_batch(batch, lengths, w, None, spec=spec))
err = float(np.abs(got - want).max())
print(json.dumps({"max_abs_err": err}))
"""


def test_hist_kernel_matches_gather_on_hardware():
    result = _run_on_device(_HIST_SCRIPT)
    if "skip" in result:
        pytest.skip(result["skip"])
    assert result["max_abs_err"] < 1e-2


_CUCKOO_SCRIPT = r"""
import json, sys
import numpy as np
import jax, jax.numpy as jnp

if jax.default_backend() == "cpu":
    print(json.dumps({"skip": "no accelerator"}))
    sys.exit(0)

from spark_languagedetector_tpu.ops import score as S
from spark_languagedetector_tpu.ops import vocab as V
from spark_languagedetector_tpu.ops.cuckoo import build_cuckoo
from spark_languagedetector_tpu.ops.encoding import pad_batch
from spark_languagedetector_tpu.ops.vocab import EXACT, VocabSpec

spec = VocabSpec(EXACT, (1, 4, 5))
rng = np.random.default_rng(31)
docs = [bytes(rng.integers(97, 105, int(rng.integers(1, 400)), dtype=np.uint8))
        for _ in range(24)]
grams = sorted({d[i:i+n] for d in docs for n in (1, 4, 5)
                for i in range(max(len(d) - n + 1, 0))})[:5000]
keys = [V.gram_key(g) for g in grams]
table = build_cuckoo(
    np.asarray([k[0] for k in keys], np.int32),
    np.asarray([k[1] for k in keys], np.int32),
)
weights = np.concatenate(
    [rng.normal(size=(len(grams), 3)), np.zeros((1, 3))]
).astype(np.float32)
batch, lengths = pad_batch(docs, pad_to=512)
got = np.asarray(S.score_batch_cuckoo(
    jnp.asarray(batch), jnp.asarray(lengths), jnp.asarray(weights),
    jnp.asarray(table.entries()),
    seed1=table.seed1, seed2=table.seed2, spec=spec,
))
# host oracle via sorted-id searchsorted
ids = np.asarray([spec.gram_to_id(g) for g in grams], np.int64)
order = np.argsort(ids)
sw = np.concatenate([weights[:len(grams)][order], np.zeros((1, 3), np.float32)])
want = S.score_batch_numpy(docs, sw, ids[order], spec)
err = float(np.abs(got - want).max())
print(json.dumps({"max_abs_err": err}))
"""


def test_cuckoo_scorer_matches_host_on_hardware():
    result = _run_on_device(_CUCKOO_SCRIPT)
    if "skip" in result:
        pytest.skip(result["skip"])
    assert result["max_abs_err"] < 1e-2


_MESH_SCRIPT = r"""
import json, sys
import numpy as np
import jax, jax.numpy as jnp

if jax.default_backend() == "cpu":
    print(json.dumps({"skip": "no accelerator"}))
    sys.exit(0)

from spark_languagedetector_tpu.api.runner import BatchRunner
from spark_languagedetector_tpu.models.profile import GramProfile
from spark_languagedetector_tpu.ops.score import score_batch_numpy
from spark_languagedetector_tpu.ops.vocab import EXACT, VocabSpec
from spark_languagedetector_tpu.parallel.mesh import build_mesh

# A TPU mesh over every visible chip (data=1 on a single chip) compiles the
# SAME shard_map + Mosaic programs a pod runs — the CPU-mesh tests cannot
# see Mosaic lowering failures under shard_map.
rng = np.random.default_rng(43)
accel = [d for d in jax.devices() if d.platform != "cpu"]
mesh = build_mesh(data=len(accel), vocab=1, devices=accel)
worst = 0.0
for spec, strategies in [
    (VocabSpec(EXACT, (1, 2)), ["pallas", "gather"]),
    (VocabSpec(EXACT, (1, 2, 4, 5)), ["hist", "hybrid"]),
]:
    L = 9
    docs = [bytes(rng.integers(97, 109, int(rng.integers(0, 600))).tolist())
            for _ in range(19)] + [b"", bytes(b"xy" * 400)]
    grams = sorted({d[i:i+n] for d in docs[:10] for n in spec.gram_lengths
                    for i in range(max(len(d)-n+1, 0))})[:2000]
    ids = np.asarray(sorted({spec.gram_to_id(g) for g in grams}), np.int64)
    prof = GramProfile(
        spec=spec, languages=tuple(f"l{i}" for i in range(L)), ids=ids,
        weights=rng.normal(size=(len(ids), L)).astype(np.float32),
    )
    w, lut, cuckoo = prof.device_membership()
    hw, hids = prof.host_arrays()
    want = score_batch_numpy(docs, hw, hids, spec)
    for strat in strategies:
        r = BatchRunner(weights=w, lut=lut, spec=spec, cuckoo=cuckoo,
                        strategy=strat, mesh=mesh,
                        length_buckets=(128, 512), batch_size=8)
        got = np.asarray(r.score(docs))
        rel = float(np.abs(got - want).max() / max(np.abs(want).max(), 1))
        worst = max(worst, rel)
        if not (np.asarray(r.predict_ids(docs))
                == np.argmax(want, axis=1)).all():
            print(json.dumps({"labels_diverged": strat}))
            sys.exit(1)
print(json.dumps({"max_rel_err": worst}))
"""


def test_mesh_strategies_on_hardware():
    """shard_map-wrapped strategies (pallas/hist/hybrid/gather) on a real
    TPU mesh — the programs a multi-chip pod runs, which the CPU-mesh
    substrate compiles with a different backend."""
    result = _run_on_device(_MESH_SCRIPT)
    if "skip" in result:
        pytest.skip(result["skip"])
    assert result["max_rel_err"] < 1e-4


_ONEHOT_SCRIPT = r"""
import json, sys
import numpy as np
import jax, jax.numpy as jnp

if jax.default_backend() == "cpu":
    print(json.dumps({"skip": "no accelerator"}))
    sys.exit(0)

from spark_languagedetector_tpu.ops import score as S
from spark_languagedetector_tpu.ops.encoding import pad_batch
from spark_languagedetector_tpu.ops.vocab import EXACT, VocabSpec

spec = VocabSpec(EXACT, (1, 2))
rng = np.random.default_rng(31)
weights = rng.normal(size=(spec.id_space_size, 33)).astype(np.float32)
docs = [b"", b"a"] + [
    bytes(rng.integers(0, 256, int(rng.integers(1, 700)), dtype=np.uint8))
    for _ in range(30)
]
batch, lengths = pad_batch(docs, pad_to=1024)
got = np.asarray(S.score_batch_onehot(
    jnp.asarray(batch), jnp.asarray(lengths), jnp.asarray(weights), spec=spec
))
want = S.score_batch_numpy(docs, weights, None, spec)  # dense mode
err = float(np.abs(got - want).max())
print(json.dumps({"max_abs_err": err}))
"""


_HIST_E2E_SCRIPT = r"""
import json, sys
import numpy as np
import jax, jax.numpy as jnp

if jax.default_backend() == "cpu":
    print(json.dumps({"skip": "no accelerator"}))
    sys.exit(0)

from spark_languagedetector_tpu.api.runner import BatchRunner
from spark_languagedetector_tpu.models.profile import GramProfile
from spark_languagedetector_tpu.ops.vocab import EXACT, VocabSpec

# Exact n=1..5 profile (config-3 shape): membership is cuckoo-derived, so
# strategy='hist' runs the single-probe bucket table + histogram kernel —
# the path the long-gram bench configs actually execute on chip.
spec = VocabSpec(EXACT, (1, 2, 3, 4, 5))
rng = np.random.default_rng(37)
docs = [b"", b"a", b"abcd"] + [
    bytes(rng.integers(97, 107, int(rng.integers(1, 600)), dtype=np.uint8))
    for _ in range(29)
]
grams = sorted({d[i:i+n] for d in docs[3:20] for n in (1, 2, 3, 4, 5)
                for i in range(max(len(d) - n + 1, 0))})[:4000]
ids = np.asarray(sorted(spec.gram_to_id(g) for g in grams), np.int64)
profile = GramProfile(
    spec=spec, languages=tuple(f"l{i}" for i in range(7)),
    ids=ids, weights=rng.normal(size=(len(ids), 7)).astype(np.float32),
)
w, lut, cuckoo = profile.device_membership()

def make(strategy):
    return BatchRunner(
        weights=w, lut=lut, spec=spec, cuckoo=cuckoo, strategy=strategy,
        length_buckets=(128, 256, 512), batch_size=16,
    )

hist, gather = make("hist"), make("gather")
assert hist._hist_state() is not None and hist._hist_state()[3] is not None, \
    "expected bucket membership (cuckoo-derived), got LUT fallback"
gs = np.asarray(gather.score(docs))
hs = np.asarray(hist.score(docs))
err = float(np.abs(hs - gs).max())
labels_equal = bool((hist.predict_ids(docs) == np.argmax(gs, axis=1)).all())
print(json.dumps({"max_abs_err": err, "labels_equal": labels_equal}))
"""


def test_hist_strategy_end_to_end_on_hardware():
    """BatchRunner(strategy='hist') — single-probe bucket membership composed
    with the histogram kernel, n=1..5 — against the gather escape hatch on
    chip, through the full plan/pack/dispatch/label pipeline."""
    result = _run_on_device(_HIST_E2E_SCRIPT)
    if "skip" in result:
        pytest.skip(result["skip"])
    assert result["max_abs_err"] < 1e-2
    assert result["labels_equal"]


_HYBRID_E2E_SCRIPT = r"""
import json, sys
import numpy as np
import jax, jax.numpy as jnp

if jax.default_backend() == "cpu":
    print(json.dumps({"skip": "no accelerator"}))
    sys.exit(0)

from spark_languagedetector_tpu.api.runner import BatchRunner
from spark_languagedetector_tpu.models.profile import GramProfile
from spark_languagedetector_tpu.ops.vocab import EXACT, VocabSpec

# Exact n=1..3 with a compact profile: strategy='hybrid' scores n<=2 through
# the pallas histogram kernel over the dense sub-table and n=3 through the
# gather path — the auto choice for config 2's shape on TPU.
spec = VocabSpec(EXACT, (1, 2, 3))
rng = np.random.default_rng(41)
docs = [b"", b"ab"] + [
    bytes(rng.integers(97, 110, int(rng.integers(1, 500)), dtype=np.uint8))
    for _ in range(30)
]
grams = sorted({d[i:i+n] for d in docs[2:20] for n in (1, 2, 3)
                for i in range(max(len(d) - n + 1, 0))})[:3000]
ids = np.asarray(sorted(spec.gram_to_id(g) for g in grams), np.int64)
profile = GramProfile(
    spec=spec, languages=tuple(f"l{i}" for i in range(5)),
    ids=ids, weights=rng.normal(size=(len(ids), 5)).astype(np.float32),
)
w, lut, cuckoo = profile.device_membership()

def make(strategy):
    return BatchRunner(
        weights=w, lut=lut, spec=spec, cuckoo=cuckoo, strategy=strategy,
        length_buckets=(128, 256, 512), batch_size=16,
    )

hybrid, gather = make("hybrid"), make("gather")
gs = np.asarray(gather.score(docs))
hs = np.asarray(hybrid.score(docs))
err = float(np.abs(hs - gs).max())
labels_equal = bool((hybrid.predict_ids(docs) == np.argmax(gs, axis=1)).all())
print(json.dumps({"max_abs_err": err, "labels_equal": labels_equal}))
"""


def test_hybrid_strategy_end_to_end_on_hardware():
    """BatchRunner(strategy='hybrid') — pallas short-gram kernel + long-gram
    gather — against the pure gather strategy on chip."""
    result = _run_on_device(_HYBRID_E2E_SCRIPT)
    if "skip" in result:
        pytest.skip(result["skip"])
    assert result["max_abs_err"] < 1e-2
    assert result["labels_equal"]


def test_onehot_scorer_matches_host_on_hardware():
    """The onehot einsum path must score at full f32 precision on TPU.

    Regression for the default-matmul-precision bug: `hist @ W` at the TPU
    default (bf16 passes) drifted scores by ~1e-2..0.24 — enough to flip
    argmax near ties. All scoring dots pin Precision.HIGHEST.
    """
    result = _run_on_device(_ONEHOT_SCRIPT)
    if "skip" in result:
        pytest.skip(result["skip"])
    assert result["max_abs_err"] < 1e-3


_BLOCKED_TOPK_SCRIPT = r"""
import json, sys
import numpy as np
import jax, jax.numpy as jnp

if jax.default_backend() == "cpu":
    print(json.dumps({"skip": "no accelerator"}))
    sys.exit(0)

from spark_languagedetector_tpu.ops.fit_tpu import (
    finalize_topk_blocked, masked_candidate_weights, top_k_rows,
    top_k_rows_blocked,
)

rng = np.random.default_rng(41)
V, L, k, block = 20000, 6, 80, 4096  # block does not divide V's tail
mismatches = []
for sub in range(4):
    counts = rng.integers(0, 5, size=(V, L)).astype(np.int32)
    counts[rng.random((V, L)) < 0.7] = 0  # sparse => giant tie plateaus
    counts[:, 1] = 0  # an empty language
    mode = ["parity", "counts"][sub % 2]
    masked = masked_candidate_weights(jnp.asarray(counts), weight_mode=mode)
    mnp = np.asarray(masked)
    occ = counts.sum(axis=1) > 0
    occ_set = {i for i in range(V) if occ[i]}
    single = np.asarray(top_k_rows(masked, k=k))
    blocked = np.asarray(top_k_rows_blocked(masked, k=k, block=block))
    fin = np.asarray(finalize_topk_blocked(
        jnp.asarray(counts), weight_mode=mode, k=k, block=block
    ))
    for lang in range(L):
        order = sorted(range(V), key=lambda i: (-mnp[i, lang], i))
        want = set(order[:k]) & occ_set
        for path, got in (
            ("single", set(single[lang].tolist()) & occ_set),
            ("blocked", {i for i in blocked[lang].tolist() if i < V} & occ_set),
            ("finalize", {i for i in fin[lang].tolist() if i < V} & occ_set),
        ):
            if got != want:
                mismatches.append([path, mode, lang])
print(json.dumps({"mismatches": mismatches}))
"""


def test_blocked_topk_matches_host_order_on_hardware():
    """The blocked/scanned top-k paths (the config-3-scale device-fit
    route) must select exactly the host (value desc, id asc) order on the
    REAL chip: the TPU lax.top_k lowering's tie behavior is where host/
    device fit divergence has historically come from, and the CPU suite
    cannot see its lowering. A 24-lang-case sweep with plateau-heavy
    tables; a 420-case on-chip fuzz at 4 shapes ran clean when this path
    landed (round 5)."""
    result = _run_on_device(_BLOCKED_TOPK_SCRIPT)
    if "skip" in result:
        pytest.skip(result["skip"])
    assert result["mismatches"] == [], result["mismatches"]


_FUSED_SCRIPT = r"""
import json, sys
import numpy as np
import jax, jax.numpy as jnp

if jax.default_backend() == "cpu":
    print(json.dumps({"skip": "no accelerator"}))
    sys.exit(0)

from spark_languagedetector_tpu.ops import score as S
from spark_languagedetector_tpu.ops import score_fused as SF
from spark_languagedetector_tpu.ops.encoding import pad_batch
from spark_languagedetector_tpu.ops.vocab import EXACT, HASHED, VocabSpec

rng = np.random.default_rng(37)
docs = [b"", b"a", b"ab"] + [
    bytes(rng.integers(0, 256, int(rng.integers(1, 700)), dtype=np.uint8))
    for _ in range(29)
]
batch, lengths = pad_batch(docs, pad_to=1024)
batch, lengths = jnp.asarray(batch), jnp.asarray(lengths)
out = {}

# Exact bigram dense (the config-1 headline form): ids fully in-kernel.
spec = VocabSpec(EXACT, (1, 2))
w = rng.normal(size=(spec.id_space_size, 5)).astype(np.float32)
want = np.asarray(S.score_batch(batch, lengths, jnp.asarray(w), None, spec=spec))
ft = SF.build_fused_tables(w, None, spec, None)
got = np.asarray(SF.score_batch_fused(
    batch, lengths, jnp.asarray(ft.wq), jnp.asarray(ft.scales), None, None,
    spec=spec, layout=ft.layout,
))
out["exact_dense_err"] = float(np.abs(got - want).max())
labels, best = SF.detect_batch_fused(
    batch, lengths, jnp.asarray(ft.wq), jnp.asarray(ft.scales), None, None,
    spec=spec, layout=ft.layout,
)
out["detect_label_mismatches"] = int(
    (np.asarray(labels) != want.argmax(axis=1)).sum()
)

# Hashed exact12 LUT split (the production 2^20 form): in-kernel short-gram
# ids + FNV-fold rows plane, int8 quantized tiles.
spec = VocabSpec(HASHED, (1, 2, 3, 4, 5), hash_bits=18)
n_rows = 4000
lut = np.full(spec.id_space_size, n_rows, np.int32)
ids = rng.choice(spec.id_space_size, n_rows, replace=False)
lut[ids] = np.arange(n_rows)
wc = np.zeros((n_rows + 1, 8), np.float32)
wc[:-1] = rng.normal(size=(n_rows, 8)).astype(np.float32)
want = np.asarray(S.score_batch(
    batch, lengths, jnp.asarray(wc), jnp.asarray(lut), spec=spec
))
ft = SF.build_fused_tables(wc, lut, spec, None)
got = np.asarray(SF.score_batch_fused(
    batch, lengths, jnp.asarray(ft.wq), jnp.asarray(ft.scales),
    jnp.asarray(ft.lut), None, spec=spec, layout=ft.layout,
))
out["exact12_lut_err"] = float(np.abs(got - want).max())
ftq = SF.build_fused_tables(wc, lut, spec, "int8")
gotq = np.asarray(SF.score_batch_fused(
    batch, lengths, jnp.asarray(ftq.wq), jnp.asarray(ftq.scales),
    jnp.asarray(ftq.lut), None, spec=spec, layout=ftq.layout,
))
out["int8_label_agreement"] = float(
    (gotq.argmax(axis=1) == want.argmax(axis=1)).mean()
)
print(json.dumps(out))
"""


def test_fused_kernel_matches_gather_on_hardware():
    """The fused detect megakernel's Mosaic lowering (in-kernel FNV fold,
    streamed quantized table tiles, in-kernel argmax) vs the gather
    reference on the real chip — the CPU suite only sees interpret mode."""
    result = _run_on_device(_FUSED_SCRIPT)
    if "skip" in result:
        pytest.skip(result["skip"])
    assert result["exact_dense_err"] < 1e-2
    assert result["exact12_lut_err"] < 1e-2
    assert result["detect_label_mismatches"] == 0
    assert result["int8_label_agreement"] >= 0.999
