"""Real-TPU parity tests (opt-in: SLD_TPU_TESTS=1).

The rest of the suite pins JAX to the CPU backend (conftest), so the Mosaic
lowering of the pallas kernels — 128-aligned lane slices, rank-2
intermediates, SMEM scalar arrays — is never exercised in-process. These
tests spawn a subprocess WITHOUT the CPU pin and compare the compiled pallas
kernel against the gather strategy on the real device (ADVICE round 1: a
Mosaic regression must not first surface at runtime on hardware).

Opt-in rather than auto-detected because probing a tunneled TPU can block for
minutes when the tunnel is unhealthy; CI with local chips sets SLD_TPU_TESTS=1.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("SLD_TPU_TESTS") != "1",
    reason="real-TPU tests are opt-in: set SLD_TPU_TESTS=1",
)

_PARITY_SCRIPT = r"""
import json, sys
import numpy as np
import jax, jax.numpy as jnp

if jax.default_backend() == "cpu":
    print(json.dumps({"skip": "no accelerator"}))
    sys.exit(0)

from spark_languagedetector_tpu.ops import score as S
from spark_languagedetector_tpu.ops import score_pallas as SP
from spark_languagedetector_tpu.ops.encoding import pad_batch
from spark_languagedetector_tpu.ops.vocab import EXACT, VocabSpec

spec = VocabSpec(EXACT, (1, 2))
rng = np.random.default_rng(23)
weights = rng.normal(size=(spec.id_space_size, 5)).astype(np.float32)
docs = [b"", b"a", b"ab"] + [
    bytes(rng.integers(0, 256, int(rng.integers(1, 700)), dtype=np.uint8))
    for _ in range(29)
]
batch, lengths = pad_batch(docs, pad_to=1024)
batch, lengths = jnp.asarray(batch), jnp.asarray(lengths)
w = jnp.asarray(weights)
w1, w2 = SP.weight_views(w, spec)

got = np.asarray(
    SP.score_batch_pallas(batch, lengths, w1, w2, None, spec=spec)
)
want = np.asarray(S.score_batch(batch, lengths, w, None, spec=spec))
err = float(np.abs(got - want).max())
print(json.dumps({"max_abs_err": err, "backend": jax.default_backend()}))
"""


def _run_on_device(script: str) -> dict:
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env["XLA_FLAGS"] = ""  # no virtual-device forcing
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=900,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, (
        f"device subprocess failed:\nstdout: {proc.stdout[-1000:]}\n"
        f"stderr: {proc.stderr[-4000:]}"
    )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_pallas_matches_gather_on_hardware():
    result = _run_on_device(_PARITY_SCRIPT)
    if "skip" in result:
        pytest.skip(result["skip"])
    assert result["max_abs_err"] < 1e-2
