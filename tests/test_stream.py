"""Streaming micro-batch loop tests."""

import pytest

from spark_languagedetector_tpu import LanguageDetectorModel
from spark_languagedetector_tpu.stream.microbatch import (
    StreamingQuery,
    kafka_source,
    memory_source,
    run_stream,
)


def _model():
    return LanguageDetectorModel.from_gram_map(
        {b"ab": [1.0, 0.0], b"xy": [0.0, 1.0]}, [2], ["a", "x"]
    )


def test_stream_scores_all_batches_in_order():
    rows = [{"fulltext": "ababab"}, {"fulltext": "xyxy"}] * 5
    outputs = []
    query = run_stream(
        _model(),
        memory_source(rows, batch_rows=3),
        sink=lambda t: outputs.extend(t.column("lang").tolist()),
    )
    assert query.batches == 4  # ceil(10 / 3)
    assert query.rows == 10
    assert outputs == ["a", "x"] * 5
    assert query.rows_per_second > 0


def test_stream_max_batches_limits_consumption():
    rows = [{"fulltext": "ab"}] * 100
    seen = []
    query = run_stream(
        _model(),
        memory_source(rows, batch_rows=10),
        sink=lambda t: seen.append(t.num_rows),
        max_batches=3,
    )
    assert query.batches == 3
    assert seen == [10, 10, 10]


def test_stream_retries_transient_failure_once():
    rows = [{"fulltext": "ab"}] * 4
    model = _model()
    real_transform = model.transform
    fails = {"left": 1}

    def flaky(batch):
        if fails["left"]:
            fails["left"] -= 1
            raise RuntimeError("transient device hiccup")
        return real_transform(batch)

    model.transform = flaky
    query = run_stream(
        model, memory_source(rows, 2), sink=lambda t: None
    )
    assert query.batches == 2
    assert query.metrics.counters["retries"] == 1


def test_stream_progress_callback():
    rows = [{"fulltext": "ab"}] * 6
    snapshots = []
    run_stream(
        _model(),
        memory_source(rows, 2),
        sink=lambda t: None,
        on_progress=lambda q: snapshots.append((q.batches, q.last_batch_rows)),
    )
    assert snapshots == [(1, 2), (2, 2), (3, 2)]


def test_stream_prefetch_zero_synchronous_path():
    rows = [{"fulltext": "ababab"}, {"fulltext": "xyxy"}] * 5
    outputs = []
    query = run_stream(
        _model(),
        memory_source(rows, batch_rows=3),
        sink=lambda t: outputs.extend(t.column("lang").tolist()),
        prefetch=0,
    )
    assert query.batches == 4
    assert outputs == ["a", "x"] * 5


def test_stream_prefetch_deep_pipeline_preserves_order():
    rows = [{"fulltext": "ababab"}, {"fulltext": "xyxy"}] * 20
    outputs = []
    query = run_stream(
        _model(),
        memory_source(rows, batch_rows=4),
        sink=lambda t: outputs.extend(t.column("lang").tolist()),
        prefetch=3,
    )
    assert query.batches == 10
    assert query.rows == 40
    assert outputs == ["a", "x"] * 20


def test_stream_prefetch_respects_max_batches():
    rows = [{"fulltext": "ab"}] * 100
    seen = []
    query = run_stream(
        _model(),
        memory_source(rows, batch_rows=10),
        sink=lambda t: seen.append(t.num_rows),
        max_batches=3,
        prefetch=2,
    )
    assert query.batches == 3
    assert seen == [10, 10, 10]


def test_stream_prefetch_retry_still_works():
    rows = [{"fulltext": "ab"}] * 4
    model = _model()
    real_transform = model.transform
    fails = {"left": 1}

    def flaky(batch):
        if fails["left"]:
            fails["left"] -= 1
            raise RuntimeError("transient device hiccup")
        return real_transform(batch)

    model.transform = flaky
    query = run_stream(model, memory_source(rows, 2), sink=lambda t: None)
    assert query.batches == 2
    assert query.metrics.counters["retries"] == 1


def test_kafka_source_gated_on_missing_dependency():
    with pytest.raises(RuntimeError, match="kafka-python"):
        next(kafka_source("topic", 10))


def test_stream_prefetch_worker_spans_attach_to_stream_parent():
    """Telemetry spans from prefetch worker threads must land under the
    engine's "stream" root (explicit-parent attachment), with exact counts
    — concurrent workers must never cross-wire or corrupt the span tree."""
    from spark_languagedetector_tpu.telemetry import REGISTRY

    REGISTRY.reset()
    captured = []
    REGISTRY.add_sink(type("S", (), {"emit": staticmethod(captured.append)})())
    try:
        rows = [{"fulltext": "ababab"}, {"fulltext": "xyxy"}] * 20
        query = run_stream(
            _model(),
            memory_source(rows, batch_rows=4),
            sink=lambda t: None,
            prefetch=3,
            workers=3,
        )
    finally:
        REGISTRY.clear_sinks()
    assert query.batches == 10
    stages = REGISTRY.stage_summary()
    # Worker transforms attach under stream/ — never as parentless roots.
    assert stages["stream/transform"]["count"] == 10
    assert stages["stream/batch"]["count"] == 10
    assert stages["stream/batch/sink"]["count"] == 10
    assert stages["stream"]["count"] == 1
    assert "transform" not in stages  # no orphaned root spans
    # The runner's nested scoring spans keep their own subtree.
    assert stages["stream/transform/score"]["count"] == 10
    span_paths = {e["path"] for e in captured if e["event"] == "telemetry.span"}
    assert {"stream", "stream/transform", "stream/batch",
            "stream/batch/sink"} <= span_paths
    # Queue-depth and stall distributions were recorded per batch.
    snap = REGISTRY.snapshot()
    assert snap["histograms"]["stream/queue_depth"]["count"] == 10
    assert snap["histograms"]["stream/prefetch_stall_s"]["count"] == 10


def test_stream_synchronous_path_records_spans_without_stall():
    from spark_languagedetector_tpu.telemetry import REGISTRY

    REGISTRY.reset()
    rows = [{"fulltext": "ab"}] * 6
    run_stream(_model(), memory_source(rows, 2), sink=lambda t: None,
               prefetch=0)
    stages = REGISTRY.stage_summary()
    assert stages["stream/transform"]["count"] == 3
    # No futures → no prefetch stalls recorded.
    assert "stream/prefetch_stall_s" not in REGISTRY.snapshot()["histograms"]


def test_stream_explicit_single_worker_preserves_order():
    """workers=1 forces serial transforms (the conservative pipeline)."""
    rows = [{"fulltext": "ababab"}, {"fulltext": "xyxy"}] * 10
    outputs = []
    query = run_stream(
        _model(),
        memory_source(rows, batch_rows=4),
        sink=lambda t: outputs.extend(t.column("lang").tolist()),
        prefetch=3,
        workers=1,
    )
    assert query.batches == 5
    assert outputs == ["a", "x"] * 10


# --------------------------------------------------- kafka source (stubbed) --
class _FakeRecord:
    def __init__(self, value):
        self.value = value


class _FakeConsumer:
    """Scripted kafka.KafkaConsumer stand-in: each poll() returns the next
    scripted {partition: [records]} dict ({} once the script runs out)."""

    instances: list = []

    def __init__(self, topic, **kwargs):
        self.topic = topic
        self.kwargs = kwargs
        self.polls = list(self.script)
        _FakeConsumer.instances.append(self)

    def poll(self, timeout_ms):
        self.poll_timeout_ms = timeout_ms
        return self.polls.pop(0) if self.polls else {}


@pytest.fixture
def fake_kafka(monkeypatch):
    """Install a fake `kafka` module so kafka_source's consumer loop runs."""
    import sys
    import types

    mod = types.ModuleType("kafka")
    mod.KafkaConsumer = _FakeConsumer
    _FakeConsumer.instances = []
    monkeypatch.setitem(sys.modules, "kafka", mod)
    return mod


def test_kafka_source_batches_decodes_and_flushes(fake_kafka):
    """One poll round: full batches yield as they fill; the round's ragged
    tail flushes after the poll; an empty poll yields nothing."""
    from itertools import islice

    _FakeConsumer.script = [
        {
            "tp0": [
                _FakeRecord(b"hello"),
                _FakeRecord(b"welt"),
                _FakeRecord("already-a-str"),
            ],
            "tp1": [_FakeRecord(b"\xff\xferaw")],  # invalid UTF-8 -> replace
        },
        {},  # empty poll round: nothing buffered, nothing yielded
        {"tp0": [_FakeRecord(12345)]},  # non-bytes non-str -> str()
    ]
    src = kafka_source(
        "mytopic", batch_rows=2, poll_timeout_s=0.5, group_id="g1"
    )
    tables = list(islice(src, 3))
    assert [t.column("fulltext").tolist() for t in tables] == [
        ["hello", "welt"],
        ["already-a-str", "��raw"],  # tail flush of round 1
        ["12345"],  # round 3's tail flush
    ]
    (consumer,) = _FakeConsumer.instances
    assert consumer.topic == "mytopic"
    assert consumer.kwargs == {"group_id": "g1"}
    assert consumer.poll_timeout_ms == 500


def test_kafka_source_drives_run_stream(fake_kafka):
    """End-to-end: kafka source -> engine -> sink, bounded by max_batches."""
    _FakeConsumer.script = [
        {"tp": [_FakeRecord(b"ababab"), _FakeRecord(b"xyxy")]},
        {"tp": [_FakeRecord(b"abab")]},
    ]
    outputs = []
    query = run_stream(
        _model(),
        kafka_source("t", batch_rows=2),
        sink=lambda t: outputs.extend(t.column("lang").tolist()),
        max_batches=2,
    )
    assert query.batches == 2
    assert outputs == ["a", "x", "a"]
