"""Streaming micro-batch loop tests."""

import pytest

from spark_languagedetector_tpu import LanguageDetectorModel
from spark_languagedetector_tpu.stream.microbatch import (
    StreamingQuery,
    kafka_source,
    memory_source,
    run_stream,
)


def _model():
    return LanguageDetectorModel.from_gram_map(
        {b"ab": [1.0, 0.0], b"xy": [0.0, 1.0]}, [2], ["a", "x"]
    )


def test_stream_scores_all_batches_in_order():
    rows = [{"fulltext": "ababab"}, {"fulltext": "xyxy"}] * 5
    outputs = []
    query = run_stream(
        _model(),
        memory_source(rows, batch_rows=3),
        sink=lambda t: outputs.extend(t.column("lang").tolist()),
    )
    assert query.batches == 4  # ceil(10 / 3)
    assert query.rows == 10
    assert outputs == ["a", "x"] * 5
    assert query.rows_per_second > 0


def test_stream_max_batches_limits_consumption():
    rows = [{"fulltext": "ab"}] * 100
    seen = []
    query = run_stream(
        _model(),
        memory_source(rows, batch_rows=10),
        sink=lambda t: seen.append(t.num_rows),
        max_batches=3,
    )
    assert query.batches == 3
    assert seen == [10, 10, 10]


def test_stream_retries_transient_failure_once():
    rows = [{"fulltext": "ab"}] * 4
    model = _model()
    real_transform = model.transform
    fails = {"left": 1}

    def flaky(batch):
        if fails["left"]:
            fails["left"] -= 1
            raise RuntimeError("transient device hiccup")
        return real_transform(batch)

    model.transform = flaky
    query = run_stream(
        model, memory_source(rows, 2), sink=lambda t: None
    )
    assert query.batches == 2
    assert query.metrics.counters["retries"] == 1


def test_stream_progress_callback():
    rows = [{"fulltext": "ab"}] * 6
    snapshots = []
    run_stream(
        _model(),
        memory_source(rows, 2),
        sink=lambda t: None,
        on_progress=lambda q: snapshots.append((q.batches, q.last_batch_rows)),
    )
    assert snapshots == [(1, 2), (2, 2), (3, 2)]


def test_stream_prefetch_zero_synchronous_path():
    rows = [{"fulltext": "ababab"}, {"fulltext": "xyxy"}] * 5
    outputs = []
    query = run_stream(
        _model(),
        memory_source(rows, batch_rows=3),
        sink=lambda t: outputs.extend(t.column("lang").tolist()),
        prefetch=0,
    )
    assert query.batches == 4
    assert outputs == ["a", "x"] * 5


def test_stream_prefetch_deep_pipeline_preserves_order():
    rows = [{"fulltext": "ababab"}, {"fulltext": "xyxy"}] * 20
    outputs = []
    query = run_stream(
        _model(),
        memory_source(rows, batch_rows=4),
        sink=lambda t: outputs.extend(t.column("lang").tolist()),
        prefetch=3,
    )
    assert query.batches == 10
    assert query.rows == 40
    assert outputs == ["a", "x"] * 20


def test_stream_prefetch_respects_max_batches():
    rows = [{"fulltext": "ab"}] * 100
    seen = []
    query = run_stream(
        _model(),
        memory_source(rows, batch_rows=10),
        sink=lambda t: seen.append(t.num_rows),
        max_batches=3,
        prefetch=2,
    )
    assert query.batches == 3
    assert seen == [10, 10, 10]


def test_stream_prefetch_retry_still_works():
    rows = [{"fulltext": "ab"}] * 4
    model = _model()
    real_transform = model.transform
    fails = {"left": 1}

    def flaky(batch):
        if fails["left"]:
            fails["left"] -= 1
            raise RuntimeError("transient device hiccup")
        return real_transform(batch)

    model.transform = flaky
    query = run_stream(model, memory_source(rows, 2), sink=lambda t: None)
    assert query.batches == 2
    assert query.metrics.counters["retries"] == 1


def test_kafka_source_gated_on_missing_dependency():
    with pytest.raises(RuntimeError, match="kafka-python"):
        next(kafka_source("topic", 10))


def test_stream_explicit_single_worker_preserves_order():
    """workers=1 forces serial transforms (the conservative pipeline)."""
    rows = [{"fulltext": "ababab"}, {"fulltext": "xyxy"}] * 10
    outputs = []
    query = run_stream(
        _model(),
        memory_source(rows, batch_rows=4),
        sink=lambda t: outputs.extend(t.column("lang").tolist()),
        prefetch=3,
        workers=1,
    )
    assert query.batches == 5
    assert outputs == ["a", "x"] * 10
