"""Telemetry subsystem tests: spans, histograms, gauges, exporters, report.

Span trees are aggregation-by-path (no shared mutable tree), so the
threading tests assert the property that actually matters: every span
lands under its intended parent path with the right count, no matter how
many worker threads interleave.
"""

import json
import math
import os
import re
import threading

import numpy as np
import pytest

from spark_languagedetector_tpu.telemetry import (
    Histogram,
    Registry,
    current_span,
    render_prometheus,
    span,
    write_prometheus,
)
from spark_languagedetector_tpu.telemetry.export import (
    JsonlSink,
    configure_sinks_from_env,
    parse_sink_spec,
)
from spark_languagedetector_tpu.telemetry.report import (
    aggregate_spans,
    load_events,
    main as report_main,
    render_report,
)

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "telemetry_fixture.jsonl")


# ------------------------------------------------------------------ spans ----
def test_span_nesting_builds_slash_paths():
    reg = Registry()
    with span("score", registry=reg):
        with span("pack", registry=reg):
            pass
        # A name already carrying the parent prefix is used verbatim —
        # the ISSUE's span("score/pack") call shape.
        with span("score/dispatch", registry=reg):
            pass
    assert set(reg.histograms) == {
        "span:score", "span:score/pack", "span:score/dispatch"
    }


def test_span_standalone_full_path_names_are_roots():
    reg = Registry()
    with span("score/pack", registry=reg):
        pass
    assert set(reg.histograms) == {"span:score/pack"}


def test_span_full_path_names_merge_under_rerooted_parent():
    """A call site naming spans by full path ("score/pack") still nests
    cleanly when its root span is itself re-rooted under another stage
    (stream/transform/score) — shared segments merge, never duplicate."""
    reg = Registry()
    with span("stream", registry=reg):
        with span("stream/transform", registry=reg):
            with span("score", registry=reg) as score_root:
                with span("score/pack", parent=score_root, registry=reg):
                    pass
    assert "span:stream/transform/score/pack" in reg.histograms
    assert not any("score/score" in k for k in reg.histograms)


def test_current_span_tracks_innermost():
    reg = Registry()
    assert current_span() is None
    with span("a", registry=reg) as a:
        assert current_span() is a
        with span("b", registry=reg) as b:
            assert current_span() is b
        assert current_span() is a
    assert current_span() is None


def test_span_attrs_ride_on_events():
    reg = Registry()
    seen = []
    reg.add_sink(type("S", (), {"emit": staticmethod(seen.append)})())
    with span("stage", registry=reg, rows=7) as sp:
        sp.set(extra="x")
    (ev,) = seen
    assert ev["event"] == "telemetry.span"
    assert ev["path"] == "stage" and ev["rows"] == 7 and ev["extra"] == "x"
    assert ev["wall_s"] >= 0


def test_span_nesting_across_threads_attaches_to_explicit_parent():
    """Worker-thread spans passed an explicit parent land under it; the
    aggregate counts stay exact under concurrency (no tree corruption)."""
    reg = Registry()
    n_threads, per_thread = 8, 25
    barrier = threading.Barrier(n_threads)

    with span("stream", registry=reg) as root:
        def worker():
            barrier.wait()
            for _ in range(per_thread):
                with span("stream/transform", parent=root, registry=reg):
                    with span("inner", registry=reg):
                        pass
        threads = [threading.Thread(target=worker) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    total = n_threads * per_thread
    assert reg.histograms["span:stream/transform"].count == total
    assert reg.histograms["span:stream/transform/inner"].count == total
    assert reg.histograms["span:stream"].count == 1
    # No stray paths: concurrency must not cross-wire parents.
    assert set(reg.histograms) == {
        "span:stream", "span:stream/transform", "span:stream/transform/inner"
    }


def test_span_in_fresh_thread_without_parent_is_root():
    reg = Registry()
    with span("outer", registry=reg):
        def run():
            with span("orphan", registry=reg):
                pass
        t = threading.Thread(target=run)
        t.start()
        t.join()
    assert "span:orphan" in reg.histograms  # not outer/orphan


def test_span_fence_records_device_seconds():
    reg = Registry()

    class FakeDeviceArray:
        blocked = 0
        def block_until_ready(self):
            FakeDeviceArray.blocked += 1

    with span("dispatch", registry=reg, fence=True) as sp:
        sp.fence(FakeDeviceArray(), None, FakeDeviceArray())
    assert FakeDeviceArray.blocked == 2
    assert reg.histograms["span_device:dispatch"].count == 1
    # wall_s <= device_s by construction
    wall = reg.histograms["span:dispatch"]
    dev = reg.histograms["span_device:dispatch"]
    assert dev.total >= wall.total


def test_span_fence_disabled_by_default():
    reg = Registry()

    class FakeDeviceArray:
        blocked = 0
        def block_until_ready(self):
            FakeDeviceArray.blocked += 1

    with span("dispatch", registry=reg) as sp:
        sp.fence(FakeDeviceArray())
    assert FakeDeviceArray.blocked == 0
    assert "span_device:dispatch" not in reg.histograms


def test_span_fence_env_opt_in(monkeypatch):
    from spark_languagedetector_tpu.telemetry import FENCE_ENV

    monkeypatch.setenv(FENCE_ENV, "1")
    reg = Registry()

    class FakeDeviceArray:
        blocked = 0
        def block_until_ready(self):
            FakeDeviceArray.blocked += 1

    with span("dispatch", registry=reg) as sp:
        sp.fence(FakeDeviceArray())
    assert FakeDeviceArray.blocked == 1


def test_span_records_on_exception():
    reg = Registry()
    with pytest.raises(ValueError):
        with span("boom", registry=reg):
            raise ValueError("x")
    assert reg.histograms["span:boom"].count == 1


# -------------------------------------------------------------- histogram ----
def test_histogram_exact_percentiles_within_reservoir():
    h = Histogram()
    values = np.arange(1, 501, dtype=float)
    for v in np.random.default_rng(0).permutation(values):
        h.record(v)
    assert h.count == 500
    assert h.total == pytest.approx(values.sum())
    assert h.min == 1 and h.max == 500
    assert h.percentile(50) == 250
    assert h.percentile(90) == 450
    assert h.percentile(99) == 495


def test_histogram_reservoir_approximation_beyond_cap():
    h = Histogram()
    for v in np.random.default_rng(1).permutation(np.arange(10_000.0)):
        h.record(v)
    assert h.count == 10_000
    assert h.min == 0 and h.max == 9999
    # Uniform reservoir of 512: percentiles land near truth.
    assert abs(h.percentile(50) - 5000) < 800
    assert h.percentile(99) > 9000


def test_histogram_deterministic_across_runs():
    def run():
        h = Histogram()
        for v in range(5000):
            h.record(float(v % 997))
        return h.percentile(50), h.percentile(99)
    assert run() == run()


def test_histogram_empty_snapshot():
    h = Histogram()
    assert h.snapshot() == {"count": 0, "sum": 0.0}
    assert math.isnan(h.percentile(50))


# --------------------------------------------------------------- registry ----
def test_registry_counters_and_gauges():
    reg = Registry()
    reg.incr("score/retries")
    reg.incr("score/retries", 2)
    reg.set_gauge("live_buffer_bytes", 100.0, device="cpu:0")
    reg.set_gauge("live_buffer_bytes", 200.0, device="cpu:0")  # last wins
    snap = reg.snapshot()
    assert snap["counters"]["score/retries"] == 3
    assert snap["gauges"]["live_buffer_bytes"] == {"device=cpu:0": 200.0}


def test_registry_stage_summary_only_spans():
    reg = Registry()
    reg.observe("score/batch_fill_ratio", 0.5)
    with span("fit/count", registry=reg):
        pass
    summary = reg.stage_summary()
    assert list(summary) == ["fit/count"]
    assert summary["fit/count"]["count"] == 1


def test_registry_thread_safety_under_contention():
    reg = Registry()
    n, per = 8, 1000
    def work():
        for i in range(per):
            reg.incr("c")
            reg.observe("h", float(i))
    threads = [threading.Thread(target=work) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.counters["c"] == n * per
    assert reg.histograms["h"].count == n * per


# -------------------------------------------------------------- exporters ----
def test_jsonl_sink_valid_json_and_monotonic_timestamps(tmp_path):
    reg = Registry()
    path = str(tmp_path / "events.jsonl")
    reg.add_sink(JsonlSink(path))
    for i in range(50):
        with span("s", registry=reg, i=i):
            pass
    reg.flush()
    lines = open(path).read().splitlines()
    events = [json.loads(l) for l in lines]  # every line parses
    assert len(events) == 51
    assert all("event" in e and "ts" in e for e in events)
    tss = [e["ts"] for e in events]
    assert all(a < b for a, b in zip(tss, tss[1:])), "ts must strictly increase"


def test_sink_failure_never_propagates_into_recording():
    """Span exit emits from inside production fit/score/stream paths — a
    dying sink (disk full, closed file) must drop events, not take down
    the computation it observes."""
    reg = Registry()

    class DyingSink:
        def emit(self, event):
            raise OSError("disk full")

        def write_snapshot(self, registry):
            raise OSError("disk full")

    reg.add_sink(DyingSink())
    with pytest.warns(RuntimeWarning, match="dropping events"):
        with span("score/pack", registry=reg):
            pass
    reg.flush()  # snapshot-sink failure contained too
    assert reg.histograms["span:score/pack"].count == 1  # still aggregated
    assert reg.counters["telemetry/sink_errors"] >= 2


def test_flush_snapshot_carries_plain_histograms(tmp_path):
    """The JSONL snapshot must carry the non-span histograms — fill ratio
    and friends are collected per batch but have no per-event record, so
    omitting them here would strand them in process memory."""
    reg = Registry()
    path = str(tmp_path / "events.jsonl")
    reg.add_sink(JsonlSink(path))
    reg.observe("score/batch_fill_ratio", 0.75)
    with span("score/pack", registry=reg):
        pass
    reg.flush()
    snap_ev = [json.loads(l) for l in open(path)][-1]
    assert snap_ev["event"] == "telemetry.snapshot"
    hists = snap_ev["histograms"]
    assert hists["score/batch_fill_ratio"]["count"] == 1
    assert hists["score/batch_fill_ratio"]["p50"] == pytest.approx(0.75)
    # Span distributions ride as per-span events, not snapshot payload.
    assert not any(k.startswith("span:") for k in hists)
    report = render_report([snap_ev])
    assert "histograms (last snapshot):" in report
    assert "score/batch_fill_ratio" in report


def test_jsonl_sink_log_event_schema_compatible(tmp_path):
    """Span events carry the same discriminator shape utils.logging events
    do: a string 'event' plus float 'ts' — scrapers need no new parser."""
    reg = Registry()
    path = str(tmp_path / "events.jsonl")
    reg.add_sink(JsonlSink(path))
    with span("s", registry=reg):
        pass
    (ev,) = [json.loads(l) for l in open(path)]
    assert isinstance(ev["event"], str) and isinstance(ev["ts"], float)


# Minimal Prometheus text-format validator: TYPE lines + sample lines.
_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})?\s+(-?\d+(\.\d+)?([eE][+-]?\d+)?|NaN)$"
)
_PROM_TYPE = re.compile(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (summary|counter|gauge)$")


def test_prometheus_snapshot_parses(tmp_path):
    reg = Registry()
    with span("score/pack", registry=reg):
        pass
    reg.observe("score/batch_fill_ratio", 0.8)
    reg.incr("score/retries")
    reg.set_gauge("live_buffer_bytes", 4096.0, device="cpu:0")
    text = render_prometheus(reg)
    assert text.endswith("\n")
    for line in text.splitlines():
        if line.startswith("#"):
            assert _PROM_TYPE.match(line), line
        else:
            assert _PROM_SAMPLE.match(line), line
    # Round-trip essentials are present.
    assert 'langdetect_span_seconds_count{path="score/pack"} 1' in text
    assert 'langdetect_counter_total{name="score/retries"} 1' in text
    assert 'langdetect_gauge{name="live_buffer_bytes",device="cpu:0"}' in text
    # Snapshot writer writes the same content atomically.
    out = tmp_path / "metrics.prom"
    write_prometheus(reg, str(out))
    assert out.read_text() == text


def test_prometheus_label_escaping():
    reg = Registry()
    reg.incr('weird"name\\with\nstuff')
    text = render_prometheus(reg)
    assert '\\"' in text and "\\\\" in text and "\\n" in text
    for line in text.splitlines():
        if not line.startswith("#"):
            assert _PROM_SAMPLE.match(line), line


def test_prometheus_gauge_labels_survive_comma_values(tmp_path):
    """A label value containing commas/equals (a full TPU device repr)
    must not shatter into bogus label tokens — every emitted line stays
    valid exposition format and the value survives intact."""
    reg = Registry()
    reg.set_gauge(
        "live_buffer_bytes", 512.0,
        device="TpuDevice(id=0, process_index=0, coords=(0,0,0))",
    )
    text = render_prometheus(reg)
    for line in text.splitlines():
        if not line.startswith("#"):
            assert _PROM_SAMPLE.match(line), line
    assert (
        'device="TpuDevice(id=0, process_index=0, coords=(0,0,0))"' in text
    )


def test_fenced_device_timings_reach_summary_and_prometheus():
    """device_s histograms must surface in the aggregate views — the
    bench stage breakdown and the .prom snapshot — not just raw JSONL."""
    reg = Registry()

    class FakeDeviceArray:
        def block_until_ready(self):
            pass

    with span("score/dispatch", registry=reg, fence=True) as sp:
        sp.fence(FakeDeviceArray())
    entry = reg.stage_summary()["score/dispatch"]
    assert entry["device_total_s"] >= entry["total_s"]
    assert "device_p99" in entry
    text = render_prometheus(reg)
    assert "# TYPE langdetect_span_device_seconds summary" in text
    assert 'langdetect_span_device_seconds_count{path="score/dispatch"} 1' in text
    for line in text.splitlines():
        if not line.startswith("#"):
            assert _PROM_SAMPLE.match(line), line


def test_import_survives_bad_sink_env(tmp_path):
    """A broken LANGDETECT_METRICS_SINK must degrade to a warning — a
    metrics env var taking down every import (scoring included) is a far
    bigger failure than a metric-less run."""
    import subprocess
    import sys

    blocker = tmp_path / "file"  # a *file*, so file/sub can't be a dir
    blocker.write_text("")
    for bad in ("bogus:/x", f"jsonl:{blocker}/sub/t.jsonl"):
        proc = subprocess.run(
            [sys.executable, "-c",
             "import spark_languagedetector_tpu.telemetry; print('ok')"],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "LANGDETECT_METRICS_SINK": bad},
        )
        assert proc.returncode == 0, proc.stderr
        assert "ok" in proc.stdout
        assert "could not attach metric sinks" in proc.stderr


def test_sink_env_spec_parsing(tmp_path):
    assert parse_sink_spec("jsonl:/a.jsonl,prom:/b.prom") == [
        ("jsonl", "/a.jsonl"), ("prom", "/b.prom")
    ]
    with pytest.raises(ValueError):
        parse_sink_spec("bogus:/x")
    with pytest.raises(ValueError):
        parse_sink_spec("jsonl")
    reg = Registry()
    jsonl = tmp_path / "t.jsonl"
    prom = tmp_path / "t.prom"
    sinks = configure_sinks_from_env(
        reg, env={"LANGDETECT_METRICS_SINK": f"jsonl:{jsonl},prom:{prom}"}
    )
    assert [s.kind for s in sinks] == ["jsonl", "prom"]
    with span("s", registry=reg):
        pass
    reg.flush()
    assert jsonl.exists() and prom.exists()
    assert "langdetect_span_seconds" in prom.read_text()


# ----------------------------------------------------------------- gauges ----
def test_sample_device_gauges_cpu():
    import jax.numpy as jnp

    from spark_languagedetector_tpu.telemetry.gauges import sample_device_gauges

    reg = Registry()
    keep = jnp.ones((128, 128), jnp.float32)  # ensure something is live
    out = sample_device_gauges(reg)
    assert "live_buffer_bytes" in out
    assert sum(out["live_buffer_bytes"].values()) >= keep.nbytes
    assert "live_buffer_bytes" in reg.snapshot()["gauges"]


def test_install_jax_hooks_counts_compiles():
    import jax
    import jax.numpy as jnp

    from spark_languagedetector_tpu.telemetry import REGISTRY, install_jax_hooks

    assert install_jax_hooks()  # global listener → global registry
    before = REGISTRY.counters.get("jax/compile_events", 0)

    @jax.jit
    def f(x):
        return x * 2 + 1

    f(jnp.arange(7)).block_until_ready()
    assert REGISTRY.counters.get("jax/compile_events", 0) > before


def test_jax_hook_duration_counts_only_backend_compiles():
    """The duration listener must exact-match the backend compile event:
    jax emits three per-compile duration events whose names all contain
    "compile", plus a compile_time_SAVED event on persistent-cache hits —
    substring matching would triple-count and bill savings as spend."""
    from jax import monitoring

    from spark_languagedetector_tpu.telemetry import (
        REGISTRY, install_jax_hooks,
    )
    from spark_languagedetector_tpu.telemetry.gauges import (
        _BACKEND_COMPILE_EVENT,
    )

    reg = Registry()
    try:
        assert install_jax_hooks(reg)
        for lookalike in (
            "/jax/core/compile/jaxpr_trace_duration",
            "/jax/core/compile/jaxpr_to_mlir_module_duration",
            "/jax/compilation_cache/compile_time_saved_sec",
        ):
            monitoring.record_event_duration_secs(lookalike, 123.0)
        assert reg.counters.get("jax/compile_events", 0) == 0
        assert "jax/compile_s" not in reg.histograms
        monitoring.record_event_duration_secs(_BACKEND_COMPILE_EVENT, 0.25)
        assert reg.counters["jax/compile_events"] == 1
        assert reg.histograms["jax/compile_s"].total == pytest.approx(0.25)
    finally:
        install_jax_hooks(REGISTRY)  # restore the process-global binding


def test_install_jax_hooks_rebinds_to_latest_registry():
    """jax listener registration is permanent, so a later install call
    with a different registry must redirect the flow — not silently keep
    feeding the first caller's registry while returning True."""
    from jax import monitoring

    from spark_languagedetector_tpu.telemetry import (
        REGISTRY, install_jax_hooks,
    )

    first, second = Registry(), Registry()
    try:
        assert install_jax_hooks(first)
        monitoring.record_event("/jax/compilation_cache/cache_misses")
        assert first.counters["jax/compile_cache_misses"] == 1
        assert install_jax_hooks(second)
        monitoring.record_event("/jax/compilation_cache/cache_misses")
        assert second.counters["jax/compile_cache_misses"] == 1
        assert first.counters["jax/compile_cache_misses"] == 1  # unchanged
    finally:
        install_jax_hooks(REGISTRY)


def test_device_label_is_short_and_comma_free():
    from spark_languagedetector_tpu.telemetry.gauges import _device_label

    class FakeTpu:
        platform = "tpu"
        id = 3
        def __str__(self):
            return "TpuDevice(id=3, process_index=0, coords=(1,1,0))"

    class Weird:
        def __str__(self):
            return "mystery-device"

    assert _device_label(FakeTpu()) == "tpu:3"
    assert _device_label(Weird()) == "mystery-device"


def test_note_donation_reuse():
    from spark_languagedetector_tpu.telemetry.gauges import note_donation_reuse

    reg = Registry()

    class Deleted:
        def is_deleted(self):
            return True

    class Alive:
        def is_deleted(self):
            return False

    assert note_donation_reuse(Deleted(), reg) is True
    assert note_donation_reuse(Alive(), reg) is False
    assert note_donation_reuse(object(), reg) is False  # unobservable
    assert reg.counters["jax/donated_reuse"] == 1
    assert reg.counters["jax/donated_copy"] == 1


# ------------------------------------------------------------- report CLI ----
def test_report_cli_on_checked_in_fixture(capsys):
    """Tier-1-safe smoke: the report CLI renders the fixture's stage tree."""
    rc = report_main([FIXTURE])
    assert rc == 0
    out = capsys.readouterr().out
    for stage in ("fit", "count", "topk", "score", "pack", "dispatch", "fetch"):
        assert re.search(rf"^\s*{stage}\b", out, re.M), f"missing {stage}:\n{out}"
    assert "counters (last snapshot):" in out
    assert "jax/compile_events" in out
    assert "live_buffer_bytes" in out
    assert "histograms (last snapshot):" in out
    assert "score/batch_fill_ratio" in out


def test_report_aggregates_fixture_percentiles():
    events = load_events(FIXTURE)
    stages = aggregate_spans(events)
    assert stages["score/pack"].count == 2
    assert stages["score/pack"].percentile(50) == pytest.approx(0.0019)
    assert stages["fit/count"].count == 1


def test_report_cli_usage_and_missing_file(capsys, tmp_path):
    assert report_main([]) == 2
    assert report_main([str(tmp_path / "nope.jsonl")]) == 2
    assert report_main(["-h"]) == 2


def test_report_skips_garbage_lines(tmp_path, capsys):
    p = tmp_path / "t.jsonl"
    p.write_text(
        '{"event": "telemetry.span", "path": "a", "wall_s": 0.1, "ts": 1.0}\n'
        "this is not json\n"
        '{"event": "telemetry.span", "path": "a", "wall_s": 0.3, "ts": 2.0}\n'
    )
    events = load_events(str(p))
    assert len(events) == 2
    report = render_report(events)
    assert re.search(r"^a\s+2\b", report, re.M)


# ------------------------------------------------------- metrics satellite ----
def test_metrics_timer_accumulates_count_and_mean():
    from spark_languagedetector_tpu.utils.metrics import Metrics

    m = Metrics()
    for _ in range(4):
        with m.timer("score_s"):
            pass
    snap = m.snapshot()
    assert isinstance(snap["timers"]["score_s"], float)  # legacy shape kept
    assert snap["timer_counts"]["score_s"] == 4
    assert m.mean_seconds("score_s") == pytest.approx(
        snap["timers"]["score_s"] / 4
    )
    assert m.mean_seconds("never") == 0.0
    m.reset()
    assert m.snapshot()["timer_counts"] == {}


def test_metrics_pickle_roundtrip_keeps_counts():
    import pickle

    from spark_languagedetector_tpu.utils.metrics import Metrics

    m = Metrics()
    with m.timer("t"):
        pass
    m2 = pickle.loads(pickle.dumps(m))
    assert m2.timer_counts["t"] == 1
    with m2.timer("t"):
        pass  # lock was rebuilt
    assert m2.timer_counts["t"] == 2


# ------------------------------------------------- end-to-end instrumentation -
def test_runner_score_records_stage_spans_and_histograms():
    from spark_languagedetector_tpu import LanguageDetectorModel, Table
    from spark_languagedetector_tpu.telemetry import REGISTRY

    REGISTRY.reset()
    model = LanguageDetectorModel.from_gram_map(
        {b"ab": [1.0, 0.0], b"xy": [0.0, 1.0]}, [2], ["a", "x"]
    )
    out = model.transform(Table({"fulltext": ["ababab", "xyxy"] * 20}))
    assert list(out.column("lang")) == ["a", "x"] * 20
    stages = REGISTRY.stage_summary()
    for path in ("score", "score/pack", "score/dispatch", "score/fetch"):
        assert path in stages, stages
    snap = REGISTRY.snapshot()
    assert snap["histograms"]["score/batch_fill_ratio"]["count"] >= 1
    assert snap["histograms"]["score/padding_waste"]["count"] >= 1
    assert snap["histograms"]["score/batch_latency_s"]["count"] >= 1
    fill = snap["histograms"]["score/batch_fill_ratio"]
    assert 0.0 < fill["p50"] <= 1.0


def test_fit_records_stage_spans_host_and_device():
    import numpy as np

    from spark_languagedetector_tpu.ops.fit import fit_profile_numpy
    from spark_languagedetector_tpu.ops.fit_tpu import fit_profile_device
    from spark_languagedetector_tpu.ops.vocab import EXACT, VocabSpec
    from spark_languagedetector_tpu.telemetry import REGISTRY

    REGISTRY.reset()
    docs = [b"abab", b"xyxy", b"abxy", b"xyab"]
    langs = np.asarray([0, 1, 0, 1])
    spec = VocabSpec(EXACT, (1, 2))
    fit_profile_numpy(docs, langs, 2, spec, 50)
    stages = REGISTRY.stage_summary()
    for path in ("fit/count", "fit/weights", "fit/topk"):
        assert path in stages, stages

    REGISTRY.reset()
    ids_h, w_h = fit_profile_numpy(docs, langs, 2, spec, 50)
    ids_d, w_d = fit_profile_device(docs, langs, 2, spec, 50)
    np.testing.assert_array_equal(ids_h, ids_d)
    stages = REGISTRY.stage_summary()
    # The device reduce half records fit/finalize (on-device weighting +
    # top-k) and fit/collect (winner-rows-only fetch, with its byte gauge).
    for path in ("fit/count", "fit/finalize", "fit/collect"):
        assert path in stages, stages
    snap = REGISTRY.snapshot()
    assert snap["counters"].get("fit/collect_bytes", 0) > 0
    assert "langdetect_fit_collect_bytes" in snap["gauges"]


def test_split_fit_records_host_half_and_merge():
    """The exact n>=4 split fit must attribute its host long-gram pass —
    often the dominant stage — not just the device half."""
    import numpy as np

    from spark_languagedetector_tpu.ops.fit_tpu import (
        fit_profile_device_split,
    )
    from spark_languagedetector_tpu.ops.vocab import EXACT, VocabSpec
    from spark_languagedetector_tpu.telemetry import REGISTRY

    REGISTRY.reset()
    docs = [b"abcde" * 3, b"vwxyz" * 3, b"abcdefgh", b"stuvwxyz"]
    langs = np.asarray([0, 1, 0, 1])
    spec = VocabSpec(EXACT, (1, 2, 3, 4, 5))
    fit_profile_device_split(docs, langs, 2, spec, 100)
    stages = REGISTRY.stage_summary()
    for path in ("fit/count", "fit/weights", "fit/topk", "fit/merge"):
        assert path in stages, stages
    # Both halves land under fit/count: the device scatter-add loop and
    # the host long-gram sweep.
    assert stages["fit/count"]["count"] >= 2, stages["fit/count"]


# ---------------------------------------------------------- request tracing --
class _ListSink:
    def __init__(self):
        self.events = []

    def emit(self, event):
        self.events.append(event)


def test_trace_request_mints_reuses_and_rebinds():
    from spark_languagedetector_tpu.telemetry import (
        current_trace_id,
        trace_request,
    )

    assert current_trace_id() is None
    with trace_request() as outer:
        assert current_trace_id() == outer
        # Default: an ambient request is reused, not shadowed.
        with trace_request() as inner:
            assert inner == outer
        # Explicit id: rebinds (the stream engine's per-batch scopes).
        with trace_request("feedface00000001") as forced:
            assert forced == "feedface00000001"
            assert current_trace_id() == forced
        assert current_trace_id() == outer
    assert current_trace_id() is None


def test_span_stamps_trace_id_and_tid():
    from spark_languagedetector_tpu.telemetry import trace_request

    reg = Registry()
    sink = _ListSink()
    reg.add_sink(sink)
    with trace_request("cafe000000000001"):
        with span("score", registry=reg):
            pass
    with span("untraced", registry=reg):
        pass
    traced, untraced = sink.events
    assert traced["trace_id"] == "cafe000000000001"
    assert isinstance(traced["tid"], int)
    assert "trace_id" not in untraced  # no ambient request, no stamp
    assert isinstance(untraced["tid"], int)


def test_trace_id_inherits_through_explicit_parent_across_threads():
    """Worker threads have no ambient trace context; the explicit span
    parent must carry the request id across — the runner's dispatch
    workers and the stream prefetch workers rely on this."""
    from spark_languagedetector_tpu.telemetry import trace_request

    reg = Registry()
    sink = _ListSink()
    reg.add_sink(sink)
    with trace_request("beef000000000001"):
        with span("score", registry=reg) as root:
            def worker():
                with span("score/dispatch", parent=root, registry=reg):
                    pass
            t = threading.Thread(target=worker)
            t.start()
            t.join()
    by_path = {e["path"]: e for e in sink.events}
    assert by_path["score/dispatch"]["trace_id"] == "beef000000000001"
    assert by_path["score"]["trace_id"] == "beef000000000001"


def test_ambient_trace_wins_over_parent_trace():
    """A per-batch request scope set on a worker thread must override the
    parent span's (stream-root) trace — that is how one stream batch gets
    its own id while still nesting under the stream tree."""
    from spark_languagedetector_tpu.telemetry import trace_request

    reg = Registry()
    sink = _ListSink()
    reg.add_sink(sink)
    with trace_request("00000000000000aa"):
        with span("stream", registry=reg) as root:
            with trace_request("00000000000000bb"):
                with span("stream/transform", parent=root, registry=reg):
                    pass
    by_path = {e["path"]: e for e in sink.events}
    assert by_path["stream/transform"]["trace_id"] == "00000000000000bb"
    assert by_path["stream"]["trace_id"] == "00000000000000aa"


def test_runner_score_call_shares_one_trace_id():
    from spark_languagedetector_tpu import LanguageDetectorModel, Table
    from spark_languagedetector_tpu.telemetry import REGISTRY

    REGISTRY.reset()
    sink = _ListSink()
    REGISTRY.add_sink(sink)
    try:
        model = LanguageDetectorModel.from_gram_map(
            {b"ab": [1.0, 0.0], b"xy": [0.0, 1.0]}, [2], ["a", "x"]
        )
        model.transform(Table({"fulltext": ["ababab", "xyxy"] * 10}))
        model.transform(Table({"fulltext": ["ababab"] * 5}))
    finally:
        REGISTRY.remove_sink(sink)
    score_roots = [e for e in sink.events if e.get("path") == "score"]
    assert len(score_roots) == 2
    ids = [e.get("trace_id") for e in score_roots]
    assert all(ids) and ids[0] != ids[1]  # one fresh request per call
    # Every sub-span of a call carries its call's id.
    for e in sink.events:
        if str(e.get("path", "")).startswith("score/"):
            assert e.get("trace_id") in ids


def test_stream_batches_get_distinct_trace_ids():
    from spark_languagedetector_tpu import LanguageDetectorModel, Table
    from spark_languagedetector_tpu.stream.microbatch import (
        memory_source,
        run_stream,
    )
    from spark_languagedetector_tpu.telemetry import REGISTRY

    REGISTRY.reset()
    sink = _ListSink()
    REGISTRY.add_sink(sink)
    try:
        model = LanguageDetectorModel.from_gram_map(
            {b"ab": [1.0, 0.0], b"xy": [0.0, 1.0]}, [2], ["a", "x"]
        )
        rows = [{"fulltext": "ababab"}] * 30
        q = run_stream(
            model, memory_source(rows, 10), lambda t: None,
            prefetch=2, workers=2,
        )
    finally:
        REGISTRY.remove_sink(sink)
    assert q.batches == 3
    batch_ids = {
        e["trace_id"] for e in sink.events if e.get("path") == "stream/batch"
    }
    transform_ids = {
        e["trace_id"]
        for e in sink.events
        if e.get("path") == "stream/transform"
    }
    assert len(batch_ids) == 3 and batch_ids == transform_ids
    assert q.last_batch_trace_id in batch_ids
    # The nested runner spans join their batch's request, not a new one.
    inner = {
        e.get("trace_id")
        for e in sink.events
        if str(e.get("path", "")).startswith("stream/transform/score")
    }
    assert inner and inner <= batch_ids


# ------------------------------------------------------- chrome trace export --
def _valid_chrome_trace(trace: dict) -> list[dict]:
    """Assert trace-event JSON validity; returns the complete ('X') events."""
    assert json.loads(json.dumps(trace)) == trace  # JSON-serializable
    events = trace["traceEvents"]
    assert isinstance(events, list)
    complete = [e for e in events if e.get("ph") == "X"]
    for e in complete:
        assert isinstance(e["name"], str)
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert isinstance(e["tid"], int) and isinstance(e["pid"], int)
    lanes = {}
    for e in complete:
        lanes.setdefault(e["tid"], []).append(e["ts"])
    for tss in lanes.values():
        assert tss == sorted(tss), "per-lane ts must be monotonic"
    return complete


def test_chrome_trace_from_fixture_is_valid_and_carries_trace_ids():
    from spark_languagedetector_tpu.telemetry.tracing import (
        render_chrome_trace,
    )

    fixture_regressed = os.path.join(
        os.path.dirname(__file__), "fixtures",
        "telemetry_fixture_regressed.jsonl",
    )
    events = load_events(fixture_regressed)
    trace = render_chrome_trace(events)
    complete = _valid_chrome_trace(trace)
    names = {e["name"] for e in complete}
    assert "score/dispatch" in names and "fit/count" in names
    # Fenced spans get a device lane alongside the host lane.
    assert "score/dispatch [device]" in names
    tids = {
        e["args"].get("trace_id") for e in complete
        if e["name"].startswith("score")
    }
    assert "deadbeef00000001" in tids
    # Gauge snapshots ride as counter events.
    assert any(e.get("ph") == "C" for e in trace["traceEvents"])


def test_chrome_trace_cli_round_trip(tmp_path, capsys):
    from spark_languagedetector_tpu.telemetry.tracing import main as t_main

    out = str(tmp_path / "fixture.trace.json")
    assert t_main([FIXTURE, out]) == 0
    assert capsys.readouterr().out.strip() == out
    with open(out) as fh:
        _valid_chrome_trace(json.load(fh))
    assert t_main([]) == 2
    assert t_main([str(tmp_path / "missing.jsonl"), out]) == 2


def test_chrome_trace_interleaved_threads_stay_monotonic_per_lane():
    """Events landing out of start-order across threads (the JSONL file is
    ordered by *end* time) must still export with per-lane monotonic ts."""
    from spark_languagedetector_tpu.telemetry.tracing import (
        render_chrome_trace,
    )

    events = [
        {"event": "telemetry.span", "ts": 10.0, "path": "a", "wall_s": 9.0,
         "tid": 1},
        {"event": "telemetry.span", "ts": 10.5, "path": "b", "wall_s": 0.2,
         "tid": 2},
        {"event": "telemetry.span", "ts": 11.0, "path": "c", "wall_s": 10.0,
         "tid": 2},  # started BEFORE b on the same lane
        {"event": "telemetry.span", "ts": 12.0, "path": "d", "wall_s": 0.1,
         "tid": 1},
    ]
    _valid_chrome_trace(render_chrome_trace(events))


def test_chrome_trace_remaps_real_thread_idents_to_small_lanes():
    """Thread idents are pthread addresses on Linux (~1e14): lanes must be
    dense ordinals — a raw ident as a lane id would label every host lane
    as a device lane, and masking one could collide two threads."""
    from spark_languagedetector_tpu.telemetry.tracing import (
        render_chrome_trace,
    )

    big_a, big_b = 139272512337664, 139272512337664 + (1 << 16)  # same low bits
    events = [
        {"event": "telemetry.span", "ts": 1.0, "path": "a", "wall_s": 0.1,
         "tid": big_a, "device_s": 0.2},
        {"event": "telemetry.span", "ts": 1.1, "path": "b", "wall_s": 0.1,
         "tid": big_b, "device_s": 0.2},
    ]
    trace = render_chrome_trace(events)
    meta = {
        e["tid"]: e["args"]["name"]
        for e in trace["traceEvents"]
        if e.get("ph") == "M" and e["name"] == "thread_name"
    }
    host = {t: n for t, n in meta.items() if n.startswith("thread ")}
    device = {t: n for t, n in meta.items() if n.startswith("device")}
    assert len(host) == 2 and len(device) == 2  # no lane collision
    assert all(t < (1 << 21) for t in meta)
    assert str(big_a) in " ".join(meta.values())  # ident kept in the label


def test_chrome_trace_empty_and_garbage_events():
    from spark_languagedetector_tpu.telemetry.tracing import (
        render_chrome_trace,
    )

    assert render_chrome_trace([])["traceEvents"]  # metadata only, valid
    trace = render_chrome_trace([
        {"event": "telemetry.span"},  # no path/wall
        {"event": "telemetry.span", "path": "x", "wall_s": "bogus"},
        {"not": "an event"},
    ])
    assert not [e for e in trace["traceEvents"] if e.get("ph") == "X"]


# ----------------------------------------------------------- flight recorder --
@pytest.fixture
def flight(tmp_path):
    from spark_languagedetector_tpu.telemetry import flightrec

    flightrec.uninstall()  # isolate from any env-armed recorder
    rec = flightrec.install(str(tmp_path / "fr"))
    yield rec
    flightrec.uninstall()


def test_flight_recorder_ring_is_bounded_and_dump_has_recent_events(tmp_path):
    from spark_languagedetector_tpu.telemetry.flightrec import FlightRecorder

    rec = FlightRecorder(str(tmp_path / "fr"), capacity=10)
    for i in range(25):
        rec.emit({"event": "telemetry.span", "path": "s", "i": i})
    assert len(rec) == 10
    path = rec.dump(context="score", error="ValueError('x')")
    lines = [json.loads(l) for l in open(path)]
    header, body = lines[0], lines[1:]
    assert header["event"] == "flightrec.dump"
    assert header["context"] == "score" and "ValueError" in header["error"]
    assert header["events"] == 10
    assert [e["i"] for e in body] == list(range(15, 25))  # most recent kept
    # A second dump gets its own file.
    assert rec.dump(context="score") != path


def test_flight_recorder_env_install(tmp_path):
    from spark_languagedetector_tpu.telemetry import flightrec

    flightrec.uninstall()
    try:
        assert flightrec.install_from_env(env={}) is None
        assert flightrec.install_from_env(
            env={"LANGDETECT_FLIGHT_RECORDER": "0"}
        ) is None
        rec = flightrec.install_from_env(env={
            "LANGDETECT_FLIGHT_RECORDER": str(tmp_path / "fr"),
            "LANGDETECT_FLIGHT_RECORDER_EVENTS": "7",
        })
        assert rec is not None and rec._ring.maxlen == 7
        assert flightrec.active() is rec
        # Idempotent: a second install returns the same recorder.
        assert flightrec.install_from_env(env={
            "LANGDETECT_FLIGHT_RECORDER": "1"
        }) is rec
    finally:
        flightrec.uninstall()


def test_runner_crash_dumps_flight_ring(flight):
    """A raising score call must leave a post-mortem with the spans that
    led up to it — the tentpole's crash contract, driven through the real
    BatchRunner entry point."""
    from spark_languagedetector_tpu import LanguageDetectorModel, Table
    from spark_languagedetector_tpu.telemetry import REGISTRY, flightrec

    REGISTRY.reset()
    model = LanguageDetectorModel.from_gram_map(
        {b"ab": [1.0, 0.0], b"xy": [0.0, 1.0]}, [2], ["a", "x"]
    )
    model.transform(Table({"fulltext": ["abab"] * 4}))  # ring gets context
    runner = model._get_runner()
    # A programming error mid-batch (not RETRYABLE): propagates at once.
    runner._pack = staticmethod(
        lambda docs, pad_to: (_ for _ in ()).throw(ValueError("bad pack"))
    )
    with pytest.raises(ValueError):
        runner.score([b"abab"])
    dump = flightrec.last_dump_path()
    assert dump is not None and os.path.exists(dump)
    lines = [json.loads(l) for l in open(dump)]
    assert lines[0]["context"] == "score"
    assert any(e.get("path") == "score" for e in lines[1:])


def test_stream_crash_dumps_once_for_nested_failure(flight):
    from spark_languagedetector_tpu import LanguageDetectorModel, Table
    from spark_languagedetector_tpu.stream.microbatch import (
        memory_source,
        run_stream,
    )
    from spark_languagedetector_tpu.telemetry import REGISTRY, flightrec

    REGISTRY.reset()
    model = LanguageDetectorModel.from_gram_map(
        {b"ab": [1.0, 0.0], b"xy": [0.0, 1.0]}, [2], ["a", "x"]
    )

    def dying_sink(table):
        raise OSError("sink full")

    with pytest.raises(OSError):
        run_stream(
            model,
            memory_source([{"fulltext": "abab"}] * 20, 10),
            dying_sink,
        )
    dump = flightrec.last_dump_path()
    assert dump is not None
    assert json.loads(open(dump).readline())["context"] == "stream"
    assert REGISTRY.counters.get("telemetry/flightrec_dumps") == 1


def test_record_crash_dedups_per_object_not_per_address(flight):
    """The same exception unwinding through nested hooks dumps once; a
    later distinct exception — even one whose object reuses the freed
    address, CPython's common case — must still dump."""
    from spark_languagedetector_tpu.telemetry import flightrec

    e1 = RuntimeError("first")
    p1 = flightrec.record_crash("score", e1)
    assert p1 is not None
    assert flightrec.record_crash("stream", e1) is None  # nested hook
    del e1  # free the address
    p2 = flightrec.record_crash("score", RuntimeError("second"))
    assert p2 is not None and p2 != p1


def test_record_crash_is_contained_and_counts_failures(tmp_path):
    from spark_languagedetector_tpu.telemetry import flightrec

    flightrec.uninstall()
    # No recorder armed: a no-op, not an error.
    assert flightrec.record_crash("score", ValueError("x")) is None
    blocker = tmp_path / "blocker"
    blocker.write_text("")  # a *file*, so dumps into it must fail
    reg = Registry()
    flightrec.install(str(blocker / "sub"), registry=reg)
    try:
        import warnings

        with warnings.catch_warnings(record=True):
            warnings.simplefilter("always")
            assert flightrec.record_crash(
                "score", ValueError("x"), registry=reg
            ) is None
        assert reg.counters["telemetry/flightrec_errors"] >= 1
    finally:
        flightrec.uninstall(registry=reg)


# ------------------------------------------------------ cost/roofline gauges --
def test_program_cost_on_abstract_shapes():
    import jax
    import jax.numpy as jnp

    from spark_languagedetector_tpu.telemetry.cost import program_cost

    cost = program_cost(
        lambda x, w: jnp.dot(x, w),
        jax.ShapeDtypeStruct((128, 64), jnp.float32),
        jax.ShapeDtypeStruct((64, 32), jnp.float32),
    )
    assert cost is not None
    # dot flops = 2 * M * K * N
    assert cost["flops"] == pytest.approx(2 * 128 * 64 * 32, rel=0.01)
    assert cost.get("bytes_accessed", 1) > 0


def test_normalize_cost_shapes():
    from spark_languagedetector_tpu.telemetry.cost import normalize_cost

    assert normalize_cost({"flops": 10.0, "bytes accessed": 5.0}) == {
        "flops": 10.0, "bytes_accessed": 5.0
    }
    assert normalize_cost([{"flops": 3.0}]) == {"flops": 3.0}
    assert normalize_cost([]) is None
    assert normalize_cost(None) is None
    assert normalize_cost({"flops": -1.0}) is None


def test_stage_summary_joins_cost_and_utilization():
    from spark_languagedetector_tpu.telemetry.cost import record_program_cost

    reg = Registry()

    class Fenced:
        def block_until_ready(self):
            pass

    with span("score/dispatch", registry=reg, fence=True) as sp:
        sp.fence(Fenced())
    record_program_cost(
        "score/dispatch",
        {"flops": 1e9, "bytes_accessed": 1e6},
        platform="cpu",
        registry=reg,
    )
    entry = reg.stage_summary()["score/dispatch"]
    assert entry["est_flops_per_call"] == pytest.approx(1e9)
    assert entry["est_flops_per_s"] > 0
    assert 0 < entry["flops_utilization"]
    assert 0 < entry["bytes_utilization"]
    assert entry["roofline_bound"] in ("compute", "memory")
    # Peaks and program cost export as plain gauges too (Prometheus).
    text = render_prometheus(reg)
    assert 'langdetect_gauge{name="program_flops",program="score/dispatch"}' in text
    assert 'langdetect_gauge{name="device_peak_flops",device="cpu"}' in text


def test_peak_rate_env_overrides(monkeypatch):
    from spark_languagedetector_tpu.telemetry.cost import peak_rates

    flops, byts = peak_rates("tpu")
    assert flops > 1e14 and byts > 1e11
    assert peak_rates("unknown-platform") is None
    monkeypatch.setenv("LANGDETECT_PEAK_FLOPS", "5e12")
    f2, b2 = peak_rates("tpu")
    assert f2 == 5e12 and b2 == byts


def test_runner_records_dispatch_cost_once():
    from spark_languagedetector_tpu import LanguageDetectorModel, Table
    from spark_languagedetector_tpu.telemetry import REGISTRY

    REGISTRY.reset()
    model = LanguageDetectorModel.from_gram_map(
        {b"ab": [1.0, 0.0], b"xy": [0.0, 1.0]}, [2], ["a", "x"]
    )
    model.transform(Table({"fulltext": ["ababab", "xyxy"] * 8}))
    runner = model._get_runner()
    assert getattr(runner, "_cost_recorded") is True
    # The analysis runs off the dispatch path (cold-start plane): join
    # the gauge thread before reading the summary.
    thread = getattr(runner, "_cost_thread", None)
    if thread is not None:
        thread.join(timeout=120)
    entry = REGISTRY.stage_summary()["score/dispatch"]
    assert entry.get("est_flops_per_call", 0) > 0
    assert "flops_utilization" in entry


def test_fit_device_records_count_cost():
    import numpy as np

    from spark_languagedetector_tpu.ops.fit_tpu import fit_profile_device
    from spark_languagedetector_tpu.ops.vocab import EXACT, VocabSpec
    from spark_languagedetector_tpu.telemetry import REGISTRY

    REGISTRY.reset()
    fit_profile_device(
        [b"abab", b"xyxy", b"abxy"], np.asarray([0, 1, 0]), 2,
        VocabSpec(EXACT, (1, 2)), 50,
    )
    entry = REGISTRY.stage_summary()["fit/count"]
    assert entry.get("est_flops_per_call", 0) > 0


# ---------------------------------------------------------------- compare CLI --
FIXTURE_REGRESSED = os.path.join(
    os.path.dirname(__file__), "fixtures",
    "telemetry_fixture_regressed.jsonl",
)


def test_compare_cli_same_capture_passes(capsys):
    from spark_languagedetector_tpu.telemetry.compare import main as c_main

    assert c_main([FIXTURE, FIXTURE]) == 0
    assert "no regression" in capsys.readouterr().out


def test_compare_cli_flags_injected_regression(capsys):
    """The acceptance gate: a capture with an injected dispatch p99
    regression exits nonzero and names the offending stage/metric."""
    from spark_languagedetector_tpu.telemetry.compare import main as c_main

    assert c_main([FIXTURE, FIXTURE_REGRESSED]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out
    assert re.search(r"score/dispatch\s+p99", out)
    # Snapshot-carried histograms are compared too.
    assert "score/batch_latency_s" in out


def test_compare_cli_threshold_and_direction(capsys):
    from spark_languagedetector_tpu.telemetry.compare import main as c_main

    # A generous threshold admits the same diff.
    assert c_main([FIXTURE, FIXTURE_REGRESSED, "--threshold", "5.0"]) == 0
    # Reversed order: the "regressed" capture as baseline means the
    # candidate got FASTER — wall metrics must not flag improvements...
    capsys.readouterr()
    rc = c_main([FIXTURE_REGRESSED, FIXTURE, "--threshold", "0.9"])
    assert rc == 0


def test_compare_cli_fill_ratio_is_higher_better(tmp_path, capsys):
    from spark_languagedetector_tpu.telemetry.compare import main as c_main

    def capture(path, fill):
        path.write_text(
            json.dumps({
                "event": "telemetry.span", "ts": 1.0, "path": "score",
                "wall_s": 0.01,
            }) + "\n" + json.dumps({
                "event": "telemetry.snapshot", "ts": 2.0, "counters": {},
                "gauges": {},
                "histograms": {"score/batch_fill_ratio": {
                    "count": 4, "sum": 4 * fill, "mean": fill, "p50": fill,
                    "p99": fill,
                }},
            }) + "\n"
        )

    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    capture(a, 0.9)
    capture(b, 0.3)  # fill collapsed: a regression even though "lower"
    assert c_main([str(a), str(b)]) == 1
    assert "batch_fill_ratio" in capsys.readouterr().out
    assert c_main([str(b), str(a)]) == 0  # improved fill never flags


def _table_capture(path, table_bytes, per_dispatch_s=0.01, bytes_accessed=8e6,
                   quant="int8", strategy="fused"):
    """Synthetic capture: one dispatch span + a snapshot carrying the
    table-traffic gauges the guard tracks."""
    events = [
        {
            "event": "telemetry.span", "ts": 1.0, "path": "score/dispatch",
            "wall_s": per_dispatch_s,
        },
        {
            "event": "telemetry.snapshot", "ts": 2.0, "counters": {},
            "histograms": {},
            "gauges": {
                "langdetect_table_bytes": {
                    f"program=score/dispatch,quant={quant},"
                    f"strategy={strategy}": table_bytes,
                },
                "program_bytes_accessed": {
                    "program=score/dispatch": bytes_accessed,
                },
                "device_peak_bytes_per_s": {"device=cpu": 5.0e10},
            },
        },
    ]
    path.write_text("".join(json.dumps(ev) + "\n" for ev in events))


def test_compare_tracked_table_bytes_regression(tmp_path, capsys):
    """A change that silently de-quantizes (table_bytes 4x) or re-balloons
    a program's table traffic fails the guard even when every latency
    percentile held steady. The tracked key is per PROGRAM: the
    de-quantization also changes the gauge's quant/strategy labels, and
    the regression must survive that label flip."""
    from spark_languagedetector_tpu.telemetry.compare import main as c_main

    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    _table_capture(a, 12.6e6, quant="int8", strategy="fused")
    # De-quantized candidate: 4x the bytes AND different labels.
    _table_capture(b, 50.4e6, quant="f32", strategy="gather")
    assert c_main([str(a), str(b)]) == 1
    assert "table_bytes[score/dispatch]" in capsys.readouterr().out
    capsys.readouterr()
    assert c_main([str(a), str(a)]) == 0  # identical captures pass


def test_compare_tracked_bytes_utilization(tmp_path, capsys):
    """est_bytes_utilization is re-derived from the capture exactly like
    stage_summary joins it (bytes/call / per-call seconds / peak) and
    regresses upward — more of the HBM roof consumed per dispatch."""
    from spark_languagedetector_tpu.telemetry.compare import (
        capture_stats,
        main as c_main,
    )
    from spark_languagedetector_tpu.telemetry.report import load_events

    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    _table_capture(a, 12.6e6, bytes_accessed=8e6)
    _table_capture(b, 12.6e6, bytes_accessed=40e6)  # 5x the traffic
    stats = capture_stats(load_events(str(a)))
    key = "est_bytes_utilization[score/dispatch]"
    assert stats["tracked"][key] == pytest.approx(8e6 / 0.01 / 5.0e10)
    assert c_main([str(a), str(b)]) == 1
    assert "est_bytes_utilization" in capsys.readouterr().out
    # A tracked metric appearing in only one capture is informational.
    plain = tmp_path / "plain.jsonl"
    plain.write_text(json.dumps({
        "event": "telemetry.span", "ts": 1.0, "path": "score/dispatch",
        "wall_s": 0.01,
    }) + "\n")
    capsys.readouterr()
    assert c_main([str(plain), str(a)]) == 0
    assert "only in candidate" in capsys.readouterr().out


def _collect_capture(path, collect_bytes):
    """Synthetic capture: a fit with the winner-rows collect gauge."""
    events = [
        {
            "event": "telemetry.span", "ts": 1.0, "path": "fit/collect",
            "wall_s": 0.002,
        },
        {
            "event": "telemetry.snapshot", "ts": 2.0, "counters": {},
            "histograms": {},
            "gauges": {
                "langdetect_fit_collect_bytes": {
                    "program=fit/collect": collect_bytes,
                },
            },
        },
    ]
    path.write_text("".join(json.dumps(ev) + "\n" for ev in events))


def test_compare_tracked_fit_collect_bytes_regression(tmp_path, capsys):
    """The fit-collect contract (docs/PERFORMANCE.md §8): a change that
    silently falls back to pulling the full [V, L] table instead of the
    k·L winner rows balloons langdetect_fit_collect_bytes and must fail
    the guard even with every latency percentile steady."""
    from spark_languagedetector_tpu.telemetry.compare import (
        capture_stats,
        main as c_main,
    )
    from spark_languagedetector_tpu.telemetry.report import load_events

    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    _collect_capture(a, 57_000.0)  # winner rows (k=400 × 6 langs × 4B + ids)
    _collect_capture(b, 1_572_864.0)  # full 2^16 × 6 table came back
    stats = capture_stats(load_events(str(a)))
    assert stats["tracked"]["fit_collect_bytes[fit/collect]"] == 57_000.0
    assert c_main([str(a), str(b)]) == 1
    assert "fit_collect_bytes[fit/collect]" in capsys.readouterr().out
    capsys.readouterr()
    assert c_main([str(a), str(a)]) == 0  # identical captures pass
    # Shrinking the collect (more aggressive winners) never flags.
    capsys.readouterr()
    assert c_main([str(b), str(a)]) == 0


def test_compare_cli_usage_and_io_errors(tmp_path, capsys):
    from spark_languagedetector_tpu.telemetry.compare import main as c_main

    assert c_main([]) == 2
    assert c_main([FIXTURE]) == 2
    assert c_main([FIXTURE, FIXTURE, "--bogus"]) == 2
    assert c_main([FIXTURE, FIXTURE, "--threshold"]) == 2
    assert c_main([str(tmp_path / "nope.jsonl"), FIXTURE]) == 2
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert c_main([str(empty), FIXTURE]) == 2  # nothing comparable


# ------------------------------------------------------ report CLI hardening --
def test_report_empty_capture_renders_message(tmp_path, capsys):
    p = tmp_path / "empty.jsonl"
    p.write_text("")
    assert report_main([str(p)]) == 0
    assert "empty capture" in capsys.readouterr().out


def test_report_snapshot_only_capture(tmp_path, capsys):
    p = tmp_path / "snap.jsonl"
    p.write_text(json.dumps({
        "event": "telemetry.snapshot", "ts": 1.0,
        "counters": {"jax/compile_events": 3},
        "gauges": {"live_buffer_bytes": {"device=cpu:0": 64.0}},
        "histograms": {"score/batch_fill_ratio": {
            "count": 1, "sum": 0.5, "mean": 0.5, "p50": 0.5, "p99": 0.5,
        }},
    }) + "\n")
    assert report_main([str(p)]) == 0
    out = capsys.readouterr().out
    assert "no span events found" in out
    assert "jax/compile_events" in out and "live_buffer_bytes" in out


def test_report_malformed_snapshot_sections_do_not_raise(tmp_path, capsys):
    """Hand-edited/truncated captures: wrong-typed snapshot sections must
    degrade to skipped entries, never to a traceback."""
    p = tmp_path / "bad.jsonl"
    p.write_text(
        "not json at all\n"
        + json.dumps({"event": "telemetry.span", "ts": 1.0, "path": "a",
                      "wall_s": 0.1}) + "\n"
        + json.dumps({
            "event": "telemetry.snapshot", "ts": 2.0,
            "counters": "not-a-dict",
            "gauges": {"g": "not-a-dict", 7: {"x": 1.0}},
            "histograms": {
                "h1": "not-a-dict",
                "h2": {"count": 2, "mean": "NaNish"},
                "h3": {"count": 1, "sum": 0.1, "mean": 0.1, "p50": 0.1,
                       "p99": 0.1},
            },
        }) + "\n"
    )
    assert report_main([str(p)]) == 0
    out = capsys.readouterr().out
    assert re.search(r"^a\s+1\b", out, re.M)
    assert "h3" in out and "h2" not in out


# ------------------------------------------------------- profiling satellites --
def test_trace_writes_per_call_subdirs_and_survives_exceptions(
    tmp_path, monkeypatch
):
    from spark_languagedetector_tpu.telemetry import REGISTRY
    from spark_languagedetector_tpu.utils.profiling import trace

    monkeypatch.setenv("LANGDETECT_TRACE_DIR", str(tmp_path))
    REGISTRY.reset()
    with trace(label="score"):
        pass
    with pytest.raises(ValueError):
        with trace(label="score"):
            raise ValueError("traced region blew up")
    subdirs = sorted(d for d in os.listdir(tmp_path) if d.startswith("score-"))
    assert len(subdirs) == 2 and subdirs[0] != subdirs[1]
    # Both captures (including the raising one) recorded the profiler span.
    assert REGISTRY.histograms["span:profile/trace"].count == 2


def test_trace_does_not_reroot_inner_stage_spans(tmp_path, monkeypatch):
    """profile/trace is recorded as a root-level sibling, never as the
    ambient parent: with LANGDETECT_TRACE_DIR set, the stage tree (and
    the cost-gauge join and cross-capture compare keyed on it) must keep
    its normal 'score/...' paths, not 'profile/trace/score/...'."""
    from spark_languagedetector_tpu.telemetry import REGISTRY, trace_request
    from spark_languagedetector_tpu.utils.profiling import trace

    monkeypatch.setenv("LANGDETECT_TRACE_DIR", str(tmp_path))
    REGISTRY.reset()
    sink = _ListSink()
    REGISTRY.add_sink(sink)
    try:
        with trace_request("aaaa00000000000f"), trace(label="score"):
            with span("score"):
                with span("score/dispatch"):
                    pass
    finally:
        REGISTRY.remove_sink(sink)
    stages = REGISTRY.stage_summary()
    assert "score" in stages and "score/dispatch" in stages
    assert not any(p.startswith("profile/trace/") for p in stages)
    assert "profile/trace" in stages
    # The profiler record still carries request/thread attribution.
    prof = [e for e in sink.events if e.get("path") == "profile/trace"]
    assert prof and prof[0]["trace_id"] == "aaaa00000000000f"
    assert isinstance(prof[0]["tid"], int)


def test_trace_noop_without_dir(monkeypatch):
    from spark_languagedetector_tpu.telemetry import REGISTRY
    from spark_languagedetector_tpu.utils.profiling import trace

    monkeypatch.delenv("LANGDETECT_TRACE_DIR", raising=False)
    REGISTRY.reset()
    with trace():
        pass
    assert "span:profile/trace" not in REGISTRY.histograms


# -------------------------------------------------- smoke capture → Perfetto --
def test_smoke_telemetry_capture_exports_to_perfetto(tmp_path):
    """Acceptance: a --smoke-telemetry capture renders to valid Perfetto
    trace-event JSON (monotonic per-lane ts, trace ids in args) and the
    smoke result points at a real flight-recorder post-mortem."""
    import bench
    from spark_languagedetector_tpu.telemetry.tracing import main as t_main

    jsonl = str(tmp_path / "smoke.jsonl")
    result = bench.smoke_telemetry(jsonl)
    assert result["flight_recorder"]["exercised"] is True
    assert os.path.exists(result["flight_recorder"]["dump"])
    assert result["flight_recorder"]["events"] > 0
    out = str(tmp_path / "smoke.trace.json")
    assert t_main([jsonl, out]) == 0
    with open(out) as fh:
        complete = _valid_chrome_trace(json.load(fh))
    assert len(complete) >= 4
    smoke_tid = result["telemetry"]["trace_id"]
    assert any(
        e["args"].get("trace_id") == smoke_tid for e in complete
    ), "the smoke score call's trace id must be in the exported args"
    # Cost/utilization gauges landed in the stage breakdown (CPU).
    disp = result["telemetry"]["stages"]["score/dispatch"]
    assert disp.get("est_flops_per_call", 0) > 0
    assert "flops_utilization" in disp
