"""Pure-Python oracle: a direct behavioral model of the reference algorithm.

Reimplements the reference's fit/detect semantics with plain dicts and floats
(no JAX, no numpy vectorization) to serve as the accuracy-parity oracle the
framework is tested against — the analog of the reference's hand-built tiny
profiles (``LanguageDetectorModelSpecs.scala:26-35``) but covering fit too.

Behavioral citations:
  * sliding windows incl. partial final group — LanguageDetector.scala:36-43,
    LanguageDetectorModel.scala:139-152 (Scala ``sliding`` semantics)
  * weight = log(1 + presence / #langs containing) — LanguageDetector.scala:86-87
  * per-language top-k then union — LanguageDetector.scala:100-132
  * scorer: sum weight vectors of matched windows, argmax (first max wins),
    zero-hit ⇒ index 0 — LanguageDetectorModel.scala:131-156
  * String→bytes predict path truncates UTF-16 units to low byte
    — LanguageDetectorModel.scala:158-165
"""

from __future__ import annotations

import math
from collections import Counter, defaultdict


def sliding(seq: bytes, n: int) -> list[bytes]:
    """Scala ``sliding(n)``: all full windows; one partial group if len < n;
    nothing for an empty sequence."""
    if len(seq) == 0:
        return []
    if len(seq) < n:
        return [seq]
    return [seq[i : i + n] for i in range(len(seq) - n + 1)]


def fit_oracle(
    docs: list[tuple[str, str]],
    supported_languages: list[str],
    gram_lengths: list[int],
    profile_size: int,
    weight_mode: str = "parity",
) -> dict[bytes, list[float]]:
    """(lang, text) pairs → gram → weight-vector map."""
    counts: dict[bytes, Counter] = defaultdict(Counter)
    for lang, text in docs:
        data = text.encode("utf-8")
        for n in gram_lengths:
            for gram in sliding(data, n):
                counts[gram][lang] += 1

    weights: dict[bytes, list[float]] = {}
    for gram, per_lang in counts.items():
        if weight_mode == "parity":
            nlangs = len(per_lang)
            weights[gram] = [
                math.log1p((1.0 if l in per_lang else 0.0) / nlangs)
                for l in supported_languages
            ]
        else:
            total = sum(per_lang.values())
            weights[gram] = [
                math.log1p(per_lang.get(l, 0) / total) for l in supported_languages
            ]

    winners: set[bytes] = set()
    for i, _ in enumerate(supported_languages):
        # Tie-break mirrors the framework's gram-id ascending order: ids are
        # grouped by gram length first, lexicographic by bytes within a length.
        ranked = sorted(
            weights.items(), key=lambda kv: (-kv[1][i], len(kv[0]), kv[0])
        )
        winners.update(g for g, _ in ranked[:profile_size])
    return {g: weights[g] for g in winners}


def detect_oracle(
    text: str,
    gram_map: dict[bytes, list[float]],
    supported_languages: list[str],
    gram_lengths: list[int],
    encoding: str = "utf8",
) -> str:
    data = (
        text.encode("utf-8")
        if encoding == "utf8"
        else bytes(b for b in text.encode("utf-16-le")[::2])
    )
    L = len(supported_languages)
    acc = [0.0] * L
    for n in gram_lengths:
        for gram in sliding(data, n):
            vec = gram_map.get(gram)
            if vec is not None:
                for i in range(L):
                    acc[i] += vec[i]
    best = max(range(L), key=lambda i: (acc[i], -i))  # first max wins
    return supported_languages[best]


def scores_oracle(
    text: str,
    gram_map: dict[bytes, list[float]],
    num_languages: int,
    gram_lengths: list[int],
    encoding: str = "utf8",
) -> list[float]:
    data = (
        text.encode("utf-8")
        if encoding == "utf8"
        else bytes(b for b in text.encode("utf-16-le")[::2])
    )
    acc = [0.0] * num_languages
    for n in gram_lengths:
        for gram in sliding(data, n):
            vec = gram_map.get(gram)
            if vec is not None:
                for i in range(num_languages):
                    acc[i] += vec[i]
    return acc
