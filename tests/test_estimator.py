"""Estimator/Model API tests — mirrors the reference's spec suite (SURVEY.md §4)
plus what the reference never tested (preprocessors, encodings, params)."""

import numpy as np
import pytest

from spark_languagedetector_tpu import LanguageDetector, LanguageDetectorModel, Table
from spark_languagedetector_tpu.ops.vocab import HASHED

from .oracle import detect_oracle, fit_oracle

TRAIN_ROWS = {
    "lang": ["de", "de", "en", "en"],
    "fulltext": [
        "Dies ist ein deutscher Text, das ist ja sehr schön",
        "Dies ist ein andere deutscher Text, und der ist auch sehr schön",
        "This is a text in english, and that is very nice",
        "This is another text in english and that is also nice",
    ],
}


def test_fit_basic_model_reference_spec():
    """LanguageDetectorSpecs.scala:15-40: trigram, k=5, 2 langs ⇒ 10 grams,
    length-2 weight vectors."""
    detector = LanguageDetector(["de", "en"], [3], 5)
    model = detector.fit(Table(TRAIN_ROWS))
    assert len(model.gram_probabilities) == 10
    assert len(next(iter(model.gram_probabilities.values()))) == 2


def test_fit_rejects_unsupported_language():
    """LanguageDetector.scala:221-228 (message preserved verbatim)."""
    data = Table(
        {
            "lang": ["de", "es"],
            "fulltext": ["Dies ist deutsch", "Habla espanol"],
        }
    )
    detector = LanguageDetector(["de", "en"], [3], 5)
    with pytest.raises(ValueError, match="contians es, but it is not"):
        detector.fit(data)


def test_fit_rejects_language_without_examples():
    """LanguageDetectorSpecs.scala:43-66: exact reference error message."""
    data = Table(
        {
            "lang": ["de", "de"],
            "fulltext": ["Dies ist deutsch", "Noch ein deutscher Text"],
        }
    )
    detector = LanguageDetector(["de", "en"], [3], 5)
    with pytest.raises(
        ValueError,
        match="No training examples found for language en. "
        "Provide examples for each language",
    ):
        detector.fit(data)


def test_transform_with_handbuilt_model_reference_spec():
    """LanguageDetectorModelSpecs.scala:13-47: hand-built model, 4 docs ⇒
    2 de + 2 en, row count preserved, output appended as 'lang'."""
    model = LanguageDetectorModel.from_gram_map(
        {b"Die": [1.0, 0.0], b"Thi": [0.0, 1.0]}, [3], ["de", "en"]
    )
    data = Table({"fulltext": TRAIN_ROWS["fulltext"]})
    out = model.transform(data)
    assert out.num_rows == 4
    langs = out.column("lang").tolist()
    assert langs.count("de") == 2
    assert langs.count("en") == 2
    assert out.schema.names == ["fulltext", "lang"]


def test_transform_requires_string_input_column():
    """LanguageDetectorModel.scala:206-209."""
    model = LanguageDetectorModel.from_gram_map({b"a": [1.0]}, [1], ["aa"])
    with pytest.raises(TypeError, match="Input type must be string"):
        model.transform(Table({"fulltext": np.asarray([1, 2, 3])}))
    with pytest.raises(KeyError):
        model.transform(Table({"other": ["text"]}))


def test_fit_then_transform_end_to_end_matches_oracle():
    detector = LanguageDetector(["de", "en"], [2, 3], 20)
    model = detector.fit(Table(TRAIN_ROWS))
    test_texts = [
        "Das ist wunderbar und sehr schön",
        "The weather is very nice today",
    ]
    out = model.transform(Table({"fulltext": test_texts}))

    train_pairs = list(zip(TRAIN_ROWS["lang"], TRAIN_ROWS["fulltext"]))
    gram_map = fit_oracle(train_pairs, ["de", "en"], [2, 3], 20)
    expected = [
        detect_oracle(t, gram_map, ["de", "en"], [2, 3]) for t in test_texts
    ]
    assert out.column("lang").tolist() == expected == ["de", "en"]


def test_custom_column_names():
    detector = (
        LanguageDetector(["de", "en"], [3], 5)
        .set_input_col("body")
        .set_label_col("language")
    )
    model = detector.fit(
        Table({"language": TRAIN_ROWS["lang"], "body": TRAIN_ROWS["fulltext"]})
    )
    model.set_input_col("body").set_output_col("detected")
    out = model.transform(Table({"body": ["Dies ist ein deutscher Text schön"]}))
    assert out.schema.names == ["body", "detected"]


def test_low_byte_predict_encoding_parity_quirk():
    """Q2: with predictEncoding='low_byte', non-ASCII grams learned at fit
    (UTF-8) can never match at predict — reference behavior."""
    model = LanguageDetectorModel.from_gram_map(
        {"schön".encode("utf-8")[-3:]: [1.0, 0.0], b"nic": [0.0, 1.0]},
        [3],
        ["de", "en"],
    )
    text = "schön"
    assert model.detect(text) == "de"  # utf8 default: gram matches
    model.set_predict_encoding("low_byte")
    assert model.detect(text) == "de"  # all-miss → first language (Q6)


def test_cpu_backend_param_places_scoring_on_cpu():
    model = LanguageDetectorModel.from_gram_map(
        {b"ab": [1.0, 0.0]}, [2], ["x", "y"]
    ).set_backend("cpu")
    assert model.detect("abab") == "x"
    runner = model._get_runner()
    assert runner.device is not None and runner.device.platform == "cpu"


def test_param_change_invalidates_cached_runner():
    model = LanguageDetectorModel.from_gram_map({b"ab": [1.0]}, [2], ["x"])
    model.detect("ab")
    first = model._runner
    assert first is not None
    model.set_batch_size(16)
    assert model._runner is None
    model.detect("ab")
    assert model._runner.batch_size == 16
    clone = model.copy()
    assert clone._runner is None


def test_hashed_vocab_fit_and_transform():
    detector = (
        LanguageDetector(["de", "en"], [1, 2, 3, 4, 5], 50)
        .set_vocab_mode(HASHED)
        .set_hash_bits(16)
    )
    model = detector.fit(Table(TRAIN_ROWS))
    out = model.transform(
        Table({"fulltext": ["Das ist schön und wunderbar", "this is very nice"]})
    )
    assert out.column("lang").tolist() == ["de", "en"]


def test_copy_covers_all_params():
    detector = LanguageDetector(["de", "en"], [3], 5)
    clone = detector.copy({"languageProfileSize": 9})
    assert clone.get("languageProfileSize") == 9
    assert clone.get("supportedLanguages") == ["de", "en"]
    assert detector.get("languageProfileSize") == 5
    assert clone.uid == detector.uid


def test_save_grams_to(tmp_path):
    import pyarrow.parquet as pq

    path = str(tmp_path / "grams")
    detector = LanguageDetector(["de", "en"], [3], 5).set_save_grams_to(path)
    detector.fit(Table(TRAIN_ROWS))
    table = pq.read_table(path + "/part-00000.parquet")
    assert table.num_rows == 10
    assert set(table.column_names) == {"gram", "probabilities"}


def test_estimator_backend_propagates_to_model():
    """The README quickstart configures the backend on the estimator
    (Spark-style: estimator params flow to the fitted model); an unset
    estimator leaves the model's 'auto' default untouched."""
    table = Table({
        "lang": ["de", "en"],
        "fulltext": ["der hund schön über", "the dog nice with"],
    })
    m = (
        LanguageDetector(["de", "en"], [2], 50)
        .set_backend("cpu")
        .fit(table)
    )
    assert m.get("backend") == "cpu"
    assert not LanguageDetector(["de", "en"], [2], 50).fit(table).is_set("backend")
