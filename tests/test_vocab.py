"""Vocab spec: id encoding round-trips and host/device lockstep."""

import numpy as np
import pytest

from spark_languagedetector_tpu.ops import vocab as V


def test_exact_offsets_cover_all_lengths_below_max():
    spec = V.VocabSpec(V.EXACT, (3,))
    assert set(spec.offsets) == {1, 2, 3}
    assert spec.offsets[1] == 0
    assert spec.offsets[2] == 256
    assert spec.offsets[3] == 256 + 65536
    assert spec.id_space_size == 256 + 65536 + 256**3


def test_exact_gram_id_roundtrip():
    spec = V.VocabSpec(V.EXACT, (1, 2, 3))
    for gram in [b"a", b"ab", b"abc", b"\x00\x00", b"\xff\xff\xff", b"\x00"]:
        gid = spec.gram_to_id(gram)
        assert spec.id_to_gram(gid) == gram


def test_exact_ids_are_disjoint_across_lengths():
    spec = V.VocabSpec(V.EXACT, (2, 3))
    ids = set()
    for gram in [b"a", b"b", b"aa", b"ab", b"aaa", b"\x00\x00\x00"]:
        gid = spec.gram_to_id(gram)
        assert gid not in ids
        ids.add(gid)


def test_exact_mode_rejects_long_grams():
    # n <= 5 is supported (cuckoo membership); beyond the packed-key limit
    # only hashed mode applies.
    V.VocabSpec(V.EXACT, (1, 5))
    with pytest.raises(ValueError, match="hashed"):
        V.VocabSpec(V.EXACT, (1, 6))


def test_hashed_mode_buckets_in_range():
    spec = V.VocabSpec(V.HASHED, (1, 2, 5), hash_bits=12)
    for gram in [b"a", b"hello", b"\xff" * 5]:
        assert 0 <= spec.gram_to_id(gram) < 4096


def test_window_ids_numpy_matches_scalar():
    spec = V.VocabSpec(V.EXACT, (2,))
    doc = b"abcd"
    batch = np.frombuffer(doc, dtype=np.uint8)[None, :]
    ids = V.window_ids_numpy(batch, 2, spec)[0]
    expected = [spec.gram_to_id(doc[i : i + 2]) for i in range(3)]
    assert ids.tolist() == expected


def test_window_ids_device_matches_numpy_exact_and_hashed():
    rng = np.random.default_rng(0)
    batch = rng.integers(0, 256, size=(4, 32), dtype=np.uint8)
    for spec in [
        V.VocabSpec(V.EXACT, (1, 2, 3)),
        V.VocabSpec(V.HASHED, (2, 4), hash_bits=20),
    ]:
        for n in spec.gram_lengths:
            host = V.window_ids_numpy(batch, n, spec)
            dev = np.asarray(V.window_ids(batch, n, spec))
            np.testing.assert_array_equal(host, dev.astype(np.int64))


def test_hashed_window_ids_match_scalar_hash():
    spec = V.VocabSpec(V.HASHED, (3,), hash_bits=16)
    doc = b"hello world"
    batch = np.frombuffer(doc, dtype=np.uint8)[None, :]
    ids = V.window_ids_numpy(batch, 3, spec)[0]
    expected = [spec.gram_to_id(doc[i : i + 3]) for i in range(len(doc) - 2)]
    assert ids.tolist() == expected


def test_exact12_scheme_short_grams_get_polynomial_ids():
    spec = V.VocabSpec(V.HASHED, (1, 2, 5), hash_bits=20)
    assert spec.hash_scheme == V.EXACT12  # auto resolves at >= 17 bits
    assert spec.gram_to_id(b"\x00") == 0
    assert spec.gram_to_id(b"\xff") == 255
    assert spec.gram_to_id(b"ab") == 256 + ord("a") * 256 + ord("b")
    # long grams fold into [65792, 2^20)
    gid = spec.gram_to_id(b"hello")
    assert 256 + 65536 <= gid < (1 << 20)


def test_exact12_scheme_window_ids_lockstep():
    rng = np.random.default_rng(3)
    batch = rng.integers(0, 256, size=(4, 32), dtype=np.uint8)
    spec = V.VocabSpec(V.HASHED, (1, 2, 3, 4, 5), hash_bits=20)
    for n in spec.gram_lengths:
        host = V.window_ids_numpy(batch, n, spec)
        dev = np.asarray(V.window_ids(batch, n, spec))
        np.testing.assert_array_equal(host, dev.astype(np.int64))
        doc = bytes(batch[0, : n + 3])
        expected = [spec.gram_to_id(doc[i : i + n]) for i in range(4)]
        assert host[0, :4].tolist() == expected


def test_exact12_auto_falls_back_below_17_bits():
    spec = V.VocabSpec(V.HASHED, (1, 2, 5), hash_bits=12)
    assert spec.hash_scheme == V.FNV1A
    with pytest.raises(ValueError, match="hash_bits >= 17"):
        V.VocabSpec(V.HASHED, (1, 2, 5), hash_bits=12, hash_scheme="exact12")


def test_fnv1a_scheme_still_available():
    spec = V.VocabSpec(V.HASHED, (1, 2, 5), hash_bits=20, hash_scheme="fnv1a")
    # pure FNV: a 1-byte gram does NOT get its polynomial id in general
    h = 2166136261
    h = ((h ^ ord("a")) * 16777619) & 0xFFFFFFFF
    assert spec.gram_to_id(b"a") == h & ((1 << 20) - 1)


def test_short_doc_ids_one_per_longer_gram_length():
    spec = V.VocabSpec(V.EXACT, (2, 3))
    assert V.short_doc_ids_numpy(b"", spec) == []
    ids = V.short_doc_ids_numpy(b"a", spec)
    assert ids == [spec.gram_to_id(b"a")] * 2  # once for n=2, once for n=3
    ids2 = V.short_doc_ids_numpy(b"ab", spec)
    assert ids2 == [spec.gram_to_id(b"ab")]  # only n=3 is longer
