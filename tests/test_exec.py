"""Execution core + config + autotuner (ISSUE 8).

The acceptance contract: one scheduler/executor core under all three front
ends with bit-exact parity to the pre-refactor paths on the
geometry-stable gather strategy (matmul strategies stay labels-exact per
the ARCHITECTURE.md reduction-order class), chaos plans replaying through
the shared retry/degrade wiring at the existing fault sites, one audited
config module resolving every LANGDETECT_* knob, and a deterministic
offline tuner whose profile the runner/stream/serve load at startup.
"""

import json
import os
import threading

import numpy as np
import pytest

from spark_languagedetector_tpu import LanguageDetector, Table
from spark_languagedetector_tpu.api.runner import BatchRunner
from spark_languagedetector_tpu.exec import config as exec_config
from spark_languagedetector_tpu.exec import core, tune
from spark_languagedetector_tpu.exec.profile import (
    TuningProfile,
    content_version,
)
from spark_languagedetector_tpu.models.profile import GramProfile
from spark_languagedetector_tpu.ops import encoding
from spark_languagedetector_tpu.ops.encoding import bucket_length
from spark_languagedetector_tpu.resilience.faults import FaultPlan, plan_scope
from spark_languagedetector_tpu.resilience.policy import (
    CircuitBreaker,
    RetryPolicy,
)
from spark_languagedetector_tpu.serve import ContinuousBatcher
from spark_languagedetector_tpu.stream.microbatch import (
    memory_source,
    run_stream,
)
from spark_languagedetector_tpu.telemetry import REGISTRY
from spark_languagedetector_tpu.telemetry.compare import (
    capture_stats,
    compare_captures,
)

LANGS = ("x", "y", "z")
GRAM_MAP = {
    b"ab": [1.0, 0.0, 0.2],
    b"bc": [0.5, 0.5, 0.0],
    b"zz": [0.0, 2.0, 0.1],
    b"qx": [0.1, 0.0, 3.0],
}


def _runner(**kw):
    profile = GramProfile.from_gram_map(GRAM_MAP, LANGS, (2,))
    weights, lut = profile.device_arrays()
    kw.setdefault("strategy", "gather")
    return BatchRunner(weights=weights, lut=lut, spec=profile.spec, **kw)


def _docs(rng, n, max_len=200):
    return [
        bytes(rng.integers(97, 123, rng.integers(0, max_len)).tolist())
        for _ in range(n)
    ]


@pytest.fixture(autouse=True)
def _fresh_profile_cache():
    exec_config.reload_profile()
    yield
    exec_config.reload_profile()


# ------------------------------------------------------------ core: plan ----
def _reference_plan(sizes, length_buckets, rows_for, order=None):
    """The pre-refactor planning algorithm, verbatim (runner + fit both
    carried a copy): bucket grouping in iteration order, per-bucket full
    batches, remainder carried into the next wider bucket, one tail."""
    idx_iter = range(len(sizes)) if order is None else order
    by_bucket = {}
    for i in idx_iter:
        b = bucket_length(sizes[i] or 1, length_buckets)
        by_bucket.setdefault(b, []).append(int(i))
    plan, carry = [], []
    for pad_to in sorted(by_bucket):
        idxs = carry + by_bucket[pad_to]
        rows = rows_for(pad_to)
        full_end = len(idxs) - len(idxs) % rows
        for start in range(0, full_end, rows):
            plan.append((idxs[start:start + rows], pad_to))
        carry = idxs[full_end:]
    if carry:
        pad_to = bucket_length(
            max(sizes[i] for i in carry) or 1, length_buckets
        )
        rows = rows_for(pad_to)
        for start in range(0, len(carry), rows):
            plan.append((carry[start:start + rows], pad_to))
    return plan


def test_plan_micro_batches_matches_pre_refactor_reference_fuzz():
    rng = np.random.default_rng(3)
    buckets = (64, 128, 512, 1024)
    for trial in range(30):
        n = int(rng.integers(0, 200))
        sizes = [int(s) for s in rng.integers(0, 1400, n)]
        rows_for = lambda p: core.rows_under_byte_budget(  # noqa: E731
            p, 16 << 10, 32, 4
        )
        order = None
        if trial % 2:
            order = np.argsort(sizes, kind="stable")
        got = core.plan_micro_batches(
            sizes, length_buckets=buckets, rows_for=rows_for, order=order
        )
        want = _reference_plan(sizes, buckets, rows_for, order=order)
        assert len(got) == len(want)
        for (gsel, gpad), (wsel, wpad) in zip(got, want):
            assert gpad == wpad
            assert list(gsel) == list(wsel)
        # Every item planned exactly once.
        planned = [int(i) for sel, _ in got for i in sel]
        assert sorted(planned) == list(range(n))


def test_rows_under_byte_budget_halves_to_floor_and_legacy_alias():
    assert core.rows_under_byte_budget(2048, 8 << 20, 4096) == 4096
    assert core.rows_under_byte_budget(8192, 8 << 20, 4096) == 1024
    assert core.rows_under_byte_budget(1 << 30, 8 << 20, 4096, 64) == 64
    # ops.encoding keeps the old import surface, delegating to the core.
    for pad_to in (128, 2048, 8192):
        assert encoding.rows_under_byte_budget(
            pad_to, 8 << 20, 4096
        ) == core.rows_under_byte_budget(pad_to, 8 << 20, 4096)


# -------------------------------------------------- core: ordered prefetch --
def test_ordered_prefetch_orders_results_and_bounds_pulls():
    pulled = []

    def src():
        for i in range(20):
            pulled.append(i)
            yield i

    done = []
    out = []
    for item, thunk, prefetched, pending in core.ordered_prefetch(
        src(), lambda i: i * i, depth=3, workers=2
    ):
        assert pending >= 1
        # Bounded pulls: never more than depth+1 ahead of the drain.
        assert len(pulled) - len(done) <= 4
        out.append(thunk())
        done.append(item)
    assert out == [i * i for i in range(20)]
    assert done == list(range(20))


def test_ordered_prefetch_depth_zero_runs_inline():
    ran_in = []

    def fn(i):
        ran_in.append(threading.current_thread())
        return i + 1

    results = []
    for _, thunk, prefetched, pending in core.ordered_prefetch(
        range(5), fn, depth=0
    ):
        assert prefetched is False and pending == 1
        assert not ran_in or ran_in[-1] is threading.current_thread()
        results.append(thunk())
    assert results == [1, 2, 3, 4, 5]
    assert all(t is threading.current_thread() for t in ran_in)


def test_ordered_prefetch_surfaces_error_at_the_failing_item():
    def fn(i):
        if i == 3:
            raise RuntimeError("boom3")
        return i

    seen = []
    with pytest.raises(RuntimeError, match="boom3"):
        for item, thunk, _, _ in core.ordered_prefetch(
            range(6), fn, depth=2, workers=2
        ):
            seen.append(thunk())
    assert seen == [0, 1, 2]  # everything before the poison item drained


def test_ordered_prefetch_close_stops_worker():
    started = []
    gen = core.ordered_prefetch(
        range(100), lambda i: started.append(i) or i, depth=2, workers=1
    )
    first = next(gen)
    assert first[1]() == 0
    gen.close()  # must cancel pending work and join the pool
    assert len(started) <= 5


def test_ordered_prefetch_close_releases_produced_buffers():
    """View-lifetime hazard (PERFORMANCE.md §11): a zero-copy producer
    hands out buffers backed by caller-owned memory (Arrow pools,
    DocBlock planes). A generator closed mid-stream must drop its queued
    (item, future) pairs deterministically — not when the GC finds the
    deque — or the pool cannot reclaim the freed buffer."""
    import weakref

    refs = []

    def produce(i):
        buf = np.full(4096, i, dtype=np.uint8)
        refs.append(weakref.ref(buf))
        return buf

    gen = core.ordered_prefetch(range(10), produce, depth=3, workers=1)
    item, thunk, _, _ = next(gen)
    thunk()
    gen.close()
    del thunk  # the yielded future is the consumer's own reference
    assert refs  # the pipeline did run ahead
    assert all(r() is None for r in refs)


def test_ordered_prefetch_close_cannot_pin_arrow_buffers():
    """The satellite regression for the real ingest shape: producers that
    wrap Arrow string arrays into DocBlocks must not keep the Arrow
    buffers alive past close() — the block's ``owners`` tuple is the only
    thing pinning them, and the cleared deque drops it."""
    import weakref

    pa = pytest.importorskip("pyarrow")

    from spark_languagedetector_tpu.ops.encode_device import DocBlock

    arrays = [
        pa.array([f"doc-{i}-{j}" * 8 for j in range(64)], type=pa.binary())
        for i in range(8)
    ]
    refs = [weakref.ref(a) for a in arrays]

    def produce(i):
        block = DocBlock.from_arrow(arrays[i])
        return block

    gen = core.ordered_prefetch(range(8), produce, depth=3, workers=1)
    _, thunk, _, _ = next(gen)
    assert len(thunk()) == 64
    gen.close()
    del arrays, thunk, gen
    assert all(r() is None for r in refs)


# ------------------------------------------------ core: guarded dispatch ----
def test_guarded_dispatch_fast_path_and_recovered_hook():
    policy = RetryPolicy(max_attempts=2, base_delay_s=0.0)
    recovered = []
    out = core.guarded_dispatch(
        lambda: 41,
        policy=policy,
        site="score/dispatch",
        breaker=CircuitBreaker(name="t"),
        degraded=lambda cause: pytest.fail("degraded must not run"),
        on_recovered=lambda: recovered.append(1),
    )
    assert out == 41 and recovered == [1]


def test_guarded_dispatch_falls_to_ladder_with_cause_and_raises_deterministic():
    policy = RetryPolicy(max_attempts=1, base_delay_s=0.0)
    causes = []

    def fast():
        raise RuntimeError("transient")

    out = core.guarded_dispatch(
        fast,
        policy=policy,
        site="score/dispatch",
        breaker=CircuitBreaker(name="t2"),
        degraded=lambda cause: causes.append(cause) or "degraded",
    )
    assert out == "degraded"
    assert isinstance(causes[0], RuntimeError)
    with pytest.raises(ValueError):
        core.guarded_dispatch(
            lambda: (_ for _ in ()).throw(ValueError("det")),
            policy=policy,
            site="score/dispatch",
            breaker=CircuitBreaker(name="t3"),
            degraded=lambda cause: pytest.fail("deterministic must raise"),
        )


def test_guarded_dispatch_open_breaker_short_circuits():
    clock = [0.0]
    breaker = CircuitBreaker(
        1, 1000.0, name="t4", clock=lambda: clock[0],
    )
    breaker.record_failure()
    assert breaker.state == "open"
    policy = RetryPolicy(max_attempts=1, base_delay_s=0.0)
    before = REGISTRY.counters.get("resilience/breaker_short_circuit", 0)
    out = core.guarded_dispatch(
        lambda: pytest.fail("fast path must not run while open"),
        policy=policy,
        site="score/dispatch",
        breaker=breaker,
        degraded=lambda cause: "ladder",
    )
    assert out == "ladder"
    assert REGISTRY.counters["resilience/breaker_short_circuit"] == before + 1


# ------------------------------------------------- core: admission queue ----
def test_admission_queue_lane_priority_and_key_partition():
    q = core.AdmissionQueue(max_rows=100, max_wait_s=0.0, max_queue_rows=1000)
    q.admit(("bulk1", True), 4, "bulk")
    q.admit(("int1", True), 4, "interactive")
    q.admit(("int2", False), 4, "interactive")
    batch = q.next_batch(key=lambda item: item[1])
    # Interactive drains first; the key flip at int2 ends the batch before
    # it, and bulk1 (matching key) follows int1.
    assert [x[0] for x in batch] == ["int1", "bulk1"]
    q.done()
    assert [x[0] for x in q.next_batch(key=lambda item: item[1])] == ["int2"]
    q.done()


def test_admission_queue_shed_reasons_and_close():
    q = core.AdmissionQueue(
        max_rows=8, max_wait_s=10.0, max_queue_rows=10,
        shed_probe=lambda lane: "degraded" if lane == "bulk" else None,
    )
    assert q.admit("a", 8, "interactive") == (None, 0.0)
    assert q.admit("b", 8, "interactive")[0] == "queue_full"
    assert q.admit("c", 1, "bulk")[0] == "degraded"
    q.ema_rows_per_s = 1.0  # 8 queued rows -> 8s estimated wait
    q2 = core.AdmissionQueue(
        max_rows=8, max_wait_s=10.0, max_queue_rows=100, slo_s=0.5,
    )
    q2.ema_rows_per_s = 1.0
    q2.admit("a", 8, "interactive")
    reason, wait = q2.admit("b", 1, "interactive")
    assert reason == "slo" and wait == pytest.approx(8.0)
    evicted = q.close(drain=False)
    assert evicted == ["a"]
    assert q.admit("d", 1, "interactive")[0] == "closed"
    assert q.next_batch() is None


# ----------------------------------------------------------------- config ---
def test_config_precedence_and_type_validation(monkeypatch, tmp_path):
    monkeypatch.delenv("LANGDETECT_BATCH_BYTES", raising=False)
    assert exec_config.resolve("batch_bytes") == 8 << 20
    prof = TuningProfile(tuned={"batch_bytes": 1 << 20})
    path = tmp_path / "p.json"
    prof.save(str(path))
    monkeypatch.setenv(exec_config.PROFILE_ENV, str(path))
    exec_config.reload_profile()
    value, source = exec_config.resolve_with_source("batch_bytes")
    assert (value, source) == (1 << 20, "profile")
    monkeypatch.setenv("LANGDETECT_BATCH_BYTES", str(2 << 20))
    value, source = exec_config.resolve_with_source("batch_bytes")
    assert (value, source) == (2 << 20, "env")  # env beats profile
    value, source = exec_config.resolve_with_source("batch_bytes", 3 << 20)
    assert (value, source) == (3 << 20, "explicit")  # explicit beats env
    monkeypatch.setenv("LANGDETECT_BATCH_BYTES", "not-a-number")
    with pytest.raises(ValueError, match="LANGDETECT_BATCH_BYTES"):
        exec_config.resolve("batch_bytes")
    monkeypatch.setenv("LANGDETECT_BATCH_BYTES", "-5")
    with pytest.raises(ValueError, match="positive"):
        exec_config.resolve("batch_bytes")
    with pytest.raises(ValueError, match="unknown config knob"):
        exec_config.resolve("no_such_knob")


def test_bad_loglevel_does_not_break_package_import():
    """The pre-config bootstrap read of LANGDETECT_TPU_LOGLEVEL tolerates
    a bad value (default + warning) instead of raising at import time —
    a typo'd level must not make the whole package unimportable, matching
    the tolerance of the post-config re-sync (sync_level_from_config)."""
    import subprocess
    import sys

    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import logging\n"
        "import spark_languagedetector_tpu.utils.logging as L\n"
        "assert L._root.level == logging.WARNING\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        env={**os.environ, "LANGDETECT_TPU_LOGLEVEL": "verbose"},
        timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "LANGDETECT_TPU_LOGLEVEL ignored" in proc.stderr


def test_config_int_tuple_and_bool_parsing(monkeypatch):
    monkeypatch.setenv("LANGDETECT_LENGTH_BUCKETS", "128, 256,512")
    assert exec_config.resolve("length_buckets") == (128, 256, 512)
    monkeypatch.setenv("LANGDETECT_LENGTH_BUCKETS", "512,128")
    with pytest.raises(ValueError, match="ascending"):
        exec_config.resolve("length_buckets")
    monkeypatch.setenv("LANGDETECT_DEGRADED", "0")
    assert exec_config.resolve("degraded") is False
    monkeypatch.setenv("LANGDETECT_DEGRADED", "yes")
    assert exec_config.resolve("degraded") is True


def test_effective_config_reports_provenance_and_deprecations(
    monkeypatch, tmp_path
):
    prof = TuningProfile(tuned={"serve_max_rows": 64})
    path = tmp_path / "p.json"
    prof.save(str(path))
    monkeypatch.setenv(exec_config.PROFILE_ENV, str(path))
    monkeypatch.setenv("LANGDETECT_SERVE_MAX_WAIT_MS", "7.5")
    monkeypatch.setenv("LANGDETECT_FIT_BATCH_ROWS", "garbage")
    exec_config.reload_profile()
    out = exec_config.effective_config()
    assert out["profile"]["version"] == prof.version
    assert out["knobs"]["serve_max_rows"] == {
        "value": 64, "source": "profile", "env": "LANGDETECT_SERVE_MAX_ROWS",
    }
    assert out["knobs"]["serve_max_wait_ms"]["source"] == "env"
    assert out["knobs"]["serve_max_wait_ms"]["value"] == 7.5
    # A malformed env var renders as an error entry instead of raising —
    # /varz must describe the misconfiguration, not 500 on it.
    assert "error" in out["knobs"]["fit_batch_rows"]
    # The deprecation table names every hand-set knob the tuner replaces.
    assert out["deprecated_env"]["LANGDETECT_SERVE_MAX_ROWS"] == (
        "serve_max_rows"
    )
    assert set(out["deprecated_env"]) >= {
        "LANGDETECT_LENGTH_BUCKETS", "LANGDETECT_BATCH_BYTES",
        "LANGDETECT_FIT_BATCH_BYTES", "LANGDETECT_SERVE_MAX_WAIT_MS",
        "LANGDETECT_SERVE_MAX_ROWS", "LANGDETECT_SERVE_QUEUE_ROWS",
    }


# ---------------------------------------------------------------- profile ---
def test_profile_round_trip_and_validation(tmp_path):
    prof = TuningProfile(
        tuned={"length_buckets": [128, 384, 1024], "batch_bytes": 4 << 20},
        source={"items": 10},
        constraints={"max_shapes": 4},
        created=123.0,
    )
    path = tmp_path / "prof.json"
    prof.save(str(path))
    back = TuningProfile.load(str(path))
    assert back.tuned == prof.tuned
    assert back.version == prof.version == content_version(prof.tuned)
    with pytest.raises(ValueError, match="unknown tuned field"):
        TuningProfile(tuned={"nope": 1})
    with pytest.raises(ValueError, match="multiples of 128"):
        TuningProfile(tuned={"length_buckets": [100, 200]})
    with pytest.raises(ValueError, match="increasing"):
        TuningProfile(tuned={"length_buckets": [256, 128]})
    with pytest.raises(ValueError, match="positive"):
        TuningProfile(tuned={"batch_bytes": 0})
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"schema": 99, "tuned": {"batch_bytes": 1}}))
    with pytest.raises(ValueError, match="schema"):
        TuningProfile.load(str(bad))


# ------------------------------------------------------------------ tuner ---
def test_solve_buckets_dp_finds_tight_lattice():
    # 100 items at <=320B and 10 at <=1024B: two buckets suffice and the
    # DP must place the first edge at 320 -> 384 (aligned), not at 512.
    bins = {320: 100, 1024: 10}
    buckets = tune.solve_buckets(bins, max_shapes=2)
    assert buckets == [384, 1024]
    # With one shape allowed, everything pads to the max.
    assert tune.solve_buckets(bins, max_shapes=1) == [1024]
    # The shape-count constraint binds: never more buckets than allowed.
    many = {64 * i: 5 for i in range(1, 20)}
    assert len(tune.solve_buckets(many, max_shapes=4)) <= 4
    with pytest.raises(ValueError, match="exec/len"):
        tune.solve_buckets({})


def _synthetic_capture(tmp_path, counters, histograms=None, name="cap.jsonl"):
    events = [
        {"event": "telemetry.span", "ts": 100.0, "path": "score",
         "wall_s": 0.5},
        {"event": "telemetry.snapshot", "ts": 110.0, "counters": counters,
         "gauges": {}, "histograms": histograms or {}},
    ]
    path = tmp_path / name
    path.write_text("".join(json.dumps(e) + "\n" for e in events))
    return str(path)


def test_solve_emits_valid_profile_with_serve_fields(tmp_path):
    counters = {
        "exec/len/256": 500, "exec/len/320": 300, "exec/len/1024": 20,
        "serve/coalesced_rows": 1000, "serve/dispatches": 50,
    }
    hists = {
        "serve/rows_per_dispatch": {"count": 50, "mean": 20.0, "p90": 40.0},
    }
    path = _synthetic_capture(tmp_path, counters, hists)
    from spark_languagedetector_tpu.telemetry.report import load_events

    profile = tune.solve(load_events(path), max_shapes=4)
    # The chunking boundary is never shrunk below the built-in top bucket
    # (re-chunking + observation ratchet — see tune.solve); the DP's
    # tight interior widths ride beneath it.
    assert profile.tuned["length_buckets"][-1] == 8192
    assert 1024 in profile.tuned["length_buckets"]
    assert all(b % 128 == 0 for b in profile.tuned["length_buckets"])
    assert len(profile.tuned["length_buckets"]) <= 4
    # Unconstrained solve records NO byte budgets: defaults must keep
    # flowing through normal config fallback, not get frozen as "tuned".
    assert "batch_bytes" not in profile.tuned
    assert "fit_batch_bytes" not in profile.tuned
    assert profile.tuned["serve_max_rows"] == 64  # pow2 >= p90 rows
    assert profile.tuned["serve_queue_rows"] == 64 * 16
    assert 1.0 <= profile.tuned["serve_max_wait_ms"] <= 50.0
    assert profile.created == 110.0  # capture time, not wall clock
    # Deterministic: same capture, same profile, same version.
    again = tune.solve(load_events(path), max_shapes=4)
    assert again.version == profile.version
    assert again.to_json() == profile.to_json()


def test_tune_cli_contract(tmp_path, capsys):
    assert tune.main([]) == 2
    assert tune.main(["a.jsonl", "b.jsonl"]) == 2
    assert tune.main(["--bogus", "x"]) == 2
    assert tune.main([str(tmp_path / "missing.jsonl")]) == 2
    empty = _synthetic_capture(tmp_path, {}, name="empty.jsonl")
    assert tune.main([empty]) == 2  # no length signal -> loud failure
    cap = _synthetic_capture(tmp_path, {"exec/len/256": 10})
    out = tmp_path / "prof.json"
    assert tune.main([cap, "-o", str(out), "--max-shapes", "3"]) == 0
    prof = TuningProfile.load(str(out))
    assert prof.tuned["length_buckets"] == (256, 8192)
    text = capsys.readouterr().out
    assert "predicted padded-byte reduction" in text


# ----------------------------------------- parity: the three front ends -----
def test_core_fed_runner_stream_serve_bit_identical_fuzz():
    """The fuzz parity sweep (ISSUE 8 satellite): the same documents
    through the direct runner, the streaming engine (prefetch on), and
    the serve batcher — bit-identical scores on the gather strategy."""
    langs = list(LANGS)
    rng = np.random.default_rng(13)
    train_rows = [
        {"lang": langs[i % 3], "fulltext": "abc " * (i % 5 + 1) + "zq" * (i % 3)}
        for i in range(30)
    ]
    det = LanguageDetector(langs, [1, 2, 3], 50)
    model = det.fit(Table.from_rows(train_rows))
    runner = model._get_runner()
    assert runner.strategy == "gather"  # geometry-stable reference

    texts = [
        "".join(
            chr(int(c)) for c in rng.integers(97, 123, int(rng.integers(1, 300)))
        )
        for _ in range(40)
    ] + ["", "ab" * 600]
    from spark_languagedetector_tpu.ops.encoding import texts_to_bytes

    docs = texts_to_bytes(texts)
    direct = runner.score(docs)

    # Stream path: transform through the same model, prefetch pipeline on.
    sunk: list = []
    run_stream(
        model,
        memory_source([{"fulltext": t} for t in texts], 7),
        sunk.append,
        prefetch=2,
        workers=2,
    )
    stream_labels = [
        lab for t in sunk for lab in t.column(model.get_output_col())
    ]
    direct_labels = [langs[i] for i in np.argmax(direct, axis=1)]
    assert stream_labels == direct_labels

    # Serve path: concurrent submitters, coalesced dispatches.
    with ContinuousBatcher(runner, max_wait_ms=5, max_rows=64) as b:
        futs = [b.submit(docs[i::4]) for i in range(4)]
        for i, fut in enumerate(futs):
            np.testing.assert_array_equal(
                fut.result(timeout=30).values, direct[i::4]
            )


def test_chaos_plan_replays_through_shared_wiring_stream_and_serve():
    """Injected transients at the existing score/dispatch site replay
    through the core's guarded dispatch identically under both stream
    and serve — outputs bit-equal to the fault-free oracle."""
    runner = _runner()
    rng = np.random.default_rng(23)
    docs = _docs(rng, 24)
    oracle = runner.score(docs)
    plan = FaultPlan.parse("score/dispatch:error@2")
    with plan_scope(plan):
        got = runner.score(docs)
    np.testing.assert_array_equal(got, oracle)

    runner2 = _runner()
    with plan_scope(FaultPlan.parse("score/dispatch:error@2")):
        with ContinuousBatcher(runner2, max_wait_ms=2, max_rows=256) as b:
            np.testing.assert_array_equal(
                b.submit(docs).result(timeout=30).values, oracle
            )


def test_runner_loads_tuning_profile_at_startup(monkeypatch, tmp_path):
    prof = TuningProfile(
        tuned={"length_buckets": [256, 2048], "batch_bytes": 1 << 20}
    )
    path = tmp_path / "p.json"
    prof.save(str(path))
    monkeypatch.setenv(exec_config.PROFILE_ENV, str(path))
    exec_config.reload_profile()
    tuned_runner = _runner()
    assert tuned_runner.length_buckets == (256, 2048)
    assert tuned_runner.batch_bytes == 1 << 20
    # Explicit ctor values still win over the profile.
    pinned = _runner(length_buckets=(64, 512), batch_bytes=2 << 20)
    assert pinned.length_buckets == (64, 512)
    assert pinned.batch_bytes == 2 << 20
    # Parity across lattices: gather scores are geometry-stable.
    rng = np.random.default_rng(5)
    docs = _docs(rng, 30, max_len=3000)
    monkeypatch.delenv(exec_config.PROFILE_ENV)
    exec_config.reload_profile()
    np.testing.assert_array_equal(
        _runner().score(docs), tuned_runner.score(docs)
    )


def test_serve_batcher_resolves_knobs_from_profile(monkeypatch, tmp_path):
    prof = TuningProfile(
        tuned={
            "serve_max_rows": 32, "serve_max_wait_ms": 3.0,
            "serve_queue_rows": 64,
        }
    )
    path = tmp_path / "p.json"
    prof.save(str(path))
    monkeypatch.setenv(exec_config.PROFILE_ENV, str(path))
    exec_config.reload_profile()
    with ContinuousBatcher(_runner()) as b:
        assert b.max_rows == 32
        assert b.max_wait_s == pytest.approx(0.003)
        assert b.max_queue_rows == 64
    monkeypatch.setenv("LANGDETECT_SERVE_MAX_ROWS", "16")
    with ContinuousBatcher(_runner()) as b:
        assert b.max_rows == 16  # env beats profile
    with ContinuousBatcher(_runner(), max_rows=8) as b:
        assert b.max_rows == 8  # explicit beats both


# ------------------------------------------------- compare: fill contract ---
def _capture_events(fill_mean, waste_mean, coalesced, capacity):
    return [
        {"event": "telemetry.span", "ts": 1.0, "path": "serve/dispatch",
         "wall_s": 0.01},
        {
            "event": "telemetry.snapshot", "ts": 2.0,
            "counters": {
                "serve/coalesced_rows": coalesced,
                "serve/dispatch_capacity_rows": capacity,
            },
            "gauges": {},
            "histograms": {
                "serve/fill_ratio": {
                    "count": 10, "mean": fill_mean, "p99": fill_mean,
                },
                "serve/padding_waste": {
                    "count": 10, "mean": waste_mean, "p99": waste_mean,
                },
            },
        },
    ]


def test_compare_regresses_serve_fill_down_and_waste_up():
    base = capture_stats(_capture_events(0.9, 0.1, 900, 1000))
    worse = capture_stats(_capture_events(0.4, 0.6, 400, 1000))
    assert base["tracked"]["fill_ratio[serve/coalesce]"] == pytest.approx(0.9)
    lines, regressions = compare_captures(base, worse, threshold=0.25)
    text = "\n".join(regressions)
    assert "serve/fill_ratio" in text  # fill dropping IS the regression
    assert "serve/padding_waste" in text
    assert "fill_ratio[serve/coalesce]" in text
    # The good direction never regresses: tuned run vs untuned baseline.
    lines, regressions = compare_captures(worse, base, threshold=0.25)
    assert not regressions


def test_compare_tracks_score_wire_fill_from_counters():
    def ev(real, cap):
        return [
            {"event": "telemetry.span", "ts": 1.0, "path": "score",
             "wall_s": 0.01},
            {"event": "telemetry.snapshot", "ts": 2.0,
             "counters": {"score/real_bytes": real,
                          "score/capacity_bytes": cap},
             "gauges": {}, "histograms": {}},
        ]

    base = capture_stats(ev(800, 1000))
    worse = capture_stats(ev(400, 1000))
    assert base["tracked"]["fill_ratio[score/wire]"] == pytest.approx(0.8)
    _, regressions = compare_captures(base, worse, threshold=0.25)
    assert any("fill_ratio[score/wire]" in r for r in regressions)
    _, regressions = compare_captures(worse, base, threshold=0.25)
    assert not regressions


def test_compare_tracks_wire_bytes_per_doc_lower_better():
    """The silent-fallback guard (PERFORMANCE.md §11): on a fixed
    replayed corpus, bytes shipped per scored document rising means the
    device-encode lane fell back to host padding — UP is the regression,
    DOWN (the wire path engaging) never is, and a wire-path capture also
    reports a higher fill_ratio[score/wire] without tripping that
    (higher-better) guard."""

    def ev(wire_bytes, docs, real, cap):
        return [
            {"event": "telemetry.span", "ts": 1.0, "path": "score",
             "wall_s": 0.01},
            {"event": "telemetry.snapshot", "ts": 2.0,
             "counters": {"score/wire_bytes": wire_bytes,
                          "score/wire_docs": docs,
                          "score/real_bytes": real,
                          "score/capacity_bytes": cap},
             "gauges": {}, "histograms": {}},
        ]

    # device-encode baseline: ~48B/doc wire, tight fill
    encode = capture_stats(ev(48_000, 1000, 40_000, 44_000))
    # host-pack fallback on the SAME corpus: ~132B/doc, loose fill
    padded = capture_stats(ev(132_000, 1000, 40_000, 128_000))
    assert encode["tracked"]["score/wire_bytes_per_doc"] == pytest.approx(48.0)
    assert padded["tracked"]["score/wire_bytes_per_doc"] == pytest.approx(132.0)
    _, regressions = compare_captures(encode, padded, threshold=0.25)
    assert any("score/wire_bytes_per_doc" in r for r in regressions)
    # engaging the wire path is never a regression, on either guard
    _, regressions = compare_captures(padded, encode, threshold=0.25)
    assert not regressions


# ------------------------------------------------------- bench smoke gate ---
@pytest.mark.slow
def test_bench_smoke_tune_gates(tmp_path):
    import bench

    result = bench.smoke_tune(str(tmp_path / "tune.jsonl"))
    assert result["ok"], result["errors"]
    assert result["argmax_parity"] == 1.0
    assert (
        result["padding_waste"]["tuned"] < result["padding_waste"]["untuned"]
    )
