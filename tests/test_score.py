"""Scorer parity: device scorer vs the pure-Python reference oracle."""

import numpy as np

from spark_languagedetector_tpu.models.profile import GramProfile
from spark_languagedetector_tpu.ops import score as S
from spark_languagedetector_tpu.ops.encoding import pad_batch, texts_to_bytes
from spark_languagedetector_tpu.ops.vocab import EXACT, HASHED, VocabSpec

from .oracle import detect_oracle, scores_oracle

LANGS = ("de", "en")
GRAM_MAP = {
    b"Die": [1.0, 0.0],
    b"Thi": [0.0, 1.0],
}
TEXTS = [
    "Dies ist ein deutscher Text, das ist ja sehr schön",
    "Dies ist ein andere deutscher Text, und der ist auch sehr schön",
    "This is a text in english, and that is very nice",
    "This is another text in english and that is also nice",
]


def _score_device(profile, texts, block=64):
    weights, sorted_ids = profile.device_arrays()
    docs = texts_to_bytes(texts)
    batch, lengths = pad_batch(docs, pad_to=max(len(d) for d in docs))
    return np.asarray(
        S.score_batch(batch, lengths, weights, sorted_ids, spec=profile.spec, block=block)
    )


def test_handbuilt_model_matches_reference_spec():
    """The reference's model unit test (LanguageDetectorModelSpecs.scala:13-47):
    hand-built 2-gram profile, 4 docs ⇒ 2×de + 2×en."""
    profile = GramProfile.from_gram_map(GRAM_MAP, LANGS, (3,))
    scores = _score_device(profile, TEXTS)
    langs = [LANGS[i] for i in np.argmax(scores, axis=1)]
    assert langs == ["de", "de", "en", "en"]


def test_scores_match_oracle_exactly():
    profile = GramProfile.from_gram_map(GRAM_MAP, LANGS, (3,))
    scores = _score_device(profile, TEXTS)
    for row, text in zip(scores, TEXTS):
        expected = scores_oracle(text, GRAM_MAP, len(LANGS), [3])
        np.testing.assert_allclose(row, expected, rtol=1e-6)


def test_zero_hit_resolves_to_first_language():
    """Q6 parity: all-miss document → all-zero scores → first language."""
    profile = GramProfile.from_gram_map(GRAM_MAP, LANGS, (3,))
    scores = _score_device(profile, ["zzzzzz"])
    assert scores[0].tolist() == [0.0, 0.0]
    assert int(np.argmax(scores[0])) == 0


def test_short_doc_partial_window_matches_oracle():
    """A doc shorter than the gram length scores via its single partial gram."""
    gram_map = {b"ab": [2.0, 0.0], b"abc": [0.0, 3.0]}
    profile = GramProfile.from_gram_map(gram_map, LANGS, (3,))
    # len-2 doc with gramLengths=[3] → partial window b"ab" matches the
    # learned short gram (learnable in fit from a short training doc).
    scores = _score_device(profile, ["ab"])
    np.testing.assert_allclose(scores[0], [2.0, 0.0], rtol=1e-6)


def test_empty_doc_scores_zero():
    profile = GramProfile.from_gram_map(GRAM_MAP, LANGS, (3,))
    scores = _score_device(profile, [""])
    assert scores[0].tolist() == [0.0, 0.0]


def test_multi_gram_lengths_match_oracle():
    rng = np.random.default_rng(1)
    grams = {
        b"a": [0.3, 0.1],
        b"th": [0.0, 0.9],
        b"ch": [0.8, 0.0],
        b"sch": [1.5, 0.0],
        b"ing": [0.0, 1.2],
    }
    profile = GramProfile.from_gram_map(grams, LANGS, (1, 2, 3))
    texts = TEXTS + ["a", "", "th", "schthing"]
    scores = _score_device(profile, texts, block=32)
    for row, text in zip(scores, texts):
        expected = scores_oracle(text, grams, 2, [1, 2, 3])
        np.testing.assert_allclose(row, expected, rtol=1e-5, atol=1e-7)


def test_numpy_scorer_matches_device():
    profile = GramProfile.from_gram_map(GRAM_MAP, LANGS, (3,))
    weights, sorted_ids = profile.device_arrays()
    docs = texts_to_bytes(TEXTS + ["ab", ""])
    host_weights, host_ids = profile.host_arrays()
    host = S.score_batch_numpy(docs, host_weights, host_ids, profile.spec)
    batch, lengths = pad_batch(docs, pad_to=max(len(d) for d in docs))
    dev = np.asarray(
        S.score_batch(batch, lengths, weights, sorted_ids, spec=profile.spec)
    )
    np.testing.assert_allclose(host, dev, rtol=1e-6, atol=1e-7)


def test_hashed_mode_scores_accumulate_bucket_weights():
    spec = VocabSpec(HASHED, (2,), hash_bits=10)
    V = spec.id_space_size
    weights = np.zeros((V, 2), dtype=np.float32)
    b_ab = spec.gram_to_id(b"ab")
    weights[b_ab] = [1.5, 0.0]
    docs = texts_to_bytes(["abab", "zz"])
    batch, lengths = pad_batch(docs, pad_to=8)
    scores = np.asarray(
        S.score_batch(batch, lengths, weights, None, spec=spec, block=16)
    )
    # "abab" has windows ab, ba, ab → two hits of b"ab"'s bucket (plus any
    # collision of "ba"/"zz" into other buckets, which are zero rows here).
    expected_hits = 2 * 1.5
    b_ba, b_zz = spec.gram_to_id(b"ba"), spec.gram_to_id(b"zz")
    assert {b_ba, b_zz}.isdisjoint({b_ab}), "test assumes no collision"
    np.testing.assert_allclose(scores[0], [expected_hits, 0.0], rtol=1e-6)
    np.testing.assert_allclose(scores[1], [0.0, 0.0])


def test_argmax_first_max_wins():
    import jax.numpy as jnp

    scores = jnp.asarray([[1.0, 1.0, 0.5], [0.0, 2.0, 2.0]])
    assert S.argmax_language(scores).tolist() == [0, 1]


# --- device strategies: dense gather vs LUT gather vs one-hot MXU ------------


def test_lut_strategy_matches_dense():
    """Forcing the compact-LUT path (tiny dense budget) must be bit-identical
    to dense direct indexing."""
    profile = GramProfile.from_gram_map(GRAM_MAP, LANGS, (3,))
    docs = texts_to_bytes(TEXTS + ["ab", "", "zzzz"])
    batch, lengths = pad_batch(docs, pad_to=max(len(d) for d in docs))

    w_dense, lut_none = profile.device_arrays(dense_budget_bytes=1 << 40)
    assert lut_none is None
    w_compact, lut = profile.device_arrays(dense_budget_bytes=0)
    assert lut is not None and lut.shape[0] == profile.spec.id_space_size

    dense = np.asarray(
        S.score_batch(batch, lengths, w_dense, None, spec=profile.spec)
    )
    compact = np.asarray(
        S.score_batch(batch, lengths, w_compact, lut, spec=profile.spec)
    )
    np.testing.assert_array_equal(dense, compact)


def test_onehot_strategy_matches_oracle():
    grams = {
        b"a": [0.3, 0.1],
        b"b": [0.05, 0.4],
        b"th": [0.0, 0.9],
        b"ch": [0.8, 0.0],
        b"ab": [1.1, 0.2],
    }
    profile = GramProfile.from_gram_map(grams, LANGS, (1, 2))
    texts = TEXTS + ["a", "", "th", "abab", "x"]
    docs = texts_to_bytes(texts)
    batch, lengths = pad_batch(docs, pad_to=max(len(d) for d in docs))
    weights, lut = profile.device_arrays()
    assert lut is None and S.onehot_supported(profile.spec, weights.shape[0])
    scores = np.asarray(
        S.score_batch_onehot(batch, lengths, weights, spec=profile.spec, block=32)
    )
    for row, text in zip(scores, texts):
        expected = scores_oracle(text, grams, 2, [1, 2])
        np.testing.assert_allclose(row, expected, rtol=1e-5, atol=1e-6)


def test_onehot_matches_gather_bigram_only():
    """gramLengths=(2,): partial windows of len-1 docs land in the unigram
    id space — both strategies must agree exactly."""
    grams = {b"ab": [1.0, 0.0], b"a": [0.0, 0.7], b"zz": [0.0, 2.0]}
    profile = GramProfile.from_gram_map(grams, LANGS, (2,))
    docs = texts_to_bytes(["abab", "a", "", "zzz", "ba"])
    batch, lengths = pad_batch(docs, pad_to=8)
    weights, lut = profile.device_arrays()
    gather = np.asarray(
        S.score_batch(batch, lengths, weights, lut, spec=profile.spec, block=16)
    )
    onehot = np.asarray(
        S.score_batch_onehot(batch, lengths, weights, spec=profile.spec, block=16)
    )
    np.testing.assert_allclose(onehot, gather, rtol=1e-6, atol=1e-7)


def test_onehot_respects_window_limit():
    import jax.numpy as jnp

    grams = {b"ab": [1.0, 0.0]}
    profile = GramProfile.from_gram_map(grams, LANGS, (2,))
    docs = texts_to_bytes(["ababab"])  # windows ab,ba,ab,ba,ab
    batch, lengths = pad_batch(docs, pad_to=8)
    weights, _ = profile.device_arrays()
    limited = np.asarray(
        S.score_batch_onehot(
            batch, lengths, weights, spec=profile.spec,
            window_limit=jnp.asarray([3], jnp.int32),
        )
    )
    # starts 0..2 only: windows ab, ba, ab → 2 hits
    np.testing.assert_allclose(limited[0], [2.0, 0.0])


def test_runner_auto_selects_onehot():
    from spark_languagedetector_tpu.api.runner import BatchRunner

    profile = GramProfile.from_gram_map({b"ab": [1.0, 0.0]}, LANGS, (1, 2))
    weights, lut = profile.device_arrays()
    runner = BatchRunner(weights=weights, lut=lut, spec=profile.spec)
    assert runner.strategy == "onehot"
    scores = runner.score(texts_to_bytes(["abab", ""]))
    np.testing.assert_allclose(scores[0], [2.0, 0.0])

    profile3 = GramProfile.from_gram_map(GRAM_MAP, LANGS, (3,))
    w3, lut3 = profile3.device_arrays(dense_budget_bytes=0)
    runner3 = BatchRunner(weights=w3, lut=lut3, spec=profile3.spec)
    assert runner3.strategy == "gather"
