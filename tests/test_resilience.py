"""Micro-batch retry, multi-host helpers, and profiler hooks.

VERDICT r1 #8 (retry in BatchRunner.score), #9 (parallel/distributed.py
coverage), and the missing jax.profiler trace hook (SURVEY.md §5.1).
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from spark_languagedetector_tpu.api.runner import BatchRunner
from spark_languagedetector_tpu.ops.vocab import EXACT, VocabSpec
from spark_languagedetector_tpu.parallel import distributed as D


def _runner(**kw):
    spec = VocabSpec(EXACT, (1, 2))
    rng = np.random.default_rng(3)
    weights = rng.normal(size=(spec.id_space_size, 3)).astype(np.float32)
    return BatchRunner(
        weights=jnp.asarray(weights), lut=None, spec=spec,
        batch_size=8, strategy="gather", **kw,
    ), weights


def _docs(n=20):
    rng = np.random.default_rng(5)
    return [bytes(rng.integers(0, 256, int(rng.integers(1, 200)), dtype=np.uint8))
            for _ in range(n)]


def test_dispatch_retry_recovers_transient_failure(monkeypatch):
    runner, _ = _runner()
    docs = _docs()
    want = runner.score(docs)

    calls = {"n": 0}
    orig = BatchRunner._dispatch_device

    def flaky(self, *a, **kw):
        calls["n"] += 1
        if calls["n"] == 2:  # second micro-batch fails once
            raise RuntimeError("transient tunnel hiccup")
        return orig(self, *a, **kw)

    monkeypatch.setattr(BatchRunner, "_dispatch_device", flaky)
    got = runner.score(docs)
    np.testing.assert_allclose(got, want, rtol=1e-6)
    assert runner.metrics.snapshot()["counters"].get("retries") == 1


def test_fetch_retry_replays_batch(monkeypatch):
    runner, _ = _runner()
    docs = _docs()
    want = runner.score(docs)

    class Poisoned:
        """Stands in for a device array whose execution failed: the fetch
        raises; copy_to_host_async is absent (AttributeError path)."""

        def __array__(self, *a, **kw):
            raise RuntimeError("execution failed on device")

    orig = BatchRunner._dispatch_device
    state = {"calls": 0, "poisoned": False}

    def flaky(self, *a, **kw):
        state["calls"] += 1
        if state["calls"] == 1 and not state["poisoned"]:
            state["poisoned"] = True
            return Poisoned()
        return orig(self, *a, **kw)

    monkeypatch.setattr(BatchRunner, "_dispatch_device", flaky)
    got = runner.score(docs)
    np.testing.assert_allclose(got, want, rtol=1e-6)
    assert runner.metrics.snapshot()["counters"].get("retries") == 1


def test_retry_metrics_absent_on_clean_run():
    runner, _ = _runner()
    runner.score(_docs(5))
    assert "retries" not in runner.metrics.snapshot()["counters"]


# ------------------------------------------------- distributed helpers ------


def test_initialize_single_process_is_noop(monkeypatch):
    for var in (
        "LANGDETECT_TPU_COORDINATOR",
        "LANGDETECT_TPU_NUM_PROCESSES",
        "LANGDETECT_TPU_PROCESS_ID",
    ):
        monkeypatch.delenv(var, raising=False)
    D.initialize()  # must not raise, must not call jax.distributed


def test_initialize_env_plumbing(monkeypatch):
    seen = {}

    def fake_init(coordinator_address, num_processes, process_id):
        seen.update(
            addr=coordinator_address, n=num_processes, pid=process_id
        )

    import jax

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    monkeypatch.setenv("LANGDETECT_TPU_COORDINATOR", "10.0.0.1:8476")
    monkeypatch.setenv("LANGDETECT_TPU_NUM_PROCESSES", "4")
    monkeypatch.setenv("LANGDETECT_TPU_PROCESS_ID", "2")
    D.initialize()
    assert seen == {"addr": "10.0.0.1:8476", "n": 4, "pid": 2}


def test_host_shard_partitions_whole_range():
    # Single-process: the shard is everything.
    s = D.host_shard(11)
    assert (s.start, s.stop) == (0, 11)


def test_host_shard_arithmetic(monkeypatch):
    import jax

    monkeypatch.setattr(jax, "process_count", lambda: 4)
    covered = []
    for p in range(4):
        monkeypatch.setattr(jax, "process_index", lambda p=p: p)
        s = D.host_shard(10)
        covered.extend(range(s.start, s.stop))
    assert covered == list(range(10))  # disjoint cover, no overlap


def test_global_batch_single_process():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from spark_languagedetector_tpu.parallel.mesh import build_mesh

    devices = jax.devices("cpu")
    mesh = build_mesh(data=len(devices), vocab=1, devices=devices)
    local = np.arange(len(devices) * 3, dtype=np.float32).reshape(-1, 3)
    arr = D.global_batch(local, NamedSharding(mesh, PartitionSpec("data")))
    np.testing.assert_array_equal(np.asarray(arr), local)


# ---------------------------------------------------- profiler hook ---------


def test_trace_noop_without_dir(monkeypatch):
    from spark_languagedetector_tpu.utils.profiling import trace

    monkeypatch.delenv("LANGDETECT_TRACE_DIR", raising=False)
    with trace():
        pass  # no jax.profiler involvement


def test_trace_writes_profile(tmp_path):
    from spark_languagedetector_tpu.utils.profiling import trace

    runner, _ = _runner()
    with trace(str(tmp_path)):
        runner.score(_docs(4))
    produced = [str(p) for p in tmp_path.rglob("*") if p.is_file()]
    assert produced, "trace produced no profile artifacts"


def test_score_traces_via_env(tmp_path, monkeypatch):
    monkeypatch.setenv("LANGDETECT_TRACE_DIR", str(tmp_path))
    runner, _ = _runner()
    runner.score(_docs(4))
    assert any(tmp_path.rglob("*")), "env-driven trace produced nothing"


def _spawn_distributed_workers(extra_args=(), timeout=180):
    """Launch the two-process worker pair; returns [(returncode, output)].

    Raises RuntimeError when a worker cannot even be spawned (missing
    interpreter, fork limits) — callers treat that as a capability gap.
    """
    import socket
    import subprocess
    import sys
    from pathlib import Path

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"
    worker = Path(__file__).with_name("_distributed_worker.py")
    env = {
        k: v for k, v in os.environ.items()
        if not k.startswith("LANGDETECT_TPU_")
    }
    # `python path/to/script.py` puts the script's dir on sys.path, not the
    # cwd — the package root must be appended (never clobber PYTHONPATH:
    # the TPU tunnel's site hooks ride on it in this container).
    repo_root = str(Path(__file__).resolve().parents[1])
    env["PYTHONPATH"] = (
        repo_root + os.pathsep + env["PYTHONPATH"]
        if env.get("PYTHONPATH")
        else repo_root
    )
    try:
        procs = [
            subprocess.Popen(
                [sys.executable, str(worker), coordinator, "2", str(pid),
                 *extra_args],
                cwd=repo_root,
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
            for pid in (0, 1)
        ]
    except OSError as e:
        raise RuntimeError(f"cannot spawn worker process: {e}") from e
    results = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            results.append((p.returncode, out))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return results


def _distributed_capability_gap() -> str | None:
    """Probe whether this host can actually run two-process jax.distributed
    CPU collectives: spawn the worker pair in ``--probe`` mode (bring-up +
    one jit reduction — pure jax/jaxlib surface). Returns a human-readable
    reason when it cannot (e.g. this jaxlib's "Multiprocess computations
    aren't implemented on the CPU backend"), None when the substrate works.
    A probe failure is a CAPABILITY gap by construction — the probe is
    built exclusively from jax public APIs (it imports nothing from this
    framework), so skipping on it can never hide a regression in the code
    the full test exercises."""
    try:
        results = _spawn_distributed_workers(("--probe",), timeout=120)
    except Exception as e:  # spawn failures, communicate timeouts
        return f"{type(e).__name__}: {e}"
    for pid, (rc, out) in enumerate(results):
        if rc != 0 or f"DIST_PROBE_OK pid={pid}" not in out:
            tail = [l for l in out.strip().splitlines() if l.strip()]
            return (
                f"worker {pid} probe failed (rc={rc}): "
                + (tail[-1] if tail else "no output")
            )
    return None


def test_two_process_distributed_initialize_and_collectives():
    """Real multi-process bring-up (VERDICT r2 item 7): two OS processes,
    localhost coordinator, 2 CPU devices each -> one 4-device global mesh;
    host_shard + global_batch assemble a globally-sharded array and a jit
    reduction crosses process boundaries. Green == the multi-host leg of
    parallel.distributed actually executes, not just plumbs env vars.
    Hosts whose jaxlib/substrate cannot run two-process CPU collectives at
    all skip with the probe's reason instead of failing."""
    gap = _distributed_capability_gap()
    if gap is not None:
        pytest.skip(f"two-process jax.distributed unavailable here: {gap}")
    results = _spawn_distributed_workers()
    for pid, (rc, out) in enumerate(results):
        assert rc == 0, f"worker {pid} failed:\n{out}"
        assert f"DIST_OK pid={pid}" in out, out
