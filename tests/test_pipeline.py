"""Pipeline composition: preprocessors + estimator as one fit/transform."""

import pytest

from spark_languagedetector_tpu import (
    LanguageDetector,
    LowerCasePreprocessor,
    Pipeline,
    PipelineModel,
    SpecialCharPreprocessor,
    Table,
)

LANGS = ["de", "en"]
ROWS = {
    "lang": ["de"] * 4 + ["en"] * 4,
    "fulltext": [
        "Dies ist ein (deutscher) Text",
        "Das ist ja SEHR schön",
        "Dieser Text ist auch deutsch",
        "Und noch ein deutscher Satz",
        "This is an {english} text",
        "That is VERY nice indeed",
        "This text is also english",
        "And one more english sentence",
    ],
}


def _pipeline():
    lower = LowerCasePreprocessor()
    lower.set_input_col("fulltext")
    clean = SpecialCharPreprocessor()
    clean.set_input_col("fulltext")
    det = LanguageDetector(LANGS, [2, 3], 50)
    return Pipeline([lower, clean, det])


def test_fit_transform_chain():
    model = _pipeline().fit(Table(ROWS))
    assert isinstance(model, PipelineModel)
    # The LowerCasePreprocessor derives its locale from the label column
    # (reference quirk Q8 — usable only on labeled data), so the inference
    # table carries labels too; the detector writes a distinct column.
    model.stages[-1].set("outputCol", "detected")
    out = model.transform(
        Table({
            "lang": ["de", "en"],
            "fulltext": ["Schöner (Text)", "nice {text}"],
        })
    )
    assert list(out.column("detected")) == ["de", "en"]


def test_preprocessors_applied_before_fit():
    """The detector must see lowercased, symbol-stripped text."""
    model = _pipeline().fit(Table(ROWS))
    det_model = model.stages[-1]
    grams = set(det_model.gram_probabilities)
    # Uppercase bytes cannot survive the LowerCasePreprocessor.
    assert not any(any(0x41 <= b <= 0x5A for b in g) for g in grams)
    # Stripped symbols cannot appear in learned grams.
    assert not any(b"(" in g or b"{" in g for g in grams)


def test_transformers_only_pipeline():
    p = Pipeline([SpecialCharPreprocessor().set_input_col("fulltext")])
    model = p.fit(Table({"fulltext": ["a (b) c"]}))
    out = model.transform(Table({"fulltext": ["x (y) z"]}))
    assert list(out.column("fulltext")) == ["x y z"]


def test_invalid_stage_rejected():
    with pytest.raises(TypeError):
        Pipeline([object()])


def test_stage_after_estimator_not_applied_during_fit():
    """Spark parity: stages after the last estimator are collected into the
    PipelineModel without running on the training table. With the detector's
    default outputCol 'lang' equal to the label column, applying the fitted
    model during fit would crash with 'column lang already exists'."""
    lower = LowerCasePreprocessor()
    lower.set_input_col("fulltext")
    det = LanguageDetector(LANGS, [2, 3], 50)
    post = SpecialCharPreprocessor()
    post.set_input_col("fulltext")
    model = Pipeline([det, post]).fit(Table(ROWS))  # must not raise
    assert len(model.stages) == 2
    model.stages[0].set("outputCol", "detected")
    out = model.transform(Table({"fulltext": ["Dies ist ein deutscher Text"]}))
    assert list(out.column("detected")) == ["de"]


def test_pipeline_model_persistence_roundtrip(tmp_path):
    """Fitted-pipeline persistence: write().save + load round-trips the whole
    preprocessor + model chain — stage order, stage params (incl. explicit
    sets), and the detector model's profile — and the loaded pipeline
    produces identical transforms. The reference gets this for free from
    Spark ML's pipeline persistence (the same MLWritable machinery as its
    model, LanguageDetectorModel.scala:22-25)."""
    model = _pipeline().fit(Table(ROWS))
    model.stages[-1].set("outputCol", "detected")
    path = str(tmp_path / "pipe")
    model.write().save(path)

    # fail-if-exists contract without overwrite()
    with pytest.raises(FileExistsError):
        model.write().save(path)
    model.write().overwrite().save(path)  # and overwrite succeeds

    loaded = PipelineModel.load(path)
    assert loaded.uid == model.uid
    assert [type(s).__name__ for s in loaded.stages] == [
        "LowerCasePreprocessor", "SpecialCharPreprocessor",
        "LanguageDetectorModel",
    ]
    assert [s.uid for s in loaded.stages] == [s.uid for s in model.stages]
    # Explicitly-set params survive (outputCol on the detector stage, the
    # in-place column choice on the preprocessors).
    assert loaded.stages[-1].get("outputCol") == "detected"
    assert loaded.stages[0].get_output_col() == "fulltext"
    # Identical profile and identical end-to-end transform.
    assert (
        loaded.stages[-1].gram_probabilities.keys()
        == model.stages[-1].gram_probabilities.keys()
    )
    table = Table(
        {"lang": ["de", "en"],
         "fulltext": ["Dies ist (ein) deutscher Text", "This is {very} nice"]}
    )
    assert list(loaded.transform(table).column("detected")) == list(
        model.transform(table).column("detected")
    )


def test_unfitted_pipeline_persistence_roundtrip(tmp_path):
    """Spark Pipeline.write parity: an UNFITTED pipeline (preprocessors +
    estimator) round-trips — the estimator rebuilds from its params
    (constructor args are Params here) — and fitting the loaded pipeline
    gives the same transforms as fitting the original."""
    pipe = _pipeline()
    pipe.stages[-1].set("weightMode", "counts")  # explicit set must survive
    path = str(tmp_path / "unfitted")
    pipe.write().save(path)
    loaded = Pipeline.load(path)
    assert loaded.uid == pipe.uid
    assert [s.uid for s in loaded.stages] == [s.uid for s in pipe.stages]
    det = loaded.stages[-1]
    assert det.get("supportedLanguages") == LANGS
    assert det.get("gramLengths") == [2, 3]
    assert det.get("languageProfileSize") == 50
    assert det.get("weightMode") == "counts"

    m1, m2 = pipe.fit(Table(ROWS)), loaded.fit(Table(ROWS))
    for m in (m1, m2):
        m.stages[-1].set("outputCol", "detected")
    probe = Table({"lang": ["de", "en"],
                   "fulltext": ["Noch ein deutscher Text", "One more text"]})
    assert list(m1.transform(probe).column("detected")) == list(
        m2.transform(probe).column("detected")
    )


def test_pipeline_model_load_rejects_foreign_class(tmp_path):
    """Stage classes resolve by import at load time; anything outside this
    package is refused (the DefaultParamsReader class-check analog)."""
    model = _pipeline().fit(Table(ROWS))
    path = str(tmp_path / "pipe")
    model.save(path)
    import json as _json
    from pathlib import Path as _Path

    meta_file = _Path(path) / "metadata" / "part-00000"
    meta = _json.loads(meta_file.read_text())
    meta["stages"][0]["class"] = "os.path.join"
    meta_file.write_text(_json.dumps(meta) + "\n")
    with pytest.raises(ValueError, match="refusing to import"):
        PipelineModel.load(path)


def test_transformer_before_estimator_only_applies_to_prefix():
    """A transformer before the last estimator transforms the training data;
    the estimator itself is last and its model must not run during fit."""
    lower = LowerCasePreprocessor()
    lower.set_input_col("fulltext")
    det = LanguageDetector(LANGS, [2, 3], 50)
    model = Pipeline([lower, det]).fit(Table(ROWS))
    grams = set(model.stages[-1].gram_probabilities)
    assert not any(any(0x41 <= b <= 0x5A for b in g) for g in grams)


def test_save_replace_failure_preserves_old_and_new(tmp_path, monkeypatch):
    """A failed tmp→root rename must destroy NEITHER save: the old tree is
    renamed aside before the swap and restored on failure, and the freshly
    built tmp tree stays on disk (it is the only copy of the new data)."""
    import os as _os

    path = str(tmp_path / "pipe")
    first = _pipeline().fit(Table(ROWS))
    first.save(path)
    first_uid = first.uid

    second = _pipeline().fit(Table(ROWS))
    real_replace = _os.replace

    def failing_replace(src, dst):
        # Only the final tmp→root swap fails; the old-root-aside rename
        # (root → .old.) and any stage-level renames still work.
        if ".tmp." in str(src) and str(dst) == path:
            raise OSError("injected replace failure")
        return real_replace(src, dst)

    monkeypatch.setattr(_os, "replace", failing_replace)
    with pytest.raises(OSError, match="injected replace failure"):
        second.save(path)
    monkeypatch.setattr(_os, "replace", real_replace)

    # Old save restored and loadable.
    restored = PipelineModel.load(path)
    assert restored.uid == first_uid
    # New save's tmp tree survived for recovery.
    tmp_dirs = [
        p for p in (tmp_path).iterdir() if ".tmp." in p.name
    ]
    assert tmp_dirs, "tmp tree was deleted along with the failed swap"
    loaded_new = PipelineModel.load(str(tmp_dirs[0]))
    assert loaded_new.uid == second.uid


def test_save_midbuild_failure_keeps_old_and_cleans_tmp(tmp_path):
    """A failure while building the temp tree (before any swap) leaves the
    existing save untouched and removes the partial tmp tree."""
    path = str(tmp_path / "pipe")
    first = _pipeline().fit(Table(ROWS))
    first.save(path)

    class ExplodingStage:
        uid = "Exploding_stage"

        def transform(self, dataset):
            return dataset

        # has neither write() nor param_metadata → TypeError mid-build

    with pytest.raises(TypeError, match="cannot persist"):
        PipelineModel([ExplodingStage()]).save(path)
    assert PipelineModel.load(path).uid == first.uid
    assert not [p for p in tmp_path.iterdir() if ".tmp." in p.name]


@pytest.mark.parametrize(
    "bad", ["", "..", ".", "a/b", "a\\b", "a b", "..\\up", "é"]
)
def test_stage_dir_name_validation_rejects(tmp_path, bad):
    """Stage dir names from metadata are allowlisted to [A-Za-z0-9._-]+
    minus '.'/'..' — empty strings and backslashes are rejected too."""
    model = _pipeline().fit(Table(ROWS))
    path = str(tmp_path / "pipe")
    model.save(path)
    import json as _json
    from pathlib import Path as _Path

    meta_file = _Path(path) / "metadata" / "part-00000"
    meta = _json.loads(meta_file.read_text())
    meta["stages"][0]["dir"] = bad
    meta_file.write_text(_json.dumps(meta) + "\n")
    with pytest.raises(ValueError, match="refusing stage directory"):
        PipelineModel.load(path)


def test_stage_dir_name_validation_accepts_normal_names(tmp_path):
    """Round-trip still works: real stage dir names (NN_Prefix_hex) pass."""
    model = _pipeline().fit(Table(ROWS))
    path = str(tmp_path / "pipe")
    model.save(path)
    loaded = PipelineModel.load(path)
    assert [s.uid for s in loaded.stages] == [s.uid for s in model.stages]
