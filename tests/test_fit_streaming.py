"""Streaming host fit: chunked reduction equals one-shot reduction.

VERDICT r1 #7: the host fit must stream per-batch uniques with incremental
merging (bounded RSS) instead of accumulating every window id for one global
np.unique. These tests pin the chunked reduction to the semantics of a
single-batch pass at several batch sizes, including merge-flush boundaries.
"""

import numpy as np

from spark_languagedetector_tpu.ops import fit as F
from spark_languagedetector_tpu.ops.vocab import EXACT, HASHED, VocabSpec


def _corpus(n_docs, seed, short_docs=True):
    rng = np.random.default_rng(seed)
    docs = []
    for i in range(n_docs):
        ln = int(rng.integers(0, 40)) if (short_docs and i % 7 == 0) else int(
            rng.integers(40, 400)
        )
        docs.append(bytes(rng.integers(97, 110, ln, dtype=np.uint8)))
    langs = rng.integers(0, 3, n_docs)
    return docs, langs


def _as_tuple(gc: F.GramCounts):
    return (
        gc.ids.tolist(),
        gc.langs.tolist(),
        gc.counts.tolist(),
    )


def test_chunked_equals_single_pass_exact():
    docs, langs = _corpus(300, seed=3)
    spec = VocabSpec(EXACT, (1, 2, 3))
    whole = F.extract_gram_counts(docs, langs, 3, spec, batch_size=10_000)
    for bs in (1, 7, 64, 299):
        chunked = F.extract_gram_counts(docs, langs, 3, spec, batch_size=bs)
        assert _as_tuple(chunked) == _as_tuple(whole)


def test_chunked_equals_single_pass_hashed():
    docs, langs = _corpus(200, seed=5)
    spec = VocabSpec(HASHED, (2, 4), hash_bits=14)
    whole = F.extract_gram_counts(docs, langs, 3, spec, batch_size=10_000)
    chunked = F.extract_gram_counts(docs, langs, 3, spec, batch_size=13)
    assert _as_tuple(chunked) == _as_tuple(whole)


def test_merge_flush_boundary(monkeypatch):
    """Force a merge after nearly every batch: results must not depend on
    when the pending set flushes into the accumulator."""
    docs, langs = _corpus(120, seed=7)
    spec = VocabSpec(EXACT, (2,))
    whole = F.extract_gram_counts(docs, langs, 3, spec, batch_size=10_000)
    monkeypatch.setattr(F, "_PENDING_MERGE_LIMIT", 10)
    chunked = F.extract_gram_counts(docs, langs, 3, spec, batch_size=2)
    assert _as_tuple(chunked) == _as_tuple(whole)


def test_large_synthetic_corpus_smoke():
    """A corpus big enough that un-reduced accumulation would be ~10x the
    reduced form still fits comfortably and matches fit_profile_numpy run
    in two halves merged by hand at the counting stage."""
    docs, langs = _corpus(3000, seed=9, short_docs=False)
    spec = VocabSpec(EXACT, (1, 2))
    gc = F.extract_gram_counts(docs, langs, 3, spec, batch_size=256)
    # distinct (gram, lang) pairs bounded by id space × langs
    assert len(gc.ids) <= spec.id_space_size * 3
    total_windows = sum(len(d) + max(len(d) - 1, 0) for d in docs)
    assert gc.counts.sum() == total_windows
