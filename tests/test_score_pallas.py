"""Fused pallas scorer parity (interpret mode on the CPU test mesh).

The kernel itself targets TPU; interpret mode executes the same program
semantics on any backend, so these tests pin the kernel's numerics to the
XLA strategies and the numpy oracle on tiny shapes.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_languagedetector_tpu.ops import score as S
from spark_languagedetector_tpu.ops import score_pallas as SP
from spark_languagedetector_tpu.ops.encoding import pad_batch
from spark_languagedetector_tpu.ops.vocab import EXACT, HASHED, VocabSpec


def _random_docs(rng, n_docs, max_len):
    docs = []
    for _ in range(n_docs):
        ln = int(rng.integers(0, max_len))
        docs.append(bytes(rng.integers(0, 256, ln, dtype=np.uint8)))
    return docs


def _pallas_scores(docs, weights, spec, pad_to, window_limit=None):
    batch, lengths = pad_batch(docs, pad_to=pad_to)
    w1, w2 = SP.weight_views(weights, spec)
    lim = None if window_limit is None else jnp.asarray(window_limit)
    return np.asarray(
        SP.score_batch_pallas(
            jnp.asarray(batch),
            jnp.asarray(lengths),
            w1,
            w2,
            lim,
            spec=spec,
            block=128,
            interpret=True,
        )
    )


@pytest.mark.parametrize("gram_lengths", [(1,), (2,), (1, 2)])
def test_matches_numpy_oracle(gram_lengths):
    spec = VocabSpec(EXACT, gram_lengths)
    rng = np.random.default_rng(7)
    weights = rng.normal(size=(spec.id_space_size, 3)).astype(np.float32)
    # Lengths 0 and 1 exercise the empty-doc and partial-window rules.
    docs = [b"", b"a", b"ab", b"hello world"] + _random_docs(rng, 12, 300)
    got = _pallas_scores(docs, weights, spec, pad_to=384)
    want = S.score_batch_numpy(docs, weights, None, spec)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)


def test_matches_xla_onehot_strategy():
    spec = VocabSpec(EXACT, (2,))
    rng = np.random.default_rng(11)
    weights = rng.normal(size=(spec.id_space_size, 5)).astype(np.float32)
    docs = _random_docs(rng, 16, 250) + [b"", b"x"]
    batch, lengths = pad_batch(docs, pad_to=256)
    xla = np.asarray(
        S.score_batch_onehot(
            jnp.asarray(batch), jnp.asarray(lengths), jnp.asarray(weights),
            spec=spec, block=128,
        )
    )
    got = _pallas_scores(docs, weights, spec, pad_to=256)
    np.testing.assert_allclose(got, xla, rtol=1e-4, atol=1e-3)


def test_window_limit_matches_gather_strategy():
    """Chunked long-doc scoring: only owned window starts count."""
    spec = VocabSpec(EXACT, (1, 2))
    rng = np.random.default_rng(13)
    weights = rng.normal(size=(spec.id_space_size, 3)).astype(np.float32)
    docs = _random_docs(rng, 8, 250)
    docs = [d if len(d) >= 2 else b"ab" for d in docs]
    batch, lengths = pad_batch(docs, pad_to=256)
    limit = np.asarray([100, 256, 3, 17, 250, 1, 56, 200], dtype=np.int32)
    gather = np.asarray(
        S.score_batch(
            jnp.asarray(batch), jnp.asarray(lengths), jnp.asarray(weights),
            None, spec=spec, block=128, window_limit=jnp.asarray(limit),
        )
    )
    got = _pallas_scores(docs, weights, spec, pad_to=256, window_limit=limit)
    np.testing.assert_allclose(got, gather, rtol=1e-4, atol=1e-3)


def test_row_padding_to_doc_block():
    """B not a multiple of 8: rows are padded and the pad rows dropped."""
    spec = VocabSpec(EXACT, (2,))
    rng = np.random.default_rng(17)
    weights = rng.normal(size=(spec.id_space_size, 2)).astype(np.float32)
    docs = _random_docs(rng, 3, 120)
    got = _pallas_scores(docs, weights, spec, pad_to=128)
    want = S.score_batch_numpy(docs, weights, None, spec)
    assert got.shape == (3, 2)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)


def test_supported_gate():
    assert SP.pallas_supported(VocabSpec(EXACT, (1, 2)), 256 + 65536, 3)
    assert not SP.pallas_supported(VocabSpec(EXACT, (1, 2, 3)), 10, 3)
    assert not SP.pallas_supported(VocabSpec(HASHED, (1, 2)), 1 << 20, 3)
    # compact (non-dense) table disqualifies; large L does NOT (hist path)
    assert not SP.pallas_supported(VocabSpec(EXACT, (2,)), 100, 3)
    assert SP.pallas_supported(
        VocabSpec(EXACT, (2,)), 256 + 65536, SP.MAX_PALLAS_LANGS + 1
    )


@pytest.mark.parametrize("gram_lengths", [(1,), (2,), (1, 2)])
def test_hist_path_many_languages_matches_oracle(gram_lengths):
    """L > MAX_PALLAS_LANGS routes through the histogram kernel + matmul."""
    spec = VocabSpec(EXACT, gram_lengths)
    rng = np.random.default_rng(23)
    L = SP.MAX_PALLAS_LANGS + 4
    weights = rng.normal(size=(spec.id_space_size, L)).astype(np.float32)
    w1, w2 = SP.weight_views(weights, spec)
    assert w2.ndim == 2  # the non-fused view
    docs = [b"", b"a", b"ab", b"hello world"] + _random_docs(rng, 12, 300)
    got = _pallas_scores(docs, weights, spec, pad_to=384)
    want = S.score_batch_numpy(docs, weights, None, spec)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)


def test_hist_path_window_limit_matches_gather():
    spec = VocabSpec(EXACT, (1, 2))
    rng = np.random.default_rng(29)
    L = SP.MAX_PALLAS_LANGS + 4
    weights = rng.normal(size=(spec.id_space_size, L)).astype(np.float32)
    docs = _random_docs(rng, 8, 250)
    docs = [d if len(d) >= 2 else b"ab" for d in docs]
    batch, lengths = pad_batch(docs, pad_to=256)
    limit = np.asarray([100, 256, 3, 17, 250, 1, 56, 200], dtype=np.int32)
    gather = np.asarray(
        S.score_batch(
            jnp.asarray(batch), jnp.asarray(lengths), jnp.asarray(weights),
            None, spec=spec, block=128, window_limit=jnp.asarray(limit),
        )
    )
    got = _pallas_scores(docs, weights, spec, pad_to=256, window_limit=limit)
    np.testing.assert_allclose(got, gather, rtol=1e-4, atol=1e-3)


def test_runner_hybrid_strategy_matches_gather():
    """hybrid = pallas histogram for n<=2 + gather for n>=3 (exact vocab)."""
    from spark_languagedetector_tpu.api.runner import BatchRunner

    spec = VocabSpec(EXACT, (1, 2, 3))
    rng = np.random.default_rng(31)
    # Compact profile + LUT (the realistic form for exact n=3 id spaces).
    G = 4000
    ids = np.sort(rng.choice(spec.id_space_size, G, replace=False))
    weights = np.zeros((G + 1, 4), np.float32)
    weights[:G] = rng.normal(size=(G, 4)).astype(np.float32)
    lut = np.full(spec.id_space_size, G, np.int32)
    lut[ids] = np.arange(G, dtype=np.int32)
    docs = _random_docs(rng, 10, 200) + [b"", b"q", b"ab"]
    hybrid = BatchRunner(
        weights=jnp.asarray(weights), lut=jnp.asarray(lut), spec=spec,
        batch_size=8, strategy="hybrid",
    )
    gather = BatchRunner(
        weights=jnp.asarray(weights), lut=jnp.asarray(lut), spec=spec,
        batch_size=8, strategy="gather",
    )
    np.testing.assert_allclose(
        hybrid.score(docs), gather.score(docs), rtol=1e-4, atol=1e-3
    )


def test_runner_hybrid_hashed_exact12_matches_gather():
    """exact12 hashed vocab: n<=2 buckets are polynomial ids, so hybrid's
    pallas sub-table slice is exact for them."""
    from spark_languagedetector_tpu.api.runner import BatchRunner

    spec = VocabSpec(HASHED, (1, 2, 3, 4), hash_bits=17)
    assert spec.hash_scheme == "exact12"
    rng = np.random.default_rng(41)
    V_ = spec.id_space_size
    G = 3000
    ids = np.sort(rng.choice(V_, G, replace=False))
    weights = np.zeros((G + 1, 5), np.float32)
    weights[:G] = rng.normal(size=(G, 5)).astype(np.float32)
    lut = np.full(V_, G, np.int32)
    lut[ids] = np.arange(G, dtype=np.int32)
    docs = _random_docs(rng, 10, 200) + [b"", b"q", b"ab"]
    hybrid = BatchRunner(
        weights=jnp.asarray(weights), lut=jnp.asarray(lut), spec=spec,
        batch_size=8, strategy="hybrid",
    )
    gather = BatchRunner(
        weights=jnp.asarray(weights), lut=jnp.asarray(lut), spec=spec,
        batch_size=8, strategy="gather",
    )
    np.testing.assert_allclose(
        hybrid.score(docs), gather.score(docs), rtol=1e-4, atol=1e-3
    )


def test_runner_hybrid_long_doc_chunking():
    """Chunked docs exercise window limits through both hybrid parts."""
    from spark_languagedetector_tpu.api.runner import BatchRunner

    spec = VocabSpec(EXACT, (1, 2, 3))
    rng = np.random.default_rng(37)
    G = 1000
    ids = np.sort(rng.choice(spec.id_space_size, G, replace=False))
    weights = np.zeros((G + 1, 3), np.float32)
    weights[:G] = rng.normal(size=(G, 3)).astype(np.float32)
    lut = np.full(spec.id_space_size, G, np.int32)
    lut[ids] = np.arange(G, dtype=np.int32)
    docs = [bytes(rng.integers(0, 256, 700, dtype=np.uint8))]
    kw = dict(
        weights=jnp.asarray(weights), lut=jnp.asarray(lut), spec=spec,
        batch_size=8, length_buckets=(128, 256),
    )
    hybrid = BatchRunner(strategy="hybrid", **kw)
    gather = BatchRunner(strategy="gather", **kw)
    np.testing.assert_allclose(
        hybrid.score(docs), gather.score(docs), rtol=1e-4, atol=1e-3
    )


def test_runner_pallas_strategy_end_to_end():
    """BatchRunner with strategy='pallas' (interpret on CPU) matches gather."""
    from spark_languagedetector_tpu.api.runner import BatchRunner

    spec = VocabSpec(EXACT, (1, 2))
    rng = np.random.default_rng(19)
    weights = rng.normal(size=(spec.id_space_size, 3)).astype(np.float32)
    docs = _random_docs(rng, 10, 200) + [b"", b"q"]
    pallas_runner = BatchRunner(
        weights=jnp.asarray(weights), lut=None, spec=spec,
        batch_size=8, strategy="pallas",
    )
    gather_runner = BatchRunner(
        weights=jnp.asarray(weights), lut=None, spec=spec,
        batch_size=8, strategy="gather",
    )
    np.testing.assert_allclose(
        pallas_runner.score(docs), gather_runner.score(docs),
        rtol=1e-4, atol=1e-3,
    )
