"""Elastic fleet: subprocess replicas, supervisor, autoscaler (ISSUE 15).

The acceptance contract: a :class:`ProcessReplica` spawned from a
persisted model serves label-identical answers to the direct runner; an
abrupt child death is detected and restarted on the pinned port within
the bounded backoff budget (and the router's breaker machinery re-admits
it without a membership change); a SIGKILLed coordinator's stranded
children are reaped by the next supervisor on the same pidfile dir; the
autoscaler's hysteresis never flaps, defers mid-outage, and its
``scale/decision`` fault site skips ticks — never a wrong scale action —
with ``%prob`` plans replaying deterministically like ``fleet/*``.
"""

import functools
import os
import time

import numpy as np
import pytest

from spark_languagedetector_tpu import LanguageDetectorModel
from spark_languagedetector_tpu.exec.core import AdmissionQueue
from spark_languagedetector_tpu.ops.encoding import texts_to_bytes
from spark_languagedetector_tpu.resilience import faults
from spark_languagedetector_tpu.resilience.faults import FaultPlan
from spark_languagedetector_tpu.resilience.policy import RetryPolicy
from spark_languagedetector_tpu.scale import (
    Autoscaler,
    ProcessReplica,
    ReplicaSupervisor,
    ScaleSignals,
    SpawnError,
)
from spark_languagedetector_tpu.serve.client import ServeClient
from spark_languagedetector_tpu.serve.router import FleetRouter
from spark_languagedetector_tpu.telemetry import REGISTRY

LANGS = ("x", "y")
GRAM_KEYS = (b"ab", b"bc", b"zz", b"abc")
TEXTS = ["abab", "zz", "abczz", "bcbc"]

# Fast, deterministic backoff for every supervisor in this module: the
# schedules are exercised, the sleeps are not the point.
FAST_RETRY = RetryPolicy(
    max_attempts=3, base_delay_s=0.01, max_delay_s=0.02, seed=7
)

# Cold spawns (no prewarm) keep each subprocess bring-up a few seconds:
# the lifecycle under test is the process protocol, not the compile.
SUP_KW = dict(retry_policy=FAST_RETRY, prewarm=False)


@functools.lru_cache(maxsize=None)
def _model(seed=0):
    rng = np.random.default_rng(seed)
    gram_map = {g: rng.normal(size=2).tolist() for g in GRAM_KEYS}
    return LanguageDetectorModel.from_gram_map(gram_map, (2, 3), LANGS)


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    path = tmp_path_factory.mktemp("scale_model") / "m"
    _model(0).save(str(path))
    return str(path)


def _counter(name):
    return int(REGISTRY.snapshot()["counters"].get(name, 0))


def _wait(pred, timeout_s=30.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return pred()


# ------------------------------------------------- subprocess lifecycle -----
def test_subprocess_replica_full_lifecycle(model_dir, tmp_path):
    """The whole subprocess story in one fleet: spawn (through an
    injected first-attempt failure, exercising the backoff) → READY →
    label parity vs the direct runner → SIGKILL → supervisor restart on
    the pinned port → router ejection + half-open re-admission without a
    membership change → graceful close with no pidfile left."""
    runner = _model(0)._get_runner()
    want = [LANGS[int(i)] for i in runner.predict_ids(texts_to_bytes(TEXTS))]

    sup = ReplicaSupervisor(
        model_dir, pidfile_dir=str(tmp_path / "pids"),
        fleet_name=f"t_lifecycle_{os.getpid()}", **SUP_KW,
    )
    try:
        fails0 = _counter("scale/spawn_failures")
        with faults.plan_scope(FaultPlan.parse("scale/spawn:error@1")):
            rep = sup.spawn("r0")
        # Attempt 1 injected-failed (counted), attempt 2 spawned: the
        # restart-backoff path ran without costing a real process.
        assert _counter("scale/spawn_failures") - fails0 == 1
        assert rep.alive and rep.address[1] > 0
        assert os.path.exists(str(tmp_path / "pids" / "r0.pid"))

        client = ServeClient(*rep.address)
        assert client.readyz()["ready"]
        got, meta = client.detect(TEXTS)
        assert got == want and meta["version"] == "v1"

        # Router over the subprocess replica: probes driven explicitly.
        router = FleetRouter(
            [rep], probe_interval_ms=30.0, breaker_threshold=2,
            breaker_cooldown_s=0.2, probe_timeout_s=2.0,
        )
        router.probe_once()
        assert router.eligible() == ["r0"]

        # Abrupt death: poll + pipe sentinel both observe it.
        port = rep.address[1]
        pid = rep.pid
        rep.proc.kill()
        assert _wait(lambda: not rep.alive, 15.0)
        assert _wait(rep._eof.is_set, 15.0)

        # The prober watches the address fail and ejects (two failed
        # probes at threshold 2) — membership unchanged.
        router.probe_once()
        router.probe_once()
        assert router.eligible() == []

        restarts0 = _counter("scale/restarts")
        assert sup.poll_once() == ["r0:restarted"]
        assert _counter("scale/restarts") - restarts0 == 1
        assert rep.alive and rep.pid != pid
        assert rep.address[1] == port  # pinned: the address the router knows

        # Cooldown elapses → the half-open probe re-admits the replica.
        time.sleep(0.25)
        assert _wait(lambda: "r0:readmitted" in router.probe_once(), 10.0)
        assert router.eligible() == ["r0"]
        got, _ = client.detect(TEXTS)
        assert got == want

        # A healthy member resets its crash-loop streak.
        assert sup.poll_once() == []
        assert sup._restart_streak["r0"] == 0
    finally:
        sup.close()
    assert not rep.alive
    assert os.listdir(str(tmp_path / "pids")) == []


def test_spawn_exhaustion_is_bounded_and_loud(model_dir, tmp_path):
    """Every spawn attempt injected to fail: the bounded backoff burns
    its budget, counts each failure, and raises — no process ever
    started, no member registered."""
    sup = ReplicaSupervisor(
        model_dir, pidfile_dir=str(tmp_path / "pids"),
        fleet_name=f"t_exhaust_{os.getpid()}", **SUP_KW,
    )
    try:
        fails0 = _counter("scale/spawn_failures")
        with faults.plan_scope(FaultPlan.parse("scale/spawn:error@1-9")):
            with pytest.raises(faults.InjectedFault):
                sup.spawn("r0")
        assert _counter("scale/spawn_failures") - fails0 == 3
        assert sup.members == {}
    finally:
        sup.close()


def test_coordinator_sigkill_orphan_reap(model_dir, tmp_path):
    """A coordinator that dies without cleanup (abandon() — the in-
    process stand-in for SIGKILL, which can never run atexit) strands a
    live child; the NEXT supervisor on the same pidfile dir reaps it
    before binding anything, and counts it."""
    pids = str(tmp_path / "pids")
    sup = ReplicaSupervisor(
        model_dir, pidfile_dir=pids,
        fleet_name=f"t_orphan_{os.getpid()}", **SUP_KW,
    )
    rep = sup.spawn("r0")
    assert rep.alive
    sup.abandon()  # children deliberately NOT killed; pidfiles stay
    assert rep.alive and os.listdir(pids) == ["r0.pid"]

    reaped0 = _counter("scale/orphans_reaped")
    sup2 = ReplicaSupervisor(
        model_dir, pidfile_dir=pids,
        fleet_name=f"t_orphan_{os.getpid()}", **SUP_KW,
    )
    try:
        assert _counter("scale/orphans_reaped") - reaped0 == 1
        assert _wait(lambda: not rep.alive, 15.0)
        assert os.listdir(pids) == []
    finally:
        sup2.close()


def test_orphan_reap_ignores_stale_and_foreign_pidfiles(tmp_path):
    """A pidfile whose pid is dead — or alive but NOT a replica worker
    (pid recycling) — is cleaned up without signalling anything."""
    pids = tmp_path / "pids"
    pids.mkdir()
    (pids / "dead.pid").write_text('{"pid": 999999999, "name": "dead"}')
    # This very test process is alive but is not a replica worker.
    (pids / "self.pid").write_text(
        '{"pid": %d, "name": "self"}' % os.getpid()
    )
    (pids / "garbage.pid").write_text("not json")
    reaped0 = _counter("scale/orphans_reaped")
    sup = ReplicaSupervisor(
        "/nonexistent/model", pidfile_dir=str(pids),
        fleet_name=f"t_stale_{os.getpid()}", **SUP_KW,
    )
    try:
        assert _counter("scale/orphans_reaped") - reaped0 == 0
        assert sorted(os.listdir(str(pids))) == []
    finally:
        sup.close()


# ------------------------------------------------------- admission odometer --
def test_admission_queue_admitted_rows_odometer():
    """``admitted_rows`` is the monotone arrival odometer the autoscaler
    differentiates — it grows on every admission and never resets on
    dispatch (unlike ``queued_rows``) or on silence (unlike a rate)."""
    q = AdmissionQueue(max_rows=8, max_wait_s=0.0, max_queue_rows=100)
    assert q.stats()["admitted_rows"] == 0
    q.admit("a", 3, "interactive")
    q.admit("b", 2, "interactive")
    assert q.stats()["admitted_rows"] == 5
    q.next_batch()
    q.done()
    stats = q.stats()
    assert stats["queued_rows"] == 0 and stats["admitted_rows"] == 5
    q.admit("c", 4, "interactive")
    assert q.stats()["admitted_rows"] == 9
    # Sheds do NOT advance the odometer: rejected rows never arrived as
    # far as the service loop is concerned.
    reason, _ = q.admit("d", 1000, "interactive")
    assert reason == "queue_full"
    assert q.stats()["admitted_rows"] == 9
    q.close(drain=False)


# ------------------------------------------------------------- autoscaler ----
class FakeFleet:
    """Deterministic fleet stand-in: the autoscaler's whole contract is
    ``check_members()`` / ``signals()`` / ``scale_to(n)`` / ``target``."""

    def __init__(self, live=1):
        self.target = live
        self.live = live
        self.sig = ScaleSignals(live=live, ready=live)
        self.scale_calls: list[int] = []

    def check_members(self):
        return []

    def signals(self):
        self.sig.live = self.live
        return self.sig

    def scale_to(self, n):
        self.scale_calls.append(n)
        self.target = n
        self.live = n
        return n


def _scaler(fleet, **kw):
    kw.setdefault("scale_min", 1)
    kw.setdefault("scale_max", 3)
    kw.setdefault("up_ticks", 2)
    kw.setdefault("down_ticks", 3)
    kw.setdefault("pressure_wait_ms", 50.0)
    kw.setdefault("idle_rows_per_s", 1.0)
    kw.setdefault("interval_ms", 10_000.0)  # ticks driven by hand
    return Autoscaler(fleet, **kw)


def test_autoscaler_hysteresis_up_and_down():
    """Pressure must persist ``up_ticks`` before a spawn; idleness must
    persist ``down_ticks`` (the cooldown) before a drain — one spike in
    either direction never moves the fleet."""
    fl = FakeFleet(live=1)
    sc = _scaler(fl)
    # One pressure tick: streak 1 of 2 — hold.
    fl.sig.est_wait_ms = 100.0
    assert sc.tick() == "hold"
    # Pressure broke: streak resets; two clean ticks, then one pressure
    # tick — still hold (the spike never accumulates across gaps).
    fl.sig.est_wait_ms = 0.0
    assert sc.tick() == "hold"
    fl.sig.est_wait_ms = 100.0
    assert sc.tick() == "hold"
    # Sustained pressure: second consecutive tick scales up.
    assert sc.tick() == "up"
    assert fl.scale_calls == [2]
    # Shed appearance alone is also pressure.
    fl.sig.est_wait_ms = 0.0
    fl.sig.shed_delta = 4
    assert sc.tick() == "hold"
    assert sc.tick() == "up"
    assert fl.target == 3
    # Idle now: queue empty, nothing in flight, EMA under the floor —
    # but only after down_ticks consecutive ticks.
    fl.sig.shed_delta = 0
    fl.sig.ema_rows_per_s = 0.1
    assert sc.tick() == "hold"
    assert sc.tick() == "hold"
    assert sc.tick() == "down"
    assert fl.target == 2
    # A traffic blip resets the idle cooldown.
    fl.sig.ema_rows_per_s = 50.0
    assert sc.tick() == "hold"
    fl.sig.ema_rows_per_s = 0.1
    assert sc.tick() == "hold"
    assert sc.tick() == "hold"
    assert sc.tick() == "down"
    assert fl.target == 1


def test_autoscaler_clamps_min_max():
    fl = FakeFleet(live=3)
    sc = _scaler(fl, scale_min=2, scale_max=3)
    fl.sig.est_wait_ms = 1000.0
    for _ in range(10):  # sustained pressure at the ceiling: never past it
        sc.tick()
    assert fl.target == 3 and fl.scale_calls == []
    fl.sig.est_wait_ms = 0.0
    fl.sig.ema_rows_per_s = 0.0
    for _ in range(20):  # sustained idleness: stops at the floor
        sc.tick()
    assert fl.target == 2 and fl.scale_calls == [2]


def test_autoscaler_defers_mid_outage():
    """A breaker-open member or a fleet below target (supervised restart
    in flight) freezes scale decisions: the breaker/half-open machinery
    owns the fleet's shape mid-outage — a dead replica must never read
    as idleness."""
    fl = FakeFleet(live=2)
    sc = _scaler(fl, scale_min=1)
    fl.sig.ema_rows_per_s = 0.0
    fl.sig.breaker_open = True
    for _ in range(10):
        assert sc.tick() == "deferred"
    assert fl.scale_calls == []
    # Restart in flight: live below target defers the same way.
    fl.sig.breaker_open = False
    fl.live = 1
    fl.sig.live = 1
    assert sc.tick() == "deferred"
    # Recovered: the idle cooldown starts counting only now.
    fl.live = 2
    assert sc.tick() == "hold"
    assert sc.tick() == "hold"
    assert sc.tick() == "down"


def test_autoscaler_min_floor_repair():
    """A member past its restart budget drops the target below the
    floor; the next tick spawns a fresh replacement rather than serving
    under min."""
    fl = FakeFleet(live=2)
    sc = _scaler(fl, scale_min=2, scale_max=3)
    fl.target = 1  # what ElasticFleet.check_members does on gave_up
    fl.live = 1
    assert sc.tick() == "up"
    assert fl.scale_calls == [2] and fl.target == 2


def test_scale_decision_fault_skips_tick_never_wrong_action():
    """An injected ``scale/decision`` error skips exactly that tick —
    fail-static: even under sustained pressure the faulted tick takes no
    scale action, and the streak does not advance behind its back."""
    fl = FakeFleet(live=1)
    sc = _scaler(fl, up_ticks=2)
    fl.sig.est_wait_ms = 1000.0
    skips0 = _counter("scale/decision_skips")
    with faults.plan_scope(FaultPlan.parse("scale/decision:error@2")):
        assert sc.tick() == "hold"     # pressure streak 1
        assert sc.tick() == "skipped"  # injected: tick 2 does not happen
        assert fl.scale_calls == []
        assert sc.tick() == "up"       # streak completes on the next tick
    assert _counter("scale/decision_skips") - skips0 == 1
    assert fl.target == 2


def test_scale_decision_prob_plan_replays_deterministically():
    """%prob plans at ``scale/decision`` fire on the same tick numbers
    for the same seed — two installs, identical skip schedules (the same
    pinned-replay contract as the ``fleet/*`` sites)."""

    def run_schedule():
        fl = FakeFleet(live=1)
        sc = _scaler(fl)
        out = []
        with faults.plan_scope(
            FaultPlan.parse("seed=11;scale/decision:error%0.4")
        ):
            for _ in range(24):
                out.append(sc.tick() == "skipped")
        return out

    first = run_schedule()
    second = run_schedule()
    assert first == second
    assert any(first) and not all(first)


# --------------------------------------------------------- bench smoke gate --
def test_bench_smoke_scale_trimmed(tmp_path):
    """Tier-1-sized elastic smoke: subprocess replicas, the full
    quiet→burst→quiet ramp with a mid-burst SIGKILL, hard-gated exactly
    like the CI gate."""
    import bench

    result = bench.smoke_scale(str(tmp_path / "scale.jsonl"), trimmed=True)
    assert result["ok"], result
    assert result["dropped_responses"] == 0
    assert result["argmax_parity"] == 1.0
    assert result["scale_ups"] >= 1 and result["scale_downs"] >= 1
    assert result["supervised_restarts"] >= 1 and result["restart_drilled"]
    tl = result["replica_timeline"]
    assert tl["quiet1_max"] == 1 and tl["burst_peak"] >= 2
    assert tl["quiet2_end"] == 1


@pytest.mark.slow
def test_bench_smoke_scale_full(tmp_path):
    import bench

    result = bench.smoke_scale(str(tmp_path / "scale_full.jsonl"))
    assert result["ok"], result
    assert result["replica_timeline"]["burst_peak"] >= 2
    assert result["health"]["target_replicas"] == 1
