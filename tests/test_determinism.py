"""Determinism + sanitizers (SURVEY.md §5.2).

The reference gets race-freedom from Spark's model; JAX's functional model
gives the same, so what's testable is *bitwise determinism* — identical
inputs must produce identical profiles and identical scores, run to run and
regardless of micro-batching — plus the NaN sanitizers.
"""

import hashlib

import numpy as np
import pytest

import jax.numpy as jnp

from spark_languagedetector_tpu import LanguageDetector, Table
from spark_languagedetector_tpu.api.runner import BatchRunner
from spark_languagedetector_tpu.ops.vocab import EXACT, VocabSpec
from spark_languagedetector_tpu.utils.debug import assert_finite, nan_checks


def _digest(*arrays) -> str:
    h = hashlib.sha256()
    for a in arrays:
        a = np.ascontiguousarray(a)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


_TRAIN = Table({
    "lang": ["de", "de", "en", "en"],
    "fulltext": [
        "der schnelle braune fuchs", "über den faulen hund",
        "the quick brown fox", "over the lazy dog",
    ],
})


def test_fit_is_bitwise_deterministic():
    digests = set()
    for _ in range(3):
        model = LanguageDetector(["de", "en"], [1, 2], 30).fit(_TRAIN)
        digests.add(_digest(model.profile.ids, model.profile.weights))
    assert len(digests) == 1


def test_scores_bitwise_deterministic_across_batch_sizes():
    spec = VocabSpec(EXACT, (1, 2))
    rng = np.random.default_rng(19)
    weights = rng.normal(size=(spec.id_space_size, 3)).astype(np.float32)
    docs = [bytes(rng.integers(0, 256, int(rng.integers(0, 300)), dtype=np.uint8))
            for _ in range(40)]
    digests = set()
    for bs in (4, 8, 40):
        runner = BatchRunner(
            weights=jnp.asarray(weights), lut=None, spec=spec,
            batch_size=bs, strategy="gather",
        )
        digests.add(_digest(runner.score(docs)))
    assert len(digests) == 1


def test_assert_finite_rejects_nan_profile():
    from spark_languagedetector_tpu.models.profile import GramProfile

    spec = VocabSpec(EXACT, (2,))
    weights = np.asarray([[0.5, np.nan]])
    with pytest.raises(ValueError, match="non-finite"):
        GramProfile(
            spec=spec, languages=("de", "en"),
            ids=np.asarray([300], np.int64), weights=weights,
        )


def test_rejects_out_of_range_ids():
    from spark_languagedetector_tpu.models.profile import GramProfile

    spec = VocabSpec(EXACT, (2,))
    w = np.ones((1, 2))
    with pytest.raises(ValueError, match="ids must lie"):
        GramProfile(spec=spec, languages=("de", "en"),
                    ids=np.asarray([-5], np.int64), weights=w)
    with pytest.raises(ValueError, match="ids must lie"):
        GramProfile(spec=spec, languages=("de", "en"),
                    ids=np.asarray([spec.id_space_size], np.int64), weights=w)


def test_nan_checks_scoped_flag():
    import jax

    prev = jax.config.jax_debug_nans
    with nan_checks(True):
        assert jax.config.jax_debug_nans is True
        with pytest.raises(FloatingPointError):
            jnp.log(jnp.zeros(2)) - jnp.log(jnp.zeros(2))  # inf - inf = nan
    assert jax.config.jax_debug_nans == prev


def test_assert_finite_passes_clean():
    assert_finite(np.ones((3, 3)), "ok")  # no raise
    assert_finite(np.zeros((0, 2)), "empty ok")
