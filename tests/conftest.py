"""Test fixture: single-process fake of the distributed substrate.

The reference tests against Spark ``local[4]`` — real shuffles/broadcasts in
one JVM (``/root/reference/src/test/.../Spark.scala:6-22``). The TPU-native
analog (SURVEY.md §4): the JAX CPU backend with 8 virtual host devices, so
mesh/sharding/collective code runs real XLA collectives without TPU hardware.
Must be set before jax initializes, hence module-level in conftest.
"""

import os

# Force CPU even when the host environment pins JAX to a TPU backend: unit
# tests must be deterministic and see 8 virtual devices. The axon TPU-tunnel
# sitecustomize sets the *programmatic* jax_platforms config (which overrides
# the env var) to "axon,cpu" at interpreter start, so setting the env var is
# not enough — update the config before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected 8 virtual devices, got {devices}"
    return devices


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-second end-to-end legs excluded from the tier-1 run "
        "(-m 'not slow'); exercised by their bench smoke gates instead",
    )
